"""Headline benchmark: ResNet-50 ImageNet-shape training throughput, images/sec/chip.

BASELINE.json's metric is "ImageNet ResNet-50 images/sec/chip"; the reference era's
per-chip number for the same job (TF1 fp32 ResNet-50 on a V100, the hardware the
reference's 2-GPU MirroredStrategy runs used) is ~360 images/sec/chip, which is the
``vs_baseline`` denominator here.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Architecture: the TPU backend in this environment is flaky — ``jax.devices()`` has
been observed to HANG for minutes (round 1 shipped no number because of exactly
this). A hang cannot be recovered in-process, so bench.py runs as a SUPERVISOR that
executes the real benchmark in a child process under a bounded timeout, retrying
with backoff; if the TPU child never succeeds, the HEADLINE stays the last known
TPU measurement (stamped ``stale: true`` with its ``measured_at``) and a CPU
child runs as a demoted ``fallback_probe`` liveness section — the top-level
metric/value/vs_baseline are TPU numbers whenever any TPU run has ever landed.
The driver always gets its one JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

V100_FP32_RESNET50_IMAGES_PER_SEC = 360.0

# bf16 peak matmul TFLOP/s per chip by device_kind substring (public figures).
PEAK_BF16_TFLOPS = {
    "v6e": 918.0,
    "v6": 918.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v5": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
}

# Supervisor budget: attempts x per-attempt timeout. First TPU compile is 20-40s
# and flaky backend init was observed at >170s; 700s covers both plus the timed
# run and extras (the headline prints early, so even a timeout mid-extras
# salvages the number). Two attempts bound the dead-backend worst case to
# ~25 min before the CPU fallback.
TPU_ATTEMPTS = 2
TPU_TIMEOUT_SECS = 700
CPU_TIMEOUT_SECS = 600


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, tflops in PEAK_BF16_TFLOPS.items():
        if key in kind:
            return tflops * 1e12
    return None


def run_benchmark(platform: str | None = None) -> dict:
    """The actual measurement (runs inside the child process).

    ``platform='cpu'`` forces the CPU backend via jax.config — this image's
    sitecustomize pre-imports jax with the tunneled TPU platform, so environment
    variables alone are too late; the config route works because backend
    initialization is lazy."""
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    # Persistent compile cache: the ResNet-50 train-step compile through the
    # tunneled TPU backend has been measured at several MINUTES — most of the
    # supervisor's per-attempt budget. Serialized executables keyed by HLO hash
    # make the second run (and the driver's end-of-round run on this machine)
    # nearly compile-free. Best-effort: unsupported backends just skip caching.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache_tpu"),
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # noqa: BLE001
        pass
    import numpy as np

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.utils.profiling import StepTimer, sync

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n = len(devices)

    if on_tpu:
        # STANDARD ResNet-50 (classic 64/128/256/512 widths, 25.6M params,
        # ~4.1 GMACs fwd) — the architecture the V100 baseline figure actually
        # quotes, bfloat16 on the MXU, taken from the preset registry so the
        # benchmark can't drift from what users train. The reference's own
        # wider layout (~3x the FLOPs/image) is measured separately below as
        # ``reference_family_wide`` so both numbers stay on record.
        from tensorflowdistributedlearning_tpu.configs import PRESETS

        cfg = PRESETS["resnet50_classic_imagenet"].model
        per_chip_batch = 256
        # 80 timed steps per host sync: over the tunnel, the sync RTT
        # (~100ms observed) amortizes across the window — at 10-20 steps it
        # inflated step time by 2-11ms/step (r5: a 40-step probe measured
        # the bf16 seg flagship at 40.3ms/step vs the 10-step section's
        # 51.7) — the bench should measure the chip, not the tunnel
        timed_steps, warmup = 80, 3
    else:
        # CPU fallback (local smoke): tiny model, tiny batch
        cfg = ModelConfig(
            num_classes=10,
            input_shape=(32, 32),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=32,
        )
        per_chip_batch = 8
        timed_steps, warmup = 3, 1

    mesh = make_mesh(n)
    tx = make_optimizer(TrainConfig())
    rng = jax.random.PRNGKey(0)

    def measure(per_chip: int, mcfg: ModelConfig | None = None):
        """(global_batch, dt, compiled_step) for one batch size; raises on OOM."""
        mcfg = cfg if mcfg is None else mcfg
        mmodel = build_model(mcfg)
        mh, mw = mcfg.input_shape
        msample = np.zeros((1, mh, mw, mcfg.input_channels), np.float32)
        global_b = per_chip * n
        state = replicate(create_train_state(mmodel, tx, rng, msample), mesh)
        gen = np.random.default_rng(0)
        batch = shard_batch(
            {
                "images": gen.normal(
                    0, 1, (global_b, mh, mw, mcfg.input_channels)
                ).astype(np.float32),
                "labels": gen.integers(0, mcfg.num_classes, global_b).astype(
                    np.int32
                ),
            },
            mesh,
        )
        # donate=False: `batch` and `state` are reused across calls here; the
        # trainer's production path donates. profiling.sync pulls a value that
        # depends on the last step — on the tunneled TPU platform
        # block_until_ready alone has been observed to return before execution
        # finishes, inflating throughput ~10x.
        step = make_train_step(mesh, ClassificationTask(), donate=False)
        # AOT-compile ONCE and reuse the executable for warmup, timing, and the
        # MFU cost analysis — step.lower().compile() does not share the jit
        # cache, so a later recompile would double the compile wall time.
        comp = step.lower(state, batch).compile()
        s = state
        for _ in range(warmup):
            s, metrics = comp(s, batch)
        sync(metrics)
        # one StepTimer window over all timed steps, synced on the final
        # metrics — the same whole-window/single-sync protocol as before
        # (per-step stops would insert a sync per step and measure the
        # tunnel), now on the shared timing implementation
        timer = StepTimer()
        timer.start()
        for _ in range(timed_steps):
            s, metrics = comp(s, batch)
        return global_b, timer.stop(metrics), comp

    # halve the batch on HBM exhaustion instead of failing the whole attempt.
    # Only the failure MESSAGE is retained — keeping the exception object would
    # pin the OOM'd attempt's device buffers via its traceback frames, making
    # the very retry this exists for OOM again.
    last_oom_msg: str | None = None
    for attempt_batch in (per_chip_batch, per_chip_batch // 2, per_chip_batch // 4):
        if attempt_batch < 1:
            continue
        try:
            global_batch, dt, compiled = measure(attempt_batch)
            break
        except Exception as e:  # noqa: BLE001 — inspect for OOM, else re-raise
            msg = str(e)
            if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
                last_oom_msg = msg[:300]
                continue
            raise
    else:
        raise RuntimeError(
            f"every benchmark batch size exhausted memory: {last_oom_msg}"
        )

    images_per_sec_per_chip = global_batch * timed_steps / dt / n
    result = {
        "metric": "resnet50_imagenet_train_throughput_per_chip"
        if on_tpu
        else "resnet_tiny_cpu_train_throughput_per_chip",
        "value": round(images_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            images_per_sec_per_chip / V100_FP32_RESNET50_IMAGES_PER_SEC, 3
        ),
        "platform": devices[0].platform,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "n_chips": n,
        "global_batch": global_batch,
        "step_time_ms": round(dt / timed_steps * 1000, 2),
    }
    # The headline number exists NOW — print it immediately so that even if the
    # optional extras below (MFU, kernel microbench, segmentation bench) push a
    # slow backend past the supervisor's timeout, the killed child still leaves
    # a parseable measurement on stdout (the supervisor reads partial output).
    print(json.dumps(result), flush=True)

    # MFU: XLA's own FLOP count for the compiled step vs chip peak. cost_analysis
    # is best-effort across backends — fall back to the analytic ResNet-50 figure
    # (~2x 4.1e9 MAC-derived FLOPs fwd, x3 for fwd+bwd) when unavailable.
    def _flops_of(executable, global_b: int, analytic_per_image: float):
        try:
            cost = executable.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            f = float(cost.get("flops", 0.0))
            if f > 0:
                return f
        except Exception:  # noqa: BLE001 — cost_analysis is best-effort
            pass
        return analytic_per_image * global_b if on_tpu else None

    peak = _peak_flops(devices[0])

    # analytic fwd+bwd FLOPs/image fallbacks when cost_analysis is unavailable:
    # classic ResNet-50 is the textbook ~4.1 GMACs fwd x2 x3; the reference's
    # wide layout measures 7.2e10 by XLA cost analysis (CPU, this repo, r3)
    CLASSIC50_FLOPS_PER_IMAGE = 3 * 2 * 4.1e9
    WIDE_FLOPS_PER_IMAGE = 7.2e10

    def _mfu_fields(
        executable,
        global_b: int,
        step_dt: float,
        analytic_per_image: float = CLASSIC50_FLOPS_PER_IMAGE,
    ) -> dict:
        flops = _flops_of(executable, global_b, analytic_per_image)
        if flops is None or peak is None:
            return {}
        return {
            "mfu": round(flops / step_dt / n / peak, 4),
            "model_tflops_per_step": round(flops / 1e12, 3),
        }

    mfu_fields = _mfu_fields(compiled, global_batch, dt / timed_steps)
    if mfu_fields:
        result.update(mfu_fields)
        # re-print after every completed extra: the supervisor keeps the LAST
        # parseable line, so a timeout mid-extras costs only the unfinished ones
        print(json.dumps(result), flush=True)

    if on_tpu:
        # Pallas-vs-XLA depthwise decision data at the flagship's ASPP shapes
        # (VERDICT r1 #5): recorded so use_pallas_depthwise can be flipped on
        # the evidence. Best-effort — the headline number stands without it.
        try:
            from bench_kernels import bench_depthwise

            result["depthwise_kernels"] = bench_depthwise(iters=20, warmup=3)
        except Exception as e:  # noqa: BLE001
            result["depthwise_kernels"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

        # Secondary metric: the reference's own wide ResNet layout (doubled
        # stage widths + 1024-wide atrous stage, ~3x classic-ResNet-50 FLOPs,
        # 40.9M params) — the architecture the parity presets train, and the
        # highest-MFU config measured (0.45-0.46 at batch 256/512, r3 probes:
        # wide channels keep the MXU full).
        try:
            wide_cfg = PRESETS["resnet50_imagenet"].model
            # start from the batch the headline actually survived at (the OOM
            # ladder may have backed off per_chip_batch) and keep the same
            # halving ladder: the wide model is ~3x the activations, so the
            # headline's size only proves the 1x model fits
            wide_err: str | None = None
            for wb in (global_batch // n, global_batch // (2 * n),
                       global_batch // (4 * n)):
                if wb < 1:
                    continue
                try:
                    wide_gb, wide_dt, wide_comp = measure(wb, wide_cfg)
                    break
                except Exception as e:  # noqa: BLE001 — OOM: halve and retry
                    msg = str(e)
                    if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
                        wide_err = msg[:200]
                        continue
                    raise
            else:
                raise RuntimeError(wide_err or "no viable wide batch size")
            wide_ips = wide_gb * timed_steps / wide_dt / n
            result["reference_family_wide"] = {
                "images_per_sec_per_chip": round(wide_ips, 2),
                "global_batch": wide_gb,
                "step_time_ms": round(wide_dt / timed_steps * 1000, 2),
                **_mfu_fields(
                    wide_comp, wide_gb, wide_dt / timed_steps, WIDE_FLOPS_PER_IMAGE
                ),
            }
        except Exception as e:  # noqa: BLE001
            result["reference_family_wide"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

        # Secondary metric: the reference's ACTUAL production workload — the
        # TGS-salt segmentation flagship (ResNet-v2-beta + DeepLabV3+ head,
        # 101x101x2, Lovász hinge) at 64 images PER CHIP — the reference's
        # whole-run global batch on its 2-GPU setup was 64 (Untitled.ipynb
        # cells 7-8), i.e. 32/chip; per-chip 64 keeps the per-chip workload
        # comparable across pod sizes (global batch scales with n).
        def _seg_flagship(dtype: str = "float32") -> dict:
            # nested so every HBM reference (state, batch, executable) dies on
            # return — the batch-x2 probe below must not compete with it
            from tensorflowdistributedlearning_tpu.train.step import (
                SegmentationTask,
            )

            # float32 = the tgs_salt preset (reference defaults, the
            # parity-comparable number); bfloat16 = the tgs_salt_bf16 preset
            # (same architecture at the MXU's bf16 rate) — both taken FROM
            # the preset registry so the bench always prices the shipped
            # configs
            seg_cfg = PRESETS[
                "tgs_salt_bf16" if dtype == "bfloat16" else "tgs_salt"
            ].model
            seg_model = build_model(seg_cfg)
            seg_state = replicate(
                create_train_state(
                    seg_model,
                    make_optimizer(TrainConfig()),
                    jax.random.PRNGKey(1),
                    np.zeros((1, 101, 101, 2), np.float32),
                ),
                mesh,
            )
            seg_gen = np.random.default_rng(1)
            seg_batch = shard_batch(
                {
                    "images": seg_gen.normal(0, 1, (64 * n, 101, 101, 2)).astype(
                        np.float32
                    ),
                    "labels": (
                        seg_gen.uniform(0, 1, (64 * n, 101, 101, 1)) > 0.5
                    ).astype(np.float32),
                },
                mesh,
            )
            seg_step = make_train_step(mesh, SegmentationTask(), donate=False)
            seg_compiled = seg_step.lower(seg_state, seg_batch).compile()
            seg_steps = 80  # long window per sync: see timed_steps note above
            for _ in range(3):
                seg_state, seg_metrics = seg_compiled(seg_state, seg_batch)
            sync(seg_metrics)
            t0 = time.perf_counter()
            for _ in range(seg_steps):
                seg_state, seg_metrics = seg_compiled(seg_state, seg_batch)
            sync(seg_metrics)
            seg_dt = time.perf_counter() - t0
            return {
                "images_per_sec_per_chip": round(64 * seg_steps / seg_dt, 2),
                "global_batch": 64 * n,
                "step_time_ms": round(seg_dt / seg_steps * 1000, 2),
            }

        try:
            result["segmentation_flagship"] = _seg_flagship()
        except Exception as e:  # noqa: BLE001
            result["segmentation_flagship"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)
        try:
            result["segmentation_flagship_bf16"] = _seg_flagship("bfloat16")
        except Exception as e:  # noqa: BLE001
            result["segmentation_flagship_bf16"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

        # Batch-x2 upside probe — late extra (low decision value; only the
        # hang-prone attention microbench, deliberately placed after it,
        # rides on its success). Only fires when the headline ran at the
        # full configured batch: if the OOM ladder already halved it, doubling
        # re-measures a size proven to exhaust HBM. Doubles the size that
        # actually succeeded; only a BETTER number replaces the headline
        # (printed last = what the supervisor records), and the superseded
        # batch-x1 figure is kept alongside for the comparison.
        if global_batch // n == per_chip_batch:
            try:
                global_b2, dt2, compiled2 = measure(per_chip_batch * 2)
                ips2 = global_b2 * timed_steps / dt2 / n
                if ips2 > images_per_sec_per_chip:
                    result["batch_x1_images_per_sec_per_chip"] = round(
                        images_per_sec_per_chip, 2
                    )
                    result.update(
                        value=round(ips2, 2),
                        vs_baseline=round(
                            ips2 / V100_FP32_RESNET50_IMAGES_PER_SEC, 3
                        ),
                        global_batch=global_b2,
                        step_time_ms=round(dt2 / timed_steps * 1000, 2),
                        **_mfu_fields(compiled2, global_b2, dt2 / timed_steps),
                    )
                result["batch_x2_images_per_sec_per_chip"] = round(ips2, 2)
                print(json.dumps(result), flush=True)
            except Exception as e:  # noqa: BLE001 — OOM/compile: keep headline
                result["batch_x2_probe"] = {"error": str(e)[:160]}

        # Pallas-vs-XLA fused attention at ViT-S shapes: the decision data for
        # use_fused_attention, same contract as the depthwise column. LAST of
        # the extras ON PURPOSE: this environment's remote Pallas compile has
        # hung twice (r3 windows, starving whatever followed it) — at the end
        # of the child a hang costs nothing but itself.
        try:
            from bench_kernels import bench_attention

            result["attention_kernels"] = bench_attention(iters=20, warmup=3)
        except Exception as e:  # noqa: BLE001
            result["attention_kernels"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

        # ViT-S/16 train throughput: the transformer family's headline beside
        # the conv ones (fused attention ON per the preset; MFU is naturally
        # low for a 384-dim model — the MXU wants bigger matmuls). `peak` is
        # the device's own bf16 figure — the v5e constant used to be
        # hardcoded inside, silently mis-scaling MFU on v4/v5p/v6e.
        try:
            result["vit_s16"] = _vit_throughput(mesh, n, peak=peak)
        except Exception as e:  # noqa: BLE001
            result["vit_s16"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

        # ZeRO-1 weight-update sharding on the ViT flagship: per-chip
        # optimizer-state bytes and step time, replicated vs sharded — the
        # measurement behind TrainConfig.weight_update_sharding's memory
        # claim (also runnable standalone: `python bench.py --zero1`).
        try:
            result["weight_update_sharding"] = bench_weight_update_sharding(
                mesh, n
            )
        except Exception as e:  # noqa: BLE001
            result["weight_update_sharding"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

        # Sync-vs-async host loop on the same mesh: step time A/B plus the
        # per-window blocked-on-fetch split (also standalone:
        # `python bench.py --async-loop`, committed as BENCH_ASYNC.json).
        try:
            result["async_host_loop"] = bench_async_loop(mesh, n)
        except Exception as e:  # noqa: BLE001
            result["async_host_loop"] = {"error": str(e)[:200]}
        print(json.dumps(result), flush=True)

    return result


def _vit_throughput(mesh, n: int, per_chip_batch: int = 256,
                    peak: float | None = None) -> dict:
    import jax
    import numpy as np
    from flax.core import unfreeze

    from tensorflowdistributedlearning_tpu.configs import PRESETS
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.utils.profiling import StepTimer, sync

    preset = PRESETS["vit_s16_imagenet"]
    model = build_model(preset.model)
    state = create_train_state(
        model,
        make_optimizer(preset.train),
        jax.random.PRNGKey(0),
        np.ones((1, 224, 224, 3), np.float32),
    )
    # normalize to plain-dict batch_stats: flax's mutable apply returns dicts,
    # and the AOT executable must see one stable pytree type across calls
    state = replicate(state.replace(batch_stats=unfreeze(state.batch_stats)), mesh)
    gen = np.random.default_rng(0)
    gb = per_chip_batch * n
    batch = shard_batch(
        {
            "images": gen.normal(0, 1, (gb, 224, 224, 3)).astype(np.float32),
            "labels": gen.integers(0, 1000, gb).astype(np.int32),
        },
        mesh,
    )
    step = make_train_step(mesh, ClassificationTask(), donate=False)
    comp = step.lower(state, batch).compile()
    s = state
    for _ in range(3):
        s, m = comp(s, batch)
    sync(m)
    steps = 80  # long window per sync — see the timed_steps note above
    timer = StepTimer()
    timer.start()
    for _ in range(steps):
        s, m = comp(s, batch)
    dt = timer.stop(m) / steps
    out = {
        "images_per_sec_per_chip": round(per_chip_batch / dt, 1),
        "global_batch": gb,
        "step_time_ms": round(dt * 1000, 2),
    }
    # compiler-counted FLOPs over the CALLER's peak figure (the headline
    # section's _peak_flops lookup by device kind — a hardcoded v5e constant
    # here used to silently mis-scale MFU on v4/v5p/v6e); no analytic
    # fallback: cost_analysis is available wherever this TPU section runs
    try:
        ca = comp.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        flops = ca.get("flops")
        if flops:
            out["model_tflops_per_step"] = round(flops / 1e12, 3)
            if peak:  # unrecognized device kind: FLOPs stand, MFU omitted
                out["mfu"] = round(flops / (peak * dt * n), 4)
    except Exception:  # noqa: BLE001 — throughput stands without MFU
        pass
    return out


def bench_weight_update_sharding(mesh=None, n: int | None = None) -> dict:
    """ZeRO-1 (TrainConfig.weight_update_sharding) vs the replicated update.

    Two measurements, so the memory claim is priced and the "step time within
    noise" claim is checked rather than asserted:

    - per-chip optimizer-state bytes for the ViT-S/16 FLAGSHIP in both modes,
      computed from the sharding specs over the abstract state (eval_shape —
      exact accounting, no 1.4 GB of host arrays materialized on CPU runs);
    - a timed A/B of real train steps through ``make_train_step`` in both
      modes — the flagship on TPU, a tiny ViT on the CPU smoke path — with
      the end-state parameter agreement recorded alongside the times.
    """
    import jax
    import numpy as np
    from flax.core import unfreeze
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.configs import PRESETS
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        BATCH_AXIS,
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import (
        create_train_state,
        tree_bytes_per_device,
    )
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.utils.profiling import StepTimer, sync

    if mesh is None:
        mesh = make_mesh(n)
    n = n or len(jax.devices())
    dp = int(mesh.shape[BATCH_AXIS])
    on_tpu = jax.devices()[0].platform == "tpu"

    def bytes_under_specs(tree, specs=None) -> int:
        leaves = jax.tree.leaves(tree)
        spec_leaves = (
            jax.tree.leaves(specs) if specs is not None else [P()] * len(leaves)
        )
        total = 0
        for leaf, spec in zip(leaves, spec_leaves):
            shape = NamedSharding(mesh, spec).shard_shape(tuple(leaf.shape))
            total += int(np.prod(shape)) * np.dtype(leaf.dtype).itemsize
        return total

    # -- flagship accounting (abstract: exact bytes, no materialization) ----
    preset = PRESETS["vit_s16_imagenet"]
    flag_model = build_model(preset.model)
    flag_tx = make_optimizer(preset.train)
    h, w = preset.model.input_shape
    abstract_opt = jax.eval_shape(
        lambda rng, x: create_train_state(flag_model, flag_tx, rng, x).opt_state,
        jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, h, w, preset.model.input_channels), np.float32),
    )
    rep_bytes = bytes_under_specs(abstract_opt)
    zero_bytes = bytes_under_specs(
        abstract_opt, zero_lib.weight_update_specs(abstract_opt, mesh)
    )
    result: dict = {
        "data_parallel": dp,
        "flagship": {
            "model": "vit_s16_imagenet",
            "opt_state_bytes_per_chip": {
                "replicated": rep_bytes,
                "zero1": zero_bytes,
            },
            "reduction": round(rep_bytes / max(zero_bytes, 1), 2),
        },
    }

    # -- timed A/B through the real train step ------------------------------
    if on_tpu:
        mcfg, tcfg = preset.model, preset.train
        per_chip, steps, warm = 128, 40, 3
    else:
        # big enough that the weight update is real work: with a tiny model
        # the A/B only measures fixed per-collective overhead (the all-gather
        # against a near-zero update), which overstates ZeRO's cost — the
        # mode's trade is 1x update compute + param gather vs dp-x redundant
        # update compute, and that needs parameters to show up on a clock
        mcfg = ModelConfig(
            backbone="vit", num_classes=10, input_shape=(32, 32),
            input_channels=3, patch_size=8, embed_dim=256, vit_layers=4,
            num_heads=4, output_stride=None,
        )
        tcfg = TrainConfig(optimizer="adam", lr=1e-3)
        per_chip, steps, warm = 4, 6, 1
    model = build_model(mcfg)
    tx = make_optimizer(tcfg)
    rng = jax.random.PRNGKey(0)
    sample = np.zeros((1, *mcfg.input_shape, mcfg.input_channels), np.float32)
    gb = per_chip * dp
    gen = np.random.default_rng(0)
    batch = shard_batch(
        {
            "images": gen.normal(
                0, 1, (gb, *mcfg.input_shape, mcfg.input_channels)
            ).astype(np.float32),
            "labels": gen.integers(0, mcfg.num_classes, gb).astype(np.int32),
        },
        mesh,
    )

    def run(zero: bool):
        state = create_train_state(model, tx, rng, sample)
        state = state.replace(batch_stats=unfreeze(state.batch_stats))
        state = (
            zero_lib.shard_state_weight_update(state, mesh)
            if zero
            else replicate(state, mesh)
        )
        opt_bytes = tree_bytes_per_device(state.opt_state)
        # donate=False: batch and both mode's states are reused/compared
        step = make_train_step(
            mesh, ClassificationTask(), donate=False,
            weight_update_sharding=zero,
        )
        comp = step.lower(state, batch).compile()
        s = state
        for _ in range(warm):
            s, m = comp(s, batch)
        sync(m)
        # best-of-3 windows: single short windows on the shared 1-core driver
        # box swing +-25% with neighbor load (the same noise bench_serve
        # absorbs with trials); min is the standard load-robust estimator
        dts = []
        for _ in range(3):
            timer = StepTimer()
            timer.start()
            for _ in range(steps):
                s, m = comp(s, batch)
            dts.append(timer.stop(m) / steps)
        return s, {
            "step_time_ms": round(min(dts) * 1000, 3),
            "opt_state_bytes_per_chip": opt_bytes,
        }

    s_rep, rep = run(False)
    s_zero, zr = run(True)
    max_diff = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree.leaves(jax.device_get(s_rep.params)),
            jax.tree.leaves(jax.device_get(s_zero.params)),
        )
    )
    result["timed"] = {
        "model": "vit_s16_imagenet" if on_tpu else "vit_cpu_smoke",
        "global_batch": gb,
        "timed_steps": steps,
        "replicated": rep,
        "zero1": zr,
        "step_time_ratio": round(
            zr["step_time_ms"] / max(rep["step_time_ms"], 1e-9), 3
        ),
        "max_param_diff_after_timed_steps": max_diff,
    }
    return result


def bench_async_loop(
    mesh=None, n: int | None = None, check: bool = False,
    max_ratio: float = 1.05,
) -> dict:
    """Sync-vs-async host loop A/B (``TrainConfig.dispatch_ahead_steps``).

    Runs the SAME compiled train step through the real host-overlap machinery
    (``train/async_loop.HostOverlap``) twice — ``dispatch_ahead=0`` (the
    legacy loop: a blocking ``device_get`` per log window) vs the default
    budget of 2 (deferred window fetch + bounded dispatch-ahead) — with
    best-of-N timing per mode, the per-window host-blocked-on-fetch ms read
    back from each run's own telemetry ledger, and a bitwise comparison of
    the final params (the overlap layer must not change a single ULP).

    ``check`` gates the result (CI's regression tripwire): async step time
    must be <= ``max_ratio`` x sync (default 1.05; CI passes a looser bound
    via ``--max-ratio`` — shared runners have wall-clock noise a best-of-N
    cannot fully absorb, and the bound only needs to catch a serialization
    regression, which lands far above any noise) and the params must match
    exactly; the verdict is recorded as ``check_passed`` and ``main`` exits
    non-zero on failure.
    """
    import shutil
    import tempfile

    import jax
    import numpy as np
    from flax.core import unfreeze

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.obs.ledger import LEDGER_FILENAME
    from tensorflowdistributedlearning_tpu.obs.telemetry import (
        SPAN_STEP,
        Telemetry,
    )
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        BATCH_AXIS,
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train import async_loop
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )

    if mesh is None:
        mesh = make_mesh(n)
    n = n or len(jax.devices())
    dp = int(mesh.shape[BATCH_AXIS])
    on_tpu = jax.devices()[0].platform == "tpu"

    if on_tpu:
        mcfg = ModelConfig(
            backbone="vit", num_classes=1000, input_shape=(224, 224),
            input_channels=3, patch_size=16, embed_dim=384, vit_layers=12,
            num_heads=6, output_stride=None,
        )
        per_chip, steps, log_every, trials = 64, 60, 10, 3
    else:
        # same smoke scale as the ZeRO-1 A/B: big enough that a step is real
        # device work the host can (or can't) hide behind, small enough for
        # the CI box
        mcfg = ModelConfig(
            backbone="vit", num_classes=10, input_shape=(32, 32),
            input_channels=3, patch_size=8, embed_dim=256, vit_layers=4,
            num_heads=4, output_stride=None,
        )
        per_chip, steps, log_every, trials = 4, 30, 5, 3
    tcfg = TrainConfig(optimizer="adam", lr=1e-3)
    model = build_model(mcfg)
    tx = make_optimizer(tcfg)
    rng = jax.random.PRNGKey(0)
    sample = np.zeros((1, *mcfg.input_shape, mcfg.input_channels), np.float32)
    gb = per_chip * dp
    gen = np.random.default_rng(0)
    # a few DISTINCT pre-placed batches, cycled: input cost off the clock (the
    # prefetcher owns that trade), but the metric stream still varies per step
    placed = [
        shard_batch(
            {
                "images": gen.normal(
                    0, 1, (gb, *mcfg.input_shape, mcfg.input_channels)
                ).astype(np.float32),
                "labels": gen.integers(0, mcfg.num_classes, gb).astype(np.int32),
            },
            mesh,
        )
        for _ in range(4)
    ]
    state0 = create_train_state(model, tx, rng, sample)
    state0 = replicate(state0.replace(batch_stats=unfreeze(state0.batch_stats)), mesh)
    # donate=False: state0 is reused across trials and modes
    step = make_train_step(mesh, ClassificationTask(), donate=False)
    comp = step.lower(state0, placed[0]).compile()
    s = state0
    for i in range(3):  # warm the executable + allocator before any clock
        s, m = comp(s, placed[i % len(placed)])
    jax.block_until_ready(m)

    def run(budget: int) -> tuple:
        """One mode: best-of-``trials`` full loops from the same init, each
        under its own telemetry workdir; returns (final_state, section)."""
        dts, fetch_ms = [], []
        final = None
        for _ in range(trials):
            workdir = tempfile.mkdtemp(prefix="bench_async_")
            tel = Telemetry(
                workdir,
                run_info={"bench": "async_loop", "dispatch_ahead": budget},
                memory_every_windows=10**6,  # no memory probes on the clock
            )
            overlap = async_loop.HostOverlap(
                tel,
                dispatch_ahead=budget,
                emit=lambda rec, scalars: tel.window_event(
                    rec.step,
                    steps=rec.steps,
                    scalars=scalars,
                    dirty=rec.dirty,
                    samples=rec.samples,
                ),
            )
            st = state0
            t0 = time.perf_counter()
            for i in range(steps):
                with tel.span(SPAN_STEP):
                    st, metrics = comp(st, placed[i % len(placed)])
                overlap.track(metrics)
                if (i + 1) % log_every == 0:
                    overlap.window(
                        async_loop.PendingWindow(
                            step=i + 1, metrics=metrics, steps=log_every,
                            lr=float(tcfg.lr),
                        )
                    )
            overlap.flush()
            jax.block_until_ready(st.params)
            dts.append(time.perf_counter() - t0)
            tel.close(steps=steps)
            waits = []
            try:
                with open(os.path.join(workdir, LEDGER_FILENAME)) as f:
                    for line in f:
                        ev = json.loads(line)
                        if ev.get("event") == "step_window":
                            waits.append(ev.get("fetch_wait_s", 0.0) * 1000)
            finally:
                shutil.rmtree(workdir, ignore_errors=True)
            fetch_ms.append(waits)
            final = st
        best = min(range(trials), key=lambda t: dts[t])
        waits = fetch_ms[best]
        return final, {
            "step_time_ms": round(dts[best] / steps * 1000, 3),
            "loop_time_s": round(dts[best], 3),
            "windows": len(waits),
            "fetch_wait_ms_per_window": {
                "mean": round(sum(waits) / len(waits), 3) if waits else 0.0,
                "max": round(max(waits), 3) if waits else 0.0,
            },
        }

    s_sync, sync = run(0)
    s_async, rasync = run(2)
    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(jax.device_get(s_sync.params)),
            jax.tree.leaves(jax.device_get(s_async.params)),
        )
    )
    ratio = rasync["step_time_ms"] / max(sync["step_time_ms"], 1e-9)
    result = {
        "data_parallel": dp,
        "model": "vit_s16_imagenet_shape" if on_tpu else "vit_cpu_smoke",
        "global_batch": gb,
        "timed_steps": steps,
        "log_every_steps": log_every,
        "trials": trials,
        "sync": sync,
        "async": rasync,
        "step_time_ratio_async_over_sync": round(ratio, 3),
        "final_params_bit_identical": identical,
    }
    # peak HBM across the whole A/B (allocator lifetime peak): the number the
    # regression sentinel bands — a change that silently doubles the step's
    # working set shows up here even when step time holds. Absent on
    # backends without the allocator query (CPU builds report nothing).
    peak = _peak_hbm_bytes()
    if peak:
        result["peak_hbm_bytes"] = peak
    if check:
        result["check"] = {"max_ratio": max_ratio}
        result["check_passed"] = bool(identical and ratio <= max_ratio)
    return result


def bench_plan(
    n: int | None = None, check: bool = False, max_ratio: float = 1.05,
) -> dict:
    """Parallelism-planner A/B (``--parallelism auto`` vs hand-tuned preset
    layouts), committed as BENCH_PLAN.json and replayed as hard gates by
    ``tools/regression_sentinel.py``.

    For each entry the planner derives the auto layout (the 8k entry gets an
    HBM budget computed to exclude the replicated optimizer state — the
    budget-driven ZeRO-1 choice the planner exists for), then BOTH layouts
    run real train steps through the production step builders, best-of-N
    windows. Gates (``--check``): auto step time <= ``max_ratio`` x hand
    (auto must match or beat the hand-tuned layout), and the plan's
    predicted params+opt+stats bytes/chip must equal the placed state's
    ``tree_bytes_per_device`` EXACTLY (the planner's accounting contract).
    """
    import dataclasses as dc

    import jax
    import numpy as np
    from flax.core import unfreeze

    from tensorflowdistributedlearning_tpu.configs import PRESETS
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel import planner as planner_lib
    from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib
    from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import (
        create_train_state,
        tree_bytes_per_device,
    )
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.utils.profiling import StepTimer, sync

    n = n or len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        steps, warm, trials = 30, 3, 3
    else:
        steps, warm, trials = 8, 2, 3

    def run_layout(mcfg, tcfg, layout, global_batch) -> dict:
        """Timed steps + measured state bytes under one layout, through the
        same builders the trainers dispatch on (shard_map dp/zero1, GSPMD
        tp) — pipeline/spatial layouts are out of this bench's scope."""
        if layout.pipeline_parallel > 1 or layout.sequence_parallel > 1 or (
            layout.expert_parallel > 1
        ):
            raise RuntimeError(
                f"bench_plan does not time layout {layout.describe()}"
            )
        tp = layout.model_parallel > 1
        mesh = make_mesh(n, model_parallel=layout.model_parallel)
        model = build_model(mcfg)
        tx = make_optimizer(tcfg)
        state = create_train_state(
            model, tx, jax.random.PRNGKey(0),
            np.zeros((1, *mcfg.input_shape, mcfg.input_channels), np.float32),
        )
        state = state.replace(batch_stats=unfreeze(state.batch_stats))
        if layout.weight_update_sharding:
            state = zero_lib.shard_state_weight_update(
                state, mesh, tensor_parallel=tp
            )
        elif tp:
            state = tp_lib.shard_state_tensor_parallel(state, mesh)
        else:
            state = replicate(state, mesh)
        measured_bytes = (
            tree_bytes_per_device(state.params)
            + tree_bytes_per_device(state.batch_stats)
            + tree_bytes_per_device(state.opt_state)
        )
        gen = np.random.default_rng(0)
        batch = shard_batch(
            {
                "images": gen.normal(
                    0, 1,
                    (global_batch, *mcfg.input_shape, mcfg.input_channels),
                ).astype(np.float32),
                "labels": gen.integers(
                    0, mcfg.num_classes, global_batch
                ).astype(np.int32),
            },
            mesh,
        )
        if tp:
            step = tp_lib.make_train_step_gspmd(
                mesh, ClassificationTask(), donate=False,
                weight_update_sharding=layout.weight_update_sharding,
            )
        else:
            step = make_train_step(
                mesh, ClassificationTask(), donate=False,
                weight_update_sharding=layout.weight_update_sharding,
            )
        comp = step.lower(state, batch).compile()
        s = state
        for _ in range(warm):
            s, m = comp(s, batch)
        sync(m)
        dts = []
        for _ in range(trials):
            timer = StepTimer()
            timer.start()
            for _ in range(steps):
                s, m = comp(s, batch)
            dts.append(timer.stop(m) / steps)
        return {
            "layout": layout.to_json(),
            "step_time_ms": round(min(dts) * 1000, 3),
            "state_bytes_per_chip": measured_bytes,
        }

    def scaled_8k_model():
        """The resnet50_bf16_8k architecture shrunk to bench scale (input +
        width only — the layout story, LARS + ZeRO-1, is what is under
        test, not the FLOPs)."""
        return dc.replace(
            PRESETS["resnet50_bf16_8k"].model,
            input_shape=(32, 32),
            width_multiplier=0.25,
        )

    entries = {
        "cifar10_smoke": {
            "model": PRESETS["cifar10_smoke"].model,
            "train": PRESETS["cifar10_smoke"].train,
            "batch": 8 * n,
            "budgeted": False,
        },
        "resnet50_bf16_8k": {
            "model": scaled_8k_model(),
            "train": PRESETS["resnet50_bf16_8k"].train,
            "batch": 4 * n,
            # budget computed below to exclude the replicated optimizer
            # state: the planner must re-derive the preset's hand-tuned
            # ZeRO-1 choice from the budget, not copy it
            "budgeted": True,
        },
    }

    result: dict = {
        "n_chips": n,
        "timed_steps": steps,
        "trials": trials,
        "presets": {},
    }
    for name, entry in entries.items():
        mcfg, hand_tcfg = entry["model"], entry["train"]
        batch = entry["batch"]
        base_tcfg = dc.replace(
            hand_tcfg,
            model_parallel=1, pipeline_parallel=1, sequence_parallel=1,
            expert_parallel=1, weight_update_sharding=False,
        )
        profile = planner_lib.profile_model(mcfg, base_tcfg)
        topo = planner_lib.detect_topology(n)
        budget = None
        if entry["budgeted"]:
            # halfway between the plain-DP footprint and the ZeRO-1 one:
            # replicated opt state cannot fit, the sharded layouts can
            free = planner_lib.plan(
                mcfg, base_tcfg, batch, topology=topo, profile=profile,
                source="auto",
            )
            totals = {
                c.layout.describe(): c.bytes["total_bytes_per_chip"]
                for c in free.candidates
                if c.bytes
            }
            budget = (totals[f"dp{n}"] + totals[f"dp{n}xzero1"]) // 2
        plan = planner_lib.plan(
            mcfg, base_tcfg, batch, topology=topo, profile=profile,
            hbm_bytes_per_device=budget, source="auto",
        )
        hand_layout = planner_lib.Layout(
            data_parallel=n // max(
                hand_tcfg.model_parallel, hand_tcfg.pipeline_parallel,
                hand_tcfg.expert_parallel,
            ) // hand_tcfg.sequence_parallel,
            model_parallel=hand_tcfg.model_parallel,
            pipeline_parallel=hand_tcfg.pipeline_parallel,
            sequence_parallel=hand_tcfg.sequence_parallel,
            expert_parallel=hand_tcfg.expert_parallel,
            weight_update_sharding=hand_tcfg.weight_update_sharding,
        )
        auto = run_layout(mcfg, base_tcfg, plan.layout, batch)
        hand = run_layout(mcfg, hand_tcfg, hand_layout, batch)
        predicted = plan.chosen.bytes or {}
        predicted_state = (
            predicted.get("params_bytes_per_chip", 0)
            + predicted.get("batch_stats_bytes_per_chip", 0)
            + predicted.get("opt_state_bytes_per_chip", 0)
        )
        auto["predicted_state_bytes_per_chip"] = predicted_state
        auto["predicted_bytes_match"] = (
            predicted_state == auto["state_bytes_per_chip"]
        )
        ratio = auto["step_time_ms"] / max(hand["step_time_ms"], 1e-9)
        result["presets"][name] = {
            "global_batch": batch,
            "budget_bytes": budget,
            "auto": auto,
            "hand": hand,
            "layout_match": auto["layout"] == hand["layout"],
            "step_time_ratio_auto_over_hand": round(ratio, 3),
        }
    if check:
        ok = all(
            p["step_time_ratio_auto_over_hand"] <= max_ratio
            and p["auto"]["predicted_bytes_match"]
            for p in result["presets"].values()
        )
        result["check"] = {"max_ratio": max_ratio}
        result["check_passed"] = bool(ok)
    return result


def _peak_hbm_bytes() -> int:
    """Max ``peak_bytes_in_use`` across local devices; 0 when the backend
    does not implement the allocator query. Delegates to the capacity
    layer's one peak-extraction rule so the sentinel's gate and the ledger's
    watermarks can never diverge."""
    from tensorflowdistributedlearning_tpu.obs.capacity import (
        peak_bytes_across_devices,
    )

    return peak_bytes_across_devices()


def bench_trace_overhead(
    mesh=None, n: int | None = None, check: bool = False,
    max_ratio: float = 1.02,
) -> dict:
    """Tracing-overhead A/B (``TrainConfig.trace_sample_rate``).

    Runs the SAME compiled train step through the real telemetry span
    machinery twice — tracing disabled (sample rate 0, the default) vs fully
    on (rate 1.0: every step/data-wait span persists as a ``trace`` ledger
    event) — with best-of-N timing per mode. The span API is pure host
    bookkeeping (ids + perf_counter + one JSONL line per sampled span), so
    the cost must disappear under real device work.

    ``check`` gates the result (CI): traced step time must be <=
    ``max_ratio`` x untraced (the ISSUE's <= 2% budget → 1.02); the verdict
    is ``check_passed`` and ``main`` exits non-zero on failure.
    """
    import shutil
    import tempfile

    import jax
    import numpy as np
    from flax.core import unfreeze

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.obs.telemetry import (
        SPAN_DATA_WAIT,
        SPAN_STEP,
        Telemetry,
    )
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        BATCH_AXIS,
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.models import build_model

    if mesh is None:
        mesh = make_mesh(n)
    n = n or len(jax.devices())
    dp = int(mesh.shape[BATCH_AXIS])
    on_tpu = jax.devices()[0].platform == "tpu"

    if on_tpu:
        mcfg = ModelConfig(
            backbone="vit", num_classes=1000, input_shape=(224, 224),
            input_channels=3, patch_size=16, embed_dim=384, vit_layers=12,
            num_heads=6, output_stride=None,
        )
        per_chip, steps, log_every, trials = 64, 60, 10, 3
    else:
        # same smoke scale as the async-loop A/B: enough device work per step
        # that host-side bookkeeping has something real to hide behind
        mcfg = ModelConfig(
            backbone="vit", num_classes=10, input_shape=(32, 32),
            input_channels=3, patch_size=8, embed_dim=256, vit_layers=4,
            num_heads=4, output_stride=None,
        )
        per_chip, steps, log_every, trials = 4, 40, 5, 5
    tcfg = TrainConfig(optimizer="adam", lr=1e-3)
    model = build_model(mcfg)
    tx = make_optimizer(tcfg)
    sample = np.zeros((1, *mcfg.input_shape, mcfg.input_channels), np.float32)
    gb = per_chip * dp
    gen = np.random.default_rng(0)
    placed = [
        shard_batch(
            {
                "images": gen.normal(
                    0, 1, (gb, *mcfg.input_shape, mcfg.input_channels)
                ).astype(np.float32),
                "labels": gen.integers(0, mcfg.num_classes, gb).astype(np.int32),
            },
            mesh,
        )
        for _ in range(4)
    ]
    state0 = create_train_state(model, tx, jax.random.PRNGKey(0), sample)
    state0 = replicate(
        state0.replace(batch_stats=unfreeze(state0.batch_stats)), mesh
    )
    step = make_train_step(mesh, ClassificationTask(), donate=False)
    comp = step.lower(state0, placed[0]).compile()
    s = state0
    for i in range(3):  # warm executable + allocator off the clock
        s, m = comp(s, placed[i % len(placed)])
    jax.block_until_ready(m)

    def run(sample_rate: float) -> dict:
        dts = []
        spans_written = 0
        for _ in range(trials):
            workdir = tempfile.mkdtemp(prefix="bench_trace_")
            tel = Telemetry(
                workdir,
                run_info={"bench": "trace_overhead", "rate": sample_rate},
                memory_every_windows=10**6,
                trace_sample_rate=sample_rate,
            )
            st = state0
            t0 = time.perf_counter()
            for i in range(steps):
                # the real loop's span shape: data_wait + step per iteration
                with tel.span(SPAN_DATA_WAIT):
                    batch = placed[i % len(placed)]
                with tel.span(SPAN_STEP):
                    st, metrics = comp(st, batch)
                if (i + 1) % log_every == 0:
                    tel.window_event(i + 1, steps=log_every)
            jax.block_until_ready(st.params)
            dts.append(time.perf_counter() - t0)
            tel.close(steps=steps)
            try:
                from tensorflowdistributedlearning_tpu.obs.ledger import (
                    LEDGER_FILENAME,
                )

                with open(os.path.join(workdir, LEDGER_FILENAME)) as f:
                    spans_written = sum(
                        1 for line in f if '"event": "trace"' in line
                    )
            finally:
                shutil.rmtree(workdir, ignore_errors=True)
        best = min(dts)
        return {
            "step_time_ms": round(best / steps * 1000, 3),
            "loop_time_s": round(best, 3),
            "trace_events_per_run": spans_written,
        }

    off = run(0.0)
    on = run(1.0)
    ratio = on["step_time_ms"] / max(off["step_time_ms"], 1e-9)
    result = {
        "data_parallel": dp,
        "model": "vit_s16_imagenet_shape" if on_tpu else "vit_cpu_smoke",
        "global_batch": gb,
        "timed_steps": steps,
        "trials": trials,
        "tracing_off": off,
        "tracing_on": on,
        "step_time_ratio_traced_over_untraced": round(ratio, 4),
    }
    if check:
        result["check"] = {"max_ratio": max_ratio}
        result["check_passed"] = bool(ratio <= max_ratio)
    return result


def bench_capacity_overhead(
    mesh=None, n: int | None = None, check: bool = False,
    max_ratio: float = 1.01,
) -> dict:
    """Watermark+cost sampling overhead A/B (obs/capacity.py).

    The SAME compiled train step through the real telemetry machinery twice —
    ``capacity_sampling`` off vs on, with the memory probe forced onto EVERY
    window (``memory_every_windows=1``, the most aggressive cadence any
    config runs) — best-of-N per mode. Capacity sampling is one allocator
    query plus a handful of float ops per WINDOW (never per step), so the
    cost must vanish under real device work: the ISSUE's <= 1% budget →
    ``max_ratio`` 1.01, the same gate discipline as ``--trace-overhead``.
    """
    import shutil
    import tempfile

    import jax
    import numpy as np
    from flax.core import unfreeze

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.obs.telemetry import (
        SPAN_DATA_WAIT,
        SPAN_STEP,
        Telemetry,
    )
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        BATCH_AXIS,
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.models import build_model

    if mesh is None:
        mesh = make_mesh(n)
    n = n or len(jax.devices())
    dp = int(mesh.shape[BATCH_AXIS])
    on_tpu = jax.devices()[0].platform == "tpu"

    if on_tpu:
        mcfg = ModelConfig(
            backbone="vit", num_classes=1000, input_shape=(224, 224),
            input_channels=3, patch_size=16, embed_dim=384, vit_layers=12,
            num_heads=6, output_stride=None,
        )
        per_chip, steps, log_every, trials = 64, 60, 10, 3
    else:
        # same smoke scale as the trace-overhead A/B: enough device work per
        # step that per-window bookkeeping has something real to hide behind
        mcfg = ModelConfig(
            backbone="vit", num_classes=10, input_shape=(32, 32),
            input_channels=3, patch_size=8, embed_dim=256, vit_layers=4,
            num_heads=4, output_stride=None,
        )
        per_chip, steps, log_every, trials = 4, 40, 5, 5
    tcfg = TrainConfig(optimizer="adam", lr=1e-3)
    model = build_model(mcfg)
    tx = make_optimizer(tcfg)
    sample = np.zeros((1, *mcfg.input_shape, mcfg.input_channels), np.float32)
    gb = per_chip * dp
    gen = np.random.default_rng(0)
    placed = [
        shard_batch(
            {
                "images": gen.normal(
                    0, 1, (gb, *mcfg.input_shape, mcfg.input_channels)
                ).astype(np.float32),
                "labels": gen.integers(0, mcfg.num_classes, gb).astype(np.int32),
            },
            mesh,
        )
        for _ in range(4)
    ]
    state0 = create_train_state(model, tx, jax.random.PRNGKey(0), sample)
    state0 = replicate(
        state0.replace(batch_stats=unfreeze(state0.batch_stats)), mesh
    )
    step = make_train_step(mesh, ClassificationTask(), donate=False)
    comp = step.lower(state0, placed[0]).compile()
    s = state0
    for i in range(3):  # warm executable + allocator off the clock
        s, m = comp(s, placed[i % len(placed)])
    jax.block_until_ready(m)

    def run(sampling: bool) -> dict:
        dts = []
        capacity_events = 0
        for _ in range(trials):
            workdir = tempfile.mkdtemp(prefix="bench_capacity_")
            tel = Telemetry(
                workdir,
                run_info={"bench": "capacity_overhead", "sampling": sampling},
                # BOTH modes run the pre-existing memory snapshot on every
                # window (the worst cadence any config runs; default is every
                # 5th) so the A/B isolates exactly what capacity_sampling
                # adds: the watermark attribution + cost event per window
                memory_every_windows=1,
                capacity_sampling=sampling,
            )
            st = state0
            t0 = time.perf_counter()
            for i in range(steps):
                with tel.span(SPAN_DATA_WAIT):
                    batch = placed[i % len(placed)]
                with tel.span(SPAN_STEP):
                    st, metrics = comp(st, batch)
                if (i + 1) % log_every == 0:
                    tel.window_event(i + 1, steps=log_every, examples=gb * log_every)
            jax.block_until_ready(st.params)
            dts.append(time.perf_counter() - t0)
            tel.close(steps=steps)
            try:
                from tensorflowdistributedlearning_tpu.obs.ledger import (
                    LEDGER_FILENAME,
                )

                with open(os.path.join(workdir, LEDGER_FILENAME)) as f:
                    capacity_events = sum(
                        1
                        for line in f
                        if '"event": "cost"' in line
                        or '"event": "memory_watermark"' in line
                    )
            finally:
                shutil.rmtree(workdir, ignore_errors=True)
        best = min(dts)
        return {
            "step_time_ms": round(best / steps * 1000, 3),
            "loop_time_s": round(best, 3),
            "capacity_events_per_run": capacity_events,
        }

    off = run(False)
    on = run(True)
    ratio = on["step_time_ms"] / max(off["step_time_ms"], 1e-9)
    result = {
        "data_parallel": dp,
        "model": "vit_s16_imagenet_shape" if on_tpu else "vit_cpu_smoke",
        "global_batch": gb,
        "timed_steps": steps,
        "trials": trials,
        "sampling_off": off,
        "sampling_on": on,
        "step_time_ratio_sampled_over_plain": round(ratio, 4),
    }
    peak = _peak_hbm_bytes()
    if peak:
        result["peak_hbm_bytes"] = peak
    if check:
        result["check"] = {"max_ratio": max_ratio}
        result["check_passed"] = bool(ratio <= max_ratio)
    return result


def bench_profile_overhead(
    mesh=None, n: int | None = None, check: bool = False,
    max_ratio: float = 1.02,
) -> dict:
    """Continuous-profiling overhead A/B (``profile_every_windows``).

    The SAME compiled train step through the real telemetry machinery twice —
    profiler off (the default) vs a windowed jax.profiler capture landing
    mid-run at a sparse cadence (the documented deployment shape: captures
    every tens of windows, each ``capture_steps`` steps parsed into a
    ledgered roofline). The profiler's steady-state cost is one attribute
    read per step span; each cadence hit adds a bounded capture whose
    stop/parse/ledger runs on a background finalize thread, so the
    amortized step-time ratio must stay <= ``max_ratio`` (the <= 2% budget →
    1.02) — the same gate discipline as ``--trace-overhead`` /
    ``--capacity-overhead``. The check also requires at least one capture to
    actually land inside the timed loop: a run that never captured would
    pass the ratio vacuously.
    """
    import shutil
    import tempfile

    import jax
    import numpy as np
    from flax.core import unfreeze

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.obs.profiler import (
        ContinuousProfiler,
    )
    from tensorflowdistributedlearning_tpu.obs.telemetry import (
        SPAN_DATA_WAIT,
        SPAN_STEP,
        Telemetry,
    )
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        BATCH_AXIS,
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.models import build_model

    if mesh is None:
        mesh = make_mesh(n)
    n = n or len(jax.devices())
    dp = int(mesh.shape[BATCH_AXIS])
    on_tpu = jax.devices()[0].platform == "tpu"

    if on_tpu:
        mcfg = ModelConfig(
            backbone="vit", num_classes=1000, input_shape=(224, 224),
            input_channels=3, patch_size=16, embed_dim=384, vit_layers=12,
            num_heads=6, output_stride=None,
        )
        per_chip, steps, log_every, trials = 64, 55, 10, 3
        cadence = 2  # captures land at windows 2 and 4 (steps 20, 40)
    else:
        # same smoke scale as the other overhead A/Bs: enough steps that a
        # sparse-cadence capture amortizes the way a real run would. On a
        # core-starved CI box the background finalize (trace stop + parse)
        # steals cycles from the step loop itself, so the run must be long
        # enough for one full capture to amortize under the budget — the
        # honest worst case; real hosts have idle cores for it to hide on.
        mcfg = ModelConfig(
            backbone="vit", num_classes=10, input_shape=(32, 32),
            input_channels=3, patch_size=8, embed_dim=256, vit_layers=4,
            num_heads=4, output_stride=None,
        )
        per_chip, steps, log_every, trials = 4, 175, 5, 2
        cadence = 18  # one capture at window 18 (step 90), mid-run — 35
        # windows total, so no second capture starts on the final window
        # whose finalize would land outside the timed loop
    tcfg = TrainConfig(optimizer="adam", lr=1e-3)
    model = build_model(mcfg)
    tx = make_optimizer(tcfg)
    sample = np.zeros((1, *mcfg.input_shape, mcfg.input_channels), np.float32)
    gb = per_chip * dp
    gen = np.random.default_rng(0)
    placed = [
        shard_batch(
            {
                "images": gen.normal(
                    0, 1, (gb, *mcfg.input_shape, mcfg.input_channels)
                ).astype(np.float32),
                "labels": gen.integers(0, mcfg.num_classes, gb).astype(np.int32),
            },
            mesh,
        )
        for _ in range(4)
    ]
    state0 = create_train_state(model, tx, jax.random.PRNGKey(0), sample)
    state0 = replicate(
        state0.replace(batch_stats=unfreeze(state0.batch_stats)), mesh
    )
    step = make_train_step(mesh, ClassificationTask(), donate=False)
    comp = step.lower(state0, placed[0]).compile()
    s = state0
    for i in range(3):  # warm executable + allocator off the clock
        s, m = comp(s, placed[i % len(placed)])
    jax.block_until_ready(m)

    def run(every_windows: int) -> dict:
        dts = []
        captures = 0
        for _ in range(trials):
            workdir = tempfile.mkdtemp(prefix="bench_profile_")
            tel = Telemetry(
                workdir,
                run_info={
                    "bench": "profile_overhead", "every": every_windows,
                },
                memory_every_windows=10**6,
            )
            tel.set_step_flops(1.0, n_devices=1)  # pricing path exercised
            prof = ContinuousProfiler(tel, every_windows=every_windows)
            tel.set_profiler(prof)
            st = state0
            t0 = time.perf_counter()
            for i in range(steps):
                with tel.span(SPAN_DATA_WAIT):
                    batch = placed[i % len(placed)]
                with tel.span(SPAN_STEP):
                    st, metrics = comp(st, batch)
                if (i + 1) % log_every == 0:
                    tel.window_event(i + 1, steps=log_every)
            jax.block_until_ready(st.params)
            dts.append(time.perf_counter() - t0)
            tel.close(steps=steps)
            captures = prof.captures
            shutil.rmtree(workdir, ignore_errors=True)
        best = min(dts)
        return {
            "step_time_ms": round(best / steps * 1000, 3),
            "loop_time_s": round(best, 3),
            "captures_per_run": captures,
        }

    off = run(0)
    on = run(cadence)
    ratio = on["step_time_ms"] / max(off["step_time_ms"], 1e-9)
    result = {
        "data_parallel": dp,
        "model": "vit_s16_imagenet_shape" if on_tpu else "vit_cpu_smoke",
        "global_batch": gb,
        "timed_steps": steps,
        "trials": trials,
        "profile_every_windows": cadence,
        "profiling_off": off,
        "profiling_on": on,
        "step_time_ratio_profiled_over_plain": round(ratio, 4),
    }
    if check:
        result["check"] = {"max_ratio": max_ratio, "min_captures": 1}
        result["check_passed"] = bool(
            ratio <= max_ratio and on["captures_per_run"] >= 1
        )
    return result


def _run_child(platform: str, timeout: int) -> dict | None:
    args = [sys.executable, os.path.abspath(__file__), "--child"]
    if platform == "cpu":
        args.append("--platform=cpu")
    try:
        proc = subprocess.run(
            args,
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        # the child prints its headline line as soon as it is measured; a child
        # killed during the optional extras still yielded a usable number
        partial = e.stdout
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        for line in reversed((partial or "").strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    parsed = json.loads(line)
                    parsed["partial"] = True
                    return parsed
                except json.JSONDecodeError:
                    continue
        return {"__error__": f"{platform} child timed out after {timeout}s"}
    parsed = None
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if proc.returncode != 0:
        # a child killed mid-extras (OOM, libtpu abort) may still have printed
        # its headline line — salvage it rather than burning more attempts
        if parsed is not None:
            parsed["partial"] = True
            return parsed
        tail = (proc.stderr or proc.stdout or "").strip()[-400:]
        return {"__error__": f"{platform} child rc={proc.returncode}: {tail}"}
    if parsed is not None:
        return parsed
    return {"__error__": f"{platform} child produced no JSON line"}


# Last successful TPU measurement, persisted across runs: the tunneled backend
# in this environment goes down for hours at a time, and a dead tunnel at
# measurement time should not erase the perf evidence a live run produced.
# Degraded outputs carry the cached result (clearly labeled with its
# timestamp) alongside the fresh failure.
TPU_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_CACHE.json"
)


def _save_tpu_cache(result: dict) -> None:
    try:
        cached = dict(result)
        # MERGE with the existing record rather than replacing it: a partial
        # run (tunnel cut mid-extras) must not clobber sections an earlier
        # window DID land (segmentation_flagship, reference_family_wide,
        # kernel microbenches...). Fresh keys win; missing keys survive.
        now_unix = int(time.time())
        now = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
        # Stamp every fresh dict section with its own measurement time so
        # sections carried over from an earlier window keep THEIR stamp and
        # stale data is distinguishable from this run's.
        for key, value in list(cached.items()):
            if isinstance(value, dict) and "measured_at" not in value:
                # stamped COPY: the caller's result dict (printed as the
                # benchmark's own output) must not grow cache-only keys
                cached[key] = {**value, "measured_at": now}
        prior = _load_tpu_cache()
        if prior:
            prior_stamp = prior.get("measured_at")
            for key, value in prior.items():
                if key not in cached or (
                    isinstance(value, dict)
                    and isinstance(cached.get(key), dict)
                    and "error" in cached[key]
                    and "error" not in value
                ):
                    if (
                        isinstance(value, dict)
                        and "measured_at" not in value
                        and prior_stamp
                    ):
                        value = {**value, "measured_at": prior_stamp}
                    cached[key] = value
        cached["measured_at_unix"] = now_unix
        cached["measured_at"] = now
        with open(TPU_CACHE_PATH, "w") as f:
            json.dump(cached, f, indent=1)
    except OSError:
        pass  # read-only checkout: caching is best-effort


def _load_tpu_cache() -> dict | None:
    try:
        with open(TPU_CACHE_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _force_host_devices() -> None:
    """8-device host platform for the standalone A/B modes: a dp=1 run is a
    vacuous A/B on CPU, and the env var is inert when a real TPU answers
    (the flag only shapes the host platform; the backend initializes lazily
    at the first device query)."""
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()


def main() -> None:
    if "--async-loop" in sys.argv:
        # Standalone sync-vs-async host loop A/B (committed as
        # BENCH_ASYNC.json); --check turns it into a pass/fail gate.
        _force_host_devices()
        import jax

        if "--platform=cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        check = "--check" in sys.argv
        max_ratio = 1.05
        if "--max-ratio" in sys.argv:
            max_ratio = float(sys.argv[sys.argv.index("--max-ratio") + 1])
        out = bench_async_loop(check=check, max_ratio=max_ratio)
        out["platform"] = jax.devices()[0].platform
        out["device_kind"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out), flush=True)
        if check and not out.get("check_passed"):
            sys.exit(1)
        return
    if "--trace-overhead" in sys.argv:
        # Tracing-cost A/B (obs/trace.py): step time with trace_sample_rate
        # 1.0 vs 0.0; --check gates the <=2% budget (CI).
        _force_host_devices()
        import jax

        if "--platform=cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        check = "--check" in sys.argv
        max_ratio = 1.02
        if "--max-ratio" in sys.argv:
            max_ratio = float(sys.argv[sys.argv.index("--max-ratio") + 1])
        out = bench_trace_overhead(check=check, max_ratio=max_ratio)
        out["platform"] = jax.devices()[0].platform
        out["device_kind"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out), flush=True)
        if check and not out.get("check_passed"):
            sys.exit(1)
        return
    if "--capacity-overhead" in sys.argv:
        # Watermark+cost sampling A/B (obs/capacity.py): step time with
        # capacity sampling fully on (memory probe every window) vs off;
        # --check gates the <=1% budget (CI).
        _force_host_devices()
        import jax

        if "--platform=cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        check = "--check" in sys.argv
        max_ratio = 1.01
        if "--max-ratio" in sys.argv:
            max_ratio = float(sys.argv[sys.argv.index("--max-ratio") + 1])
        out = bench_capacity_overhead(check=check, max_ratio=max_ratio)
        out["platform"] = jax.devices()[0].platform
        out["device_kind"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out), flush=True)
        if check and not out.get("check_passed"):
            sys.exit(1)
        return
    if "--profile-overhead" in sys.argv:
        # Continuous-profiling A/B (obs/profiler.py): step time with a
        # sparse-cadence windowed jax.profiler capture landing mid-run vs
        # profiler off; --check gates the <=2% budget (CI).
        _force_host_devices()
        import jax

        if "--platform=cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        check = "--check" in sys.argv
        max_ratio = 1.02
        if "--max-ratio" in sys.argv:
            max_ratio = float(sys.argv[sys.argv.index("--max-ratio") + 1])
        out = bench_profile_overhead(check=check, max_ratio=max_ratio)
        out["platform"] = jax.devices()[0].platform
        out["device_kind"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out), flush=True)
        if check and not out.get("check_passed"):
            sys.exit(1)
        return
    if "--plan" in sys.argv:
        # Parallelism-planner A/B: auto layout vs the hand-tuned preset
        # layouts through real train steps (committed as BENCH_PLAN.json);
        # --check gates step-time ratio <= 1.05 and exact bytes accounting.
        _force_host_devices()
        import jax

        if "--platform=cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        check = "--check" in sys.argv
        max_ratio = 1.05
        if "--max-ratio" in sys.argv:
            max_ratio = float(sys.argv[sys.argv.index("--max-ratio") + 1])
        out = bench_plan(check=check, max_ratio=max_ratio)
        out["platform"] = jax.devices()[0].platform
        out["device_kind"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out), flush=True)
        if check and not out.get("check_passed"):
            sys.exit(1)
        return
    if "--zero1" in sys.argv:
        # Standalone ZeRO-1 section on whatever platform answers (committed
        # as BENCH_ZERO1.json; the TPU supervisor path also embeds it in the
        # full run as result["weight_update_sharding"]).
        _force_host_devices()
        import jax

        if "--platform=cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        out = bench_weight_update_sharding()
        out["platform"] = jax.devices()[0].platform
        out["device_kind"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out), flush=True)
        return
    if "--child" in sys.argv:
        # Child mode: do the measurement; any crash surfaces via rc + stderr.
        platform = "cpu" if "--platform=cpu" in sys.argv else None
        print(json.dumps(run_benchmark(platform)), flush=True)
        return

    errors = []
    # TPU attempts with backoff, bounded per attempt (a hung backend init in the
    # child is killed by the timeout instead of wedging the driver).
    for attempt in range(TPU_ATTEMPTS):
        result = _run_child("tpu", TPU_TIMEOUT_SECS)
        if result is not None and "__error__" not in result:
            if result.get("platform") != "tpu":
                # the child initialized some other backend (tunnel down but jax
                # found a fallback): that is a FAILED TPU attempt — routing it
                # through the degraded path keeps the headline honest
                errors.append(
                    f"tpu child ran on platform={result.get('platform')!r}"
                )
            else:
                _save_tpu_cache(result)
                print(json.dumps(result), flush=True)
                return
        else:
            errors.append(result["__error__"] if result else "no result")
        if attempt < TPU_ATTEMPTS - 1:  # no pointless backoff before the fallback
            time.sleep(min(30 * (attempt + 1), 60))

    cached = _load_tpu_cache()

    # Degraded path. The CPU child is a LIVENESS PROBE (the software path
    # still measures end to end), never the headline: the committed artifact's
    # top-level metric/value/vs_baseline must stay a TPU truth — fresh when
    # the tunnel answers, explicitly stale (stale=true + measured_at) when it
    # does not. Round 4's artifact led with 30 img/s vs_baseline=0.084 from a
    # dead tunnel and the real number needed archaeology; this ordering is the
    # fix.
    probe = _run_child("cpu", CPU_TIMEOUT_SECS)
    probe_ok = probe is not None and "__error__" not in probe
    if not probe_ok:
        errors.append(probe["__error__"] if probe else "no result")

    if cached is not None:
        result = dict(cached)
        result["stale"] = True
        result["degraded"] = True
        result["error"] = "TPU unavailable: " + " | ".join(errors)
        if probe_ok:
            result["fallback_probe"] = probe
        print(json.dumps(result), flush=True)
        return

    # No TPU cache exists (first run ever on this checkout): the CPU probe is
    # the only real measurement there is — promote it, clearly degraded.
    if probe_ok:
        probe["error"] = "TPU unavailable: " + " | ".join(errors)
        probe["degraded"] = True
        print(json.dumps(probe), flush=True)
        return

    # Last resort: a syntactically valid JSON line with the failure recorded.
    fallback = {
        "metric": "resnet50_imagenet_train_throughput_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": " | ".join(errors),
    }
    print(json.dumps(fallback), flush=True)


if __name__ == "__main__":
    main()
