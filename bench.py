"""Headline benchmark: ResNet-50 ImageNet-shape training throughput, images/sec/chip.

BASELINE.json's metric is "ImageNet ResNet-50 images/sec/chip"; the reference era's
per-chip number for the same job (TF1 fp32 ResNet-50 on a V100, the hardware the
reference's 2-GPU MirroredStrategy runs used) is ~360 images/sec/chip, which is the
``vs_baseline`` denominator here.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

V100_FP32_RESNET50_IMAGES_PER_SEC = 360.0


def main() -> None:
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        make_optimizer,
        make_train_step,
    )

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n = len(devices)

    if on_tpu:
        # ResNet-50 ImageNet config, bfloat16 on the MXU. output_stride=None is the
        # standard stride-32 classification architecture (the atrous output_stride=8
        # default is the segmentation flagship and does ~3x the FLOPs/image).
        cfg = ModelConfig(
            num_classes=1000,
            input_shape=(224, 224),
            input_channels=3,
            n_blocks=(3, 4, 6),
            dtype="bfloat16",
            output_stride=None,
        )
        per_chip_batch = 256
        timed_steps, warmup = 20, 3
    else:
        # CPU fallback (local smoke): tiny model, tiny batch
        cfg = ModelConfig(
            num_classes=10,
            input_shape=(32, 32),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=32,
        )
        per_chip_batch = 8
        timed_steps, warmup = 3, 1

    global_batch = per_chip_batch * n
    mesh = make_mesh(n)
    model = build_model(cfg)
    tx = make_optimizer(TrainConfig())
    h, w = cfg.input_shape
    rng = jax.random.PRNGKey(0)
    sample = np.zeros((1, h, w, cfg.input_channels), np.float32)
    state = replicate(create_train_state(model, tx, rng, sample), mesh)

    rng_np = np.random.default_rng(0)
    batch = {
        "images": rng_np.normal(0, 1, (global_batch, h, w, cfg.input_channels)).astype(
            np.float32
        ),
        "labels": rng_np.integers(0, cfg.num_classes, global_batch).astype(np.int32),
    }
    batch = shard_batch(batch, mesh)

    from tensorflowdistributedlearning_tpu.utils.profiling import sync

    # donate=False: `batch` and `state` are reused across calls here; the trainer's
    # production path donates. profiling.sync pulls a value that depends on the last
    # step — on the tunneled TPU platform block_until_ready alone has been observed
    # to return before execution finishes, inflating throughput ~10x.
    step = make_train_step(mesh, ClassificationTask(), donate=False)
    for _ in range(warmup):
        state, metrics = step(state, batch)
    sync(metrics)

    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, metrics = step(state, batch)
    sync(metrics)
    dt = time.perf_counter() - t0

    images_per_sec_per_chip = global_batch * timed_steps / dt / n
    print(
        json.dumps(
            {
                "metric": "resnet50_imagenet_train_throughput_per_chip"
                if on_tpu
                else "resnet_tiny_cpu_train_throughput_per_chip",
                "value": round(images_per_sec_per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    images_per_sec_per_chip / V100_FP32_RESNET50_IMAGES_PER_SEC, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
