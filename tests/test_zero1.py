"""ZeRO-1 cross-replica weight-update sharding (parallel/zero.py,
``TrainConfig.weight_update_sharding``).

What must hold, on the forced 8-device CPU mesh:

- spec rule: every optimizer-state leaf partitions along the ``batch`` axis on
  its LARGEST dp-divisible dimension; scalars/indivisible leaves replicate;
  under tensor parallelism the batch shard composes with (never collides
  with) the model-axis channel sharding;
- placement: Adam moments AND the EMA tracker land sharded (1/dp per-chip
  bytes), params stay replicated;
- equivalence: a sharded-update run matches the replicated-update run
  STEP-FOR-STEP within tolerance — with donation on, through the multi-step
  scan, and through gradient accumulation (acceptance criteria of ISSUE 4);
- checkpoints: a sharded run's checkpoint restores into a replicated template
  and vice versa (the resume-across-modes contract), with values intact and
  the target placement honored.
"""

import os
import subprocess
import sys
import tempfile

if __name__ == "__main__":
    # subprocess worker mode (test_fit_end_to_end_with_weight_update_sharding
    # runs the e2e in a fresh interpreter): repo root onto sys.path — a
    # script invocation gets tests/ there instead
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import synthetic_batches
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    MODEL_AXIS,
    largest_divisible_dim,
    make_mesh,
    replicate,
    shard_batch,
    shard_batch_stacked,
)
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.state import (
    create_train_state,
    tree_bytes_per_device,
)

TINY_VIT = ModelConfig(
    backbone="vit",
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    patch_size=4,
    embed_dim=32,
    vit_layers=2,
    num_heads=4,
    output_stride=None,
)
# the everything-on optimizer chain: clip -> AdamW(kernels-only decay) -> EMA
FULL_CHAIN = TrainConfig(
    optimizer="adam", lr=0.01, weight_decay=1e-4, ema_decay=0.9,
    grad_clip_norm=1.0,
)


def _state(tcfg, mesh=None, cfg=TINY_VIT, zero=False):
    from flax.core import unfreeze

    model = build_model(cfg)
    tx = step_lib.make_optimizer(tcfg)
    shape = (1,) + cfg.input_shape + (cfg.input_channels,)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.ones(shape, jnp.float32)
    )
    # plain-dict batch_stats: flax's mutable apply returns dicts, and the
    # multi-step scan needs one stable carry pytree type (the same
    # normalization bench.py's ViT section applies)
    state = state.replace(batch_stats=unfreeze(state.batch_stats))
    if mesh is None:
        return state
    if zero:
        return zero_lib.shard_state_weight_update(state, mesh)
    return replicate(state, mesh)


def _batches(n_steps, batch=32, seed=0):
    return list(
        synthetic_batches(
            "classification", batch, seed=seed, steps=n_steps,
            input_shape=(16, 16), channels=3, num_classes=4,
        )
    )


# -- spec rule ---------------------------------------------------------------


def test_largest_divisible_dim():
    assert largest_divisible_dim((16, 8), 8) == 0
    assert largest_divisible_dim((4, 16), 8) == 1
    assert largest_divisible_dim((3, 5), 8) is None
    assert largest_divisible_dim((), 8) is None
    # `taken` dims are skipped even when they divide
    assert largest_divisible_dim((16, 8), 8, taken={0}) == 1
    assert largest_divisible_dim((16, 5), 8, taken={0}) is None


def test_weight_update_spec_partitions_largest_dim():
    mesh = make_mesh(8)
    assert zero_lib.weight_update_spec((16, 8), mesh) == P(BATCH_AXIS, None)
    assert zero_lib.weight_update_spec((4, 16), mesh) == P(None, BATCH_AXIS)
    assert zero_lib.weight_update_spec((3, 3, 8, 16), mesh) == P(
        None, None, None, BATCH_AXIS
    )
    # scalars and indivisible leaves replicate (the cheap tail)
    assert zero_lib.weight_update_spec((), mesh) == P()
    assert zero_lib.weight_update_spec((3, 5), mesh) == P()
    assert zero_lib.weight_update_spec((7,), mesh) == P()


def test_weight_update_spec_composes_with_tensor_parallel():
    mesh = make_mesh(8, model_parallel=2)  # dp=4, tp=2
    # trailing dim goes to the model axis (the TP channel rule); the batch
    # axis takes the largest FREE dim that divides dp
    spec = zero_lib.weight_update_spec((3, 3, 8, 16), mesh, tensor_parallel=True)
    assert spec == P(None, None, BATCH_AXIS, MODEL_AXIS)
    # nothing free divides dp -> batch stacks onto the channel dim
    spec = zero_lib.weight_update_spec((5, 16), mesh, tensor_parallel=True)
    assert spec == P(None, (MODEL_AXIS, BATCH_AXIS))
    # nothing divides at all -> TP-only
    spec = zero_lib.weight_update_spec((5, 6), mesh, tensor_parallel=True)
    assert spec == P(None, MODEL_AXIS)


def test_opt_state_specs_cover_moments_and_ema():
    """The spec tree derived from a real optimizer chain: Adam mu/nu and the
    EMA tracker shard; schedule counters stay replicated."""
    mesh = make_mesh(8)
    state = _state(FULL_CHAIN)
    specs = zero_lib.weight_update_specs(state.opt_state, mesh)
    flat = {
        jax.tree_util.keystr(path): spec
        for path, spec in jax.tree_util.tree_leaves_with_path(specs)
    }
    sharded = [k for k, s in flat.items() if s != P()]
    scalar = [k for k, s in flat.items() if s == P()]
    # the bulk of the slots shard: mu, nu, and the EMA all mirror params
    assert sum(".mu" in k for k in sharded) > 5
    assert sum(".nu" in k for k in sharded) > 5
    assert sum(".ema" in k for k in sharded) > 5
    # the schedule step counter is scalar and must replicate
    assert any("count" in k for k in scalar)


# -- placement + accounting --------------------------------------------------


def test_placement_shards_opt_state_not_params():
    mesh = make_mesh(8)
    state = _state(FULL_CHAIN, mesh, zero=True)
    for leaf in jax.tree.leaves(state.params):
        assert leaf.sharding.spec == P()
    flat = jax.tree_util.tree_leaves_with_path(state.opt_state)
    n_sharded = sum(1 for _, leaf in flat if leaf.sharding.spec != P())
    assert n_sharded > 0.8 * len(flat)  # only scalars/tiny leaves replicate
    # a sharded leaf really holds 1/8 per device
    sharded_leaf = next(
        leaf for _, leaf in flat if leaf.sharding.spec != P()
    )
    shard_elems = np.prod(sharded_leaf.sharding.shard_shape(sharded_leaf.shape))
    assert shard_elems * 8 == np.prod(sharded_leaf.shape)


def test_per_device_bytes_drop_by_dp():
    mesh = make_mesh(8)
    rep = _state(FULL_CHAIN, mesh)
    zero = _state(FULL_CHAIN, mesh, zero=True)
    rep_bytes = tree_bytes_per_device(rep.opt_state)
    zero_bytes = tree_bytes_per_device(zero.opt_state)
    # ~dp-fold reduction (the replicated scalar tail keeps it under exactly 8)
    assert rep_bytes / zero_bytes > 6.0
    # params are replicated in both modes
    assert tree_bytes_per_device(rep.params) == tree_bytes_per_device(zero.params)


# -- equivalence (the acceptance criterion) ----------------------------------


def _assert_states_close(a, b, atol):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(a.params)),
        jax.tree.leaves(jax.device_get(b.params)),
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


def test_sharded_update_matches_replicated_step_for_step():
    """3 donated steps, full optimizer chain (clip -> AdamW -> EMA): params
    agree within float32 tolerance after EVERY step and the metric streams
    are identical. Adam's eps-divide amplifies reduction-order noise in the
    early steps, hence the 1e-3 bound (SGD below pins a much tighter one)."""
    mesh = make_mesh(8)
    task = step_lib.ClassificationTask()
    rep_step = step_lib.make_train_step(mesh, task)  # donate=True default
    zero_step = step_lib.make_train_step(
        mesh, task, weight_update_sharding=True
    )
    rep = _state(FULL_CHAIN, mesh)
    zero = _state(FULL_CHAIN, mesh, zero=True)
    for raw in _batches(3):
        batch = shard_batch(raw, mesh)
        rep, m_rep = rep_step(rep, batch)
        zero, m_zero = zero_step(zero, batch)
        _assert_states_close(rep, zero, atol=1e-3)
        assert step_lib.compute_metrics(jax.device_get(m_rep))[
            "loss"
        ] == pytest.approx(
            step_lib.compute_metrics(jax.device_get(m_zero))["loss"], rel=1e-5
        )
    assert int(jax.device_get(zero.step)) == 3
    # the carried opt_state stayed sharded through the donated updates
    flat = jax.tree_util.tree_leaves_with_path(zero.opt_state)
    assert sum(1 for _, leaf in flat if leaf.sharding.spec != P()) > 0.8 * len(flat)
    # the EMA tracker rode along sharded and matches the replicated one
    ema_rep = step_lib.find_ema_params(rep.opt_state)
    ema_zero = step_lib.find_ema_params(zero.opt_state)
    for x, y in zip(jax.tree.leaves(ema_rep), jax.tree.leaves(ema_zero)):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)),
            atol=1e-3,
        )


def test_sharded_update_matches_replicated_sgd_tight():
    """SGD+momentum (no eps-divide): the sharded update is the same math in a
    different layout, so the agreement bound is near-bitwise."""
    mesh = make_mesh(8)
    tcfg = TrainConfig(optimizer="sgd", lr=0.05, weight_decay=1e-4)
    task = step_lib.ClassificationTask()
    rep_step = step_lib.make_train_step(mesh, task)
    zero_step = step_lib.make_train_step(
        mesh, task, weight_update_sharding=True
    )
    rep = _state(tcfg, mesh)
    zero = _state(tcfg, mesh, zero=True)
    for raw in _batches(3, seed=11):
        batch = shard_batch(raw, mesh)
        rep, _ = rep_step(rep, batch)
        zero, _ = zero_step(zero, batch)
        _assert_states_close(rep, zero, atol=1e-5)


def test_multi_step_scan_with_sharded_update():
    """The device-side K-step loop (make_multi_train_step) composes: one
    dispatch runs 2 zero-mode steps under lax.scan with donation, matching
    2 sequential replicated steps within the scan's reassociation tolerance
    (same bound family as test_multi_step_matches_sequential)."""
    mesh = make_mesh(8)
    task = step_lib.ClassificationTask()
    raws = _batches(2, seed=3)
    stacked = shard_batch_stacked(
        {k: np.stack([b[k] for b in raws]) for k in raws[0]}, mesh
    )
    multi_zero = step_lib.make_multi_train_step(
        mesh, task, n_steps=2, weight_update_sharding=True
    )
    zero_final, m_multi = multi_zero(_state(FULL_CHAIN, mesh, zero=True), stacked)

    rep_step = step_lib.make_train_step(mesh, task, donate=False)
    rep = _state(FULL_CHAIN, mesh)
    m_seq = None
    for raw in raws:
        rep, m = rep_step(rep, shard_batch(raw, mesh))
        m_seq = step_lib.merge_metrics(m_seq, jax.device_get(m))
    assert int(jax.device_get(zero_final.step)) == 2
    _assert_states_close(rep, zero_final, atol=2e-3)
    assert step_lib.compute_metrics(jax.device_get(m_multi))[
        "loss"
    ] == pytest.approx(step_lib.compute_metrics(m_seq)["loss"], rel=1e-4)
    # opt_state leaves still sharded in the scan-carried result
    flat = jax.tree_util.tree_leaves_with_path(zero_final.opt_state)
    assert sum(1 for _, leaf in flat if leaf.sharding.spec != P()) > 0.8 * len(flat)


def test_grad_accum_with_sharded_update():
    """accum=4 microbatches + ZeRO-1 == accum=4 replicated (BN-free model:
    the accumulated mean gradient is identical, the update is the same math
    sharded)."""
    mesh = make_mesh(8)
    tcfg = TrainConfig(optimizer="sgd", lr=0.01, weight_decay=1e-4)
    task = step_lib.ClassificationTask()
    raw = _batches(1)[0]
    batch = shard_batch(raw, mesh)
    rep_step = step_lib.make_train_step(mesh, task, donate=False, accum=4)
    zero_step = step_lib.make_train_step(
        mesh, task, donate=False, accum=4, weight_update_sharding=True
    )
    rep, m_rep = rep_step(_state(tcfg, mesh), batch)
    zero, m_zero = zero_step(_state(tcfg, mesh, zero=True), batch)
    _assert_states_close(rep, zero, atol=1e-5)
    assert step_lib.compute_metrics(jax.device_get(m_rep))[
        "loss"
    ] == pytest.approx(
        step_lib.compute_metrics(jax.device_get(m_zero))["loss"], rel=1e-5
    )


def test_gspmd_tensor_parallel_composition():
    """fit()'s TP path: optimizer slots shard over (model, batch) jointly and
    the constrained GSPMD update matches the plain TP update."""
    from tensorflowdistributedlearning_tpu.data.synthetic import (
        synthetic_classification_batch,
    )
    from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

    cfg = ModelConfig(
        num_classes=8, input_shape=(16, 16), input_channels=3,
        n_blocks=(1, 1, 1), base_depth=16, width_multiplier=0.125,
        output_stride=None,
    )
    mesh = make_mesh(8, model_parallel=2)  # dp=4, tp=2
    state = _state(TrainConfig(), cfg=cfg)
    placed = tp_lib.shard_state_weight_update(state, mesh)
    mu = placed.opt_state[0].mu["backbone"]["conv1_3"]["conv"]["kernel"]
    assert BATCH_AXIS in jax.tree.leaves(tuple(mu.sharding.spec)) or any(
        BATCH_AXIS in (axes if isinstance(axes, tuple) else (axes,))
        for axes in mu.sharding.spec
        if axes is not None
    )
    batch = synthetic_classification_batch(
        np.random.default_rng(0), 8, input_shape=(16, 16), channels=3,
        num_classes=8,
    )
    zero_step = tp_lib.make_train_step_gspmd(
        mesh, step_lib.ClassificationTask(), donate=False,
        weight_update_sharding=True,
    )
    new_zero, m_zero = zero_step(placed, tp_lib.place_batch_gspmd(batch, mesh))
    # slots stay (model, batch)-sharded after the constrained update
    mu2 = new_zero.opt_state[0].mu["backbone"]["conv1_3"]["conv"]["kernel"]
    spec_axes = [
        a for axes in mu2.sharding.spec if axes is not None
        for a in (axes if isinstance(axes, tuple) else (axes,))
    ]
    assert BATCH_AXIS in spec_axes and MODEL_AXIS in spec_axes

    rep_step = tp_lib.make_train_step_gspmd(
        mesh, step_lib.ClassificationTask(), donate=False
    )
    new_rep, m_rep = rep_step(
        tp_lib.shard_state_tensor_parallel(_state(TrainConfig(), cfg=cfg), mesh),
        tp_lib.place_batch_gspmd(batch, mesh),
    )
    assert step_lib.compute_metrics(jax.device_get(m_zero))[
        "loss"
    ] == pytest.approx(
        step_lib.compute_metrics(jax.device_get(m_rep))["loss"], rel=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(
            jax.device_get(new_zero.params["backbone"]["conv1_3"]["conv"]["kernel"])
        ),
        np.asarray(
            jax.device_get(new_rep.params["backbone"]["conv1_3"]["conv"]["kernel"])
        ),
        atol=1e-3,
    )


# -- checkpoint round trip across sharding modes -----------------------------


def _ckpt(directory):
    from tensorflowdistributedlearning_tpu.train.checkpoint import (
        CheckpointManager,
    )

    return CheckpointManager(directory, save_every_steps=1)


def test_checkpoint_roundtrip_sharded_to_replicated_and_back():
    mesh = make_mesh(8)
    task = step_lib.ClassificationTask()
    zero_step = step_lib.make_train_step(
        mesh, task, donate=False, weight_update_sharding=True
    )
    zero = _state(FULL_CHAIN, mesh, zero=True)
    zero, _ = zero_step(zero, shard_batch(_batches(1)[0], mesh))

    with tempfile.TemporaryDirectory() as d:
        ckpt = _ckpt(os.path.join(d, "a"))
        try:
            assert ckpt.save(zero, force=True)
            # sharded run's checkpoint -> REPLICATED template
            rep = ckpt.restore_latest(_state(FULL_CHAIN, mesh))
        finally:
            ckpt.close()
    assert int(jax.device_get(rep.step)) == 1
    for a, b in zip(
        jax.tree.leaves(jax.device_get(zero.opt_state)),
        jax.tree.leaves(jax.device_get(rep.opt_state)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for leaf in jax.tree.leaves(rep.opt_state):
        assert leaf.sharding.spec == P()

    with tempfile.TemporaryDirectory() as d:
        ckpt = _ckpt(os.path.join(d, "b"))
        try:
            assert ckpt.save(rep, force=True)
            # replicated checkpoint -> ZERO-sharded template
            zero2 = ckpt.restore_latest(_state(FULL_CHAIN, mesh, zero=True))
        finally:
            ckpt.close()
    flat = jax.tree_util.tree_leaves_with_path(zero2.opt_state)
    assert sum(1 for _, leaf in flat if leaf.sharding.spec != P()) > 0.8 * len(flat)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(rep.opt_state)),
        jax.tree.leaves(jax.device_get(zero2.opt_state)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the restored-into-sharded state keeps TRAINING correctly
    zero2, m = zero_step(zero2, shard_batch(_batches(1, seed=9)[0], mesh))
    assert np.isfinite(step_lib.compute_metrics(jax.device_get(m))["loss"])
    assert int(jax.device_get(zero2.step)) == 2


# -- trainer wiring ----------------------------------------------------------


def test_fit_end_to_end_with_weight_update_sharding(tmp_path):
    """ClassifierTrainer.fit() trains, checkpoints, evaluates, and RESUMES
    through the ZeRO-1 path — and the run ledger records the per-device
    opt-state bytes the mode exists to shrink.

    Runs in a FRESH SUBPROCESS interpreter (the resilience e2e's isolation
    pattern): compiling this BN-backbone double-fit inside a long-lived
    suite process flakily crashes this box's XLA:CPU — the root-conftest-
    documented cumulative-compile crash, seen here as SIGSEGV or SIGABRT at
    either fit's compile, with the persistent-cache writer thread one of the
    triggers — while a fresh interpreter passes deterministically. The
    worker is this file's ``__main__`` mode; compile cache off in the child
    for the same reason the resilience worker keeps it off."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["TFDL_NO_COMPILE_CACHE"] = "1"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0 and "FIT_E2E_OK" in (out.stdout or ""), (
        f"fit e2e worker failed rc={out.returncode}\n"
        f"stdout:{(out.stdout or '')[-3000:]}\n"
        f"stderr:{(out.stderr or '')[-2000:]}"
    )


def _run_fit_e2e(tmp_path):
    import json

    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    model_cfg = ModelConfig(
        num_classes=3, input_shape=(8, 8), input_channels=1,
        n_blocks=(1, 1, 1), block_type="basic_block", width_multiplier=0.25,
        output_stride=None,
    )
    train_cfg = TrainConfig(
        optimizer="adam", lr=0.01, weight_update_sharding=True,
        checkpoint_every_steps=2, ema_decay=0.9,
    )
    workdir = str(tmp_path / "run")
    trainer = ClassifierTrainer(workdir, None, model_cfg, train_cfg)
    result = trainer.fit(batch_size=16, steps=3, eval_every_steps=3)
    assert result.steps == 3
    assert np.isfinite(result.final_metrics["loss"])

    # the memory event carries the exact per-device opt-state accounting
    events = [
        json.loads(line)
        for line in open(os.path.join(workdir, "telemetry.jsonl"))
    ]
    mem = [e for e in events if e.get("event") == "memory"]
    assert any(e.get("weight_update_sharding") for e in mem)
    tracked = [e for e in mem if "opt_state_bytes_per_device" in e]
    assert tracked
    # sharded slots are well under the replicated footprint (~3x params
    # with adam+ema; sharded ~3x/8 + replicated tail)
    assert (
        tracked[-1]["opt_state_bytes_per_device"]
        < tracked[-1]["params_bytes_per_device"]
    )

    # resume continues through the zero path (restore into sharded template)
    trainer2 = ClassifierTrainer(workdir, None, model_cfg, train_cfg)
    result2 = trainer2.fit(batch_size=16, steps=5, eval_every_steps=5)
    assert result2.steps == 5


def test_config_validation():
    with pytest.raises(ValueError, match="weight_update_sharding"):
        TrainConfig(weight_update_sharding=True, pipeline_parallel=2)
    # the modes it composes with all construct
    TrainConfig(weight_update_sharding=True, grad_accum_steps=2)
    TrainConfig(weight_update_sharding=True, sequence_parallel=2)
    TrainConfig(weight_update_sharding=True, model_parallel=2)
    TrainConfig(weight_update_sharding=True, sync_batch_norm=True)


def test_merge_stacked_metrics_rejects_non_mean_leaf():
    """The one shared merge of both scan paths fails loudly on anything that
    is not a Mean state — a blind sum would silently mis-merge it."""
    from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib

    stacked = {
        "loss": metrics_lib.Mean(
            total=jnp.ones((3,)), count=jnp.ones((3,))
        ),
        "rogue": jnp.ones((3,)),
    }
    with pytest.raises(TypeError, match="rogue"):
        step_lib._merge_stacked_metrics(stacked)
    ok = step_lib._merge_stacked_metrics(
        {"loss": metrics_lib.Mean(total=jnp.ones((3,)), count=jnp.ones((3,)))}
    )
    assert float(ok["loss"].total) == 3.0


if __name__ == "__main__":
    # worker mode for test_fit_end_to_end_with_weight_update_sharding's
    # subprocess: run the double-fit e2e against the given workdir and print
    # a sentinel the parent asserts on (any assert/crash surfaces via rc)
    import pathlib

    _run_fit_e2e(pathlib.Path(sys.argv[1]))
    print("FIT_E2E_OK", flush=True)
