"""The window-sprint supervisor (tools/window_sprint.py) runs unattended when
a TPU tunnel window opens; these tests pin its orchestration contract so a
regression cannot silently waste a window: sections run in order under their
own budgets, JSON lines from children are captured, timeouts/skips are
recorded, and every outcome lands in WINDOW_SPRINT.jsonl."""

import importlib.util
import json
import os
import sys


def _load(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "window_sprint",
        os.path.join(os.path.dirname(__file__), "..", "tools", "window_sprint.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.OUT = str(tmp_path / "sprint.jsonl")
    return mod


def test_sections_record_output_skip_and_timeout(tmp_path, capsys, monkeypatch):
    mod = _load(tmp_path)
    mod.SECTIONS = [
        (
            "ok",
            [sys.executable, "-c", "print('{\"hello\": 1}')"],
            30,
        ),
        ("skipme", [sys.executable, "-c", "print('never')"], 30),
        (
            "fails",
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            30,
        ),
        (
            "hangs",
            [sys.executable, "-c", "import time; time.sleep(60)"],
            1,
        ),
    ]
    monkeypatch.setattr(sys, "argv", ["window_sprint.py", "--skip", "skipme"])
    assert mod.main() == 0

    entries = [
        json.loads(line) for line in open(mod.OUT).read().strip().splitlines()
    ]
    by_name = {e["section"]: e for e in entries}
    assert by_name["ok"]["rc"] == 0
    assert by_name["ok"]["output"] == [{"hello": 1}]
    assert by_name["skipme"]["skipped"] is True
    assert by_name["fails"]["rc"] == 3
    assert by_name["fails"]["output"] == []
    assert by_name["hangs"]["timeout"] == 1
    # stdout mirrors the file (the live view during a window)
    assert capsys.readouterr().out.count('"section"') == 4
