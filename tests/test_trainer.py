"""End-to-end Trainer orchestration tests on a tiny on-disk dataset: K-fold training
with checkpoints + best exports, auto-resume idempotency, and fold x TTA ensemble
prediction (reference: model.py:138-255)."""

import glob
import os

import numpy as np
import pytest
from PIL import Image

from tensorflowdistributedlearning_tpu.config import TrainConfig
from tensorflowdistributedlearning_tpu.train.trainer import Model, Trainer
from tensorflowdistributedlearning_tpu.utils.summary import read_events

N_IMAGES = 16
SHAPE = (32, 32)


@pytest.fixture(scope="module")
def salt_dirs(tmp_path_factory):
    """Tiny TGS-salt-layout dataset: {data}/images+masks, {test}/images."""
    from tests.conftest import make_salt_dataset

    return make_salt_dataset(
        tmp_path_factory.mktemp("salt"), n_images=N_IMAGES, shape=SHAPE
    )


@pytest.fixture(scope="module")
def trained(salt_dirs, tmp_path_factory):
    data, test, ids = salt_dirs
    model_dir = str(tmp_path_factory.mktemp("model"))
    tcfg = TrainConfig(
        n_folds=2,
        seed=0,
        save_best=2,
        checkpoint_every_steps=2,
        eval_throttle_secs=0,
        train_log_every_steps=2,
    )
    trainer = Trainer(
        model_dir,
        data,
        train_config=tcfg,
        input_shape=SHAPE,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.0625,
    )
    results = trainer.train(ids, batch_size=8, steps=4)
    return trainer, results, model_dir, test, ids


def test_trains_all_folds(trained):
    _, results, *_ = trained
    assert len(results) == 2
    for metrics in results:
        assert set(metrics) >= {"loss", "metrics/mean_iou", "metrics/mean_acc"}


def test_params_available_after_train(trained):
    trainer, *_ = trained
    assert trainer.params > 1000


def test_checkpoints_and_best_exports_on_disk(trained):
    _, _, model_dir, *_ = trained
    for fold in range(2):
        assert os.path.isdir(os.path.join(model_dir, f"fold{fold}", "checkpoints"))
        assert os.path.isdir(
            os.path.join(model_dir, f"fold{fold}", "export", "best")
        )


def test_fold_manifests_written_and_disjoint(trained):
    _, _, model_dir, _, ids = trained
    from tensorflowdistributedlearning_tpu.data.folds import read_fold_manifests

    manifests = read_fold_manifests(model_dir)
    assert len(manifests) == 2
    for m in manifests:
        assert not set(m["train"]) & set(m["eval"])
        assert sorted(m["train"] + m["eval"]) == sorted(ids)


def test_event_files_parse(trained):
    _, _, model_dir, *_ = trained
    train_events = glob.glob(
        os.path.join(model_dir, "fold0", "train", "events.out.tfevents.*")
    )
    eval_events = glob.glob(
        os.path.join(model_dir, "fold0", "eval", "events.out.tfevents.*")
    )
    assert train_events and eval_events
    steps = [s for s, _ in read_events(train_events[0])]
    assert steps and all(s % 2 == 0 for s in steps)  # train_log_every_steps=2
    assert any("loss" in v for _, v in read_events(eval_events[0]))
    # exact lr of the next update rides the train scalars (observability the
    # reference's TB summaries never had)
    lr_points = [v["lr"] for _, v in read_events(train_events[0]) if "lr" in v]
    assert lr_points and all(p > 0 for p in lr_points)


def test_resume_is_idempotent(trained, salt_dirs):
    trainer, results, *_ , ids = trained
    again = trainer.train(ids, batch_size=8, steps=4)
    # already at target step: folds skip training and re-run eval only
    assert len(again) == 2
    for a, b in zip(results, again):
        assert abs(a["metrics/mean_iou"] - b["metrics/mean_iou"]) < 1e-5


def test_predict_tta_ensemble(trained):
    trainer, _, _, test, _ = trained
    pred = trainer.predict(test, batch_size=8, tta=True)
    assert pred["probabilities"].shape == (6, *SHAPE, 1)
    assert pred["masks"].shape == (6, *SHAPE, 1)
    assert len(pred["ids"]) == 6
    assert np.all(pred["probabilities"] >= 0) and np.all(pred["probabilities"] <= 1)
    assert set(np.unique(pred["masks"])) <= {0.0, 1.0}


def test_predict_without_tta_differs_from_ensemble(trained):
    trainer, _, _, test, _ = trained
    tta = trainer.predict(test, batch_size=8, tta=True)
    plain = trainer.predict(test, batch_size=8, tta=False)
    # same shapes, generally different values (4-member vs 1-member average per fold)
    assert tta["probabilities"].shape == plain["probabilities"].shape
    assert not np.allclose(tta["probabilities"], plain["probabilities"])


def test_serving_fn(trained):
    import jax.numpy as jnp

    trainer, *_ = trained
    serve = trainer.serving_fn(fold=0)
    # the serving signature: preprocessed [B, H, W, input_channels] images
    images = jnp.zeros((2, *SHAPE, 2), jnp.float32)
    out = serve(images)
    assert out["probabilities"].shape == (2, *SHAPE, 1)
    assert set(np.unique(np.asarray(out["mask"]))) <= {0.0, 1.0}


def test_serving_fn_nchw_boundary(trained, salt_dirs):
    """data_format='NCHW' is honored at the serving boundary (VERDICT r1: the
    flag used to be accepted and ignored; reference transposed in model_fn,
    model.py:344-351)."""
    import jax.numpy as jnp

    _, _, model_dir, _, _ = trained
    data, *_ = salt_dirs
    t2 = Trainer(
        model_dir,
        data,
        data_format="NCHW",
        n_fold=2,
        seed=0,
        input_shape=SHAPE,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.0625,
    )
    serve = t2.serving_fn(fold=0)
    images = jnp.zeros((2, 2, *SHAPE), jnp.float32)  # [B, C, H, W]
    out = serve(images)
    assert out["probabilities"].shape == (2, 1, *SHAPE)
    assert out["mask"].shape == (2, 1, *SHAPE)


def test_nchw_training_rejected_predict_honors_layout(trained, salt_dirs):
    """Round-2 VERDICT missing #4: data_format='NCHW' must not be
    accepted-and-inert at the train boundary. Training REJECTS it with
    guidance (pipelines feed NHWC; XLA owns TPU compute layout), while
    predict() — a user-facing array boundary like serving — returns NCHW
    outputs."""
    _, _, model_dir, test, ids = trained
    data, *_ = salt_dirs
    t2 = Trainer(
        model_dir,
        data,
        data_format="NCHW",
        n_fold=2,
        seed=0,
        input_shape=SHAPE,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.0625,
    )
    with pytest.raises(ValueError, match="serving/predict boundary"):
        t2.train(ids, batch_size=8, steps=1)
    pred = t2.predict(test, batch_size=8, tta=False)
    assert pred["probabilities"].shape == (6, 1, *SHAPE)
    assert pred["masks"].shape == (6, 1, *SHAPE)


def test_export_serving_artifact_roundtrip(trained):
    """A standalone serialized-StableHLO artifact reloads WITHOUT the trainer and
    reproduces serving_fn's outputs (VERDICT r1 #7; reference: model.py:190-204)."""
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    trainer, *_ = trained
    path = trainer.export_serving(fold=0)
    assert os.path.isfile(path)
    directory = os.path.dirname(path)
    manifest = serving_lib.read_manifest(directory)
    assert manifest["input_shape"] == [None, *SHAPE, 2]

    serve = serving_lib.load_serving_artifact(directory)
    rng = np.random.default_rng(0)
    # batch-polymorphic: a batch size never seen at export time
    images = rng.normal(0, 1, (3, *SHAPE, 2)).astype(np.float32)
    out = serve(images)
    ref = trainer.serving_fn(fold=0)(jnp.asarray(images))
    np.testing.assert_allclose(
        np.asarray(out["probabilities"]),
        np.asarray(ref["probabilities"]),
        rtol=1e-5,
        atol=1e-6,
    )


def test_serving_fn_refuses_untrained_fold(trained):
    trainer, *_ = trained
    with pytest.raises(RuntimeError, match="no trained checkpoint"):
        trainer.serving_fn(fold=9)


def test_predict_refuses_untrained_fold(trained):
    trainer, _, _, test, _ = trained
    with pytest.raises(RuntimeError, match="no trained checkpoint"):
        # fold 7 was never trained
        trainer.predict(test, batch_size=8, folds=[7])


def test_eval_every_steps_decoupled_from_checkpointing(salt_dirs, tmp_path_factory):
    """TrainConfig.eval_every_steps evaluates on its own cadence even when the
    checkpoint cadence never fires mid-run (round-1 weak spot: eval was only
    considered when a periodic checkpoint landed)."""
    data, _, ids = salt_dirs
    model_dir = str(tmp_path_factory.mktemp("eval_cadence"))
    tcfg = TrainConfig(
        n_folds=2,
        seed=0,
        checkpoint_every_steps=100,  # never fires in a 4-step run
        eval_every_steps=2,
        eval_throttle_secs=0,
        train_log_every_steps=2,
    )
    trainer = Trainer(
        model_dir, data, train_config=tcfg,
        input_shape=SHAPE, n_blocks=(1, 1, 1), base_depth=8, width_multiplier=0.0625,
    )
    trainer.train(ids, batch_size=8, steps=4)
    events = glob.glob(
        os.path.join(model_dir, "fold0", "eval", "events.out.tfevents.*")
    )
    assert events
    steps = sorted({s for s, _ in read_events(events[0])})
    assert steps == [2, 4]


def test_model_alias():
    assert Model is Trainer


def test_unknown_kwarg_rejected(tmp_path):
    with pytest.raises(ValueError, match="Unknown model config keys"):
        Trainer(str(tmp_path), "", weight_decayy=0.1)


def test_tensor_parallel_trainer_end_to_end(salt_dirs, tmp_path_factory):
    """The K-fold segmentation Trainer with model_parallel=2: params/optimizer
    channel-shard over the model axis and every step (train/eval/predict) runs
    in shard_map's hybrid auto-model mode (make_train_step(auto_model=True)).
    Replaces round-4's NotImplementedError guard with the real capability.
    Checkpoint/restore, best-export, and the TTA ensemble must all survive the
    sharded state."""
    import jax

    from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS

    data, test, ids = salt_dirs
    model_dir = str(tmp_path_factory.mktemp("model_tp"))
    tcfg = TrainConfig(
        n_folds=2,
        seed=0,
        save_best=2,
        checkpoint_every_steps=2,
        eval_throttle_secs=0,
        model_parallel=2,
    )
    trainer = Trainer(
        model_dir,
        data,
        train_config=tcfg,
        input_shape=SHAPE,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.125,  # conv widths divisible by tp degree 2
    )
    # the initial state is genuinely channel-sharded over the model axis
    state = trainer._init_state()
    kernel = state.params["backbone"]["conv1_3"]["conv"]["kernel"]
    assert MODEL_AXIS in tuple(kernel.sharding.spec), kernel.sharding.spec

    results = trainer.train(ids, batch_size=8, steps=4)
    assert len(results) == 2
    for fold_metrics in results:
        assert np.isfinite(fold_metrics["loss"])

    pred = trainer.predict(test, batch_size=8)
    assert pred["masks"].shape == (len(pred["ids"]),) + SHAPE + (1,)
    assert np.isfinite(pred["probabilities"]).all()
