"""obs/trace.py: the request/step-granular trace layer.

Contracts under test, the ones the acceptance criteria name: spans nest and
parent correctly with per-trace sampling; a served request's trace shows
queue_wait→pad→compute child spans linked (``batch_span_id``) to its batch's
compute span, with the trace id echoed as ``x-request-id`` on success AND on
shed/timeout errors; a training run's trace shows step/eval/checkpoint spans;
and the exported Chrome/Perfetto JSON carries every required trace-event
field."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.serve import (
    InferenceEngine,
    MicroBatcher,
    ServingServer,
)

FEATURES = 4
CLASSES = 3


@pytest.fixture(scope="module")
def serve_fn():
    import jax
    import jax.numpy as jnp

    w = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.3

    @jax.jit
    def fn(x):
        logits = x @ w
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return fn


# -- tracer unit behavior ----------------------------------------------------


def test_null_tracer_is_inert():
    assert not trace_lib.NULL_TRACER.enabled
    with trace_lib.NULL_TRACER.span("anything") as span:
        assert span is None
    assert trace_lib.NULL_TRACER.current() is None


def test_span_nesting_parents_and_children():
    written = []
    tracer = trace_lib.Tracer(emit=written.append, sample_rate=1.0)
    with tracer.span("root", attrs={"k": 1}) as root:
        with tracer.span("child") as child:
            assert tracer.current() is child
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        with tracer.span("sibling") as sib:
            assert sib.parent_id == root.span_id
    assert tracer.current() is None
    # children collected on the open parent (the batcher relies on this)
    assert [c.name for c in root.children] == ["child", "sibling"]
    # written innermost-first, all sampled, ids unique
    assert [w["name"] for w in written] == ["child", "sibling", "root"]
    assert len({w["span_id"] for w in written}) == 3
    assert written[-1].get("parent_id") is None
    assert written[-1]["attrs"] == {"k": 1}
    assert all(w["duration_s"] >= 0 for w in written)


def test_sampling_is_decided_per_trace():
    written = []
    tracer = trace_lib.Tracer(emit=written.append, sample_rate=0.5)
    # an unsampled root drops its whole trace — children included — while
    # ids still exist for propagation
    with tracer.span("root", sampled=False) as root:
        with tracer.span("child") as child:
            assert child.sampled is False
        assert root.span_id
    assert written == []
    with tracer.span("root", sampled=True):
        with tracer.span("child"):
            pass
    assert [w["name"] for w in written] == ["child", "root"]
    # retroactive emits respect the caller's verdict too
    tracer.emit("late", trace_id="t", start_t=0.0, duration_s=1.0, sampled=False)
    assert len(written) == 2
    tracer.emit("late", trace_id="t", start_t=0.0, duration_s=1.0)
    assert written[-1]["name"] == "late"


def test_tracer_rejects_bad_sample_rate():
    with pytest.raises(ValueError, match="sample_rate"):
        trace_lib.Tracer(emit=lambda e: None, sample_rate=1.5)


# -- serve request path ------------------------------------------------------


def _post(url, payload, timeout=10, headers=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


@pytest.fixture
def traced_server(serve_fn, tmp_path):
    workdir = str(tmp_path / "serve_traced")
    tel = obs.Telemetry(
        workdir, run_info={"kind": "serve"}, trace_sample_rate=1.0
    )
    engine = InferenceEngine(
        serve_fn,
        (FEATURES,),
        buckets=(4,),
        registry=tel.registry,
        tracer=tel.tracer,
    )
    engine.warmup(telemetry=tel)
    batcher = MicroBatcher(engine, max_wait_ms=2, max_queue=16)
    server = ServingServer(
        engine, batcher, port=0, telemetry=tel, window_secs=0
    ).start()
    yield server, workdir
    server.shutdown()


def _trace_events(workdir, server=None):
    if server is not None:
        # trace events are buffered (no flush per span); push them to disk
        # before reading a LIVE server's ledger
        server.telemetry.flush()
    return [
        e for e in obs.read_ledger(workdir) if e.get("event") == "trace"
    ]


def test_request_trace_links_queue_pad_compute_to_batch(traced_server):
    server, workdir = traced_server
    x = np.ones((2, FEATURES), np.float32)  # n=2 < bucket 4: padding happens
    status, headers, body = _post(
        server.url + "/v1/predict", {"instances": x.tolist()}
    )
    assert status == 200 and body["n"] == 2
    rid = headers["x-request-id"]
    assert rid

    spans = _trace_events(workdir, server)
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    # the echoed x-request-id IS the request trace id
    request_spans = [
        e for e in by_name["request"] if e["trace_id"] == rid
    ]
    assert len(request_spans) == 1
    root = request_spans[0]
    assert root.get("parent_id") is None
    assert root["attrs"]["status"] == 200

    # queue→pad→compute children of the request root, in its trace
    members = {
        name: [
            e
            for e in by_name.get(name, [])
            if e["trace_id"] == rid and e.get("parent_id") == root["span_id"]
        ]
        for name in ("queue_wait", "pad", "compute")
    }
    for name, found in members.items():
        assert len(found) == 1, f"missing member span {name}: {spans}"

    # the member pad/compute spans link to the batch trace's compute span
    batch_roots = by_name.get("batch", [])
    assert batch_roots, "batcher wrote no batch span"
    batch = batch_roots[-1]
    batch_compute = [
        e
        for e in by_name["compute"]
        if e["trace_id"] == batch["trace_id"]
        and e.get("parent_id") == batch["span_id"]
    ]
    assert len(batch_compute) == 1
    link = members["compute"][0]["attrs"]
    assert link["batch_span_id"] == batch_compute[0]["span_id"]
    assert link["batch_trace_id"] == batch["trace_id"]
    assert members["compute"][0]["attrs"]["bucket"] == 4


def test_client_supplied_request_id_is_honored(traced_server):
    server, workdir = traced_server
    x = np.ones((1, FEATURES), np.float32)
    status, headers, _ = _post(
        server.url + "/v1/predict",
        {"instances": x.tolist()},
        headers={"x-request-id": "my-req-42"},
    )
    assert status == 200
    assert headers["x-request-id"] == "my-req-42"
    assert any(
        e["name"] == "request" and e["trace_id"] == "my-req-42"
        for e in _trace_events(workdir, server)
    )


def test_error_responses_carry_request_id_and_kind(serve_fn, tmp_path):
    """429 (shed) and 400 (malformed) answers are correlatable: machine-
    readable error.code + the request id in body and header."""
    import time as time_lib

    barrier = threading.Event()

    def slow_fn(x):
        barrier.wait(timeout=10)
        return serve_fn(x)

    engine = InferenceEngine(slow_fn, (FEATURES,), buckets=(1,))
    batcher = MicroBatcher(engine, max_wait_ms=1, max_queue=1)
    server = ServingServer(engine, batcher, port=0, window_secs=0).start()
    try:
        results = []

        def post_one():
            try:
                _post(
                    server.url + "/v1/predict",
                    {"instances": [[0.0] * FEATURES]},
                    timeout=15,
                )
                results.append((200, None, None))
            except urllib.error.HTTPError as err:
                body = json.loads(err.read())
                results.append(
                    (err.code, body["error"], err.headers.get("x-request-id"))
                )

        # one in flight (worker blocked), one queued, the rest shed with 429
        threads = [threading.Thread(target=post_one) for _ in range(4)]
        for t in threads:
            t.start()
            time_lib.sleep(0.05)
        barrier.set()
        for t in threads:
            t.join(timeout=15)
        shed = [r for r in results if r[0] == 429]
        assert shed, f"expected at least one 429, got {results}"
        for _, error, header_rid in shed:
            assert error["code"] == "queue_full"
            assert error["request_id"]
            assert header_rid == error["request_id"]

        # malformed request: same contract on the 400 path
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/v1/predict", {"wrong": []})
        body = json.loads(err.value.read())
        assert err.value.code == 400
        assert body["error"]["code"] == "bad_request"
        assert body["error"]["request_id"]
        assert err.value.headers.get("x-request-id") == body["error"]["request_id"]

        # a POST 404 mints its OWN id — never echoes a previous request's
        # (keep-alive handler instances are reused across requests)
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/v1/nope", {"instances": []})
        body = json.loads(err.value.read())
        assert err.value.code == 404
        assert body["error"]["request_id"]
    finally:
        server.shutdown()


# -- chrome export -----------------------------------------------------------

CHROME_REQUIRED = ("name", "ph", "ts", "pid", "tid")


def _assert_valid_chrome(doc):
    assert "traceEvents" in doc
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert xs, "no complete events in export"
    for e in doc["traceEvents"]:
        for field in CHROME_REQUIRED:
            assert field in e, f"missing {field}: {e}"
    for e in xs:
        assert "dur" in e and e["dur"] >= 0
        assert e["ts"] >= 0
    return xs


def test_chrome_export_from_serve_trace(traced_server, tmp_path):
    server, workdir = traced_server
    x = np.ones((3, FEATURES), np.float32)
    _post(server.url + "/v1/predict", {"instances": x.tolist()})
    server.telemetry.flush()
    out = str(tmp_path / "trace.json")
    n = trace_lib.write_chrome_trace(workdir, out)
    with open(out) as f:
        doc = json.load(f)
    xs = _assert_valid_chrome(doc)
    assert len(xs) == n
    names = {e["name"] for e in xs}
    assert {"request", "queue_wait", "compute"} <= names
    # parenting survives the export (in args), and the request's compute
    # child still points at its batch
    by_span = {e["args"]["span_id"]: e for e in xs if "span_id" in e["args"]}
    linked = [e for e in xs if "batch_span_id" in e.get("args", {})]
    assert linked
    for e in linked:
        assert e["args"]["batch_span_id"] in by_span
    # the flow links rendered too (s/f pairs share ids)
    flows = [e for e in doc["traceEvents"] if e.get("ph") in ("s", "f")]
    assert flows and len(flows) % 2 == 0


def test_chrome_export_empty_ledger_is_valid(tmp_path):
    workdir = str(tmp_path / "empty")
    tel = obs.Telemetry(workdir, run_info={})
    tel.close()
    out = str(tmp_path / "trace.json")
    assert trace_lib.write_chrome_trace(workdir, out) == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"] == []


# -- training run ------------------------------------------------------------

TINY = dict(
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    n_blocks=(1, 1, 1),
    width_multiplier=0.125,
    output_stride=None,
)


@pytest.fixture(scope="module")
def traced_fit_workdir(tmp_path_factory):
    """One short synthetic fit() with tracing fully on."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    workdir = str(tmp_path_factory.mktemp("traced_fit"))
    trainer = ClassifierTrainer(
        workdir,
        None,
        ModelConfig(**TINY),
        TrainConfig(
            train_log_every_steps=2,
            checkpoint_every_steps=4,
            eval_every_steps=4,
            trace_sample_rate=1.0,
        ),
    )
    trainer.fit(batch_size=8, steps=8, eval_every_steps=4)
    return workdir


def test_training_run_traces_step_eval_checkpoint(traced_fit_workdir):
    spans = _trace_events(traced_fit_workdir)
    names = {e["name"] for e in spans}
    assert {"step", "eval", "checkpoint"} <= names, names
    # rate 1.0: every train step traced
    assert sum(1 for e in spans if e["name"] == "step") >= 8


def test_training_trace_exports_and_cli(traced_fit_workdir, tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    out = str(tmp_path / "train_trace.json")
    rc = main(["telemetry-report", traced_fit_workdir, "--export-trace", out])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["written"] == out and verdict["span_events"] > 0
    with open(out) as f:
        xs = _assert_valid_chrome(json.load(f))
    assert {"step", "eval", "checkpoint"} <= {e["name"] for e in xs}


def test_report_renders_trace_summary(traced_fit_workdir):
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    rendered = report_workdir(traced_fit_workdir)
    assert "tracing:" in rendered and "--export-trace" in rendered


def test_cli_parser_accepts_observability_flags():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["fit", "--preset", "p", "--model-dir", "m",
         "--trace-sample-rate", "0.5", "--nan-guard", "abort"]
    )
    assert args.trace_sample_rate == 0.5 and args.nan_guard == "abort"
    args = build_parser().parse_args(
        ["serve", "--artifact-dir", "d", "--slo-p99-ms", "50",
         "--trace-sample-rate", "0.1"]
    )
    assert args.slo_p99_ms == 50.0 and args.slo_error_budget == 0.01
    # defaults leave the config in charge
    args = build_parser().parse_args(
        ["train", "--model-dir", "m", "--data-dir", "d"]
    )
    assert args.trace_sample_rate is None and args.nan_guard is None
