"""Streaming data service (data/service.py): global-shuffle shard assignment,
worker-count-invariant index-keyed batches, deterministic resume (including
the headline supervised kill-and-resume over record shards), the .idx
count/offset sidecar, backpressure telemetry, and the data_starved monitor."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import records as rec
from tensorflowdistributedlearning_tpu.data import service as svc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _shards(tmp_path, n=40, shards=3, hw=12, classes=5, seed=1):
    rng = np.random.default_rng(seed)
    images = [
        rng.integers(0, 255, (hw, hw, 3), dtype=np.uint8) for _ in range(n)
    ]
    labels = list(rng.integers(0, classes, n))
    paths = rec.write_classification_shards(
        str(tmp_path), images, labels, shards=shards
    )
    return paths, images, labels


def _service(paths, *, workers=2, start=0, batch=8, seed=7, registry=None,
             num_classes=5, hw=12):
    source = svc.ClassificationRecordSource(
        paths,
        image_shape=(hw, hw),
        channels=3,
        num_classes=num_classes,
        process_index=0,
        process_count=1,
    )
    return svc.StreamingDataService(
        source,
        batch_size=batch,
        seed=seed,
        workers=workers,
        start_batch=start,
        registry=registry,
    )


# -- shard assignment ---------------------------------------------------------


def test_epoch_assignment_uneven_exact_once():
    """n_shards not divisible by process_count: every epoch, every shard is
    owned by exactly one host and no host is starved (>= 1 shard each)."""
    paths = [f"/data/shard-{i:03d}" for i in range(7)]
    for process_count in (2, 3, 4, 7):
        for epoch in range(5):
            owned = [
                svc.epoch_shard_assignment(
                    paths,
                    seed=3,
                    epoch=epoch,
                    process_index=p,
                    process_count=process_count,
                )
                for p in range(process_count)
            ]
            flat = [s for host in owned for s in host]
            assert sorted(flat) == sorted(paths)  # exactly once each
            assert all(host for host in owned)  # no host starved


def test_epoch_assignment_deterministic_and_reshuffled():
    paths = [f"/data/shard-{i:03d}" for i in range(6)]
    a = svc.epoch_shard_assignment(
        paths, seed=0, epoch=1, process_index=0, process_count=2
    )
    b = svc.epoch_shard_assignment(
        paths, seed=0, epoch=1, process_index=0, process_count=2
    )
    assert a == b  # pure function of (seed, epoch, slot)
    epochs = {
        tuple(
            svc.epoch_shard_assignment(
                paths, seed=0, epoch=e, process_index=0, process_count=2
            )
        )
        for e in range(8)
    }
    assert len(epochs) > 1  # the global shuffle actually reshuffles epochs


def test_host_shard_paths_uneven_explicit_processes():
    """The static assigner under the same uneven-split contract, without a
    jax cluster: round-robin over sorted paths, every shard exactly once."""
    paths = [f"/data/s{i}" for i in range(7)]
    owned = [rec.host_shard_paths(paths, p, 3) for p in range(3)]
    assert sorted(s for host in owned for s in host) == sorted(paths)
    assert {len(h) for h in owned} == {2, 3}


def test_too_few_shards_for_processes_raises(tmp_path):
    paths, *_ = _shards(tmp_path, n=6, shards=2)
    with pytest.raises(ValueError, match="every process needs at least one"):
        svc.ClassificationRecordSource(
            paths, image_shape=(12, 12), process_index=0, process_count=3
        )


# -- the service stream -------------------------------------------------------


def test_batches_worker_count_invariant(tmp_path):
    """Batch CONTENT is a pure function of (seed, i): 1, 2 and 5 workers
    produce bit-identical streams (scheduling changes, the plan does not)."""
    paths, *_ = _shards(tmp_path)
    streams = [
        list(_service(paths, workers=w).batches(steps=10)) for w in (1, 2, 5)
    ]
    for other in streams[1:]:
        for a, b in zip(streams[0], other):
            assert np.array_equal(a["images"], b["images"])
            assert np.array_equal(a["labels"], b["labels"])
            assert np.array_equal(a["valid"], b["valid"])


def test_resume_replays_exact_remaining_stream(tmp_path):
    """start_batch=k yields batches k, k+1, ... bit-identical to the
    uninterrupted stream — the index-keyed resume contract."""
    paths, *_ = _shards(tmp_path)
    full = list(_service(paths, workers=3).batches(steps=12))
    resumed = list(_service(paths, workers=2, start=5).batches(steps=7))
    assert len(resumed) == 7
    for a, b in zip(full[5:], resumed):
        assert np.array_equal(a["images"], b["images"])
        assert np.array_equal(a["labels"], b["labels"])


def test_global_shuffle_covers_each_epoch_exactly_once(tmp_path):
    """Per epoch, every record appears exactly once (full permutation), and
    consecutive epochs are ordered differently."""
    paths, _, labels = _shards(tmp_path, n=24, shards=3, classes=5)
    # batch 8 divides 24: epochs align with batch boundaries (3 per epoch)
    batches = list(_service(paths, workers=2, batch=8).batches(steps=6))
    e0 = np.concatenate([b["labels"] for b in batches[:3]])
    e1 = np.concatenate([b["labels"] for b in batches[3:]])
    assert sorted(e0.tolist()) == sorted(labels)
    assert sorted(e1.tolist()) == sorted(labels)
    assert e0.tolist() != e1.tolist()  # reshuffled between epochs


def test_dataset_smaller_than_batch_spans_epochs(tmp_path):
    """n < batch_size: batches span epoch boundaries instead of spinning or
    dropping records (the infinite virtual sequence has no tail)."""
    paths, _, labels = _shards(tmp_path, n=3, shards=1, classes=3)
    batches = list(_service(paths, workers=2, batch=4).batches(steps=3))
    got = np.concatenate([b["labels"] for b in batches])  # 12 rows = 4 epochs
    assert sorted(got.tolist()) == sorted(labels * 4)


def test_resume_state_sidecar_roundtrip_and_mismatch(tmp_path):
    paths, *_ = _shards(tmp_path)
    service = _service(paths, workers=1, start=4)
    state = service.state(4)
    assert state.batch_index == 4 and state.seed == 7
    restored = svc.DataServiceState.from_json(
        json.loads(json.dumps(state.to_json()))
    )
    assert restored == state  # full json round-trip
    assert (restored.batch_size, restored.process_count) == (8, 1)
    assert restored.shard_fingerprint  # shard-set identity rides along
    # a matching sidecar validates...
    _service(paths, workers=1, start=4).close()
    ok = svc.StreamingDataService(
        svc.ClassificationRecordSource(
            paths, image_shape=(12, 12), process_index=0, process_count=1
        ),
        batch_size=8, seed=7, workers=1, start_batch=4,
        resume_state=state.to_json(),
    )
    ok.close()
    # ...a mismatched one must crash loud: wrong seed, and wrong batch size
    # (same (seed, batch_index) but batch 4 would map to DIFFERENT records)
    with pytest.raises(ValueError, match="resume state mismatch"):
        svc.StreamingDataService(
            svc.ClassificationRecordSource(
                paths, image_shape=(12, 12), process_index=0, process_count=1
            ),
            batch_size=8, seed=8, workers=1, start_batch=4,
            resume_state=state.to_json(),
        )
    with pytest.raises(ValueError, match="resume state mismatch"):
        svc.StreamingDataService(
            svc.ClassificationRecordSource(
                paths, image_shape=(12, 12), process_index=0, process_count=1
            ),
            batch_size=16, seed=7, workers=1, start_batch=4,
            resume_state=state.to_json(),
        )
    # ...and a CHANGED SHARD SET (re-shard, added/removed files): every epoch
    # plan re-deals, so the resume must refuse even with seed/step matching
    with pytest.raises(ValueError, match="resume state mismatch"):
        svc.StreamingDataService(
            svc.ClassificationRecordSource(
                paths[:-1], image_shape=(12, 12),
                process_index=0, process_count=1,
            ),
            batch_size=8, seed=7, workers=1, start_batch=4,
            resume_state=state.to_json(),
        )


def test_two_host_simulation_partitions_every_epoch(tmp_path):
    """Simulated 2-process split: per-epoch record counts partition the
    dataset, and both hosts' label multisets union to the full epoch."""
    paths, _, labels = _shards(tmp_path, n=30, shards=3, classes=5)
    total = len(labels)
    sources = [
        svc.ClassificationRecordSource(
            paths, image_shape=(12, 12), channels=3,
            process_index=p, process_count=2,
        )
        for p in range(2)
    ]
    for epoch in range(4):
        sizes = [s.epoch_size(7, epoch) for s in sources]
        assert sum(sizes) == total
        assert all(n > 0 for n in sizes)  # 3 shards, 2 hosts: nobody starved


def test_worker_error_propagates(tmp_path):
    paths, *_ = _shards(tmp_path, classes=5)
    # num_classes=2 makes the label-range check fail inside a WORKER; the
    # consumer must see the ValueError, not hang
    service = _service(paths, workers=2, num_classes=2)
    with pytest.raises(ValueError, match="label out of range"):
        list(service.batches(steps=4))


def test_close_unblocks_waiting_consumer(tmp_path):
    """close() while a consumer is blocked waiting for the next batch must
    END the stream, not leave the thread polling for a batch the discarded
    workers will never produce (the device_prefetch producer thread hits
    exactly this on run teardown)."""
    import threading

    paths, *_ = _shards(tmp_path)
    service = _service(paths, workers=1)
    stream = service.batches(steps=1000)
    next(stream)
    done = threading.Event()

    def drain():
        for _ in stream:
            if done.is_set():
                return
        done.set()

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    time.sleep(0.2)  # let the consumer reach a blocking wait
    service.close()
    t.join(timeout=5)
    assert not t.is_alive(), "consumer still blocked after close()"


def test_abandoned_stream_releases_workers(tmp_path):
    paths, *_ = _shards(tmp_path)
    service = _service(paths, workers=2)
    stream = service.batches(steps=50)
    next(stream)
    stream.close()  # consumer walks away mid-stream
    deadline = time.time() + 5
    while any(t.is_alive() for t in service._threads):
        assert time.time() < deadline, "service workers leaked"
        time.sleep(0.05)


def test_backpressure_telemetry_recorded(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.metrics import MetricsRegistry
    from tensorflowdistributedlearning_tpu.obs import telemetry as tm

    paths, *_ = _shards(tmp_path)
    registry = MetricsRegistry()
    service = _service(paths, workers=2, registry=registry)
    n = len(list(service.batches(steps=6)))
    assert n == 6
    assert len(registry.histogram(tm.DATA_READY_HISTOGRAM).samples) == 6
    assert registry.gauge(tm.DATA_WORKERS_GAUGE).value == 2
    assert len(registry.histogram(tm.DATA_WORKER_BUSY_HISTOGRAM).samples) >= 6


# -- .idx sidecar -------------------------------------------------------------


def test_shard_index_written_and_used(tmp_path):
    paths, *_ = _shards(tmp_path, n=10, shards=2)
    for p in paths:
        idx = rec.shard_index_path(p)
        assert os.path.isfile(idx)
        offs = rec.shard_offsets(p)
        assert np.array_equal(offs, rec._scan_offsets(p))
    assert rec.count_records(paths) == 10


def test_stale_index_falls_back_to_scan(tmp_path):
    """A rewritten shard invalidates its sidecar (size mismatch): offsets
    must come from the fresh scan, not the stale index."""
    path = str(tmp_path / "a.tfrecord")
    rec.write_records(path, [b"one", b"two"])
    rec.write_shard_index(path)
    stale = rec.shard_offsets(path)
    assert len(stale) == 2
    rec.write_records(path, [b"one", b"two", b"three-longer"])
    # the shard grew but the old .idx is still on disk (and even if its
    # mtime ties, the size check must reject it)
    got = rec.shard_offsets(path)
    assert len(got) == 3
    assert np.array_equal(got, rec._scan_offsets(path))


def test_corrupt_index_falls_back_to_scan(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    rec.write_records(path, [b"x", b"y", b"z"])
    with open(rec.shard_index_path(path), "wb") as f:
        f.write(b"not an npz")
    os.utime(rec.shard_index_path(path))  # newer than the shard
    assert len(rec.shard_offsets(path)) == 3
    assert rec.count_records([path]) == 3


def test_count_records_still_detects_truncation_without_index(tmp_path):
    path = str(tmp_path / "t.tfrecord")
    rec.write_records(path, [b"abc", b"defg"])
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-3])
    with pytest.raises(ValueError, match="truncated record body"):
        rec.count_records([path])


def test_range_reader_native_matches_python(tmp_path, monkeypatch):
    path = str(tmp_path / "r.tfrecord")
    payloads = [f"payload-{i}".encode() * (i + 1) for i in range(12)]
    rec.write_records(path, payloads)
    offs = rec.shard_offsets(path)
    sel = [7, 0, 11, 3, 3]
    with rec.ShardRangeReader(path) as native:
        got_native = native.read([offs[i] for i in sel])
    monkeypatch.setattr(rec, "_records_lib", lambda: None)
    with rec.ShardRangeReader(path) as fallback:
        assert fallback._lib is None  # really on the python path
        got_py = fallback.read([offs[i] for i in sel])
    want = [payloads[i] for i in sel]
    assert got_native == want and got_py == want


def test_range_reader_rejects_corrupt_offset(tmp_path):
    path = str(tmp_path / "r.tfrecord")
    rec.write_records(path, [b"aaaa", b"bbbb"])
    with rec.ShardRangeReader(path) as reader:
        with pytest.raises(ValueError):
            reader.read([5])  # mid-record garbage offset


# -- decode-ahead parity ------------------------------------------------------


def test_decode_ahead_stream_matches_inline(tmp_path):
    paths, *_ = _shards(tmp_path, n=20, shards=2)
    ds = rec.ClassificationRecords(
        str(tmp_path), image_shape=(12, 12), channels=3
    )
    inline = list(ds.batches(6, seed=3, repeat=False, decode_ahead=0))
    ahead = list(ds.batches(6, seed=3, repeat=False, decode_ahead=2))
    assert len(inline) == len(ahead)
    for a, b in zip(inline, ahead):
        assert np.array_equal(a["images"], b["images"])
        assert np.array_equal(a["labels"], b["labels"])
        assert np.array_equal(a["valid"], b["valid"])


# -- data_starved monitor -----------------------------------------------------


def test_data_starved_monitor_alerts_and_resolves():
    from tensorflowdistributedlearning_tpu.obs.health import (
        DataStarvedDetector,
    )

    d = DataStarvedDetector(threshold=0.5, consecutive=2)
    assert d.check(1, 0.9, dirty=True) is None  # dirty windows excluded
    assert d.check(2, 0.9) is None  # first strike
    alert = d.check(3, 0.8)
    assert alert and alert["monitor"] == "data_starved" and d.degraded
    assert d.check(4, 0.9) is None  # still starved: transition already fired
    resolved = d.check(5, 0.1)
    assert resolved and resolved.get("resolved") and not d.degraded


def test_health_monitor_routes_data_wait_frac():
    from tensorflowdistributedlearning_tpu.obs import NULL_TELEMETRY
    from tensorflowdistributedlearning_tpu.obs.health import HealthMonitor

    hm = HealthMonitor(nan_action="off")
    for step in (1, 2):
        hm.observe_window(
            NULL_TELEMETRY, step, {}, {"data_wait_frac": 0.95, "dirty": False}
        )
    assert any(a["monitor"] == "data_starved" for a in hm.alerts)
    assert hm.status == "degraded"
    hm.observe_window(
        NULL_TELEMETRY, 3, {}, {"data_wait_frac": 0.01, "dirty": False}
    )
    assert hm.status == "ok"


# -- trainer integration ------------------------------------------------------


def _run_worker(args, timeout=300):
    return subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tests", "resilience_train_worker.py"),
            *args,
        ],
        capture_output=True, text=True, timeout=timeout,
    )


def test_fit_service_writes_and_validates_sidecar(tmp_path):
    """fit() over record shards with the service: data_state sidecars ride
    the checkpoints and a later resume consumes them. Runs through the
    resilience worker subprocess (the in-process pytest path trips this
    box's known XLA:CPU compile-cache serialization abort — see the root
    conftest's TFDL_NO_COMPILE_CACHE note; the subprocess matches how every
    other real-fit resilience drill runs)."""
    data_dir = str(tmp_path / "data")
    model_dir = str(tmp_path / "m")
    _shards(data_dir, n=24, shards=3, hw=16, classes=4)
    out = _run_worker(
        ["run", "--model-dir", model_dir, "--steps", "4",
         "--data-dir", data_dir]
    )
    assert out.returncode == 0, out.stderr[-800:]
    sidecar = os.path.join(model_dir, "checkpoints", "data_state-4.json")
    with open(sidecar) as f:
        state = json.load(f)
    assert state["batch_index"] == 4 and state["seed"] == 0
    # resume consumes the sidecar (the service validates it) and continues
    out = _run_worker(
        ["run", "--model-dir", model_dir, "--steps", "6",
         "--data-dir", data_dir]
    )
    assert out.returncode == 0, out.stderr[-800:]
    result = json.loads(
        [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    )
    assert result["steps"] == 6
    assert os.path.isfile(
        os.path.join(model_dir, "checkpoints", "data_state-6.json")
    )


def test_array_source_fold_stream_via_service():
    """The K-fold segmentation trainer's in-memory fold stream through the
    ArrayBatchSource: index-keyed batches {'images','masks'} identical across
    worker counts, with full per-epoch coverage."""
    images = np.random.default_rng(0).normal(
        size=(10, 8, 8, 1)
    ).astype(np.float32)
    masks = (np.random.default_rng(1).uniform(size=(10, 8, 8, 1)) > 0.5
             ).astype(np.float32)
    a = list(
        svc.StreamingDataService(
            svc.ArrayBatchSource({"images": images, "masks": masks}),
            batch_size=4, seed=3, workers=1,
        ).batches(steps=6)
    )
    b = list(
        svc.StreamingDataService(
            svc.ArrayBatchSource({"images": images, "masks": masks}),
            batch_size=4, seed=3, workers=3,
        ).batches(steps=6)
    )
    for x, y in zip(a, b):
        assert np.array_equal(x["images"], y["images"])
        assert np.array_equal(x["masks"], y["masks"])
    # epoch coverage: the first 20 rows are 2 full epochs, each row exactly
    # twice (exact byte match — the source fancy-indexes, no recompute)
    by_bytes = {images[i].tobytes(): i for i in range(10)}
    rows = np.concatenate([x["images"] for x in a[:5]])
    matches = sorted(by_bytes[r.tobytes()] for r in rows)
    assert matches == sorted(list(range(10)) * 2)


def test_legacy_stream_refuses_service_sidecar_resume(tmp_path):
    """Resuming a service-written checkpoint with data_service_workers=0
    must crash loud — the legacy stream would silently replay/skip records
    relative to the index-keyed plan."""
    from tensorflowdistributedlearning_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    _shards(tmp_path / "data", n=24, shards=3, hw=16, classes=4)
    trainer = ClassifierTrainer(
        str(tmp_path / "m"),
        str(tmp_path / "data"),
        ModelConfig(
            num_classes=4, input_shape=(16, 16), input_channels=3,
            n_blocks=(1, 1, 1), base_depth=8, width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(seed=0, augmentation="none", data_service_workers=0),
    )
    trainer._restored_data_state = {"seed": 0, "batch_index": 4}
    with pytest.raises(ValueError, match="data-service resume sidecar"):
        trainer._train_stream(8, 4, 4)


def test_restore_data_state_tolerates_garbage_sidecar(tmp_path):
    """A parseable-but-wrong-shape sidecar warns and derives from the step
    (None), same as an unreadable one — it must not kill the resume."""
    from tensorflowdistributedlearning_tpu.train.checkpoint import (
        CheckpointManager,
    )

    ckpt = CheckpointManager(str(tmp_path / "m"))
    try:
        ckpt.save_data_state(4, {"seed": 1, "batch_index": 4})
        assert ckpt.restore_data_state(4)["batch_index"] == 4
        with open(ckpt._data_state_path(6), "w") as f:
            f.write(json.dumps([1, 2, 3]))  # valid JSON, not a sidecar
        assert ckpt.restore_data_state(6) is None
        with open(ckpt._data_state_path(8), "w") as f:
            f.write("{not json")
        assert ckpt.restore_data_state(8) is None
    finally:
        ckpt.close()


# -- the headline: supervised kill mid-epoch over record shards ---------------


def test_supervised_resume_over_records_bit_identical(tmp_path):
    """Kill a service-fed record-shard training run mid-epoch (seeded SIGTERM
    via the existing fault seams), let the supervisor restart it, and require
    the final params BIT-IDENTICAL to an uninterrupted golden run — the
    index-keyed stream contract proven end to end through checkpoint +
    DataServiceState sidecar + global-shuffle resume."""
    data_dir = str(tmp_path / "data")
    _shards(data_dir, n=40, shards=3, hw=16, classes=4)
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tests", "resilience_train_worker.py"),
            "smoke",
            "--workdir", str(tmp_path / "drill"),
            "--steps", "8",
            "--data-dir", data_dir,
        ],
        capture_output=True, text=True, timeout=420,
    )
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no smoke verdict; stderr tail: {out.stderr[-800:]}"
    verdict = json.loads(lines[-1])
    assert verdict["ok"], verdict
    assert verdict["identical"] is True
    assert verdict["restarts"] >= 1
