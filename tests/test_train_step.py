"""End-to-end SPMD train-step tests on the 8-device CPU mesh — the minimum slice of
SURVEY §7: loss decreases, metrics flow, state stays replicated, runs are
deterministic (the determinism check SURVEY §5.2 calls for in place of race detection)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import synthetic_batches
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import make_mesh, replicate, shard_batch
from tensorflowdistributedlearning_tpu.train import (
    ClassificationTask,
    SegmentationTask,
    create_train_state,
    make_eval_step,
    make_optimizer,
    make_predict_step,
    make_train_step,
)
from tensorflowdistributedlearning_tpu.train.step import (
    compute_metrics,
    merge_metrics,
)

SMALL_SEG = ModelConfig(
    n_blocks=(1, 1, 1), input_shape=(32, 32), base_depth=8, width_multiplier=0.0625
)
SMALL_CLS = ModelConfig(
    n_blocks=(1, 1, 1),
    input_shape=(32, 32),
    input_channels=3,
    num_classes=4,
    base_depth=8,
    width_multiplier=0.0625,
    output_stride=None,
)


def _setup(cfg, task, mesh, batch_shape):
    model = build_model(cfg)
    tx = make_optimizer(TrainConfig(lr=0.003))
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.ones(batch_shape, jnp.float32)
    )
    state = replicate(state, mesh)
    return state


def test_segmentation_loss_decreases_on_mesh():
    mesh = make_mesh(8)
    task = SegmentationTask()
    state = _setup(SMALL_SEG, task, mesh, (1, 32, 32, 2))
    train_step = make_train_step(mesh, task)
    batches = synthetic_batches(
        "segmentation", 16, seed=1, input_shape=(32, 32), steps=6
    )
    losses = []
    for batch in batches:
        state, metrics = train_step(state, shard_batch(batch, mesh))
        losses.append(compute_metrics(metrics)["loss"])
    assert int(state.step) == 6
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_eval_and_predict_steps():
    mesh = make_mesh(8)
    task = SegmentationTask()
    state = _setup(SMALL_SEG, task, mesh, (1, 32, 32, 2))
    eval_step = make_eval_step(mesh, task)
    predict_step = make_predict_step(mesh, task)
    batch = next(synthetic_batches("segmentation", 8, seed=2, input_shape=(32, 32)))
    sharded = shard_batch(batch, mesh)

    acc = None
    for _ in range(2):
        acc = merge_metrics(acc, eval_step(state, sharded))
    values = compute_metrics(acc)
    assert set(values) == {"metrics/mean_iou", "metrics/mean_acc", "loss"}
    assert acc["metrics/mean_iou"].count == 16  # 8 images x 2 passes

    preds = predict_step(state, sharded)
    assert preds["probabilities"].shape == (8, 32, 32, 1)
    assert preds["mask"].shape == (8, 32, 32, 1)
    probs = np.asarray(preds["probabilities"])
    assert np.all((probs >= 0) & (probs <= 1))


def test_classification_loss_decreases_on_mesh():
    mesh = make_mesh(8)
    task = ClassificationTask()
    state = _setup(SMALL_CLS, task, mesh, (1, 32, 32, 3))
    train_step = make_train_step(mesh, task)
    batches = synthetic_batches(
        "classification", 16, seed=3, input_shape=(32, 32), num_classes=4, steps=12
    )
    losses = []
    for batch in batches:
        state, metrics = train_step(state, shard_batch(batch, mesh))
        losses.append(compute_metrics(metrics)["loss"])
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_sharded_step_matches_single_device():
    """DP invariance: the 8-way sharded step must produce the same new params as a
    1-device run on the identical global batch (per-shard BN stats make batch_stats the
    one intentional difference — compare params and loss only).

    Note: with BN computing per-shard statistics, forward activations differ between
    1-way and 8-way; so we compare a BN-stat-free configuration... instead we compare
    8-way vs 8-way determinism here and cross-degree equivalence in
    test_cross_degree_grads for a BN-free model.
    """
    mesh = make_mesh(8)
    task = SegmentationTask()
    state_a = _setup(SMALL_SEG, task, mesh, (1, 32, 32, 2))
    state_b = _setup(SMALL_SEG, task, mesh, (1, 32, 32, 2))
    train_step = make_train_step(mesh, task, donate=False)
    batch = next(synthetic_batches("segmentation", 16, seed=4, input_shape=(32, 32)))
    sharded = shard_batch(batch, mesh)
    new_a, m_a = train_step(state_a, sharded)
    new_b, m_b = train_step(state_b, sharded)
    la, lb = compute_metrics(m_a)["loss"], compute_metrics(m_b)["loss"]
    assert la == pytest.approx(lb, abs=0.0)  # bitwise determinism
    flat_a = jax.tree.leaves(new_a.params)
    flat_b = jax.tree.leaves(new_b.params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cross_degree_grads():
    """True MirroredStrategy semantics: training on the SAME global batch must
    produce the same parameter update at data-parallel degree 1 and 8 (grads are
    the global-batch MEAN, not a per-shard sum — reference: MirroredStrategy's
    cross-device gradient aggregation, model.py:115-121). Uses a BN-free model so
    per-shard batch statistics cannot introduce a legitimate difference."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(8, (3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = x.mean(axis=(1, 2))
            return nn.Dense(4)(x)

    task = ClassificationTask()
    batch = next(
        synthetic_batches(
            "classification", 16, seed=9, input_shape=(8, 8), num_classes=4
        )
    )
    tx = make_optimizer(TrainConfig(lr=0.01))
    results = {}
    for n in (1, 8):
        mesh = make_mesh(n)
        model = Tiny()
        state = replicate(
            create_train_state(
                model, tx, jax.random.PRNGKey(0), np.zeros((1, 8, 8, 3), np.float32)
            ),
            mesh,
        )
        step = make_train_step(mesh, task, donate=False)
        new_state, _ = step(state, shard_batch(batch, mesh))
        results[n] = jax.tree.leaves(jax.device_get(new_state.params))
    for a, b in zip(results[1], results[8]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_state_stays_replicated_after_step():
    mesh = make_mesh(8)
    task = SegmentationTask()
    state = _setup(SMALL_SEG, task, mesh, (1, 32, 32, 2))
    train_step = make_train_step(mesh, task)
    batch = next(synthetic_batches("segmentation", 8, seed=5, input_shape=(32, 32)))
    state, _ = train_step(state, shard_batch(batch, mesh))
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_eval_step_valid_mask_excludes_padding():
    """Eval metrics with a `valid` mask must equal metrics computed over only the
    valid rows — the wrap-around-padding exclusion contract of eval_batches."""
    mesh = make_mesh(8)
    task = SegmentationTask()
    state = _setup(SMALL_SEG, task, mesh, (1, 32, 32, 2))
    eval_step = make_eval_step(mesh, task)
    batch = next(synthetic_batches("segmentation", 16, seed=6, input_shape=(32, 32)))

    # full batch, but only the first 10 rows are real
    valid = np.zeros(16, np.float32)
    valid[:10] = 1.0
    masked = dict(batch)
    masked["valid"] = valid
    got = compute_metrics(eval_step(state, shard_batch(masked, mesh)))

    # reference: build a 16-row batch whose rows are the 10 real ones wrapped around,
    # all valid -- metrics over exactly the same multiset requires matching rows, so
    # instead compare against a masked run with the padded rows REPLACED by garbage:
    # results must be identical since weight 0 excludes them.
    garbage = dict(masked)
    garbage["images"] = batch["images"].copy()
    garbage["images"][10:] = 999.0
    got_garbage = compute_metrics(eval_step(state, shard_batch(garbage, mesh)))
    for k in got:
        assert got[k] == pytest.approx(got_garbage[k], rel=1e-6), k
    # and the count only reflects valid rows
    acc = eval_step(state, shard_batch(masked, mesh))
    assert float(acc["metrics/mean_iou"].count) == 10.0


def test_bfloat16_train_step_close_to_float32():
    """The bf16 compute path (MXU dtype) trains: finite losses, and the first
    step's loss stays close to the float32 path on identical data/params."""
    import dataclasses

    mesh = make_mesh(8)
    task = SegmentationTask()
    batch = next(synthetic_batches("segmentation", 16, seed=21, input_shape=(32, 32)))
    losses = {}
    for dtype in ("float32", "bfloat16"):
        cfg = dataclasses.replace(SMALL_SEG, dtype=dtype)
        state = _setup(cfg, task, mesh, (1, 32, 32, 2))
        step = make_train_step(mesh, task, donate=False)
        new_state, metrics = step(state, shard_batch(batch, mesh))
        losses[dtype] = compute_metrics(metrics)["loss"]
        # params stay float32 regardless of compute dtype
        assert all(
            leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(new_state.params)
        )
    assert np.isfinite(losses["bfloat16"])
    assert losses["bfloat16"] == pytest.approx(losses["float32"], rel=0.05)


def test_sgd_optimizer_trains():
    """TrainConfig.optimizer='sgd' (Nesterov momentum, the ImageNet recipe):
    loss decreases on the mesh like the Adam default."""
    mesh = make_mesh(8)
    task = ClassificationTask()
    model = build_model(SMALL_CLS)
    tx = make_optimizer(TrainConfig(optimizer="sgd", lr=0.05))
    state = replicate(
        create_train_state(
            model, tx, jax.random.key(1), jnp.ones((1, 32, 32, 3), jnp.float32)
        ),
        mesh,
    )
    train_step = make_train_step(mesh, task)
    losses = []
    for batch in synthetic_batches(
        "classification", 16, seed=30, input_shape=(32, 32), num_classes=4, steps=12
    ):
        state, metrics = train_step(state, shard_batch(batch, mesh))
        losses.append(compute_metrics(metrics)["loss"])
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="Unknown optimizer"):
        TrainConfig(optimizer="adagrad")


def _toy_params():
    return {
        "conv": {"kernel": jnp.ones((3, 3, 2, 4), jnp.float32)},
        "bn": {"scale": jnp.ones((4,), jnp.float32), "bias": jnp.zeros((4,), jnp.float32)},
    }


def test_weight_decay_update_differs_and_masks_kernels():
    """The decayed SGD chain produces a different update from the undecayed one
    (VERDICT round-2 task #2), and the decay touches ONLY kernel leaves: with
    zero gradients the kernel shrinks toward zero while BN scale/bias —
    excluded by the mask, per the recipe (arXiv:1706.02677 §5.3) — stay put."""
    params = _toy_params()
    grads = jax.tree.map(jnp.zeros_like, params)

    plain = make_optimizer(TrainConfig(optimizer="sgd", lr=0.1))
    decayed = make_optimizer(TrainConfig(optimizer="sgd", lr=0.1, weight_decay=1e-2))

    up_plain, _ = plain.update(grads, plain.init(params), params)
    up_decayed, _ = decayed.update(grads, decayed.init(params), params)

    # undecayed + zero grads = zero update everywhere
    assert all(np.all(leaf == 0) for leaf in jax.tree.leaves(up_plain))
    # decayed: kernel moves (toward zero), non-kernels still untouched
    assert np.all(np.asarray(up_decayed["conv"]["kernel"]) < 0)
    assert np.all(np.asarray(up_decayed["bn"]["scale"]) == 0)
    assert np.all(np.asarray(up_decayed["bn"]["bias"]) == 0)


def test_weight_decay_mask_covers_moe_expert_weights():
    """The decay mask treats MoE expert matrices (w_in/w_out) and the router as
    weight matrices — they replace dense mlp kernels and must regularize like
    them — while expert biases stay excluded (code review r3)."""
    from tensorflowdistributedlearning_tpu.train.step import kernel_decay_mask

    params = {
        "moe": {
            "w_in": jnp.ones((2, 4, 8)),
            "b_in": jnp.zeros((2, 8)),
            "w_out": jnp.ones((2, 8, 4)),
            "b_out": jnp.zeros((2, 4)),
            "router": jnp.ones((4, 2)),
        },
        "ln": {"scale": jnp.ones((4,))},
    }
    mask = kernel_decay_mask(params)
    assert mask["moe"]["w_in"] and mask["moe"]["w_out"] and mask["moe"]["router"]
    assert not mask["moe"]["b_in"] and not mask["moe"]["b_out"]
    assert not mask["ln"]["scale"]


def test_weight_decay_adam_is_adamw():
    """weight_decay>0 with adam switches the chain to AdamW (decoupled decay),
    again masked to kernels only."""
    params = _toy_params()
    grads = jax.tree.map(jnp.zeros_like, params)
    tx = make_optimizer(TrainConfig(optimizer="adam", lr=0.1, weight_decay=1e-2))
    updates, _ = tx.update(grads, tx.init(params), params)
    assert np.all(np.asarray(updates["conv"]["kernel"]) < 0)
    assert np.all(np.asarray(updates["bn"]["scale"]) == 0)


def test_imagenet_presets_carry_weight_decay():
    """Every ImageNet preset ships the weight decay its cited recipe requires
    (Goyal et al. 1e-4 for the SGD/LARS ResNets, DeiT 0.1 for ViT); the
    reference-parity presets keep 0 — the reference never minimized its
    declared l2 (reference: model.py:462-467)."""
    from tensorflowdistributedlearning_tpu.configs import PRESETS

    assert PRESETS["resnet50_imagenet"].train.weight_decay == 1e-4
    assert PRESETS["resnet101_imagenet"].train.weight_decay == 1e-4
    assert PRESETS["resnet152_imagenet"].train.weight_decay == 1e-4
    assert PRESETS["xception41_imagenet"].train.weight_decay == 1e-4
    assert PRESETS["vit_s16_imagenet"].train.weight_decay == 0.1
    assert PRESETS["resnet50_bf16_8k"].train.weight_decay == 1e-4
    assert PRESETS["resnet50_bf16_8k"].train.optimizer == "lars"
    assert PRESETS["tgs_salt"].train.weight_decay == 0.0


def test_xception_classifier_trains():
    """Regression: Xception41's pre-logits dropout is live in train mode, so
    the train step must supply a 'dropout' PRNG stream — before the fix,
    train-mode apply raised InvalidRngError and the xception41 preset could
    not train a single step."""
    mesh = make_mesh(8)
    cfg = ModelConfig(
        backbone="xception",
        num_classes=4,
        input_shape=(32, 32),
        input_channels=3,
        width_multiplier=0.125,
    )
    task = ClassificationTask()
    state = _setup(cfg, task, mesh, (1, 32, 32, 3))
    train_step = make_train_step(mesh, task)
    batches = synthetic_batches(
        "classification", 16, seed=5, input_shape=(32, 32), num_classes=4, steps=8
    )
    losses = []
    for batch in batches:
        state, metrics = train_step(state, shard_batch(batch, mesh))
        losses.append(compute_metrics(metrics)["loss"])
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_xception_trains_under_grad_accum():
    """The accum scan threads a per-chunk index into the dropout stream; a
    dropout-bearing model must run under accum > 1 too (learning-rate descent
    is asserted by the non-accum test — with 0.5 dropout a handful of accum
    steps is too noisy for a monotonicity check)."""
    mesh = make_mesh(8)
    cfg = ModelConfig(
        backbone="xception",
        num_classes=4,
        input_shape=(32, 32),
        input_channels=3,
        width_multiplier=0.125,
    )
    task = ClassificationTask()
    state = _setup(cfg, task, mesh, (1, 32, 32, 3))
    train_step = make_train_step(mesh, task, accum=2)
    batches = synthetic_batches(
        "classification", 16, seed=5, input_shape=(32, 32), num_classes=4, steps=2
    )
    for batch in batches:
        state, metrics = train_step(state, shard_batch(batch, mesh))
        assert np.isfinite(compute_metrics(metrics)["loss"])


def test_dropout_stream_follows_configured_seed():
    """The dropout PRNG roots at the configured seed (TrainConfig.seed in the
    drivers), not a hardcoded key: same seed ⇒ bitwise-identical update,
    different seed ⇒ different dropout masks ⇒ different params."""
    mesh = make_mesh(8)
    cfg = ModelConfig(
        backbone="xception",
        num_classes=4,
        input_shape=(32, 32),
        input_channels=3,
        width_multiplier=0.125,
    )
    task = ClassificationTask()
    state = _setup(cfg, task, mesh, (1, 32, 32, 3))
    batch = shard_batch(
        next(
            synthetic_batches(
                "classification", 16, seed=5, input_shape=(32, 32), num_classes=4
            )
        ),
        mesh,
    )
    leaves = lambda s: jax.tree.leaves(jax.device_get(s.params))  # noqa: E731
    out_a = leaves(make_train_step(mesh, task, donate=False)(state, batch)[0])
    out_a2 = leaves(make_train_step(mesh, task, donate=False)(state, batch)[0])
    out_b = leaves(
        make_train_step(mesh, task, donate=False, seed=123)(state, batch)[0]
    )
    for a, a2 in zip(out_a, out_a2):
        np.testing.assert_array_equal(a, a2)
    assert any(not np.array_equal(a, b) for a, b in zip(out_a, out_b))


def test_lars_optimizer_trains():
    """TrainConfig.optimizer='lars' (large-batch layer-wise scaling,
    arXiv:1708.03888 — the 8k preset's optimizer) trains on the CPU mesh:
    loss decreases and stays finite."""
    mesh = make_mesh(8)
    task = ClassificationTask()
    model = build_model(SMALL_CLS)
    # kernels ride the trust-ratio-scaled update; BN/bias (excluded from trust
    # scaling, per the recipe) take the raw lr — keep it moderate, and use a
    # real per-shard batch (8): LARS is a large-batch method, and per-shard
    # BN over 2 images makes the raw-lr BN updates noisy enough to diverge
    tx = make_optimizer(TrainConfig(optimizer="lars", lr=0.2, weight_decay=1e-4))
    state = replicate(
        create_train_state(
            model, tx, jax.random.key(1), jnp.ones((1, 32, 32, 3), jnp.float32)
        ),
        mesh,
    )
    train_step = make_train_step(mesh, task)
    losses = []
    for batch in synthetic_batches(
        "classification", 64, seed=31, input_shape=(32, 32), num_classes=4, steps=12
    ):
        state, metrics = train_step(state, shard_batch(batch, mesh))
        losses.append(compute_metrics(metrics)["loss"])
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_multi_step_matches_sequential():
    """The device-side K-step loop (make_multi_train_step) runs the SAME
    per-step math as K sequential single steps — the scan body IS the
    single-step builder. Inlining under scan lets XLA fuse differently, so
    the comparison is tight-tolerance numerical, not bitwise: the Lovász
    sort's tie order shifts under different fusion, producing bounded
    (~1e-4-scale) param drift after 3 SGD steps. Two guards separate that
    noise from real bugs: absolute bars, and a DISCRIMINATOR — the same
    executable fed the batches in reversed order must diverge by at least
    4x the same-order drift (a carry/order bug would make same-order look
    like reversed-order); a carry/PRNG/order bug would blow
    far past these bars (a reversed batch order differs in the first
    digit)."""
    from tensorflowdistributedlearning_tpu.parallel import shard_batch_stacked
    from tensorflowdistributedlearning_tpu.train import make_multi_train_step

    mesh = make_mesh(8)
    task = SegmentationTask()
    k = 3
    raw = list(
        synthetic_batches("segmentation", 16, seed=5, input_shape=(32, 32), steps=k)
    )

    def sgd_setup():
        model = build_model(SMALL_SEG)
        tx = make_optimizer(TrainConfig(optimizer="sgd", lr=0.01))
        st = create_train_state(
            model, tx, jax.random.key(0), jnp.ones((1, 32, 32, 2), jnp.float32)
        )
        return replicate(st, mesh)

    state_a = sgd_setup()
    single = make_train_step(mesh, task, donate=False)
    seq_metrics = []
    for b in raw:
        state_a, m = single(state_a, shard_batch(b, mesh))
        seq_metrics.append(m)

    state_b = sgd_setup()
    multi = make_multi_train_step(mesh, task, n_steps=k)
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *raw)
    state_b, merged = multi(state_b, shard_batch_stacked(stacked, mesh))

    assert int(state_b.step) == k
    def maxdiff(ta, tb):
        return max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(jax.tree.leaves(ta), jax.tree.leaves(tb))
        )

    drift = maxdiff(state_a.params, state_b.params)
    bn_drift = maxdiff(state_a.batch_stats, state_b.batch_stats)
    # measured while writing the test: drift 3.9e-3 / bn 5.5e-6, with the
    # reversed-order control at 2.5e-1 / 1.3e-2 (64x / 2300x away)
    assert drift < 2e-2, f"same-order param drift {drift} exceeds the noise bar"
    assert bn_drift < 1e-4, f"same-order BN drift {bn_drift} exceeds the bar"

    # discriminator: reversed batch order through the SAME executable must
    # land far from the sequential trajectory
    state_c = sgd_setup()
    reversed_stacked = jax.tree.map(lambda x: x[::-1].copy(), stacked)
    state_c, _ = multi(state_c, shard_batch_stacked(reversed_stacked, mesh))
    rev_drift = maxdiff(state_a.params, state_c.params)
    assert rev_drift > 4 * max(drift, 1e-6), (drift, rev_drift)
    # merged streaming Means == sum of the per-step Means (merge is addition)
    summed = jax.tree.map(lambda *xs: sum(np.asarray(x) for x in xs), *seq_metrics)
    for a, b in zip(jax.tree.leaves(summed), jax.tree.leaves(merged)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3)


def test_sync_batch_norm_matches_global_batch_oracle():
    """TrainConfig.sync_batch_norm semantics: BN statistics span the GLOBAL
    batch (flax BN pmean over the batch mesh axis), so one train step on the
    8-shard mesh must reproduce the same step on a 1-device mesh where BN
    sees the full batch natively — params, BN stats, and loss. The per-shard
    default (the reference's per-tower semantics) measurably diverges: the
    negative control asserts it, and DIGITS_RUN.json's xception rows price
    it at up to 10 points of real accuracy."""
    from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS

    def setup(model, mesh):
        tx = make_optimizer(TrainConfig(optimizer="sgd", lr=0.01))
        st = create_train_state(
            model, tx, jax.random.key(0), jnp.ones((1, 32, 32, 2), jnp.float32)
        )
        return replicate(st, mesh)

    task = SegmentationTask()
    batch = next(
        synthetic_batches("segmentation", 16, seed=9, input_shape=(32, 32), steps=1)
    )

    mesh1 = make_mesh(1)
    oracle_model = build_model(SMALL_SEG)
    st = setup(oracle_model, mesh1)
    st, m_oracle = make_train_step(mesh1, task, donate=False)(
        st, shard_batch(batch, mesh1)
    )
    oracle = st

    mesh8 = make_mesh(8)
    sync_model = build_model(SMALL_SEG, bn_axis_name=BATCH_AXIS)
    st = setup(sync_model, mesh8)
    st, m_sync = make_train_step(mesh8, task, donate=False)(
        st, shard_batch(batch, mesh8)
    )

    def maxdiff(ta, tb):
        return max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(jax.tree.leaves(ta), jax.tree.leaves(tb))
        )

    assert maxdiff(oracle.params, st.params) < 1e-4
    assert maxdiff(oracle.batch_stats, st.batch_stats) < 1e-5
    np.testing.assert_allclose(
        compute_metrics(m_sync)["loss"], compute_metrics(m_oracle)["loss"],
        rtol=1e-5,
    )

    # negative control: per-shard BN (the default) does NOT match the oracle
    plain_model = build_model(SMALL_SEG)
    st_p = setup(plain_model, mesh8)
    st_p, _ = make_train_step(mesh8, task, donate=False)(
        st_p, shard_batch(batch, mesh8)
    )
    assert maxdiff(oracle.batch_stats, st_p.batch_stats) > 1e-4
