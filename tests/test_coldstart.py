"""Cold-start elimination: persistent compile cache + load-not-compile.

The contracts under test are the ones the cold-start work ships on:

- the persistent compile cache survives the PROCESS — a fresh interpreter
  running the same-shape computation loads its executables (ledgered cache
  hits, zero real compiles) instead of rebuilding them;
- an exported artifact's shipped ``compile_cache/`` subdir round-trips
  through the real manifest seam (attach at export, fingerprint-verified
  consume at load) and a warm replica's warmup is compile-free;
- an unwritable cache dir degrades to an uncached run with a warning —
  never a crash (utils/compile_cache.py configure());
- parallel bucket warmup preserves the warm-mark ordering and the
  ``warmed_buckets`` accounting;
- ``replica_ready.time_to_ready_s`` and the compile-cache verdicts surface
  in ``telemetry-report``/``telemetry-top``, with cache-served compiles
  counted apart from real recompiles (the zero-post-warmup contract stays
  meaningful under a shared cache).
"""

import json
import os
import stat
import subprocess
import sys

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs.report import (
    build_report,
    render_report,
)
from tensorflowdistributedlearning_tpu.utils import compile_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FEATURES = 6
CLASSES = 3


def _env(extra=None):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    env.update(extra or {})
    return env


# -- cross-process persistent-cache round-trip -------------------------------

_ROUNDTRIP_SCRIPT = """
import json, sys
sys.path.insert(0, {repo!r})
import numpy as np
from tensorflowdistributedlearning_tpu.utils import compile_cache
from tensorflowdistributedlearning_tpu.obs import Telemetry

assert compile_cache.configure({cache_dir!r})
import jax, jax.numpy as jnp

tel = Telemetry({workdir!r}, run_info={{"kind": "cache-roundtrip"}})

@jax.jit
def f(x):
    return jnp.tanh(x @ x.T).sum()

@jax.jit
def g(x):
    return (x * 2.0 + 1.0).mean()

jax.block_until_ready(f(jnp.ones((8, 8))))
jax.block_until_ready(g(jnp.ones((16,))))
tel.close()
print(json.dumps(compile_cache.stats()))
"""


@pytest.fixture(scope="module")
def cache_roundtrip(tmp_path_factory):
    """Two fresh interpreters, same cache dir, same computation — the
    second must LOAD. Shared by the ledger and report assertions."""
    base = tmp_path_factory.mktemp("cc_roundtrip")
    cache_dir = str(base / "cache")
    runs = []
    for i in (0, 1):
        workdir = str(base / f"run{i}")
        script = _ROUNDTRIP_SCRIPT.format(
            repo=REPO, cache_dir=cache_dir, workdir=workdir
        )
        out = subprocess.run(
            [sys.executable, "-c", script], env=_env(), capture_output=True,
            text=True, timeout=240,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        stats = json.loads(out.stdout.strip().splitlines()[-1])
        runs.append({"workdir": workdir, "stats": stats})
    return cache_dir, runs


def test_second_interpreter_loads_from_cache(cache_roundtrip):
    cache_dir, (cold, warm) = cache_roundtrip
    # run 0 populated the cache (misses), run 1 consumed it (hits, 0 misses)
    assert cold["stats"]["misses"] >= 2 and cold["stats"]["hits"] == 0
    assert warm["stats"]["hits"] >= 2 and warm["stats"]["misses"] == 0
    entries = compile_cache.fingerprint(cache_dir)["entries"]
    assert entries >= 2


def test_cache_verdicts_reach_the_ledger(cache_roundtrip):
    _, (cold, warm) = cache_roundtrip
    cold_events = obs.read_ledger(cold["workdir"])
    warm_events = obs.read_ledger(warm["workdir"])

    def compiles(events):
        return [e for e in events if e.get("event") == "compile"]

    # cache-consulted compiles are ALWAYS ledgered (the duration threshold
    # would hide exactly the proof the cache works)
    assert any(e.get("cache_hit") is False for e in compiles(cold_events))
    warm_hits = [e for e in compiles(warm_events) if e.get("cache_hit")]
    assert warm_hits, "second run ledgered no cache hits"
    # the second run did strictly fewer REAL compiles than the first
    real = lambda evs: [e for e in compiles(evs) if not e.get("cache_hit")]
    assert len(real(warm_events)) < len(real(cold_events))
    # run_end totals carry the detector's exact counters
    warm_end = [e for e in warm_events if e.get("event") == "run_end"][-1]
    assert warm_end["compile_cache_hits"] >= 2
    assert warm_end["compile_cache_misses"] == 0


def test_report_renders_hit_ratio(cache_roundtrip):
    _, (_, warm) = cache_roundtrip
    report = build_report(warm["workdir"])
    cc = report["compile_cache"]
    assert cc["hits"] >= 2 and cc["misses"] == 0
    assert cc["hit_ratio"] == 1.0
    text = render_report(report)
    assert "compile cache:" in text
    assert "100% served from cache" in text


# -- degradation: unwritable cache dir ---------------------------------------


def test_unwritable_cache_dir_degrades_uncached(tmp_path, caplog):
    ro = tmp_path / "ro"
    ro.mkdir()
    os.chmod(ro, stat.S_IRUSR | stat.S_IXUSR)
    try:
        if os.access(str(ro / "probe"), os.W_OK) or os.getuid() == 0:
            pytest.skip("running as root — read-only dirs are writable")
        before = compile_cache.active_dir()
        with caplog.at_level("WARNING"):
            assert compile_cache.configure(str(ro)) is False
        assert compile_cache.active_dir() == before  # untouched, not crashed
        assert any("UNCACHED" in r.message for r in caplog.records)
    finally:
        os.chmod(ro, stat.S_IRWXU)


def test_configure_none_is_a_noop():
    before = compile_cache.active_dir()
    assert compile_cache.configure(None) is False
    assert compile_cache.active_dir() == before


# -- artifact cache subdir: attach -> fingerprint -> consume -----------------


@pytest.fixture(scope="module")
def serve_fn():
    import jax
    import jax.numpy as jnp

    w = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.3

    @jax.jit
    def fn(x):
        logits = x @ w
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return fn


@pytest.fixture(scope="module")
def cached_artifact(tmp_path_factory, serve_fn):
    """An exported artifact with its compile cache attached through the
    real seam (train/serving.py attach_compile_cache)."""
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory = str(tmp_path_factory.mktemp("artifact") / "art")
    serving_lib.export_serving_artifact(serve_fn, (1, FEATURES), directory)
    section = serving_lib.attach_compile_cache(directory, buckets=(1, 4))
    return directory, section


def test_attach_stamps_manifest_fingerprint(cached_artifact):
    from tensorflowdistributedlearning_tpu.serve.engine import (
        ARTIFACT_CACHE_SUBDIR,
    )
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory, section = cached_artifact
    assert section["subdir"] == ARTIFACT_CACHE_SUBDIR
    assert section["entries"] >= 1
    assert section["buckets"] == [1, 4]
    sub = os.path.join(directory, ARTIFACT_CACHE_SUBDIR)
    assert os.path.isdir(sub)
    manifest = serving_lib.read_manifest(directory)
    assert manifest["compile_cache"]["fingerprint"] == section["fingerprint"]
    # the attach must NOT leave the process writing into the artifact
    assert compile_cache.active_dir() != sub


_LOAD_SCRIPT = """
import json, sys
sys.path.insert(0, {repo!r})
from tensorflowdistributedlearning_tpu.utils import compile_cache
assert compile_cache.configure({cache_dir!r})
from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine
eng = InferenceEngine.from_artifact({artifact!r}, buckets=(1, 4))
timings = eng.warmup()
print(json.dumps({{
    "stats": compile_cache.stats(),
    "warmed": sorted(eng.warmed_buckets),
    "timings": {{str(k): v for k, v in timings.items()}},
}}))
"""


def _load_replica(artifact: str, cache_dir: str) -> dict:
    script = _LOAD_SCRIPT.format(
        repo=REPO, cache_dir=cache_dir, artifact=artifact
    )
    out = subprocess.run(
        [sys.executable, "-c", script], env=_env(), capture_output=True,
        text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_warm_artifact_load_is_compile_free(cached_artifact, tmp_path):
    directory, _ = cached_artifact
    res = _load_replica(directory, str(tmp_path / "replica_cache"))
    # every warmup compile answered from the shipped entries
    assert res["warmed"] == [1, 4]
    assert res["stats"]["hits"] >= 2
    assert res["stats"]["misses"] == 0


def test_cold_artifact_load_compiles(cached_artifact, tmp_path):
    import shutil

    from tensorflowdistributedlearning_tpu.serve.engine import (
        ARTIFACT_CACHE_SUBDIR,
    )

    directory, _ = cached_artifact
    bare = str(tmp_path / "bare_artifact")
    shutil.copytree(directory, bare)
    shutil.rmtree(os.path.join(bare, ARTIFACT_CACHE_SUBDIR))
    res = _load_replica(bare, str(tmp_path / "replica_cache"))
    assert res["warmed"] == [1, 4]
    assert res["stats"]["misses"] >= 2
    assert res["stats"]["hits"] == 0


def test_torn_shipped_cache_is_refused(cached_artifact, tmp_path, caplog):
    """A shipped cache whose fingerprint mismatches the manifest (truncated
    copy, mixed artifact) is skipped — warmup compiles, serving proceeds."""
    import shutil

    from tensorflowdistributedlearning_tpu.serve.engine import (
        ARTIFACT_CACHE_SUBDIR,
        consume_artifact_cache,
    )
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory, _ = cached_artifact
    torn = str(tmp_path / "torn_artifact")
    shutil.copytree(directory, torn)
    sub = os.path.join(torn, ARTIFACT_CACHE_SUBDIR)
    entry = next(
        os.path.join(root, f)
        for root, _, files in os.walk(sub)
        for f in files
    )
    with open(entry, "ab") as fh:
        fh.write(b"torn")
    manifest = serving_lib.read_manifest(torn)
    with caplog.at_level("WARNING"):
        assert consume_artifact_cache(torn, manifest) == 0
    assert any("fingerprint" in r.message for r in caplog.records)


# -- parallel warmup: ordering + accounting ----------------------------------


def test_parallel_warmup_accounting_and_warm_mark(tmp_path, serve_fn):
    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine

    eng = InferenceEngine(serve_fn, (FEATURES,), buckets=(1, 4, 8))
    tel = Telemetry(str(tmp_path), run_info={"kind": "serve"})
    timings = eng.warmup(telemetry=tel)
    assert set(timings) == {1, 4, 8}
    assert eng.warmed and eng.warmed_buckets == {1, 4, 8}
    assert all(t >= 0 for t in timings.values())
    # the warm mark landed strictly after every bucket: steady-state traffic
    # on warmed shapes triggers zero post-warmup recompiles
    x = np.random.default_rng(0).normal(size=(3, FEATURES)).astype("float32")
    eng.infer(x)
    assert tel.detector.post_warmup_count == 0
    tel.close()
    events = obs.read_ledger(str(tmp_path))
    warmup_events = [e for e in events if e.get("event") == "serve_warmup"]
    assert len(warmup_events) == 1
    assert sorted(warmup_events[0]["buckets"]) == ["1", "4", "8"]


def test_deferred_warm_mark_for_multi_engine_load(tmp_path, serve_fn):
    """mark_warm=False (the multi-engine registry path) must leave the
    detector unarmed so a SECOND engine's warmup is not flagged."""
    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine

    tel = Telemetry(str(tmp_path), run_info={"kind": "serve"})
    a = InferenceEngine(serve_fn, (FEATURES,), buckets=(1, 4))
    a.warmup(telemetry=tel, mark_warm=False)
    b = InferenceEngine(lambda x: {"y": x * 3.0}, (FEATURES,), buckets=(2,))
    b.warmup(telemetry=tel, mark_warm=False)
    assert tel.detector.post_warmup_count == 0
    tel.mark_warm()
    tel.close()


# -- replica time_to_ready_s + compile split in report/top -------------------


def test_replica_ttr_surfaces_in_report_and_top(tmp_path):
    from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib
    from tensorflowdistributedlearning_tpu.obs import top as top_lib

    ledger = obs.RunLedger(str(tmp_path))
    ledger.event("run_header", schema_version=1, kind="serve-fleet")
    ledger.event("replica_spawn", replica=0, port=9001)
    ledger.event("replica_ready", replica=0, port=9001, time_to_ready_s=6.4)
    ledger.event("replica_spawn", replica=1, port=9002)
    ledger.event("replica_ready", replica=1, port=9002, time_to_ready_s=1.6)
    ledger.close()

    report = build_report(str(tmp_path))
    ttr = report["serve_fleet"]["replicas"]["time_to_ready_s"]
    assert ttr["count"] == 2
    assert ttr["mean"] == 4.0
    assert ttr["max"] == 6.4
    assert ttr["last"] == 1.6
    text = render_report(report)
    assert "replica time-to-ready" in text

    led = fleet_lib.discover_ledgers(str(tmp_path))[0]
    row = top_lib._process_status(led, now=led.events[-1]["t"] + 1)
    assert row["last_replica_ready"]["time_to_ready_s"] == 1.6
    assert row["last_replica_ready"]["replica"] == 1


def test_cache_served_compiles_split_from_recompiles(tmp_path):
    """The satellite bugfix: a post-warmup compile the persistent cache
    answered is a LOAD — it must not trip the recompile alarm, but it must
    stay visible."""
    ledger = obs.RunLedger(str(tmp_path))
    ledger.event("run_header", schema_version=1, task="classification")
    ledger.event(
        "compile", duration_s=0.002, phase="train", post_warmup=True,
        cache_hit=True, saved_s=0.5,
    )
    ledger.event(
        "compile", duration_s=1.25, phase="train", post_warmup=True,
        cache_hit=False,
    )
    ledger.close()
    report = build_report(str(tmp_path))
    rc = report["recompiles"]
    assert rc["post_warmup_count"] == 1  # the REAL rebuild only
    assert rc["cache_served_post_warmup"] == 1
    assert rc["post_warmup_s"] == 1.25
    # no run_end totals here: the section falls back to ledgered verdicts
    cc = report["compile_cache"]
    assert cc == {"hits": 1, "misses": 1, "hit_ratio": 0.5, "saved_s": 0.5}
    text = render_report(report)
    assert "1 POST-WARMUP RECOMPILE(S)" in text
    assert "served from the persistent cache" in text
