"""Real-pixel segmentation end to end: foreground (ink) masks over the genuine
8x8 digit scans through the FULL flagship loop — salt-layout PNGs, K-fold
Trainer, Lovász hinge, thresholded mIOU, best-export, fold x TTA ensemble
predict — asserting the loop LEARNS real image statistics (every other
segmentation test in the suite fits synthetic masks). CI twin of
``examples/train_digit_seg.py`` / the committed ``SEG_RUN.json``; same data
code (``data/digits.py:prepare_digit_segmentation``), scaled-down budget.

Reference analogue: its notebooks' real TGS-salt runs (reference:
model.py:138-227, Untitled.ipynb cells 7-8) — the production proof its repo
had and unit tests cannot substitute for."""

import os

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import TrainConfig
from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
from tensorflowdistributedlearning_tpu.data.digits import (
    SHORT_BUDGET_BN_DECAY,
    prepare_digit_segmentation,
)
from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib
from tensorflowdistributedlearning_tpu.train.trainer import Trainer

STEPS = 40
SIZE = 64

# slow tier: a real K-fold training run (~3 min on the 1-core CI box) — run
# via tools/run_suite.py's group budgets, outside the 870s tier-1 window
pytestmark = pytest.mark.slow


def test_digit_segmentation_learns_real_pixels(tmp_path):
    data_dir = str(tmp_path / "data")
    train_dir, test_dir = prepare_digit_segmentation(
        data_dir, size=(SIZE, SIZE), limit=256
    )
    trainer = Trainer(
        str(tmp_path / "run"),
        train_dir,
        n_fold=2,
        train_config=TrainConfig(
            n_folds=2,
            checkpoint_every_steps=STEPS // 2,
            eval_every_steps=STEPS // 2,
            eval_throttle_secs=0,
        ),
        input_shape=(SIZE, SIZE),
        width_multiplier=0.125,
        batch_norm_decay=SHORT_BUDGET_BN_DECAY,
    )
    ids = pipeline_lib.discover_ids(train_dir)
    fold_metrics = trainer.train(ids, batch_size=16, steps=STEPS)
    assert len(fold_metrics) == 2
    for m in fold_metrics:
        assert np.isfinite(m["loss"])

    # fold x TTA ensemble on images the K-fold pool never contained; the
    # loose floor asserts real learning (an all-background or all-foreground
    # prediction scores ~0.0-0.1 on this corpus; the committed SEG_RUN.json
    # run documents what the full budget reaches)
    pred = trainer.predict(test_dir, batch_size=16)
    truth = pipeline_lib.load_masks(test_dir, pred["ids"])
    miou = float(np.mean(np.asarray(metrics_lib.iou_scores(truth, pred["masks"]))))
    assert miou >= 0.2, f"TTA-ensemble mIOU {miou:.3f} on held-out real pixels"

    # best-export artifacts exist for every fold (the predict path used them)
    for fold in range(2):
        assert os.path.isdir(str(tmp_path / "run" / f"fold{fold}" / "export" / "best"))
