"""GPipe-style pipeline parallelism (parallel/pipeline.py): exactness of the
scan+ppermute schedule against sequential stage application, gradient parity
through the pipelined computation, and microbatch-count flexibility."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.parallel import pipeline as pp
from tensorflowdistributedlearning_tpu.parallel.mesh import make_mesh

K = 4  # pipeline stages (model-axis size of the (2, 4, 1) mesh)


def stage_fn(params, x):
    """One homogeneous stage: 3x3 same-width conv + bias + relu."""
    y = jax.lax.conv_general_dilated(
        x, params["w"], (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    return jax.nn.relu(y + params["b"])


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(8, model_parallel=K)  # (batch=2, model=4, sequence=1)
    rng = np.random.default_rng(0)
    stages = [
        {
            "w": rng.normal(0, 0.3, (3, 3, 4, 4)).astype(np.float32),
            "b": rng.normal(0, 0.1, (4,)).astype(np.float32),
        }
        for _ in range(K)
    ]
    stacked = pp.stack_stage_params([jax.tree.map(jnp.asarray, s) for s in stages])
    x = rng.normal(0, 1, (6, 2, 8, 8, 4)).astype(np.float32)  # [M=6, mb=2, ...]
    return mesh, stages, stacked, x


def _sequential(stages, x_micro):
    out = []
    for m in range(x_micro.shape[0]):
        h = x_micro[m]
        for s in stages:
            h = stage_fn(s, h)
        out.append(h)
    return np.stack(out)


def test_pipeline_matches_sequential(setup):
    mesh, stages, stacked, x = setup
    run = pp.make_pipeline_fn(stage_fn, mesh)
    out = np.asarray(jax.device_get(run(stacked, x)))
    ref = _sequential(stages, x)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_pipeline_single_microbatch(setup):
    mesh, stages, stacked, x = setup
    run = pp.make_pipeline_fn(stage_fn, mesh)
    out = np.asarray(jax.device_get(run(stacked, x[:1])))
    np.testing.assert_allclose(out, _sequential(stages, x[:1]), rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential(setup):
    """Reverse-mode autodiff through the scan+ppermute schedule: the compiler-
    derived backward pipeline produces the same parameter gradients as the
    sequential composition."""
    mesh, stages, stacked, x = setup
    run = pp.make_pipeline_fn(stage_fn, mesh)

    def loss_pipelined(params):
        return jnp.sum(run(params, x) ** 2)

    def loss_sequential(params_list):
        total = 0.0
        for m in range(x.shape[0]):
            h = jnp.asarray(x[m])
            for k in range(K):
                h = stage_fn(jax.tree.map(lambda p: p[k], params_list), h)
            total = total + jnp.sum(h**2)
        return total

    g_pipe = jax.grad(loss_pipelined)(stacked)
    g_seq = jax.grad(loss_sequential)(stacked)
    for path, leaf in jax.tree_util.tree_leaves_with_path(g_pipe):
        ref = dict(jax.tree_util.tree_leaves_with_path(g_seq))[path]
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)),
            np.asarray(jax.device_get(ref)),
            rtol=2e-4,
            atol=2e-4,
            err_msg=str(path),
        )
