"""End-to-end telemetry: a real fit() run writes a complete telemetry.jsonl,
the `telemetry-report` CLI renders it, and a deliberately-triggered
post-warmup recompile is counted and surfaced in both the ledger and the
report (the acceptance pin for the obs subsystem)."""

import json

import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs.report import (
    build_report,
    render_report,
)

TINY = dict(
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    n_blocks=(1, 1, 1),
    width_multiplier=0.125,
    output_stride=None,
)


@pytest.fixture(scope="module")
def fit_workdir(tmp_path_factory):
    """One short synthetic fit() run shared by the ledger/report assertions."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    workdir = str(tmp_path_factory.mktemp("telemetry_run"))
    trainer = ClassifierTrainer(
        workdir,
        None,  # synthetic data
        ModelConfig(**TINY),
        TrainConfig(
            train_log_every_steps=2,
            checkpoint_every_steps=4,
            eval_every_steps=4,
            telemetry_memory_every_windows=2,
        ),
    )
    result = trainer.fit(batch_size=8, steps=8, eval_every_steps=4)
    return workdir, result


def test_fit_writes_complete_ledger(fit_workdir):
    workdir, result = fit_workdir
    events = obs.read_ledger(workdir)
    kinds = {e["event"] for e in events}
    assert {
        "run_header",
        "step_window",
        "eval",
        "checkpoint",
        "memory",
        "run_end",
    } <= kinds

    header = events[0]
    assert header["event"] == "run_header"
    assert header["fingerprint"]["n_devices"] >= 1
    assert header["mesh"]["batch"] >= 1
    assert header["train_config"]["train_log_every_steps"] == 2

    windows = [e for e in events if e["event"] == "step_window"]
    assert windows, "no step windows recorded"
    for w in windows:
        assert w["data_wait_s"] >= 0 and w["compute_s"] > 0
        assert 0.0 <= w["data_wait_frac"] <= 1.0
        assert w["step_time_ms"]["p50_ms"] > 0
    # the first window carries the compile: dirty, no throughput point
    assert windows[0]["dirty"]

    evals = [e for e in events if e["event"] == "eval"]
    assert evals and evals[-1]["metrics"]["metrics/top1"] >= 0
    assert all(e["duration_s"] > 0 for e in evals)

    assert any(e["event"] == "memory" for e in events)

    end = events[-1]
    assert end["event"] == "run_end"
    assert end["steps"] == result.steps == 8


def test_report_builds_and_renders(fit_workdir):
    workdir, _ = fit_workdir
    report = build_report(workdir)
    assert report["run"]["completed"]
    assert report["run"]["last_step"] == 8
    ts = report["time_split"]
    assert ts["compute_s"] > 0
    assert ts["eval_s"] > 0
    assert report["evals"]["count"] >= 2
    assert report["checkpoints"] >= 1
    assert report["memory"]["snapshots"] >= 1
    assert report["trace"] is None  # no xplane capture in this run
    text = render_report(report)
    assert "goodput report" in text
    assert "data-wait" in text and "step-compute" in text


def test_report_cli_renders_and_json(fit_workdir, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    workdir, _ = fit_workdir
    assert main(["telemetry-report", workdir]) == 0
    out = capsys.readouterr().out
    assert "goodput report" in out and "where the wall time went" in out

    assert main(["telemetry-report", workdir, "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["run"]["last_step"] == 8


def test_report_cli_missing_workdir_fails_cleanly(tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    # missing workdir / missing ledger is rc 2 (a CI pipeline pointing at
    # the wrong dir must fail loudly) with a one-line stderr hint
    assert main(["telemetry-report", str(tmp_path / "nope")]) == 2
    assert "telemetry-report" in capsys.readouterr().err


def test_report_empty_ledger_raises(tmp_path):
    (tmp_path / obs.LEDGER_FILENAME).write_text("")
    with pytest.raises(ValueError, match="empty telemetry ledger"):
        build_report(str(tmp_path))


# -- section renderers against partial ledgers (every producer writes the
# same schema, but not every workdir has every section) ----------------------


def _header_only_ledger(workdir, **fields):
    ledger = obs.RunLedger(str(workdir))
    ledger.event("run_header", schema_version=1, **fields)
    return ledger


def test_report_serving_only_workdir(tmp_path):
    """A serve --workdir has serve_window events and NO step windows: the
    report must build and render with a serving section and n/a splits."""
    ledger = _header_only_ledger(tmp_path, kind="serve", replica=0)
    ledger.event(
        "serve_window", replica=0, requests=10, completed=9,
        rejected_queue_full=1, deadline_exceeded=0, errors=0, batches=3,
        batched_examples=9, bucket_hits={"4": 3},
        latency_ms={"compute": {
            "count": 3.0, "mean_ms": 2.0, "p50_ms": 2.0, "p90_ms": 3.0,
            "p99_ms": 4.0, "max_ms": 4.0,
        }},
    )
    ledger.close()
    report = build_report(str(tmp_path))
    assert report["run"]["last_step"] is None
    assert report["run"]["windows"] == 0
    assert report["serve"]["requests"] == 10
    assert report["serve"]["mean_batch_fill"] == 3.0
    text = render_report(report)
    assert "serving" in text
    assert "9 completed" in text


def test_report_health_events_only_workdir(tmp_path):
    """Health alerts with no windows (e.g. a run that died in warmup after
    an injected NaN) still render a health section."""
    ledger = _header_only_ledger(tmp_path, task="classification")
    ledger.event(
        "health_alert", monitor="nan_loss", severity="critical", step=1,
        loss="nan", action="abort",
    )
    ledger.close()
    report = build_report(str(tmp_path))
    assert report["health"]["alerts"] == 1
    assert report["health"]["degraded"] == ["nan_loss"]
    text = render_report(report)
    assert "health: 1 alert(s)" in text
    assert "nan_loss" in text


def test_report_header_only_workdir(tmp_path):
    """A run header and nothing else (crashed before the first window):
    report and rendering survive with empty sections."""
    _header_only_ledger(tmp_path, task="classification").close()
    report = build_report(str(tmp_path))
    assert report["run"]["windows"] == 0
    assert not report["run"]["completed"]
    assert report["evals"]["count"] == 0
    text = render_report(report)
    assert "goodput report" in text
    assert "IN PROGRESS / interrupted" in text


def test_op_breakdown_failure_paths(tmp_path):
    """xplane.op_breakdown on a missing and on an empty logdir raises the
    clean FileNotFoundError the report layer turns into trace=None."""
    from tensorflowdistributedlearning_tpu.utils import xplane

    with pytest.raises(FileNotFoundError):
        xplane.op_breakdown(str(tmp_path / "missing"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        xplane.op_breakdown(str(empty))


def test_forced_recompile_surfaces_in_ledger_and_report(tmp_path, caplog):
    """The acceptance pin: a deliberately-triggered post-warmup recompile
    (reshape-induced retrace) is counted and surfaced in BOTH the ledger and
    the rendered report."""
    import jax
    import jax.numpy as jnp

    workdir = str(tmp_path)
    tel = obs.Telemetry(workdir, is_main=True, run_info={"task": "test"})
    try:

        @jax.jit
        def step(x):
            return (x * 3 + 1).sum()

        with tel.span(obs.SPAN_STEP):
            step(jnp.ones((4,)))  # expected warmup compile
        tel.window_event(1, steps=1, dirty=True)
        tel.mark_warm(obs.SPAN_STEP, obs.SPAN_DATA_WAIT)
        with tel.span(obs.SPAN_STEP):
            step(jnp.ones((6,)))  # shape drift => the silent goodput killer
        tel.window_event(2, steps=1)
    finally:
        tel.close(steps=2)

    events = obs.read_ledger(workdir)
    flagged = [
        e for e in events if e["event"] == "compile" and e["post_warmup"]
    ]
    assert flagged, "post-warmup recompile missing from the ledger"
    assert flagged[0]["phase"] == obs.SPAN_STEP
    # the window and run_end carry the running count
    last_window = [e for e in events if e["event"] == "step_window"][-1]
    assert last_window["recompiles_post_warmup"] >= 1
    assert events[-1]["recompiles_post_warmup"] >= 1
    # ... and the detector warned loudly
    assert any("recompilation" in r.message.lower() for r in caplog.records)

    report = build_report(workdir)
    assert report["recompiles"]["post_warmup_count"] >= 1
    assert report["recompiles"]["events"][0]["phase"] == obs.SPAN_STEP
    assert "POST-WARMUP RECOMPILE" in render_report(report)
