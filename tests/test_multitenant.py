"""Multi-tenant serving: artifact registry, model-aware routing/autoscaling,
weighted fair shedding, and the single-model promotion flip.

The contracts under test are the ones a shared fleet is operated by: the
registry document is strict (a typo'd field fails the fleet at spawn, not
silently at 3am), a legacy single-artifact workdir keeps working as an
implicit one-entry registry (no flag-day), the router routes on the
payload's model hint and sheds by weighted fair share only under live
saturation pressure, the per-model autoscaler defers — explicitly, ledgered
— rather than bust the fleet-wide chip budget, and a promotion scoped to one
model flips exactly that registry entry's version while every other tenant
keeps serving.

The subprocess end-to-end drills (slow-marked, run unfiltered by the focused
ci.yml step) drive the real tier: a 2-model registry fleet behind one
router — saturating tenant alpha sheds per fair-share weights while beta's
p99 stays inside its SLO band, and ``promote --model alpha`` rolls only
alpha's replicas with zero client-visible errors on beta.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.serve.registry import (
    DEFAULT_MODEL,
    REGISTRY_FLIP_EVENT,
    ModelEntry,
    Registry,
    RegistryError,
    read_registry,
    registry_path,
    write_registry,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEATURES = 6
CLASSES = 3


# -- registry schema ---------------------------------------------------------


def test_registry_round_trip(tmp_path):
    wd = str(tmp_path)
    write_registry(wd, [
        ModelEntry(name="alpha", artifact_dir="/a", weight=2.0,
                   buckets=(1, 4), prewarm_budget=1, slo_p99_ms=50.0,
                   replicas=2, max_replicas=3, chips_per_replica=2,
                   device_slots=("0,1", "2,3")),
        ModelEntry(name="beta", artifact_dir="/b"),
    ])
    reg = read_registry(wd)
    assert not reg.implicit
    assert sorted(reg.models) == ["alpha", "beta"]
    a = reg.entry("alpha")
    assert a.weight == 2.0
    assert a.buckets == (1, 4)
    assert a.prewarm_budget == 1
    assert a.slo_p99_ms == 50.0
    assert a.replicas == 2 and a.max_replicas == 3
    assert a.chips_per_replica == 2
    assert a.device_slots == ("0,1", "2,3")
    b = reg.entry("beta")
    assert b.version == 1 and b.weight == 1.0 and b.buckets is None


def test_registry_rejects_unknown_field(tmp_path):
    """The manifest.json lesson: a typo'd knob must fail the fleet at spawn,
    not silently warm everything."""
    wd = str(tmp_path)
    doc = {
        "schema_version": 1,
        "models": [
            {"name": "m", "artifact_dir": "/a", "prewarm_budgit": 2},
        ],
    }
    with open(registry_path(wd), "w") as f:
        json.dump(doc, f)
    with pytest.raises(RegistryError, match="prewarm_budgit"):
        read_registry(wd)


def test_registry_rejects_corrupt_and_unknown_version(tmp_path):
    wd = str(tmp_path)
    with open(registry_path(wd), "w") as f:
        f.write("{not json")
    with pytest.raises(RegistryError):
        read_registry(wd)
    with open(registry_path(wd), "w") as f:
        json.dump({"schema_version": 99, "models": []}, f)
    with pytest.raises(RegistryError, match="schema_version"):
        read_registry(wd)


def test_registry_legacy_workdir_loads_implicit(tmp_path):
    """No flag-day: a workdir without registry.json resolves to an implicit
    one-entry registry under DEFAULT_MODEL, and saving it never writes a
    registry.json the operator didn't ask for."""
    wd = str(tmp_path)
    reg = read_registry(wd, default_artifact_dir="/legacy/artifact")
    assert reg.implicit
    assert list(reg.models) == [DEFAULT_MODEL]
    assert reg.entry(DEFAULT_MODEL).artifact_dir == "/legacy/artifact"
    reg.set_version(DEFAULT_MODEL, "/legacy/v2")
    assert not os.path.exists(registry_path(wd))


def test_registry_without_source_is_an_error(tmp_path):
    with pytest.raises(RegistryError):
        read_registry(str(tmp_path))


def test_registry_unknown_model_lists_known(tmp_path):
    write_registry(str(tmp_path), [ModelEntry(name="alpha",
                                              artifact_dir="/a")])
    reg = read_registry(str(tmp_path))
    with pytest.raises(RegistryError, match="alpha"):
        reg.entry("nope")


def test_registry_version_flip_is_atomic_and_forward_only(tmp_path):
    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    wd = str(tmp_path / "fleet")
    os.makedirs(wd)
    write_registry(wd, [ModelEntry(name="alpha", artifact_dir="/v1"),
                        ModelEntry(name="beta", artifact_dir="/b")])
    reg = read_registry(wd)
    led = str(tmp_path / "ledger")
    tel = Telemetry(led, run_info={"kind": "test"})
    entry = reg.set_version("alpha", "/v2", telemetry=tel)
    tel.close()
    assert entry.version == 2 and entry.artifact_dir == "/v2"
    # the flip is on disk (atomic rewrite), other entries untouched
    reread = read_registry(wd)
    assert reread.entry("alpha").version == 2
    assert reread.entry("alpha").artifact_dir == "/v2"
    assert reread.entry("beta").version == 1
    # forward-only: a stale promoter cannot roll the counter back
    with pytest.raises(RegistryError):
        reg.set_version("alpha", "/v1", version=1)
    # and the flip is ledgered
    events = read_ledger(led)
    flips = [e for e in events if e.get("event") == REGISTRY_FLIP_EVENT]
    assert len(flips) == 1
    assert flips[0]["model"] == "alpha"
    assert flips[0]["version"] == 2 and flips[0]["previous_version"] == 1


def test_model_entry_device_slot_round_robin():
    e = ModelEntry(name="m", artifact_dir="/a", device_slots=("0", "1"))
    assert [e.device_slot(i) for i in range(4)] == ["0", "1", "0", "1"]
    assert ModelEntry(name="m", artifact_dir="/a").device_slot(0) is None


# -- weighted fair shedding --------------------------------------------------


def _shedder(**kw):
    from tensorflowdistributedlearning_tpu.serve.router import FairShedder

    return FairShedder({"alpha": 2.0, "beta": 1.0}, **kw)


def test_fair_shedder_idle_without_pressure():
    s = _shedder()
    for _ in range(50):
        s.note_demand("alpha")
        s.note_admitted("alpha")
        s.note_demand("beta")
        s.note_admitted("beta")
    # equal admitted shares exceed beta's fair share, but with no live
    # saturation signal nothing is shed — fair shedding is a pressure
    # policy, not a rate limiter
    assert not s.should_shed("beta", now=100.0)


def test_fair_shedder_sheds_over_share_model_under_pressure():
    s = _shedder()
    for _ in range(50):
        for m in ("alpha", "beta"):
            s.note_demand(m)
            s.note_admitted(m)
    s.note_saturation(now=100.0)
    # equal admitted shares (50/50) against 2:1 weights: beta (fair share
    # 33%) is over, alpha (fair share 67%) is under
    assert s.should_shed("beta", now=100.0)
    assert not s.should_shed("alpha", now=100.0)


def test_fair_shedder_single_model_never_shed():
    s = _shedder()
    for _ in range(50):
        s.note_demand("beta")
        s.note_admitted("beta")
    s.note_saturation(now=100.0)
    # no competing tenant in the window: 100% of the traffic is beta's fair
    # share by definition
    assert not s.should_shed("beta", now=100.0)


# -- per-model autoscaling under a chip budget -------------------------------


def _fleet_scaler(chip_budget=None, chips=None):
    from tensorflowdistributedlearning_tpu.serve import AutoscaleConfig
    from tensorflowdistributedlearning_tpu.serve.autoscale import (
        FleetAutoscaler,
    )

    clock = {"t": 0.0}
    cfg = dict(queue_high=2.0, queue_low=0.25, sustain=2, cooldown_s=0.0)
    scaler = FleetAutoscaler(
        {
            "alpha": AutoscaleConfig(min_replicas=1, max_replicas=4, **cfg),
            "beta": AutoscaleConfig(min_replicas=1, max_replicas=4, **cfg),
        },
        chip_budget=chip_budget,
        chips_per_replica=chips,
        clock=lambda: clock["t"],
    )
    return scaler, clock


def _pressure_snapshot(alpha_queue=0.0, beta_queue=0.0):
    return {
        "models": {
            "alpha": {"replicas": 1, "degraded": 0,
                      "queue_depth": alpha_queue, "shed": 0},
            "beta": {"replicas": 1, "degraded": 0,
                     "queue_depth": beta_queue, "shed": 0},
        }
    }


def test_fleet_autoscaler_decisions_are_model_tagged():
    scaler, clock = _fleet_scaler()
    decisions = []
    for _ in range(3):
        clock["t"] += 5.0
        decisions += scaler.evaluate(_pressure_snapshot(alpha_queue=50.0))
    ups = [d for d in decisions if d["action"] == "scale_up"]
    assert ups and all(d["model"] == "alpha" for d in ups)
    assert not any(d["model"] == "beta" for d in decisions)


def test_fleet_autoscaler_defers_over_budget_scale_up():
    # budget 2 chips, both models already hold 1 each: pressure on alpha
    # must produce an explicit budget_deferred decision, not a spawn order
    scaler, clock = _fleet_scaler(chip_budget=2)
    deferred = []
    for _ in range(4):
        clock["t"] += 5.0
        for d in scaler.evaluate(_pressure_snapshot(alpha_queue=50.0)):
            if d["action"] == "budget_deferred":
                deferred.append(d)
    assert deferred, "over-budget pressure vanished silently"
    d = deferred[0]
    assert d["model"] == "alpha"
    assert d["to_replicas"] == d["from_replicas"]
    assert d["chip_budget"] == 2
    assert d["chips_needed"] >= 1


def test_fleet_autoscaler_budget_within_headroom_scales():
    scaler, clock = _fleet_scaler(chip_budget=3)
    ups = []
    for _ in range(4):
        clock["t"] += 5.0
        for d in scaler.evaluate(_pressure_snapshot(alpha_queue=50.0)):
            if d["action"] == "scale_up":
                ups.append(d)
    assert ups and ups[0]["model"] == "alpha"


def test_fleet_autoscaler_unsatisfiable_budget_raises():
    with pytest.raises(ValueError, match="chip_budget"):
        _fleet_scaler(chip_budget=1)


# -- multi-model replica (one server, N engines) -----------------------------


@pytest.fixture(scope="module")
def serve_fns():
    import jax
    import jax.numpy as jnp

    def make(seed):
        w = jax.random.normal(
            jax.random.PRNGKey(seed), (FEATURES, CLASSES)
        ) * 0.3

        @jax.jit
        def fn(x):
            return {
                "probabilities": jax.nn.softmax(x @ w, axis=-1),
                "class": jnp.argmax(x @ w, axis=-1),
            }

        return fn

    return make(0), make(1)


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def two_model_server(serve_fns):
    from tensorflowdistributedlearning_tpu.obs.metrics import MetricsRegistry
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
    )

    fn_a, fn_b = serve_fns
    eng_a = InferenceEngine(fn_a, (FEATURES,), buckets=(1, 4))
    eng_a.warmup()
    eng_b = InferenceEngine(
        fn_b, (FEATURES,), buckets=(1, 4), registry=MetricsRegistry()
    )
    eng_b.warmup()
    server = ServingServer(
        eng_a,
        MicroBatcher(eng_a, max_wait_ms=1, max_queue=32),
        port=0,
        model="alpha",
        registry_version=3,
    )
    server.add_model(
        "beta", eng_b, MicroBatcher(eng_b, max_wait_ms=1, max_queue=32),
        version=7,
    )
    server.start()
    yield server
    server.shutdown()


def test_multi_model_server_routes_by_payload(two_model_server):
    server = two_model_server
    url = f"http://{server.host}:{server.port}"
    x = np.zeros((1, FEATURES), np.float32).tolist()
    for model in ("alpha", "beta"):
        status, body = _post(url + "/v1/predict",
                             {"model": model, "instances": x})
        assert status == 200 and body["n"] == 1
    # no hint routes to the primary; an unknown name is a structured 404
    status, _ = _post(url + "/v1/predict", {"instances": x})
    assert status == 200
    status, body = _post(url + "/v1/predict",
                         {"model": "gamma", "instances": x})
    assert status == 404
    assert body["error"]["code"] == "model_unknown"
    # per-tenant counters stayed isolated
    snap = server.models_snapshot()
    assert snap["alpha"]["completed"] == 2  # explicit + default-routed
    assert snap["beta"]["completed"] == 1
    assert snap["alpha"]["version"] == 3 and snap["beta"]["version"] == 7


def test_multi_model_healthz_and_prometheus_carry_identity(two_model_server):
    server = two_model_server
    url = f"http://{server.host}:{server.port}"
    health = _get(url + "/healthz")
    assert set(health["models"]) == {"alpha", "beta"}
    assert health["models"]["alpha"]["version"] == 3
    assert health["models"]["beta"]["version"] == 7
    req = urllib.request.Request(url + "/metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        text = resp.read().decode()
    assert 'model="alpha"' in text and 'model="beta"' in text
    assert 'version="7"' in text


def test_add_model_rejects_shared_metrics_registry(serve_fns):
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
    )

    fn_a, fn_b = serve_fns
    eng_a = InferenceEngine(fn_a, (FEATURES,), buckets=(1,))
    eng_b = InferenceEngine(
        fn_b, (FEATURES,), buckets=(1,), registry=eng_a.registry
    )
    server = ServingServer(
        eng_a, MicroBatcher(eng_a, max_wait_ms=1, max_queue=8), port=0
    )
    with pytest.raises(ValueError, match="MetricsRegistry"):
        server.add_model(
            "beta", eng_b, MicroBatcher(eng_b, max_wait_ms=1, max_queue=8)
        )


# -- pre-warm budget ---------------------------------------------------------


def test_warmup_budget_caps_warmed_ladder(serve_fns):
    from tensorflowdistributedlearning_tpu.serve import InferenceEngine

    fn, _ = serve_fns
    engine = InferenceEngine(fn, (FEATURES,), buckets=(1, 4, 16))
    engine.warmup(budget=2)
    assert engine.warmed_buckets == {1, 4}
    # traffic that escapes the warmed prefix compiles lazily ONCE, and the
    # cold hit is counted per bucket
    x = np.zeros((8, FEATURES), np.float32)
    engine.infer(x)
    assert engine.registry.counter("serve/cold_bucket_hits/16").value == 1
    engine.infer(x)
    assert engine.registry.counter("serve/cold_bucket_hits/16").value == 1


def test_warmup_full_ladder_by_default(serve_fns):
    from tensorflowdistributedlearning_tpu.serve import InferenceEngine

    fn, _ = serve_fns
    engine = InferenceEngine(fn, (FEATURES,), buckets=(1, 4))
    engine.warmup()
    assert engine.warmed_buckets == {1, 4}


# -- fleet plumbing: model-aware spawns and device placement -----------------


def _registry_manager(tmp_path, **entry_kw):
    from tensorflowdistributedlearning_tpu.serve import (
        FleetConfig,
        FleetManager,
    )

    wd = str(tmp_path)
    write_registry(wd, [
        ModelEntry(name="alpha", artifact_dir="/art/alpha", weight=2.0,
                   **entry_kw),
        ModelEntry(name="beta", artifact_dir="/art/beta"),
    ])
    cfg = FleetConfig(
        artifact_dir="/art/alpha", workdir=wd, buckets=(1, 4),
        registry=read_registry(wd),
    )
    return FleetManager(cfg)


def test_replica_argv_carries_model_identity(tmp_path):
    manager = _registry_manager(
        tmp_path, prewarm_budget=1, slo_p99_ms=80.0, buckets=(1,),
    )
    argv = manager._replica_argv(
        1, None, model="alpha", device_mask="0,1"
    )
    joined = " ".join(argv)
    assert "--artifact-dir /art/alpha" in joined
    assert "--model alpha" in joined
    assert "--model-version 1" in joined
    assert "--prewarm-buckets 1" in joined
    assert "--visible-devices 0,1" in joined
    assert "--slo-p99-ms 80.0" in joined
    # the entry's own ladder overrides the fleet default
    assert "--buckets 1 " in joined + " "
    # the other tenant spawns against its own artifact, no prewarm cap
    argv_b = " ".join(manager._replica_argv(2, None, model="beta"))
    assert "--artifact-dir /art/beta" in argv_b
    assert "--model beta" in argv_b
    assert "--prewarm-buckets" not in argv_b
    assert "--visible-devices" not in argv_b


def test_device_masks_round_robin_per_model(tmp_path):
    manager = _registry_manager(tmp_path, device_slots=("0,1", "2,3"))
    masks = [manager._draw_device_mask("alpha") for _ in range(3)]
    assert masks == ["0,1", "2,3", "0,1"]
    assert manager._draw_device_mask("beta") is None


# -- promotion scoping -------------------------------------------------------


def test_promotion_model_requires_registry(tmp_path):
    import types

    from tensorflowdistributedlearning_tpu.serve.promote import (
        PromotionController,
    )

    manager = types.SimpleNamespace(
        config=types.SimpleNamespace(registry=None, artifact_dir="/a")
    )
    controller = PromotionController(manager, router=None)
    with pytest.raises(ValueError, match="no registry"):
        controller.start("/candidate", model="alpha")


def test_promotion_on_multimodel_fleet_requires_model(tmp_path):
    import types

    from tensorflowdistributedlearning_tpu.serve.promote import (
        PromotionController,
    )

    wd = str(tmp_path)
    write_registry(wd, [ModelEntry(name="alpha", artifact_dir="/a"),
                        ModelEntry(name="beta", artifact_dir="/b")])
    manager = types.SimpleNamespace(
        config=types.SimpleNamespace(
            registry=read_registry(wd), artifact_dir="/a"
        )
    )
    controller = PromotionController(manager, router=None)
    with pytest.raises(ValueError, match="requires a model name"):
        controller.start("/candidate")


# -- telemetry: the mixed-fleet warning is tenant-aware ----------------------


def test_silent_mixed_fleet_is_multitenant_aware():
    from tensorflowdistributedlearning_tpu.obs.report import (
        silent_mixed_fleet,
    )

    # two artifacts, no models data, no promotion: the legacy warning
    assert silent_mixed_fleet(
        {"artifacts": {"f32:a": 1, "f32:b": 1}, "promotion_active": False}
    )
    # two artifacts BECAUSE two tenants, each on one version: by design
    assert not silent_mixed_fleet({
        "artifacts": {"f32:a": 1, "f32:b": 1},
        "promotion_active": False,
        "models": {"alpha": {"versions": {"1": 1}},
                   "beta": {"versions": {"1": 1}}},
    })
    # one tenant answering from two versions with no promotion in charge:
    # that IS the silent mix
    assert silent_mixed_fleet({
        "artifacts": {"f32:a": 1, "f32:b": 1},
        "promotion_active": False,
        "models": {"alpha": {"versions": {"1": 1, "2": 1}},
                   "beta": {"versions": {"1": 1}}},
    })
    assert not silent_mixed_fleet({
        "artifacts": {"f32:a": 1, "f32:b": 1},
        "promotion_active": True,
        "models": {"alpha": {"versions": {"1": 1, "2": 1}}},
    })


def test_report_renders_per_model_serve_and_router_lines(tmp_path):
    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    wd = str(tmp_path)
    tel = Telemetry(wd, run_info={"kind": "serve"})
    tel.event(
        "serve_window",
        requests=10, completed=9, rejected_queue_full=1,
        deadline_exceeded=0, errors=0, batches=5, batched_examples=9,
        models={
            "alpha": {"version": 3, "requests": 6, "completed": 6,
                      "queue_depth": 0,
                      "latency_ms": {"request": {"count": 6, "mean_ms": 4.0,
                                                 "p50_ms": 4.0, "p90_ms": 5.0,
                                                 "p99_ms": 6.0}}},
            "beta": {"version": 7, "requests": 4, "completed": 3,
                     "queue_depth": 0},
        },
    )
    tel.event(
        "router_window",
        requests=10, routed=10, retries=0, shed=2, no_replica=0,
        replica_failures=0,
        fleet={
            "status": "ok", "live": 2, "starting": 0, "draining": 0,
            "dead": 0, "queue_depth_total": 0,
            "models": {
                "alpha": {"replicas": 1, "requests": 6, "routed": 6,
                          "shed": 0, "fair_shed": 0, "worst_p99_ms": 6.0,
                          "versions": {"3": 1}, "weight": 2.0,
                          "queue_depth": 0, "degraded": 0},
                "beta": {"replicas": 1, "requests": 4, "routed": 4,
                         "shed": 2, "fair_shed": 2, "worst_p99_ms": 9.0,
                         "versions": {"7": 1}, "weight": 1.0,
                         "queue_depth": 0, "degraded": 0},
            },
        },
        fair_share={
            "pressured": True,
            "weights": {"alpha": 2.0, "beta": 1.0},
            "demand": {"alpha": 6, "beta": 6},
            "admitted_shares": {"alpha": 0.66, "beta": 0.34},
            "fair_shed": {"beta": 2},
        },
    )
    tel.close()
    rendered = report_workdir(wd)
    assert "model alpha v3" in rendered
    assert "model beta v7" in rendered
    assert "model alpha: 1 replica(s)" in rendered
    assert "(2 fair-shed)" in rendered
    assert "admitted shares UNDER PRESSURE" in rendered
    as_json = json.loads(report_workdir(wd, as_json=True))
    assert as_json["serve"]["models"]["alpha"]["version"] == 3
    assert (
        as_json["serve_fleet"]["router"]["models"]["beta"]["fair_shed"] == 2
    )


# -- the regression sentinel's multitenant gates -----------------------------


def test_sentinel_multitenant_gates():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from regression_sentinel import check_multitenant

    good = {
        "multitenant": {
            "slo_p99_ms": 750.0,
            "models": {
                "alpha": {"ok": 100, "errors_5xx": 0, "errors_4xx": 0,
                          "errors_conn": 0, "latency_ms": {"p99": 40.0}},
                "beta": {"ok": 90, "errors_5xx": 0, "errors_4xx": 0,
                         "errors_conn": 0, "latency_ms": {"p99": 45.0}},
            },
            "replicas": {
                "1": {"completed": 100, "recompiles_post_warmup": 0},
                "2": {"completed": 90, "recompiles_post_warmup": 0},
            },
            "saturation": {
                "shed_429_total": 50, "errors_5xx": 0,
                "per_model": {"alpha": {"ok": 60}, "beta": {"ok": 30}},
                "fair_weighted": True,
            },
        }
    }
    findings = check_multitenant(good)
    assert findings and all(f["ok"] for f in findings)

    bad = json.loads(json.dumps(good))
    bad["multitenant"]["models"]["beta"]["latency_ms"]["p99"] = 900.0
    bad["multitenant"]["replicas"]["1"]["recompiles_post_warmup"] = 3
    bad["multitenant"]["saturation"]["fair_weighted"] = False
    bad["multitenant"]["saturation"]["per_model"]["beta"]["ok"] = 0
    failed = {
        f["metric"] for f in check_multitenant(bad) if not f["ok"]
    }
    assert "models.beta.p99_ms" in failed
    assert "replica_post_warmup_recompiles" in failed
    assert "saturation.fair_weighted" in failed
    assert "saturation.beta.ok" in failed

    # the committed baseline must itself clear every gate
    committed = json.load(open(os.path.join(REPO, "BENCH_SERVE.json")))
    findings = check_multitenant(committed)
    assert findings, "BENCH_SERVE.json lost its multitenant section"
    assert all(f["ok"] for f in findings)


# -- subprocess end-to-end drills --------------------------------------------


def _export_identified_artifact(directory, seed, perturb=0.0):
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import quantize
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    w = jax.random.normal(
        jax.random.PRNGKey(seed), (FEATURES, CLASSES)
    ) * 0.5
    if perturb:
        w = w + perturb * jax.random.normal(
            jax.random.PRNGKey(seed + 100), w.shape
        )
    params = {"dense": {"kernel": w}}
    _, section = quantize.quantize_pytree(params, "float32")

    def serve(x):
        logits = x @ params["dense"]["kernel"]
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    serving_lib.export_serving_artifact(
        serve, (1, FEATURES), directory, quantization=section
    )
    return directory


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _spawn_registry_fleet(workdir, extra=()):
    proc = subprocess.Popen(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
         "serve-fleet", "--workdir", workdir,
         "--registry", registry_path(workdir),
         "--port", "0", "--replicas", "2", "--no-autoscale",
         "--window-secs", "2", "--buckets", "1", "4",
         "--poll-interval-s", "0.25", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_env(), text=True,
    )
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        line = proc.stdout.readline().strip()
        if line.startswith("{"):
            return proc, json.loads(line)
    proc.kill()
    raise RuntimeError("registry serve-fleet not ready")


def _stop_fleet(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(90)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(10)


class _ModelLoad:
    """Closed-loop client driving ONE tenant; latencies + non-200s kept."""

    def __init__(self, url, model, clients=1, delay_s=0.01):
        self.url = url
        self.model = model
        self.delay_s = delay_s
        self.ok = 0
        self.shed = 0
        self.errors = []
        self.latencies = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        rng = np.random.default_rng(3)
        self.x = rng.normal(0, 1, (1, FEATURES)).astype(np.float32)
        self.threads = [
            threading.Thread(target=self._run, daemon=True)
            for _ in range(clients)
        ]
        for t in self.threads:
            t.start()

    def _run(self):
        import http.client
        import urllib.parse

        parsed = urllib.parse.urlsplit(self.url)
        body = json.dumps(
            {"model": self.model, "instances": self.x.tolist()}
        )
        conn = None
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        parsed.hostname, parsed.port, timeout=30
                    )
                t0 = time.perf_counter()
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                dt = time.perf_counter() - t0
                with self._lock:
                    if resp.status == 200:
                        self.ok += 1
                        self.latencies.append(dt)
                    elif resp.status == 429:
                        self.shed += 1
                    else:
                        self.errors.append(resp.status)
            except (OSError, Exception) as e:  # noqa: BLE001
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = None
                with self._lock:
                    self.errors.append(f"conn:{type(e).__name__}")
            if self.delay_s:
                time.sleep(self.delay_s)

    def p99_ms(self):
        with self._lock:
            lat = list(self.latencies)
        if not lat:
            return None
        return float(np.percentile(np.asarray(lat) * 1000, 99))

    def stop(self):
        self._stop.set()
        for t in self.threads:
            t.join(10)


@pytest.mark.slow
def test_multitenant_drill_fair_shed_keeps_beta_slo(tmp_path):
    """The headline drill, part 1: two tenants behind one router with tiny
    per-replica queues. A saturating burst on alpha must be shed back at
    alpha (structured 429s, fair-share policy), while beta — light, steady,
    weight 1 — keeps answering inside its SLO band with zero errors."""
    alpha_art = _export_identified_artifact(str(tmp_path / "alpha"), seed=1)
    beta_art = _export_identified_artifact(str(tmp_path / "beta"), seed=2)
    workdir = str(tmp_path / "fleet")
    os.makedirs(workdir)
    slo_ms = 750.0
    write_registry(workdir, [
        ModelEntry(name="alpha", artifact_dir=alpha_art, weight=2.0,
                   slo_p99_ms=slo_ms),
        ModelEntry(name="beta", artifact_dir=beta_art, weight=1.0,
                   slo_p99_ms=slo_ms),
    ])
    proc, header = _spawn_registry_fleet(
        workdir, extra=("--queue-size", "4")
    )
    url = header["router"]
    assert set(header.get("models") or {}) == {"alpha", "beta"}
    beta = _ModelLoad(url, "beta", clients=1, delay_s=0.02)
    alpha = _ModelLoad(url, "alpha", clients=16, delay_s=0.0)
    try:
        time.sleep(6.0)
        alpha.stop()
        beta.stop()
        metrics = _get(url + "/metrics")
        models = (metrics.get("fleet") or {}).get("models") or {}
    finally:
        alpha.stop()
        beta.stop()
        _stop_fleet(proc)
    # the router routed both tenants and saw the saturation on alpha
    assert models.get("alpha", {}).get("requests", 0) > 0
    assert models.get("beta", {}).get("requests", 0) > 0
    assert alpha.ok > 0
    assert alpha.shed > 0, "saturating alpha was never shed"
    # beta rode through alpha's burst: zero errors, zero shed, p99 in band
    assert beta.errors == [], f"beta client-visible errors: {beta.errors[:5]}"
    assert beta.shed == 0, "light beta traffic was shed during alpha's burst"
    assert beta.ok > 20
    assert beta.p99_ms() is not None and beta.p99_ms() <= slo_ms


@pytest.mark.slow
def test_multitenant_drill_promote_flips_one_model(tmp_path):
    """The headline drill, part 2: ``promote --model alpha`` on a 2-tenant
    fleet runs the full admission -> canary/shadow -> rollout machinery
    against alpha only and completes as a registry version flip. Beta's
    replica never rolls, beta's clients never see an error, and beta's
    registry entry is untouched."""
    alpha_v1 = _export_identified_artifact(str(tmp_path / "a1"), seed=1)
    alpha_v2 = _export_identified_artifact(
        str(tmp_path / "a2"), seed=1, perturb=0.002
    )
    beta_art = _export_identified_artifact(str(tmp_path / "b1"), seed=2)
    workdir = str(tmp_path / "fleet")
    os.makedirs(workdir)
    write_registry(workdir, [
        ModelEntry(name="alpha", artifact_dir=alpha_v1, weight=1.0),
        ModelEntry(name="beta", artifact_dir=beta_art, weight=1.0),
    ])
    proc, header = _spawn_registry_fleet(workdir)
    url = header["router"]
    alpha = _ModelLoad(url, "alpha", clients=1, delay_s=0.005)
    beta = _ModelLoad(url, "beta", clients=1, delay_s=0.005)
    try:
        time.sleep(1.0)  # pre-promotion traffic on both tenants
        result = subprocess.run(
            [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
             "promote", "--workdir", workdir, "--candidate-dir", alpha_v2,
             "--model", "alpha",
             "--shadow-secs", "1.5", "--shadow-fraction", "1.0",
             "--shadow-min-requests", "5", "--observe-secs", "0.5",
             "--max-p99-ratio", "5.0", "--timeout", "420", "--json"],
            capture_output=True, text=True, env=_env(), timeout=600,
        )
        assert result.returncode == 0, (
            f"promote --model alpha failed: {result.stdout}\n{result.stderr}"
        )
        status = json.loads(result.stdout.strip().splitlines()[-1])
        assert status["state"] == "complete"
        assert status.get("model") == "alpha"
        alpha.stop()
        beta.stop()
    finally:
        alpha.stop()
        beta.stop()
        _stop_fleet(proc)
    # the flip landed in the registry document: alpha v2 on the candidate,
    # beta untouched at v1 on its own artifact
    reg = read_registry(workdir)
    assert reg.entry("alpha").version == 2
    assert reg.entry("alpha").artifact_dir == alpha_v2
    assert reg.entry("beta").version == 1
    assert reg.entry("beta").artifact_dir == beta_art
    # zero client-visible errors on the tenant that was NOT promoted (and
    # none on the promoted one either — that is the rollout contract)
    assert beta.errors == [], f"beta errors during alpha promotion: " \
                              f"{beta.errors[:10]}"
    assert alpha.errors == [], f"alpha errors during its promotion: " \
                               f"{alpha.errors[:10]}"
    assert beta.ok > 50
    # the ledger tells the scoped story: a registry_flip for alpha, and the
    # promotion events carry the model tag
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    events = read_ledger(workdir)
    flips = [e for e in events if e.get("event") == REGISTRY_FLIP_EVENT]
    assert len(flips) == 1 and flips[0]["model"] == "alpha"
    start = next(e for e in events if e.get("event") == "promotion_start")
    assert start["model"] == "alpha"
    complete = next(
        e for e in events if e.get("event") == "promotion_complete"
    )
    assert complete["model"] == "alpha" and complete["version"] == 2
    # beta's original replica survived the whole drill: every replica_drain
    # belongs to alpha's rollout
    spawns = {
        e["replica"]: e.get("model")
        for e in events if e.get("event") == "replica_spawn"
    }
    beta_ids = {rid for rid, m in spawns.items() if m == "beta"}
    drained = {
        e["replica"] for e in events if e.get("event") == "replica_drain"
    }
    assert beta_ids and not (beta_ids & drained)
