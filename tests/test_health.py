"""obs/health.py: online health monitors + Prometheus exposition.

The acceptance pins: an injected NaN loss (via the resilience/faults.py hook
pattern, ``nan-loss@N``) produces a structured ``health_alert`` ledger event
and honors warn-vs-abort; a forced p99 SLO breach alerts, renders in
``telemetry-report``, and degrades ``/healthz``; ``/metrics`` with a
Prometheus Accept header returns parseable exposition text."""

import json
import urllib.request

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs import health as health_lib
from tensorflowdistributedlearning_tpu.resilience import faults as faults_lib
from tensorflowdistributedlearning_tpu.serve import (
    InferenceEngine,
    MicroBatcher,
    ServingServer,
)

FEATURES = 4
CLASSES = 3


# -- monitor units -----------------------------------------------------------


def test_nan_guard_warn_abort_off():
    warn = health_lib.NanGuard("warn")
    assert warn.check(1, 0.5) is None
    alert = warn.check(2, float("nan"))
    assert alert["monitor"] == "nan_loss" and alert["severity"] == "warn"
    assert warn.check(3, float("inf"))["loss"] == "inf"
    abort = health_lib.NanGuard("abort")
    assert abort.check(1, float("nan"))["severity"] == "critical"
    off = health_lib.NanGuard("off")
    assert off.check(1, float("nan")) is None
    with pytest.raises(ValueError):
        health_lib.NanGuard("explode")


def test_loss_spike_detector_median_mad():
    det = health_lib.LossSpikeDetector(min_history=4, threshold=8.0)
    for step, loss in enumerate((1.0, 1.02, 0.98, 1.01, 0.99)):
        assert det.check(step, loss) is None
    alert = det.check(10, 9.0)
    assert alert["monitor"] == "loss_spike"
    assert alert["loss"] == 9.0 and 0.9 < alert["median"] < 1.1
    # a non-finite loss is the NaN guard's business, never a spike
    assert det.check(11, float("nan")) is None
    # back to normal: no alert
    assert det.check(12, 1.0) is None


def test_step_time_regression_transitions():
    det = health_lib.StepTimeRegressionDetector(baseline_windows=3, factor=1.5)
    for step, ms in enumerate((100.0, 102.0, 98.0)):
        assert det.check(step, ms) is None
    assert det.baseline_ms == 100.0
    # dirty windows never alert (compile/eval noise)
    assert det.check(10, 500.0, dirty=True) is None
    alert = det.check(11, 200.0)
    assert alert["monitor"] == "step_time" and not alert.get("resolved")
    # sustained regression: ONE alert, not a flood
    assert det.check(12, 210.0) is None
    resolved = det.check(13, 105.0)
    assert resolved["resolved"] is True
    assert det.check(14, 104.0) is None


def test_slo_tracker_breach_and_recovery():
    slo = health_lib.SloTracker(50.0, error_budget=0.01, min_requests=10)
    assert slo.healthy
    # idle window: too few requests, never degrades
    slo.observe(1.0)
    assert slo.evaluate() is None and slo.healthy
    # breached window: >1% of requests over 50ms
    for _ in range(20):
        slo.observe(0.2)
    alert = slo.evaluate()
    assert alert["monitor"] == "slo" and alert["severity"] == "critical"
    assert not slo.healthy and alert["violation_frac"] == 1.0
    # still breached: no repeat alert (state, not spam)
    for _ in range(20):
        slo.observe(0.2)
    assert slo.evaluate() is None and not slo.healthy
    # recovered window
    for _ in range(20):
        slo.observe(0.001)
    resolved = slo.evaluate()
    assert resolved["resolved"] is True and slo.healthy
    # deadline expiries count as violations without a latency sample
    for _ in range(20):
        slo.observe_violation()
    assert slo.evaluate()["window_violations"] == 20


def test_slo_tracker_memory_is_bounded_with_exact_counts():
    """A tracker nobody evaluates (idle windows, --window-secs 0) must not
    grow host memory; the budget math stays exact past the sample cap."""
    slo = health_lib.SloTracker(50.0, min_requests=10)
    n = 3 * health_lib.SloTracker.MAX_WINDOW_SAMPLES
    for _ in range(n):
        slo.observe(0.2)  # all over target
    assert len(slo._latencies) == health_lib.SloTracker.MAX_WINDOW_SAMPLES
    alert = slo.evaluate()
    assert alert["window_requests"] == n
    assert alert["window_violations"] == n
    assert alert["violation_frac"] == 1.0


# -- trainer-side integration (Telemetry.window_event) -----------------------


def _window(tel, step, loss, mean_ms=None):
    scalars = {"loss": loss}
    # feed a fake step-time via compute samples so fields carry step_time_ms
    if mean_ms is not None:
        tel.registry.histogram(f"span/{obs.SPAN_STEP}").record(mean_ms / 1000)
    tel.window_event(step, steps=1, scalars=scalars)


def test_nan_alert_written_and_warn_continues(tmp_path):
    workdir = str(tmp_path / "run")
    tel = obs.Telemetry(
        workdir, run_info={}, health=health_lib.HealthMonitor(nan_action="warn")
    )
    _window(tel, 1, 1.0)
    _window(tel, 2, float("nan"))
    _window(tel, 3, 1.0)  # warn: training goes on
    tel.close()
    events = obs.read_ledger(workdir)
    alerts = [e for e in events if e["event"] == "health_alert"]
    assert len(alerts) == 1
    assert alerts[0]["monitor"] == "nan_loss" and alerts[0]["step"] == 2
    assert alerts[0]["loss"] == "nan"
    # the window that carried the NaN was written BEFORE the alert
    kinds = [e["event"] for e in events]
    assert kinds.index("health_alert") > kinds.index("step_window")


def test_nan_abort_raises_after_ledgering(tmp_path):
    workdir = str(tmp_path / "run")
    tel = obs.Telemetry(
        workdir, run_info={},
        health=health_lib.HealthMonitor(nan_action="abort"),
    )
    _window(tel, 1, 1.0)
    with pytest.raises(health_lib.HealthAbortError):
        _window(tel, 2, float("nan"))
    tel.close()
    alerts = [
        e for e in obs.read_ledger(workdir) if e["event"] == "health_alert"
    ]
    assert alerts and alerts[0]["severity"] == "critical"
    assert alerts[0]["action"] == "abort"


def test_injected_nan_via_faults_hook(tmp_path):
    """The drill the satellite pins: nan-loss@2 poisons the 2nd observed
    window; the guard alerts even though the training loss stream is clean."""
    workdir = str(tmp_path / "run")
    tel = obs.Telemetry(
        workdir, run_info={}, health=health_lib.HealthMonitor(nan_action="warn")
    )
    faults_lib.install("nan-loss@2")
    try:
        _window(tel, 10, 1.0)
        _window(tel, 20, 1.0)  # poisoned
        _window(tel, 30, 1.0)
    finally:
        faults_lib.uninstall()
    tel.close()
    alerts = [
        e for e in obs.read_ledger(workdir) if e["event"] == "health_alert"
    ]
    assert len(alerts) == 1
    assert alerts[0]["monitor"] == "nan_loss" and alerts[0]["step"] == 20


def test_injected_nan_honors_abort(tmp_path):
    faults_lib.install("nan-loss@1")
    tel = obs.Telemetry(
        str(tmp_path / "run"), run_info={},
        health=health_lib.HealthMonitor(nan_action="abort"),
    )
    try:
        with pytest.raises(health_lib.HealthAbortError):
            _window(tel, 5, 0.7)
    finally:
        faults_lib.uninstall()
        tel.close()


def test_fit_run_with_injected_nan_alerts_and_reports(tmp_path):
    """End to end through the real trainer: a fit() run with nan-loss
    injected writes the alert and telemetry-report renders the health
    section."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    workdir = str(tmp_path / "fit_nan")
    trainer = ClassifierTrainer(
        workdir,
        None,
        ModelConfig(
            num_classes=4, input_shape=(16, 16), input_channels=3,
            n_blocks=(1, 1, 1), width_multiplier=0.125, output_stride=None,
        ),
        TrainConfig(
            train_log_every_steps=2, checkpoint_every_steps=8,
            eval_every_steps=8, nan_guard="warn",
        ),
    )
    faults_lib.install("nan-loss@2")
    try:
        trainer.fit(batch_size=8, steps=8, eval_every_steps=8)
    finally:
        faults_lib.uninstall()
    alerts = [
        e for e in obs.read_ledger(workdir) if e["event"] == "health_alert"
    ]
    assert len(alerts) == 1 and alerts[0]["monitor"] == "nan_loss"
    rendered = report_workdir(workdir)
    assert "health" in rendered and "nan_loss" in rendered


def test_fit_run_nan_abort_stops_with_ledgered_story(tmp_path):
    """nan_guard=abort through the real trainer: the run stops with
    HealthAbortError, the alert precedes the exit in the ledger, and the
    close path records the run as interrupted."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    workdir = str(tmp_path / "fit_abort")
    trainer = ClassifierTrainer(
        workdir,
        None,
        ModelConfig(
            num_classes=4, input_shape=(16, 16), input_channels=3,
            n_blocks=(1, 1, 1), width_multiplier=0.125, output_stride=None,
        ),
        TrainConfig(
            train_log_every_steps=2, checkpoint_every_steps=8,
            eval_every_steps=8, nan_guard="abort",
        ),
    )
    faults_lib.install("nan-loss@1")
    try:
        with pytest.raises(health_lib.HealthAbortError):
            trainer.fit(batch_size=8, steps=8, eval_every_steps=8)
    finally:
        faults_lib.uninstall()
    events = obs.read_ledger(workdir)
    kinds = [e["event"] for e in events]
    assert "health_alert" in kinds
    run_end = [e for e in events if e["event"] == "run_end"][-1]
    assert run_end.get("interrupted") is True


def test_health_monitor_reset_clears_fold_history():
    """The K-fold boundary contract: a converged phase's low-loss history
    must not flag the next phase's fresh loss as a spike."""
    monitor = health_lib.HealthMonitor(nan_action="warn")
    for step in range(12):
        assert monitor.spike.check(step, 0.1) is None
    monitor.reset()
    # fresh fold starts high: no history yet, so no spurious spike
    assert monitor.spike.check(100, 2.5) is None


def test_health_monitor_off_config():
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    assert (
        health_lib.HealthMonitor.from_train_config(
            TrainConfig(health_monitors=False)
        )
        is None
    )
    monitor = health_lib.HealthMonitor.from_train_config(
        TrainConfig(nan_guard="abort")
    )
    assert monitor.nan_guard.action == "abort"
    with pytest.raises(ValueError, match="nan_guard"):
        TrainConfig(nan_guard="bogus")
    with pytest.raises(ValueError, match="trace_sample_rate"):
        TrainConfig(trace_sample_rate=2.0)


# -- serving SLO + /healthz + Prometheus -------------------------------------


@pytest.fixture(scope="module")
def serve_fn():
    import jax
    import jax.numpy as jnp

    w = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.3

    @jax.jit
    def fn(x):
        logits = x @ w
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return fn


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _post_predict(url, x):
    req = urllib.request.Request(
        url + "/v1/predict",
        data=json.dumps({"instances": x.tolist()}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


QUANT = {"dtype": "bfloat16", "source_fingerprint": "cafe" * 16}


@pytest.fixture
def slo_server(serve_fn, tmp_path):
    workdir = str(tmp_path / "serve_slo")
    tel = obs.Telemetry(workdir, run_info={"kind": "serve"})
    engine = InferenceEngine(
        serve_fn, (FEATURES,), buckets=(1, 4),
        registry=tel.registry, quantization=QUANT,
    )
    engine.warmup(telemetry=tel)
    batcher = MicroBatcher(engine, max_wait_ms=1, max_queue=32)
    # an impossible p99 target: every answered request violates it
    server = ServingServer(
        engine, batcher, port=0, telemetry=tel, window_secs=0,
        slo_p99_ms=0.000001,
    ).start()
    yield server, workdir
    server.shutdown()


def test_slo_breach_degrades_healthz_and_ledgers(slo_server):
    server, workdir = slo_server
    x = np.ones((1, FEATURES), np.float32)

    # healthy replica first: healthz ok, artifact identity present
    status, _, body = _get(server.url + "/healthz")
    health = json.loads(body)
    assert status == 200 and health["ok"] and health["status"] == "ok"
    assert health["artifact"] == {
        "dtype": "bfloat16",
        "source_fingerprint": QUANT["source_fingerprint"],
    }
    assert health["uptime_s"] >= 0

    # force the breach: >= min_requests answered requests, all over target
    for _ in range(25):
        _post_predict(server.url, x)
    window = server.emit_window()
    assert window["slo"]["healthy"] is False

    status, _, body = _get(server.url + "/healthz")
    health = json.loads(body)
    # alive (200 — the router reads status, draining is the 503 case) but
    # degraded: the drain signal
    assert status == 200
    assert health["ok"] is False and health["status"] == "degraded"

    events = obs.read_ledger(workdir)
    alerts = [e for e in events if e["event"] == "health_alert"]
    assert len(alerts) == 1
    assert alerts[0]["monitor"] == "slo"
    assert alerts[0]["severity"] == "critical"
    assert alerts[0]["violation_frac"] == 1.0

    # the serve window carries end-to-end request latency now
    windows = [e for e in events if e["event"] == "serve_window"]
    assert "request" in windows[-1]["latency_ms"]

    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    rendered = report_workdir(workdir)
    assert "BREACHED" in rendered and "health" in rendered


def _parse_prometheus(text):
    """Minimal exposition-format validation: every non-comment line is
    `name{labels} value` with a float value; returns {name: value}."""
    import re

    metrics = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (TYPE|HELP) \S+", line), line
            continue
        m = re.match(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? ([0-9.eE+-]+|NaN|[+-]Inf)$',
            line,
        )
        assert m, f"unparseable exposition line: {line!r}"
        metrics[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return metrics


def test_metrics_prometheus_content_negotiation(slo_server):
    server, _ = slo_server
    x = np.ones((2, FEATURES), np.float32)
    _post_predict(server.url, x)

    # default stays JSON (no Accept preference)
    status, headers, body = _get(server.url + "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("application/json")
    snapshot = json.loads(body)
    assert "registry" in snapshot and "slo" in snapshot

    # Prometheus via Accept header
    status, headers, body = _get(
        server.url + "/metrics", headers={"Accept": "text/plain"}
    )
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
    metrics = _parse_prometheus(body.decode())
    assert metrics["tfdl_serve_requests_total"] >= 1
    assert metrics["tfdl_serve_completed_total"] >= 1
    assert "tfdl_serve_queue_depth" in metrics
    assert metrics["tfdl_serve_draining"] == 0.0
    # summary series for the request latency histogram
    assert metrics["tfdl_serve_request_seconds_count"] >= 1
    assert metrics["tfdl_serve_request_seconds_sum"] > 0
    assert any(k.startswith('tfdl_serve_request_seconds{quantile="0.99"}')
               or k == 'tfdl_serve_request_seconds{quantile="0.99"}'
               for k in metrics)

    # ... and via ?format= for scrape configs that can't set headers
    status, headers, _ = _get(server.url + "/metrics?format=prometheus")
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")


def test_render_prometheus_counts_survive_window_drain(serve_fn):
    """Scrape-vs-ledger-window independence: draining a histogram for the
    serve window must not reset the exposition's monotonic _count/_sum."""
    reg = obs.MetricsRegistry()
    h = reg.histogram("serve/compute")
    for _ in range(5):
        h.record(0.01)
    h.drain()  # the ledger window took the samples
    h.record(0.01)
    metrics = _parse_prometheus(reg.render_prometheus())
    assert metrics["tfdl_serve_compute_seconds_count"] == 6.0
    assert abs(metrics["tfdl_serve_compute_seconds_sum"] - 0.06) < 1e-9
