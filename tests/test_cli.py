"""CLI driver tests (the reference's notebook flows as commands, SURVEY §2.1 C13)."""

import json

import pytest

from tensorflowdistributedlearning_tpu.cli import build_parser, main


def test_parser_requires_command():
    with pytest.raises(SystemExit):
        build_parser().parse_args([])


def test_parser_train_defaults():
    args = build_parser().parse_args(
        ["train", "--data-dir", "d", "--model-dir", "m"]
    )
    assert args.batch_size == 64
    assert args.steps == 10_000
    assert args.n_fold == 5
    assert tuple(args.input_shape) == (101, 101)


def test_smoke_command_trains(capsys):
    rc = main(["smoke", "--steps", "2", "--batch-size", "8"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 2
    assert out["devices"] >= 1
    assert out["last_loss"] == pytest.approx(out["last_loss"])  # finite


def test_train_command_missing_data(tmp_path, capsys):
    rc = main(
        [
            "train",
            "--data-dir",
            str(tmp_path),
            "--model-dir",
            str(tmp_path / "m"),
            "--steps",
            "1",
        ]
    )
    assert rc == 1
