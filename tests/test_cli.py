"""CLI driver tests (the reference's notebook flows as commands, SURVEY §2.1 C13)."""

import json

import pytest

from tensorflowdistributedlearning_tpu.cli import build_parser, main


def test_parser_requires_command():
    with pytest.raises(SystemExit):
        build_parser().parse_args([])


def test_parser_train_defaults():
    args = build_parser().parse_args(
        ["train", "--data-dir", "d", "--model-dir", "m"]
    )
    assert args.batch_size == 64
    assert args.steps == 10_000
    assert args.n_fold == 5
    assert tuple(args.input_shape) == (101, 101)


def test_smoke_command_trains(capsys):
    rc = main(["smoke", "--steps", "2", "--batch-size", "8"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 2
    assert out["devices"] >= 1
    assert out["last_loss"] == pytest.approx(out["last_loss"])  # finite


def test_train_command_missing_data(tmp_path, capsys):
    rc = main(
        [
            "train",
            "--data-dir",
            str(tmp_path),
            "--model-dir",
            str(tmp_path / "m"),
            "--steps",
            "1",
        ]
    )
    assert rc == 1


def test_doctor_healthy_imagefolder(tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.data import imagefolder

    root = str(tmp_path / "data")
    imagefolder.write_synthetic_imagefolder(
        root + "/train", 3, 4, (16, 16), channels=3
    )
    rc = main(["doctor", "--data-dir", root, "--batch-size", "16"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["ok"]
    assert report["data"]["layout"] == "imagefolder"
    assert report["data"]["train"] == {"examples": 12, "classes": 3}
    assert report["backend"]["n_devices"] == 8
    assert report["batch"]["per_shard"] == 2


def test_doctor_reports_problems(tmp_path, capsys):
    rc = main(
        ["doctor", "--data-dir", str(tmp_path / "nope"), "--batch-size", "17"]
    )
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and not report["ok"]
    assert any("not divisible" in p for p in report["problems"])
    assert any("does not exist" in p for p in report["problems"])


def test_doctor_detects_corrupt_shard(tmp_path, capsys):
    import numpy as np

    from tensorflowdistributedlearning_tpu.data import records as rec

    root = str(tmp_path / "recs")
    rng = np.random.default_rng(0)
    rec.write_classification_shards(
        root,
        list(rng.integers(0, 255, (6, 8, 8, 3), dtype=np.uint8)),
        [0, 1, 2, 0, 1, 2],
        shards=2,
        prefix="train",
    )
    shard = sorted(
        p for p in __import__("os").listdir(root) if p.startswith("train-")
    )[0]
    path = root + "/" + shard
    with open(path, "r+b") as f:  # truncate mid-record
        f.truncate(max(f.seek(0, 2) - 7, 1))
    rc = main(["doctor", "--data-dir", root])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and not report["ok"]
    assert any("corrupt" in p for p in report["problems"])
