"""Worker for tests/test_multiprocess.py: one rank of a REAL 2-process
jax.distributed training step over gloo CPU collectives.

Runs the production multi-host path end to end — ``multihost.initialize`` with
explicit coordinator args, per-process batch math, ``global_shard_batch``
assembly from process-local rows, and one collective-bearing SPMD train step —
then prints ``RESULT <loss> <step>`` for the parent to compare across ranks and
against the single-process oracle."""

import os
import sys

# every strategy the "both" mode runs — the parent's completeness check
# (tests/test_multiprocess.py:_run_workers) derives its expectation from this
# tuple so adding a strategy here is automatically enforced there
ALL_STRATEGIES = ("dp", "tp", "sp", "ep", "pp", "3ax", "tpsp", "zero")


def main() -> int:
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "dp"
    devices_per_proc = 4
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # noqa: BLE001 — parent skips on this exact marker
        print("no gloo:", e, flush=True)
        return 3

    from tensorflowdistributedlearning_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=rank,
    )
    assert jax.process_count() == nproc, jax.process_count()

    import numpy as np

    from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    def run(strategy: str):
        # init always uses a twin that applies OUTSIDE shard_map (plain conv /
        # dense MoE / plain ViT); identical param trees let the values drop
        # into the collective twin, whose apply_fn is swapped in below. The
        # pp strategy builds its own (ViT) state in its branch.
        raw_state = None
        if strategy != "pp":
            raw_state = create_train_state(
                tiny_model(moe=(strategy in ("ep", "3ax"))),
                step_lib.make_optimizer(TrainConfig(lr=0.01)),
                jax.random.PRNGKey(0),
                np.zeros((1, 8, 8, 3), np.float32),
            )
        if strategy in ("sp", "tpsp"):
            raw_state = raw_state.replace(
                apply_fn=tiny_model(spatial=True).apply
            )
        elif strategy == "ep":
            raw_state = raw_state.replace(
                apply_fn=tiny_model(moe=True, ep=True).apply
            )
        elif strategy == "3ax":
            raw_state = raw_state.replace(
                apply_fn=tiny_model(spatial=True, moe=True, ep=True).apply
            )
        if strategy == "tp":
            # multi-host TENSOR parallelism: (batch=4, model=2) global mesh —
            # model-axis groups are intra-process (make_mesh requires it), the
            # BATCH axis spans the processes; params and optimizer are sharded
            # over the model axis and assembled from per-process shards
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            mesh = mesh_lib.make_mesh(None, model_parallel=2)
            state = tp_lib.shard_state_tensor_parallel(raw_state, mesh)
            train_step = tp_lib.make_train_step_gspmd(
                mesh, step_lib.ClassificationTask(), donate=False
            )
        elif strategy == "sp":
            # multi-host SPATIAL parallelism: (batch=4, 1, sequence=2) global
            # mesh — sequence groups intra-process, halo-exchange convs run
            # over gloo collectives; images are additionally H-sharded
            mesh = mesh_lib.make_mesh(None, sequence_parallel=2)
            state = mesh_lib.replicate(raw_state, mesh)
            train_step = step_lib.make_train_step(
                mesh, step_lib.ClassificationTask(), donate=False, spatial=True
            )
        elif strategy == "ep":
            # multi-host EXPERT parallelism: (batch=4, model=2) global mesh —
            # one expert per model shard (intra-process groups), the top-1
            # all-to-all dispatch + load-balancing aux loss running with the
            # batch axis spanning both processes
            mesh = mesh_lib.make_mesh(None, model_parallel=2)
            state = mesh_lib.replicate(raw_state, mesh)
            train_step = step_lib.make_train_step(
                mesh, step_lib.ClassificationTask(), donate=False
            )
        elif strategy == "zero":
            # multi-host ZeRO-style weight-update sharding
            # (arXiv:2004.13336): optimizer moments shard 1/dp over the
            # BATCH axis, which SPANS the two processes — the update's
            # cross-replica gather rides gloo; params stay replicated
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            mesh = mesh_lib.make_mesh(None)  # pure DP global mesh
            state = tp_lib.shard_state_weight_update(raw_state, mesh)
            train_step = tp_lib.make_train_step_gspmd(
                mesh, step_lib.ClassificationTask(), donate=False
            )
        elif strategy == "3ax":
            # THREE-axis composition dp x ep x sp: the full (batch=2, model=2,
            # sequence=2) global mesh across both processes — halo-exchange
            # convs over the sequence axis, MoE all-to-all over the model
            # axis, gradient mean over the batch axis, all in ONE shard_map
            # step (real pods run 3-axis layouts; pairwise proofs alone don't
            # cover the interaction)
            mesh = mesh_lib.make_mesh(
                None, model_parallel=2, sequence_parallel=2
            )
            state = mesh_lib.replicate(raw_state, mesh)
            train_step = step_lib.make_train_step(
                mesh, step_lib.ClassificationTask(), donate=False, spatial=True
            )
        elif strategy == "tpsp":
            # THREE-axis dp x tp x sp via shard_map's HYBRID mode: the
            # (batch=2, model=2, sequence=2) global mesh with (batch,
            # sequence) manual — halo-exchange convs + gradient mean as
            # explicit collectives — while the model axis stays auto: params
            # channel-shard over it (shard_state_tensor_parallel) and the
            # SPMD partitioner derives the tensor-parallel all-reduces
            # INSIDE each manual shard. The composition the pairwise dp x tp
            # (GSPMD) and dp x sp (shard_map) proofs could not reach, since
            # the two execution strategies exclude each other whole-step.
            from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib

            mesh = mesh_lib.make_mesh(
                None, model_parallel=2, sequence_parallel=2
            )
            state = tp_lib.shard_state_tensor_parallel(raw_state, mesh)
            train_step = step_lib.make_train_step(
                mesh,
                step_lib.ClassificationTask(),
                donate=False,
                spatial=True,
                auto_model=True,
            )
        elif strategy == "pp":
            # multi-host PIPELINE parallelism: (batch=4, model=2) global mesh —
            # a tiny ViT's 2 blocks run as 2 GPipe stages (intra-process
            # model-axis groups), microbatches ticking over ppermute while the
            # batch axis spans both processes
            from tensorflowdistributedlearning_tpu.models import build_model
            from tensorflowdistributedlearning_tpu.train import (
                pipeline_step as pp_step,
            )

            cfg = tiny_vit_cfg()
            raw_state = create_train_state(
                build_model(cfg),
                step_lib.make_optimizer(TrainConfig(lr=0.01)),
                jax.random.PRNGKey(0),
                np.zeros((1, 8, 8, 3), np.float32),
            )
            mesh = mesh_lib.make_mesh(None, model_parallel=2)
            state = mesh_lib.replicate(raw_state, mesh)
            train_step = pp_step.make_train_step_pipeline(
                mesh,
                step_lib.ClassificationTask(),
                cfg,
                microbatches=2,
                donate=False,
            )
        else:
            mesh = mesh_lib.make_mesh(None)  # all 8 global devices, pure DP
            state = mesh_lib.replicate(raw_state, mesh)
            train_step = step_lib.make_train_step(
                mesh, step_lib.ClassificationTask(), donate=False
            )

        global_batch = 16
        local_bs = multihost.per_process_batch_size(global_batch)
        assert local_bs == global_batch // nproc
        # deterministic global batch; THIS process contributes its local rows
        batch = make_global_batch(global_batch)
        rows = multihost.process_local_rows(global_batch, mesh)
        local = {k: v[rows] for k, v in batch.items()}
        sharded = multihost.global_shard_batch(
            local, mesh, spatial=(strategy in ("sp", "3ax", "tpsp"))
        )

        new_state, metrics = train_step(state, sharded)
        loss = step_lib.compute_metrics(jax.device_get(metrics))["loss"]
        print(
            f"RESULT_{strategy.upper()} {loss:.8f} "
            f"{int(jax.device_get(new_state.step))}",
            flush=True,
        )

    # "both" amortizes the expensive part (process spawn + jax.distributed
    # init, ~15 s per 2-process pair) across ALL strategies — collectives run
    # in the same jax.distributed session either way
    for strategy in ALL_STRATEGIES if mode == "both" else (mode,):
        run(strategy)
    return 0


def tiny_vit_cfg():
    """Tiny ViT for the pipeline strategy: 2 blocks -> 2 GPipe stages."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig

    return ModelConfig(
        backbone="vit",
        num_classes=4,
        input_shape=(8, 8),
        input_channels=3,
        patch_size=4,
        embed_dim=16,
        vit_layers=2,
        num_heads=2,
        output_stride=None,
    )


def tiny_model(spatial: bool = False, moe: bool = False, ep: bool = False):
    """Plain model, or a collective twin with the IDENTICAL param tree
    (layers share names and init fns, so the simple twin's init values drop
    straight into the sharded apply — the checkpoint-compatibility contract).

    ``spatial``: SpatialConv + sequence-pmean'd pooling (apply only inside
    shard_map — halo exchange binds the sequence axis).
    ``moe``: the production Switch-style MoE layer (models/vit.py:MoEMlp, 2
    experts) on the pooled features; ``ep=True`` runs its all-to-all
    expert-parallel path over the model axis (apply only inside shard_map)."""
    import flax.linen as nn

    from tensorflowdistributedlearning_tpu.models.layers import (
        SpatialConv,
        conv_kernel_init,
    )
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        MODEL_AXIS,
        SEQUENCE_AXIS,
    )
    from tensorflowdistributedlearning_tpu.parallel.spatial import (
        spatial_global_mean,
    )

    class Tiny(nn.Module):
        spatial: bool = False
        moe: bool = False
        ep: bool = False

        @nn.compact
        def __call__(self, x, train=False):
            if self.spatial:
                x = SpatialConv(
                    8, kernel_size=3, axis_name=SEQUENCE_AXIS, name="conv"
                )(x)
            else:
                x = nn.Conv(
                    8,
                    (3, 3),
                    padding="SAME",
                    kernel_init=conv_kernel_init,
                    name="conv",
                )(x)
            x = nn.relu(x)
            if self.spatial:
                x = spatial_global_mean(x, axis_name=SEQUENCE_AXIS)
            else:
                x = x.mean(axis=(1, 2))
            if self.moe:
                from tensorflowdistributedlearning_tpu.models.vit import MoEMlp

                x = MoEMlp(
                    embed_dim=8,
                    mlp_dim=8,
                    n_experts=2,
                    expert_axis_name=MODEL_AXIS if self.ep else None,
                    name="moe",
                )(x[:, None, :])[:, 0, :]
            return nn.Dense(4, name="head")(x)

    return Tiny(spatial=spatial, moe=moe, ep=ep)


def make_global_batch(n: int):
    import numpy as np

    rng = np.random.default_rng(7)
    return {
        "images": rng.normal(0, 1, (n, 8, 8, 3)).astype(np.float32),
        "labels": rng.integers(0, 4, n).astype(np.int32),
    }


if __name__ == "__main__":
    raise SystemExit(main())
