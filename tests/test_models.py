"""Model-shape and end-point tests — the golden-shape unit layer that would have caught
the reference's dead Xception (SURVEY §2.4.8-10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models import (
    ResNetBackbone,
    ResNetClassifier,
    ResNetSegmentation,
    SplitSeparableConv2D,
    Xception41,
    build_model,
    subsample,
    upsample,
)
from tensorflowdistributedlearning_tpu.utils import count_params


def init_and_apply(model, x, train=False):
    variables = model.init(jax.random.key(0), x, train=False)
    if train:
        out, _ = model.apply(
            variables, x, train=True, mutable=["batch_stats"],
            rngs={"dropout": jax.random.key(1)},
        )
        return variables, out
    return variables, model.apply(variables, x, train=False)


def abstract_init_and_apply(model, x):
    """Shape-level twin of ``init_and_apply``: traces init+apply under
    ``jax.eval_shape`` — full param trees and output ShapeDtypeStructs with
    identical .shape/.dtype assertions, but no XLA compile and no compute
    (shape-parity tests on the full-width reference configs would otherwise
    dominate suite wall time)."""

    def both(key, inp):
        variables = model.init(key, inp, train=False)
        return variables, model.apply(variables, inp, train=False)

    return jax.eval_shape(both, jax.random.key(0), x)


def test_upsample_shape():
    x = jnp.ones((2, 13, 13, 8))
    assert upsample(x, (26, 26)).shape == (2, 26, 26, 8)
    assert upsample(x, (101, 101)).shape == (2, 101, 101, 8)


def test_subsample():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    out = subsample(x, 2)
    assert out.shape == (1, 2, 2, 1)
    np.testing.assert_array_equal(np.asarray(out)[0, :, :, 0], [[0, 2], [8, 10]])


def test_split_separable_conv_params_and_shape():
    model = SplitSeparableConv2D(16, 3, rate=2)
    x = jnp.ones((1, 8, 8, 4))
    variables, out = init_and_apply(model, x)
    assert out.shape == (1, 8, 8, 16)
    # depthwise kernel is per-channel: [3,3,1,4]; pointwise [1,1,4,16]
    assert variables["params"]["depthwise"]["kernel"].shape == (3, 3, 1, 4)
    assert variables["params"]["pointwise"]["kernel"].shape == (1, 1, 4, 16)


def test_backbone_endpoint_shapes_output_stride_8():
    """101x101 input at output_stride 8: root 26x26, block1 13x13 (stride-2 last unit),
    block2-4 stay 13x13 atrous; the decoder skip is 26x26 — the resolution the reference
    hard-coded as (26, 26) (reference: core/resnet.py:474)."""
    cfg = ModelConfig()
    model = ResNetBackbone(cfg)
    x = jnp.ones((1, 101, 101, 2))
    _, eps = abstract_init_and_apply(model, x)
    assert eps["root"].shape == (1, 26, 26, 128)
    assert eps["block1_unit1_residual"].shape == (1, 26, 26, 512)
    assert eps["block1"].shape == (1, 13, 13, 512)
    assert eps["block2"].shape == (1, 13, 13, 1024)
    assert eps["block3"].shape == (1, 13, 13, 2048)
    assert eps["block4"].shape == (1, 13, 13, 1024)


def test_backbone_no_output_stride_is_stride_32():
    cfg = ModelConfig(output_stride=None, input_shape=(64, 64), input_channels=3)
    model = ResNetBackbone(cfg)
    x = jnp.ones((1, 64, 64, 3))
    _, eps = abstract_init_and_apply(model, x)
    assert eps["features"].shape == (1, 2, 2, 1024)


def test_backbone_invalid_output_stride_raises():
    cfg = ModelConfig(output_stride=6)
    with pytest.raises(ValueError):
        ResNetBackbone(cfg).init(jax.random.key(0), jnp.ones((1, 32, 32, 2)), train=False)


def test_segmentation_logits_shape_and_dtype():
    cfg = ModelConfig()
    model = ResNetSegmentation(cfg)
    x = jnp.ones((1, 101, 101, 2))
    variables, logits = abstract_init_and_apply(model, x)
    assert logits.shape == (1, 101, 101, 1)
    assert logits.dtype == jnp.float32
    assert count_params(variables["params"]) > 1_000_000


def test_segmentation_other_input_size():
    """The (26,26) hard-coding is gone: any input size works (SURVEY §2.4.7)."""
    cfg = ModelConfig(input_shape=(128, 128))
    model = ResNetSegmentation(cfg)
    x = jnp.ones((1, 128, 128, 2))
    _, logits = abstract_init_and_apply(model, x)
    assert logits.shape == (1, 128, 128, 1)


def test_segmentation_basic_block():
    cfg = ModelConfig(block_type="basic_block", n_blocks=(2, 2, 2))
    model = ResNetSegmentation(cfg)
    x = jnp.ones((1, 101, 101, 2))
    _, logits = abstract_init_and_apply(model, x)
    assert logits.shape == (1, 101, 101, 1)


def test_segmentation_train_mode_updates_batch_stats():
    cfg = ModelConfig(n_blocks=(1, 1, 1))
    model = ResNetSegmentation(cfg)
    x = jnp.ones((2, 101, 101, 2))
    variables = model.init(jax.random.key(0), x, train=False)
    out, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = variables["batch_stats"]
    after = mutated["batch_stats"]
    changed = jax.tree_util.tree_reduce(
        lambda acc, pair: acc or bool(np.any(pair)),
        jax.tree.map(lambda a, b: np.any(np.asarray(a) != np.asarray(b)), before, after),
        False,
    )
    assert changed


def test_bfloat16_compute_keeps_float32_params_and_logits():
    cfg = ModelConfig(n_blocks=(1, 1, 1), dtype="bfloat16")
    model = ResNetSegmentation(cfg)
    x = jnp.ones((1, 101, 101, 2))
    variables, logits = init_and_apply(model, x)
    assert logits.dtype == jnp.float32
    leaf = variables["params"]["backbone"]["conv1_1"]["conv"]["kernel"]
    assert leaf.dtype == jnp.float32


def test_classifier_logits():
    cfg = ModelConfig(num_classes=10, input_shape=(64, 64), input_channels=3)
    model = ResNetClassifier(cfg)
    x = jnp.ones((2, 64, 64, 3))
    _, logits = abstract_init_and_apply(model, x)
    assert logits.shape == (2, 10)


def test_classic_layout_block_specs():
    from tensorflowdistributedlearning_tpu.models.resnet import classic_block_specs

    specs = classic_block_specs((3, 4, 6, 3))
    assert [s.name for s in specs] == ["block1", "block2", "block3", "block4"]
    assert [len(s.units) for s in specs] == [3, 4, 6, 3]
    # standard bottleneck ladder 64/128/256/512, outputs x4
    assert [s.units[0].depth_bottleneck for s in specs] == [64, 128, 256, 512]
    assert [s.units[0].depth for s in specs] == [256, 512, 1024, 2048]
    # v2-beta convention: stride-2 unit LAST; final stage unstrided (stride 32
    # overall with the root's 4)
    for spec, last_stride in zip(specs, (2, 2, 2, 1)):
        assert [u.stride for u in spec.units[:-1]] == [1] * (len(spec.units) - 1)
        assert spec.units[-1].stride == last_stride
    with pytest.raises(ValueError, match="length 4"):
        classic_block_specs((3, 4, 6))


def test_classic_classifier_shapes_and_params():
    """block_layout='classic' is the published 25.6M-param ResNet-50: standard
    stage widths, stride-32 features, ~25-26M params at 1000 classes (the
    reference family's wide layout is 40.9M)."""
    cfg = ModelConfig(
        num_classes=10,
        input_shape=(64, 64),
        input_channels=3,
        n_blocks=(3, 4, 6, 3),
        block_layout="classic",
        output_stride=None,
    )
    model = ResNetClassifier(cfg)
    x = jnp.ones((2, 64, 64, 3))
    _, logits = init_and_apply(model, x)
    assert logits.shape == (2, 10)

    # full ImageNet-config param count via eval_shape (no real compute)
    inet = ModelConfig(
        num_classes=1000,
        input_shape=(224, 224),
        input_channels=3,
        n_blocks=(3, 4, 6, 3),
        block_layout="classic",
        output_stride=None,
    )
    variables, _ = abstract_init_and_apply(
        build_model(inet), jnp.zeros((1, 224, 224, 3))
    )
    assert 24e6 < count_params(variables["params"]) < 27e6


def test_classic_layout_validation():
    with pytest.raises(ValueError, match="length 4"):
        ModelConfig(block_layout="classic", n_blocks=(3, 4, 6), num_classes=10)
    with pytest.raises(ValueError, match="resnet"):
        ModelConfig(
            backbone="vit", block_layout="classic", n_blocks=(3, 4, 6, 3),
            num_classes=10,
        )
    with pytest.raises(ValueError, match="block_layout"):
        ModelConfig(block_layout="wide")


def test_xception_classifier():
    cfg = ModelConfig(
        backbone="xception", num_classes=10, input_shape=(64, 64), input_channels=3
    )
    model = Xception41(cfg)
    x = jnp.ones((2, 64, 64, 3))
    variables, logits = abstract_init_and_apply(model, x)
    assert logits.shape == (2, 10)
    # all 8 middle-flow units must exist — the reference's dedented loop built only one
    # (SURVEY §2.4.8)
    params = variables["params"]["backbone"]
    middle = [k for k in params if k.startswith("middle_block1_unit")]
    assert len(middle) == 8


def test_xception_atrous_output_stride():
    cfg = ModelConfig(
        backbone="xception", output_stride=16, input_shape=(64, 64), input_channels=3
    )
    from tensorflowdistributedlearning_tpu.models.xception import XceptionBackbone

    model = XceptionBackbone(cfg)
    x = jnp.ones((1, 64, 64, 3))
    _, eps = abstract_init_and_apply(model, x)
    assert eps["features"].shape[1:3] == (4, 4)  # 64/16


def test_build_model_factory():
    assert isinstance(build_model(ModelConfig()), ResNetSegmentation)
    assert isinstance(build_model(ModelConfig(num_classes=5)), ResNetClassifier)
    assert isinstance(build_model(ModelConfig(backbone="xception", num_classes=5)), Xception41)


def test_remat_matches_no_remat():
    # remat is a pure memory/recompute trade: outputs and gradients must be
    # identical to the non-remat model with the same parameters
    base = dict(
        input_shape=(33, 33), n_blocks=(1, 1, 1), base_depth=16, width_multiplier=0.125
    )
    m_plain = build_model(ModelConfig(**base))
    m_remat = build_model(ModelConfig(remat=True, **base))
    x = jnp.asarray(
        np.random.default_rng(11).normal(0, 1, (1, 33, 33, 2)), jnp.float32
    )
    variables = m_plain.init(jax.random.PRNGKey(0), x, train=False)
    out_plain = m_plain.apply(variables, x, train=False)
    out_remat = m_remat.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_remat), np.asarray(out_plain), rtol=1e-5, atol=1e-5
    )

    def loss(params, model):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        return jnp.sum(out**2)

    # jit both: eager-mode remat recomputes op-by-op with interpreter overhead
    # (measured ~3x slower than the compiled pair on one core)
    g_plain = jax.jit(jax.grad(loss), static_argnums=1)(variables["params"], m_plain)
    g_remat = jax.jit(jax.grad(loss), static_argnums=1)(variables["params"], m_remat)
    # recompute changes float op ordering, so compare with a relative tolerance
    # scaled to each leaf's magnitude
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.abs(a).max(), 1.0)
        np.testing.assert_allclose(a / scale, b / scale, rtol=1e-3, atol=1e-3)


def test_xception_segmentation():
    # the DeepLabV3+ head on the Xception backbone — the pairing the reference's
    # dead xception.py was built for but never wired up (SURVEY §2.4.8-10)
    cfg = ModelConfig(backbone="xception", input_shape=(33, 33))
    model = build_model(cfg)
    x = jnp.zeros((1, 33, 33, 2), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 33, 33, 1)
    assert out.dtype == jnp.float32
