"""Expert parallelism (parallel/expert.py): top-1 routing math, all-to-all MoE
exactness vs a dense per-token reference, capacity dropping, and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel import expert as moe
from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS, make_mesh

E = 4   # experts = model-axis size
D = 8   # token width
T = 16  # tokens per shard


def expert_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(8, model_parallel=E)  # (2, 4, 1)
    rng = np.random.default_rng(0)
    experts = [
        {
            "w": rng.normal(0, 0.5, (D, D)).astype(np.float32),
            "b": rng.normal(0, 0.1, (D,)).astype(np.float32),
        }
        for _ in range(E)
    ]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *[
        jax.tree.map(jnp.asarray, e) for e in experts
    ])
    gate = rng.normal(0, 1.0, (D, E)).astype(np.float32)
    x = rng.normal(0, 1, (T, D)).astype(np.float32)
    return mesh, experts, stacked, gate, x


def _dense_reference(experts, gate, x, capacity):
    """Per-token reference with identical routing/capacity semantics."""
    logits = x @ gate
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    chosen = logits.argmax(-1)
    counts = {e: 0 for e in range(E)}
    out = np.zeros_like(x)
    for t in range(x.shape[0]):
        e = int(chosen[t])
        if counts[e] < capacity:
            y = np.tanh(x[t] @ experts[e]["w"] + experts[e]["b"])
            out[t] = y * probs[t, e]
        counts[e] += 1
    return out


def _run_moe(mesh, stacked, gate, x, capacity_factor=1.25):
    def body(params_shard, gate_k, tokens):
        my_params = jax.tree.map(lambda p: p[0], params_shard)
        out = moe.moe_apply(
            expert_fn, my_params, gate_k, tokens,
            capacity_factor=capacity_factor,
        )
        # tokens are replicated in this harness, so every shard computes the
        # same output; pmean is numerically an identity that proves it
        return jax.lax.pmean(out, MODEL_AXIS)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(), P()),
            out_specs=P(),
        )
    )(stacked, jnp.asarray(gate), jnp.asarray(x))


def test_top1_dispatch_routing():
    logits = jnp.asarray(
        [[3.0, 0.0], [0.0, 2.0], [1.0, 0.5], [0.2, 0.9]], jnp.float32
    )
    expert, slot, keep, prob = moe.top1_dispatch(logits, capacity=1)
    np.testing.assert_array_equal(np.asarray(expert), [0, 1, 0, 1])
    np.testing.assert_array_equal(np.asarray(slot), [0, 0, 1, 1])
    np.testing.assert_array_equal(np.asarray(keep), [True, True, False, False])
    assert np.all((np.asarray(prob) > 0.5) & (np.asarray(prob) < 1.0))


def test_moe_matches_dense_reference(setup):
    mesh, experts, stacked, gate, x = setup
    import math

    capacity = max(1, math.ceil(T * 1.25 / E))
    out = np.asarray(jax.device_get(_run_moe(mesh, stacked, gate, x)))
    ref = _dense_reference(experts, gate, x, capacity)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_moe_rejects_overwide_router(setup):
    mesh, experts, stacked, gate, x = setup
    wide_gate = np.zeros((D, E * 2), np.float32)
    with pytest.raises(ValueError, match="mesh axis has"):
        _run_moe(mesh, stacked, wide_gate, x)


def test_moe_capacity_drops_tokens(setup):
    """capacity_factor small enough forces drops; dropped rows are exactly 0."""
    mesh, experts, stacked, gate, x = setup
    out = np.asarray(
        jax.device_get(_run_moe(mesh, stacked, gate, x, capacity_factor=0.25))
    )
    import math

    capacity = max(1, math.ceil(T * 0.25 / E))
    ref = _dense_reference(experts, gate, x, capacity)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    assert (np.abs(out).sum(axis=1) == 0).any()  # someone was dropped


def test_moe_gradients_flow(setup):
    """Autodiff through both all-to-alls: expert AND gate kernels receive
    finite, nonzero gradients."""
    mesh, experts, stacked, gate, x = setup

    def loss(params, gate_k):
        def body(params_shard, gk, tokens):
            my_params = jax.tree.map(lambda p: p[0], params_shard)
            out = moe.moe_apply(expert_fn, my_params, gk, tokens)
            return jax.lax.psum(jnp.sum(out**2), MODEL_AXIS) / jax.lax.axis_size(
                MODEL_AXIS
            )

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(), P()),
            out_specs=P(),
        )(params, gate_k, jnp.asarray(x)).sum()

    g_params, g_gate = jax.jit(jax.grad(loss, argnums=(0, 1)))(
        stacked, jnp.asarray(gate)
    )
    for leaf in jax.tree_util.tree_leaves(g_params):
        arr = np.asarray(jax.device_get(leaf))
        assert np.isfinite(arr).all()
    assert np.isfinite(np.asarray(jax.device_get(g_gate))).all()
    assert float(np.abs(np.asarray(jax.device_get(g_gate))).sum()) > 0
