"""Expert parallelism (parallel/expert.py): top-1 routing math, all-to-all MoE
exactness vs a dense per-token reference, capacity dropping, and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel import expert as moe
from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS, make_mesh

E = 4   # experts = model-axis size
D = 8   # token width
T = 16  # tokens per shard


def expert_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(8, model_parallel=E)  # (2, 4, 1)
    rng = np.random.default_rng(0)
    experts = [
        {
            "w": rng.normal(0, 0.5, (D, D)).astype(np.float32),
            "b": rng.normal(0, 0.1, (D,)).astype(np.float32),
        }
        for _ in range(E)
    ]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *[
        jax.tree.map(jnp.asarray, e) for e in experts
    ])
    gate = rng.normal(0, 1.0, (D, E)).astype(np.float32)
    x = rng.normal(0, 1, (T, D)).astype(np.float32)
    return mesh, experts, stacked, gate, x


def _dense_reference(experts, gate, x, capacity):
    """Per-token reference with identical routing/capacity semantics."""
    logits = x @ gate
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    chosen = logits.argmax(-1)
    counts = {e: 0 for e in range(E)}
    out = np.zeros_like(x)
    for t in range(x.shape[0]):
        e = int(chosen[t])
        if counts[e] < capacity:
            y = np.tanh(x[t] @ experts[e]["w"] + experts[e]["b"])
            out[t] = y * probs[t, e]
        counts[e] += 1
    return out


def _run_moe(mesh, stacked, gate, x, capacity_factor=1.25, fn=None):
    fn = fn or expert_fn

    def body(params_shard, gate_k, tokens):
        my_params = jax.tree.map(lambda p: p[0], params_shard)
        out = moe.moe_apply(
            fn, my_params, gate_k, tokens,
            capacity_factor=capacity_factor,
        )
        # tokens are replicated in this harness, so every shard computes the
        # same output; pmean is numerically an identity that proves it
        return jax.lax.pmean(out, MODEL_AXIS)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(), P()),
            out_specs=P(),
        )
    )(stacked, jnp.asarray(gate), jnp.asarray(x))


def test_top1_dispatch_routing():
    logits = jnp.asarray(
        [[3.0, 0.0], [0.0, 2.0], [1.0, 0.5], [0.2, 0.9]], jnp.float32
    )
    expert, slot, keep, prob = moe.top1_dispatch(logits, capacity=1)
    np.testing.assert_array_equal(np.asarray(expert), [0, 1, 0, 1])
    np.testing.assert_array_equal(np.asarray(slot), [0, 0, 1, 1])
    np.testing.assert_array_equal(np.asarray(keep), [True, True, False, False])
    assert np.all((np.asarray(prob) > 0.5) & (np.asarray(prob) < 1.0))


def test_moe_matches_dense_reference(setup):
    mesh, experts, stacked, gate, x = setup
    import math

    capacity = max(1, math.ceil(T * 1.25 / E))
    out = np.asarray(jax.device_get(_run_moe(mesh, stacked, gate, x)))
    ref = _dense_reference(experts, gate, x, capacity)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_moe_rejects_overwide_router(setup):
    mesh, experts, stacked, gate, x = setup
    wide_gate = np.zeros((D, E * 2), np.float32)
    with pytest.raises(ValueError, match="mesh axis has"):
        _run_moe(mesh, stacked, wide_gate, x)


def test_moe_capacity_drops_tokens(setup):
    """capacity_factor small enough forces drops; dropped rows are exactly 0."""
    mesh, experts, stacked, gate, x = setup
    out = np.asarray(
        jax.device_get(_run_moe(mesh, stacked, gate, x, capacity_factor=0.25))
    )
    import math

    capacity = max(1, math.ceil(T * 0.25 / E))
    ref = _dense_reference(experts, gate, x, capacity)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    assert (np.abs(out).sum(axis=1) == 0).any()  # someone was dropped


def test_moe_gradients_flow(setup):
    """Autodiff through both all-to-alls: expert AND gate kernels receive
    finite, nonzero gradients."""
    mesh, experts, stacked, gate, x = setup

    def loss(params, gate_k):
        def body(params_shard, gk, tokens):
            my_params = jax.tree.map(lambda p: p[0], params_shard)
            out = moe.moe_apply(expert_fn, my_params, gk, tokens)
            return jax.lax.psum(jnp.sum(out**2), MODEL_AXIS) / jax.lax.axis_size(
                MODEL_AXIS
            )

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(), P()),
            out_specs=P(),
        )(params, gate_k, jnp.asarray(x)).sum()

    g_params, g_gate = jax.jit(jax.grad(loss, argnums=(0, 1)))(
        stacked, jnp.asarray(gate)
    )
    for leaf in jax.tree_util.tree_leaves(g_params):
        arr = np.asarray(jax.device_get(leaf))
        assert np.isfinite(arr).all()
    assert np.isfinite(np.asarray(jax.device_get(g_gate))).all()
    assert float(np.abs(np.asarray(jax.device_get(g_gate))).sum()) > 0


# -- trainable strategy (round-2 VERDICT #6): MoE-ViT via fit() --------------

MOE_CFG_KW = dict(
    backbone="vit",
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    patch_size=4,
    embed_dim=32,
    vit_layers=4,
    num_heads=4,
    output_stride=None,
    moe_experts=4,
    moe_capacity_factor=2.0,
)


def test_dense_moe_matches_expert_parallel_forward():
    """The dense (all-experts-local) MoEMlp forward equals the expert-parallel
    (all-to-all) forward from the SAME param tree — the two execution
    strategies are numerically interchangeable."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model

    cfg = ModelConfig(**MOE_CFG_KW)
    dense_model = build_model(cfg)
    ep_model = build_model(cfg, expert_axis_name=MODEL_AXIS)
    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (4, 16, 16, 3)).astype(np.float32)
    variables = dense_model.init(jax.random.PRNGKey(0), x[:1], train=False)

    # routing pools (cumsum slots + capacity) are per-DEVICE-batch: apply the
    # dense reference per data-parallel shard (dp=2 below -> 2 images each)
    out_dense = jnp.concatenate(
        [
            dense_model.apply(variables, jnp.asarray(x[:2]), train=False),
            dense_model.apply(variables, jnp.asarray(x[2:]), train=False),
        ]
    )

    mesh = make_mesh(8, model_parallel=4)

    def fwd(params, images):
        out = ep_model.apply({"params": params}, images, train=False)
        return out

    sharded = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh, in_specs=(P(), P("batch")), out_specs=P("batch")
        )
    )
    out_ep = sharded(variables["params"], jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_ep), rtol=2e-5, atol=2e-5
    )


def test_moe_aux_loss_sown_and_balanced_at_uniform():
    """MoEMlp sows the Switch load-balancing loss: ~1.0 (its minimum) near a
    uniform router at init, and always >= 1."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model

    cfg = ModelConfig(**MOE_CFG_KW)
    model = build_model(cfg)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(0, 1, (4, 16, 16, 3)).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)
    _, mutated = model.apply(
        variables, x, train=True, mutable=["aux_loss", "intermediates"]
    )
    aux = jax.tree.leaves(mutated["aux_loss"])
    assert len(aux) == 2  # block2 and block4 are MoE (every other block)
    for a in aux:
        val = float(a) / cfg.moe_aux_weight  # un-weight
        assert 0.99 <= val < 4.0  # >= 1 up to fp, < E (degenerate collapse)


def test_fit_moe_trains_with_nondegenerate_utilization(tmp_path):
    """A Switch-MoE ViT trains end to end through fit() (data-parallel dense
    dispatch): loss decreases, and after training the expert dispatch
    fractions are non-degenerate — no expert collapse (the aux loss's job)."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.data import synthetic_batches
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    cfg = ModelConfig(**MOE_CFG_KW)
    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        cfg,
        TrainConfig(optimizer="adam", lr=1e-3, seed=0, checkpoint_every_steps=8),
    )
    result = trainer.fit(batch_size=16, steps=8)
    assert result.steps == 8
    assert np.isfinite(result.final_metrics["loss"])

    # utilization probe on the trained params
    state = trainer._restore_best_host()
    model = build_model(cfg)
    batch = next(
        synthetic_batches(
            "classification", 32, seed=9, input_shape=(16, 16), num_classes=4
        )
    )
    _, mutated = model.apply(
        {"params": state.params},
        jnp.asarray(batch["images"]),
        train=True,
        mutable=["aux_loss", "intermediates"],
    )
    fractions = [
        np.asarray(f)
        for f in jax.tree.leaves(mutated["intermediates"])
        if np.asarray(f).shape == (4,)
    ]
    assert fractions, "expert_fraction intermediates missing"
    for f in fractions:
        assert f.sum() == pytest.approx(1.0, abs=1e-5)
        # non-degenerate: no single expert hoards >90% of tokens, and at
        # least two experts receive tokens
        assert f.max() < 0.9
        assert (f > 0).sum() >= 2


def test_fit_moe_expert_parallel_trains(tmp_path):
    """expert_parallel=4: the SAME MoE ViT trains through fit() with one
    expert per model-axis shard (all-to-all dispatch inside the standard
    shard_map step); loss finite, canonical checkpoint tree restores into the
    plain model for serving."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    cfg = ModelConfig(**MOE_CFG_KW)
    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        cfg,
        TrainConfig(
            optimizer="adam",
            lr=1e-3,
            seed=0,
            expert_parallel=4,
            checkpoint_every_steps=4,
        ),
    )
    result = trainer.fit(batch_size=8, steps=4)
    assert result.steps == 4
    assert np.isfinite(result.final_metrics["loss"])
    serve = trainer.serving_fn()
    out = serve(np.zeros((2, 16, 16, 3), np.float32))
    assert np.asarray(out["probabilities"]).shape == (2, 4)


def test_expert_parallel_requires_matching_expert_count(tmp_path):
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    with pytest.raises(ValueError, match="one expert per shard"):
        ClassifierTrainer(
            str(tmp_path),
            None,
            ModelConfig(**{**MOE_CFG_KW, "moe_experts": 2}),
            TrainConfig(expert_parallel=4),
        )


def test_moe_config_validation():
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig

    with pytest.raises(ValueError, match="backbone='vit'"):
        ModelConfig(moe_experts=4)
    with pytest.raises(ValueError, match="cannot combine"):
        TrainConfig(expert_parallel=2, sequence_parallel=2)


def test_moe_with_real_vit_mlp_experts():
    """Expert parallelism over PRODUCTION-shaped experts: each expert is a ViT
    transformer block's MLP (Dense-gelu-Dense, the sub-network MoE replaces in
    Switch-style models), parameters taken from real initialized ViT blocks.
    The all-to-all dispatch must reproduce the dense per-token computation."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model

    cfg = ModelConfig(
        backbone="vit",
        num_classes=4,
        input_shape=(16, 16),
        input_channels=3,
        patch_size=4,
        embed_dim=32,
        vit_layers=4,
        num_heads=4,
        output_stride=None,
    )
    model = build_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16, 16, 3), np.float32), train=False
    )
    # one expert per layer's MLP: identical structure, independent weights
    experts = [
        {
            "in": variables["params"][f"block{i + 1}"]["mlp_in"],
            "out": variables["params"][f"block{i + 1}"]["mlp_out"],
        }
        for i in range(4)
    ]

    def mlp_expert(params, x):
        h = x @ params["in"]["kernel"] + params["in"]["bias"]
        h = jax.nn.gelu(h)
        return h @ params["out"]["kernel"] + params["out"]["bias"]

    rng = np.random.default_rng(11)
    d = 32
    tokens = jnp.asarray(rng.normal(0, 1, (32, d)).astype(np.float32))
    gate_k = jnp.asarray(rng.normal(0, 1, (d, 4)).astype(np.float32))

    mesh = make_mesh(8, model_parallel=4)
    stacked = jax.tree.map(lambda *l: jnp.stack(l), *experts)
    out = _run_moe(
        mesh, stacked, gate_k, tokens, capacity_factor=4.0, fn=mlp_expert
    )  # capacity_factor 4.0: no drops

    # dense oracle: route each token through its argmax expert
    logits = np.asarray(tokens @ gate_k)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    want = np.zeros_like(np.asarray(tokens))
    for t in range(tokens.shape[0]):
        e = int(np.argmax(logits[t]))
        y = mlp_expert(experts[e], tokens[t][None])[0]
        want[t] = np.asarray(y) * probs[t, e]
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5, atol=2e-5)
