"""Expert parallelism (parallel/expert.py): top-1 routing math, all-to-all MoE
exactness vs a dense per-token reference, capacity dropping, and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel import expert as moe
from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS, make_mesh

E = 4   # experts = model-axis size
D = 8   # token width
T = 16  # tokens per shard


def expert_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(8, model_parallel=E)  # (2, 4, 1)
    rng = np.random.default_rng(0)
    experts = [
        {
            "w": rng.normal(0, 0.5, (D, D)).astype(np.float32),
            "b": rng.normal(0, 0.1, (D,)).astype(np.float32),
        }
        for _ in range(E)
    ]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *[
        jax.tree.map(jnp.asarray, e) for e in experts
    ])
    gate = rng.normal(0, 1.0, (D, E)).astype(np.float32)
    x = rng.normal(0, 1, (T, D)).astype(np.float32)
    return mesh, experts, stacked, gate, x


def _dense_reference(experts, gate, x, capacity):
    """Per-token reference with identical routing/capacity semantics."""
    logits = x @ gate
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    chosen = logits.argmax(-1)
    counts = {e: 0 for e in range(E)}
    out = np.zeros_like(x)
    for t in range(x.shape[0]):
        e = int(chosen[t])
        if counts[e] < capacity:
            y = np.tanh(x[t] @ experts[e]["w"] + experts[e]["b"])
            out[t] = y * probs[t, e]
        counts[e] += 1
    return out


def _run_moe(mesh, stacked, gate, x, capacity_factor=1.25, fn=None):
    fn = fn or expert_fn

    def body(params_shard, gate_k, tokens):
        my_params = jax.tree.map(lambda p: p[0], params_shard)
        out = moe.moe_apply(
            fn, my_params, gate_k, tokens,
            capacity_factor=capacity_factor,
        )
        # tokens are replicated in this harness, so every shard computes the
        # same output; pmean is numerically an identity that proves it
        return jax.lax.pmean(out, MODEL_AXIS)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(), P()),
            out_specs=P(),
        )
    )(stacked, jnp.asarray(gate), jnp.asarray(x))


def test_top1_dispatch_routing():
    logits = jnp.asarray(
        [[3.0, 0.0], [0.0, 2.0], [1.0, 0.5], [0.2, 0.9]], jnp.float32
    )
    expert, slot, keep, prob = moe.top1_dispatch(logits, capacity=1)
    np.testing.assert_array_equal(np.asarray(expert), [0, 1, 0, 1])
    np.testing.assert_array_equal(np.asarray(slot), [0, 0, 1, 1])
    np.testing.assert_array_equal(np.asarray(keep), [True, True, False, False])
    assert np.all((np.asarray(prob) > 0.5) & (np.asarray(prob) < 1.0))


def test_moe_matches_dense_reference(setup):
    mesh, experts, stacked, gate, x = setup
    import math

    capacity = max(1, math.ceil(T * 1.25 / E))
    out = np.asarray(jax.device_get(_run_moe(mesh, stacked, gate, x)))
    ref = _dense_reference(experts, gate, x, capacity)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_moe_rejects_overwide_router(setup):
    mesh, experts, stacked, gate, x = setup
    wide_gate = np.zeros((D, E * 2), np.float32)
    with pytest.raises(ValueError, match="mesh axis has"):
        _run_moe(mesh, stacked, wide_gate, x)


def test_moe_capacity_drops_tokens(setup):
    """capacity_factor small enough forces drops; dropped rows are exactly 0."""
    mesh, experts, stacked, gate, x = setup
    out = np.asarray(
        jax.device_get(_run_moe(mesh, stacked, gate, x, capacity_factor=0.25))
    )
    import math

    capacity = max(1, math.ceil(T * 0.25 / E))
    ref = _dense_reference(experts, gate, x, capacity)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    assert (np.abs(out).sum(axis=1) == 0).any()  # someone was dropped


def test_moe_gradients_flow(setup):
    """Autodiff through both all-to-alls: expert AND gate kernels receive
    finite, nonzero gradients."""
    mesh, experts, stacked, gate, x = setup

    def loss(params, gate_k):
        def body(params_shard, gk, tokens):
            my_params = jax.tree.map(lambda p: p[0], params_shard)
            out = moe.moe_apply(expert_fn, my_params, gk, tokens)
            return jax.lax.psum(jnp.sum(out**2), MODEL_AXIS) / jax.lax.axis_size(
                MODEL_AXIS
            )

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(), P()),
            out_specs=P(),
        )(params, gate_k, jnp.asarray(x)).sum()

    g_params, g_gate = jax.jit(jax.grad(loss, argnums=(0, 1)))(
        stacked, jnp.asarray(gate)
    )
    for leaf in jax.tree_util.tree_leaves(g_params):
        arr = np.asarray(jax.device_get(leaf))
        assert np.isfinite(arr).all()
    assert np.isfinite(np.asarray(jax.device_get(g_gate))).all()
    assert float(np.abs(np.asarray(jax.device_get(g_gate))).sum()) > 0


def test_moe_with_real_vit_mlp_experts():
    """Expert parallelism over PRODUCTION-shaped experts: each expert is a ViT
    transformer block's MLP (Dense-gelu-Dense, the sub-network MoE replaces in
    Switch-style models), parameters taken from real initialized ViT blocks.
    The all-to-all dispatch must reproduce the dense per-token computation."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model

    cfg = ModelConfig(
        backbone="vit",
        num_classes=4,
        input_shape=(16, 16),
        input_channels=3,
        patch_size=4,
        embed_dim=32,
        vit_layers=4,
        num_heads=4,
        output_stride=None,
    )
    model = build_model(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16, 16, 3), np.float32), train=False
    )
    # one expert per layer's MLP: identical structure, independent weights
    experts = [
        {
            "in": variables["params"][f"block{i + 1}"]["mlp_in"],
            "out": variables["params"][f"block{i + 1}"]["mlp_out"],
        }
        for i in range(4)
    ]

    def mlp_expert(params, x):
        h = x @ params["in"]["kernel"] + params["in"]["bias"]
        h = jax.nn.gelu(h)
        return h @ params["out"]["kernel"] + params["out"]["bias"]

    rng = np.random.default_rng(11)
    d = 32
    tokens = jnp.asarray(rng.normal(0, 1, (32, d)).astype(np.float32))
    gate_k = jnp.asarray(rng.normal(0, 1, (d, 4)).astype(np.float32))

    mesh = make_mesh(8, model_parallel=4)
    stacked = jax.tree.map(lambda *l: jnp.stack(l), *experts)
    out = _run_moe(
        mesh, stacked, gate_k, tokens, capacity_factor=4.0, fn=mlp_expert
    )  # capacity_factor 4.0: no drops

    # dense oracle: route each token through its argmax expert
    logits = np.asarray(tokens @ gate_k)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    want = np.zeros_like(np.asarray(tokens))
    for t in range(tokens.shape[0]):
        e = int(np.argmax(logits[t]))
        y = mlp_expert(experts[e], tokens[t][None])[0]
        want[t] = np.asarray(y) * probs[t, e]
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5, atol=2e-5)
