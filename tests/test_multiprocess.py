"""REAL multi-process SPMD integration: two jax.distributed processes (gloo CPU
collectives, 4 virtual devices each = one 8-device global mesh) run the
production multi-host path — multihost.initialize with explicit coordinator,
per-process batch assembly via global_shard_batch, one collective-bearing train
step — and must agree with each other AND with the single-process oracle.

This is the test the reference could never have (its MirroredStrategy was
single-process by construction, SURVEY §2.3) and the proof VERDICT r1 #3 asked
for, upgraded from mocked process counts to real processes."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "mp_train_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# slow tier: each spawn runs real 2-process gloo training (~2 min total on the
# 1-core CI box) — covered by tools/run_suite.py's 1500s group budgets, kept
# out of the 870s tier-1 window (ROADMAP.md)
pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(mode: str):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            if p.returncode != 0:
                # the worker exits 3 with a "no gloo:" marker ONLY when the
                # collectives-implementation config itself is unsupported;
                # anything else is a real failure this test exists to catch
                if p.returncode == 3 and "no gloo:" in out:
                    pytest.skip("gloo CPU collectives unavailable")
                raise AssertionError(
                    f"worker rc={p.returncode}\nstdout:{out[-2000:]}\n"
                    f"stderr:{err[-2000:]}"
                )
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    from tests.mp_train_worker import ALL_STRATEGIES

    expected = len(ALL_STRATEGIES) if mode == "both" else 1
    results = []
    for out in outs:
        per_mode = {}
        for ln in out.splitlines():
            if ln.startswith("RESULT_"):
                # a RESULT line mangled by interleaved child logging
                # (observed transiently under full-suite load on the
                # 1-core box) must not crash the parser mid-line; the
                # completeness check below turns the gap into ONE readable
                # failure with the raw output attached instead of an
                # opaque unpack/parse ValueError
                parts = ln.split()
                if len(parts) != 3:
                    continue
                tag, loss, step = parts
                try:
                    parsed = (float(loss), int(step))
                except ValueError:
                    continue
                per_mode[tag.removeprefix("RESULT_").lower()] = parsed
        if len(per_mode) < expected:
            raise AssertionError(
                f"worker produced {sorted(per_mode)} of {expected} expected "
                f"strategy results; raw output tail:\n{out[-2000:]}"
            )
        results.append(per_mode)
    return results


@pytest.fixture(scope="module")
def worker_results():
    """One 2-process spawn runs ALL strategies in
    ``mp_train_worker.ALL_STRATEGIES`` — the spawn + jax.distributed init
    dominates the test's cost, so it is paid once."""
    return _run_workers("both")


def test_ranks_agree(worker_results):
    (loss0, step0), (loss1, step1) = (r["dp"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)  # bitwise across processes


def test_matches_single_process_oracle(worker_results):
    """The 2-process run must equal a 1-process 8-device run on the identical
    global batch (the MirroredStrategy invariance, generalized per host)."""
    loss0, _ = worker_results[0]["dp"]
    assert loss0 == pytest.approx(_oracle_loss(), rel=1e-6)


def _oracle_loss(spatial: bool = False, ep: bool = False, pp: bool = False):
    """Single-process 8-device loss on the identical seeded batch/model (no BN,
    so the DP shard_map step, the GSPMD TP step, the exactness-guaranteed
    spatial step, and the all-to-all MoE step all agree to reassociation).
    One recipe serves every strategy's oracle so they cannot diverge; the
    oracle mesh matches the workers' dp degree so per-shard routing pools
    (MoE capacity) are identical."""
    import jax

    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tests.mp_train_worker import make_global_batch, tiny_model

    mesh = mesh_lib.make_mesh(
        8,
        sequence_parallel=2 if spatial else 1,
        model_parallel=2 if (ep or pp) else 1,
    )  # spatial+ep composes to the full (2, 2, 2) three-axis mesh
    if pp:
        from tensorflowdistributedlearning_tpu.models import build_model
        from tensorflowdistributedlearning_tpu.train import (
            pipeline_step as pp_step,
        )
        from tests.mp_train_worker import tiny_vit_cfg

        cfg = tiny_vit_cfg()
        state = create_train_state(
            build_model(cfg),
            step_lib.make_optimizer(TrainConfig(lr=0.01)),
            jax.random.PRNGKey(0),
            np.zeros((1, 8, 8, 3), np.float32),
        )
        train_step = pp_step.make_train_step_pipeline(
            mesh, step_lib.ClassificationTask(), cfg, microbatches=2,
            donate=False,
        )
    else:
        state = create_train_state(
            tiny_model(moe=ep),
            step_lib.make_optimizer(TrainConfig(lr=0.01)),
            jax.random.PRNGKey(0),
            np.zeros((1, 8, 8, 3), np.float32),
        )
        if spatial and ep:
            state = state.replace(
                apply_fn=tiny_model(spatial=True, moe=True, ep=True).apply
            )
        elif spatial:
            state = state.replace(apply_fn=tiny_model(spatial=True).apply)
        elif ep:
            state = state.replace(apply_fn=tiny_model(moe=True, ep=True).apply)
        train_step = step_lib.make_train_step(
            mesh, step_lib.ClassificationTask(), donate=False, spatial=spatial
        )
    state = mesh_lib.replicate(state, mesh)
    shard = mesh_lib.shard_batch_spatial if spatial else mesh_lib.shard_batch
    _, metrics = train_step(state, shard(make_global_batch(16), mesh))
    return step_lib.compute_metrics(jax.device_get(metrics))["loss"]


def test_tensor_parallel_across_processes(worker_results):
    """Multi-host TENSOR parallelism with real processes: a (4, 2, 1) dp x tp
    mesh — each model-axis group is intra-process (make_mesh requires
    it), the BATCH axis spans the two processes — with params/optimizer
    assembled from per-process shards and the GSPMD train step over gloo.
    Ranks must agree bitwise AND match the single-process oracle loss."""
    (loss0, step0), (loss1, step1) = (r["tp"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(), rel=1e-5)


def test_spatial_parallel_across_processes(worker_results):
    """Multi-host SPATIAL parallelism with real processes: a (4, 1, 2) dp x sp
    mesh — sequence groups intra-process, the BATCH axis spanning both
    processes — running halo-exchange convs + sequence-pmean'd global pooling
    over gloo. Ranks agree bitwise and match the single-process spatial
    oracle."""
    (loss0, step0), (loss1, step1) = (r["sp"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(spatial=True), rel=1e-5)


def test_expert_parallel_across_processes(worker_results):
    """Multi-host EXPERT parallelism with real processes: a (4, 2, 1) dp x ep
    mesh — one expert per intra-process model shard, the batch axis spanning
    both ranks — running the production MoE layer's top-1 all-to-all dispatch
    + load-balancing aux loss over gloo. Ranks agree bitwise and match the
    single-process oracle on the same dp degree (identical capacity pools)."""
    (loss0, step0), (loss1, step1) = (r["ep"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(ep=True), rel=1e-5)


def test_three_axis_composition_across_processes(worker_results):
    """THREE parallelism axes at once with real processes: the full (dp=2,
    ep=2, sp=2) global mesh — halo-exchange convs over the sequence axis, MoE
    all-to-all over the model axis, gradient mean over the batch axis, in ONE
    shard_map step spanning both ranks. Real pods run 3-axis layouts
    (dp x tp x sp, dp x pp x ep); the pairwise matrix alone doesn't cover the
    axis interactions. Ranks agree bitwise and match the single-process
    (2, 2, 2) oracle."""
    (loss0, step0), (loss1, step1) = (r["3ax"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(spatial=True, ep=True), rel=1e-5)


def test_tensor_spatial_composition_across_processes(worker_results):
    """THREE axes including TENSOR parallelism with real processes: the
    (dp=2, tp=2, sp=2) global mesh via shard_map's hybrid ``axis_names``
    mode — (batch, sequence) manual (halo-exchange convs, explicit gradient
    mean) while the model axis stays auto, with channel-sharded params and
    the SPMD partitioner deriving the tensor-parallel reductions inside each
    manual shard. This is the composition VERDICT r4 #7 asked for: the
    pairwise dp x tp proof is whole-step GSPMD and dp x sp is whole-step
    shard_map, so only the hybrid mode can put tp and sp in ONE step. Ranks
    agree bitwise and match the plain spatial oracle (tensor parallelism is
    a layout, not a numerics change, up to reassociation)."""
    (loss0, step0), (loss1, step1) = (r["tpsp"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(spatial=True), rel=1e-5)


def test_zero_weight_update_sharding_across_processes(worker_results):
    """Multi-host ZeRO-style weight-update sharding (arXiv:2004.13336):
    optimizer moments shard 1/dp over the batch axis spanning BOTH
    processes; the update's cross-replica gather rides gloo. Numerics are
    identical to plain replication (the single-process proof is
    tests/test_tensor_parallel.py::test_weight_update_sharding_zero_style),
    so ranks agree bitwise and the loss equals the plain dp oracle."""
    (loss0, step0), (loss1, step1) = (r["zero"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(), rel=1e-5)


def test_pipeline_parallel_across_processes(worker_results):
    """Multi-host PIPELINE parallelism with real processes: a (4, 2, 1) dp x pp
    mesh — a tiny ViT's 2 blocks as 2 GPipe stages in intra-process model
    groups, microbatches ticking stage-to-stage over ppermute while the batch
    axis spans both ranks. Ranks agree bitwise and match the single-process
    pipeline oracle."""
    (loss0, step0), (loss1, step1) = (r["pp"] for r in worker_results)
    assert step0 == step1 == 1
    assert loss0 == pytest.approx(loss1, abs=0.0)
    assert loss0 == pytest.approx(_oracle_loss(pp=True), rel=1e-5)
