"""serve/ subsystem: bucketed engine, micro-batcher, HTTP server, telemetry.

The contracts under test are the ones production serving is operated by:
padding round-trips exactly (a padded batch answers identically to the
unbatched forward), the bucket ladder keeps steady state recompile-free
(asserted through obs.recompile's detector, not by faith), the bounded queue
rejects structurally instead of growing, deadlines expire without burning
bucket slots, and the localhost HTTP stack serves /v1/predict + /healthz +
/metrics and drains gracefully into the telemetry ledger.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.obs import Telemetry
from tensorflowdistributedlearning_tpu.serve import (
    DeadlineExceededError,
    InferenceEngine,
    MicroBatcher,
    QueueFullError,
    RequestTooLargeError,
    ServerClosedError,
    ServingServer,
)

FEATURES = 6
CLASSES = 3


@pytest.fixture(scope="module")
def serve_fn():
    """Tiny params-baked jitted closure, shaped like the trainers' serving_fn."""
    import jax
    import jax.numpy as jnp

    w = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.3

    @jax.jit
    def fn(x):
        logits = x @ w
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    return fn


@pytest.fixture
def engine(serve_fn):
    return InferenceEngine(serve_fn, (FEATURES,), buckets=(1, 4, 8))


def _reference(serve_fn, x):
    return {k: np.asarray(v) for k, v in serve_fn(x).items()}


# -- engine: bucket selection + padding round-trip --------------------------


def test_bucket_selection():
    eng = InferenceEngine(lambda x: {"y": x}, (2,), buckets=(4, 1, 16, 4))
    assert eng.buckets == (1, 4, 16)  # sorted, deduped
    assert eng.select_bucket(1) == 1
    assert eng.select_bucket(2) == 4
    assert eng.select_bucket(4) == 4
    assert eng.select_bucket(5) == 16
    assert eng.max_batch_size == 16
    with pytest.raises(RequestTooLargeError):
        eng.select_bucket(17)
    with pytest.raises(ValueError):
        eng.select_bucket(0)


def test_padding_roundtrip_identical_to_unbatched(engine, serve_fn, rng):
    """The whole point of padding: results for n examples through any bucket
    are bit-comparable to the plain forward on those n examples."""
    for n in (1, 2, 3, 4, 5, 8):
        x = rng.normal(0, 1, (n, FEATURES)).astype(np.float32)
        got = engine.infer(x)
        ref = _reference(serve_fn, x)
        assert got["probabilities"].shape == (n, CLASSES)
        assert got["class"].shape == (n,)
        np.testing.assert_allclose(
            got["probabilities"], ref["probabilities"], rtol=1e-6
        )
        np.testing.assert_array_equal(got["class"], ref["class"])


def test_bucket_hit_accounting(engine, rng):
    for n, expected_bucket in ((1, 1), (3, 4), (4, 4), (7, 8)):
        engine.infer(rng.normal(0, 1, (n, FEATURES)).astype(np.float32))
    assert engine.bucket_hits == {1: 1, 4: 2, 8: 1}


def test_engine_rejects_wrong_example_shape(engine):
    with pytest.raises(ValueError, match="expected examples"):
        engine.infer(np.zeros((2, FEATURES + 1), np.float32))


# -- engine: artifact loading + manifest signature --------------------------


def test_manifest_records_output_signature(serve_fn, tmp_path):
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory = str(tmp_path / "artifact")
    serving_lib.export_serving_artifact(serve_fn, (1, FEATURES), directory)
    manifest = serving_lib.read_manifest(directory)
    assert manifest["input_shape"] == [None, FEATURES]
    assert manifest["input_dtype"] == "float32"
    # the output side too: clients validate responses from the manifest alone
    assert manifest["outputs"]["probabilities"] == {
        "shape": [None, CLASSES],
        "dtype": "float32",
    }
    assert manifest["outputs"]["class"]["shape"] == [None]


def test_engine_from_artifact_roundtrip(serve_fn, tmp_path, rng):
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory = str(tmp_path / "artifact")
    serving_lib.export_serving_artifact(serve_fn, (1, FEATURES), directory)
    eng = InferenceEngine.from_artifact(directory, buckets=(1, 4))
    x = rng.normal(0, 1, (3, FEATURES)).astype(np.float32)
    np.testing.assert_allclose(
        eng.infer(x)["probabilities"],
        _reference(serve_fn, x)["probabilities"],
        rtol=1e-5,
        atol=1e-6,
    )


def test_load_takes_input_dtype_from_manifest(serve_fn, tmp_path):
    """An artifact exported for a non-float32 input signature must be fed
    that dtype on reload — previously load hardcoded float32."""
    import jax

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory = str(tmp_path / "artifact")
    serving_lib.export_serving_artifact(
        serve_fn, (1, FEATURES), directory, input_dtype="bfloat16"
    )
    manifest = serving_lib.read_manifest(directory)
    assert manifest["input_dtype"] == "bfloat16"
    loaded = serving_lib.load_serving_artifact(directory)
    out = loaded(np.zeros((2, FEATURES), np.float32))  # cast happens inside
    assert jax.block_until_ready(out)["probabilities"].shape == (2, CLASSES)


# -- recompile discipline ----------------------------------------------------


def test_zero_post_warmup_recompiles(tmp_path, rng):
    """After warmup compiles every bucket, NO request batch size may trigger
    a compile — asserted via the obs.recompile detector, which must also have
    actually seen the warmup compiles (guards against a dead listener)."""
    import jax

    # a FRESH jit closure: the shared fixture's buckets are already compiled
    # by earlier tests, which would leave the detector nothing to see
    w = jax.random.normal(jax.random.PRNGKey(1), (FEATURES, CLASSES))
    fn = jax.jit(lambda x: {"probabilities": jax.nn.softmax(x @ w, axis=-1)})
    tel = Telemetry(str(tmp_path), run_info={"kind": "serve"})
    try:
        eng = InferenceEngine(
            fn, (FEATURES,), buckets=(1, 4, 8), registry=tel.registry
        )
        eng.warmup(telemetry=tel)
        assert eng.warmed
        assert tel.detector.compile_count >= 1, "detector saw no compiles at all"
        assert tel.detector.post_warmup_count == 0
        for n in range(1, 9):
            eng.infer(rng.normal(0, 1, (n, FEATURES)).astype(np.float32))
        assert tel.detector.post_warmup_count == 0
    finally:
        tel.close()


# -- batcher -----------------------------------------------------------------


def test_batcher_coalesces_and_preserves_results(engine, serve_fn, rng):
    batcher = MicroBatcher(engine, max_wait_ms=25, max_queue=64)
    xs = [rng.normal(0, 1, (2, FEATURES)).astype(np.float32) for _ in range(4)]
    reqs = [batcher.submit(x) for x in xs]
    for x, req in zip(xs, reqs):
        out = req.result(timeout=10)
        np.testing.assert_allclose(
            out["probabilities"],
            _reference(serve_fn, x)["probabilities"],
            rtol=1e-6,
        )
    # 4 requests x 2 examples coalesced into fewer forwards than requests
    assert engine.registry.counter("serve/batches").value < 4
    assert engine.registry.counter("serve/completed").value == 4
    batcher.close()


def test_batcher_bare_example_promoted_to_batch(engine):
    batcher = MicroBatcher(engine, max_wait_ms=1)
    out = batcher.submit(np.zeros(FEATURES, np.float32)).result(timeout=10)
    assert out["probabilities"].shape == (1, CLASSES)
    batcher.close()


def _stalled_batcher(max_queue, release):
    """Batcher whose engine blocks until ``release`` is set — the queue fills
    deterministically behind the stalled worker."""

    def stalled(x):
        release.wait(10)
        return {"y": np.asarray(x)}

    eng = InferenceEngine(stalled, (FEATURES,), buckets=(1,))
    return MicroBatcher(eng, max_queue=max_queue, max_wait_ms=0.0), eng


def test_batcher_full_queue_rejects_structurally():
    release = threading.Event()
    batcher, eng = _stalled_batcher(3, release)
    x = np.zeros((1, FEATURES), np.float32)
    accepted = []
    with pytest.raises(QueueFullError):
        # queue(3) + at most 1 in flight: the 5th submit MUST reject
        for _ in range(5):
            accepted.append(batcher.submit(x))
    assert eng.registry.counter("serve/rejected_queue_full").value == 1
    release.set()
    for req in accepted:  # everything accepted still completes — no loss
        assert req.result(timeout=10)["y"].shape == (1, FEATURES)
    batcher.close()


def test_batcher_deadline_expires_in_queue():
    release = threading.Event()
    batcher, eng = _stalled_batcher(8, release)
    x = np.zeros((1, FEATURES), np.float32)
    blocker = batcher.submit(x)  # occupies the worker
    time.sleep(0.05)  # let the worker take it
    doomed = batcher.submit(x, deadline_ms=1)
    ok = batcher.submit(x)  # no deadline — must still be served
    time.sleep(0.05)  # deadline passes while the worker is stalled
    release.set()
    with pytest.raises(DeadlineExceededError):
        doomed.result(timeout=10)
    assert ok.result(timeout=10)["y"].shape == (1, FEATURES)
    assert blocker.result(timeout=10)["y"].shape == (1, FEATURES)
    assert eng.registry.counter("serve/deadline_exceeded").value == 1
    batcher.close()


def test_batcher_too_large_and_closed_rejections(engine):
    batcher = MicroBatcher(engine, max_wait_ms=1)
    with pytest.raises(RequestTooLargeError):
        batcher.submit(np.zeros((engine.max_batch_size + 1, FEATURES), np.float32))
    batcher.close()
    with pytest.raises(ServerClosedError):
        batcher.submit(np.zeros((1, FEATURES), np.float32))


def test_batcher_engine_error_fails_requests_not_worker(engine):
    batcher = MicroBatcher(engine, max_wait_ms=1)
    bad = batcher.submit(np.zeros((2, FEATURES), np.float32))
    bad.x = np.zeros((2, FEATURES + 3), np.float32)  # corrupt post-validation
    with pytest.raises(ValueError):
        bad.result(timeout=10)
    # the worker survived: subsequent traffic still flows
    ok = batcher.submit(np.zeros((1, FEATURES), np.float32))
    assert ok.result(timeout=10)["probabilities"].shape == (1, CLASSES)
    batcher.close()


# -- HTTP end-to-end ---------------------------------------------------------


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_server_end_to_end(serve_fn, tmp_path, rng):
    """Localhost smoke over the full stack: predict round-trip, health,
    metrics, structured 4xx errors, graceful drain, ledger + report."""
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    workdir = str(tmp_path / "serve_run")
    tel = Telemetry(workdir, run_info={"kind": "serve"})
    engine = InferenceEngine(
        serve_fn, (FEATURES,), buckets=(1, 4), registry=tel.registry
    )
    engine.warmup(telemetry=tel)
    batcher = MicroBatcher(engine, max_wait_ms=2, max_queue=16)
    server = ServingServer(
        engine, batcher, port=0, telemetry=tel, window_secs=0
    ).start()
    try:
        x = rng.normal(0, 1, (3, FEATURES)).astype(np.float32)
        status, body = _post(server.url + "/v1/predict", {"instances": x.tolist()})
        assert status == 200 and body["n"] == 3
        np.testing.assert_allclose(
            np.asarray(body["predictions"]["probabilities"], np.float32),
            _reference(serve_fn, x)["probabilities"],
            rtol=1e-4,
            atol=1e-6,
        )

        health = _get(server.url + "/healthz")
        assert health["ok"] and not health["draining"]
        metrics = _get(server.url + "/metrics")
        assert metrics["buckets"] == {"1": 0, "4": 1}
        assert metrics["registry"]["counters"]["serve/completed"] == 1

        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/v1/predict", {"wrong_key": []})
        assert err.value.code == 400
        assert json.loads(err.value.read())["error"]["code"] == "bad_request"

        with pytest.raises(urllib.error.HTTPError) as err:
            _post(
                server.url + "/v1/predict",
                {"instances": np.zeros((5, FEATURES)).tolist()},  # > bucket 4
            )
        assert err.value.code == 413
    finally:
        server.shutdown()

    # drained shutdown wrote the final window + run_end into the ledger,
    # and the goodput report renders a serving section from it
    from tensorflowdistributedlearning_tpu.obs import read_ledger

    events = read_ledger(workdir)
    kinds = [e["event"] for e in events]
    assert "serve_window" in kinds and "run_end" in kinds
    window = [e for e in events if e["event"] == "serve_window"][-1]
    assert window["completed"] == 1
    assert window["recompiles_post_warmup"] == 0
    rendered = report_workdir(workdir)
    assert "serving" in rendered
    assert "post-warmup recompiles on the request path: none" in rendered


def test_http_rejects_while_draining(serve_fn):
    engine = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
    batcher = MicroBatcher(engine, max_wait_ms=1)
    server = ServingServer(engine, batcher, port=0, window_secs=0).start()
    url = server.url
    server.shutdown()
    # listener is closed after drain: connection refused, not a hang
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _post(url + "/v1/predict", {"instances": [[0.0] * FEATURES]}, timeout=3)


def _get_status(url, timeout=10):
    """GET returning (status, json_body) — error statuses included."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_admin_profile_endpoint(serve_fn, tmp_path, monkeypatch):
    """/admin/profile route semantics: 400 on bad seconds, 202 with a
    capture_id when a capture starts, 409 while one is in flight, and the
    finished capture ledgered as a profile_capture event. jax.profiler is
    faked — the route and the profiler's single-capture discipline are the
    contract here, not TSL."""
    import jax

    dirs = []

    def fake_start(logdir):
        dirs.append(logdir)

    def fake_stop():
        import os

        run = os.path.join(dirs[-1], "plugins", "profile", "run0")
        os.makedirs(run, exist_ok=True)
        with open(os.path.join(run, "host.xplane.pb"), "wb") as f:
            f.write(b"")  # valid empty XSpace: zero ops, zero skips

    monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)

    workdir = str(tmp_path / "profile_run")
    tel = Telemetry(workdir, run_info={"kind": "serve"})
    engine = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
    batcher = MicroBatcher(engine, max_wait_ms=1)
    server = ServingServer(
        engine, batcher, port=0, telemetry=tel, window_secs=0
    ).start()
    try:
        for bad in ("abc", "0", "-1", "61"):
            status, body = _get_status(
                server.url + f"/admin/profile?seconds={bad}"
            )
            assert status == 400
            assert body["error"]["code"] == "bad_request"
        status, body = _get_status(server.url + "/admin/profile?seconds=0.4")
        assert status == 202
        assert body["status"] == "started" and body["capture_id"]
        assert "replica" in body
        # single-capture discipline: the running capture wins
        status, body = _get_status(server.url + "/admin/profile?seconds=0.4")
        assert status == 409
        assert body["error"]["code"] == "capture_in_flight"
    finally:
        server.shutdown()  # waits out the capture; ledger closes after it
    from tensorflowdistributedlearning_tpu.obs import read_ledger

    events = read_ledger(workdir)
    captures = [e for e in events if e["event"] == "profile_capture"]
    assert len(captures) == 1
    assert captures[0]["reason"] == "admin"
    assert captures[0]["capture_id"]
    assert dirs and dirs[0].startswith(workdir)


def test_http_admin_profile_without_workdir_503(serve_fn):
    """A server on disabled telemetry has nowhere to write captures: the
    route answers 503 profiling_unavailable instead of pretending."""
    engine = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
    batcher = MicroBatcher(engine, max_wait_ms=1)
    server = ServingServer(engine, batcher, port=0, window_secs=0).start()
    try:
        status, body = _get_status(server.url + "/admin/profile?seconds=1")
        assert status == 503
        assert body["error"]["code"] == "profiling_unavailable"
    finally:
        server.shutdown()


# -- CLI surface -------------------------------------------------------------


def test_cli_serve_parser_defaults():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(["serve", "--artifact-dir", "d"])
    assert args.port == 8000
    assert tuple(args.buckets) == (1, 4, 16, 64)
    assert args.queue_size == 256
    args = build_parser().parse_args(
        ["predict", "--test-dir", "t", "--model-dir", "m", "--artifact-dir", "a"]
    )
    assert args.artifact_dir == "a"


def test_cli_predict_from_artifact(serve_fn, tmp_path, capsys):
    """predict --artifact-dir: checkpoint-free inference through the engine
    (segmentation-shaped artifact so the Laplacian-channel contract runs)."""
    from tensorflowdistributedlearning_tpu.cli import main
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib
    from tests.conftest import make_salt_dataset

    _, test_dir, _ = make_salt_dataset(tmp_path, n_images=1, n_test=3, shape=(8, 8))

    def seg_fn(images):  # [B, 8, 8, 2] -> probabilities/mask, serving_fn-shaped
        import jax
        import jax.numpy as jnp

        probs = jax.nn.sigmoid(images.mean(axis=-1, keepdims=True))
        return {"probabilities": probs, "mask": (probs > 0.5).astype(jnp.float32)}

    artifact_dir = str(tmp_path / "artifact")
    serving_lib.export_serving_artifact(seg_fn, (1, 8, 8, 2), artifact_dir)
    out_npz = str(tmp_path / "pred.npz")
    rc = main(
        [
            "predict",
            "--test-dir", test_dir,
            "--model-dir", "unused",
            "--artifact-dir", artifact_dir,
            "--output", out_npz,
        ]
    )
    assert rc == 0
    written = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert written["n"] == 3
    loaded = np.load(out_npz, allow_pickle=True)
    assert loaded["probabilities"].shape == (3, 8, 8, 1)
    assert loaded["mask"].shape == (3, 8, 8, 1)
