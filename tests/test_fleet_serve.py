"""Serving fleet: router, continuous batching, autoscaler, replica failover.

The contracts under test are the ones a fleet is operated by: backlog built
up during a compute dispatches into the NEXT batch with no inserted wait
(continuous batching), 429/503 responses tell clients WHEN to come back
(Retry-After from the live drain rate), the router balances on real queue
depth and survives replica death without losing an accepted request, the
autoscaler's state machine is boring (sustained signals, cooldown, hard
bounds), and the whole tier's story — routing counters, fleet_scale
decisions, replica lifecycle — renders from one merged workdir.

The subprocess end-to-end tests (slow-marked out of the tier-1 window, run
unfiltered by the focused ci.yml step) drive the real thing: `serve --port 0`
reporting its ephemeral port, and the headline failover soak — SIGKILL a
replica mid-load via the fault seam (`--inject-fault sigkill@N`), assert the
router converges with zero client-visible errors and the supervisor restarts
the dead replica.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.obs import Telemetry
from tensorflowdistributedlearning_tpu.serve import (
    AutoscaleConfig,
    Autoscaler,
    InferenceEngine,
    MicroBatcher,
    ServingServer,
    bind_ephemeral,
)
from tensorflowdistributedlearning_tpu.serve.router import (
    FleetRouter,
    ReplicaState,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEATURES = 6
CLASSES = 3


@pytest.fixture(scope="module")
def serve_fn():
    import jax
    import jax.numpy as jnp

    w = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.3

    @jax.jit
    def fn(x):
        return {
            "probabilities": jax.nn.softmax(x @ w, axis=-1),
            "class": jnp.argmax(x @ w, axis=-1),
        }

    return fn


def _server(serve_fn, *, replica_id=0, max_queue=16, buckets=(1, 4),
            max_wait_ms=2, telemetry=None, window_secs=0):
    engine = InferenceEngine(
        serve_fn, (FEATURES,), buckets=buckets,
        registry=telemetry.registry if telemetry else None,
    )
    engine.warmup()
    batcher = MicroBatcher(engine, max_wait_ms=max_wait_ms, max_queue=max_queue)
    server = ServingServer(
        engine, batcher, port=0, replica_id=replica_id,
        telemetry=telemetry, window_secs=window_secs,
    )
    return server.start()


def _post(url, payload, timeout=10, headers=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


# -- continuous batching -----------------------------------------------------


def _timed_stall_engine(hold_s):
    """Engine whose serve_fn records (start, end) per call and stalls the
    FIRST call for ``hold_s`` — the compute a backlog builds up behind.
    Bucket 4, so a lone request never fills the batch (a full batch
    dispatches instantly in both modes, which would mask the window)."""
    calls = []
    first = threading.Event()

    def fn(x):
        t0 = time.monotonic()
        hold = not first.is_set()
        first.set()
        if hold:
            time.sleep(hold_s)
        calls.append((t0, time.monotonic()))
        return {"y": np.asarray(x)}

    return InferenceEngine(fn, (FEATURES,), buckets=(4,)), calls, first


def test_continuous_batching_dispatches_backlog_immediately():
    """A request that queued during the previous batch's compute has already
    spent its coalesce budget — the next dispatch must go out with no
    inserted max_wait_ms wait."""
    engine, calls, first = _timed_stall_engine(hold_s=0.4)
    batcher = MicroBatcher(engine, max_wait_ms=250, max_queue=8)
    x = np.zeros((1, FEATURES), np.float32)
    r1 = batcher.submit(x)
    assert first.wait(10)  # r1 is in its 0.4s compute
    r2 = batcher.submit(x)  # queues during compute: waits ~0.4s >= 250ms
    r1.result(timeout=10)
    r2.result(timeout=10)
    batcher.close()
    assert len(calls) == 2
    gap = calls[1][0] - calls[0][1]
    assert gap < 0.15, (
        f"backlogged dispatch waited {gap * 1000:.0f}ms — continuous "
        "batching must not re-run the coalesce window"
    )


def test_legacy_fixed_window_still_waits():
    """continuous=False restores the A/B baseline: a fresh coalesce window
    opens when the worker collects, even for backlog."""
    engine, calls, first = _timed_stall_engine(hold_s=0.4)
    batcher = MicroBatcher(
        engine, max_wait_ms=250, max_queue=8, continuous=False
    )
    x = np.zeros((1, FEATURES), np.float32)
    r1 = batcher.submit(x)
    assert first.wait(10)
    r2 = batcher.submit(x)
    r1.result(timeout=10)
    r2.result(timeout=10)
    batcher.close()
    gap = calls[1][0] - calls[0][1]
    assert gap >= 0.2, (
        f"legacy mode dispatched after only {gap * 1000:.0f}ms — expected "
        "a fresh max_wait_ms window"
    )


# -- Retry-After -------------------------------------------------------------


def test_retry_after_math():
    """queue_depth / observed drain rate, clamped to [1, 30]; no drain
    observed => the conservative default."""
    release = threading.Event()

    def stalled(x):
        release.wait(5)
        return {"y": np.asarray(x)}

    engine = InferenceEngine(stalled, (FEATURES,), buckets=(1,))
    batcher = MicroBatcher(engine, max_queue=4, max_wait_ms=0.0)
    server = ServingServer(engine, batcher, port=0, window_secs=0)
    try:
        # nothing completed yet: conservative default
        assert server.retry_after_s() == 5
        # fabricate a drain history: 40 completions over 2s = 20/s
        now = time.monotonic()
        server._drain_samples.append((now - 2.0, 0))
        engine.registry.counter("serve/completed").inc(40)
        engine.registry.gauge("serve/queue_depth").set(60)
        # 60 queued / ~20 per sec ~ 3s (the estimator's own clock read
        # makes the window a hair over 2s, so ceil may land on 4)
        assert server.retry_after_s() in (3, 4)
        engine.registry.gauge("serve/queue_depth").set(10_000)
        assert server.retry_after_s() == 30  # clamped
        engine.registry.gauge("serve/queue_depth").set(0)
        assert server.retry_after_s() == 1  # clamped from below
    finally:
        release.set()
        batcher.close()
        server.shutdown()


def test_http_429_and_503_carry_retry_after(serve_fn):
    """The backpressure statuses tell clients when to come back: 429 (queue
    full) and 503 (draining) carry Retry-After derived from the drain rate,
    in the header AND the structured body."""
    release = threading.Event()

    def stalled(x):
        release.wait(10)
        return {"y": np.asarray(x)}

    engine = InferenceEngine(stalled, (FEATURES,), buckets=(1,))
    batcher = MicroBatcher(engine, max_queue=1, max_wait_ms=0.0)
    server = ServingServer(engine, batcher, port=0, window_secs=0).start()
    x = np.zeros((1, FEATURES), np.float32)
    try:
        blocker = batcher.submit(x)  # occupies the worker
        time.sleep(0.05)
        filler = batcher.submit(x)  # fills the queue (max_queue=1)
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/v1/predict", {"instances": x.tolist()})
        assert err.value.code == 429
        retry_after = err.value.headers.get("Retry-After")
        assert retry_after is not None and int(retry_after) >= 1
        body = json.loads(err.value.read())
        assert body["error"]["code"] == "queue_full"
        assert body["error"]["retry_after_s"] == int(retry_after)

        # draining: same contract on the 503
        server.draining = True
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/v1/predict", {"instances": x.tolist()})
        assert err.value.code == 503
        assert int(err.value.headers.get("Retry-After")) >= 1
        assert json.loads(err.value.read())["error"]["code"] == "draining"
        server.draining = False
        release.set()
        blocker.result(10)
        filler.result(10)
    finally:
        release.set()
        server.shutdown()


# -- ephemeral port ----------------------------------------------------------


def test_bind_ephemeral_port_known_before_server(serve_fn):
    """bind_ephemeral gives the real port BEFORE the server (and therefore
    before the telemetry run header) exists; the server adopts the socket."""
    sock = bind_ephemeral("127.0.0.1", 0)
    port = sock.getsockname()[1]
    assert port > 0
    engine = InferenceEngine(serve_fn, (FEATURES,), buckets=(1,))
    engine.warmup()
    batcher = MicroBatcher(engine, max_wait_ms=1)
    server = ServingServer(
        engine, batcher, port=0, window_secs=0, sock=sock
    ).start()
    try:
        assert server.port == port
        health = _get(f"http://127.0.0.1:{port}/healthz")
        assert health["ok"]
    finally:
        server.shutdown()


# -- router ------------------------------------------------------------------


def test_router_candidate_ordering():
    """Healthy-lowest-backlog first; degraded only after every ok replica;
    draining and dead never routed."""
    router = FleetRouter([], port=0, window_secs=0)

    def rep(rid, status, queue, inflight=0, p99=None):
        r = ReplicaState(rid, f"http://127.0.0.1:{9000 + rid}")
        r.status = status
        r.queue_depth = queue
        r.inflight = inflight
        r.p99_ms = p99
        router._replicas[rid] = r
        return r

    rep(1, "ok", 5.0)
    rep(2, "ok", 1.0, inflight=1)
    rep(3, "degraded", 0.0)
    rep(4, "draining", 0.0)
    rep(5, "dead", 0.0)
    rep(6, "ok", 2.0, p99=10.0)
    order = [r.replica_id for r in router._candidates()]
    assert order == [2, 6, 1, 3]  # ok by backlog, degraded last
    router._httpd.server_close()


def test_router_round_trip_and_failover(serve_fn):
    """Predict through the router; kill one replica's listener; every
    subsequent request is re-dispatched onto the survivor — no accepted
    request is lost."""
    s1 = _server(serve_fn, replica_id=1)
    s2 = _server(serve_fn, replica_id=2)
    router = FleetRouter(
        [(1, s1.url), (2, s2.url)], port=0, window_secs=0,
        poll_interval_s=0.2,
    ).start()
    x = np.random.default_rng(0).normal(0, 1, (2, FEATURES)).astype(np.float32)
    try:
        status, body, headers = _post(
            router.url + "/v1/predict", {"instances": x.tolist()},
            headers={"x-request-id": "fleet-test-1"},
        )
        assert status == 200 and body["n"] == 2
        # the client's id survives the hop to the replica and back
        assert headers.get("x-request-id") == "fleet-test-1"
        health = _get(router.url + "/healthz")
        assert health["status"] == "ok" and health["live"] == 2

        s1.shutdown()  # replica 1 vanishes (listener closed)
        for _ in range(6):
            status, body, _ = _post(
                router.url + "/v1/predict", {"instances": x.tolist()}
            )
            assert status == 200
        router.poll_once()
        router.poll_once()  # dead after 2 consecutive failures
        health = _get(router.url + "/healthz")
        assert health["live"] == 1
        states = {r["replica"]: r["status"] for r in health["replicas"]}
        assert states[1] == "dead" and states[2] == "ok"
    finally:
        router.shutdown()
        s2.shutdown()


def test_router_sheds_with_retry_after_when_fleet_saturated(serve_fn):
    """Every replica saturated => the router sheds with its own 429 and the
    smallest Retry-After any replica advertised — explicit backpressure end
    to end, no unbounded queueing anywhere."""
    release = threading.Event()

    def stalled(x):
        release.wait(10)
        return {"y": np.asarray(x)}

    servers = []
    fillers = []
    x = np.zeros((1, FEATURES), np.float32)
    for rid in (1, 2):
        engine = InferenceEngine(stalled, (FEATURES,), buckets=(1,))
        batcher = MicroBatcher(engine, max_queue=1, max_wait_ms=0.0)
        server = ServingServer(
            engine, batcher, port=0, window_secs=0, replica_id=rid
        ).start()
        fillers.append(batcher.submit(x))  # worker busy
        time.sleep(0.05)
        fillers.append(batcher.submit(x))  # queue full
        servers.append(server)
    router = FleetRouter(
        [(1, servers[0].url), (2, servers[1].url)], port=0, window_secs=0
    ).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(router.url + "/v1/predict", {"instances": x.tolist()})
        assert err.value.code == 429
        assert int(err.value.headers.get("Retry-After")) >= 1
        body = json.loads(err.value.read())
        assert body["error"]["code"] == "fleet_saturated"
        assert router.counters()["shed"] == 1
    finally:
        release.set()
        for f in fillers:
            f.result(10)
        router.shutdown()
        for s in servers:
            s.shutdown()


def test_router_no_replicas_is_structured_503():
    router = FleetRouter([], port=0, window_secs=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(router.url + "/v1/predict", {"instances": [[0.0] * 6]})
        assert err.value.code == 503
        assert json.loads(err.value.read())["error"]["code"] == "no_replicas"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(router.url + "/healthz")
        assert err.value.code == 503  # a fleet of nothing is down
    finally:
        router.shutdown()


def test_router_routes_around_draining(serve_fn):
    """A draining replica (reported by its own /metrics status) stops
    receiving traffic while it finishes accepted work."""
    s1 = _server(serve_fn, replica_id=1)
    s2 = _server(serve_fn, replica_id=2)
    router = FleetRouter(
        [(1, s1.url), (2, s2.url)], port=0, window_secs=0
    ).start()
    x = np.zeros((1, FEATURES), np.float32)
    try:
        s1.draining = True  # flips its /metrics status to "draining"
        router.poll_once()
        for _ in range(5):
            status, _, _ = _post(
                router.url + "/v1/predict", {"instances": x.tolist()}
            )
            assert status == 200
        snap = {r["replica"]: r for r in router.metrics_snapshot()["replicas"]}
        assert snap[1]["status"] == "draining"
        assert snap[1]["routed"] == 0 and snap[2]["routed"] == 5
    finally:
        router.shutdown()
        s1.draining = False
        s1.shutdown()
        s2.shutdown()


# -- autoscaler --------------------------------------------------------------


def _snap(live=1, starting=0, degraded=0, queue=0.0, shed=0):
    return {
        "live": live,
        "starting": starting,
        "degraded": degraded,
        "queue_depth_total": queue,
        "shed_total": shed,
    }


def test_autoscaler_sustained_pressure_scales_up():
    clock = [0.0]
    a = Autoscaler(
        AutoscaleConfig(max_replicas=3, sustain=3, cooldown_s=10),
        clock=lambda: clock[0],
    )
    assert a.evaluate(_snap(queue=10.0)) is None
    assert a.evaluate(_snap(queue=10.0)) is None
    d = a.evaluate(_snap(queue=10.0))
    assert d is not None and d["action"] == "scale_up"
    assert d["from_replicas"] == 1 and d["to_replicas"] == 2
    assert d["reason"] == "queue_depth"
    # cooldown: pressure persists but no second decision inside the window
    clock[0] = 5.0
    for _ in range(5):
        assert a.evaluate(_snap(live=2, queue=20.0)) is None
    # past the cooldown the sustained streak fires on the next evaluation
    clock[0] = 20.0
    d = None
    for _ in range(a.config.sustain):
        d = d or a.evaluate(_snap(live=2, queue=20.0))
    assert d is not None and d["to_replicas"] == 3
    # max bound: never past max_replicas
    clock[0] = 60.0
    for _ in range(5):
        assert a.evaluate(_snap(live=3, queue=50.0)) is None


def test_autoscaler_counts_starting_capacity():
    """A spawn in progress is already the response to pressure — the scaler
    must not double-order."""
    a = Autoscaler(
        AutoscaleConfig(max_replicas=2, sustain=1, cooldown_s=0),
        clock=lambda: 0.0,
    )
    assert a.evaluate(_snap(live=1, starting=1, queue=100.0)) is None


def test_autoscaler_idle_scales_down_and_respects_min():
    clock = [0.0]
    a = Autoscaler(
        AutoscaleConfig(min_replicas=1, max_replicas=3, sustain=2,
                        cooldown_s=0),
        clock=lambda: clock[0],
    )
    assert a.evaluate(_snap(live=2, queue=0.0)) is None
    d = a.evaluate(_snap(live=2, queue=0.0))
    assert d["action"] == "scale_down" and d["reason"] == "idle"
    assert d["to_replicas"] == 1
    # at min: idle forever never goes below
    for _ in range(5):
        assert a.evaluate(_snap(live=1, queue=0.0)) is None


def test_autoscaler_slo_and_shed_signals():
    a = Autoscaler(
        AutoscaleConfig(sustain=2, cooldown_s=0), clock=lambda: 0.0
    )
    a.evaluate(_snap(degraded=1))
    d = a.evaluate(_snap(degraded=1))
    assert d["action"] == "scale_up" and d["reason"] == "slo_degraded"

    b = Autoscaler(
        AutoscaleConfig(sustain=2, cooldown_s=0), clock=lambda: 0.0
    )
    b.evaluate(_snap(shed=10))  # delta 10 vs initial 0
    d = b.evaluate(_snap(shed=20))
    assert d["action"] == "scale_up" and d["reason"] == "shed"


def test_autoscaler_dead_fleet_is_an_emergency():
    """Zero capacity bypasses the sustain counter AND the cooldown — a dead
    fleet must never stay dead because the scaler was being patient."""
    clock = [0.0]
    a = Autoscaler(
        AutoscaleConfig(min_replicas=2, max_replicas=4, sustain=5,
                        cooldown_s=30),
        clock=lambda: clock[0],
    )
    # a decision just fired (cooldown freshly armed) ...
    a._last_decision_t = 0.0
    clock[0] = 1.0
    # ... and then everything died: the emergency still fires, straight to
    # min_replicas (not by one)
    d = a.evaluate(_snap(live=0, queue=0.0))
    assert d["action"] == "scale_up" and d["reason"] == "no_capacity"
    assert d["from_replicas"] == 0 and d["to_replicas"] == 2


def test_autoscaler_config_validation():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscaleConfig(queue_high=1.0, queue_low=2.0)


# -- fault seam --------------------------------------------------------------


def test_sigkill_fault_spec_fires_on_request_site(monkeypatch):
    from tensorflowdistributedlearning_tpu.resilience import faults

    spec = faults.parse_fault_spec("sigkill@3")
    assert spec.site == faults.SITE_REQUEST and spec.at == 3
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append(sig))
    injector = faults.FaultInjector(spec)
    injector.fire(faults.SITE_REQUEST)
    injector.fire(faults.SITE_REQUEST)
    assert not kills
    injector.fire(faults.SITE_REQUEST)
    assert kills == [signal.SIGKILL]
    injector.fire(faults.SITE_REQUEST)  # count=1: fires exactly once
    assert kills == [signal.SIGKILL]


# -- ledger + report ---------------------------------------------------------


def test_fleet_scale_events_render_in_report(tmp_path):
    """The controller's ledger renders the fleet story: router counters,
    autoscale decisions, replica lifecycle — in text and JSON."""
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    workdir = str(tmp_path / "fleet")
    tel = Telemetry(workdir, run_info={"kind": "serve-fleet"})
    tel.event("replica_spawn", replica=1, pid=1)
    tel.event("replica_ready", replica=1, endpoint="http://x:1")
    tel.event(
        "fleet_scale", action="scale_up", from_replicas=1, to_replicas=2,
        reason="queue_depth", mean_queue_depth=7.5, shed_delta=0,
        slo_degraded_replicas=0, sustain=3,
    )
    tel.event("replica_exit", replica=2, rc=137, restarts=0)
    tel.event("replica_restart", replica=2, attempt=1, backoff_s=0.5)
    tel.event(
        "router_window", requests=100, routed=104, retries=4, shed=2,
        no_replica=0, replica_failures=1,
        per_replica_routed={"1": 60, "2": 40},
        fleet={"status": "ok", "live": 2, "starting": 0, "draining": 0,
               "dead": 0},
    )
    tel.close()
    rendered = report_workdir(workdir)
    assert "serving fleet router" in rendered
    assert "autoscale: 1 decision(s)" in rendered
    assert "scale_up: 1 -> 2 (queue_depth" in rendered
    assert "replica lifecycle: 1 spawn(s), 1 unplanned exit(s), 1 restart(s)" in rendered
    as_json = json.loads(report_workdir(workdir, as_json=True))
    sf = as_json["serve_fleet"]
    assert sf["router"]["shed"] == 2
    assert sf["autoscale"]["final_replicas"] == 2
    assert sf["replicas"]["restart"] == 1


def test_sentinel_fleet_gates():
    """check_fleet replays a committed fleet section: good numbers pass,
    a broken scaling floor / recompile / lost-request record fails."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from regression_sentinel import check_fleet

    good = {
        "fleet": {
            "replica_counts": {
                "1": {"replicas": {"1": {"recompiles_post_warmup": 0}}},
                "2": {"replicas": {"1": {"recompiles_post_warmup": 0},
                                   "2": {"recompiles_post_warmup": 0}}},
            },
            "scaling": {"2": {"speedup_vs_1": 1.85}},
            "saturation": {"shed_429": 100, "shed_with_retry_after": 100,
                           "errors_5xx": 0},
            "kill_soak": {"client_errors": 0, "converged": True},
        }
    }
    findings = check_fleet(good)
    assert findings and all(f["ok"] for f in findings)

    bad = json.loads(json.dumps(good))
    bad["fleet"]["scaling"]["2"]["speedup_vs_1"] = 1.2
    bad["fleet"]["replica_counts"]["2"]["replicas"]["2"][
        "recompiles_post_warmup"] = 1
    bad["fleet"]["kill_soak"]["client_errors"] = 3
    failed = {f["metric"] for f in check_fleet(bad) if not f["ok"]}
    assert failed == {
        "scaling.2.speedup_vs_1",
        "replica_post_warmup_recompiles",
        "kill_soak.client_errors",
    }
    # a record with no fleet section compares nothing (pre-fleet baselines)
    assert check_fleet({}) == []


# -- CLI surface -------------------------------------------------------------


def test_cli_serve_fleet_parser_defaults():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(["serve-fleet", "--artifact-dir", "d"])
    assert args.replicas == 2
    assert args.min_replicas == 1 and args.max_replicas == 4
    assert not args.no_autoscale
    assert args.replica_inject_fault is None
    args = build_parser().parse_args(
        ["serve", "--artifact-dir", "d", "--inject-fault", "sigkill@30"]
    )
    assert args.inject_fault == "sigkill@30"


def test_cli_serve_fleet_rejects_bad_fault_spec(capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main([
        "serve-fleet", "--artifact-dir", "d",
        "--replica-inject-fault", "nonsense",
    ])
    assert rc == 2
    assert "replica-inject-fault" in capsys.readouterr().err


# -- subprocess end-to-end ---------------------------------------------------


def _export_artifact(tmp_path, serve_fn):
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    directory = str(tmp_path / "artifact")
    serving_lib.export_serving_artifact(serve_fn, (1, FEATURES), directory)
    return directory


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


@pytest.mark.slow
def test_serve_port0_reports_bound_port(serve_fn, tmp_path):
    """`serve --port 0`: the ephemeral port lands on stdout AND in the run
    header ledger event — the contract fleet spawns and tests rely on."""
    artifact = _export_artifact(tmp_path, serve_fn)
    workdir = str(tmp_path / "wd")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu", "serve",
         "--artifact-dir", artifact, "--workdir", workdir,
         "--port", "0", "--window-secs", "0", "--buckets", "1", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_env(), text=True,
    )
    try:
        line = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline().strip()
            if line.startswith("{"):
                break
        header = json.loads(line)
        port = header["port"]
        assert port > 0
        assert header["serving"].endswith(f":{port}")
        health = _get(f"http://127.0.0.1:{port}/healthz")
        assert health["ok"]
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(30)
    assert rc == 0  # graceful drain
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    events = read_ledger(workdir)
    run_header = events[0]
    assert run_header["event"] == "run_header"
    assert run_header["port"] == port
    assert run_header["endpoint"].endswith(f":{port}")


@pytest.mark.slow
def test_fleet_sigkill_failover_converges(serve_fn, tmp_path):
    """The headline failover soak: two real replica subprocesses behind the
    router, one SIGKILLed mid-load via the fault seam — zero accepted
    requests lost, the dead replica restarted, traffic on both afterwards,
    and the whole story in the merged ledger."""
    from tensorflowdistributedlearning_tpu.obs import fleet as obs_fleet
    from tensorflowdistributedlearning_tpu.serve import (
        FleetConfig,
        FleetManager,
    )

    artifact = _export_artifact(tmp_path, serve_fn)
    workdir = str(tmp_path / "fleet")
    tel = Telemetry(workdir, run_info={"kind": "serve-fleet"})
    manager = FleetManager(
        FleetConfig(
            artifact_dir=artifact,
            workdir=workdir,
            buckets=(1, 4),
            max_wait_ms=1.0,
            window_secs=2.0,
            spawn_timeout_s=300.0,
            # the fault seam: replica 2's first launch dies (SIGKILL — no
            # drain, no goodbye) after its 25th answered request
            fault_specs={2: "sigkill@25"},
        ),
        telemetry=tel,
    )
    manager.start(2)
    router = FleetRouter(
        manager.endpoints, port=0, telemetry=tel, window_secs=0,
        poll_interval_s=0.2,
    ).start()
    x = np.random.default_rng(1).normal(0, 1, (1, FEATURES)).astype(np.float32)
    try:
        # soak: enough requests that the kill fires mid-stream (the 25th
        # answered request on replica 2 ~ the 50th overall under balance)
        statuses = []
        for _ in range(120):
            status, _, _ = _post(
                router.url + "/v1/predict", {"instances": x.tolist()}
            )
            statuses.append(status)
        assert statuses == [200] * 120, "an accepted request was lost"

        # convergence: the supervisor restarts replica 2 (clean relaunch —
        # the drill spec applies to the first launch only)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(manager.endpoints()) < 2:
            time.sleep(0.25)
        assert len(manager.endpoints()) == 2
        replicas = {r.replica_id: r for r in manager.replicas()}
        assert replicas[2].restarts == 1
        # the router re-admits the restarted replica within a poll or two
        deadline = time.monotonic() + 30
        while (
            time.monotonic() < deadline
            and router.fleet_snapshot()["live"] < 2
        ):
            router.poll_once()
            time.sleep(0.2)
        assert router.fleet_snapshot()["live"] == 2

        # the restarted replica takes traffic again
        routed_before = {
            r.replica_id: r.routed for r in router._replicas.values()
        }
        for _ in range(30):
            status, _, _ = _post(
                router.url + "/v1/predict", {"instances": x.tolist()}
            )
            assert status == 200
        routed_after = {
            r.replica_id: r.routed for r in router._replicas.values()
        }
        assert routed_after[2] > routed_before.get(2, 0)
    finally:
        router.shutdown()
        manager.shutdown()
        tel.close()

    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    events = read_ledger(workdir)
    kinds = [e["event"] for e in events]
    assert "replica_exit" in kinds and "replica_restart" in kinds
    exit_event = next(e for e in events if e["event"] == "replica_exit")
    assert exit_event["rc"] == 128 + signal.SIGKILL  # 137: killed, not drained

    # the merged fleet view covers controller + both replica ledgers, with
    # zero post-warmup recompiles on every replica
    ledgers = obs_fleet.discover_ledgers(workdir)
    assert {led.process_index for led in ledgers} >= {0, 1, 2}
    for led in ledgers:
        windows = [
            e for e in led.events if e.get("event") == "serve_window"
        ]
        for w in windows:
            assert w.get("recompiles_post_warmup", 0) == 0


@pytest.mark.slow
def test_serve_fleet_cli_end_to_end(serve_fn, tmp_path):
    """The serve-fleet CLI: comes up, answers through the router, reports
    aggregate health, drains the whole fleet on SIGTERM with rc 0."""
    artifact = _export_artifact(tmp_path, serve_fn)
    workdir = str(tmp_path / "fleet-cli")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
         "serve-fleet", "--artifact-dir", artifact, "--workdir", workdir,
         "--port", "0", "--replicas", "1", "--no-autoscale",
         "--window-secs", "2", "--buckets", "1", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_env(), text=True,
    )
    try:
        line = ""
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            line = proc.stdout.readline().strip()
            if line.startswith("{"):
                break
        header = json.loads(line)
        url = header["router"]
        assert header["replicas"][0]["replica"] == 1
        x = np.zeros((1, FEATURES), np.float32)
        status, body, _ = _post(url + "/v1/predict", {"instances": x.tolist()})
        assert status == 200 and body["n"] == 1
        health = _get(url + "/healthz")
        assert health["status"] == "ok" and health["live"] == 1
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(60)
    assert rc == 0
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    events = read_ledger(workdir)
    kinds = [e["event"] for e in events]
    assert "router_start" in kinds and "fleet_start" in kinds
