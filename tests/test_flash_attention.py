"""Fused block attention kernel (ops/flash_attention.py): exactness vs the XLA
oracle (causal and not, ragged final q block), gradient parity through the
custom VJP, VMEM-budget fallback, and the ViT integration switch. Off-TPU these
run the Pallas interpreter — the same kernel code the Mosaic path compiles."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.ops.flash_attention import flash_attention
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    attention_reference,
)


def _qkv(seed, b=2, t=64, h=2, d=16):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(0, 1, (b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_matches_oracle(causal):
    q, k, v = _qkv(0)
    out = flash_attention(q, k, v, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_ragged_final_q_block():
    # t=300 > _BLOCK_Q=256 forces a second, partial q tile
    q, k, v = _qkv(1, b=1, t=300, h=1, d=8)
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_oracle(causal):
    q, k, v = _qkv(2, t=32)
    w = jnp.asarray(
        np.random.default_rng(3).normal(0, 1, q.shape).astype(np.float32)
    )

    def loss_flash(q, k, v):
        return jnp.sum(w * flash_attention(q, k, v, causal=causal))

    def loss_ref(q, k, v):
        return jnp.sum(w * attention_reference(q, k, v, causal=causal))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-6
        )


def test_bf16_inputs():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(4, t=32))
    out = flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


def test_vmem_fallback_path(monkeypatch):
    # K/V bytes above the budget must route through the XLA oracle (still
    # exact); shrink the budget so a small shape triggers the fallback
    from tensorflowdistributedlearning_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "_VMEM_KV_LIMIT_BYTES", 1024)
    q, k, v = _qkv(5, b=1, t=64, h=1, d=16)
    out = fa.flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_vit_uses_fused_attention_when_enabled(monkeypatch):
    """use_fused_attention is a pure execution-path switch: identical params,
    matching outputs. The platform gate is patched open so the Pallas
    (interpreter) path actually runs on the CPU mesh — unpatched, the gate
    degrades the flag to XLA off-TPU and the check would be vacuous."""
    import tensorflowdistributedlearning_tpu.models.vit as vit_mod
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model

    monkeypatch.setattr(vit_mod, "_fused_platform_ok", lambda: True)

    base = ModelConfig(
        backbone="vit",
        num_classes=4,
        input_shape=(16, 16),
        input_channels=3,
        patch_size=4,
        embed_dim=32,
        vit_layers=1,
        num_heads=4,
        output_stride=None,
    )
    m_plain = build_model(base)
    m_fused = build_model(dataclasses.replace(base, use_fused_attention=True))
    x = jnp.asarray(
        np.random.default_rng(6).normal(0, 1, (2, 16, 16, 3)), jnp.float32
    )
    variables = m_plain.init(jax.random.PRNGKey(0), x, train=False)
    out_plain = m_plain.apply(variables, x, train=False)
    out_fused = m_fused.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_fused), np.asarray(out_plain), rtol=2e-5, atol=2e-5
    )


def test_fused_attention_seq_gate(monkeypatch):
    """Above ``_FUSED_MAX_SEQ`` the flag degrades to the XLA path: the gate
    sits at the measured ceiling (1024 under the device-dominated protocol —
    beyond it the kernel is unmeasured and the VMEM fallback applies), and
    this test pins the MACHINERY by patching the gate low and confirming the
    kernel is not dispatched above it."""
    import tensorflowdistributedlearning_tpu.models.vit as vit_mod
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.ops import flash_attention as fa

    monkeypatch.setattr(vit_mod, "_fused_platform_ok", lambda: True)
    monkeypatch.setattr(vit_mod, "_FUSED_MAX_SEQ", 8)

    def _must_not_dispatch(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("fused kernel dispatched above the seq gate")

    monkeypatch.setattr(fa, "flash_attention", _must_not_dispatch)

    cfg = ModelConfig(
        backbone="vit",
        num_classes=4,
        input_shape=(16, 16),
        input_channels=3,
        patch_size=4,  # 16 tokens + cls > the patched gate of 8
        embed_dim=32,
        vit_layers=1,
        num_heads=4,
        output_stride=None,
        use_fused_attention=True,
    )
    model = build_model(cfg)
    x = jnp.asarray(
        np.random.default_rng(7).normal(0, 1, (2, 16, 16, 3)), jnp.float32
    )
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    model.apply(variables, x, train=False)  # must not raise


def test_fused_seq_gate_counts_patch_tokens_not_prefix(monkeypatch):
    """The _FUSED_MAX_SEQ ceiling was measured in PATCH tokens: a model that
    prepends auxiliary tokens (cls/registers) declares them via
    ``num_prefix_tokens`` so a ceiling-sized patch grid does not fall back to
    XLA one token early (ADVICE round 5). This repo's ViT pools (no cls), so
    its sequence length IS the patch count — pinned by the t == gate case."""
    import tensorflowdistributedlearning_tpu.models.vit as vit_mod

    monkeypatch.setattr(vit_mod, "_fused_platform_ok", lambda: True)
    monkeypatch.setattr(vit_mod, "_FUSED_MAX_SEQ", 16)
    calls = []

    def _count(q, k, v):
        calls.append(q.shape)
        from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
            attention_reference,
        )

        return attention_reference(q, k, v)

    import tensorflowdistributedlearning_tpu.ops.flash_attention as fa

    monkeypatch.setattr(fa, "flash_attention", _count)

    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(0, 1, (2, 16, 32)), jnp.float32)  # t == gate
    attn = vit_mod.MultiHeadSelfAttention(32, 4, use_fused=True)
    variables = attn.init(jax.random.PRNGKey(0), x)
    calls.clear()  # init traced __call__ once too
    attn.apply(variables, x)
    assert len(calls) == 1  # t == ceiling dispatches (inclusive gate)

    # 16 patches + 1 prefix token: still within the PATCH ceiling
    x17 = jnp.asarray(rng.normal(0, 1, (2, 17, 32)), jnp.float32)
    attn_prefix = vit_mod.MultiHeadSelfAttention(
        32, 4, use_fused=True, num_prefix_tokens=1
    )
    v17 = attn_prefix.init(jax.random.PRNGKey(0), x17)
    calls.clear()
    attn_prefix.apply(v17, x17)
    assert len(calls) == 1  # the prefix token did not push it over

    # but 17 PATCH tokens (no prefix) is genuinely above the ceiling
    attn17 = vit_mod.MultiHeadSelfAttention(32, 4, use_fused=True)
    calls.clear()
    attn17.apply(v17, x17)
    assert calls == []  # fell back to XLA


def test_tpu_vit_presets_carry_the_measured_flip():
    """The attention verdict lives in the presets: ViT-family TPU presets
    ship with use_fused_attention=True (train-step tie, long-seq forward win
    under the device-dominated protocol; seq-gated in the dispatch)."""
    from tensorflowdistributedlearning_tpu.configs import PRESETS

    for name in ("vit_s16_imagenet", "vit_s16_moe_imagenet"):
        assert PRESETS[name].model.use_fused_attention, name
