"""Kaggle driver-helper tests: CSV parsing, RLE round-trip (including the empty
mask), coverage stratification classes (data/kaggle.py — the notebooks' data-prep
cells, SURVEY §2.1 C13)."""

import os

import numpy as np
import pytest
from PIL import Image

from tensorflowdistributedlearning_tpu.data import kaggle


def test_rle_roundtrip():
    rng = np.random.default_rng(0)
    mask = (rng.uniform(0, 1, (101, 101)) > 0.7).astype(np.uint8)
    rle = kaggle.rle_encode(mask)
    back = kaggle.rle_decode(rle, (101, 101))
    np.testing.assert_array_equal(mask, back)


def test_rle_empty_and_full():
    empty = np.zeros((4, 4), np.uint8)
    assert kaggle.rle_encode(empty) == ""
    np.testing.assert_array_equal(kaggle.rle_decode("", (4, 4)), empty)
    full = np.ones((4, 4), np.uint8)
    assert kaggle.rle_encode(full) == "1 16"
    np.testing.assert_array_equal(kaggle.rle_decode("1 16", (4, 4)), full)


def test_rle_is_column_major():
    mask = np.zeros((3, 3), np.uint8)
    mask[:, 0] = 1  # first column = first run in Kaggle's Fortran order
    assert kaggle.rle_encode(mask) == "1 3"


def test_csv_and_training_set(tmp_path):
    data = str(tmp_path / "train")
    os.makedirs(os.path.join(data, "images"))
    os.makedirs(os.path.join(data, "masks"))
    rng = np.random.default_rng(1)
    ids = [f"k{i}" for i in range(6)]
    coverages = [0.0, 0.0, 0.3, 0.5, 0.8, 1.0]
    for id_, cov in zip(ids, coverages):
        img = rng.integers(0, 255, (16, 16)).astype(np.uint8)
        Image.fromarray(img).save(os.path.join(data, "images", f"{id_}.png"))
        mask = np.zeros((16, 16), np.uint8)
        mask[: int(cov * 16), :] = 255
        Image.fromarray(mask).save(os.path.join(data, "masks", f"{id_}.png"))

    csv_path = str(tmp_path / "train.csv")
    with open(csv_path, "w") as f:
        f.write("id,rle_mask\n" + "\n".join(f"{i}," for i in ids))

    got_ids, classes = kaggle.load_tgs_training_set(data, csv_path)
    assert got_ids == sorted(ids)
    assert classes.shape == (6,)
    assert classes[0] == 0  # empty mask -> class 0
    assert classes[-1] == 10  # full mask -> class 10
    assert (np.diff(classes) >= 0).all()  # monotone in coverage


def test_training_set_missing_image_raises(tmp_path):
    data = str(tmp_path / "train")
    os.makedirs(os.path.join(data, "images"))
    os.makedirs(os.path.join(data, "masks"))
    csv_path = str(tmp_path / "train.csv")
    with open(csv_path, "w") as f:
        f.write("id,rle_mask\nghost,\n")
    with pytest.raises(FileNotFoundError, match="ghost"):
        kaggle.load_tgs_training_set(data, csv_path)


def test_depths(tmp_path):
    p = str(tmp_path / "depths.csv")
    with open(p, "w") as f:
        f.write("id,z\na,100\nb,250.5\n")
    d = kaggle.load_depths(p)
    assert d == {"a": 100.0, "b": 250.5}


def test_write_submission(tmp_path):
    masks = np.zeros((2, 4, 4, 1), np.float32)
    masks[1, :, 0, 0] = 1.0
    out = str(tmp_path / "sub.csv")
    kaggle.write_submission(out, ["x", "y"], masks)
    rows = kaggle.read_two_column_csv(out)
    assert rows == {"x": "", "y": "1 4"}
