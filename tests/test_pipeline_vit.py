"""Pipeline parallelism over REAL ViT transformer blocks (not toy stages): the
GPipe runner applied to a trained ViTClassifier's own block params must match
sequential layer application exactly, forward and backward — connecting
parallel/pipeline.py to the production model family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.models.vit import (
    pipeline_stage_fn,
    stack_vit_block_params,
)
from tensorflowdistributedlearning_tpu.parallel import pipeline as pp
from tensorflowdistributedlearning_tpu.parallel.mesh import make_mesh

CFG = ModelConfig(
    backbone="vit",
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    patch_size=4,
    embed_dim=32,
    vit_layers=4,  # = the pipeline's model-axis degree
    num_heads=4,
    output_stride=None,
)


@pytest.fixture(scope="module")
def vit_setup():
    model = build_model(CFG)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16, 16, 3), np.float32), train=False
    )
    stage = pipeline_stage_fn(CFG)
    stacked = stack_vit_block_params(variables["params"], CFG.vit_layers)
    rng = np.random.default_rng(9)
    # [M=8 microbatches, mb=2, T=16 tokens, D=32]
    tokens = jnp.asarray(rng.normal(0, 1, (8, 2, 16, 32)).astype(np.float32))
    return variables, stage, stacked, tokens


def _sequential(variables, stage, tokens):
    out = tokens
    for i in range(CFG.vit_layers):
        params_i = variables["params"][f"block{i + 1}"]
        out = jax.vmap(lambda mb, p=params_i: stage(p, mb))(out)
    return out


def test_pipelined_blocks_match_sequential(vit_setup):
    variables, stage, stacked, tokens = vit_setup
    mesh = make_mesh(8, model_parallel=4)
    run = pp.make_pipeline_fn(stage, mesh)
    out_pipe = run(stacked, tokens)
    out_seq = _sequential(variables, stage, tokens)
    np.testing.assert_allclose(
        np.asarray(out_pipe), np.asarray(out_seq), rtol=2e-5, atol=2e-5
    )


def test_pipelined_blocks_gradients_match(vit_setup):
    variables, stage, stacked, tokens = vit_setup
    mesh = make_mesh(8, model_parallel=4)
    run = pp.make_pipeline_fn(stage, mesh)
    w = jnp.asarray(
        np.random.default_rng(10).normal(0, 1, tokens.shape).astype(np.float32)
    )

    def loss_pipe(p):
        return jnp.sum(w * run(p, tokens))

    def loss_seq(p):
        out = tokens
        for i in range(CFG.vit_layers):
            p_i = jax.tree.map(lambda leaf, i=i: leaf[i], p)
            out = jax.vmap(lambda mb, pi=p_i: stage(pi, mb))(out)
        return jnp.sum(w * out)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )
