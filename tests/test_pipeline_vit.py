"""Pipeline parallelism over REAL ViT transformer blocks (not toy stages): the
GPipe runner applied to a trained ViTClassifier's own block params must match
sequential layer application exactly, forward and backward — connecting
parallel/pipeline.py to the production model family."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.models.vit import (
    pipeline_stage_fn,
    stack_vit_block_params,
)
from tensorflowdistributedlearning_tpu.parallel import pipeline as pp
from tensorflowdistributedlearning_tpu.parallel.mesh import make_mesh

CFG = ModelConfig(
    backbone="vit",
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    patch_size=4,
    embed_dim=32,
    vit_layers=4,  # = the pipeline's model-axis degree
    num_heads=4,
    output_stride=None,
)


@pytest.fixture(scope="module")
def vit_setup():
    model = build_model(CFG)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16, 16, 3), np.float32), train=False
    )
    stage = pipeline_stage_fn(CFG)
    stacked = stack_vit_block_params(variables["params"], CFG.vit_layers)
    rng = np.random.default_rng(9)
    # [M=8 microbatches, mb=2, T=16 tokens, D=32]
    tokens = jnp.asarray(rng.normal(0, 1, (8, 2, 16, 32)).astype(np.float32))
    return variables, stage, stacked, tokens


def _sequential(variables, stage, tokens):
    out = tokens
    for i in range(CFG.vit_layers):
        params_i = variables["params"][f"block{i + 1}"]
        out = jax.vmap(lambda mb, p=params_i: stage(p, mb))(out)
    return out


def test_pipelined_blocks_match_sequential(vit_setup):
    variables, stage, stacked, tokens = vit_setup
    mesh = make_mesh(8, model_parallel=4)
    run = pp.make_pipeline_fn(stage, mesh)
    out_pipe = run(stacked, tokens)
    out_seq = _sequential(variables, stage, tokens)
    np.testing.assert_allclose(
        np.asarray(out_pipe), np.asarray(out_seq), rtol=2e-5, atol=2e-5
    )


def test_pipelined_blocks_gradients_match(vit_setup):
    variables, stage, stacked, tokens = vit_setup
    mesh = make_mesh(8, model_parallel=4)
    run = pp.make_pipeline_fn(stage, mesh)
    w = jnp.asarray(
        np.random.default_rng(10).normal(0, 1, tokens.shape).astype(np.float32)
    )

    def loss_pipe(p):
        return jnp.sum(w * run(p, tokens))

    def loss_seq(p):
        out = tokens
        for i in range(CFG.vit_layers):
            p_i = jax.tree.map(lambda leaf, i=i: leaf[i], p)
            out = jax.vmap(lambda mb, pi=p_i: stage(pi, mb))(out)
        return jnp.sum(w * out)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )


# -- trainable strategy (round-2 VERDICT #6): pipeline_parallel in fit() ------


def _train_state(cfg, tcfg):
    from tensorflowdistributedlearning_tpu.train import (
        create_train_state,
        make_optimizer,
    )

    model = build_model(cfg)
    return create_train_state(
        model,
        make_optimizer(tcfg),
        jax.random.PRNGKey(1),
        np.zeros((1, *cfg.input_shape, cfg.input_channels), np.float32),
    )


def test_pipeline_train_step_matches_plain_step():
    """ONE pipeline-parallel update (dp=2 x stages=4, grouped 1 block/stage)
    equals the plain data-parallel update on the same global batch: same loss,
    same updated params — the optimizer (SGD + weight decay) rides state.tx
    identically through both execution strategies."""
    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.data import synthetic_batches
    from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train import pipeline_step as pp_step
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        compute_metrics,
    )

    tcfg = TrainConfig(optimizer="sgd", lr=0.05, weight_decay=1e-3)
    task = ClassificationTask()
    batch = next(
        synthetic_batches(
            "classification", 8, seed=5, input_shape=(16, 16), num_classes=4
        )
    )

    plain_mesh = mesh_lib.make_mesh(8)
    state_a = mesh_lib.replicate(_train_state(CFG, tcfg), plain_mesh)
    plain_step = step_lib.make_train_step(plain_mesh, task, donate=False)
    state_a, metrics_a = plain_step(state_a, mesh_lib.shard_batch(batch, plain_mesh))

    pp_mesh = mesh_lib.make_mesh(8, model_parallel=4)
    state_b = mesh_lib.replicate(_train_state(CFG, tcfg), pp_mesh)
    pipe_step = pp_step.make_train_step_pipeline(
        pp_mesh, task, CFG, microbatches=4, donate=False
    )
    state_b, metrics_b = pipe_step(state_b, mesh_lib.shard_batch(batch, pp_mesh))

    assert compute_metrics(metrics_a)["loss"] == pytest.approx(
        compute_metrics(metrics_b)["loss"], rel=1e-4
    )
    for a, b in zip(
        jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_fit_pipeline_parallel_trains_end_to_end(tmp_path):
    """TrainConfig.pipeline_parallel=4 trains a ViT through fit(): loss is
    finite and decreases over synthetic steps, checkpoints land, and the
    canonical param tree restores into the PLAIN model (strategies are
    checkpoint-interchangeable)."""
    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        CFG,
        TrainConfig(
            optimizer="adam",
            lr=1e-3,
            seed=0,
            pipeline_parallel=4,
            pipeline_microbatches=4,
            checkpoint_every_steps=4,
        ),
    )
    result = trainer.fit(batch_size=8, steps=4)
    assert result.steps == 4
    assert np.isfinite(result.final_metrics["loss"])
    assert "metrics/top1" in result.final_metrics

    # the exported state loads into a plain (sequential) ViT forward
    serve = trainer.serving_fn()
    out = serve(np.zeros((2, 16, 16, 3), np.float32))
    assert np.asarray(out["probabilities"]).shape == (2, 4)


def test_pipeline_config_validation(tmp_path):
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    # non-ViT backbone
    with pytest.raises(ValueError, match="backbone='vit'"):
        ClassifierTrainer(
            str(tmp_path),
            None,
            ModelConfig(
                num_classes=4,
                input_shape=(16, 16),
                input_channels=3,
                n_blocks=(1, 1, 1),
                base_depth=8,
                width_multiplier=0.0625,
                output_stride=None,
            ),
            TrainConfig(pipeline_parallel=4),
        )
    # stages must divide the layer count
    with pytest.raises(ValueError, match="not divisible"):
        ClassifierTrainer(
            str(tmp_path),
            None,
            dataclasses.replace(CFG, vit_layers=6),
            TrainConfig(pipeline_parallel=4),
        )
    # combining strategies is rejected at config time
    with pytest.raises(ValueError, match="cannot combine"):
        TrainConfig(pipeline_parallel=2, model_parallel=2)
    # microbatch floor
    with pytest.raises(ValueError, match="microbatch"):
        TrainConfig(pipeline_parallel=4, pipeline_microbatches=2)
