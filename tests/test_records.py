"""TFRecord streaming (native/records.cc + data/records.py): framing round-trip,
native-vs-Python reader parity, crc corruption detection, shuffle semantics,
blob decoding, and the classification stream feeding the fit-style batch shape."""

import os
import struct

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import records as rec
from tensorflowdistributedlearning_tpu.native import loader as native_loader


def _payloads(n=20):
    return [f"record-{i:03d}".encode() * (i + 1) for i in range(n)]


def test_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    data = _payloads()
    rec.write_records(path, data)
    assert list(rec.read_records(path)) == data


def test_masked_crc_is_tfrecord_standard():
    # crc32c("") == 0 -> masked 0xa282ead8; crc32c of 9 x 0x00 bytes is the
    # classic Castagnoli test vector family
    assert rec.masked_crc(b"") == 0xA282EAD8
    # crc32c("123456789") == 0xE3069283 (public test vector)
    crc = rec._crc32c(b"123456789")
    assert crc == 0xE3069283


def test_native_reader_matches_python(tmp_path):
    paths = []
    for s in range(3):
        p = str(tmp_path / f"s{s}.tfrecord")
        rec.write_records(p, [f"{s}-{i}".encode() for i in range(7)])
        paths.append(p)
    got = sorted(rec.RecordStream(paths, shuffle_buffer=1, seed=0))
    want = sorted(b for p in paths for b in rec.read_records(p))
    assert got == want


def test_shuffle_buffer_changes_order_keeps_multiset(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    data = _payloads(50)
    rec.write_records(path, data)
    plain = list(rec.RecordStream([path], shuffle_buffer=1, seed=0))
    shuffled = list(rec.RecordStream([path], shuffle_buffer=16, seed=0))
    assert sorted(plain) == sorted(shuffled) == sorted(data)
    assert plain == data  # buffer 1 = file order (single shard)
    assert shuffled != data  # buffer >1 actually shuffles


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "bad.tfrecord")
    rec.write_records(path, _payloads(5))
    raw = bytearray(open(path, "rb").read())
    raw[20] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError):
        list(rec.RecordStream([path], verify_crc=True))


def test_decode_image_blobs_matches_files(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    blobs, paths = [], []
    for i in range(4):
        arr = rng.uniform(0, 255, (40 + i, 30, 3)).astype(np.uint8)
        p = str(tmp_path / f"{i}.png")
        Image.fromarray(arr).save(p)
        paths.append(p)
        blobs.append(open(p, "rb").read())
    via_files = native_loader.decode_image_batch(paths, 32, 32, 3)
    via_blobs = native_loader.decode_image_blobs(blobs, (32, 32), 3)
    np.testing.assert_allclose(via_blobs, via_files, atol=1e-6)


def test_classification_stream_end_to_end(tmp_path):
    rng = np.random.default_rng(1)
    images = [rng.uniform(0, 255, (32, 32, 3)).astype(np.uint8) for _ in range(10)]
    labels = list(rng.integers(0, 3, 10))
    paths = rec.write_classification_shards(
        str(tmp_path), images, labels, shards=2
    )
    assert len(paths) == 2 and all(os.path.isfile(p) for p in paths)

    ds = rec.ClassificationRecords(str(tmp_path), image_shape=(32, 32), channels=3)
    batches = list(ds.batches(4, seed=0, repeat=True, steps=3))
    assert len(batches) == 3
    for b in batches:
        assert b["images"].shape == (4, 32, 32, 3)
        assert b["images"].dtype == np.float32
        assert b["labels"].shape == (4,) and b["labels"].dtype == np.int32
        assert set(np.unique(b["labels"])) <= set(range(3))

    # eval mode: one ordered pass; the final batch is padded to full size with
    # valid=0 rows so every process can run fixed-shape eval steps
    eval_batches = list(ds.batches(4, repeat=False))
    assert all(b["images"].shape == (4, 32, 32, 3) for b in eval_batches)
    assert sum(int(b["valid"].sum()) for b in eval_batches) == 10

    # pad_to_batches extends with fully-invalid batches (multi-host equal-step
    # contract); valid count is unchanged
    padded = list(ds.batches(4, repeat=False, pad_to_batches=5))
    assert len(padded) == 5
    assert sum(int(b["valid"].sum()) for b in padded) == 10

    # label range validation
    ds_strict = rec.ClassificationRecords(
        str(tmp_path), image_shape=(32, 32), channels=3, num_classes=2
    )
    with pytest.raises(ValueError, match="label out of range"):
        list(ds_strict.batches(4, repeat=False))


def test_record_payload_codec():
    payload = rec.encode_classification_record(7, b"\x89PNGxyz")
    label, img = rec.decode_classification_record(payload)
    assert label == 7 and img == b"\x89PNGxyz"
    assert struct.unpack("<i", payload[:4])[0] == 7


def test_repeat_stream_smaller_than_batch_still_emits(tmp_path):
    """Regression (advisor round 2): a repeat-mode stream over a dataset with
    fewer records than batch_size used to reset its partial batch each epoch and
    spin forever. Partial batches now carry across epoch boundaries, so batches
    span epochs and every record is used."""
    rng = np.random.default_rng(2)
    images = [rng.uniform(0, 255, (8, 8, 3)).astype(np.uint8) for _ in range(3)]
    rec.write_classification_shards(str(tmp_path), images, [0, 1, 2], shards=1)
    ds = rec.ClassificationRecords(str(tmp_path), image_shape=(8, 8), channels=3)
    batches = list(ds.batches(4, seed=0, repeat=True, steps=3))
    assert len(batches) == 3
    # 3 batches x 4 rows = 12 rows = 4 full epochs of the 3-record dataset
    all_labels = np.concatenate([b["labels"] for b in batches])
    assert sorted(all_labels.tolist()) == sorted([0, 1, 2] * 4)
    assert all(b["valid"].all() for b in batches)


def test_count_records_detects_truncated_final_record(tmp_path):
    """Regression (advisor round 2): count_records seeks over payloads, and a
    seek past EOF silently succeeds — a shard truncated mid-record must raise,
    not be counted as whole."""
    path = str(tmp_path / "trunc.tfrecord")
    rec.write_records(path, _payloads(3))
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-5])  # cut into the final record's body
    with pytest.raises(ValueError, match="truncated record body"):
        rec.count_records([path])


def test_native_next_on_closed_handle_is_lifecycle_error():
    """Regression (advisor round 2): tfdl_rec_next on an unknown/closed handle
    returns the dedicated -3 code, not the -1 corruption code."""
    lib = rec._records_lib()
    if lib is None:
        pytest.skip("native records library unavailable")
    import ctypes

    data = ctypes.POINTER(ctypes.c_uint8)()
    length = ctypes.c_uint64()
    assert lib.tfdl_rec_next(999999, ctypes.byref(data), ctypes.byref(length)) == -3


def test_fit_trains_from_record_shards(tmp_path):
    """ClassifierTrainer streams {data_dir}/train-*.tfrecord through the native
    record reader + blob decoder end to end."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    rng = np.random.default_rng(2)
    images = [rng.uniform(0, 255, (16, 16, 3)).astype(np.uint8) for _ in range(12)]
    labels = list(rng.integers(0, 4, 12))
    rec.write_classification_shards(str(tmp_path / "data"), images, labels, shards=2)

    trainer = ClassifierTrainer(
        str(tmp_path / "model"),
        str(tmp_path / "data"),
        ModelConfig(
            num_classes=4,
            input_shape=(16, 16),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(seed=0, checkpoint_every_steps=100),
    )
    result = trainer.fit(batch_size=8, steps=2)
    assert result.steps == 2
    assert np.isfinite(result.final_metrics["loss"])


def test_eval_holdout_fraction_partitions_train_shards(tmp_path, caplog):
    """Round-2 VERDICT weak #6: with record shards and no val split, best-
    checkpoint selection used to run silently on train data. With
    eval_holdout_fraction set, the last shards become a held-out val split
    (train excludes them); without it, a loud warning fires."""
    import logging

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    rng = np.random.default_rng(3)
    images = [rng.uniform(0, 255, (16, 16, 3)).astype(np.uint8) for _ in range(16)]
    labels = list(rng.integers(0, 4, 16))
    rec.write_classification_shards(str(tmp_path / "data"), images, labels, shards=4)
    mcfg = ModelConfig(
        num_classes=4,
        input_shape=(16, 16),
        input_channels=3,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.0625,
        output_stride=None,
    )

    held = ClassifierTrainer(
        str(tmp_path / "m1"),
        str(tmp_path / "data"),
        mcfg,
        TrainConfig(seed=0, checkpoint_every_steps=100, eval_holdout_fraction=0.25),
    )
    train_ds = held._open_records("train")
    val_ds = held._open_records("val")
    assert len(train_ds.paths) == 3 and len(val_ds.paths) == 1
    assert set(train_ds.paths).isdisjoint(val_ds.paths)
    with caplog.at_level(logging.WARNING):
        result = held.fit(batch_size=8, steps=2)
    assert np.isfinite(result.final_metrics["loss"])
    assert not any("overestimate" in r.message for r in caplog.records)

    caplog.clear()
    plain = ClassifierTrainer(
        str(tmp_path / "m2"),
        str(tmp_path / "data"),
        mcfg,
        TrainConfig(seed=0, checkpoint_every_steps=100),
    )
    with caplog.at_level(logging.WARNING):
        plain.fit(batch_size=8, steps=2)
    assert any("overestimate" in r.message for r in caplog.records)

    # holding out every shard is a config error, caught before training
    with pytest.raises(ValueError, match="leaving none to train"):
        ClassifierTrainer(
            str(tmp_path / "m3"),
            str(tmp_path / "data"),
            mcfg,
            TrainConfig(eval_holdout_fraction=0.99),
        ).fit(batch_size=8, steps=1)


def test_python_fallback_reader_matches_native(tmp_path, monkeypatch):
    """With the native library forced unavailable, RecordStream's pure-Python
    path (shuffle pool included) yields the same multiset — the documented
    no-toolchain fallback actually exercised."""
    from tensorflowdistributedlearning_tpu.data import records as records_mod

    paths = []
    for s in range(2):
        p = str(tmp_path / f"s{s}.tfrecord")
        rec.write_records(p, [f"{s}-{i}".encode() for i in range(9)])
        paths.append(p)
    native = sorted(rec.RecordStream(paths, shuffle_buffer=4, seed=1))
    monkeypatch.setattr(records_mod, "_records_lib", lambda: None)
    fallback_plain = list(rec.RecordStream(paths, shuffle_buffer=1, seed=1))
    fallback_shuffled = sorted(rec.RecordStream(paths, shuffle_buffer=4, seed=1))
    assert sorted(fallback_plain) == fallback_shuffled == native
    assert len(fallback_plain) == 18
