"""XPlane reader tests against hand-encoded wire-format fixtures (no
TensorFlow: the parser IS the point — tensorflowdistributedlearning_tpu/utils/xplane.py reads
jax.profiler's *.xplane.pb without the TensorBoard dependency)."""

import os

import pytest

from tensorflowdistributedlearning_tpu.utils import xplane


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field(number: int, wire: int, payload: bytes) -> bytes:
    return _varint((number << 3) | wire) + payload


def _bytes_field(number: int, payload: bytes) -> bytes:
    return _field(number, 2, _varint(len(payload)) + payload)


def _varint_field(number: int, value: int) -> bytes:
    return _field(number, 0, _varint(value))


def _event(metadata_id: int, duration_ps: int, occurrences: int = 1) -> bytes:
    body = _varint_field(1, metadata_id) + _varint_field(3, duration_ps)
    if occurrences != 1:
        body += _varint_field(5, occurrences)
    return body


def _event_metadata_entry(meta_id: int, name: str) -> bytes:
    meta = _varint_field(1, meta_id) + _bytes_field(2, name.encode())
    entry = _varint_field(1, meta_id) + _bytes_field(2, meta)
    return entry


def make_xspace(tmp_path, plane_name="/device:TPU:0 (pid 1)", events=None,
                lines=None):
    """Serialize a one-plane XSpace. Either ``events`` = [(op, duration_ps, n)]
    for a single unnamed line, or ``lines`` = {line_name: [(op, dur, n)]}."""
    if lines is None:
        lines = {"": events or []}
    metadata = b""
    next_id = 1
    ids = {}
    for line_events in lines.values():
        for name, _, _ in line_events:
            if name not in ids:
                ids[name] = next_id
                metadata += _bytes_field(4, _event_metadata_entry(next_id, name))
                next_id += 1
    line_bufs = b""
    for line_name, line_events in lines.items():
        body = _varint_field(1, 7)
        if line_name:
            body += _bytes_field(2, line_name.encode())
        for name, dur, n in line_events:
            body += _bytes_field(4, _event(ids[name], dur, n))
        line_bufs += _bytes_field(3, body)
    plane = (
        _varint_field(1, 1)
        + _bytes_field(2, plane_name.encode())
        + metadata
        + line_bufs
    )
    space = _bytes_field(1, plane)
    run_dir = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(run_dir)
    path = run_dir / "host.xplane.pb"
    path.write_bytes(space)
    return str(tmp_path)


def test_op_breakdown_aggregates_and_sorts(tmp_path):
    logdir = make_xspace(
        tmp_path,
        events=[
            ("fusion.123", 2_000_000, 4),      # 0.002 ms
            ("convolution.5", 10_000_000, 2),  # 0.01 ms
        ],
    )
    rows = xplane.op_breakdown(logdir)
    assert [r.name for r in rows] == ["convolution.5", "fusion.123"]
    assert rows[0].total_ms == pytest.approx(0.01)
    assert rows[0].occurrences == 2
    assert rows[0].fraction == pytest.approx(10 / 12, abs=1e-3)


def test_plane_filter_excludes_host(tmp_path):
    logdir = make_xspace(
        tmp_path, plane_name="/host:CPU", events=[("python_thread", 5_000_000, 1)]
    )
    assert xplane.op_breakdown(logdir, plane_filter="") != []
    assert xplane.op_breakdown(logdir, plane_filter="TPU") == []
    assert xplane.plane_names(logdir) == ["/host:CPU"]


def test_grouped_breakdown_buckets():
    rows = [
        xplane.OpTime("convolution.9", 5.0, 1, 0.5),
        xplane.OpTime("loop_fusion.2", 3.0, 1, 0.3),
        xplane.OpTime("reduce.7", 1.0, 1, 0.1),
        xplane.OpTime("weird-op", 1.0, 1, 0.1),
    ]
    groups = xplane.grouped_breakdown(rows)
    assert groups["conv"] == 5.0
    assert groups["fusion(elementwise/bn)"] == 3.0
    assert groups["reduce"] == 1.0
    assert groups["other"] == 1.0


def test_grouped_breakdown_tags_quant_and_fused_kernels():
    """The Pallas quant/fused kernels show up in device traces under their
    kernel function names; the roofline classifier must fold the int8
    matmul/conv into the MXU compute buckets and the fused heads into the
    elementwise-fusion bucket, not ``other``."""
    rows = [
        xplane.OpTime("_qmm_kernel.4", 6.0, 2, 0.6),
        xplane.OpTime("_qconv_kernel.2", 3.0, 1, 0.3),
        xplane.OpTime("_sigmoid_mask_kernel.1", 1.0, 1, 0.1),
        xplane.OpTime("_fused_bias_act_kernel.3", 0.5, 1, 0.05),
    ]
    groups = xplane.grouped_breakdown(rows)
    assert groups["matmul"] == 6.0
    assert groups["conv"] == 3.0
    assert groups["fusion(elementwise/bn)"] == 1.5
    assert "other" not in groups
    assert xplane.classify_bucket("_qmm_kernel.4") == "matmul"
    assert xplane.classify_bucket("_qconv_kernel.2") == "conv"


def test_grouped_breakdown_splits_collectives_from_compute():
    """Cross-chip communication is its own bucket — all-reduce/all-gather/
    reduce-scatter/collective-permute time must NOT fold into the generic
    reduce bucket (the "slow network" half of straggler attribution)."""
    rows = [
        xplane.OpTime("all-reduce.1", 2.0, 4, 0.2),
        xplane.OpTime("all-gather.3", 1.0, 2, 0.1),
        xplane.OpTime("reduce-scatter.2", 1.5, 2, 0.15),
        xplane.OpTime("collective-permute.5", 0.5, 1, 0.05),
        xplane.OpTime("reduce.11", 1.0, 1, 0.1),
        xplane.OpTime("convolution.9", 4.0, 1, 0.4),
    ]
    groups = xplane.grouped_breakdown(rows)
    assert groups["collectives"] == 5.0
    assert groups["reduce"] == 1.0
    assert groups["conv"] == 4.0


def test_nested_lines_do_not_double_count(tmp_path):
    """Device planes nest timelines (Steps > XLA Modules > XLA Ops): the
    auto line filter must aggregate the op-level line ONLY, not re-count the
    whole step through its enclosing module/step events."""
    logdir = make_xspace(
        tmp_path,
        lines={
            "Steps": [("step_42", 12_000_000, 1)],
            "XLA Modules": [("jit_step", 12_000_000, 1)],
            "XLA Ops": [
                ("convolution.1", 8_000_000, 10),
                ("fusion.7", 4_000_000, 20),
            ],
        },
    )
    rows = xplane.op_breakdown(logdir)
    assert {r.name for r in rows} == {"convolution.1", "fusion.7"}
    assert sum(r.total_ms for r in rows) == pytest.approx(0.012)
    assert rows[0].fraction == pytest.approx(8 / 12, abs=1e-3)
    # explicit line filter overrides the auto selection
    module_rows = xplane.op_breakdown(logdir, line_filter="Modules")
    assert [r.name for r in module_rows] == ["jit_step"]


def test_host_planes_survive_unfiltered_aggregation(tmp_path):
    """plane_filter='' promises host threads included: the per-plane auto line
    filter must restrict only planes that HAVE an op-level line, not starve
    flat host planes because some other plane has one."""
    # two separate captures in one logdir: a device plane and a host plane
    make_xspace(
        tmp_path / "a",
        plane_name="/device:TPU:0",
        lines={
            "XLA Modules": [("jit_step", 9_000_000, 1)],
            "XLA Ops": [("convolution.1", 6_000_000, 3)],
        },
    )
    make_xspace(
        tmp_path / "b",
        plane_name="/host:CPU",
        lines={"thread/7": [("python_decode", 2_000_000, 5)]},
    )
    import shutil

    merged = tmp_path / "merged" / "plugins" / "profile" / "run1"
    os.makedirs(merged)
    shutil.copy(
        tmp_path / "a" / "plugins" / "profile" / "run1" / "host.xplane.pb",
        merged / "a.xplane.pb",
    )
    shutil.copy(
        tmp_path / "b" / "plugins" / "profile" / "run1" / "host.xplane.pb",
        merged / "b.xplane.pb",
    )
    rows = xplane.op_breakdown(str(tmp_path / "merged"), plane_filter="")
    names = {r.name for r in rows}
    assert "convolution.1" in names       # device op line kept
    assert "python_decode" in names       # host plane NOT starved
    assert "jit_step" not in names        # device module line still excluded


def test_missing_logdir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        xplane.op_breakdown(str(tmp_path))


def test_user_substring_filter_skips_async_lines(tmp_path):
    """A user-supplied line_filter that substring-matches BOTH the op line and
    the overlapping 'Async XLA Ops' line (e.g. --line Ops) must not fold the
    async copy spans in through the side door — they overlap compute and
    corrupt every fraction (ADVICE round 5). Naming Async explicitly is the
    deliberate opt-in that still aggregates them."""
    logdir = make_xspace(
        tmp_path,
        lines={
            "XLA Ops": [("convolution.1", 8_000_000, 10)],
            "Async XLA Ops": [("copy-start.5", 56_000_000, 40)],
        },
    )
    # substring filter matching both lines: async skipped
    rows = xplane.op_breakdown(logdir, line_filter="Ops")
    assert [r.name for r in rows] == ["convolution.1"]
    assert rows[0].fraction == pytest.approx(1.0)
    # a filter that matches ONLY the async line: still skipped (it does not
    # name Async, so the user has not opted into overlap-corrupted sums)
    assert xplane.op_breakdown(logdir, line_filter="nc XLA") == []
    # naming Async explicitly is the opt-in
    async_rows = xplane.op_breakdown(logdir, line_filter="Async")
    assert [r.name for r in async_rows] == ["copy-start.5"]
    # exact-name behavior is unchanged
    exact = xplane.op_breakdown(logdir, line_filter="XLA Ops")
    assert [r.name for r in exact] == ["convolution.1"]
