"""Streaming classification pipeline + fit() loop tests: ImageFolder scanning,
batch streams, end-to-end preset training from disk via the CLI, resume, and
synthetic fallback (VERDICT r1 #2: the ImageNet/classification presets must be
actually trainable)."""

import dataclasses
import json
import os

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import imagefolder

SHAPE = (16, 16)
N_CLASSES = 4
PER_CLASS = 8


@pytest.fixture(scope="module")
def folder(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("imagefolder"))
    imagefolder.write_synthetic_imagefolder(
        os.path.join(root, "train"), N_CLASSES, PER_CLASS, SHAPE, channels=3
    )
    imagefolder.write_synthetic_imagefolder(
        os.path.join(root, "val"), N_CLASSES, 3, SHAPE, channels=3, seed=1
    )
    return root


def test_imagefolder_scan(folder):
    ds = imagefolder.ImageFolder(os.path.join(folder, "train"), SHAPE, channels=3)
    assert len(ds) == N_CLASSES * PER_CLASS
    assert ds.num_classes == N_CLASSES
    assert sorted(set(ds.labels.tolist())) == list(range(N_CLASSES))
    # labels follow sorted class-dir order
    assert ds.class_names == [f"class{k:03d}" for k in range(N_CLASSES)]


def test_imagefolder_shard_disjoint_cover(folder):
    ds = imagefolder.ImageFolder(os.path.join(folder, "train"), SHAPE, channels=3)
    shards = [ds.shard(i, 3) for i in range(3)]
    paths = [p for s in shards for p in s.paths]
    assert sorted(paths) == sorted(ds.paths)
    assert len(set(paths)) == len(ds.paths)


def test_train_batches_stream(folder):
    ds = imagefolder.ImageFolder(os.path.join(folder, "train"), SHAPE, channels=3)
    batches = list(imagefolder.train_batches(ds, 8, seed=0, steps=3))
    assert len(batches) == 3
    for b in batches:
        assert b["images"].shape == (8, *SHAPE, 3)
        assert b["images"].dtype == np.float32
        assert b["labels"].shape == (8,)
        # normalized: not raw [0,1] pixels
        assert b["images"].min() < -0.1


def test_eval_batches_counts_every_example_once(folder):
    ds = imagefolder.ImageFolder(os.path.join(folder, "val"), SHAPE, channels=3)
    n = len(ds)
    total_valid = 0
    for b in imagefolder.eval_batches(ds, 5):
        assert b["images"].shape[0] == 5
        total_valid += int(b["valid"].sum())
    assert total_valid == n


def test_eval_batches_forced_num_batches(folder):
    ds = imagefolder.ImageFolder(os.path.join(folder, "val"), SHAPE, channels=3)
    batches = list(imagefolder.eval_batches(ds, 5, num_batches=7))
    assert len(batches) == 7
    assert sum(int(b["valid"].sum()) for b in batches) == len(ds)


@pytest.fixture(scope="module")
def fitted(folder, tmp_path_factory):
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    model_dir = str(tmp_path_factory.mktemp("fit_model"))
    trainer = ClassifierTrainer(
        model_dir,
        folder,
        ModelConfig(
            num_classes=N_CLASSES,
            input_shape=SHAPE,
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(seed=0, checkpoint_every_steps=2, train_log_every_steps=2),
    )
    result = trainer.fit(batch_size=8, steps=4)
    return trainer, result, model_dir


def test_fit_end_to_end_from_disk(fitted):
    _, result, model_dir = fitted
    assert result.steps == 4
    assert set(result.final_metrics) >= {"loss", "metrics/top1"}
    assert 0.0 <= result.final_metrics["metrics/top1"] <= 1.0
    assert result.n_params > 1000
    assert os.path.isdir(os.path.join(model_dir, "checkpoints"))
    assert os.path.isdir(os.path.join(model_dir, "export", "best"))
    # TB event files for both phases
    assert any(
        f.startswith("events.out.tfevents")
        for f in os.listdir(os.path.join(model_dir, "train"))
    )


def test_fit_resume_is_idempotent(fitted):
    trainer, result, _ = fitted
    again = trainer.fit(batch_size=8, steps=4)
    assert again.steps == 4
    assert abs(again.final_metrics["metrics/top1"] - result.final_metrics["metrics/top1"]) < 1e-5


def test_fit_synthetic_without_data_dir(tmp_path):
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        ModelConfig(
            num_classes=N_CLASSES,
            input_shape=SHAPE,
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(seed=0, checkpoint_every_steps=100),
    )
    result = trainer.fit(batch_size=8, steps=2)
    assert result.steps == 2
    assert "metrics/top1" in result.final_metrics


def test_fit_sequence_parallel_end_to_end(tmp_path):
    """fit() honors TrainConfig.sequence_parallel: one training step over a
    (4, 1, 2) mesh with the H-sharded backbone."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        ModelConfig(
            num_classes=N_CLASSES,
            input_shape=(64, 64),  # divisible by overall_stride(32) x sp(2)
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(seed=0, sequence_parallel=2, checkpoint_every_steps=100),
    )
    assert trainer.mesh.shape == {"batch": 4, "model": 1, "sequence": 2}
    result = trainer.fit(batch_size=8, steps=1)
    assert result.steps == 1
    assert "metrics/top1" in result.final_metrics


def test_augment_classification_batch_on_device():
    """Jittable flip+crop: deterministic per key, shape-preserving, and actually
    transforms (different key => generally different pixels)."""
    import jax

    from tensorflowdistributedlearning_tpu.data.augment import (
        augment_classification_batch,
    )

    rng = np.random.default_rng(0)
    images = rng.normal(0, 1, (8, 16, 16, 3)).astype(np.float32)
    fn = jax.jit(augment_classification_batch)
    a = np.asarray(fn(jax.random.PRNGKey(0), images))
    b = np.asarray(fn(jax.random.PRNGKey(0), images))
    c = np.asarray(fn(jax.random.PRNGKey(1), images))
    assert a.shape == images.shape
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    # padding-free variant is flip-only: every row is either identical or mirrored
    flip_only = np.asarray(
        jax.jit(lambda k, im: augment_classification_batch(k, im, crop_padding=0))(
            jax.random.PRNGKey(2), images
        )
    )
    for i in range(8):
        same = np.array_equal(flip_only[i], images[i])
        mirrored = np.array_equal(flip_only[i], images[i, :, ::-1])
        assert same or mirrored
    # flip=False (TrainConfig.augmentation="crop"): never mirrors — with no
    # padding either, the batch passes through untouched
    no_aug = np.asarray(
        jax.jit(
            lambda k, im: augment_classification_batch(
                k, im, crop_padding=0, flip=False
            )
        )(jax.random.PRNGKey(3), images)
    )
    np.testing.assert_array_equal(no_aug, images)


def test_mixup_and_cutmix_batches():
    import jax

    from tensorflowdistributedlearning_tpu.data.augment import (
        cutmix_batch,
        mixup_batch,
    )

    rng = np.random.default_rng(3)
    images = rng.normal(0, 1, (16, 12, 12, 3)).astype(np.float32)
    labels = rng.integers(0, 4, 16).astype(np.int32)

    mixed = jax.jit(mixup_batch)(jax.random.PRNGKey(0), images, labels)
    assert set(mixed) == {"images", "labels", "labels_b", "lam"}
    assert mixed["images"].shape == images.shape
    lam = np.asarray(mixed["lam"])
    assert np.all((lam >= 0.5) & (lam <= 1.0))  # majority-target convention
    # each mixed image is the stated convex combination of its pair
    # (recover the permutation by matching labels_b rows)
    np.testing.assert_array_equal(
        np.sort(np.asarray(mixed["labels_b"])), np.sort(labels)
    )

    # unique labels recover the permutation, so fixed points (an image paired
    # with itself) are excluded from the area check
    uniq = np.arange(16, dtype=np.int32)
    cut = jax.jit(cutmix_batch)(jax.random.PRNGKey(1), images, uniq)
    cl = np.asarray(cut["lam"])
    assert np.all((cl >= 0.0) & (cl <= 1.0))
    out = np.asarray(cut["images"])
    perm = np.asarray(cut["labels_b"])
    # lam is the exact surviving-area fraction: pixels equal to the original
    # image occupy lam of each map (partner pixels differ a.s. for gaussians)
    checked = 0
    for i in range(16):
        if perm[i] == i:
            continue
        same = np.isclose(out[i], images[i]).all(axis=-1).mean()
        assert same == pytest.approx(cl[i], abs=1e-6)
        checked += 1
    assert checked >= 8  # a random 16-permutation has few fixed points


def test_mixup_loss_mixes_per_example_ce():
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.ops import losses
    from tensorflowdistributedlearning_tpu.train.step import ClassificationTask

    task = ClassificationTask()
    logits = jnp.asarray(np.random.default_rng(0).normal(0, 1, (6, 5)), jnp.float32)
    labels = jnp.asarray([0, 1, 2, 3, 4, 0], jnp.int32)
    labels_b = jnp.asarray([4, 3, 2, 1, 0, 2], jnp.int32)
    lam = jnp.asarray([1.0, 0.5, 0.75, 1.0, 0.25, 0.6], jnp.float32)
    batch = {"labels": labels, "labels_b": labels_b, "lam": lam}
    got = float(task.loss(logits, batch))
    ce_a = np.asarray(losses.softmax_cross_entropy_per_example(logits, labels))
    ce_b = np.asarray(losses.softmax_cross_entropy_per_example(logits, labels_b))
    want = float(np.mean(np.asarray(lam) * ce_a + (1 - np.asarray(lam)) * ce_b))
    assert got == pytest.approx(want, rel=1e-6)
    # lam == 1 everywhere degenerates to plain CE
    ones = {"labels": labels, "labels_b": labels_b,
            "lam": jnp.ones_like(lam)}
    assert float(task.loss(logits, ones)) == pytest.approx(
        float(np.mean(ce_a)), rel=1e-6
    )


def test_fit_trains_with_mixup(tmp_path):
    """mixup flows through the real SPMD train step (extra per-example batch
    fields ride the batch-axis specs) and the loss decreases training on one
    repeated batch."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        ModelConfig(
            num_classes=N_CLASSES,
            input_shape=SHAPE,
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=16,
            width_multiplier=0.125,
            output_stride=None,
        ),
        TrainConfig(augmentation="mixup", checkpoint_every_steps=4, n_devices=8),
    )
    result = trainer.fit(batch_size=8, steps=4, eval_every_steps=4)
    assert result.steps == 4
    assert np.isfinite(result.final_metrics["loss"])
    # mixing policies refuse the execution strategies that don't thread the
    # pairing fields
    with pytest.raises(ValueError, match="mixup"):
        TrainConfig(augmentation="mixup", sequence_parallel=2)
    with pytest.raises(ValueError, match="cutmix"):
        TrainConfig(augmentation="cutmix", pipeline_parallel=2)


def test_augmentation_policy_validation_and_none_passthrough(tmp_path):
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    with pytest.raises(ValueError, match="augmentation"):
        TrainConfig(augmentation="randaug")
    trainer = ClassifierTrainer(
        str(tmp_path / "m"),
        None,
        ModelConfig(
            num_classes=N_CLASSES,
            input_shape=SHAPE,
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=16,
            width_multiplier=0.125,
            output_stride=None,
        ),
        TrainConfig(augmentation="none", n_devices=8),
    )
    prepare = trainer._make_prepare_train()
    batch = {"images": np.ones((4, 8, 8, 3), np.float32),
             "labels": np.zeros((4,), np.int32)}
    assert prepare(0, batch) is batch


def test_fit_rejects_unshardable_spatial_config(tmp_path):
    """224x224 stride-32 trunks cannot H-shard at sequence_parallel=2 — the
    config-time validation catches it (code review r2 finding)."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    with pytest.raises(ValueError, match="divisible by stride"):
        ClassifierTrainer(
            str(tmp_path),
            None,
            ModelConfig(
                num_classes=10,
                input_shape=(224, 224),
                input_channels=3,
                output_stride=None,
            ),
            TrainConfig(sequence_parallel=2),
        )


def test_fit_rejects_segmentation_config(tmp_path):
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    with pytest.raises(ValueError, match="num_classes"):
        ClassifierTrainer(str(tmp_path), None, ModelConfig())


def test_fit_rejects_nchw_training(tmp_path):
    """Round-2 VERDICT missing #4: NCHW at the fit() training boundary is
    rejected with guidance instead of being accepted-and-ignored."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        ModelConfig(
            num_classes=4,
            input_shape=(16, 16),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(data_format="NCHW"),
    )
    with pytest.raises(ValueError, match="serving/predict boundary"):
        trainer.fit(batch_size=8, steps=1)


def test_fit_preset_rejects_segmentation_preset(tmp_path):
    from tensorflowdistributedlearning_tpu.train.fit import fit_preset

    with pytest.raises(ValueError, match="segmentation"):
        fit_preset("tgs_salt", str(tmp_path))


def test_fit_loop_accepts_imagenet_preset_architecture(tmp_path):
    """The resnet50_imagenet preset flows through the same loop — proven at test
    scale by shrinking only input/blocks (the wiring, bf16 dtype, optimizer, and
    head are the preset's own)."""
    from tensorflowdistributedlearning_tpu.configs import get_preset
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    preset = get_preset("resnet50_imagenet")
    small = dataclasses.replace(
        preset.model, input_shape=SHAPE, n_blocks=(1, 1, 1), base_depth=16,
        num_classes=N_CLASSES, width_multiplier=0.25,
    )
    trainer = ClassifierTrainer(str(tmp_path), None, small, preset.train)
    result = trainer.fit(batch_size=8, steps=1)
    assert result.steps == 1


def test_cli_fit_cifar10_smoke(folder, tmp_path):
    """VERDICT r1 #2 'done' criterion: the fit CLI trains a preset end-to-end
    from on-disk data on the CPU mesh."""
    import shutil

    from tensorflowdistributedlearning_tpu import cli

    # cifar10_smoke expects 32x32x3 inputs; build a matching tiny dataset
    root = str(tmp_path / "data")
    imagefolder.write_synthetic_imagefolder(
        os.path.join(root, "train"), 10, 2, (32, 32), channels=3
    )
    model_dir = str(tmp_path / "model")
    rc = cli.main([
        "fit",
        "--preset", "cifar10_smoke",
        "--model-dir", model_dir,
        "--data-dir", root,
        "--steps", "2",
        "--batch-size", "8",
    ])
    assert rc == 0
    assert os.path.isdir(os.path.join(model_dir, "checkpoints"))
    shutil.rmtree(model_dir)


def test_fit_serving_fn_and_export_roundtrip(fitted):
    """The classification twin of the K-fold serving path: best-state inference
    closure + standalone StableHLO artifact that reloads without the trainer."""
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    trainer, _, model_dir = fitted
    serve = trainer.serving_fn()
    images = jnp.zeros((2, *SHAPE, 3), jnp.float32)
    out = serve(images)
    assert out["probabilities"].shape == (2, N_CLASSES)
    assert out["class"].shape == (2,)

    path = trainer.export_serving()
    assert os.path.isfile(path)
    directory = os.path.dirname(path)
    loaded = serving_lib.load_serving_artifact(directory)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (3, *SHAPE, 3)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(loaded(x)["probabilities"]),
        np.asarray(serve(jnp.asarray(x))["probabilities"]),
        rtol=1e-5,
        atol=1e-6,
    )


def test_fit_preset_optimizer_override_requires_lr(tmp_path):
    """Swapping a preset's optimizer without an lr tuned for it is refused
    (SGD presets carry linearly-scaled rates that diverge under Adam)."""
    from tensorflowdistributedlearning_tpu.train.fit import fit_preset

    with pytest.raises(ValueError, match="requires an explicit"):
        fit_preset(
            "resnet50_imagenet", str(tmp_path), steps=1, optimizer="adam"
        )


def test_resume_stream_order_differs(tmp_path):
    """A resumed run must not replay the fresh run's shuffled order from the
    beginning: the resume point is folded into the stream seed (the reference
    DID replay — Estimator input_fns restart — kept out of parity on purpose)."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path / "m"),
        None,  # synthetic fallback source; seeding logic is shared
        ModelConfig(
            num_classes=N_CLASSES,
            input_shape=SHAPE,
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(n_devices=1),
    )
    fresh = next(iter(trainer._train_stream(8, 4, start_step=0)))
    resumed = next(iter(trainer._train_stream(8, 4, start_step=2)))
    fresh_again = next(iter(trainer._train_stream(8, 4, start_step=0)))
    assert not np.array_equal(fresh["images"], resumed["images"])
    # same start point stays deterministic
    np.testing.assert_array_equal(fresh["images"], fresh_again["images"])


def test_sync_batch_norm_rebinds_apply_fn(tmp_path):
    """TrainConfig.sync_batch_norm must reach the executed model: the train
    state's apply_fn is the axis-named (BN-pmean) model, not the plain init
    twin — the exact wiring gap that once made the flag a silent no-op (the
    guard skipped the rebind unless spatial/expert parallelism was also on),
    invalidating a committed A/B."""
    import dataclasses as _dc

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    cfg = ModelConfig(
        num_classes=4,
        input_shape=(32, 32),
        input_channels=3,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.0625,
        output_stride=None,
    )
    tr = ClassifierTrainer(
        str(tmp_path / "run"),
        None,
        cfg,
        TrainConfig(sync_batch_norm=True),
    )
    state = tr._init_state()
    assert state.apply_fn == tr.model.apply
    assert state.apply_fn != tr._plain_model.apply
