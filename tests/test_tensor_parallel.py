"""Tensor (model) parallelism via GSPMD (parallel/tensor.py): spec rules, state
placement actually sharding parameters over the model axis, a training step on a
(4, 2, 1) dp x tp mesh, and forward parity with the unsharded model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from contextlib import nullcontext
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data.synthetic import (
    synthetic_classification_batch,
)
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.state import create_train_state

CFG = ModelConfig(
    num_classes=8,
    input_shape=(16, 16),
    input_channels=3,
    n_blocks=(1, 1, 1),
    base_depth=16,
    width_multiplier=0.125,  # conv1_3 = 16 channels; TP degree 2 still divides
    output_stride=None,
)


@pytest.fixture(scope="module")
def tp_mesh():
    return make_mesh(8, model_parallel=2)  # (batch=4, model=2, sequence=1)


@pytest.fixture(scope="module")
def state():
    model = build_model(CFG)
    return create_train_state(
        model,
        step_lib.make_optimizer(TrainConfig()),
        jax.random.PRNGKey(0),
        np.zeros((1, 16, 16, 3), np.float32),
    )


def test_specs_shard_channel_dims(tp_mesh, state):
    specs = tp_lib.tensor_parallel_specs(state.params, tp_mesh)
    flat = dict(jax.tree_util.tree_leaves_with_path(specs))
    leaves = dict(jax.tree_util.tree_leaves_with_path(state.params))
    sharded = 0
    for path, spec in flat.items():
        shape = jnp.shape(leaves[path])
        if spec != P():
            assert spec[-1] == MODEL_AXIS
            assert shape[-1] % 2 == 0
            sharded += 1
    assert sharded > 10  # the bulk of the network is channel-sharded


def test_state_params_actually_sharded(tp_mesh, state):
    placed = tp_lib.shard_state_tensor_parallel(state, tp_mesh)
    # a representative large kernel: each device holds half the output channels
    leaf = placed.params["backbone"]["conv1_3"]["conv"]["kernel"]
    assert leaf.shape[-1] == 16
    shard_shapes = {s.data.shape for s in leaf.addressable_shards}
    assert shard_shapes == {(3, 3, 8, 8)}
    # optimizer moments shard like their params (the point of TP: per-chip
    # param+optimizer memory drops by the model-axis degree)
    adam_mu = placed.opt_state[0].mu
    mu_leaf = adam_mu["backbone"]["conv1_3"]["conv"]["kernel"]
    assert MODEL_AXIS in tuple(mu_leaf.sharding.spec), mu_leaf.sharding.spec
    assert {s.data.shape for s in mu_leaf.addressable_shards} == {(3, 3, 8, 8)}
    assert placed.step.sharding.spec == P()


def test_gspmd_train_step_runs_and_keeps_sharding(tp_mesh, state):
    placed = tp_lib.shard_state_tensor_parallel(state, tp_mesh)
    step = tp_lib.make_train_step_gspmd(tp_mesh, step_lib.ClassificationTask(), donate=False)
    batch = synthetic_classification_batch(
        np.random.default_rng(0), 8, input_shape=(16, 16), channels=3, num_classes=8
    )
    new_state, metrics = step(placed, tp_lib.place_batch_gspmd(batch, tp_mesh))
    values = step_lib.compute_metrics(jax.device_get(metrics))
    assert np.isfinite(values["loss"])
    assert 0.0 <= values["metrics/top1"] <= 1.0
    assert int(jax.device_get(new_state.step)) == 1
    # the big kernels stay model-axis sharded after the update
    leaf = new_state.params["backbone"]["conv1_3"]["conv"]["kernel"]
    assert MODEL_AXIS in tuple(leaf.sharding.spec), leaf.sharding.spec


def test_weight_update_sharding_zero_style(state):
    """ZeRO-style optimizer sharding over the DATA axis (arXiv:2004.13336):
    moments shard 1/dp per replica, params stay replicated, and one training
    step matches the fully-replicated update bitwise-closely."""
    from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS

    mesh = make_mesh(8)  # (8, 1, 1) pure DP
    placed = tp_lib.shard_state_weight_update(state, mesh)
    adam_mu = placed.opt_state[0].mu
    mu_leaf = adam_mu["backbone"]["conv1_3"]["conv"]["kernel"]
    assert BATCH_AXIS in tuple(mu_leaf.sharding.spec)
    assert {s.data.shape for s in mu_leaf.addressable_shards} == {(3, 3, 8, 2)}
    # params replicated
    assert placed.params["backbone"]["conv1_3"]["conv"]["kernel"].sharding.spec == P()

    batch = synthetic_classification_batch(
        np.random.default_rng(3), 8, input_shape=(16, 16), channels=3, num_classes=8
    )
    step = tp_lib.make_train_step_gspmd(
        mesh, step_lib.ClassificationTask(), donate=False
    )
    new_zero, m_zero = step(placed, tp_lib.place_batch_gspmd(batch, mesh))

    replicated = tp_lib.shard_state_tensor_parallel(state, mesh)  # tp=1 ⇒ replicated
    new_rep, m_rep = step(replicated, tp_lib.place_batch_gspmd(batch, mesh))
    v_zero = step_lib.compute_metrics(jax.device_get(m_zero))
    v_rep = step_lib.compute_metrics(jax.device_get(m_rep))
    assert v_zero["loss"] == pytest.approx(v_rep["loss"], rel=1e-5)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(new_zero.params["backbone"]["conv1_3"]["conv"]["kernel"])),
        np.asarray(jax.device_get(new_rep.params["backbone"]["conv1_3"]["conv"]["kernel"])),
        rtol=1e-5,
        atol=1e-6,
    )


def test_gspmd_forward_matches_unsharded(tp_mesh, state):
    """Eval-mode logits with model-axis-sharded params match the single-device
    forward (GSPMD inserts the collectives; numerics agree to reduction-order
    tolerance)."""
    model = build_model(CFG)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    rng = np.random.default_rng(1)
    images = rng.normal(0, 1, (8, 16, 16, 3)).astype(np.float32)
    ref = jax.jit(lambda v, im: model.apply(v, im, train=False))(variables, images)

    placed = tp_lib.shard_state_tensor_parallel(state, tp_mesh)
    sharded_vars = {"params": placed.params, "batch_stats": placed.batch_stats}
    ctx = (
        jax.sharding.use_mesh(tp_mesh)
        if hasattr(jax.sharding, "use_mesh")
        else nullcontext()
    )
    with ctx:
        out = jax.jit(lambda v, im: model.apply(v, im, train=False))(
            sharded_vars,
            tp_lib.place_batch_gspmd({"images": images}, tp_mesh)["images"],
        )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out)), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_fit_end_to_end_with_model_parallel(tmp_path):
    """TrainConfig.model_parallel wires GSPMD tensor parallelism through the
    production fit loop: params/optimizer shard over the model axis, training,
    eval, checkpointing, and best export all run (the integration the spatial
    axis got in round 2 — TP is a capability, not a demo)."""
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,  # synthetic data
        CFG,
        TrainConfig(seed=0, model_parallel=2, checkpoint_every_steps=2),
    )
    assert trainer.mesh.shape == {"batch": 4, "model": 2, "sequence": 1}
    result = trainer.fit(batch_size=8, steps=4)
    assert result.steps == 4
    assert np.isfinite(result.final_metrics["loss"])
    assert 0.0 <= result.final_metrics["metrics/top1"] <= 1.0

    # resume restores INTO the tensor-parallel sharding and skips retraining
    again = ClassifierTrainer(
        str(tmp_path), None, CFG,
        TrainConfig(seed=0, model_parallel=2, checkpoint_every_steps=2),
    ).fit(batch_size=8, steps=4)
    assert again.steps == 4


def test_model_and_sequence_parallel_mutually_exclusive():
    with pytest.raises(ValueError, match="cannot both exceed 1"):
        TrainConfig(model_parallel=2, sequence_parallel=2)


def test_hybrid_tp_sp_step_matches_spatial_oracle():
    """dp x tp x sp in ONE train step via shard_map's hybrid ``axis_names``
    mode (make_train_step(auto_model=True)): (batch, sequence) manual — halo
    exchange + explicit gradient mean — while the model axis stays auto with
    channel-sharded params (GSPMD derives the tensor-parallel reductions
    inside each manual shard). Loss matches the plain spatial step with
    replicated params (tensor parallelism is a layout, not a numerics change,
    up to reassociation), and params stay model-axis sharded after the
    update. The 2-process twin is tests/test_multiprocess.py::
    test_tensor_spatial_composition_across_processes."""
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        replicate,
        shard_batch_spatial,
    )
    from tests.mp_train_worker import make_global_batch, tiny_model

    spatial_model = tiny_model(spatial=True)
    raw = create_train_state(
        tiny_model(),  # init OUTSIDE shard_map with the plain twin
        step_lib.make_optimizer(TrainConfig(lr=0.01)),
        jax.random.PRNGKey(0),
        np.zeros((1, 8, 8, 3), np.float32),
    ).replace(apply_fn=spatial_model.apply)
    batch = make_global_batch(16)

    mesh3 = make_mesh(8, model_parallel=2, sequence_parallel=2)  # (2, 2, 2)
    placed = tp_lib.shard_state_tensor_parallel(raw, mesh3)
    kernel_spec = tuple(placed.params["conv"]["kernel"].sharding.spec)
    assert MODEL_AXIS in kernel_spec, kernel_spec  # genuinely channel-sharded
    hybrid_step = step_lib.make_train_step(
        mesh3,
        step_lib.ClassificationTask(),
        donate=False,
        spatial=True,
        auto_model=True,
    )
    new_state, metrics = hybrid_step(placed, shard_batch_spatial(batch, mesh3))
    hybrid_loss = step_lib.compute_metrics(jax.device_get(metrics))["loss"]

    mesh_sp = make_mesh(8, sequence_parallel=2)  # (4, 1, 2) — the sp oracle
    plain_step = step_lib.make_train_step(
        mesh_sp, step_lib.ClassificationTask(), donate=False, spatial=True
    )
    _, m_plain = plain_step(
        replicate(raw, mesh_sp), shard_batch_spatial(batch, mesh_sp)
    )
    oracle_loss = step_lib.compute_metrics(jax.device_get(m_plain))["loss"]

    assert np.isfinite(hybrid_loss)
    assert hybrid_loss == pytest.approx(oracle_loss, rel=1e-5)
    # the updated params keep their model-axis sharding (no silent gather)
    new_spec = tuple(new_state.params["conv"]["kernel"].sharding.spec)
    assert MODEL_AXIS in new_spec, new_spec
