"""Resilience subsystem tests: fault injection, retry, preemption, corrupt-
checkpoint fallback, the restart supervisor, and the headline kill-and-resume
e2e (SIGTERM a real training subprocess mid-run, supervise its restart, and
require the final params to match an uninterrupted run bit-for-bit).

Everything here stays OUT of the ``slow`` marker on purpose (ISSUE 3): the
recovery path must be exercised by every tier-1 sweep, not only by the full
suite runner."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib
from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger, read_ledger
from tensorflowdistributedlearning_tpu.resilience import (
    ABORT_CRASH_LOOP,
    ABORT_RESTART_BUDGET,
    EXIT_PREEMPTED,
    InjectedFault,
    RetryExhaustedError,
    Supervisor,
    TransientInjectedIOError,
    faults,
    parse_fault_spec,
    preempt,
)

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "resilience_train_worker.py")


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Process-global injector/handler/retry counters must not leak between
    tests (or into the rest of the suite)."""
    yield
    faults.uninstall()
    preempt.uninstall()
    retry_lib.reset_registry()


# -- fault specs ---------------------------------------------------------------


def test_parse_fault_spec_forms():
    assert parse_fault_spec("raise@12") == faults.FaultSpec("raise", 12, 1)
    assert parse_fault_spec("sigterm@3") == faults.FaultSpec("sigterm", 3, 1)
    assert parse_fault_spec("io-data@3x2") == faults.FaultSpec("io-data", 3, 2)
    assert parse_fault_spec("io-ckpt@1").site == faults.SITE_CHECKPOINT
    assert parse_fault_spec("io-read@2").site == faults.SITE_IO


def test_parse_fault_spec_seeded_range_is_deterministic():
    a = parse_fault_spec("sigterm@5-20", seed=7)
    b = parse_fault_spec("sigterm@5-20", seed=7)
    c = parse_fault_spec("sigterm@5-20", seed=8)
    assert a == b
    assert 5 <= a.at <= 20 and 5 <= c.at <= 20
    # different seeds should usually differ; at minimum both stay in range
    assert parse_fault_spec("sigterm@9-9", seed=3).at == 9


@pytest.mark.parametrize(
    "bad", ["", "boom@3", "raise@", "raise@5-2", "io-data@3x0", "raise3"]
)
def test_parse_fault_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_spec(bad)


def test_injector_step_fault_fires_once_at_exact_step():
    faults.install("raise@3")
    faults.fire(faults.SITE_STEP, 1)
    faults.fire(faults.SITE_STEP, 2)
    with pytest.raises(InjectedFault):
        faults.fire(faults.SITE_STEP, 3)
    # count=1: the same step offered again does not re-fire
    faults.fire(faults.SITE_STEP, 3)
    faults.fire(faults.SITE_STEP, 4)


def test_injector_io_occurrence_window():
    faults.install("io-read@2x2")
    faults.fire(faults.SITE_IO)  # occurrence 1: clean
    for _ in range(2):  # occurrences 2 and 3: fail
        with pytest.raises(TransientInjectedIOError):
            faults.fire(faults.SITE_IO)
    faults.fire(faults.SITE_IO)  # occurrence 4: clean again
    # other sites never see it
    faults.fire(faults.SITE_DATA)
    faults.fire(faults.SITE_CHECKPOINT)


def test_fire_is_noop_when_nothing_installed():
    faults.uninstall()
    faults.fire(faults.SITE_STEP, 1)
    faults.fire(faults.SITE_IO)


# -- retry ---------------------------------------------------------------------


def test_retry_recovers_and_counts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = retry_lib.call_with_retry(
        flaky, name="unit", sleep=lambda _s: None
    )
    assert out == "ok"
    assert len(calls) == 3
    assert retry_lib.retries("unit") == 2
    assert retry_lib.retries() == 2


def test_retry_exhaustion_error_shape():
    def always():
        raise OSError("disk on fire")

    with pytest.raises(RetryExhaustedError) as exc:
        retry_lib.call_with_retry(
            always, name="unit", attempts=3, sleep=lambda _s: None
        )
    err = exc.value
    assert err.name == "unit"
    assert err.attempts == 3
    assert isinstance(err.last, OSError)
    assert isinstance(err.__cause__, OSError)
    assert "disk on fire" in str(err)
    # exhaustion is NOT itself OSError: outer retries must not re-retry it
    assert not isinstance(err, OSError)
    assert retry_lib.retries("unit") == 2  # attempts - 1 sleeps/counts


def test_retry_clean_path_counts_nothing():
    assert retry_lib.call_with_retry(lambda: 7, name="unit") == 7
    assert retry_lib.retries() == 0


def test_retry_gives_up_immediately_on_non_transient_oserrors():
    """Missing files / permission walls are deterministic: no backoff, and the
    caller keeps the original exception type (not RetryExhaustedError)."""
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("/no/such/shard")

    with pytest.raises(FileNotFoundError):
        retry_lib.call_with_retry(missing, name="unit", sleep=lambda _s: None)
    assert len(calls) == 1
    assert retry_lib.retries() == 0


def test_retry_does_not_swallow_unlisted_exceptions():
    with pytest.raises(ValueError):
        retry_lib.call_with_retry(
            lambda: (_ for _ in ()).throw(ValueError("no")),
            name="unit",
            sleep=lambda _s: None,
        )


# -- preemption ----------------------------------------------------------------


def test_preempt_signal_sets_flag_and_reason():
    preempt.install(signals=(signal.SIGUSR1,))
    assert not preempt.requested()
    os.kill(os.getpid(), signal.SIGUSR1)
    assert preempt.requested()
    assert preempt.reason() == "signal:SIGUSR1"


def test_preempt_notice_file(tmp_path):
    notice = tmp_path / "PREEMPT"
    preempt.install(notice_file=str(notice), signals=None)
    assert not preempt.requested()
    notice.write_text("drain please")
    # the throttle caches the first (pre-notice) stat briefly; force recheck
    preempt.handler()._last_notice_check = 0.0
    assert preempt.requested()
    assert preempt.reason().startswith("notice-file:")


def test_preempt_uninstalled_is_false():
    preempt.uninstall()
    assert not preempt.requested()
    assert preempt.reason() == "unknown"


def test_preempted_error_carries_step_and_exit_code_is_distinct():
    err = preempt.PreemptedError(41)
    assert err.step == 41
    assert EXIT_PREEMPTED == 75
    assert EXIT_PREEMPTED not in (0, 1, 2, 130, 137, 139, 143)


# -- supervisor (fake launches: no subprocesses, no sleeping) ------------------


def _supervisor(tmp_path, rcs, progress, **kw):
    """Supervisor over a scripted child: ``rcs`` consumed per launch,
    ``progress`` consumed per progress query."""
    rcs, progress = list(rcs), list(progress)
    kw.setdefault("sleep", lambda _s: None)
    kw.setdefault("backoff_base_s", 0.0)
    return Supervisor(
        ["true"],
        workdir=str(tmp_path),
        launch=lambda: rcs.pop(0),
        progress_fn=lambda: progress.pop(0),
        **kw,
    )


def test_supervisor_restarts_through_failures_to_success(tmp_path):
    # initial probe, then one query after each of 3 launches
    sup = _supervisor(
        tmp_path, rcs=[1, EXIT_PREEMPTED, 0], progress=[None, 2, 5, 8],
        max_restarts=3,
    )
    result = sup.run()
    assert result.ok
    assert result.restarts == 2
    assert result.final_step == 8
    events = read_ledger(str(tmp_path))
    restarts = [e for e in events if e["event"] == "restart"]
    assert [e["rc"] for e in restarts] == [1, EXIT_PREEMPTED]
    assert restarts[0]["reason"] == "crash"
    assert restarts[1]["reason"] == "preempted"
    assert all(e["downtime_s"] >= 0 for e in restarts)


def test_supervisor_crash_loop_aborts(tmp_path):
    # step never advances past 3: two consecutive no-progress failures abort
    sup = _supervisor(
        tmp_path, rcs=[1, 1, 1, 1], progress=[3, 3, 3, 3, 3], max_restarts=10,
    )
    result = sup.run()
    assert not result.ok
    assert result.aborted == ABORT_CRASH_LOOP
    assert result.restarts == 1  # first no-progress restart, then abort
    aborts = [
        e for e in read_ledger(str(tmp_path)) if e["event"] == "supervisor_abort"
    ]
    assert aborts and aborts[-1]["reason"] == ABORT_CRASH_LOOP


def test_supervisor_restart_budget_aborts(tmp_path):
    # progress every time (no crash loop) but the child never succeeds
    sup = _supervisor(
        tmp_path, rcs=[1, 1, 1], progress=[0, 1, 2, 3], max_restarts=2,
    )
    result = sup.run()
    assert not result.ok
    assert result.aborted == ABORT_RESTART_BUDGET
    assert result.restarts == 2


def test_supervisor_clean_run_writes_nothing(tmp_path):
    result = _supervisor(tmp_path, rcs=[0], progress=[None, 7]).run()
    assert result.ok and result.restarts == 0 and result.downtime_s == 0.0
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry.jsonl")) or not [
        e
        for e in read_ledger(str(tmp_path))
        if e["event"] in ("restart", "supervisor_abort")
    ]


def test_supervisor_signal_stops_restart_loop(tmp_path):
    """A signal delivered to the SUPERVISOR must not trigger a relaunch: the
    child's (preempted) exit is final when the whole job is being torn down."""
    from tensorflowdistributedlearning_tpu.resilience import ABORT_SIGNALED

    def launch():
        os.kill(os.getpid(), signal.SIGTERM)  # handled by the supervisor
        return EXIT_PREEMPTED

    sup = Supervisor(
        ["true"],
        workdir=str(tmp_path),
        launch=launch,
        progress_fn=lambda: 5,
        sleep=lambda _s: None,
    )
    result = sup.run()
    assert result.restarts == 0
    assert not result.ok
    assert result.exit_code == EXIT_PREEMPTED
    assert result.aborted == ABORT_SIGNALED
    aborts = [
        e for e in read_ledger(str(tmp_path)) if e["event"] == "supervisor_abort"
    ]
    assert aborts and aborts[-1]["reason"] == ABORT_SIGNALED
    # the supervisor restored the previous SIGTERM disposition on exit
    assert signal.getsignal(signal.SIGTERM) != sup._on_signal


def test_supervisor_signal_during_backoff_prevents_relaunch(tmp_path):
    """A signal landing between child lifetimes (mid backoff sleep) must stop
    the loop — launching a fresh child the scheduler would have to kill again
    fights the teardown."""
    launches = []

    def launch():
        launches.append(1)
        return 1

    sup = Supervisor(
        ["true"],
        workdir=str(tmp_path),
        launch=launch,
        progress_fn=lambda: len(launches),  # always progresses: no crash loop
        max_restarts=5,
    )
    # deliver the signal "during" the backoff sleep
    sup._sleep = lambda _s: sup._on_signal(signal.SIGTERM, None)
    result = sup.run()
    assert launches == [1]
    assert not result.ok and result.exit_code == 1
    assert result.restarts == 0  # the aborted relaunch does not count


def test_transient_restore_exhaustion_keeps_checkpoints_and_raises(
    tmp_path, tiny_state, monkeypatch
):
    """A filesystem blip (RetryExhaustedError out of the restore retry) must
    NOT delete the step and must NOT fresh-init next to it (mixed lineage):
    it raises, the supervisor backs off, and the kept checkpoint restores
    fine once the blip passes."""
    import jax

    ck = _manager(tmp_path)
    ck.save(tiny_state.replace(step=tiny_state.step + 1), force=True)
    original = ck._ckpt.restore

    def flaky_restore(*args, **kwargs):
        raise OSError("NFS blip")

    monkeypatch.setattr(ck._ckpt, "restore", flaky_restore)
    with pytest.raises(RetryExhaustedError):
        ck.restore_latest(tiny_state)
    assert ck._ckpt.all_steps() == [1]  # the checkpoint survived the blip
    monkeypatch.setattr(ck._ckpt, "restore", original)
    assert int(jax.device_get(ck.restore_latest(tiny_state).step)) == 1
    ck.close()


def test_supervisor_signal_after_clean_child_exit_is_not_an_abort(tmp_path):
    """SIGTERM arriving as the child finishes cleanly: the run completed —
    no supervisor_abort event, ok result."""

    def launch():
        os.kill(os.getpid(), signal.SIGTERM)
        return 0  # the child finished its run before the signal mattered

    result = Supervisor(
        ["true"],
        workdir=str(tmp_path),
        launch=launch,
        progress_fn=lambda: 4,
        sleep=lambda _s: None,
    ).run()
    assert result.ok and result.restarts == 0 and result.aborted is None
    assert not [
        e for e in read_ledger(str(tmp_path)) if e["event"] == "supervisor_abort"
    ]


def test_supervised_child_never_recurses(tmp_path, monkeypatch):
    """The env marker makes supervisor recursion structurally impossible even
    if a --max-restarts spelling survives the argv strip (argparse accepts
    prefix abbreviations)."""
    from tensorflowdistributedlearning_tpu import cli

    calls = []
    monkeypatch.setattr(
        cli, "_run_supervised", lambda args, argv: calls.append(1) or 42
    )
    argv = ["fit", "--preset", "nope", "--model-dir", str(tmp_path),
            "--max-restarts", "2"]
    assert cli.main(argv) == 42  # parent: supervised path taken
    monkeypatch.setenv("TFDL_SUPERVISED_CHILD", "1")
    with pytest.raises(ValueError, match="Unknown preset"):
        cli.main(argv)  # child: runs the command directly, no recursion
    assert calls == [1]


def test_ledger_progress_reads_last_stepped_event(tmp_path):
    from tensorflowdistributedlearning_tpu.resilience import ledger_progress

    assert ledger_progress(str(tmp_path)) is None
    ledger = RunLedger(str(tmp_path))
    ledger.event("run_header", kind="x")
    ledger.event("checkpoint", step=4)
    ledger.event("step_window", step=9)
    ledger.event("run_end")
    ledger.close()
    assert ledger_progress(str(tmp_path)) == 9


# -- report integration --------------------------------------------------------


def test_report_renders_goodput_lost_to_restarts(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    ledger = RunLedger(str(tmp_path))
    ledger.event("supervisor_start", max_restarts=3)
    ledger.event("run_header", kind="train", supervised=True)
    ledger.event("checkpoint", step=5)
    ledger.event("preempted", step=5, reason="signal:SIGTERM")
    ledger.event(
        "restart", attempt=1, rc=EXIT_PREEMPTED, reason="preempted", step=5,
        prev_step=None, backoff_s=0.5, downtime_s=0.6,
    )
    # the relaunch's own header (children stamp `supervised`)
    ledger.event("run_header", kind="train", supervised=True)
    ledger.event("resumed", step=5)
    ledger.event("checkpoint_retry", step=6, attempt=1, error="EIO")
    ledger.event("run_end", steps=8)
    ledger.event("supervisor_end", ok=True, restarts=1)
    ledger.close()

    report = build_report(str(tmp_path))
    res = report["resilience"]
    assert res["restarts"] == 1
    assert res["preemptions"] == 1
    assert res["resumes"] == 1
    assert res["checkpoint_retries"] == 1
    assert res["restart_downtime_s"] == pytest.approx(0.6)
    assert res["last_restart"]["reason"] == "preempted"
    text = render_report(report)
    assert "goodput lost to restarts" in text
    assert "1 restart(s)" in text


def test_report_resilience_scope_forgets_old_sessions(tmp_path):
    """A clean standalone run AFTER a closed supervised session must not
    inherit that session's restarts/aborts in its report."""
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    ledger = RunLedger(str(tmp_path))
    ledger.event("supervisor_start", max_restarts=1)
    ledger.event("run_header", kind="train", supervised=True)
    ledger.event("restart", attempt=1, rc=1, reason="crash", downtime_s=2.0)
    ledger.event("supervisor_abort", reason="crash-loop", rc=1, restarts=1)
    ledger.event("supervisor_end", ok=False, restarts=1, aborted="crash-loop")
    # ... user fixes the problem and reruns unsupervised, cleanly
    ledger.event("run_header", kind="train")
    ledger.event("step_window", step=4, steps=4)
    ledger.event("run_end", steps=4)
    ledger.close()
    report = build_report(str(tmp_path))
    assert "resilience" not in report
    assert "gave this run up" not in render_report(report)


def test_report_scope_survives_a_hard_killed_supervisor(tmp_path):
    """A supervisor that never wrote supervisor_end (SIGKILL, machine death)
    must not haunt later clean standalone runs either — the takeover keys on
    the run header's `supervised` stamp, not on the end marker."""
    from tensorflowdistributedlearning_tpu.obs.report import build_report

    ledger = RunLedger(str(tmp_path))
    ledger.event("supervisor_start", max_restarts=3)
    ledger.event("run_header", kind="train", supervised=True)
    ledger.event("restart", attempt=1, rc=1, reason="crash", downtime_s=1.0)
    # supervisor hard-killed here: no supervisor_end ever lands
    ledger.event("run_header", kind="train")  # later clean standalone run
    ledger.event("run_end", steps=4)
    ledger.close()
    assert "resilience" not in build_report(str(tmp_path))


def test_report_abort_explanations_match_reason(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    ledger = RunLedger(str(tmp_path))
    ledger.event("supervisor_start", max_restarts=1)
    ledger.event("run_header", kind="train", supervised=True)
    ledger.event("supervisor_abort", reason="signaled", rc=75, restarts=0)
    ledger.event("supervisor_end", ok=False, restarts=0, aborted="signaled")
    ledger.close()
    text = render_report(build_report(str(tmp_path)))
    assert "signaled" in text
    assert "itself was signaled" in text
    assert "progress between restarts" not in text


# -- checkpoint layer ----------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_state():
    import jax

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.train import (
        create_train_state,
        make_optimizer,
    )

    cfg = ModelConfig(
        n_blocks=(1, 1, 1), input_shape=(16, 16), base_depth=8,
        width_multiplier=0.0625,
    )
    return create_train_state(
        build_model(cfg),
        make_optimizer(TrainConfig()),
        jax.random.PRNGKey(0),
        np.zeros((1, 16, 16, 2), np.float32),
    )


def _manager(directory, telemetry=None):
    from tensorflowdistributedlearning_tpu.train.checkpoint import (
        CheckpointManager,
    )

    return CheckpointManager(
        str(directory), save_every_steps=1, telemetry=telemetry
    )


def test_corrupt_latest_checkpoint_falls_back_to_previous(tmp_path, tiny_state):
    import shutil

    import jax

    from tensorflowdistributedlearning_tpu.obs import Telemetry

    tel = Telemetry(str(tmp_path), run_info={"kind": "test"})
    ck = _manager(tmp_path, telemetry=tel)
    ck.save(tiny_state.replace(step=tiny_state.step + 1), force=True)
    ck.save(tiny_state.replace(step=tiny_state.step + 2), force=True)
    # the signature of a run killed mid-write: the newest step dir exists but
    # its save unit is gone
    shutil.rmtree(os.path.join(str(tmp_path), "checkpoints", "2", "default"))
    restored = ck.restore_latest(tiny_state)
    assert int(jax.device_get(restored.step)) == 1
    # the corrupt step was dropped, so retraining through step 2 can RE-write
    # it (save()'s per-step idempotence guard must not see the corpse) and the
    # next restart does not re-walk it
    assert 2 not in ck._ckpt.all_steps()
    assert ck.save(tiny_state.replace(step=tiny_state.step + 2), force=True)
    assert int(jax.device_get(ck.restore_latest(tiny_state).step)) == 2
    ck.close()
    tel.close()
    corrupt = [
        e for e in read_ledger(str(tmp_path))
        if e["event"] == "checkpoint_corrupt"
    ]
    assert corrupt and corrupt[0]["step"] == 2


def test_all_checkpoints_corrupt_falls_back_to_template(tmp_path, tiny_state):
    import shutil

    ck = _manager(tmp_path)
    ck.save(tiny_state.replace(step=tiny_state.step + 1), force=True)
    shutil.rmtree(os.path.join(str(tmp_path), "checkpoints", "1", "default"))
    restored = ck.restore_latest(tiny_state)
    assert restored is tiny_state  # fresh init beats a permanent crash loop
    ck.close()


def test_structure_mismatch_still_raises_through_fallback(tmp_path, tiny_state):
    """A config change is NOT corruption: the corrupt-checkpoint fallback must
    re-raise it instead of silently restarting from scratch."""
    import jax

    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.train import make_optimizer
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.config import ModelConfig

    ck = _manager(tmp_path)
    ck.save(tiny_state.replace(step=tiny_state.step + 1), force=True)
    cfg = ModelConfig(
        n_blocks=(1, 1, 1), input_shape=(16, 16), base_depth=8,
        width_multiplier=0.0625,
    )
    sgd_template = create_train_state(
        build_model(cfg),
        make_optimizer(TrainConfig(optimizer="sgd")),
        jax.random.PRNGKey(0),
        np.zeros((1, 16, 16, 2), np.float32),
    )
    with pytest.raises(RuntimeError, match="optimizer|structure"):
        ck.restore_latest(sgd_template)
    ck.close()


def test_injected_transient_checkpoint_io_recovers(tmp_path, tiny_state):
    """io-ckpt@1: the first save attempt fails transiently, the retry layer
    recovers it, and the retry is counted + ledgered."""
    from tensorflowdistributedlearning_tpu.obs import Telemetry

    tel = Telemetry(str(tmp_path), run_info={"kind": "test"})
    ck = _manager(tmp_path, telemetry=tel)
    faults.install("io-ckpt@1")
    assert ck.save(tiny_state.replace(step=tiny_state.step + 1), force=True)
    assert retry_lib.retries("checkpoint_save") == 1
    ck.close()
    tel.close()
    retries = [
        e for e in read_ledger(str(tmp_path))
        if e["event"] == "checkpoint_retry"
    ]
    assert retries and retries[0]["step"] == 1


def test_checkpoint_close_is_idempotent(tmp_path, tiny_state):
    ck = _manager(tmp_path)
    ck.save(tiny_state.replace(step=tiny_state.step + 1), force=True)
    ck.close()
    ck.close()  # atexit may also call close(); must be a no-op


# -- data-path injection -------------------------------------------------------


def test_injected_transient_record_batch_recovers(tmp_path):
    pytest.importorskip("PIL")
    from tensorflowdistributedlearning_tpu.data import records as rec

    rng = np.random.default_rng(0)
    images = [rng.integers(0, 255, (8, 8, 3), dtype=np.uint8) for _ in range(8)]
    rec.write_classification_shards(
        str(tmp_path), images, [i % 4 for i in range(8)], shards=2
    )
    ds = rec.ClassificationRecords(
        str(tmp_path), image_shape=(8, 8), channels=3, num_classes=4
    )
    faults.install("io-data@1")
    batches = list(ds.batches(4, repeat=False))
    assert len(batches) == 2
    assert retry_lib.retries("record_batch") == 1


def test_injected_transient_shard_open_recovers(tmp_path):
    from tensorflowdistributedlearning_tpu.data import records as rec

    path = os.path.join(str(tmp_path), "a.tfrecord")
    rec.write_records(path, [b"x", b"y"])
    faults.install("io-read@1")
    assert list(rec.read_records(path)) == [b"x", b"y"]
    assert retry_lib.retries("record_open") == 1


# -- the headline: kill at a (seeded-)random step, supervised resume, bit-for-
# -- bit identical result ------------------------------------------------------


def test_kill_and_resume_e2e(tmp_path):
    """SIGTERM a real fit() subprocess mid-run via injection, let the restart
    supervisor bring it back, and require the final checkpoint's params to be
    IDENTICAL to an uninterrupted golden run — plus restart/preempted/resumed
    accounting in the ledger and a goodput-lost line in telemetry-report."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, WORKER, "smoke", "--workdir", str(tmp_path),
         "--steps", "6"],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    lines = [ln for ln in (out.stdout or "").splitlines() if ln.startswith("{")]
    assert out.returncode == 0 and lines, (
        f"smoke failed rc={out.returncode}\nstdout:{out.stdout[-3000:]}\n"
        f"stderr:{out.stderr[-2000:]}"
    )
    result = json.loads(lines[-1])
    assert result["ok"]
    assert result["identical"], "resumed params differ from the golden run"
    assert result["restarts"] >= 1
    assert 2 <= result["kill_step"] <= 5

    # the supervised workdir's ledger carries the whole story
    events = read_ledger(str(tmp_path / "supervised"))
    kinds = [e["event"] for e in events]
    assert "preempted" in kinds and "restart" in kinds and "resumed" in kinds
    restart = next(e for e in events if e["event"] == "restart")
    assert restart["rc"] == EXIT_PREEMPTED and restart["reason"] == "preempted"

    # telemetry-report renders the restart with time-lost accounting
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    text = report_workdir(str(tmp_path / "supervised"))
    assert "goodput lost to restarts" in text
    assert "1 restart(s)" in text

    # zero restarts/preemptions/retries observed on the clean (golden) path
    golden = read_ledger(str(tmp_path / "golden"))
    assert not [
        e for e in golden
        if e["event"] in (
            "restart", "preempted", "checkpoint_retry", "checkpoint_corrupt",
            "resumed",
        )
    ]
