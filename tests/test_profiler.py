"""Continuous-profiling subsystem tests (obs/profiler.py + the planner's
measured-cost loop): degraded paths first — CPU hosts must OMIT MFU rather
than fabricate 0/0, empty/missing capture logdirs and torn plane files must
degrade to counted warnings, alert-triggered postmortems must rate-limit,
capture-during-capture must be refused, and a constructed-but-disabled
profiler must leave the ledger event stream untouched — then the headline
drill: a real ``fit_preset`` run with ``profile_every_windows`` set ledgers
an ``op_roofline`` whose MFU agrees with the report's goodput MFU within
10%, and ``plan --measured-costs-from`` re-scores candidates from it with
measured provenance."""

import json
import os
import time

import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs import profiler as profiler_lib
from tensorflowdistributedlearning_tpu.obs.health import HealthMonitor
from tensorflowdistributedlearning_tpu.utils import xplane


# -- synthetic xplane wire bytes ---------------------------------------------
# Hand-rolled protobuf wire encoding matching the field numbers
# utils/xplane.py scans (XSpace.planes=1; XPlane.name=2, lines=3,
# event_metadata=4; XLine.name=2, events=4; XEvent.metadata_id=1,
# duration_ps=3, num_occurrences=5) — lets every state-machine test run
# without paying for a real jax.profiler trace.


def _vint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _varint_field(field: int, value: int) -> bytes:
    return _vint(field << 3) + _vint(value)


def _bytes_field(field: int, payload: bytes) -> bytes:
    return _vint((field << 3) | 2) + _vint(len(payload)) + payload


def _xspace_bytes(
    plane_name: str = "/host:CPU",
    line_name: str = "XLA Ops",
    events=(("fusion.1", 2.0, 1),),
) -> bytes:
    meta = b""
    line_events = b""
    for i, (name, dur_ms, occ) in enumerate(events, start=1):
        meta += _bytes_field(
            4,
            _varint_field(1, i)
            + _bytes_field(
                2, _varint_field(1, i) + _bytes_field(2, name.encode())
            ),
        )
        line_events += _bytes_field(
            4,
            _varint_field(1, i)
            + _varint_field(3, int(dur_ms * 1e9))  # ps
            + _varint_field(5, occ),
        )
    line = _bytes_field(2, line_name.encode()) + line_events
    plane = _bytes_field(2, plane_name.encode()) + meta + _bytes_field(3, line)
    return _bytes_field(1, plane)


def _write_xspace(dirpath, name="host.xplane.pb", **kw) -> str:
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with open(path, "wb") as f:
        f.write(_xspace_bytes(**kw))
    return path


class _FakeJaxProfiler:
    """Monkeypatched stand-in for jax.profiler.start/stop_trace: records the
    requested logdir and, on stop, writes a small synthetic plane file there
    so the parse/ledger path runs for real."""

    def __init__(self, write_planes: bool = True):
        self.write_planes = write_planes
        self.dirs = []
        self._current = None

    def start_trace(self, logdir):
        self._current = logdir
        self.dirs.append(logdir)

    def stop_trace(self):
        if self.write_planes and self._current:
            _write_xspace(
                self._current,
                events=(
                    ("dot.1", 6.0, 3),  # compute class
                    ("all-reduce.2", 3.0, 3),  # collective class
                    ("copy.3", 1.0, 3),  # hbm class
                ),
            )
        self._current = None


@pytest.fixture
def fake_tracer(monkeypatch):
    import jax

    fake = _FakeJaxProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


# -- xplane degraded paths ---------------------------------------------------


def test_xplane_synthetic_roundtrip(tmp_path):
    _write_xspace(str(tmp_path), events=(("matmul.5", 4.0, 2),
                                         ("all-reduce.1", 1.0, 2)))
    rows, skipped = xplane.op_breakdown_with_errors(
        str(tmp_path), plane_filter="/host:CPU"
    )
    assert skipped == 0
    assert [r.name for r in rows] == ["matmul.5", "all-reduce.1"]
    assert rows[0].total_ms == pytest.approx(4.0)
    assert rows[0].occurrences == 2


def test_torn_plane_file_skipped_with_count(tmp_path):
    _write_xspace(str(tmp_path), name="good.xplane.pb")
    # 0x80 continuation bytes forever: _read_varint runs off the buffer end
    with open(tmp_path / "torn.xplane.pb", "wb") as f:
        f.write(b"\x80" * 64)
    rows, skipped = xplane.op_breakdown_with_errors(
        str(tmp_path), plane_filter="/host:CPU"
    )
    assert skipped == 1
    assert [r.name for r in rows] == ["fusion.1"]  # the good file survives


def test_all_torn_returns_empty_not_raise(tmp_path):
    with open(tmp_path / "a.xplane.pb", "wb") as f:
        f.write(b"\x80" * 16)
    with open(tmp_path / "b.xplane.pb", "wb") as f:
        f.write(b"\xff" * 16)
    rows, skipped = xplane.op_breakdown_with_errors(str(tmp_path))
    assert rows == [] and skipped == 2


def test_missing_and_empty_logdir_raise_filenotfound(tmp_path):
    with pytest.raises(FileNotFoundError):
        xplane.op_breakdown_with_errors(str(tmp_path / "nope"))
    with pytest.raises(FileNotFoundError):
        xplane.op_breakdown_with_errors(str(tmp_path))  # exists, no planes


def test_plane_name_prefilter_skips_nonmatching(tmp_path):
    _write_xspace(str(tmp_path), plane_name="/host:metadata",
                  events=(("noise", 9.0, 1),))
    _write_xspace(str(tmp_path), name="dev.xplane.pb",
                  plane_name="/device:TPU:0", events=(("op.1", 2.0, 1),))
    rows, _ = xplane.op_breakdown_with_errors(str(tmp_path),
                                              plane_filter="TPU")
    assert [r.name for r in rows] == ["op.1"]


# -- MFU pricing: absent beats fabricated ------------------------------------


def _drive_windows(tel, n_windows=1, step_s=0.002, steps_per_window=2,
                   dirty=False):
    step = 0
    for _ in range(n_windows):
        for _ in range(steps_per_window):
            with tel.span(obs.SPAN_DATA_WAIT):
                pass
            with tel.span(obs.SPAN_STEP):
                time.sleep(step_s)
            step += 1
        tel.window_event(step, steps=steps_per_window, dirty=dirty)
    return step


def test_cpu_mfu_absent_never_zero(tmp_path, monkeypatch):
    monkeypatch.delenv("TFDL_PEAK_FLOPS", raising=False)
    assert profiler_lib.resolve_peak_flops() is None  # CPU host
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    tel.set_step_flops(1e9, n_devices=1)
    _drive_windows(tel)
    tel.close(steps=2)
    window = next(e for e in obs.read_ledger(str(tmp_path))
                  if e["event"] == "step_window")
    # no device peak -> MFU is OMITTED, never a fabricated 0 or a 0/0 crash
    assert "mfu" not in window


def test_mfu_priced_against_env_peak(tmp_path, monkeypatch):
    monkeypatch.setenv("TFDL_PEAK_FLOPS", "1e12")
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    tel.set_step_flops(1e9, n_devices=1)
    _drive_windows(tel, step_s=0.005)
    tel.close(steps=2)
    window = next(e for e in obs.read_ledger(str(tmp_path))
                  if e["event"] == "step_window")
    mean_s = window["step_time_ms"]["mean_ms"] / 1e3
    assert window["mfu"] == pytest.approx(1e9 / mean_s / 1e12, rel=0.05)
    assert 0 < window["mfu"] < 1


# -- profiler state machine --------------------------------------------------


def test_disabled_profiler_is_ledger_inert(tmp_path):
    def run(subdir, attach):
        wd = str(tmp_path / subdir)
        tel = obs.Telemetry(wd, run_info={"task": "t"})
        if attach:
            prof = profiler_lib.ContinuousProfiler(tel, every_windows=0)
            tel.set_profiler(prof)
        _drive_windows(tel, n_windows=3)
        tel.close(steps=6)
        return wd, [e["event"] for e in obs.read_ledger(wd)]

    _, plain = run("plain", attach=False)
    wd, with_prof = run("prof", attach=True)
    assert with_prof == plain  # identical event stream — byte-inert
    assert not os.path.isdir(os.path.join(wd, "profile"))  # no capture dirs


def test_profiler_without_workdir_degrades(fake_tracer):
    tel = obs.NULL_TELEMETRY
    prof = profiler_lib.ContinuousProfiler(tel, every_windows=1)
    assert prof.logdir is None and not prof.enabled
    assert prof._begin("cadence") is None
    assert prof.capture_timed(0.01, wait=True) is None
    prof.on_window(step=1, windows=1, alerts=[])  # no crash, no capture
    assert prof.captures == 0 and fake_tracer.dirs == []


def test_capture_during_capture_refused(tmp_path, fake_tracer):
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    prof = profiler_lib.ContinuousProfiler(tel, every_windows=1,
                                           capture_steps=2)
    tel.set_profiler(prof)
    rec = prof._begin("cadence")
    assert rec is not None and prof.capturing
    assert prof._begin("cadence") is None  # the running capture wins
    assert prof.capture_timed(0.01) is None  # timed path refuses too
    prof.note_step(0.001)
    prof.note_step(0.001)  # capture_steps reached -> background finalize
    prof.close()  # joins the finalize
    tel.close(steps=2)
    assert prof.captures == 1
    captures = [e for e in obs.read_ledger(str(tmp_path))
                if e["event"] == profiler_lib.PROFILE_CAPTURE_EVENT]
    assert len(captures) == 1
    assert captures[0]["reason"] == "cadence"
    assert captures[0]["steps"] == 2
    # only ONE trace session ever started
    assert len(fake_tracer.dirs) == 1


def test_cadence_capture_ledgers_roofline(tmp_path, fake_tracer, monkeypatch):
    monkeypatch.setenv("TFDL_PEAK_FLOPS", "1e12")
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    tel.set_step_flops(1e9, n_devices=1)
    prof = profiler_lib.ContinuousProfiler(tel, every_windows=2,
                                           capture_steps=3)
    tel.set_profiler(prof)
    _drive_windows(tel, n_windows=4, steps_per_window=3)
    tel.close(steps=12)
    events = obs.read_ledger(str(tmp_path))
    rooflines = [e for e in events
                 if e["event"] == profiler_lib.OP_ROOFLINE_EVENT]
    assert rooflines, "cadence capture must ledger an op_roofline"
    r = rooflines[0]
    fracs = r["classes"]
    assert fracs["compute_frac"] == pytest.approx(0.6, abs=0.01)
    assert fracs["collective_frac"] == pytest.approx(0.3, abs=0.01)
    assert fracs["hbm_frac"] == pytest.approx(0.1, abs=0.01)
    assert r["phase"] == "train"
    assert r["mfu"] is not None and r["mfu"] > 0
    assert r["achieved_flops_per_sec_per_chip"] > 0


def test_triggered_postmortem_rate_limited_and_alert_linked(
    tmp_path, fake_tracer
):
    """The injected-regression drill: a step_time health alert auto-captures
    exactly ONE postmortem profile stamped with the alert's id; a second
    trigger inside the rate-limit interval is refused and counted."""
    health = HealthMonitor()
    health.step_time.baseline_windows = 1
    health.step_time.factor = 1.5
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"}, health=health)
    prof = profiler_lib.ContinuousProfiler(tel, every_windows=0,
                                           capture_steps=2)
    tel.set_profiler(prof)
    _drive_windows(tel, n_windows=1, step_s=0.002)  # baseline window
    _drive_windows(tel, n_windows=1, step_s=0.02)  # 10x regression -> alert
    _drive_windows(tel, n_windows=1, step_s=0.02)  # finishes the capture
    # a second synthetic alert inside the 300s interval must be refused
    assert prof.trigger({"monitor": "step_time", "alert_id": "x"}) is None
    assert prof.rate_limited == 1
    tel.close(steps=6)
    events = obs.read_ledger(str(tmp_path))
    alerts = [e for e in events if e["event"] == "health_alert"
              and e.get("monitor") == "step_time" and not e.get("resolved")]
    captures = [e for e in events
                if e["event"] == profiler_lib.PROFILE_CAPTURE_EVENT]
    assert len(alerts) == 1 and len(captures) == 1
    assert captures[0]["reason"] == "alert"
    assert captures[0]["alert_id"] == alerts[0]["alert_id"]


def test_capture_timed_runs_off_thread(tmp_path, fake_tracer):
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    prof = profiler_lib.ContinuousProfiler(tel)
    tel.set_profiler(prof)
    out = prof.capture_timed(0.05, wait=True)
    assert out is not None and out["status"] == "complete"
    tel.close(steps=0)
    captures = [e for e in obs.read_ledger(str(tmp_path))
                if e["event"] == profiler_lib.PROFILE_CAPTURE_EVENT]
    assert len(captures) == 1
    assert captures[0]["reason"] == "admin"
    assert captures[0]["seconds"] == pytest.approx(0.05)


def test_close_mid_capture_still_ledgers(tmp_path, fake_tracer):
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    prof = profiler_lib.ContinuousProfiler(tel, every_windows=1)
    tel.set_profiler(prof)
    assert prof._begin("cadence") is not None
    tel.close(steps=0)  # run ends mid-capture: close() finishes + ledgers
    captures = [e for e in obs.read_ledger(str(tmp_path))
                if e["event"] == profiler_lib.PROFILE_CAPTURE_EVENT]
    assert len(captures) == 1


# -- measured planner costs --------------------------------------------------


def _ledger_roofline(workdir, flops_rate, coll_rate=None):
    tel = obs.Telemetry(workdir, run_info={"task": "t"})
    fields = {"phase": "train",
              "achieved_flops_per_sec_per_chip": flops_rate}
    if coll_rate is not None:
        fields["achieved_collective_bytes_per_sec"] = coll_rate
    tel.event(profiler_lib.OP_ROOFLINE_EVENT, **fields)
    tel.close(steps=0)


def test_measured_costs_from_workdir_last_event_wins(tmp_path):
    from tensorflowdistributedlearning_tpu.parallel import planner

    assert planner.measured_costs_from_workdir(str(tmp_path)) is None
    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    tel.event(profiler_lib.OP_ROOFLINE_EVENT, phase="train",
              achieved_flops_per_sec_per_chip=2e12)
    tel.event(profiler_lib.OP_ROOFLINE_EVENT, phase="train",
              achieved_flops_per_sec_per_chip=3e12,
              achieved_collective_bytes_per_sec=5e10)
    tel.close(steps=0)
    mc = planner.measured_costs_from_workdir(str(tmp_path))
    assert mc is not None
    assert mc.flops_per_sec_per_chip == pytest.approx(3e12)  # last wins
    assert mc.collective_bytes_per_sec == pytest.approx(5e10)
    assert mc.captures == 2
    assert mc.source == str(tmp_path)


def test_plan_cli_no_rooflines_exits_2(tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main([
        "plan", "--preset", "cifar10_smoke", "--n-devices", "8",
        "--measured-costs-from", str(tmp_path),
    ])
    captured = capsys.readouterr()
    assert rc == 2
    assert "op_roofline" in captured.err
    assert "--profile-every-windows" in captured.err


def test_plan_cli_measured_provenance(tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    _ledger_roofline(str(tmp_path), flops_rate=2e12, coll_rate=4e10)
    rc = main([
        "plan", "--preset", "cifar10_smoke", "--n-devices", "8",
        "--measured-costs-from", str(tmp_path),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "measured" in out
    assert "analytic" in out  # side-by-side columns


def test_plan_cli_analytic_provenance_hint(capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main(["plan", "--preset", "cifar10_smoke", "--n-devices", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "analytic" in out
    assert "--measured-costs-from" in out  # how to upgrade the cost model


# -- report / top degraded rendering ----------------------------------------


def test_report_renders_without_captures(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    _drive_windows(tel, n_windows=2)
    tel.close(steps=4)
    report = build_report(str(tmp_path))
    text = render_report(report)
    assert report.get("profiling", {}).get("captures", 0) == 0
    assert "mfu" not in report or report["mfu"]["windows"] == 0
    assert text  # renders clean, no crash, no fabricated numbers


def test_top_renders_dash_without_captures(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.top import (
        build_frame,
        render_frame,
    )

    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    _drive_windows(tel, n_windows=1)
    tel.close(steps=2)
    frame = build_frame(str(tmp_path))
    text = render_frame(frame)
    assert "mfu -" in text or "roofline -" in text


def test_top_renders_roofline_row(tmp_path, fake_tracer, monkeypatch):
    monkeypatch.setenv("TFDL_PEAK_FLOPS", "1e12")
    from tensorflowdistributedlearning_tpu.obs.top import (
        build_frame,
        render_frame,
    )

    tel = obs.Telemetry(str(tmp_path), run_info={"task": "t"})
    tel.set_step_flops(1e9, n_devices=1)
    prof = profiler_lib.ContinuousProfiler(tel, every_windows=1,
                                           capture_steps=2)
    tel.set_profiler(prof)
    _drive_windows(tel, n_windows=2)
    tel.close(steps=4)
    text = render_frame(build_frame(str(tmp_path)))
    assert "roofline" in text and "compute" in text


# -- the headline drill ------------------------------------------------------


@pytest.mark.slow
def test_continuous_profiling_headline_drill(tmp_path, monkeypatch):
    """A real fit run with ``profile_every_windows`` set: a cadence capture
    lands mid-run, its ledgered ``op_roofline`` MFU agrees with the report's
    goodput MFU within 10%, and the planner re-scores from the workdir with
    measured provenance."""
    monkeypatch.setenv("TFDL_PEAK_FLOPS", "1e12")
    from tensorflowdistributedlearning_tpu.cli import main
    from tensorflowdistributedlearning_tpu.obs.report import build_report
    from tensorflowdistributedlearning_tpu.parallel import planner
    from tensorflowdistributedlearning_tpu.train.fit import fit_preset

    workdir = str(tmp_path / "run")
    fit_preset(
        "cifar10_smoke", workdir, steps=65, batch_size=16,
        eval_every_steps=1000, profile_every_windows=2,
    )
    events = obs.read_ledger(workdir)
    rooflines = [e for e in events
                 if e["event"] == profiler_lib.OP_ROOFLINE_EVENT]
    assert rooflines, "the run must ledger at least one op_roofline"
    roofline = rooflines[-1]
    assert roofline["phase"] == "train"
    assert roofline["mfu"] is not None

    report = build_report(workdir)
    goodput_mfu = report["mfu"]["mean"]
    assert goodput_mfu is not None and goodput_mfu > 0
    # the capture's 3-step busy window and the report's clean-window mean
    # price the same steady state: within 10% of each other
    assert roofline["mfu"] == pytest.approx(goodput_mfu, rel=0.10)

    # planner loop: measured rates from this workdir re-score candidates
    mc = planner.measured_costs_from_workdir(workdir)
    assert mc is not None and mc.flops_per_sec_per_chip > 0
    rc = main([
        "plan", "--preset", "cifar10_smoke", "--n-devices", "8",
        "--measured-costs-from", workdir,
    ])
    assert rc == 0
