"""Tests for on-device augmentation (reference semantics: preprocessing.py:112-278).
The reference had no tests; its augmentation was only ever eyeballed via matplotlib
(SURVEY §4) — these are the assertions that practice lacked."""

from dataclasses import replace as dataclasses_replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import augment


def _batch(rng, b=2, h=101, w=101):
    images = rng.normal(0, 1, (b, h, w, 1)).astype(np.float32)
    masks = (rng.uniform(size=(b, h, w, 1)) > 0.5).astype(np.float32)
    return jnp.asarray(images), jnp.asarray(masks)


def test_laplacian_of_constant_is_zero():
    x = jnp.ones((1, 8, 8, 1))
    lap = augment.laplacian(x)
    # stencil sums to zero => flat interior response is zero
    assert jnp.allclose(lap[0, 2:-2, 2:-2, 0], 0.0, atol=1e-5)


def test_laplacian_detects_edge():
    x = jnp.zeros((1, 8, 8, 1)).at[:, :, 4:, :].set(1.0)
    lap = augment.laplacian(x)
    assert jnp.abs(lap[0, 4, 4, 0]) > 0.5


def test_add_laplace_channel_shape():
    x = jnp.zeros((3, 101, 101, 1))
    out = augment.add_laplace_channel(x)
    assert out.shape == (3, 101, 101, 2)
    assert jnp.array_equal(out[..., :1], x)


def test_augment_batch_shapes_and_determinism(rng):
    images, masks = _batch(rng)
    key = jax.random.PRNGKey(0)
    out1 = augment.augment_batch(key, images, masks)
    out2 = augment.augment_batch(key, images, masks)
    assert out1["images"].shape == (2, 101, 101, 2)
    assert out1["labels"].shape == (2, 101, 101, 1)
    # fixed key => bitwise identical (the determinism test SURVEY §5.2 calls for)
    assert jnp.array_equal(out1["images"], out2["images"])
    assert jnp.array_equal(out1["labels"], out2["labels"])


def test_augment_batch_per_image_randomness(rng):
    """Different images in one batch get different transforms — the reference's numpy
    shift bug applied ONE shift to all images (SURVEY §2.4.11); verify the fix."""
    img = rng.normal(0, 1, (1, 101, 101, 1)).astype(np.float32)
    images = jnp.asarray(np.repeat(img, 4, axis=0))
    masks = jnp.ones((4, 101, 101, 1), jnp.float32)
    out = augment.augment_batch(jax.random.PRNGKey(1), images, masks)
    a = np.asarray(out["images"])
    assert not np.array_equal(a[0], a[1]) or not np.array_equal(a[1], a[2])


def test_augment_mask_stays_binary(rng):
    """NEAREST interpolation for masks (reference: preprocessing.py:235-238) must not
    create fractional values."""
    images, masks = _batch(rng)
    out = augment.augment_batch(jax.random.PRNGKey(2), images, masks)
    vals = np.unique(np.asarray(out["labels"]))
    assert set(vals.tolist()) <= {0.0, 1.0}


def test_augment_jits(rng):
    images, masks = _batch(rng, b=2)
    f = jax.jit(augment.augment_batch)
    out = f(jax.random.PRNGKey(3), images, masks)
    assert out["images"].shape == (2, 101, 101, 2)


def test_identity_affine_roundtrip(rng):
    """With all randomness disabled the augmentation is pad + identity warp + central
    crop — the image must come back (nearly) unchanged."""
    cfg = augment.AugmentConfig(
        horizontal_flip=False,
        vertical_flip=False,
        rotate_range=0.0,
        crop_probability=0.0,
        height_shift_range=0.0,
        width_shift_range=0.0,
        transpose_probability=0.0,
    )
    images = jnp.asarray(rng.normal(0, 1, (1, 32, 32, 1)).astype(np.float32))
    masks = (jnp.asarray(rng.uniform(size=(1, 32, 32, 1))) > 0.5).astype(jnp.float32)

    out = augment.augment_batch(jax.random.PRNGKey(0), images, masks, cfg)
    got = np.asarray(out["images"][..., :1])
    np.testing.assert_allclose(got, np.asarray(images), atol=1e-4)
    np.testing.assert_array_equal(np.asarray(out["labels"]), np.asarray(masks))


def test_transpose_probability_knob(rng):
    """transpose_probability=0 must disable the transpose; =1 must force it."""
    cfg_off = augment.AugmentConfig(
        horizontal_flip=False, vertical_flip=False, rotate_range=0.0,
        crop_probability=0.0, height_shift_range=0.0, width_shift_range=0.0,
        transpose_probability=0.0,
    )
    cfg_on = dataclasses_replace(cfg_off, transpose_probability=1.0)
    # asymmetric image so a transpose is detectable
    img = np.zeros((1, 16, 16, 1), np.float32)
    img[0, 2, 10, 0] = 1.0
    images = jnp.asarray(img)
    masks = jnp.asarray((img > 0).astype(np.float32))
    for k in range(8):
        out = augment.augment_batch(jax.random.PRNGKey(k), images, masks, cfg_off)
        np.testing.assert_allclose(
            np.asarray(out["images"][..., :1]), img, atol=1e-4
        )
    out = augment.augment_batch(jax.random.PRNGKey(0), images, masks, cfg_on)
    np.testing.assert_allclose(
        np.asarray(out["images"][..., :1]), img.transpose(0, 2, 1, 3), atol=1e-4
    )


def test_tta_transforms_are_involutions(rng):
    x = jnp.asarray(rng.normal(0, 1, (2, 7, 7, 1)).astype(np.float32))
    for name in augment.TTA_TRANSFORMS:
        y = augment.tta_transform(x, name)
        assert jnp.array_equal(augment.tta_inverse(y, name), x)
    with pytest.raises(ValueError):
        augment.tta_transform(x, "bogus")


def test_tta_transforms_differ(rng):
    x = jnp.asarray(rng.normal(0, 1, (1, 5, 5, 1)).astype(np.float32))
    outs = [np.asarray(augment.tta_transform(x, t)) for t in ("vertical", "horizontal", "transpose")]
    for o in outs:
        assert not np.array_equal(o, np.asarray(x))
