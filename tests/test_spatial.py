"""Spatial/sequence-parallel tests on the 8-device CPU mesh: halo exchange,
H-sharded convolution exactness vs the unsharded op, ring all-gather, and
reduce-scatter (parallel/spatial.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel import spatial as sp
from tensorflowdistributedlearning_tpu.parallel.mesh import (
    SEQUENCE_AXIS,
    make_mesh,
)


@pytest.fixture(scope="module")
def seq_mesh():
    # all 8 devices on the sequence axis (batch=1)
    return make_mesh(8, sequence_parallel=8)


def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_halo_exchange_matches_zero_padding(seq_mesh):
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 16, 4, 3)).astype(np.float32)  # H=16 over 8 devs

    f = _shard_map(
        lambda a: sp.halo_exchange(a, 1),
        seq_mesh,
        (P(None, SEQUENCE_AXIS, None, None),),
        P(None, SEQUENCE_AXIS, None, None),
    )
    out = np.asarray(jax.device_get(f(x)))
    # each 2-row shard becomes 4 rows: [prev-edge, own 2 rows, next-edge]
    assert out.shape == (2, 8 * 4, 4, 3)
    shards = out.reshape(2, 8, 4, 4, 3)
    padded = np.pad(x, [(0, 0), (1, 1), (0, 0), (0, 0)])  # global zero padding
    for s in range(8):
        lo = s * 2  # global row of this shard's first own row, in padded coords
        np.testing.assert_allclose(shards[:, s], padded[:, lo : lo + 4], rtol=0, atol=0)


@pytest.mark.parametrize("stride,kh", [(1, 3), (1, 5), (2, 3)])
def test_spatial_conv_matches_unsharded(seq_mesh, stride, kh):
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (2, 32, 8, 3)).astype(np.float32)  # H=32: 4 rows/shard
    k = rng.normal(0, 0.5, (kh, 3, 3, 5)).astype(np.float32)

    ref = jax.lax.conv_general_dilated(
        x, k, (stride, stride), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )

    f = _shard_map(
        lambda a: sp.spatial_conv2d(a, jnp.asarray(k), stride=stride),
        seq_mesh,
        (P(None, SEQUENCE_AXIS, None, None),),
        P(None, SEQUENCE_AXIS, None, None),
    )
    out = jax.device_get(f(x))
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_halo_larger_than_shard_raises(seq_mesh):
    x = jnp.zeros((1, 16, 4, 1))  # 2 rows per shard
    with pytest.raises(ValueError, match="exceeds the local shard extent"):
        _shard_map(
            lambda a: sp.halo_exchange(a, 3),
            seq_mesh,
            (P(None, SEQUENCE_AXIS, None, None),),
            P(None, SEQUENCE_AXIS, None, None),
        )(x)


def test_spatial_conv_rejects_even_kernel(seq_mesh):
    x = jnp.zeros((1, 16, 4, 1))
    k = jnp.zeros((2, 2, 1, 1))
    with pytest.raises(ValueError, match="odd kernel height"):
        _shard_map(
            lambda a: sp.spatial_conv2d(a, k),
            seq_mesh,
            (P(None, SEQUENCE_AXIS, None, None),),
            P(None, SEQUENCE_AXIS, None, None),
        )(x)


def test_ring_all_gather_matches_lax(seq_mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (16, 3)).astype(np.float32)  # 2 rows per device

    # check_vma=False: the ring result IS replicated, but shard_map cannot prove
    # that statically for a ppermute-built value
    ring = jax.jit(
        jax.shard_map(
            lambda a: sp.ring_all_gather(a),
            mesh=seq_mesh,
            in_specs=(P(SEQUENCE_AXIS, None),),
            out_specs=P(None, None),
            check_vma=False,
        )
    )
    out = np.asarray(jax.device_get(ring(x)))
    np.testing.assert_allclose(out, x, rtol=0, atol=0)


def test_reduce_scatter_matches_psum_slice(seq_mesh):
    rng = np.random.default_rng(3)
    # each device holds a distinct [16, 2] block; reduce_scatter sums them and
    # hands each device rows [2i:2i+2] of the sum
    x = rng.normal(0, 1, (8, 16, 2)).astype(np.float32)

    def body(a):
        a = a[0]  # my [16, 2] block
        return sp.reduce_scatter(a, axis=0)

    f = _shard_map(
        body,
        seq_mesh,
        (P(SEQUENCE_AXIS, None, None),),
        P(SEQUENCE_AXIS, None),
    )
    out = np.asarray(jax.device_get(f(x)))  # [16, 2] stacked shards
    np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-5, atol=1e-6)


def test_shard_spatial_places_on_sequence_axis(seq_mesh):
    x = np.zeros((1, 16, 4, 1), np.float32)
    arr = sp.shard_spatial(x, seq_mesh)
    assert arr.sharding.spec == P("batch", SEQUENCE_AXIS, None, None)
    assert sp.sequence_parallel_degree(seq_mesh) == 8
