"""Metric tests pinning the reference's exact semantics (reference: core/metric.py),
including its nonstandard score*(score>t) thresholding (SURVEY §2.4.14)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.ops import (
    IOU_THRESHOLDS,
    Mean,
    iou_scores,
    mean_accuracy,
    mean_accuracy_scores,
    miou,
)
from tensorflowdistributedlearning_tpu.ops.metrics import top1_accuracy_scores


def expected_threshold_score(score: float) -> float:
    return float(np.mean([score * (score > t) for t in IOU_THRESHOLDS]))


def test_perfect_nonempty_mask():
    y = jnp.ones((1, 4, 4, 1))
    assert float(iou_scores(y, y)[0]) == pytest.approx(1.0)


def test_empty_mask_rule():
    """TP+FP+FN == 0 => score 1.0 (reference: core/metric.py:27-30)."""
    y = jnp.zeros((1, 4, 4, 1))
    assert float(iou_scores(y, y)[0]) == pytest.approx(1.0)


def test_partial_overlap_thresholding():
    # IoU = 2/6: pred covers 4 cells, truth covers 4 cells, overlap 2
    t = np.zeros((1, 4, 4, 1), np.float32)
    p = np.zeros((1, 4, 4, 1), np.float32)
    t[0, :2, :2, 0] = 1  # 4 cells
    p[0, 1:3, :2, 0] = 1  # 4 cells, 2 overlap
    iou = 2 / 6
    got = float(iou_scores(jnp.asarray(t), jnp.asarray(p))[0])
    assert got == pytest.approx(expected_threshold_score(iou))


def test_false_positive_on_empty_truth():
    t = np.zeros((1, 4, 4, 1), np.float32)
    p = np.zeros((1, 4, 4, 1), np.float32)
    p[0, 0, 0, 0] = 1
    got = float(iou_scores(jnp.asarray(t), jnp.asarray(p))[0])
    assert got == pytest.approx(0.0)  # score 0, fails every threshold


def test_streaming_miou_matches_tf_metrics_mean_semantics():
    """Two updates must average over all images, as tf.metrics.mean's running
    (total, count) does (reference: core/metric.py:42)."""
    y1 = jnp.ones((2, 4, 4, 1))
    y0 = jnp.zeros((2, 4, 4, 1))
    bad = jnp.concatenate([jnp.ones((2, 2, 4, 1)), jnp.zeros((2, 2, 4, 1))], axis=1)
    value1, state = miou(y1, y1)
    assert float(value1) == pytest.approx(1.0)
    value2, state = miou(y1, bad, state)  # IoU 0.5 per image -> thresholded 0
    assert float(value2) == pytest.approx((1.0 + 1.0 + 0.0 + 0.0) / 4)
    assert float(state.count) == 4


def test_mean_state_merge_psum_equivalence():
    a = Mean.empty().update(jnp.asarray([1.0, 0.0]))
    b = Mean.empty().update(jnp.asarray([1.0, 1.0]))
    merged = a.merge(b)
    assert float(merged.compute()) == pytest.approx(0.75)


def test_mean_accuracy():
    t = jnp.asarray(np.array([[[[1.0]], [[0.0]]], [[[1.0]], [[1.0]]]]))  # [2,2,1,1]
    p = jnp.asarray(np.array([[[[1.0]], [[1.0]]], [[[1.0]], [[1.0]]]]))
    scores = mean_accuracy_scores(t, p)
    np.testing.assert_allclose(np.asarray(scores), [0.5, 1.0])
    value, state = mean_accuracy(t, p)
    assert float(value) == pytest.approx(0.75)


def test_top1_accuracy():
    logits = jnp.asarray([[0.1, 0.9], [0.8, 0.2]])
    labels = jnp.asarray([1, 1])
    np.testing.assert_allclose(np.asarray(top1_accuracy_scores(logits, labels)), [1.0, 0.0])


def test_mean_weighted_update_excludes_padding():
    """Weights=0 must exclude values — the eval wrap-around-padding mask contract."""
    m = Mean.empty().update(jnp.asarray([1.0, 3.0, 100.0]), jnp.asarray([1.0, 1.0, 0.0]))
    assert float(m.compute()) == pytest.approx(2.0)
    # unweighted stream merged with a weighted one
    m2 = m.merge(Mean.empty().update(jnp.asarray([2.0])))
    assert float(m2.compute()) == pytest.approx(2.0)


def test_topk_accuracy_scores():
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.ops.metrics import (
        top1_accuracy_scores,
        topk_accuracy_scores,
    )

    logits = jnp.asarray(
        [
            [5.0, 4.0, 3.0, 2.0, 1.0, 0.0],  # label 1: top-1 miss, top-5 hit
            [0.0, 1.0, 2.0, 3.0, 4.0, 5.0],  # label 5: top-1 hit
            [5.0, 4.0, 3.0, 2.0, 1.0, 0.0],  # label 5: top-5 miss
        ],
        jnp.float32,
    )
    labels = jnp.asarray([1, 5, 5])
    np.testing.assert_array_equal(
        np.asarray(topk_accuracy_scores(logits, labels, k=5)), [1.0, 1.0, 0.0]
    )
    np.testing.assert_array_equal(
        np.asarray(top1_accuracy_scores(logits, labels)), [0.0, 1.0, 0.0]
    )
    # k >= class count degrades to TOP-1 (a clamped k would be a vacuous 1.0)
    np.testing.assert_array_equal(
        np.asarray(topk_accuracy_scores(logits, labels, k=10)), [0.0, 1.0, 0.0]
    )


def test_cosine_schedule_warmup_and_decay():
    """Asserts on the schedule make_lr_schedule actually builds from the config
    (not a hand-made optax schedule), so wiring regressions are caught."""
    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.train.step import make_lr_schedule

    sched = make_lr_schedule(
        TrainConfig(lr=0.4, lr_schedule="cosine", lr_warmup_steps=10, lr_decay_steps=100)
    )
    assert float(sched(0)) == 0.0
    assert float(sched(10)) == pytest.approx(0.4)
    assert float(sched(100)) < 1e-3
    # warmup=0: the first step runs at PEAK lr, not zero
    no_warmup = make_lr_schedule(
        TrainConfig(lr=0.4, lr_schedule="cosine", lr_warmup_steps=0, lr_decay_steps=100)
    )
    assert float(no_warmup(0)) == pytest.approx(0.4)
    assert float(no_warmup(100)) < 1e-3
    # exponential default: reference semantics (halves at lr_decay_steps)
    exp = make_lr_schedule(TrainConfig(lr=0.4, lr_decay_steps=100))
    assert float(exp(100)) == pytest.approx(0.2)


def test_unknown_lr_schedule_rejected():
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="lr_schedule"):
        TrainConfig(lr_schedule="linear")
