"""Metric tests pinning the reference's exact semantics (reference: core/metric.py),
including its nonstandard score*(score>t) thresholding (SURVEY §2.4.14)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.ops import (
    IOU_THRESHOLDS,
    Mean,
    iou_scores,
    mean_accuracy,
    mean_accuracy_scores,
    miou,
)
from tensorflowdistributedlearning_tpu.ops.metrics import top1_accuracy_scores


def expected_threshold_score(score: float) -> float:
    return float(np.mean([score * (score > t) for t in IOU_THRESHOLDS]))


def test_perfect_nonempty_mask():
    y = jnp.ones((1, 4, 4, 1))
    assert float(iou_scores(y, y)[0]) == pytest.approx(1.0)


def test_empty_mask_rule():
    """TP+FP+FN == 0 => score 1.0 (reference: core/metric.py:27-30)."""
    y = jnp.zeros((1, 4, 4, 1))
    assert float(iou_scores(y, y)[0]) == pytest.approx(1.0)


def test_partial_overlap_thresholding():
    # IoU = 2/6: pred covers 4 cells, truth covers 4 cells, overlap 2
    t = np.zeros((1, 4, 4, 1), np.float32)
    p = np.zeros((1, 4, 4, 1), np.float32)
    t[0, :2, :2, 0] = 1  # 4 cells
    p[0, 1:3, :2, 0] = 1  # 4 cells, 2 overlap
    iou = 2 / 6
    got = float(iou_scores(jnp.asarray(t), jnp.asarray(p))[0])
    assert got == pytest.approx(expected_threshold_score(iou))


def test_false_positive_on_empty_truth():
    t = np.zeros((1, 4, 4, 1), np.float32)
    p = np.zeros((1, 4, 4, 1), np.float32)
    p[0, 0, 0, 0] = 1
    got = float(iou_scores(jnp.asarray(t), jnp.asarray(p))[0])
    assert got == pytest.approx(0.0)  # score 0, fails every threshold


def test_streaming_miou_matches_tf_metrics_mean_semantics():
    """Two updates must average over all images, as tf.metrics.mean's running
    (total, count) does (reference: core/metric.py:42)."""
    y1 = jnp.ones((2, 4, 4, 1))
    y0 = jnp.zeros((2, 4, 4, 1))
    bad = jnp.concatenate([jnp.ones((2, 2, 4, 1)), jnp.zeros((2, 2, 4, 1))], axis=1)
    value1, state = miou(y1, y1)
    assert float(value1) == pytest.approx(1.0)
    value2, state = miou(y1, bad, state)  # IoU 0.5 per image -> thresholded 0
    assert float(value2) == pytest.approx((1.0 + 1.0 + 0.0 + 0.0) / 4)
    assert float(state.count) == 4


def test_mean_state_merge_psum_equivalence():
    a = Mean.empty().update(jnp.asarray([1.0, 0.0]))
    b = Mean.empty().update(jnp.asarray([1.0, 1.0]))
    merged = a.merge(b)
    assert float(merged.compute()) == pytest.approx(0.75)


def test_mean_accuracy():
    t = jnp.asarray(np.array([[[[1.0]], [[0.0]]], [[[1.0]], [[1.0]]]]))  # [2,2,1,1]
    p = jnp.asarray(np.array([[[[1.0]], [[1.0]]], [[[1.0]], [[1.0]]]]))
    scores = mean_accuracy_scores(t, p)
    np.testing.assert_allclose(np.asarray(scores), [0.5, 1.0])
    value, state = mean_accuracy(t, p)
    assert float(value) == pytest.approx(0.75)


def test_top1_accuracy():
    logits = jnp.asarray([[0.1, 0.9], [0.8, 0.2]])
    labels = jnp.asarray([1, 1])
    np.testing.assert_allclose(np.asarray(top1_accuracy_scores(logits, labels)), [1.0, 0.0])


def test_mean_weighted_update_excludes_padding():
    """Weights=0 must exclude values — the eval wrap-around-padding mask contract."""
    m = Mean.empty().update(jnp.asarray([1.0, 3.0, 100.0]), jnp.asarray([1.0, 1.0, 0.0]))
    assert float(m.compute()) == pytest.approx(2.0)
    # unweighted stream merged with a weighted one
    m2 = m.merge(Mean.empty().update(jnp.asarray([2.0])))
    assert float(m2.compute()) == pytest.approx(2.0)
