"""Parameter EMA (train/step.py:ema_tracker): pass-through optimizer stage
whose state is the exponential moving average of the parameter trajectory,
consumed by eval/best-export through with_ema_params."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowdistributedlearning_tpu.config import TrainConfig
from tensorflowdistributedlearning_tpu.train.step import (
    ema_tracker,
    find_ema_params,
    make_optimizer,
    with_ema_params,
)


def test_ema_tracker_matches_manual_trajectory():
    """After k sgd steps, the tracked EMA equals the hand-rolled recurrence
    over the post-update parameter values — and the updates themselves are
    UNCHANGED by the tracker (identical final params with or without it)."""
    decay = 0.9
    params = {"w": jnp.array([1.0, -2.0]), "b": jnp.array(0.5)}
    grads = [
        {"w": jnp.array([0.1, 0.2]), "b": jnp.array(-0.3)},
        {"w": jnp.array([-0.4, 0.0]), "b": jnp.array(0.2)},
        {"w": jnp.array([0.05, -0.1]), "b": jnp.array(0.0)},
    ]
    plain = optax.sgd(0.1, momentum=0.9)
    tracked = optax.chain(optax.sgd(0.1, momentum=0.9), ema_tracker(decay))

    p_plain, s_plain = dict(params), plain.init(params)
    p_track, s_track = dict(params), tracked.init(params)
    ema_manual = jax.tree.map(lambda x: x, params)
    for g in grads:
        u, s_plain = plain.update(g, s_plain, p_plain)
        p_plain = optax.apply_updates(p_plain, u)
        u, s_track = tracked.update(g, s_track, p_track)
        p_track = optax.apply_updates(p_track, u)
        ema_manual = jax.tree.map(
            lambda e, p: decay * e + (1 - decay) * p, ema_manual, p_plain
        )
    # updates pass through unchanged
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), p_plain, p_track
    )
    ema = find_ema_params(s_track)
    assert ema is not None
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), ema_manual, ema
    )


def test_ema_initializes_at_params():
    params = {"w": jnp.array([3.0])}
    tx = optax.chain(optax.sgd(0.1), ema_tracker(0.99))
    state = tx.init(params)
    np.testing.assert_allclose(find_ema_params(state)["w"], params["w"])


def test_find_ema_none_without_tracker():
    params = {"w": jnp.array([1.0])}
    assert find_ema_params(optax.adam(1e-3).init(params)) is None


def test_make_optimizer_wires_ema_for_every_family():
    params = {"kernel": jnp.ones((2, 2))}
    for opt in ("adam", "sgd", "lars"):
        cfg = TrainConfig(optimizer=opt, lr=0.1, ema_decay=0.999)
        state = make_optimizer(cfg).init(params)
        assert find_ema_params(state) is not None, opt
        off = make_optimizer(TrainConfig(optimizer=opt, lr=0.1))
        assert find_ema_params(off.init(params)) is None, opt


def test_with_ema_params_swaps_eval_view():
    """with_ema_params returns the SAME treedef with EMA leaf values (jit
    executables cache-hit), and is the identity when nothing is tracked."""
    import numpy as _np

    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.train.state import create_train_state

    cfg = ModelConfig(
        num_classes=3,
        input_shape=(8, 8),
        input_channels=1,
        n_blocks=(1, 1, 1),
        block_type="basic_block",
        width_multiplier=0.25,
        output_stride=None,
    )
    model = build_model(cfg)
    sample = _np.zeros((1, 8, 8, 1), _np.float32)
    tx = make_optimizer(TrainConfig(optimizer="sgd", lr=0.5, ema_decay=0.5))
    state = create_train_state(model, tx, jax.random.PRNGKey(0), sample)
    # one synthetic update moves params away from the (param-initialized) EMA
    grads = jax.tree.map(jnp.ones_like, state.params)
    state = state.apply_gradients(grads, state.batch_stats)
    view = with_ema_params(state)
    assert jax.tree.structure(view.params) == jax.tree.structure(state.params)
    moved = jax.tree.leaves(
        jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), view.params, state.params
        )
    )
    assert max(moved) > 0  # the eval view differs from the live params
    # identity without a tracker
    tx0 = make_optimizer(TrainConfig(optimizer="sgd", lr=0.5))
    state0 = create_train_state(model, tx0, jax.random.PRNGKey(0), sample)
    assert with_ema_params(state0) is state0


def test_fit_best_export_carries_ema_params(tmp_path):
    """End to end: with ema_decay set, the best-exported checkpoint's params
    are the EMA (differ from the live params), and restore_best serves them."""
    import numpy as _np

    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    model_cfg = ModelConfig(
        num_classes=3,
        input_shape=(8, 8),
        input_channels=1,
        n_blocks=(1, 1, 1),
        block_type="basic_block",
        width_multiplier=0.25,
        output_stride=None,
    )
    train_cfg = TrainConfig(
        optimizer="sgd",
        lr=0.5,  # big steps keep params visibly away from their EMA
        ema_decay=0.9,
        checkpoint_every_steps=4,
        n_devices=1,
    )
    trainer = ClassifierTrainer(
        str(tmp_path / "run"), None, model_cfg, train_cfg
    )
    trainer.fit(batch_size=8, steps=4, eval_every_steps=4)
    # same step, two lanes: the periodic checkpoint holds the LIVE params,
    # the best export holds the EMA view
    template = trainer._host_template()
    ckpt = trainer._checkpointer()
    try:
        live = ckpt.restore_latest(template)
        best = ckpt.restore_best(template)
    finally:
        ckpt.close()
    assert int(jax.device_get(live.step)) == int(jax.device_get(best.step)) == 4
    diffs = jax.tree.leaves(
        jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b)))),
            best.params,
            live.params,
        )
    )
    assert max(diffs) > 1e-6, "best export should store the EMA view"
    # and the stored EMA view equals the EMA tracked in the live opt_state
    ema = find_ema_params(live.opt_state)
    jax.tree.map(
        lambda a, b: _np.testing.assert_allclose(
            _np.asarray(a), _np.asarray(b), rtol=1e-6
        ),
        best.params,
        ema,
    )


def test_ema_decay_validation():
    with pytest.raises(ValueError, match="ema_decay"):
        TrainConfig(ema_decay=1.0)
    with pytest.raises(ValueError, match="ema_decay"):
        TrainConfig(ema_decay=-0.1)


def test_serving_falls_back_to_ema_without_best_export(tmp_path):
    """Interrupt before any best export: restore falls back to the periodic
    checkpoint (live trajectory), and serving_fn must still serve the EMA
    weights (train/trainer.py + train/fit.py apply with_ema_params before
    dropping opt_state)."""
    import numpy as _np

    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    model_cfg = ModelConfig(
        num_classes=3,
        input_shape=(8, 8),
        input_channels=1,
        n_blocks=(1, 1, 1),
        block_type="basic_block",
        width_multiplier=0.25,
        output_stride=None,
    )
    train_cfg = TrainConfig(
        optimizer="sgd",
        lr=0.5,
        ema_decay=0.9,
        checkpoint_every_steps=2,
        n_devices=1,
    )
    trainer = ClassifierTrainer(str(tmp_path / "run"), None, model_cfg, train_cfg)
    trainer.fit(batch_size=8, steps=2, eval_every_steps=100)
    # simulate an interrupted run: periodic checkpoints landed but the final
    # best export never happened
    import shutil

    shutil.rmtree(tmp_path / "run" / "export" / "best")

    template = trainer._host_template()
    ckpt = trainer._checkpointer()
    try:
        # restore_best now falls back to the latest PERIODIC checkpoint, whose
        # params are the live trajectory — exactly the hazard under test
        live = ckpt.restore_latest(template)
        fallback = ckpt.restore_best(template)
    finally:
        ckpt.close()
    jax.tree.map(
        lambda a, b: _np.testing.assert_array_equal(_np.asarray(a), _np.asarray(b)),
        fallback.params,
        live.params,
    )
    ema = find_ema_params(live.opt_state)
    diffs = jax.tree.leaves(
        jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b)))),
            ema,
            live.params,
        )
    )
    assert max(diffs) > 1e-6, "precondition: EMA visibly differs from live"

    served = trainer.serving_fn()
    # the closure's weights are not directly reachable; compare served logits
    # against forwarding the EMA params explicitly
    x = _np.random.default_rng(0).normal(0, 1, (2, 8, 8, 1)).astype(_np.float32)
    out = served(x)["probabilities"]
    from tensorflowdistributedlearning_tpu.models import build_model

    model = build_model(model_cfg)
    logits = model.apply(
        {"params": ema, "batch_stats": live.batch_stats}, jnp.asarray(x), train=False
    )
    expect = jax.nn.softmax(logits, axis=-1)
    _np.testing.assert_allclose(
        _np.asarray(out), _np.asarray(expect), rtol=1e-5, atol=1e-5
    )
