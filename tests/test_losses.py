"""Lovász hinge tests against an independent numpy oracle (the reference shipped its
loss untested — reference: core/losses.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.ops import (
    lovasz_hinge,
    lovasz_hinge_flat,
    lovasz_loss,
)
from tensorflowdistributedlearning_tpu.ops.losses import (
    sigmoid_cross_entropy,
    softmax_cross_entropy,
)


def np_lovasz_hinge_flat(logits, labels):
    """Straight-from-the-paper numpy implementation (Berman et al. 2018, Alg. 1)."""
    signs = 2.0 * labels - 1.0
    errors = 1.0 - logits * signs
    order = np.argsort(-errors, kind="stable")
    errors_sorted = errors[order]
    gt_sorted = labels[order]
    gts = gt_sorted.sum()
    intersection = gts - np.cumsum(gt_sorted)
    union = gts + np.cumsum(1.0 - gt_sorted)
    jaccard = 1.0 - intersection / union
    jaccard[1:] = jaccard[1:] - jaccard[:-1]
    return float(np.maximum(errors_sorted, 0.0) @ jaccard)


def test_matches_numpy_oracle(rng):
    logits = rng.normal(size=128).astype(np.float32)
    labels = (rng.random(128) > 0.6).astype(np.float32)
    got = float(lovasz_hinge_flat(jnp.asarray(logits), jnp.asarray(labels)))
    want = np_lovasz_hinge_flat(logits, labels)
    assert got == pytest.approx(want, rel=1e-5)


def test_perfect_prediction_low_loss(rng):
    labels = (rng.random(64) > 0.5).astype(np.float32)
    logits = (2.0 * labels - 1.0) * 50.0  # confidently correct
    loss = float(lovasz_hinge_flat(jnp.asarray(logits), jnp.asarray(labels)))
    assert loss == pytest.approx(0.0, abs=1e-5)


def test_wrong_prediction_high_loss(rng):
    labels = (rng.random(64) > 0.5).astype(np.float32)
    logits = -(2.0 * labels - 1.0) * 50.0  # confidently wrong
    loss = float(lovasz_hinge_flat(jnp.asarray(logits), jnp.asarray(labels)))
    assert loss > 1.0


def test_all_background_image():
    # empty ground truth: union accumulates, intersection stays 0 — loss is finite and
    # pushes logits negative
    labels = np.zeros(32, np.float32)
    logits = np.full(32, 0.5, np.float32)
    loss = float(lovasz_hinge_flat(jnp.asarray(logits), jnp.asarray(labels)))
    assert np.isfinite(loss) and loss > 0


def test_per_image_averages(rng):
    logits = rng.normal(size=(4, 8, 8)).astype(np.float32)
    labels = (rng.random((4, 8, 8)) > 0.5).astype(np.float32)
    per_image = float(lovasz_hinge(jnp.asarray(logits), jnp.asarray(labels)))
    manual = np.mean(
        [np_lovasz_hinge_flat(l.ravel(), y.ravel()) for l, y in zip(logits, labels)]
    )
    assert per_image == pytest.approx(manual, rel=1e-5)


def test_ignore_mask_matches_dropping_pixels(rng):
    """Fixed-shape void handling must equal the reference's dynamic boolean_mask
    (core/losses.py:68-80): compare against the oracle run on only the valid pixels."""
    logits = rng.normal(size=64).astype(np.float32)
    labels = (rng.random(64) > 0.5).astype(np.float32)
    labels[rng.random(64) < 0.3] = 255.0  # void label
    got = float(
        lovasz_hinge(
            jnp.asarray(logits)[None], jnp.asarray(labels)[None], ignore=255
        )
    )
    keep = labels != 255.0
    want = np_lovasz_hinge_flat(logits[keep], labels[keep])
    assert got == pytest.approx(want, rel=1e-4)


def test_all_void_image_zero_loss():
    """All-void image yields 0 (the reference's tf.cond arm, core/losses.py:59-64)."""
    logits = jnp.ones((1, 16))
    labels = jnp.full((1, 16), 255.0)
    got = float(lovasz_hinge(logits, labels, ignore=255))
    assert got == pytest.approx(0.0, abs=1e-6)


def test_lovasz_loss_layout_wrappers(rng):
    y = (rng.random((2, 8, 8, 1)) > 0.5).astype(np.float32)
    p = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    nhwc = float(lovasz_loss(jnp.asarray(y), jnp.asarray(p), "NHWC"))
    nchw = float(
        lovasz_loss(
            jnp.asarray(y.transpose(0, 3, 1, 2)),
            jnp.asarray(p.transpose(0, 3, 1, 2)),
            "NCHW",
        )
    )
    assert nhwc == pytest.approx(nchw, rel=1e-6)


def test_gradients_finite_and_jittable(rng):
    y = (rng.random((2, 8, 8, 1)) > 0.5).astype(np.float32)
    p = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    grad = jax.jit(jax.grad(lambda logits: lovasz_loss(jnp.asarray(y), logits)))(
        jnp.asarray(p)
    )
    assert np.all(np.isfinite(np.asarray(grad)))


def test_aux_losses(rng):
    logits = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    labels = jnp.asarray((rng.random(8) > 0.5).astype(np.float32))
    assert np.isfinite(float(sigmoid_cross_entropy(logits, labels)))
    cls_logits = jnp.asarray(rng.normal(size=(4, 10)).astype(np.float32))
    cls_labels = jnp.asarray([1, 2, 3, 4])
    assert np.isfinite(float(softmax_cross_entropy(cls_logits, cls_labels)))


def test_label_smoothing_cross_entropy():
    """Smoothed CE matches the closed form against a one-hot/uniform mixture
    oracle; s=0 reduces to plain CE; perfect predictions keep nonzero loss."""
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops import losses as L

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2, (6, 5)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 5, 6).astype(np.int32))
    s = 0.1
    got = np.asarray(L.softmax_cross_entropy_per_example(logits, labels, s))

    logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    onehot = np.eye(5)[np.asarray(labels)]
    target = (1 - s) * onehot + s / 5
    want = -(target * logp).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    plain = np.asarray(L.softmax_cross_entropy_per_example(logits, labels, 0.0))
    np.testing.assert_allclose(
        plain, -(onehot * logp).sum(-1), rtol=1e-6, atol=1e-6
    )
    # smoothing keeps a loss floor even for confident-correct predictions
    confident = jnp.asarray(onehot * 50.0, jnp.float32)
    assert float(L.softmax_cross_entropy(confident, labels, s)) > 0.01
