"""ViT classifier (models/vit.py): forward contract, training on the SPMD mesh,
sequence-parallel (ring attention) exactness vs the unsharded model, remat
equivalence, and end-to-end fit() integration — the training-stack consumer of
parallel/ring_attention.py (beyond-parity; the reference had no attention op)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import synthetic_batches
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import (
    SEQUENCE_AXIS,
    make_mesh,
)
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.state import create_train_state

TINY_VIT = ModelConfig(
    backbone="vit",
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    patch_size=4,
    embed_dim=32,
    vit_layers=2,
    num_heads=4,
    output_stride=None,
)


def test_forward_contract():
    model = build_model(TINY_VIT)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 4) and out.dtype == jnp.float32
    assert "batch_stats" not in variables  # LayerNorm only, no BN state


def test_bfloat16_compute_keeps_float32_params_and_logits():
    import dataclasses

    cfg = dataclasses.replace(TINY_VIT, dtype="bfloat16")
    model = build_model(cfg)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert all(
        leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(variables["params"])
    )
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32


def test_loss_decreases_on_mesh():
    mesh = make_mesh(8)
    task = step_lib.ClassificationTask()
    model = build_model(TINY_VIT)
    state = mesh_lib.replicate(
        create_train_state(
            model,
            step_lib.make_optimizer(TrainConfig(lr=0.003)),
            jax.random.PRNGKey(0),
            np.zeros((1, 16, 16, 3), np.float32),
        ),
        mesh,
    )
    train_step = step_lib.make_train_step(mesh, task)
    losses = []
    for batch in synthetic_batches(
        "classification", 16, seed=5, input_shape=(16, 16), num_classes=4, steps=12
    ):
        state, metrics = train_step(state, mesh_lib.shard_batch(batch, mesh))
        losses.append(step_lib.compute_metrics(metrics)["loss"])
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_sequence_parallel_forward_matches_unsharded():
    """H-sharded ViT (ring attention + sliced position table + pmean'd pool) must
    reproduce the unsharded forward exactly (reassociation tolerance)."""
    plain = build_model(TINY_VIT)
    spatial = build_model(
        TINY_VIT, bn_axis_name=SEQUENCE_AXIS, spatial_axis_name=SEQUENCE_AXIS
    )
    rng = np.random.default_rng(6)
    images = rng.normal(0, 1, (8, 16, 16, 3)).astype(np.float32)
    variables = plain.init(jax.random.PRNGKey(1), images[:1], train=False)
    ref = jax.jit(lambda v, im: plain.apply(v, im, train=False))(variables, images)

    mesh = make_mesh(8, sequence_parallel=2)  # 8 rows per shard, patch 4

    def fwd(v, im):
        return spatial.apply(v, im, train=False)

    f = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), P("batch", SEQUENCE_AXIS, None, None)),
            out_specs=P("batch", None),
        )
    )
    from tensorflowdistributedlearning_tpu.parallel import spatial as sp

    out = f(mesh_lib.replicate(variables, mesh), sp.shard_spatial(images, mesh))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_sequence_parallel_train_step():
    """One end-to-end sequence-parallel train step (mesh (4,1,2)) runs and matches
    the pure-DP step's loss on the same global batch."""
    import dataclasses

    task = step_lib.ClassificationTask()
    plain = build_model(TINY_VIT)
    spatial = build_model(
        TINY_VIT, bn_axis_name=SEQUENCE_AXIS, spatial_axis_name=SEQUENCE_AXIS
    )
    tx = step_lib.make_optimizer(TrainConfig())
    state = create_train_state(
        plain, tx, jax.random.PRNGKey(2), np.zeros((1, 16, 16, 3), np.float32)
    )
    batch = next(
        synthetic_batches(
            "classification", 8, seed=7, input_shape=(16, 16), num_classes=4
        )
    )

    mesh_dp = make_mesh(4)
    mesh_sp = make_mesh(8, sequence_parallel=2)
    state_dp = mesh_lib.replicate(state, mesh_dp)
    state_sp = mesh_lib.replicate(state, mesh_sp).replace(apply_fn=spatial.apply)

    step_dp = step_lib.make_train_step(mesh_dp, task, donate=False)
    step_sp = step_lib.make_train_step(mesh_sp, task, donate=False, spatial=True)
    _, m_dp = step_dp(state_dp, mesh_lib.shard_batch(batch, mesh_dp))
    _, m_sp = step_sp(state_sp, mesh_lib.shard_batch_spatial(batch, mesh_sp))
    l_dp = step_lib.compute_metrics(jax.device_get(m_dp))["loss"]
    l_sp = step_lib.compute_metrics(jax.device_get(m_sp))["loss"]
    assert l_dp == pytest.approx(l_sp, rel=1e-4)


def test_remat_matches_no_remat():
    import dataclasses

    m_plain = build_model(TINY_VIT)
    m_remat = build_model(dataclasses.replace(TINY_VIT, remat=True))
    x = jnp.asarray(
        np.random.default_rng(8).normal(0, 1, (1, 16, 16, 3)), jnp.float32
    )
    variables = m_plain.init(jax.random.PRNGKey(3), x, train=False)
    out_plain = m_plain.apply(variables, x, train=False)
    out_remat = m_remat.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_remat), np.asarray(out_plain), rtol=1e-5, atol=1e-6
    )


def test_fit_end_to_end_with_sequence_parallel(tmp_path):
    """fit() trains a ViT with sequence_parallel=2: ring attention inside the
    production train loop, checkpoints + metrics included."""
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,  # synthetic data
        TINY_VIT,
        TrainConfig(seed=0, sequence_parallel=2, checkpoint_every_steps=100),
    )
    assert trainer.mesh.shape == {"batch": 4, "model": 1, "sequence": 2}
    result = trainer.fit(batch_size=8, steps=2)
    assert result.steps == 2
    assert np.isfinite(result.final_metrics["loss"])


def test_vit_requires_num_classes():
    with pytest.raises(ValueError, match="classification head"):
        cfg = ModelConfig(
            backbone="vit", input_shape=(16, 16), patch_size=4,
            embed_dim=32, vit_layers=1, num_heads=4,
        )
        model = build_model(cfg)
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 2)), train=False)


def test_fixed_seed_bitwise_stable():
    """Two identical-seed ViT training runs produce bitwise-equal loss
    sequences (the determinism contract extended to the transformer family)."""
    def run():
        mesh = make_mesh(8)
        model = build_model(TINY_VIT)
        state = mesh_lib.replicate(
            create_train_state(
                model,
                step_lib.make_optimizer(TrainConfig(lr=0.003)),
                jax.random.PRNGKey(0),
                np.zeros((1, 16, 16, 3), np.float32),
            ),
            mesh,
        )
        train_step = step_lib.make_train_step(
            mesh, step_lib.ClassificationTask(), donate=False
        )
        losses = []
        for batch in synthetic_batches(
            "classification", 16, seed=13, input_shape=(16, 16), num_classes=4,
            steps=3,
        ):
            state, metrics = train_step(state, mesh_lib.shard_batch(batch, mesh))
            losses.append(step_lib.compute_metrics(metrics)["loss"])
        return losses

    assert run() == run()


def test_fit_end_to_end_with_model_parallel(tmp_path):
    """ViT under GSPMD tensor parallelism: qkv/proj/mlp kernels shard over the
    model axis through the same fit loop (no ViT-specific TP code — the
    channel-dim spec rule covers Dense layers)."""
    from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        TINY_VIT,
        TrainConfig(seed=0, model_parallel=2, checkpoint_every_steps=100),
    )
    state = trainer._init_state()
    qkv = state.params["block1"]["attn"]["qkv"]["kernel"]
    assert MODEL_AXIS in tuple(qkv.sharding.spec)
    result = trainer.fit(batch_size=8, steps=2)
    assert result.steps == 2
    assert np.isfinite(result.final_metrics["loss"])
