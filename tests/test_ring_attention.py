"""Ring attention (parallel/ring_attention.py): exact blockwise sequence-parallel
attention must reproduce full-sequence attention bit-for-bit in float32 tolerance,
including causal masking by global positions, and be differentiable through the
ppermute rotation (beyond-parity long-context capability; arXiv:2310.01889)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    SEQUENCE_AXIS,
    make_mesh,
)
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    attention_reference,
    make_ring_attention,
    ring_attention,
)

B, S, H, D = 2, 32, 2, 8  # 8-way ring -> 4 tokens per device


def _qkv(seed: int):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(8, sequence_parallel=8)  # (1, 1, 8): pure sequence ring


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(seq_mesh, causal):
    q, k, v = _qkv(0)
    ref = attention_reference(q, k, v, causal=causal)
    out = make_ring_attention(seq_mesh, causal=causal)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


def test_composes_with_batch_parallelism():
    mesh = make_mesh(8, sequence_parallel=4)  # (batch=2, model=1, sequence=4)
    q, k, v = _qkv(1)
    ref = attention_reference(q, k, v)
    out = make_ring_attention(mesh)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_full_attention(seq_mesh, causal):
    """Reverse-mode AD flows through the scan + ppermute rotation and matches the
    full-attention gradients."""
    q, k, v = _qkv(2)
    # weight the sum so the gradient is not constant in the value tensor
    w = jnp.asarray(
        np.random.default_rng(3).normal(0, 1, (B, S, H, D)).astype(np.float32)
    )

    def ref_loss(q, k, v):
        return jnp.sum(w * attention_reference(q, k, v, causal=causal))

    spec = P(BATCH_AXIS, SEQUENCE_AXIS, None, None)

    def ring_loss(q, k, v, w):
        out = ring_attention(q, k, v, causal=causal)
        return jax.lax.psum(
            jax.lax.psum(jnp.sum(w * out), SEQUENCE_AXIS), BATCH_AXIS
        )

    sharded_loss = jax.jit(
        jax.shard_map(
            ring_loss,
            mesh=seq_mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=P(),
        )
    )
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda q, k, v: sharded_loss(q, k, v, w), argnums=(0, 1, 2))(
        q, k, v
    )
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-5, atol=5e-6
        )


def test_single_device_degenerate():
    """Ring of size 1 must reduce to plain attention (mesh with sequence=1)."""
    mesh = make_mesh(1)
    q, k, v = _qkv(4)
    out = make_ring_attention(mesh, causal=True, batch_axis=None)(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_kv_mask_matches_full_attention(seq_mesh, causal):
    """Padding masks (variable-length batches): the kv_mask rotates around the
    ring with its K/V block and the sharded result matches the full-sequence
    oracle, including rows whose every visible key is masked (exact zeros)."""
    q, k, v = _qkv(11)
    rng = np.random.default_rng(3)
    kv_mask = jnp.asarray(rng.uniform(size=(B, S)) > 0.35)
    # example 0 masks its entire FIRST ring block: under causal, its first
    # 4 queries see no visible key at all -> must return exact zeros
    kv_mask = kv_mask.at[0, :4].set(False)

    ref = attention_reference(q, k, v, causal=causal, kv_mask=kv_mask)
    out = make_ring_attention(seq_mesh, causal=causal, masked=True)(
        q, k, v, kv_mask
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    if causal:
        assert np.all(np.asarray(out)[0, :4] == 0.0)


def test_kv_mask_gradients_match(seq_mesh):
    """Differentiable through the mask path (mask itself is non-diff data)."""
    q, k, v = _qkv(12)
    rng = np.random.default_rng(5)
    kv_mask = jnp.asarray(rng.uniform(size=(B, S)) > 0.3)

    def loss_ref(q, k, v):
        return jnp.sum(
            attention_reference(q, k, v, causal=True, kv_mask=kv_mask) ** 2
        )

    spec = P(BATCH_AXIS, SEQUENCE_AXIS, None, None)

    @jax.jit
    def loss_ring(q, k, v):
        def inner(q, k, v, m):
            out = ring_attention(q, k, v, causal=True, kv_mask=m)
            return jax.lax.psum(
                jax.lax.psum(jnp.sum(out**2), SEQUENCE_AXIS), BATCH_AXIS
            )

        return jax.shard_map(
            inner,
            mesh=seq_mesh,
            in_specs=(spec, spec, spec, P(BATCH_AXIS, SEQUENCE_AXIS)),
            out_specs=P(),
        )(q, k, v, kv_mask)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_isolate_packed_documents(seq_mesh, causal):
    """Packed multi-document batches: segment ids rotate with their K/V block
    and a query attends only within its own document — exact vs the oracle."""
    q, k, v = _qkv(21)
    # documents of uneven length spanning ring-block boundaries
    segment_ids = jnp.asarray(
        np.concatenate(
            [
                np.repeat([0, 1, 2], [10, 14, 8]),  # example 0
                np.repeat([0, 1], [5, 27]),  # example 1
            ]
        ).reshape(B, S)
    )
    ref = attention_reference(q, k, v, causal=causal, segment_ids=segment_ids)
    out = make_ring_attention(seq_mesh, causal=causal, segmented=True)(
        q, k, v, segment_ids
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # cross-document isolation, verified independently of the ring: attending
    # within document 1 of example 0 must equal attending over ONLY its slice
    lo, hi = 10, 24
    sliced = attention_reference(
        q[:, lo:hi], k[:, lo:hi], v[:, lo:hi], causal=causal
    )
    np.testing.assert_allclose(
        np.asarray(out)[0, lo:hi], np.asarray(sliced)[0], atol=2e-5
    )


def test_segment_ids_compose_with_kv_mask(seq_mesh):
    """masked + segmented: padding inside a document is excluded, documents
    stay isolated, fully-padded documents return zeros."""
    q, k, v = _qkv(22)
    rng = np.random.default_rng(9)
    segment_ids = jnp.asarray(
        np.repeat([[0, 1]], B, axis=0).repeat([16, 16], axis=1)
    )
    kv_mask = jnp.asarray(rng.uniform(size=(B, S)) > 0.25)
    # example 1: document 0 entirely padding
    kv_mask = kv_mask.at[1, :16].set(False)

    ref = attention_reference(
        q, k, v, causal=False, kv_mask=kv_mask, segment_ids=segment_ids
    )
    out = make_ring_attention(seq_mesh, masked=True, segmented=True)(
        q, k, v, kv_mask, segment_ids
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # every query of the fully-padded document sees no visible key
    assert np.all(np.asarray(out)[1, :16] == 0.0)


def test_segment_ids_gradients_match(seq_mesh):
    """Backward pass through the segment-mask path matches the oracle (the
    PARITY 'differentiable end to end' claim, per mask kind)."""
    q, k, v = _qkv(23)
    segment_ids = jnp.asarray(
        np.repeat([[0, 1, 2, 3]], B, axis=0).repeat([8, 8, 8, 8], axis=1)
    )

    def loss_ref(q, k, v):
        return jnp.sum(
            attention_reference(q, k, v, causal=True, segment_ids=segment_ids)
            ** 2
        )

    spec = P(BATCH_AXIS, SEQUENCE_AXIS, None, None)

    @jax.jit
    def loss_ring(q, k, v):
        def inner(q, k, v, seg):
            out = ring_attention(q, k, v, causal=True, segment_ids=seg)
            return jax.lax.psum(
                jax.lax.psum(jnp.sum(out**2), SEQUENCE_AXIS), BATCH_AXIS
            )

        return jax.shard_map(
            inner,
            mesh=seq_mesh,
            in_specs=(spec, spec, spec, P(BATCH_AXIS, SEQUENCE_AXIS)),
            out_specs=P(),
        )(q, k, v, segment_ids)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=3e-4)
