"""Ring attention (parallel/ring_attention.py): exact blockwise sequence-parallel
attention must reproduce full-sequence attention bit-for-bit in float32 tolerance,
including causal masking by global positions, and be differentiable through the
ppermute rotation (beyond-parity long-context capability; arXiv:2310.01889)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    SEQUENCE_AXIS,
    make_mesh,
)
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    attention_reference,
    make_ring_attention,
    ring_attention,
)

B, S, H, D = 2, 32, 2, 8  # 8-way ring -> 4 tokens per device


def _qkv(seed: int):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(0, 1, (B, S, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(8, sequence_parallel=8)  # (1, 1, 8): pure sequence ring


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(seq_mesh, causal):
    q, k, v = _qkv(0)
    ref = attention_reference(q, k, v, causal=causal)
    out = make_ring_attention(seq_mesh, causal=causal)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


def test_composes_with_batch_parallelism():
    mesh = make_mesh(8, sequence_parallel=4)  # (batch=2, model=1, sequence=4)
    q, k, v = _qkv(1)
    ref = attention_reference(q, k, v)
    out = make_ring_attention(mesh)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_full_attention(seq_mesh, causal):
    """Reverse-mode AD flows through the scan + ppermute rotation and matches the
    full-attention gradients."""
    q, k, v = _qkv(2)
    # weight the sum so the gradient is not constant in the value tensor
    w = jnp.asarray(
        np.random.default_rng(3).normal(0, 1, (B, S, H, D)).astype(np.float32)
    )

    def ref_loss(q, k, v):
        return jnp.sum(w * attention_reference(q, k, v, causal=causal))

    spec = P(BATCH_AXIS, SEQUENCE_AXIS, None, None)

    def ring_loss(q, k, v, w):
        out = ring_attention(q, k, v, causal=causal)
        return jax.lax.psum(
            jax.lax.psum(jnp.sum(w * out), SEQUENCE_AXIS), BATCH_AXIS
        )

    sharded_loss = jax.jit(
        jax.shard_map(
            ring_loss,
            mesh=seq_mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=P(),
        )
    )
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda q, k, v: sharded_loss(q, k, v, w), argnums=(0, 1, 2))(
        q, k, v
    )
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-5, atol=5e-6
        )


def test_single_device_degenerate():
    """Ring of size 1 must reduce to plain attention (mesh with sequence=1)."""
    mesh = make_mesh(1)
    q, k, v = _qkv(4)
    out = make_ring_attention(mesh, causal=True, batch_axis=None)(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_kv_mask_matches_full_attention(seq_mesh, causal):
    """Padding masks (variable-length batches): the kv_mask rotates around the
    ring with its K/V block and the sharded result matches the full-sequence
    oracle, including rows whose every visible key is masked (exact zeros)."""
    q, k, v = _qkv(11)
    rng = np.random.default_rng(3)
    kv_mask = jnp.asarray(rng.uniform(size=(B, S)) > 0.35)
    # example 0 masks its entire FIRST ring block: under causal, its first
    # 4 queries see no visible key at all -> must return exact zeros
    kv_mask = kv_mask.at[0, :4].set(False)

    ref = attention_reference(q, k, v, causal=causal, kv_mask=kv_mask)
    out = make_ring_attention(seq_mesh, causal=causal, masked=True)(
        q, k, v, kv_mask
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    if causal:
        assert np.all(np.asarray(out)[0, :4] == 0.0)


def test_kv_mask_gradients_match(seq_mesh):
    """Differentiable through the mask path (mask itself is non-diff data)."""
    q, k, v = _qkv(12)
    rng = np.random.default_rng(5)
    kv_mask = jnp.asarray(rng.uniform(size=(B, S)) > 0.3)

    def loss_ref(q, k, v):
        return jnp.sum(
            attention_reference(q, k, v, causal=True, kv_mask=kv_mask) ** 2
        )

    spec = P(BATCH_AXIS, SEQUENCE_AXIS, None, None)

    @jax.jit
    def loss_ring(q, k, v):
        def inner(q, k, v, m):
            out = ring_attention(q, k, v, causal=True, kv_mask=m)
            return jax.lax.psum(
                jax.lax.psum(jnp.sum(out**2), SEQUENCE_AXIS), BATCH_AXIS
            )

        return jax.shard_map(
            inner,
            mesh=seq_mesh,
            in_specs=(spec, spec, spec, P(BATCH_AXIS, SEQUENCE_AXIS)),
            out_specs=P(),
        )(q, k, v, kv_mask)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=3e-4)
