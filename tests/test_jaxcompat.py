"""The jax version shim (utils/jaxcompat.py): importing the package must
publish the modern `jax.shard_map` / `jax.lax.axis_size` surface on older jax
builds, with axis_names→auto translated correctly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import tensorflowdistributedlearning_tpu  # noqa: F401 — installs the shim


def test_shard_map_surface_present():
    assert hasattr(jax, "shard_map")
    assert hasattr(jax.lax, "axis_size")


def test_shard_map_runs_with_keyword_api():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("batch",))

    def f(x):
        return jax.lax.psum(x, "batch")

    g = jax.shard_map(f, mesh=mesh, in_specs=P("batch"), out_specs=P("batch"))
    out = g(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 28.0))


def test_axis_size_inside_shard_map():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("batch", "model"))

    def f(x):
        return (
            x
            * jax.lax.axis_size("batch")
            * jax.lax.axis_size(("batch", "model"))
        )

    g = jax.shard_map(f, mesh=mesh, in_specs=P("batch"), out_specs=P("batch"))
    np.testing.assert_allclose(
        np.asarray(g(jnp.ones((4,)))), np.full((4,), 32.0)
    )


def test_mean_grads_normalization_still_exact():
    """The shim must not change gradient numerics: the sharded step's mean
    gradient equals the single-device gradient of the global-mean loss."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("batch",))
    x = jnp.arange(16.0).reshape(8, 2)
    w = jnp.ones((2,))

    def loss(w, x):
        return jnp.mean((x @ w) ** 2)

    ref = jax.grad(loss)(w, x)

    def sharded_grad(w, x):
        g = jax.grad(loss)(w, x)  # per-shard gradient of the SHARD mean
        return jax.lax.pmean(g, "batch")

    g = jax.shard_map(
        sharded_grad, mesh=mesh, in_specs=(P(), P("batch")), out_specs=P()
    )
    np.testing.assert_allclose(np.asarray(g(w, x)), np.asarray(ref), rtol=1e-6)


def test_install_is_idempotent():
    from tensorflowdistributedlearning_tpu.utils import jaxcompat

    before = jax.shard_map
    jaxcompat.install()
    assert jax.shard_map is before


def test_legacy_bridge_refuses_hybrid_auto_axes():
    """On the legacy bridge, hybrid (auto-axis) shard_map must fail with a
    clean NotImplementedError at the API boundary — lowering it has aborted
    the process outright (the failure mode that killed a full suite run)."""
    from tensorflowdistributedlearning_tpu.utils import jaxcompat

    if not jaxcompat.LEGACY_BRIDGE:
        pytest.skip("native jax.shard_map: hybrid mode is supported")
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("batch", "model"))
    with pytest.raises(NotImplementedError, match="auto"):
        jax.shard_map(
            lambda x: x,
            mesh=mesh,
            in_specs=P("batch"),
            out_specs=P("batch"),
            axis_names=frozenset({"batch"}),
        )
