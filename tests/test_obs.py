"""Unit tests for the obs telemetry subsystem: metrics registry, JSONL run
ledger (including the degrade-don't-crash failure paths), span API, and the
jax.monitoring recompile detector (including a forced reshape-induced
recompile)."""

import json
import os

import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs.ledger import last_run_events


# -- metrics ----------------------------------------------------------------


def test_time_histogram_percentiles():
    h = obs.TimeHistogram("t")
    for v in [0.01 * i for i in range(1, 101)]:  # 0.01..1.00
        h.record(v)
    s = h.summary()
    assert s["count"] == 100
    assert s["p50_s"] == pytest.approx(0.505, abs=0.02)
    assert s["p90_s"] == pytest.approx(0.901, abs=0.02)
    assert s["p99_s"] == pytest.approx(0.99, abs=0.02)
    assert s["max_s"] == pytest.approx(1.0)
    assert s["total_s"] == pytest.approx(50.5)


def test_time_summary_skip_first_and_empty():
    assert obs.time_summary([5.0, 1.0, 1.0], skip_first=1)["mean_s"] == 1.0
    # skipping everything falls back to the full sequence, not a crash
    assert obs.time_summary([5.0], skip_first=1)["mean_s"] == 5.0
    with pytest.raises(ValueError, match="no samples"):
        obs.time_summary([])


def test_histogram_window_deltas():
    h = obs.TimeHistogram("t")
    h.record(1.0)
    mark = len(h)
    h.record(2.0)
    h.record(3.0)
    assert h.samples_since(mark) == [2.0, 3.0]


def test_histogram_memory_is_bounded_with_exact_totals():
    """The unbounded-list regression pin: a histogram nothing drains retains
    at most max_samples raw floats, while count/total stay EXACT — long runs
    cannot grow host memory without bound."""
    cap = 64
    h = obs.TimeHistogram("t", max_samples=cap)
    n = 10 * cap
    for i in range(n):
        h.record(0.5)
    assert len(h.samples) == cap  # the ring bound
    assert len(h) == n  # exact count survives eviction
    assert h.total_s == pytest.approx(0.5 * n)
    s = h.summary()
    assert s["count"] == n and s["total_s"] == pytest.approx(0.5 * n)
    assert s["p50_s"] == 0.5
    # drain: retained samples, exact interval accounting, then empty
    win = h.drain()
    assert isinstance(win, list) and len(win) == cap
    assert win.count == n and win.total_s == pytest.approx(0.5 * n)
    assert len(h) == 0 and h.samples == []
    # lifetime (Prometheus) series is monotonic across drains
    h.record(1.0)
    assert h.lifetime_count == n + 1
    assert h.lifetime_total_s == pytest.approx(0.5 * n + 1.0)


def test_histogram_samples_since_across_eviction():
    h = obs.TimeHistogram("t", max_samples=4)
    h.record(1.0)
    h.record(2.0)
    mark = len(h)  # 2
    for v in (3.0, 4.0, 5.0, 6.0):  # evicts 1.0 and 2.0
        h.record(v)
    # everything after the mark is still retained here
    assert h.samples_since(mark) == [3.0, 4.0, 5.0, 6.0]
    # a mark the ring has evicted past resolves to everything retained
    assert h.samples_since(0) == [3.0, 4.0, 5.0, 6.0]


def test_drain_semantics_unchanged_for_unsaturated_windows():
    """Existing callers' contract: below the cap, drain returns exactly the
    recorded samples and the window sums match the naive sum."""
    from tensorflowdistributedlearning_tpu.obs.metrics import (
        window_count,
        window_total_s,
    )

    h = obs.TimeHistogram("t")
    for v in (0.1, 0.2, 0.3):
        h.record(v)
    win = h.drain()
    assert list(win) == [0.1, 0.2, 0.3]
    assert window_total_s(win) == pytest.approx(sum(win))
    assert window_count(win) == 3
    # plain lists (tests, deferred-window payloads) still work
    assert window_total_s([1.0, 2.0]) == 3.0
    assert window_count(None) == 0


def test_render_prometheus_naming_and_types():
    reg = obs.MetricsRegistry()
    reg.counter("serve/requests").inc(7)
    reg.gauge("serve/queue_depth").set(3)
    reg.histogram("span/step").record(0.25)
    text = reg.render_prometheus()
    assert "# TYPE tfdl_serve_requests_total counter" in text
    assert "tfdl_serve_requests_total 7" in text
    assert "# TYPE tfdl_serve_queue_depth gauge" in text
    assert "tfdl_serve_queue_depth 3" in text
    assert "# TYPE tfdl_span_step_seconds summary" in text
    assert 'tfdl_span_step_seconds{quantile="0.5"} 0.25' in text
    assert "tfdl_span_step_seconds_count 1" in text
    assert text.endswith("\n")


def test_registry_get_or_create_and_snapshot():
    reg = obs.MetricsRegistry()
    reg.counter("compiles").inc()
    reg.counter("compiles").inc(2)
    reg.gauge("lr").set(0.1)
    reg.histogram("step").record(0.5)
    snap = reg.snapshot()
    assert snap["counters"]["compiles"] == 3
    assert snap["gauges"]["lr"] == 0.1
    assert snap["histograms"]["step"]["count"] == 1
    # empty instruments stay out of the snapshot
    reg.histogram("never_recorded")
    assert "never_recorded" not in reg.snapshot()["histograms"]


def test_step_timer_shares_the_histogram_implementation():
    from tensorflowdistributedlearning_tpu.utils.profiling import StepTimer

    t = StepTimer(items_per_step=4)
    for _ in range(3):
        t.start()
        t.stop()
    s = t.summary(skip_first=1)
    assert s["steps"] == 2
    assert {"p50_s", "p90_s", "p99_s", "items_per_sec"} <= set(s)
    assert len(t.times) == 3


# -- ledger -----------------------------------------------------------------


def test_ledger_roundtrip(tmp_path):
    led = obs.RunLedger(str(tmp_path))
    assert led.enabled
    led.event("run_header", schema_version=1)
    led.event("step_window", step=10, data_wait_s=0.1)
    led.close()
    events = obs.read_ledger(str(tmp_path))
    assert [e["event"] for e in events] == ["run_header", "step_window"]
    assert all("t" in e for e in events)
    assert events[1]["step"] == 10


def test_ledger_appends_and_last_run_selects_final_header(tmp_path):
    for run in range(2):
        led = obs.RunLedger(str(tmp_path))
        led.event("run_header", run=run)
        led.event("step_window", step=run * 100)
        led.close()
    events = obs.read_ledger(str(tmp_path))
    assert len(events) == 4
    last = last_run_events(events)
    assert len(last) == 2 and last[0]["run"] == 1


def test_ledger_unwritable_workdir_degrades_to_warning(tmp_path, caplog):
    target = tmp_path / "not_a_dir"
    target.write_text("occupied")
    led = obs.RunLedger(str(target))  # workdir is a file: cannot create/open
    assert not led.enabled
    led.event("step_window", step=1)  # must be a silent no-op, never a crash
    led.close()
    assert any("ledger disabled" in r.message for r in caplog.records)


def test_ledger_mid_run_write_failure_disables(tmp_path, caplog):
    led = obs.RunLedger(str(tmp_path))
    led.event("run_header")
    led._f.close()  # simulate the fd dying under the writer (volume gone)
    led.event("step_window", step=1)
    assert not led.enabled
    led.event("step_window", step=2)  # still a no-op
    assert any("disabled mid-run" in r.message for r in caplog.records)


def test_ledger_numpy_values_serialize(tmp_path):
    import numpy as np

    led = obs.RunLedger(str(tmp_path))
    led.event("eval", loss=np.float32(1.5), steps=np.int64(3))
    led.close()
    e = obs.read_ledger(str(tmp_path))[0]
    assert e["loss"] == 1.5 and e["steps"] == 3


def test_read_ledger_tolerates_truncated_tail(tmp_path):
    path = os.path.join(str(tmp_path), obs.LEDGER_FILENAME)
    with open(path, "w") as f:
        f.write(json.dumps({"event": "run_header", "t": 1.0}) + "\n")
        f.write('{"event": "step_window", "t": 2.0, "ste')  # killed mid-write
    events = obs.read_ledger(str(tmp_path))
    assert len(events) == 1 and events[0]["event"] == "run_header"


# -- telemetry façade --------------------------------------------------------


def test_null_telemetry_is_inert(tmp_path):
    tel = obs.NULL_TELEMETRY
    with tel.span("step"):
        pass
    tel.window_event(1, steps=1)
    tel.eval_event(1, {"loss": 1.0}, 0.1)
    tel.memory_event()
    tel.close()
    assert tel.ledger is None and tel.detector is None


def test_telemetry_spans_feed_window_events(tmp_path):
    tel = obs.Telemetry(str(tmp_path), is_main=True, run_info={"task": "test"})
    for _ in range(3):
        with tel.span(obs.SPAN_DATA_WAIT):
            pass
        with tel.span(obs.SPAN_STEP):
            pass
    tel.window_event(3, steps=3, images_per_sec=100.0, scalars={"loss": 0.5})
    tel.close(steps=3)
    events = obs.read_ledger(str(tmp_path))
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_header" and kinds[-1] == "run_end"
    window = next(e for e in events if e["event"] == "step_window")
    assert window["data_wait_s"] >= 0 and window["compute_s"] > 0
    assert window["step_time_ms"]["p50_ms"] >= 0
    assert window["scalars"]["loss"] == 0.5
    assert window["images_per_sec"] == 100.0
    # window marks advanced: a second window only sees new samples
    with tel.span(obs.SPAN_STEP):
        pass
    assert len(tel._span_delta(obs.SPAN_STEP)) == 1


def test_interrupted_close_reports_run_incomplete(tmp_path):
    """The trainers' finally blocks close with interrupted=True on exception
    exits; the report must not render a crashed run as completed."""
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    tel = obs.Telemetry(str(tmp_path), is_main=True, run_info={"task": "t"})
    tel.close(interrupted=True)
    report = build_report(str(tmp_path))
    assert not report["run"]["completed"]
    assert "interrupted" in render_report(report)
    # close() on success records a clean run_end — second close is a no-op
    tel2 = obs.Telemetry(str(tmp_path / "ok"), is_main=True)
    tel2.close(steps=5)
    tel2.close(interrupted=True)  # the finally-block close after success
    assert build_report(str(tmp_path / "ok"))["run"]["completed"]


def test_telemetry_readonly_workdir_never_crashes(tmp_path, caplog):
    target = tmp_path / "file_in_the_way"
    target.write_text("occupied")
    tel = obs.Telemetry(str(target), is_main=True)
    with tel.span("step"):
        pass
    tel.window_event(1, steps=1)
    tel.memory_event()
    tel.close()
    assert any("ledger disabled" in r.message for r in caplog.records)


def test_telemetry_memory_event_has_host_rss(tmp_path):
    tel = obs.Telemetry(str(tmp_path), is_main=True)
    tel.memory_event(step=0)
    tel.close()
    mem = next(
        e for e in obs.read_ledger(str(tmp_path)) if e["event"] == "memory"
    )
    assert "devices" in mem
    # CPU backends report no per-device stats; host RSS keeps the snapshot
    # meaningful (Linux: always present)
    assert mem.get("host_rss_bytes", 0) > 0


# -- recompile detector ------------------------------------------------------


def test_recompile_detector_counts_forced_reshape_recompile():
    import jax
    import jax.numpy as jnp

    assert obs.RecompileDetector.available()
    det = obs.RecompileDetector().attach()
    try:

        @jax.jit
        def f(x):
            return x * 2 + 1

        f(jnp.ones((3,)))  # warmup compile: counted, not flagged
        warm_count = det.compile_count
        assert warm_count >= 1
        assert det.post_warmup_count == 0
        det.mark_warm()
        f(jnp.ones((3,)))  # cache hit: no compile event
        assert det.compile_count == warm_count
        f(jnp.ones((5,)))  # reshape => retrace + recompile
        assert det.post_warmup_count >= 1
        event = det.post_warmup_events[0]
        assert event.duration_s > 0 and event.post_warmup
    finally:
        det.detach()


def test_recompile_phase_warmup_is_independent():
    import jax
    import jax.numpy as jnp

    phases = ["step"]
    det = obs.RecompileDetector(phase_fn=lambda: phases[0]).attach()
    try:
        det.mark_warm("eval")  # only eval is warm

        @jax.jit
        def g(x):
            return x - 1

        g(jnp.ones((7,)))  # compiles in phase "step": not flagged
        assert det.post_warmup_count == 0
        det.mark_warm("step")
        g(jnp.ones((9,)))  # now flagged
        assert det.post_warmup_count >= 1
        assert det.post_warmup_events[0].phase == "step"
    finally:
        det.detach()


# -- config validation (the ZeroDivisionError-mid-run guards) ----------------


@pytest.mark.parametrize(
    "field",
    [
        "train_log_every_steps",
        "checkpoint_every_steps",
        "eval_every_steps",
        "telemetry_memory_every_windows",
    ],
)
def test_cadence_knobs_reject_zero(field):
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    with pytest.raises(ValueError, match=field):
        TrainConfig(**{field: 0})


def test_negative_eval_throttle_rejected():
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="eval_throttle_secs"):
        TrainConfig(eval_throttle_secs=-1)


def test_valid_cadence_accepted():
    from tensorflowdistributedlearning_tpu.config import TrainConfig

    cfg = TrainConfig(
        train_log_every_steps=1, checkpoint_every_steps=1, eval_every_steps=1
    )
    assert cfg.telemetry and cfg.telemetry_memory_every_windows >= 1
