"""Checkpoint manager tests: periodic cadence, auto-resume, best-k export with the
comparison the right way around (SURVEY §2.4.4 — the reference exported on
regressions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import make_mesh, replicate
from tensorflowdistributedlearning_tpu.train import create_train_state, make_optimizer
from tensorflowdistributedlearning_tpu.train.checkpoint import CheckpointManager

TINY = ModelConfig(
    n_blocks=(1, 1, 1), input_shape=(32, 32), base_depth=8, width_multiplier=0.0625
)


@pytest.fixture(scope="module")
def state(eight_devices_module=None):
    cfg = TINY
    model = build_model(cfg)
    tx = make_optimizer(TrainConfig())
    sample = np.zeros((1, 32, 32, 2), np.float32)
    mesh = make_mesh(8)
    return replicate(
        create_train_state(model, tx, jax.random.PRNGKey(0), sample), mesh
    )


def _bump(state, n):
    return state.replace(
        step=state.step + n,
        params=jax.tree.map(lambda x: x + 1.0, state.params),
    )


def test_save_restore_roundtrip(state, tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "m"), save_every_steps=2)
    s1 = _bump(state, 2)
    assert ckpt.maybe_save(s1)
    restored = ckpt.restore_latest(state)
    assert int(restored.step) == 2
    a = jax.tree.leaves(s1.params)[0]
    b = jax.tree.leaves(restored.params)[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_maybe_save_respects_cadence(state, tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "m"), save_every_steps=4)
    assert not ckpt.maybe_save(_bump(state, 3))  # off-cadence
    assert ckpt.maybe_save(_bump(state, 4))
    assert ckpt.latest_step() == 4
    ckpt.close()


def test_restore_latest_without_checkpoint_returns_template(state, tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "m"))
    restored = ckpt.restore_latest(state)
    assert restored is state
    ckpt.close()


def test_best_export_keeps_top_k_and_right_direction(state, tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "m"), save_best=2)
    # offer three states with mIOU 0.5 (step 1), 0.9 (step 2), 0.2 (step 3)
    for step, miou in [(1, 0.5), (2, 0.9), (3, 0.2)]:
        s = state.replace(step=jnp.asarray(step, jnp.int32))
        ckpt.export_best(s, {"metrics/mean_iou": miou})
    # best must be the 0.9 run, NOT the most recent worse one
    assert ckpt.best_step() == 2
    restored = ckpt.restore_best(state)
    assert int(restored.step) == 2
    ckpt.close()


def test_restore_best_falls_back_to_latest(state, tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "m"), save_every_steps=1)
    s1 = _bump(state, 1)
    ckpt.save(s1)
    restored = ckpt.restore_best(state)  # no best export yet
    assert int(restored.step) == 1
    ckpt.close()


def test_async_checkpointing_roundtrip(tmp_path):
    """async_checkpointing=True: saves overlap training, and restore_latest
    waits for in-flight saves before reading."""
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train.checkpoint import CheckpointManager
    from tensorflowdistributedlearning_tpu.train.state import create_train_state

    cfg = ModelConfig(input_shape=(16, 16), n_blocks=(1, 1, 1), base_depth=8)
    model = build_model(cfg)
    state = create_train_state(
        model,
        step_lib.make_optimizer(TrainConfig()),
        jax.random.PRNGKey(0),
        np.zeros((1, 16, 16, 2), np.float32),
    )
    ckpt = CheckpointManager(
        str(tmp_path), save_every_steps=1, async_checkpointing=True
    )
    assert ckpt.save(state, force=True)
    restored = ckpt.restore_latest(state.replace(step=state.step + 99))
    assert int(jax.device_get(restored.step)) == int(jax.device_get(state.step))
    ckpt.close()


def test_optimizer_change_on_resume_raises_clearly(tmp_path):
    """Resuming a checkpoint with a different optimizer (Adam vs SGD changes the
    opt_state pytree) fails with an explanation, not a raw orbax tree error."""
    import jax

    from tensorflowdistributedlearning_tpu.config import TrainConfig

    mesh = make_mesh(8)
    model = build_model(TINY)
    adam_state = replicate(
        create_train_state(
            model,
            make_optimizer(TrainConfig(optimizer="adam")),
            jax.random.PRNGKey(0),
            np.zeros((1, 32, 32, 2), np.float32),
        ),
        mesh,
    )
    ck = CheckpointManager(str(tmp_path), save_every_steps=1)
    ck.save(adam_state.replace(step=adam_state.step + 1), force=True)

    sgd_template = replicate(
        create_train_state(
            model,
            make_optimizer(TrainConfig(optimizer="sgd")),
            jax.random.PRNGKey(0),
            np.zeros((1, 32, 32, 2), np.float32),
        ),
        mesh,
    )
    with pytest.raises((RuntimeError, ValueError), match="optimizer|structure"):
        ck.restore_latest(sgd_template)
    ck.close()
