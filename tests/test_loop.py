"""Continuous learning loop (loop/): traffic capture tee determinism
(captured shards byte-identical through ShardRangeReader), quota eviction,
ingest validation/dedup/idempotence, DriftMonitor transitions, and the
flywheel controller's trigger -> retrain -> verdict cycle."""

import json
import os
import queue
import time

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import records as rec
from tensorflowdistributedlearning_tpu.loop import capture as cap_lib
from tensorflowdistributedlearning_tpu.loop import ingest as ing_lib
from tensorflowdistributedlearning_tpu.loop.capture import (
    TrafficCapture,
    encode_example,
    to_uint8_image,
)
from tensorflowdistributedlearning_tpu.loop.controller import (
    FlywheelConfig,
    FlywheelController,
    scan_drift_alerts,
)
from tensorflowdistributedlearning_tpu.loop.ingest import (
    ingest_shards,
    read_dataset_manifest,
)
from tensorflowdistributedlearning_tpu.obs.health import DriftMonitor


class RecordingTelemetry:
    def __init__(self):
        self.events = []

    def event(self, kind, **fields):
        self.events.append({"event": kind, **fields})

    def kinds(self):
        return [e["event"] for e in self.events]


def _batch(rng, n=4, shape=(8, 8, 3)):
    return rng.standard_normal((n, *shape)).astype(np.float32)


def _outputs(labels):
    return {"class": np.asarray(labels, np.int32)}


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# -- capture: encode determinism ----------------------------------------------


def test_to_uint8_image_deterministic(rng):
    arr = rng.standard_normal((8, 8, 3)).astype(np.float32)
    a, b = to_uint8_image(arr), to_uint8_image(arr.copy())
    assert a.dtype == np.uint8
    assert np.array_equal(a, b)
    # uint8 passes through untouched; [0,1] scales by 255 exactly
    u8 = rng.integers(0, 256, (4, 4), dtype=np.uint8)
    assert to_uint8_image(u8) is u8
    unit = np.full((2, 2), 0.5)
    assert np.array_equal(to_uint8_image(unit), np.full((2, 2), 128, np.uint8))
    with pytest.raises(ValueError):
        to_uint8_image(np.array([1.0, np.nan]))


def test_encode_example_roundtrips_label_and_is_stable(rng):
    img = rng.standard_normal((8, 8, 3)).astype(np.float32)
    payload = encode_example(img, 3)
    assert payload == encode_example(img.copy(), 3)
    label, png = rec.decode_classification_record(payload)
    assert label == 3
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_capture_byte_identity_via_shard_range_reader(tmp_path, rng):
    """THE determinism contract: what the tee wrote is byte-identical to
    encode_example over the samples it selected, re-read through the .idx
    sidecar + ShardRangeReader (the data-service read path)."""
    d = str(tmp_path / "cap")
    cap = TrafficCapture(d, sample_fraction=1.0, records_per_shard=6)
    batches = [_batch(rng, n=3) for _ in range(4)]
    labels = [[i % 4, (i + 1) % 4, (i + 2) % 4] for i in range(4)]
    for b, l in zip(batches, labels):
        cap.maybe_capture(b, _outputs(l))
    assert _wait(lambda: cap.total_captured == 12)
    cap.close()

    want = [
        encode_example(b[j], l[j])
        for b, l in zip(batches, labels)
        for j in range(3)
    ]
    shards = sorted(
        str(p) for p in (tmp_path / "cap").glob("capture-*.tfrecord")
    )
    assert len(shards) == 2  # 12 records / 6 per shard
    got = []
    for path in shards:
        assert os.path.exists(rec.shard_index_path(path))
        offsets = rec.shard_offsets(path)
        with rec.ShardRangeReader(path) as r:
            got.extend(r.read(list(offsets)))
    assert got == want


def test_capture_stride_sampling_and_window_drain(tmp_path, rng):
    cap = TrafficCapture(str(tmp_path), sample_fraction=0.5, records_per_shard=64)
    for i in range(10):
        cap.maybe_capture(_batch(rng, n=2), _outputs([0, 1]))
    assert _wait(lambda: cap.total_captured == 10)  # 5 batches x 2
    snap = cap.window_snapshot(drain=True)
    assert snap["selected"] == 5
    assert snap["captured"] == 10
    assert snap["total_captured"] == 10
    assert snap["dropped"] == 0
    # drained: next window starts clean but totals persist
    snap2 = cap.window_snapshot()
    assert snap2["selected"] == 0 and snap2["total_captured"] == 10
    cap.close()


def test_capture_full_queue_counts_drop(tmp_path, rng, monkeypatch):
    cap = TrafficCapture(str(tmp_path), sample_fraction=1.0)

    def full(_item):
        raise queue.Full

    monkeypatch.setattr(cap._queue, "put_nowait", full)
    cap.maybe_capture(_batch(rng), _outputs([0, 1, 2, 3]))
    snap = cap.window_snapshot()
    assert snap["dropped"] == 1
    assert snap["total_dropped"] == 1
    monkeypatch.undo()
    cap.close()


def test_capture_quota_evicts_oldest_first(tmp_path, rng):
    d = str(tmp_path)
    # seal 1-record shards; quota sized to hold ~2 of them
    cap = TrafficCapture(d, records_per_shard=1, quota_bytes=1)
    # quota 1 byte: every seal evicts the previous shard, newest survives
    for i in range(5):
        cap.maybe_capture(_batch(rng, n=1), _outputs([i % 4]))
    assert _wait(lambda: cap.total_captured == 5)
    cap.close()
    snap = cap.window_snapshot()
    left = sorted(p.name for p in tmp_path.glob("capture-*.tfrecord"))
    assert left == ["capture-00004.tfrecord"]  # newest always survives
    assert snap["shards_evicted"] == 4
    assert snap["bytes_on_disk"] <= snap["bytes_written"]
    # evicted sidecars went with their shards
    assert sorted(p.name for p in tmp_path.glob("*.idx")) == [
        "capture-00004.tfrecord.idx"
    ]


def test_capture_close_seals_partial_shard(tmp_path, rng):
    cap = TrafficCapture(str(tmp_path), records_per_shard=100)
    cap.maybe_capture(_batch(rng, n=3), _outputs([0, 1, 2]))
    assert _wait(lambda: cap.total_captured == 3)
    cap.close()
    shards = list(tmp_path.glob("capture-*.tfrecord"))
    assert len(shards) == 1
    assert len(list(rec.read_records(str(shards[0])))) == 3
    # idempotent close
    cap.close()


def test_capture_restart_resumes_sequence(tmp_path, rng):
    """A restarted replica (promotion flip) must not overwrite the shards
    its previous incarnation sealed into the same capture dir."""
    cap = TrafficCapture(str(tmp_path), records_per_shard=2)
    cap.maybe_capture(_batch(rng, n=2), _outputs([0, 1]))
    assert _wait(lambda: cap.total_captured == 2)
    cap.close()
    cap2 = TrafficCapture(str(tmp_path), records_per_shard=2)
    cap2.maybe_capture(_batch(rng, n=2), _outputs([2, 3]))
    assert _wait(lambda: cap2.total_captured == 2)
    cap2.close()
    names = sorted(p.name for p in tmp_path.glob("capture-*.tfrecord"))
    assert names == ["capture-00000.tfrecord", "capture-00001.tfrecord"]


def test_capture_rejects_bad_config(tmp_path):
    with pytest.raises(ValueError):
        TrafficCapture(str(tmp_path), sample_fraction=0.0)
    with pytest.raises(ValueError):
        TrafficCapture(str(tmp_path), records_per_shard=0)


def test_label_array_picks_integer_output():
    out = {
        "prob": np.ones((3, 4), np.float32),
        "class": np.array([2, 0, 1], np.int64),
    }
    assert list(cap_lib._label_array(out, 3)) == [2, 0, 1]
    # no integer output -> zeros, structurally valid shard
    assert list(cap_lib._label_array({"p": np.ones((3,))}, 3)) == [0, 0, 0]


# -- ingest -------------------------------------------------------------------


def _capture_tree(tmp_path, rng, replicas=2, shards_per=2, n_per=3):
    root = tmp_path / "capture"
    total = 0
    for r in range(1, replicas + 1):
        d = root / f"replica-{r}"
        cap = TrafficCapture(str(d), records_per_shard=n_per)
        for s in range(shards_per):
            labels = [(r + s + j) % 4 for j in range(n_per)]
            cap.maybe_capture(_batch(rng, n=n_per), _outputs(labels))
            total += n_per
        assert _wait(lambda: cap.total_captured == shards_per * n_per)
        cap.close()
    return str(root), total


def test_ingest_validates_copies_and_versions(tmp_path, rng):
    cap_dir, total = _capture_tree(tmp_path, rng)
    ds = str(tmp_path / "ds")
    tel = RecordingTelemetry()
    summary = ingest_shards(cap_dir, ds, telemetry=tel)
    assert summary["new_shards"] == 4
    assert summary["records_added"] == total
    assert summary["version"] == 1
    assert summary["corrupt"] == 0 and summary["deduped"] == 0
    manifest = read_dataset_manifest(ds)
    assert manifest["version"] == 1
    assert manifest["records_total"] == total
    # dataset shards are fit-glob compatible, indexed, and CRC-clean
    names = sorted(os.listdir(ds))
    train = [n for n in names if n.startswith("train-") and n.endswith(".tfrecord")]
    assert len(train) == 4
    for n in train:
        path = os.path.join(ds, n)
        assert os.path.exists(rec.shard_index_path(path))
        assert len(list(rec.read_records(path, verify=True))) == 3
    assert tel.kinds() == ["records_ingest"]


def test_ingest_idempotent_reingest_is_ledgered_noop(tmp_path, rng):
    cap_dir, _ = _capture_tree(tmp_path, rng)
    ds = str(tmp_path / "ds")
    first = ingest_shards(cap_dir, ds)
    tel = RecordingTelemetry()
    again = ingest_shards(cap_dir, ds, telemetry=tel)
    assert again["new_shards"] == 0
    assert again["records_added"] == 0
    assert again["deduped"] == first["new_shards"]
    assert again["version"] == first["version"]  # version did NOT bump
    assert tel.kinds() == ["records_ingest"]  # the no-op is still ledgered
    assert sorted(os.listdir(ds)) == sorted(os.listdir(ds))


def test_ingest_dedups_identical_content_across_paths(tmp_path):
    cap_dir = tmp_path / "capture"
    (cap_dir / "a").mkdir(parents=True)
    (cap_dir / "b").mkdir(parents=True)
    payloads = [b"same-payload-%d" % i for i in range(4)]
    rec.write_records(str(cap_dir / "a" / "capture-00000.tfrecord"), payloads)
    rec.write_records(str(cap_dir / "b" / "capture-00007.tfrecord"), payloads)
    summary = ingest_shards(str(cap_dir), str(tmp_path / "ds"))
    assert summary["new_shards"] == 1
    assert summary["deduped"] == 1
    assert summary["records_added"] == 4


def test_ingest_skips_corrupt_and_empty_shards(tmp_path):
    cap_dir = tmp_path / "capture"
    cap_dir.mkdir()
    good = str(cap_dir / "capture-00000.tfrecord")
    rec.write_records(good, [b"ok-%d" % i for i in range(3)])
    bad = str(cap_dir / "capture-00001.tfrecord")
    rec.write_records(bad, [b"will-corrupt"])
    raw = bytearray(open(bad, "rb").read())
    raw[-3] ^= 0xFF  # flip a payload/crc byte
    open(bad, "wb").write(bytes(raw))
    open(str(cap_dir / "capture-00002.tfrecord"), "wb").close()  # empty
    summary = ingest_shards(str(cap_dir), str(tmp_path / "ds"))
    assert summary["new_shards"] == 1
    assert summary["corrupt"] == 2
    assert summary["records_added"] == 3


def test_ingest_growth_bumps_version_once_per_change(tmp_path, rng):
    cap_dir, _ = _capture_tree(tmp_path, rng, replicas=1, shards_per=1)
    ds = str(tmp_path / "ds")
    assert ingest_shards(cap_dir, ds)["version"] == 1
    # a new shard arrives
    extra = os.path.join(cap_dir, "replica-1", "capture-00009.tfrecord")
    rec.write_records(extra, [b"fresh-%d" % i for i in range(2)])
    rec.write_shard_index(extra)
    second = ingest_shards(cap_dir, ds)
    assert second["version"] == 2
    assert second["new_shards"] == 1
    assert read_dataset_manifest(ds)["records_total"] == second["records_total"]


# -- drift monitor ------------------------------------------------------------


def _baseline(hist=None):
    return {
        "outputs": {
            "class": {"kind": "integer", "hist": hist or {"0": 50, "1": 50}},
            "prob": {"kind": "float", "mean": 0.5, "std": 0.1},
        }
    }


def test_drift_monitor_requires_integer_histogram():
    with pytest.raises(ValueError):
        DriftMonitor({"outputs": {"prob": {"kind": "float"}}})
    with pytest.raises(ValueError):
        DriftMonitor({})


def test_drift_monitor_sustain_then_alert_then_resolve():
    mon = DriftMonitor(
        _baseline(), threshold=0.3, min_requests=10, sustain_windows=2
    )
    shifted = np.ones(30, np.int64)  # all class 1: TV distance 0.5
    mon.observe({"class": shifted})
    assert mon.evaluate() is None  # first bad window: not sustained yet
    assert mon.healthy
    mon.observe({"class": shifted})
    alert = mon.evaluate()
    assert alert is not None and alert["severity"] == "critical"
    assert alert["score"] == pytest.approx(0.5)
    assert alert["sustained_windows"] == 2
    assert not mon.healthy
    snap = mon.snapshot()
    assert snap["healthy"] is False and snap["output"] == "class"
    # recovery: balanced traffic -> one resolved:true event, then silence
    balanced = np.array([0, 1] * 15, np.int64)
    mon.observe({"class": balanced})
    resolved = mon.evaluate()
    assert resolved is not None and resolved.get("resolved") is True
    assert mon.healthy
    mon.observe({"class": balanced})
    assert mon.evaluate() is None


def test_drift_monitor_ignores_thin_windows_and_unknown_outputs():
    mon = DriftMonitor(_baseline(), threshold=0.3, min_requests=20,
                       sustain_windows=1)
    mon.observe({"class": np.ones(5, np.int64)})
    assert mon.evaluate() is None  # under min_requests: no distribution
    mon.observe({"other": np.ones(50, np.int64)})  # not the tracked output
    assert mon.evaluate() is None
    assert mon.healthy


# -- flywheel controller ------------------------------------------------------


def _stub_ingest(records_per_call):
    calls = iter(records_per_call)

    def fn(capture_dir, dataset_dir, telemetry=None, **kw):
        n = next(calls, 0)
        return {
            "records_added": n,
            "version": 1 if n else 0,
            "records_total": n,
        }

    return fn


def test_flywheel_config_requires_a_trigger(tmp_path):
    with pytest.raises(ValueError):
        FlywheelConfig(
            capture_dir=str(tmp_path), dataset_dir=str(tmp_path),
            min_new_records=0, fleet_workdir=None,
        )
    with pytest.raises(ValueError):
        FlywheelConfig(
            capture_dir=str(tmp_path), dataset_dir=str(tmp_path), poll_secs=0
        )


def test_flywheel_volume_trigger_promotes(tmp_path):
    tel = RecordingTelemetry()
    cfg = FlywheelConfig(
        capture_dir=str(tmp_path), dataset_dir=str(tmp_path),
        min_new_records=10, poll_secs=0.01, max_cycles=1,
    )
    seen = {}

    def retrain(trigger, summary):
        seen.update(trigger)
        return {"rc": 0, "candidate_dir": "/tmp/cand", "fingerprint": "abc123"}

    ctl = FlywheelController(
        cfg, retrain_fn=retrain, telemetry=tel,
        ingest_fn=_stub_ingest([4, 7]),  # 4 then 11 >= 10
    )
    assert ctl.run() == 0
    assert ctl.cycles == 1 and ctl.promoted == 1 and ctl.rejected == 0
    assert seen["reason"] == "data_volume" and seen["records_new"] == 11
    assert tel.kinds() == ["loop_trigger", "loop_retrain", "loop_promoted"]
    retrain_ev = tel.events[1]
    assert retrain_ev["rc"] == 0
    assert retrain_ev["fingerprint"] == "abc123"
    assert "duration_s" in retrain_ev


def test_flywheel_rejected_cycle_and_crash_are_rc_1(tmp_path):
    tel = RecordingTelemetry()
    cfg = FlywheelConfig(
        capture_dir=str(tmp_path), dataset_dir=str(tmp_path),
        min_new_records=1, poll_secs=0.01, max_cycles=2,
    )
    outcomes = iter([{"rc": 1}, RuntimeError("train exploded")])

    def retrain(trigger, summary):
        out = next(outcomes)
        if isinstance(out, Exception):
            raise out
        return out

    ctl = FlywheelController(
        cfg, retrain_fn=retrain, telemetry=tel, ingest_fn=_stub_ingest([5, 5])
    )
    assert ctl.run() == 1
    assert ctl.rejected == 2 and ctl.promoted == 0
    kinds = tel.kinds()
    assert kinds.count("loop_rejected") == 2
    crashed = tel.events[-1]
    assert "train exploded" in crashed.get("error", "")


def test_flywheel_timeout_without_trigger_is_rc_3(tmp_path):
    cfg = FlywheelConfig(
        capture_dir=str(tmp_path), dataset_dir=str(tmp_path),
        min_new_records=1000, poll_secs=0.01, max_wait_secs=0.05,
    )
    ctl = FlywheelController(
        cfg, retrain_fn=lambda t, s: {"rc": 0},
        ingest_fn=lambda *a, **k: {"records_added": 0, "version": 0},
    )
    assert ctl.run() == 3
    assert ctl.cycles == 0


def _write_ledger(path, events):
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_scan_drift_alerts_latest_unresolved_wins(tmp_path):
    fw = str(tmp_path)
    now = time.time()
    _write_ledger(
        os.path.join(fw, "telemetry-1.jsonl"),
        [
            {"event": "drift_alert", "t": now - 5, "score": 0.6, "replica": 1},
            {"event": "serve_window", "t": now - 4},
        ],
    )
    _write_ledger(
        os.path.join(fw, "telemetry-2.jsonl"),
        [
            {"event": "drift_alert", "t": now - 3, "score": 0.7, "replica": 2},
            {"event": "drift_alert", "t": now - 1, "resolved": True,
             "replica": 2},
        ],
    )
    # replica 2's alert was retracted by its resolution; replica 1's stands
    alert = scan_drift_alerts(fw)
    assert alert is not None and alert["replica"] == 1
    # since_t past replica 1's firing -> nothing live
    assert scan_drift_alerts(fw, since_t=now - 4) is None
    # torn trailing line is skipped, not fatal
    with open(os.path.join(fw, "telemetry-1.jsonl"), "a") as f:
        f.write('{"event": "drift_alert", "t":')
    assert scan_drift_alerts(fw)["replica"] == 1


def test_flywheel_drift_trigger_fires_and_is_consumed(tmp_path):
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    _write_ledger(
        str(fleet / "telemetry-1.jsonl"),
        [{"event": "drift_alert", "t": time.time(), "score": 0.55,
          "threshold": 0.3, "alert_id": "a1", "replica": 1}],
    )
    tel = RecordingTelemetry()
    cfg = FlywheelConfig(
        capture_dir=str(tmp_path), dataset_dir=str(tmp_path),
        fleet_workdir=str(fleet), min_new_records=0,  # drift-only loop
        poll_secs=0.01, max_cycles=1,
    )
    triggers = []

    def retrain(trigger, summary):
        triggers.append(trigger)
        return {"rc": 0}

    ctl = FlywheelController(
        cfg, retrain_fn=retrain, telemetry=tel,
        ingest_fn=lambda *a, **k: {"records_added": 0, "version": 0},
    )
    assert ctl.run() == 0
    assert triggers[0]["reason"] == "drift"
    assert triggers[0]["drift_score"] == 0.55
    assert triggers[0]["alert_id"] == "a1"
    # the retrain consumed the alert: a fresh run on the same ledger times out
    cfg2 = FlywheelConfig(
        capture_dir=str(tmp_path), dataset_dir=str(tmp_path),
        fleet_workdir=str(fleet), min_new_records=0,
        poll_secs=0.01, max_cycles=1, max_wait_secs=0.05,
    )
    ctl2 = FlywheelController(
        cfg2, retrain_fn=retrain, telemetry=RecordingTelemetry(),
        ingest_fn=lambda *a, **k: {"records_added": 0, "version": 0},
    )
    ctl2._drift_handled_t = time.time()
    assert ctl2.run() == 3


def test_flywheel_capture_to_retrain_uses_real_ingest(tmp_path, rng):
    """loop-level integration: real capture shards -> real ingest -> the
    volume trigger cites the real dataset version."""
    cap_dir, total = _capture_tree(tmp_path, rng, replicas=1, shards_per=2)
    ds = str(tmp_path / "ds")
    tel = RecordingTelemetry()
    cfg = FlywheelConfig(
        capture_dir=cap_dir, dataset_dir=ds,
        min_new_records=total, poll_secs=0.01, max_cycles=1,
    )
    ctl = FlywheelController(
        cfg, retrain_fn=lambda t, s: {"rc": 0}, telemetry=tel
    )
    assert ctl.run() == 0
    trig = [e for e in tel.events if e["event"] == "loop_trigger"][0]
    assert trig["records_new"] == total
    assert trig["dataset_version"] == 1
    assert read_dataset_manifest(ds)["records_total"] == total
    # ingest events rode the same ledger
    assert "records_ingest" in tel.kinds()
