"""Spatial (sequence) parallelism integrated into the real model + train step
(VERDICT r1 #4): atrous/pool/global-mean spatial ops exactness, H-sharded flagship
forward exactness, and the end-to-end criterion — one train step on a (4, 1, 2)
mesh matching the same-tower-count (4, 1, 1) run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data.synthetic import (
    synthetic_segmentation_batch,
)
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.parallel import spatial as sp
from tensorflowdistributedlearning_tpu.parallel.mesh import (
    SEQUENCE_AXIS,
    make_mesh,
)
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.state import create_train_state

CFG = ModelConfig(
    input_shape=(32, 32), n_blocks=(1, 1, 1), base_depth=8, width_multiplier=0.0625
)


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(8, sequence_parallel=8)


def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


@pytest.mark.parametrize("rate", [2, 4, 8])
def test_spatial_conv_dilated_matches_unsharded(seq_mesh, rate):
    """rate 8 on 4-row shards exceeds the single-hop halo and exercises the
    gather fallback; rates 2/4 ride the halo exchange."""
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 32, 8, 3)).astype(np.float32)  # 4 rows/shard
    k = rng.normal(0, 0.5, (3, 3, 3, 5)).astype(np.float32)

    ref = jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", rhs_dilation=(rate, rate),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    f = _shard_map(
        lambda a: sp.spatial_conv2d(a, jnp.asarray(k), rate=rate),
        seq_mesh,
        (P(None, SEQUENCE_AXIS, None, None),),
        P(None, SEQUENCE_AXIS, None, None),
    )
    np.testing.assert_allclose(
        jax.device_get(f(x)), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_spatial_max_pool_matches_unsharded(seq_mesh):
    import flax.linen as nn

    rng = np.random.default_rng(1)
    # negative values probe the -inf boundary handling (zero halo fill must not win)
    x = (rng.normal(0, 1, (2, 32, 7, 3)) - 2.0).astype(np.float32)
    ref = nn.max_pool(jnp.asarray(x), (3, 3), strides=(2, 2), padding="SAME")
    f = _shard_map(
        lambda a: sp.spatial_max_pool(a, 3, 2),
        seq_mesh,
        (P(None, SEQUENCE_AXIS, None, None),),
        P(None, SEQUENCE_AXIS, None, None),
    )
    np.testing.assert_allclose(
        jax.device_get(f(x)), np.asarray(ref), rtol=0, atol=0
    )


def test_spatial_global_mean_matches(seq_mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (2, 16, 5, 3)).astype(np.float32)
    f = _shard_map(
        lambda a: sp.spatial_global_mean(a),
        seq_mesh,
        (P(None, SEQUENCE_AXIS, None, None),),
        P(None, None),
    )
    np.testing.assert_allclose(
        jax.device_get(f(x)), x.mean(axis=(1, 2)), rtol=1e-5, atol=1e-6
    )


@pytest.fixture(scope="module")
def models_and_state():
    plain = build_model(CFG)
    spatial = build_model(
        CFG, bn_axis_name=SEQUENCE_AXIS, spatial_axis_name=SEQUENCE_AXIS
    )
    tx = step_lib.make_optimizer(TrainConfig())
    state = create_train_state(
        plain, tx, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 2), np.float32)
    )
    return plain, spatial, state


def test_spatial_param_tree_matches_plain(models_and_state):
    """SpatialConv is checkpoint-compatible with nn.Conv: identical param trees
    (init must run inside shard_map — the spatial ops need the sequence axis)."""
    plain, spatial, state = models_and_state
    mesh = make_mesh(8, sequence_parallel=2)

    def init_fn(im):
        return spatial.init(jax.random.PRNGKey(0), im, train=False)

    v = jax.jit(
        jax.shard_map(
            init_fn,
            mesh=mesh,
            in_specs=(P("batch", SEQUENCE_AXIS, None, None),),
            out_specs=P(),
        )
    )(np.zeros((8, 32, 32, 2), np.float32))
    plain_shapes = jax.tree.map(jnp.shape, state.params)
    spatial_shapes = jax.tree.map(jnp.shape, v["params"])
    assert plain_shapes == spatial_shapes


def test_spatial_forward_matches_unsharded(models_and_state):
    plain, spatial, state = models_and_state
    mesh = make_mesh(8, sequence_parallel=2)  # (4, 1, 2)
    rng = np.random.default_rng(3)
    images = rng.normal(0, 1, (8, 32, 32, 2)).astype(np.float32)
    variables = {"params": state.params, "batch_stats": state.batch_stats}

    ref = jax.jit(lambda v, im: plain.apply(v, im, train=False))(variables, images)

    def fwd(v, im):
        out = spatial.apply(v, im, train=False)
        # numerically an identity (every sequence shard holds the gathered full
        # output); clears the sequence-varying type so P(batch) out_specs hold
        return jax.lax.pmean(out, SEQUENCE_AXIS)

    f = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), P("batch", SEQUENCE_AXIS, None, None)),
            out_specs=P("batch", None, None, None),
        )
    )
    out = f(
        mesh_lib.replicate(variables, mesh),
        sp.shard_spatial(images, mesh),
    )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out)), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_spatial_train_step_matches_plain_mesh(models_and_state):
    """VERDICT r1 #4 'done' criterion: one end-to-end train step on mesh (4,1,2)
    matches the (4,1,1) run with the same 4-way tower split (same per-tower BN
    batches; the sequence axis must be numerically free)."""
    plain, spatial, state = models_and_state
    mesh_dp = make_mesh(4)                      # (4, 1, 1)
    mesh_sp = make_mesh(8, sequence_parallel=2)  # (4, 1, 2)
    task = step_lib.SegmentationTask()

    batch = synthetic_segmentation_batch(
        np.random.default_rng(4), 8, input_shape=(32, 32), channels=2
    )
    batch = {"images": batch["images"], "labels": batch["labels"]}

    state_dp = mesh_lib.replicate(state, mesh_dp)
    state_sp = mesh_lib.replicate(state, mesh_sp).replace(apply_fn=spatial.apply)

    step_dp = step_lib.make_train_step(mesh_dp, task, donate=False)
    step_sp = step_lib.make_train_step(mesh_sp, task, donate=False, spatial=True)

    new_dp, m_dp = step_dp(state_dp, mesh_lib.shard_batch(batch, mesh_dp))
    new_sp, m_sp = step_sp(state_sp, mesh_lib.shard_batch_spatial(batch, mesh_sp))

    r_dp = step_lib.compute_metrics(jax.device_get(m_dp))
    r_sp = step_lib.compute_metrics(jax.device_get(m_sp))
    assert r_dp["loss"] == pytest.approx(r_sp["loss"], rel=1e-4)
    assert r_dp["metrics/mean_iou"] == pytest.approx(
        r_sp["metrics/mean_iou"], rel=1e-4
    )

    # Param atol is set by Adam's update scale: where a gradient element is
    # ~zero, float32 reassociation across the two reduction orders can flip the
    # sign of g/sqrt(v), moving the element by up to 2*lr = 2e-3. The tight loss/
    # metric agreement above is the exactness signal; this guards the overall tree.
    flat_dp = jax.tree_util.tree_leaves_with_path(jax.device_get(new_dp.params))
    flat_sp = dict(
        jax.tree_util.tree_leaves_with_path(jax.device_get(new_sp.params))
    )
    for path, leaf in flat_dp:
        np.testing.assert_allclose(
            np.asarray(leaf),
            np.asarray(flat_sp[path]),
            rtol=5e-4,
            atol=2.5e-3,
            err_msg=str(path),
        )
    # BN moving stats also agree (sequence-synced BN == full-H per-tower BN)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        jax.device_get(new_dp.batch_stats)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf),
            np.asarray(
                dict(
                    jax.tree_util.tree_leaves_with_path(
                        jax.device_get(new_sp.batch_stats)
                    )
                )[path]
            ),
            rtol=5e-4,
            atol=5e-5,
            err_msg=str(path),
        )


def test_spatial_classifier_forward_matches(models_and_state):
    # 64x64 keeps every strided stage of the stride-32 classification trunk
    # shard-aligned at sequence degree 2 (32x32 would shrink H_local below the
    # stride — an invalid spatial config that spatial_conv2d rejects loudly)
    cfg = ModelConfig(
        num_classes=5,
        input_shape=(64, 64),
        input_channels=3,
        n_blocks=(1, 1, 1),
        base_depth=8,
        width_multiplier=0.0625,
        output_stride=None,
    )
    plain = build_model(cfg)
    spatial = build_model(
        cfg, bn_axis_name=SEQUENCE_AXIS, spatial_axis_name=SEQUENCE_AXIS
    )
    state = create_train_state(
        plain,
        step_lib.make_optimizer(TrainConfig()),
        jax.random.PRNGKey(1),
        np.zeros((1, 64, 64, 3), np.float32),
    )
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    rng = np.random.default_rng(5)
    images = rng.normal(0, 1, (8, 64, 64, 3)).astype(np.float32)
    ref = jax.jit(lambda v, im: plain.apply(v, im, train=False))(variables, images)

    mesh = make_mesh(8, sequence_parallel=2)

    def fwd(v, im):
        out = spatial.apply(v, im, train=False)
        return jax.lax.pmean(out, SEQUENCE_AXIS)

    f = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), P("batch", SEQUENCE_AXIS, None, None)),
            out_specs=P("batch", None),
        )
    )
    out = f(mesh_lib.replicate(variables, mesh), sp.shard_spatial(images, mesh))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out)), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_trainer_end_to_end_with_sequence_parallel(tmp_path):
    """The full K-fold Trainer on a (4, 1, 2) mesh: training, eval, best export,
    TTA predict — every phase running the H-sharded spatial path (32x32 inputs
    divide overall_stride(8) x sp(2))."""
    from tests.conftest import make_salt_dataset

    from tensorflowdistributedlearning_tpu.train.trainer import Trainer

    data, test, ids = make_salt_dataset(
        tmp_path, n_images=12, n_test=4, shape=(32, 32)
    )

    trainer = Trainer(
        str(tmp_path / "model"),
        str(data),
        train_config=TrainConfig(
            n_folds=2,
            seed=0,
            sequence_parallel=2,
            checkpoint_every_steps=2,
            eval_throttle_secs=0,
            # > steps: skips the train-phase image-summary forward (a whole
            # extra spatial-mesh trace; that path is covered on the plain mesh
            # by test_trainer.py) — this test's job is train/eval/predict
            # phases running H-sharded
            train_log_every_steps=5,
        ),
        input_shape=(32, 32),
        n_blocks=(1, 1, 1),
        base_depth=8,
    )
    assert trainer.mesh.shape == {"batch": 4, "model": 1, "sequence": 2}
    results = trainer.train(ids, batch_size=8, steps=2)
    assert len(results) == 2
    assert all(np.isfinite(r["loss"]) for r in results)

    pred = trainer.predict(str(test), batch_size=8, tta=True)
    assert pred["probabilities"].shape == (4, 32, 32, 1)
    assert np.all((pred["probabilities"] >= 0) & (pred["probabilities"] <= 1))


def test_spatial_xception_forward_matches():
    """Xception spatial support: strided separable convs use the fixed_padding
    phase; forward parity with the unsharded model on a (4, 1, 2) mesh."""
    cfg = ModelConfig(
        backbone="xception", input_shape=(64, 64), base_depth=8,
        width_multiplier=0.0625
    )
    plain = build_model(cfg)
    spatial = build_model(
        cfg, bn_axis_name=SEQUENCE_AXIS, spatial_axis_name=SEQUENCE_AXIS
    )
    state = create_train_state(
        plain,
        step_lib.make_optimizer(TrainConfig()),
        jax.random.PRNGKey(2),
        np.zeros((1, 64, 64, 2), np.float32),
    )
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    rng = np.random.default_rng(6)
    images = rng.normal(0, 1, (4, 64, 64, 2)).astype(np.float32)
    ref = jax.jit(lambda v, im: plain.apply(v, im, train=False))(variables, images)

    mesh = make_mesh(8, sequence_parallel=2)

    def fwd(v, im):
        out = spatial.apply(v, im, train=False)
        return jax.lax.pmean(out, SEQUENCE_AXIS)

    f = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), P("batch", SEQUENCE_AXIS, None, None)),
            out_specs=P("batch", None, None, None),
        )
    )
    out = f(mesh_lib.replicate(variables, mesh), sp.shard_spatial(images, mesh))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out)), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_spatial_xception_train_step_matches_plain_mesh():
    """Xception end-to-end under sequence parallelism: one train step on mesh
    (4,1,2) matches the (4,1,1) run (same per-tower BN batches) — the train-step
    counterpart of the forward-parity test above.

    64x64 (deepest stage 4x4, >=2 rows/shard): at degenerate 2x2 feature maps
    (32x32/os16) the ~1e-6 reassociation noise of synced-BN batch stats gets
    amplified ~1000x through the middle flow's 8 sum-residual units dividing by
    tiny-sample variances — measured, isolated (BN sync itself is exact to
    4e-7), and not a sharding defect; production spatial-parallel sizes keep
    feature maps far from that regime."""
    cfg = ModelConfig(
        backbone="xception",
        input_shape=(64, 64),
        base_depth=8,
        width_multiplier=0.0625,
        output_stride=16,
    )
    plain = build_model(cfg)
    spatial = build_model(
        cfg, bn_axis_name=SEQUENCE_AXIS, spatial_axis_name=SEQUENCE_AXIS
    )
    task = step_lib.SegmentationTask()
    state = create_train_state(
        plain,
        step_lib.make_optimizer(TrainConfig()),
        jax.random.PRNGKey(4),
        np.zeros((1, 64, 64, 2), np.float32),
    )
    batch = synthetic_segmentation_batch(
        np.random.default_rng(5), 8, input_shape=(64, 64), channels=2
    )
    batch = {"images": batch["images"], "labels": batch["labels"]}

    mesh_dp = make_mesh(4)
    mesh_sp = make_mesh(8, sequence_parallel=2)
    state_dp = mesh_lib.replicate(state, mesh_dp)
    state_sp = mesh_lib.replicate(state, mesh_sp).replace(apply_fn=spatial.apply)
    step_dp = step_lib.make_train_step(mesh_dp, task, donate=False)
    step_sp = step_lib.make_train_step(mesh_sp, task, donate=False, spatial=True)
    _, m_dp = step_dp(state_dp, mesh_lib.shard_batch(batch, mesh_dp))
    _, m_sp = step_sp(state_sp, mesh_lib.shard_batch_spatial(batch, mesh_sp))
    r_dp = step_lib.compute_metrics(jax.device_get(m_dp))
    r_sp = step_lib.compute_metrics(jax.device_get(m_sp))
    assert r_dp["loss"] == pytest.approx(r_sp["loss"], rel=1e-4)
    assert r_dp["metrics/mean_iou"] == pytest.approx(
        r_sp["metrics/mean_iou"], rel=1e-4
    )
