"""Tests for fold manifests and the host-side pipeline (reference:
preprocessing/preprocessing.py:33-88 symlink trees; model.py:287-322 input_fns)."""

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import folds, pipeline


def test_coverage_to_class_bins():
    cov = np.array([0.0, 0.01, 0.5, 1.0])
    cls = folds.coverage_to_class(cov)
    assert cls.tolist() == [0, 1, 5, 10]


def test_stratified_kfold_partition():
    y = np.array([0] * 10 + [1] * 20 + [2] * 5)
    splits = folds.stratified_kfold(y, n_splits=5, seed=0)
    assert len(splits) == 5
    all_eval = np.concatenate([ev for _, ev in splits])
    # eval folds partition the dataset
    assert sorted(all_eval.tolist()) == list(range(35))
    for train_idx, eval_idx in splits:
        assert set(train_idx) & set(eval_idx) == set()
        # stratification: each fold's class-1 share within one sample of 20/35
        n1 = (y[eval_idx] == 1).sum()
        assert 3 <= n1 <= 5


def test_stratified_kfold_deterministic():
    y = np.random.default_rng(0).integers(0, 3, 50)
    a = folds.stratified_kfold(y, 5, seed=7)
    b = folds.stratified_kfold(y, 5, seed=7)
    for (ta, ea), (tb, eb) in zip(a, b):
        assert np.array_equal(ta, tb) and np.array_equal(ea, eb)


def test_write_fold_manifests_idempotent(tmp_path):
    ids = [f"img{i}" for i in range(20)]
    y = [i % 2 for i in range(20)]
    m1 = folds.write_fold_manifests(str(tmp_path), ids, y, 4, seed=1)
    # second call must reuse the saved split even with different inputs
    m2 = folds.write_fold_manifests(str(tmp_path), list(reversed(ids)), y, 4, seed=99)
    assert m1 == m2
    assert len(m1) == 4
    for fold in m1:
        assert set(fold["train"]) | set(fold["eval"]) == set(ids)


def _png_dataset(tmp_path, n=6, h=101, w=101):
    from PIL import Image

    rng = np.random.default_rng(0)
    (tmp_path / "images").mkdir()
    (tmp_path / "masks").mkdir()
    ids = []
    for i in range(n):
        ids.append(f"ex{i}")
        img = (rng.uniform(size=(h, w)) * 255).astype(np.uint8)
        msk = (rng.uniform(size=(h, w)) > 0.5).astype(np.uint8) * 255
        Image.fromarray(img, "L").save(tmp_path / "images" / f"ex{i}.png")
        Image.fromarray(msk, "L").save(tmp_path / "masks" / f"ex{i}.png")
    return ids


def test_in_memory_dataset_from_pngs(tmp_path):
    ids = _png_dataset(tmp_path)
    ds = pipeline.InMemoryDataset.from_directory(str(tmp_path))
    assert ds.ids == ids
    assert ds.images.shape == (6, 101, 101, 1)
    assert ds.masks.shape == (6, 101, 101, 1)
    assert set(np.unique(ds.masks)) <= {0.0, 1.0}
    # normalization applied (reference: preprocessing.py:146)
    assert abs(ds.images.mean()) < 1.0

    sub = ds.select(["ex3", "ex1"])
    assert sub.ids == ["ex3", "ex1"]
    assert np.array_equal(sub.images[0], ds.images[3])


def test_train_batches_shuffled_and_bounded(tmp_path):
    ids = _png_dataset(tmp_path)
    ds = pipeline.InMemoryDataset.from_directory(str(tmp_path))
    batches = list(pipeline.train_batches(ds, batch_size=4, seed=0, steps=5))
    assert len(batches) == 5
    for b in batches:
        assert b["images"].shape == (4, 101, 101, 1)
    # deterministic under the same seed
    again = list(pipeline.train_batches(ds, batch_size=4, seed=0, steps=5))
    assert np.array_equal(batches[0]["images"], again[0]["images"])


def test_eval_batches_pads_final_with_valid_mask(tmp_path):
    _png_dataset(tmp_path)
    ds = pipeline.InMemoryDataset.from_directory(str(tmp_path))
    batches = list(pipeline.eval_batches(ds, batch_size=4))
    assert len(batches) == 2
    assert all(b["images"].shape[0] == 4 for b in batches)
    # wrap-around padding repeats the head of the dataset, masked out via `valid`
    assert np.array_equal(batches[1]["images"][2], ds.images[0])
    assert batches[0]["valid"].tolist() == [1, 1, 1, 1]
    assert batches[1]["valid"].tolist() == [1, 1, 0, 0]
    # every example counts exactly once
    assert sum(b["valid"].sum() for b in batches) == len(ds)


def test_device_prefetch_passthrough():
    src = iter([{"x": np.ones((2,))} for _ in range(3)])
    out = list(pipeline.device_prefetch(src, place=lambda b: b, depth=2))
    assert len(out) == 3


def test_device_prefetch_propagates_errors():
    def bad_iter():
        yield {"x": 1}
        raise RuntimeError("decode failed")

    it = pipeline.device_prefetch(bad_iter(), place=lambda b: b, depth=2)
    assert next(it) == {"x": 1}
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_train_batches_oversized_batch_repeats_dataset(tmp_path):
    # folds can be smaller than one batch; the infinite-repeat stream must still
    # fill full batches (the reference's shuffle_and_repeat, model.py:301-304)
    _png_dataset(tmp_path, n=3)
    ds = pipeline.InMemoryDataset.from_directory(str(tmp_path))
    batch = next(pipeline.train_batches(ds, batch_size=8, seed=0))
    assert batch["images"].shape[0] == 8
    # every underlying example appears at least twice in 8 draws from 3
    flat = batch["images"].reshape(8, -1)
    assert len(np.unique(flat, axis=0)) == 3


def test_train_batches_empty_raises():
    ds = pipeline.InMemoryDataset(np.zeros((0, 1, 1, 1)), np.zeros((0, 1, 1, 1)), [])
    with pytest.raises(ValueError):
        next(pipeline.train_batches(ds, 2, 0))
