"""Fleet observability: per-process ledger discovery/merge, straggler
attribution with ``straggler_alert`` emission, barrier-probe spans, the
cross-run registry + compare, and the bench regression sentinel.

The acceptance pins live here: a simulated 2-process run (one host skewed)
must produce a merged report that NAMES the slow host and emits a
``straggler_alert``; ``telemetry-report --compare`` on two real fit()
workdirs must emit structured deltas; and the regression sentinel must pass
on the committed benches but exit nonzero on an injected 2x step-time
regression."""

import json
import os
import sys
import time

import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs import compare as compare_lib
from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib
from tensorflowdistributedlearning_tpu.obs.ledger import (
    RunLedger,
    per_process_filename,
)
from tensorflowdistributedlearning_tpu.obs.report import (
    build_report,
    render_report,
)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import regression_sentinel  # noqa: E402


# -- simulated fleet ledgers -------------------------------------------------


def _write_process_ledger(
    workdir,
    idx,
    mean_ms,
    *,
    process_count=2,
    steps=(2, 4, 6),
    barrier_s=0.0,
    kind="classification",
):
    led = RunLedger(str(workdir), filename=per_process_filename(idx))
    led.event(
        "run_header",
        schema_version=1,
        process_index=idx,
        process_count=process_count,
        task=kind,
        fingerprint={
            "platform": "cpu",
            "device_kind": "cpu",
            "n_devices": 4,
            "process_index": idx,
            "process_count": process_count,
            "jax_version": "0.0",
        },
    )
    for s in steps:
        led.event(
            "step_window",
            step=s,
            steps=2,
            data_wait_s=0.01,
            compute_s=mean_ms * 2 / 1000,
            fetch_wait_s=0.0,
            barrier_wait_s=barrier_s,
            data_wait_frac=0.0,
            dirty=False,
            step_time_ms={
                "count": 2.0,
                "mean_ms": mean_ms,
                "p50_ms": mean_ms,
                "p90_ms": mean_ms,
                "p99_ms": mean_ms,
                "max_ms": mean_ms,
            },
        )
    led.event("run_end", steps=steps[-1])
    led.close()


def test_per_process_filename_contract():
    assert per_process_filename(0) == "telemetry.jsonl"
    assert per_process_filename(1) == "telemetry-1.jsonl"
    assert per_process_filename(7) == "telemetry-7.jsonl"


def test_discover_ledgers_orders_and_scopes(tmp_path):
    _write_process_ledger(tmp_path, 1, 120.0)
    _write_process_ledger(tmp_path, 0, 100.0)
    ledgers = fleet_lib.discover_ledgers(str(tmp_path))
    assert [led.process_index for led in ledgers] == [0, 1]
    assert all(
        led.events[0]["event"] == "run_header" for led in ledgers
    )
    # non-ledger jsonl files are not picked up
    (tmp_path / "telemetry-notanumber.jsonl").write_text("{}\n")
    assert len(fleet_lib.discover_ledgers(str(tmp_path))) == 2


def test_merged_report_names_slow_host_and_emits_straggler_alert(tmp_path):
    """THE acceptance pin: two per-process ledgers, process 1 skewed 2x —
    the merged report's straggler section names process 1 and carries
    straggler_alert entries; the rendering says so in prose."""
    # the fast host waits at barriers for the slow one; the slow host barely
    # waits — the asymmetry behind the slow-host-vs-slow-network hint
    _write_process_ledger(tmp_path, 0, 100.0, barrier_s=0.4)
    _write_process_ledger(tmp_path, 1, 200.0, barrier_s=0.01)
    report = build_report(str(tmp_path))
    fleet = report["fleet"]
    assert fleet["processes"] == 2
    assert {r["process_index"] for r in fleet["per_process"]} == {0, 1}

    st = fleet["straggler"]
    assert st["windows_compared"] == 3
    assert st["worst_process"] == 1
    assert st["alert_count"] == 3
    alert = st["alerts"][0]
    assert alert["event"] == obs.STRAGGLER_ALERT_EVENT == "straggler_alert"
    assert alert["worst_process"] == 1
    assert alert["skew"] == pytest.approx(200.0 / 150.0, abs=0.01)
    # barrier asymmetry attributes the skew to the host, not the network
    assert "slow HOST" in fleet["attribution_hint"]

    text = render_report(report)
    assert "straggler_alert" in text
    assert "worst host: process 1" in text
    assert "fleet: 2 process ledgers merged" in text


def test_no_straggler_alert_within_threshold(tmp_path):
    _write_process_ledger(tmp_path, 0, 100.0)
    _write_process_ledger(tmp_path, 1, 104.0)
    report = build_report(str(tmp_path))
    st = report["fleet"]["straggler"]
    assert st["alert_count"] == 0
    assert st["max_skew"] < 1.25
    assert "no straggler alerts" in render_report(report)


def test_straggler_threshold_is_configurable(tmp_path):
    _write_process_ledger(tmp_path, 0, 100.0)
    _write_process_ledger(tmp_path, 1, 115.0)
    assert (
        build_report(str(tmp_path))["fleet"]["straggler"]["alert_count"] == 0
    )
    tight = build_report(str(tmp_path), straggler_threshold=1.05)
    assert tight["fleet"]["straggler"]["alert_count"] == 3


def test_single_ledger_report_has_no_fleet_section(tmp_path):
    _write_process_ledger(tmp_path, 0, 100.0, process_count=1)
    assert "fleet" not in build_report(str(tmp_path))


def test_fleet_merge_covers_serving_replicas(tmp_path):
    """serve_window events carry their replica id; the merge attributes
    request-path telemetry per replica ledger."""
    _write_process_ledger(tmp_path, 0, 100.0)
    led = RunLedger(str(tmp_path), filename=per_process_filename(1))
    led.event("run_header", kind="serve", process_index=1, process_count=2,
              replica=1)
    led.event(
        "serve_window", replica=1, requests=50, completed=48,
        rejected_queue_full=2, deadline_exceeded=0, errors=0, batches=10,
        batched_examples=48,
        latency_ms={"request": {
            "count": 48.0, "mean_ms": 4.0, "p50_ms": 3.5, "p90_ms": 6.0,
            "p99_ms": 9.5, "max_ms": 11.0,
        }},
    )
    led.close()
    fleet = build_report(str(tmp_path))["fleet"]
    serve_row = next(
        r for r in fleet["per_process"] if r["process_index"] == 1
    )
    assert serve_row["kind"] == "serve"
    assert serve_row["serve"]["replica"] == 1
    assert serve_row["serve"]["completed"] == 48
    assert serve_row["serve"]["request_p99_worst_window_ms"] == 9.5
    text = render_report(build_report(str(tmp_path)))
    assert "serve replica 1: 48/50 ok" in text


# -- ledger parse errors (satellite) ----------------------------------------


def test_torn_ledger_lines_are_counted_not_silent(tmp_path):
    _write_process_ledger(tmp_path, 0, 100.0, process_count=1)
    with open(tmp_path / "telemetry.jsonl", "a", encoding="utf-8") as f:
        f.write('{"event": "step_window", "step": 8, "trunc')  # torn tail
    events, errors = obs.read_ledger_with_errors(str(tmp_path))
    assert errors == 1
    assert all(e["event"] != "trunc" for e in events)
    report = build_report(str(tmp_path))
    assert report["header"]["ledger_parse_errors"] == 1
    assert "unparseable ledger line" in render_report(report)


def test_clean_ledger_reports_zero_parse_errors(tmp_path):
    _write_process_ledger(tmp_path, 0, 100.0, process_count=1)
    assert build_report(str(tmp_path))["header"]["ledger_parse_errors"] == 0


# -- telemetry-report CLI exit contract (satellite) --------------------------


def test_report_cli_no_ledger_exits_nonzero_with_hint(tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main(["telemetry-report", str(tmp_path)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "no telemetry ledger" in err
    assert "telemetry.jsonl" in err


def test_report_cli_export_trace_no_ledger_exits_nonzero(tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main([
        "telemetry-report", str(tmp_path),
        "--export-trace", str(tmp_path / "out.json"),
    ])
    assert rc == 2
    assert "no telemetry ledger" in capsys.readouterr().err
    assert not (tmp_path / "out.json").exists()


# -- barrier probe -----------------------------------------------------------


def test_barrier_probe_records_span_into_windows(tmp_path):
    from tensorflowdistributedlearning_tpu.parallel import multihost

    tel = obs.Telemetry(str(tmp_path), is_main=True, run_info={"task": "t"})
    try:
        multihost.instrument(tel)
        with multihost.barrier_probe():
            time.sleep(0.02)
        tel.window_event(1, steps=1)
    finally:
        multihost.uninstrument(tel)
        tel.close()
    windows = [
        e for e in obs.read_ledger(str(tmp_path))
        if e["event"] == "step_window"
    ]
    assert windows[0]["barrier_wait_s"] >= 0.015


def test_barrier_probe_noop_when_uninstrumented():
    from tensorflowdistributedlearning_tpu.parallel import multihost

    multihost.uninstrument()
    with multihost.barrier_probe():
        pass  # must not raise, must not need telemetry


def test_uninstrument_only_detaches_own_telemetry(tmp_path):
    from tensorflowdistributedlearning_tpu.parallel import multihost

    tel_a = obs.Telemetry(str(tmp_path / "a"), is_main=True)
    tel_b = obs.Telemetry(str(tmp_path / "b"), is_main=True)
    try:
        multihost.instrument(tel_b)
        multihost.uninstrument(tel_a)  # a stale teardown must not clobber b
        assert multihost._probe_telemetry is tel_b
    finally:
        multihost.uninstrument()
        tel_a.close()
        tel_b.close()


def test_serving_server_stamps_replica_on_windows(tmp_path):
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
    )

    engine = InferenceEngine(lambda x: {"y": jnp.asarray(x)}, (2,), buckets=(1,))
    batcher = MicroBatcher(engine, max_wait_ms=1.0, max_queue=4)
    tel = obs.Telemetry(
        str(tmp_path), process_index=2, run_info={"kind": "serve"}
    )
    server = ServingServer(
        engine, batcher, port=0, telemetry=tel, window_secs=0, replica_id=2
    ).start()
    try:
        fields = server.emit_window()
        assert fields["replica"] == 2
    finally:
        server.shutdown()
    # replica 2 wrote its own per-replica ledger under the fleet contract
    path = tmp_path / "telemetry-2.jsonl"
    assert path.exists()
    events = obs.read_ledger(str(path))
    assert any(
        e["event"] == "serve_window" and e["replica"] == 2 for e in events
    )


# -- cross-run compare + registry -------------------------------------------

TINY = dict(
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    n_blocks=(1, 1, 1),
    width_multiplier=0.125,
    output_stride=None,
)


@pytest.fixture(scope="module")
def two_fit_workdirs(tmp_path_factory):
    """Two real (tiny) fit() runs — the --compare acceptance operands."""
    from tensorflowdistributedlearning_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    dirs = []
    for name in ("run_a", "run_b"):
        workdir = str(tmp_path_factory.mktemp(name))
        ClassifierTrainer(
            workdir,
            None,  # synthetic data
            ModelConfig(**TINY),
            TrainConfig(
                train_log_every_steps=2,
                checkpoint_every_steps=4,
                eval_every_steps=4,
            ),
        ).fit(batch_size=8, steps=6, eval_every_steps=3)
        dirs.append(workdir)
    return dirs


@pytest.mark.slow  # two real fit() runs: outside the tier-1 window like the
# other real-training e2e tests; CI runs this module unfiltered ahead of
# tier-1, and tools/run_suite.py covers it in the full sweep
def test_compare_two_real_fit_workdirs_emits_structured_deltas(
    two_fit_workdirs, capsys
):
    from tensorflowdistributedlearning_tpu.cli import main

    a, b = two_fit_workdirs
    assert main(["telemetry-report", "--compare", a, b, "--json"]) == 0
    result = json.loads(capsys.readouterr().out)
    assert result["config_match"] is True  # same preset, apples-to-apples
    metrics = {d["metric"] for d in result["deltas"]}
    assert "step_time_mean_ms" in metrics
    assert "wall_s" in metrics
    assert any(m.startswith("eval:") for m in metrics)
    for d in result["deltas"]:
        assert d["verdict"] in ("regressed", "improved", "neutral")
        assert {"a", "b", "delta", "direction", "threshold"} <= set(d)

    # human rendering names verdicts and both runs
    assert main(["telemetry-report", "--compare", a, b]) == 0
    text = capsys.readouterr().out
    assert "run compare" in text
    assert "configs match" in text


def test_compare_detects_injected_step_time_regression(tmp_path):
    _write_process_ledger(tmp_path / "fast", 0, 100.0, process_count=1)
    _write_process_ledger(tmp_path / "slow", 0, 250.0, process_count=1)
    result = compare_lib.compare_workdirs(
        str(tmp_path / "fast"), str(tmp_path / "slow")
    )
    by_metric = {d["metric"]: d for d in result["deltas"]}
    assert by_metric["step_time_mean_ms"]["verdict"] == "regressed"
    assert result["regressions"] >= 1
    # ... and the same delta in the other direction is an improvement
    back = compare_lib.compare_workdirs(
        str(tmp_path / "slow"), str(tmp_path / "fast")
    )
    assert {d["metric"]: d for d in back["deltas"]}[
        "step_time_mean_ms"
    ]["verdict"] == "improved"


def test_compare_small_deltas_are_neutral(tmp_path):
    _write_process_ledger(tmp_path / "a", 0, 100.0, process_count=1)
    _write_process_ledger(tmp_path / "b", 0, 104.0, process_count=1)  # 4% < 10%
    result = compare_lib.compare_workdirs(
        str(tmp_path / "a"), str(tmp_path / "b")
    )
    assert {d["metric"]: d for d in result["deltas"]}[
        "step_time_mean_ms"
    ]["verdict"] == "neutral"


@pytest.mark.slow  # same two real fit() runs as the compare acceptance
def test_registry_register_and_compare_by_run_id(
    two_fit_workdirs, tmp_path, capsys
):
    from tensorflowdistributedlearning_tpu.cli import main

    a, b = two_fit_workdirs
    registry = str(tmp_path / "registry")
    ids = []
    for wd in (a, b):
        assert main([
            "telemetry-report", wd, "--registry-dir", registry, "--register",
        ]) == 0
        row = json.loads(capsys.readouterr().out)
        assert row["config_hash"]
        assert row["steps"] == 6
        assert row["goodput"]["compute_s"] > 0
        ids.append(row["run_id"])
    rows = compare_lib.load_registry(registry)
    assert [r["run_id"] for r in rows] == ids
    # compare by registered run id (no workdir access needed)
    assert main([
        "telemetry-report", "--registry-dir", registry,
        "--compare", ids[0], ids[1], "--json",
    ]) == 0
    result = json.loads(capsys.readouterr().out)
    assert result["a"]["run_id"] == ids[0]
    assert result["deltas"]


def test_register_without_registry_dir_is_an_error(
    tmp_path, capsys
):
    from tensorflowdistributedlearning_tpu.cli import main

    _write_process_ledger(tmp_path, 0, 100.0, process_count=1)
    assert main(["telemetry-report", str(tmp_path), "--register"]) == 2
    assert "--registry-dir" in capsys.readouterr().err


def test_resolve_run_unknown_ref_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="neither a workdir"):
        compare_lib.resolve_run("nope-123", str(tmp_path))


def test_run_id_is_stable_across_registrations(tmp_path):
    """run_id keys off the RUN's own start clock (report run.started_t), not
    registration time: re-registering the same workdir reproduces the same
    id (resolve_run's most-recent-duplicate contract relies on this)."""
    _write_process_ledger(tmp_path / "wd", 0, 100.0, process_count=1)
    reg = str(tmp_path / "reg")
    row1 = compare_lib.register_run(reg, str(tmp_path / "wd"))
    time.sleep(1.1)  # run_id has second granularity
    row2 = compare_lib.register_run(reg, str(tmp_path / "wd"))
    assert row1["run_id"] == row2["run_id"]
    assert row1["t"] == row2["t"]


def test_export_trace_covers_secondary_only_workdir(tmp_path, capsys):
    """A workdir holding ONLY a replica's telemetry-N.jsonl (no canonical
    telemetry.jsonl) must still export its sampled spans."""
    from tensorflowdistributedlearning_tpu.cli import main

    led = RunLedger(str(tmp_path), filename=per_process_filename(1))
    led.event("run_header", kind="serve", process_index=1)
    led.event(
        "trace", trace_id="t1", span_id="s1", name="request",
        start_t=1.0, duration_s=0.002,
    )
    led.close()
    out = tmp_path / "trace.json"
    assert main([
        "telemetry-report", str(tmp_path), "--export-trace", str(out),
    ]) == 0
    assert json.loads(capsys.readouterr().out)["span_events"] == 1
    doc = json.loads(out.read_text())
    assert doc["traceEvents"][0]["name"] == "request"


# -- regression sentinel -----------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_sentinel_passes_on_committed_benches():
    """fresh == committed baseline must pass: the committed history is, by
    definition, not a regression against itself."""
    rc = regression_sentinel.main([
        "--check",
        "--fresh-async", os.path.join(REPO, "BENCH_ASYNC.json"),
        "--fresh-serve", os.path.join(REPO, "BENCH_SERVE.json"),
    ])
    assert rc == 0


def test_sentinel_fails_on_injected_2x_step_time_regression(tmp_path):
    """THE acceptance pin: a 2x async step-time regression (and the async
    loop now slower than sync) must exit nonzero."""
    with open(os.path.join(REPO, "BENCH_ASYNC.json")) as f:
        doctored = json.load(f)
    doctored["async"]["step_time_ms"] *= 2.0
    doctored["step_time_ratio_async_over_sync"] = round(
        doctored["async"]["step_time_ms"] / doctored["sync"]["step_time_ms"], 3
    )
    fresh = tmp_path / "fresh_async.json"
    fresh.write_text(json.dumps(doctored))
    rc = regression_sentinel.main([
        "--check", "--benches", "async", "--fresh-async", str(fresh),
    ])
    assert rc == 1


def test_sentinel_fails_on_serve_recompile_or_throughput_collapse(tmp_path):
    with open(os.path.join(REPO, "BENCH_SERVE.json")) as f:
        doctored = json.load(f)
    doctored["batched"]["requests_per_sec"] /= 3.0  # half-throughput class
    doctored["batched"]["latency_ms"]["p99"] *= 10  # order-of-magnitude tail
    doctored["post_warmup_recompiles"] = 2  # hard gate
    fresh = tmp_path / "fresh_serve.json"
    fresh.write_text(json.dumps(doctored))
    rc = regression_sentinel.main([
        "--check", "--benches", "serve", "--fresh-serve", str(fresh),
        "--json-out", str(tmp_path / "verdict.json"),
    ])
    assert rc == 1
    verdict = json.loads((tmp_path / "verdict.json").read_text())
    failed = {f["metric"] for f in verdict["findings"] if not f["ok"]}
    assert "batched.requests_per_sec" in failed
    assert "batched.latency_ms.p99" in failed
    assert "post_warmup_recompiles" in failed


def test_sentinel_missing_baseline_is_an_error_not_a_pass(tmp_path):
    rc = regression_sentinel.main([
        "--check", "--benches", "async",
        "--baseline-async", str(tmp_path / "missing.json"),
        "--fresh-async", str(tmp_path / "missing.json"),
    ])
    assert rc == 1  # a bench that cannot run/load must fail the gate


def test_sentinel_nothing_compared_is_not_a_pass():
    # an empty bench selection compares nothing: rc 2, never a green gate
    assert regression_sentinel.main(["--check", "--benches", ""]) == 2


def test_sentinel_params_parity_is_a_hard_gate(tmp_path):
    with open(os.path.join(REPO, "BENCH_ASYNC.json")) as f:
        doctored = json.load(f)
    doctored["final_params_bit_identical"] = False
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(doctored))
    rc = regression_sentinel.main([
        "--check", "--benches", "async", "--fresh-async", str(fresh),
    ])
    assert rc == 1


# -- run_suite --aggregate ---------------------------------------------------


def test_run_suite_aggregate_merges_group_ledgers(tmp_path):
    from run_suite import _write_group_ledger

    ledger_dir = str(tmp_path)
    suite = RunLedger(ledger_dir)
    suite.event("run_header", kind="test_suite", groups=2, files=4,
                process_index=0, process_count=3)
    suite.event("run_end", ok=True, total_secs=1.0)
    suite.close()
    _write_group_ledger(ledger_dir, 1, ["test_a.py"], secs=1.5, rc=0)
    _write_group_ledger(ledger_dir, 2, ["test_b.py"], secs=2.5, rc=1)

    agg = fleet_lib.fleet_summary(ledger_dir)
    assert agg["processes"] == 3
    kinds = {
        r["process_index"]: r["kind"] for r in agg["per_process"]
    }
    assert kinds == {0: "test_suite", 1: "suite_group", 2: "suite_group"}
    group2 = obs.read_ledger(os.path.join(ledger_dir, "telemetry-2.jsonl"))
    assert group2[-1] == {
        **group2[-1], "event": "run_end", "ok": False,
    }
