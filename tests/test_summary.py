"""TensorBoard event-writer tests: wire-format correctness (CRC-32C known-answer,
TFRecord framing) and scalar round-trips via the bundled parser."""

import glob
import os
import struct

import numpy as np

from tensorflowdistributedlearning_tpu.utils import summary as summary_lib


def test_crc32c_known_answer():
    # RFC 3720 check value for "123456789"
    assert summary_lib._crc32c(b"123456789") == 0xE3069283


def test_tfrecord_framing():
    rec = summary_lib._tfrecord(b"abc")
    (length,) = struct.unpack_from("<Q", rec, 0)
    assert length == 3
    assert rec[12:15] == b"abc"
    # payload crc verifies
    (crc,) = struct.unpack_from("<I", rec, 15)
    assert crc == summary_lib._masked_crc(b"abc")


def test_scalar_roundtrip(tmp_path):
    w = summary_lib.SummaryWriter(str(tmp_path))
    w.scalar("loss", 1.5, step=10)
    w.scalars({"metrics/mean_iou": 0.25, "metrics/mean_acc": 0.75}, step=20)
    w.close()
    (path,) = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    events = summary_lib.read_events(path)
    assert events[0] == (10, {"loss": 1.5})
    step, scalars = events[1]
    assert step == 20
    assert abs(scalars["metrics/mean_iou"] - 0.25) < 1e-6
    assert abs(scalars["metrics/mean_acc"] - 0.75) < 1e-6


def test_image_event_written(tmp_path):
    w = summary_lib.SummaryWriter(str(tmp_path))
    w.image("probability/0", np.random.default_rng(0).uniform(0, 1, (8, 8)), step=1)
    w.close()
    (path,) = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    # parseable (image events yield no scalars but must not break the reader)
    assert summary_lib.read_events(path) == []
    assert os.path.getsize(path) > 100
