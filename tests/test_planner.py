"""Parallelism planner (parallel/planner.py): divisibility rejection with
named constraints, budget-driven layout choice, scoring tie-breaks, fake pod
topologies, exact bytes/chip accounting against ``tree_bytes_per_device``,
the ``plan`` CLI, and the headline equivalence drill — ``--parallelism auto``
on the 8-device CPU mesh lands bit-identical params vs the same layout passed
as explicit flags."""

import dataclasses
import json

import jax
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.parallel import planner


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _profile(params, opt, act=0, n_layers=1):
    count = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))
    return planner.ModelProfile(
        params=params,
        batch_stats={},
        opt_state=opt,
        activation_bytes_per_example=act,
        param_count=count,
        n_layers=n_layers,
    )


CIFARISH = ModelConfig(
    num_classes=10,
    input_shape=(32, 32),
    input_channels=3,
    n_blocks=(1, 1, 1),
    base_depth=8,
    width_multiplier=0.0625,
    output_stride=None,
)
TOPO8 = planner.Topology(n_devices=8, local_device_count=8)


# -- divisibility / named constraints ---------------------------------------


def test_indivisible_model_axis_named():
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    with pytest.raises(planner.PlanError, match=planner.REJECT_MODEL_AXIS):
        planner.plan(
            CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile,
            pinned={"model_parallel": 3},
        )


def test_batch_indivisible_named():
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    # batch 12 over dp8 does not divide; pinning pure dp (all other degrees 1)
    # leaves no fallback layout
    with pytest.raises(planner.PlanError, match=planner.REJECT_BATCH):
        planner.plan(
            CIFARISH, TrainConfig(), 12, topology=TOPO8, profile=profile,
            pinned={
                "model_parallel": 1, "pipeline_parallel": 1,
                "sequence_parallel": 1, "expert_parallel": 1,
                "weight_update_sharding": False,
            },
        )


def test_spatial_rejected_with_stride_detail():
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    p = planner.plan(CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile)
    spatial = [
        c for c in p.candidates if c.layout.sequence_parallel > 1
    ]
    assert spatial, "spatial candidates must be enumerated"
    assert all(c.reject_reason == planner.REJECT_SPATIAL for c in spatial)
    # 32x32 stride-32 trunk cannot H-shard: the detail names the rule
    assert "stride" in spatial[0].reject_detail


def test_grad_accum_indivisible_named():
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    cfg = TrainConfig(grad_accum_steps=3)
    with pytest.raises(planner.PlanError, match=planner.REJECT_GRAD_ACCUM):
        planner.plan(
            CIFARISH, cfg, 64, topology=TOPO8, profile=profile,
            pinned={
                "model_parallel": 1, "pipeline_parallel": 1,
                "sequence_parallel": 1, "expert_parallel": 1,
                "weight_update_sharding": False,
            },
        )


def test_pipeline_only_for_stage_backbones():
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    p = planner.plan(CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile)
    assert not any(c.layout.pipeline_parallel > 1 for c in p.candidates), (
        "resnet cannot pipeline — pp layouts must not be enumerated for it"
    )
    vit = ModelConfig(
        backbone="vit", num_classes=10, input_shape=(32, 32), input_channels=3,
        patch_size=8, embed_dim=64, vit_layers=4, num_heads=2, output_stride=None,
    )
    p = planner.plan(vit, TrainConfig(), 64, topology=TOPO8, profile=profile)
    pp = [c for c in p.candidates if c.layout.pipeline_parallel > 1]
    assert pp
    # 4 ViT layers: pp2/pp4 divide, pp8 is rejected with the stage rule
    verdicts = {c.layout.pipeline_parallel: c for c in pp}
    assert verdicts[2].feasible and verdicts[4].feasible
    assert verdicts[8].reject_reason == planner.REJECT_PIPELINE


def test_conflicting_strategies_rejected_named():
    """The execution strategies' mutual-exclusivity matrix holds at plan
    time: a pinned tp x pp combination (which no step builder can run, and
    TrainConfig would reject) fails with the named strategy_conflict, not a
    green-lit impossible layout."""
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    vit = ModelConfig(
        backbone="vit", num_classes=10, input_shape=(32, 32), input_channels=3,
        patch_size=8, embed_dim=64, vit_layers=4, num_heads=2, output_stride=None,
    )
    with pytest.raises(planner.PlanError, match=planner.REJECT_CONFLICT):
        planner.plan(
            vit, TrainConfig(), 64, topology=TOPO8, profile=profile,
            pinned={"model_parallel": 2, "pipeline_parallel": 2},
        )
    with pytest.raises(planner.PlanError, match=planner.REJECT_CONFLICT):
        planner.plan(
            vit, TrainConfig(), 64, topology=TOPO8, profile=profile,
            pinned={"pipeline_parallel": 2, "weight_update_sharding": True},
        )


def test_auto_respects_train_config_composition_rules():
    """Auto must never choose a layout the TrainConfig would then reject:
    under grad accumulation the tensor/pipeline candidates are out, and
    under mixup so are sequence/pipeline."""
    profile = _profile(
        {"w": _sds((4096, 4096))}, {"mu": _sds((4096, 4096))}, act=1024
    )
    # this profile prefers TP when unconstrained (pinned by the scoring
    # test); grad accumulation must veto that choice
    cfg = TrainConfig(grad_accum_steps=2)
    p = planner.plan(CIFARISH, cfg, 16, topology=TOPO8, profile=profile)
    assert p.layout.model_parallel == 1
    tp = [c for c in p.candidates if c.layout.model_parallel > 1]
    assert tp and all(
        c.reject_reason == planner.REJECT_CONFLICT for c in tp
    )


def test_plan_for_config_dispatch():
    """plan_for_config: 'auto' plans with non-default degrees pinned,
    'explicit' validates the hand spec through the same machinery."""
    profile = _profile({"w": _sds((8, 16))}, {"mu": _sds((8, 16))})
    auto = TrainConfig(parallelism="auto", weight_update_sharding=True)
    p = planner.plan_for_config(
        CIFARISH, auto, 64, topology=TOPO8, profile=profile
    )
    assert p.source == "auto" and p.layout.weight_update_sharding
    explicit = TrainConfig()
    p = planner.plan_for_config(
        CIFARISH, explicit, 64, topology=TOPO8, profile=profile
    )
    assert p.source == "explicit"
    assert p.layout == planner.Layout(data_parallel=8)


def test_trainer_refuses_unresolved_auto(tmp_path):
    """parallelism='auto' on a directly-constructed trainer is a loud
    contract error, never a silent explicit-layout run."""
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    with pytest.raises(ValueError, match="resolved before constructing"):
        ClassifierTrainer(
            str(tmp_path), None,
            dataclasses.replace(CIFARISH),
            TrainConfig(parallelism="auto"),
        )


# -- budget ------------------------------------------------------------------


def test_budget_rejects_replicated_and_picks_zero1():
    params = {"w": _sds((8, 3))}   # 96 bytes, trailing dim resists tp
    opt = {"mu": _sds((8, 3))}     # ZeRO-1 shards the leading dim /dp
    profile = _profile(params, opt)
    p_bytes = 8 * 3 * 4
    budget = p_bytes + p_bytes // 4 - 1  # fits only the dp8 ZeRO-1 shard
    p = planner.plan(
        CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile,
        hbm_bytes_per_device=budget,
    )
    assert p.layout.weight_update_sharding
    assert p.layout.data_parallel == 8
    assert p.chosen.bytes["opt_state_bytes_per_chip"] == p_bytes // 8
    plain = [
        c for c in p.candidates
        if c.layout == planner.Layout(data_parallel=8)
    ][0]
    assert plain.reject_reason == planner.REJECT_BUDGET
    assert "bytes/chip" in plain.reject_detail


def test_explicit_over_budget_warns_not_raises():
    profile = _profile({"w": _sds((8, 4))}, {"mu": _sds((8, 4))})
    cfg = TrainConfig()
    p = planner.plan(
        CIFARISH, cfg, 64, topology=TOPO8, profile=profile,
        pinned=planner._pinned_from_config(cfg), hbm_bytes_per_device=16,
    )
    assert p.source == "explicit"
    assert not p.chosen.feasible
    assert p.chosen.reject_reason == planner.REJECT_BUDGET
    assert p.warnings and "budget" in p.warnings[0]


# -- scoring -----------------------------------------------------------------


def test_scoring_tie_prefers_simpler_layout():
    """An all-zero profile leaves only the per-collective latency term:
    pure DP (one bucketed all-reduce) wins outright, and the genuinely TIED
    pair (dp4xtp2 vs dp2xtp4 — identical op counts, zero volume) must order
    deterministically by the complexity tie-break (lower degree first)."""
    profile = _profile({}, {}, act=0, n_layers=1)
    p = planner.plan(
        CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile
    )
    assert p.layout == planner.Layout(data_parallel=8)
    by_layout = {c.layout: c for c in p.candidates}
    tp2 = by_layout[planner.Layout(data_parallel=4, model_parallel=2)]
    tp4 = by_layout[planner.Layout(data_parallel=2, model_parallel=4)]
    assert tp2.score == tp4.score  # genuinely tied
    ordered = sorted(
        [tp4, tp2], key=lambda c: (c.score, planner._complexity(c.layout))
    )
    assert ordered[0] is tp2


def test_large_params_small_batch_prefers_tensor_parallel():
    """The comms-vs-compute trade: gradient all-reduce volume dominating
    per-chip activations makes a TP layout score better than pure DP."""
    params = {"w": _sds((4096, 4096))}  # 64 MB of gradient per step
    opt = {"mu": _sds((4096, 4096))}
    profile = _profile(params, opt, act=1024, n_layers=1)
    p = planner.plan(CIFARISH, TrainConfig(), 8, topology=TOPO8, profile=profile)
    assert p.layout.model_parallel > 1


# -- pod topologies (fake process_info) --------------------------------------


def test_pod_topology_rejects_process_spanning_shards():
    pod = planner.Topology(n_devices=32, local_device_count=8, process_count=4)
    profile = _profile({"w": _sds((8, 16))}, {"mu": _sds((8, 16))})
    with pytest.raises(
        planner.PlanError, match=planner.REJECT_SPANS_PROCESSES
    ):
        planner.plan(
            CIFARISH, TrainConfig(), 64, topology=pod, profile=profile,
            pinned={"model_parallel": 16},
        )
    # tp8 stays within one host's 8 chips: feasible
    p = planner.plan(
        CIFARISH, TrainConfig(), 64, topology=pod, profile=profile,
        pinned={"model_parallel": 8},
    )
    assert p.layout.model_parallel == 8
    assert p.layout.data_parallel == 4


def test_pod_topology_process_batch_divisibility():
    pod = planner.Topology(n_devices=32, local_device_count=8, process_count=4)
    profile = _profile({"w": _sds((8, 16))}, {"mu": _sds((8, 16))})
    with pytest.raises(planner.PlanError, match=planner.REJECT_PROCESS_BATCH):
        planner.plan(
            CIFARISH, TrainConfig(), 30, topology=pod, profile=profile
        )


# -- exact bytes accounting ---------------------------------------------------


@pytest.mark.parametrize(
    "layout_kwargs",
    [
        {},
        {"weight_update_sharding": True},
        {"model_parallel": 2},
        {"model_parallel": 2, "weight_update_sharding": True},
    ],
    ids=["replicated", "zero1", "tp2", "tp2_zero1"],
)
def test_predicted_bytes_match_tree_bytes_per_device(layout_kwargs):
    """The acceptance contract: the planner's predicted params/opt bytes per
    chip equal ``tree_bytes_per_device`` of the actually-placed state, bit
    for bit, for every placement mode."""
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
    from tensorflowdistributedlearning_tpu.parallel import tensor as tp_lib
    from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train.state import (
        create_train_state,
        tree_bytes_per_device,
    )

    tcfg = TrainConfig(**{
        k: v for k, v in layout_kwargs.items() if k == "model_parallel"
    })
    tcfg = dataclasses.replace(
        tcfg,
        weight_update_sharding=layout_kwargs.get(
            "weight_update_sharding", False
        ),
    )
    plan = planner.validate_config(CIFARISH, tcfg, 16, topology=TOPO8)
    predicted = plan.chosen.bytes

    mesh = mesh_lib.make_mesh(
        8, model_parallel=layout_kwargs.get("model_parallel", 1)
    )
    model = build_model(CIFARISH)
    state = create_train_state(
        model,
        step_lib.make_optimizer(tcfg),
        jax.random.PRNGKey(0),
        np.zeros((1, 32, 32, 3), np.float32),
    )
    tp = layout_kwargs.get("model_parallel", 1) > 1
    if layout_kwargs.get("weight_update_sharding"):
        state = zero_lib.shard_state_weight_update(
            state, mesh, tensor_parallel=tp
        )
    elif tp:
        state = tp_lib.shard_state_tensor_parallel(state, mesh)
    else:
        state = mesh_lib.replicate(state, mesh)

    assert predicted["params_bytes_per_chip"] == tree_bytes_per_device(
        state.params
    )
    assert predicted["opt_state_bytes_per_chip"] == tree_bytes_per_device(
        state.opt_state
    )
    assert predicted["batch_stats_bytes_per_chip"] == tree_bytes_per_device(
        state.batch_stats
    )


# -- plan application ---------------------------------------------------------


def test_auto_pins_explicit_flags():
    profile = _profile(
        {"w": _sds((8, 16))}, {"mu": _sds((8, 16))}, act=64, n_layers=2
    )
    p = planner.plan(
        CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile,
        pinned={"weight_update_sharding": True},
    )
    assert p.layout.weight_update_sharding  # the pinned flag won
    overrides = p.overrides()
    cfg = dataclasses.replace(TrainConfig(parallelism="auto"), **overrides)
    assert cfg.weight_update_sharding


def test_plan_header_is_json_clean():
    profile = _profile({"w": _sds((8, 16))}, {"mu": _sds((8, 16))})
    p = planner.plan(
        CIFARISH, TrainConfig(), 64, topology=TOPO8, profile=profile
    )
    header = json.loads(json.dumps(p.header()))
    assert header["source"] == "auto"
    assert header["layout"]["data_parallel"] >= 1
    assert "total_bytes_per_chip" in header["predicted"]
    json.loads(json.dumps(p.to_json()))  # the full table too


def test_config_hash_distinguishes_plan_layouts():
    from tensorflowdistributedlearning_tpu.obs import compare as compare_lib

    base = {
        "model_config": {"backbone": "resnet"},
        "train_config": {"lr": 0.1},
        "mesh": {"batch": 8},
    }
    a = dict(base, plan={"layout": {"data_parallel": 8}})
    b = dict(base, plan={"layout": {"data_parallel": 4, "model_parallel": 2}})
    assert compare_lib.config_hash(a) != compare_lib.config_hash(b)
    # and identical layouts still match
    assert compare_lib.config_hash(a) == compare_lib.config_hash(
        json.loads(json.dumps(a))
    )
    # plan absence must not change the identity: a header whose best-effort
    # plan failed to resolve hashes like its planned twin (the layout is
    # reconstructed from train_config + mesh)
    planned = {
        "model_config": {"backbone": "resnet"},
        "train_config": {
            "lr": 0.1, "model_parallel": 2, "pipeline_parallel": 1,
            "sequence_parallel": 1, "expert_parallel": 1,
            "weight_update_sharding": False,
        },
        "mesh": {"batch": 4, "model": 2, "sequence": 1},
    }
    with_plan = dict(planned, plan={"layout": {
        "data_parallel": 4, "model_parallel": 2, "pipeline_parallel": 1,
        "sequence_parallel": 1, "expert_parallel": 1,
        "weight_update_sharding": False,
    }})
    assert compare_lib.config_hash(planned) == compare_lib.config_hash(
        with_plan
    )


def test_validate_config_names_constraint_for_presets():
    """Satellite: a preset whose hardcoded layout cannot run on this topology
    fails at parse time with the named constraint."""
    bad = TrainConfig(model_parallel=5)
    with pytest.raises(planner.PlanError, match=planner.REJECT_MODEL_AXIS):
        planner.validate_config(CIFARISH, bad, 64, topology=TOPO8)


def test_plan_cli_table_and_json(capsys):
    from tensorflowdistributedlearning_tpu import cli

    rc = cli.main([
        "plan", "--preset", "cifar10_smoke", "--batch-size", "64",
        "--n-devices", "8",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "chosen" in out and "parallelism plan" in out

    rc = cli.main([
        "plan", "--preset", "cifar10_smoke", "--batch-size", "64",
        "--n-devices", "8", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    parsed = json.loads(out)
    assert parsed["feasible"] and parsed["candidates"]


def test_plan_cli_infeasible_pin_fails_with_named_reason(capsys):
    from tensorflowdistributedlearning_tpu import cli

    rc = cli.main([
        "plan", "--preset", "cifar10_smoke", "--batch-size", "64",
        "--n-devices", "8", "--model-parallel", "3",
    ])
    err = capsys.readouterr().err
    assert rc == 1
    assert planner.REJECT_MODEL_AXIS in err


# -- the headline equivalence drill -------------------------------------------


@pytest.mark.slow
def test_auto_equals_explicit_bit_identical(tmp_path):
    """``--parallelism auto`` on the 8-device CPU mesh picks a valid layout
    and lands bit-identical params vs the same layout passed as explicit
    flags (the two runs share seeds and the synthetic stream)."""
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger
    from tensorflowdistributedlearning_tpu.train.fit import fit_preset

    steps, batch = 4, 16
    fit_preset(
        "cifar10_smoke", str(tmp_path / "auto"), steps=steps,
        batch_size=batch, eval_every_steps=100, parallelism="auto",
    )
    header = next(
        e for e in read_ledger(str(tmp_path / "auto"))
        if e.get("event") == "run_header"
    )
    plan = header["plan"]
    assert plan["source"] == "auto" and plan["feasible"]
    layout = plan["layout"]

    fit_preset(
        "cifar10_smoke", str(tmp_path / "explicit"), steps=steps,
        batch_size=batch, eval_every_steps=100,
        model_parallel=layout["model_parallel"],
        pipeline_parallel=layout["pipeline_parallel"],
        sequence_parallel=layout["sequence_parallel"],
        expert_parallel=layout["expert_parallel"],
        weight_update_sharding=layout["weight_update_sharding"],
    )
    exp_header = next(
        e for e in read_ledger(str(tmp_path / "explicit"))
        if e.get("event") == "run_header"
    )
    assert exp_header["plan"]["source"] == "explicit"
    assert exp_header["plan"]["layout"] == layout

    def final_params(model_dir, layout):
        from tensorflowdistributedlearning_tpu.configs import get_preset
        from tensorflowdistributedlearning_tpu.train.fit import (
            ClassifierTrainer,
        )

        preset = get_preset("cifar10_smoke")
        tcfg = dataclasses.replace(
            preset.train,
            model_parallel=layout["model_parallel"],
            pipeline_parallel=layout["pipeline_parallel"],
            sequence_parallel=layout["sequence_parallel"],
            expert_parallel=layout["expert_parallel"],
            weight_update_sharding=layout["weight_update_sharding"],
        )
        trainer = ClassifierTrainer(str(model_dir), None, preset.model, tcfg)
        ckpt = trainer._checkpointer()
        try:
            state = ckpt.restore_latest(trainer._host_template())
        finally:
            ckpt.close()
        assert int(jax.device_get(state.step)) == steps
        return jax.device_get(state.params)

    a = final_params(tmp_path / "auto", layout)
    b = final_params(tmp_path / "explicit", layout)
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert flat_a and len(flat_a) == len(flat_b)
    for la, lb in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
