"""Tests for the named-config registry, profiling utilities, and serving export."""

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.configs import (
    PRESETS,
    get_preset,
    resnet_depth_blocks,
)
from tensorflowdistributedlearning_tpu.utils.profiling import StepTimer, annotate, sync


BASELINE_LADDER = {
    "tgs_salt",
    "cifar10_smoke",
    "resnet50_imagenet",
    "resnet101_imagenet",
    "resnet152_imagenet",
    "xception41_imagenet",
    "resnet50_bf16_8k",
}


def test_registry_covers_baseline_ladder():
    assert BASELINE_LADDER <= set(PRESETS)


def test_presets_are_buildable():
    # every preset's ModelConfig must pass validation and build a module
    from tensorflowdistributedlearning_tpu.models import build_model

    for name in PRESETS:
        preset = get_preset(name)
        model = build_model(preset.model)
        assert model is not None
        assert preset.global_batch > 0


def test_tgs_salt_is_reference_parity():
    p = get_preset("tgs_salt")
    assert p.model.input_shape == (101, 101)
    assert p.model.input_channels == 2
    assert p.train.lr == 0.001
    assert p.train.n_folds == 5
    assert p.global_batch == 64  # Untitled.ipynb cells 7-8


def test_unknown_preset_raises():
    with pytest.raises(ValueError, match="Unknown preset"):
        get_preset("resnet9000")


def test_resnet_depth_blocks():
    assert resnet_depth_blocks(50) == (3, 4, 6)
    assert resnet_depth_blocks(101) == (3, 4, 23)
    assert resnet_depth_blocks(152) == (3, 8, 36)
    with pytest.raises(ValueError):
        resnet_depth_blocks(42)


def test_step_timer_summary():
    import jax.numpy as jnp

    t = StepTimer(items_per_step=8)
    for _ in range(4):
        t.start()
        out = jnp.ones((4, 4)) * 2
        t.stop(out)
    s = t.summary(skip_first=1)
    assert s["steps"] == 3
    assert s["mean_s"] > 0
    assert s["items_per_sec"] > 0
    assert s["p50_s"] <= s["p90_s"] or abs(s["p50_s"] - s["p90_s"]) < 1e-9


def test_step_timer_requires_start():
    with pytest.raises(RuntimeError):
        StepTimer().stop()


def test_step_timer_empty_summary_raises():
    with pytest.raises(RuntimeError, match="no steps recorded"):
        StepTimer().summary()


def test_sync_handles_non_arrays():
    sync({"a": 1, "b": [2, 3]})  # no jax arrays: must be a no-op, not a crash


def test_annotate_span_runs():
    with annotate("decode"):
        np.zeros(3)


def test_cli_presets_command(capsys):
    import json

    from tensorflowdistributedlearning_tpu.cli import main

    assert main(["presets"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert BASELINE_LADDER <= set(out)


def test_memory_stats_graceful():
    """memory_stats never raises; absent on backends without the query (CPU),
    populated with bytes_in_use/peak on TPU."""
    from tensorflowdistributedlearning_tpu.utils import profiling

    stats = profiling.memory_stats()
    assert isinstance(stats, dict)
    for s in stats.values():
        assert isinstance(s, dict)
    logged = profiling.log_memory(lambda *a: None)
    # live counters can drift between snapshots on TPU; the contract is shape
    assert set(logged) == set(stats)


def test_profiler_trace_context_writes_logdir(tmp_path):
    """utils.profiling.trace wraps jax.profiler: the context manager runs the
    body and leaves a trace directory behind (CPU backend suffices)."""
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.utils import profiling

    import os

    logdir = str(tmp_path / "trace")
    with profiling.trace(logdir):
        jnp.ones((64, 64)).sum().block_until_ready()
    assert os.path.isdir(logdir)
    assert any(os.scandir(logdir))  # plugins/profile/... written
