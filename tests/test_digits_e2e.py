"""Real-data end-to-end proof: train on sklearn's handwritten digits (genuine
8x8 scans, the one real image corpus available without network access) through
the full record-shard -> native reader -> fit() -> eval path, and assert the
held-out accuracy of a REAL trained model (loose tolerance — the reference's
own real-data proof was its notebook runs, Untitled.ipynb cells 7-8)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _resnet_cfg():
    """The shared tiny reference-family trunk the recipe e2e tests train."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.data.digits import (
        SHORT_BUDGET_BN_DECAY,
    )

    return ModelConfig(
        num_classes=10,
        input_shape=(16, 16),
        input_channels=1,
        n_blocks=(1, 1, 1),
        block_type="basic_block",
        width_multiplier=0.25,
        output_stride=None,
        batch_norm_decay=SHORT_BUDGET_BN_DECAY,
    )


def _fit_digits(tmp_path, model_cfg, train_cfg, *, steps, upscale=2):
    """One copy of the prepare-shards -> ClassifierTrainer -> fit boilerplate
    (the file once let example and test recipes drift — lr 1e-3 vs 3e-3 —
    costing 24 points of top-1; one shape here keeps the three e2e tests
    training the SAME pipeline)."""
    from tensorflowdistributedlearning_tpu.data.digits import prepare_digits
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    data_dir = str(tmp_path / "data")
    prepare_digits(data_dir, upscale=upscale, val_fraction=0.2, seed=0, shards=2)
    trainer = ClassifierTrainer(
        str(tmp_path / "run"), data_dir, model_cfg, train_cfg
    )
    return trainer.fit(batch_size=64, steps=steps, eval_every_steps=steps)


@pytest.mark.slow  # real training run (minutes on the 1-core box); run_suite covers it
def test_digits_trains_to_real_accuracy(tmp_path):
    """A tiny trunk on 16x16 upscaled digits reaches >=85% held-out top-1 in a
    short budget (a linear model scores ~95% on this corpus; the loose bar
    keeps the test robust to init noise while still proving the pipeline
    learns real structure from real data). The recipe is the SHARED one the
    example's committed DIGITS_RUN.json ran."""
    from tensorflowdistributedlearning_tpu.data.digits import (
        short_budget_train_config,
    )

    result = _fit_digits(
        tmp_path,
        _resnet_cfg(),
        short_budget_train_config(250, n_devices=1),
        steps=250,
    )
    assert result.final_metrics["metrics/top1"] >= 0.85, result.final_metrics
    # the val split is genuinely held out: prepare_digits partitions the
    # corpus by a seeded permutation (359 val + 1438 train)
    assert result.steps == 250


def test_large_batch_recipe_config_contract():
    """The LARS recipe's measured operating point (lr 0.8 @ batch 256, 10%
    warmup — behind DIGITS_RUN.json's committed 97.2%/150-step run and the
    README claim) must stay reproducible: assert the constructed config's
    fields rather than retrain (a full LARS run is ~8 min on the 1-core CI
    box; the field contract is free)."""
    from tensorflowdistributedlearning_tpu.data.digits import (
        large_batch_recipe_train_config,
    )

    cfg = large_batch_recipe_train_config(150, 256)
    assert cfg.optimizer == "lars"
    assert cfg.lr == pytest.approx(0.8)
    assert cfg.lr_warmup_steps == 15
    assert cfg.lr_schedule == "cosine"
    assert cfg.lr_decay_steps == 150
    assert cfg.weight_decay == 1e-4
    assert cfg.label_smoothing == 0.1
    assert cfg.augmentation == "crop"
    # linear scaling in batch around the anchor
    assert large_batch_recipe_train_config(150, 512).lr == pytest.approx(1.6)
    # overrides win (the lr-probe path this recipe was calibrated with)
    assert large_batch_recipe_train_config(150, 256, lr=0.5).lr == 0.5


@pytest.mark.slow  # real training run (minutes on the 1-core box); run_suite covers it
def test_digits_production_recipe_trains_to_real_accuracy(tmp_path):
    """The ImageNet PRODUCTION recipe (SGD Nesterov + linear-scaled lr +
    warmup-cosine + kernels-only wd + label smoothing — the knobs behind the
    resnet50_imagenet preset) learns real data: >=80% held-out top-1 at the
    same tiny budget as the adam test above (the committed full-budget run is
    DIGITS_RUN.json's 'sgd' entry: 93.9% at 600 steps). Loose bar — SGD
    converges slower than adam at short budgets; the assertion is that the
    recipe HELPS on real data, not that it matches adam here."""
    from tensorflowdistributedlearning_tpu.data.digits import (
        production_recipe_train_config,
    )

    result = _fit_digits(
        tmp_path,
        _resnet_cfg(),
        production_recipe_train_config(250, 64, n_devices=1),
        steps=250,
    )
    assert result.final_metrics["metrics/top1"] >= 0.80, result.final_metrics


def _xception_cfg():
    """One copy of the tiny Xception config so the plain and pipelined
    goldens provably train the SAME architecture (the drift failure
    _fit_digits documents)."""
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.data.digits import (
        SHORT_BUDGET_BN_DECAY,
    )

    return ModelConfig(
        backbone="xception",
        num_classes=10,
        input_shape=(32, 32),
        input_channels=1,
        width_multiplier=0.125,
        output_stride=None,
        batch_norm_decay=SHORT_BUDGET_BN_DECAY,
    )


@pytest.mark.slow  # real training run (minutes on the 1-core box); run_suite covers it
def test_digits_xception_trains_end_to_end(tmp_path):
    """The Xception-41 classifier — the family whose train path the
    dropout-PRNG fix unblocked — learns real structure from real data through
    the full record-shard fit() path: >=25% held-out top-1 (2.5x chance) at a
    tiny budget (~110 s measured on the 1-core box — the suite stays under
    its 15-min budget). Measured 41.2% at these exact settings while writing
    the test; the committed 300-step quarter-width run is DIGITS_RUN.json's
    'xception_adam' entry at 86.1%."""
    from tensorflowdistributedlearning_tpu.data.digits import (
        short_budget_train_config,
    )

    result = _fit_digits(
        tmp_path,
        _xception_cfg(),
        short_budget_train_config(150, n_devices=1),
        steps=150,
        # 4x upscale: the stride-32 Xception trunk needs >=32px inputs
        upscale=4,
    )
    assert result.final_metrics["metrics/top1"] >= 0.25, result.final_metrics


def test_train_digits_driver_help():
    """The example driver exists and its CLI parses (full runs are covered by
    the in-process test above; the driver itself is exercised in-session)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_digits.py"),
         "--help"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    assert "--model-dir" in proc.stdout


@pytest.mark.slow  # real training run (minutes on the 1-core box); run_suite covers it
def test_digits_xception_pipelined_learns(tmp_path):
    """GPipe-BN learns for the conv family (VERDICT r4 #4): the SAME
    Xception config as the plain test above, split into 2 pipeline stages
    (middle flow as GPipe stages, BN stats assembled from microbatch-averaged
    updates), still learns real structure from real data — >=25% held-out
    top-1 (2.5x chance) at the tiny budget. The committed full-budget
    comparison is DIGITS_RUN.json's 'xception_pp2' entry beside the plain
    'xception_adam' 86.1%; this golden pins the LEARNING claim, which
    one-step parity under identical microbatches cannot
    (tests/test_pipeline_xception.py)."""
    from tensorflowdistributedlearning_tpu.data.digits import (
        short_budget_train_config,
    )

    # 2 devices: both become pipeline stages (dp=1) — the minimal real GPipe
    # mesh; the committed example run used 8 (2 stages x 4-way dp)
    train_cfg = short_budget_train_config(
        150, n_devices=2, pipeline_parallel=2
    )
    result = _fit_digits(
        tmp_path, _xception_cfg(), train_cfg, steps=150, upscale=4
    )
    assert result.final_metrics["metrics/top1"] >= 0.25, result.final_metrics
