"""Supervisor-level contract of bench.py's one JSON line.

The committed artifact's TOP-LEVEL metric/value/vs_baseline must be a TPU
truth whenever any TPU measurement has ever landed: fresh when the tunnel
answers, explicitly ``stale: true`` (with its ``measured_at``) when it does
not, with the CPU child demoted to a ``fallback_probe`` liveness section.
(Round 4's artifact led with a 30 img/s CPU number and vs_baseline=0.084
from a dead tunnel; these tests pin the fix.)

No jax, no children: ``_run_child`` / ``_load_tpu_cache`` are monkeypatched
and ``main()``'s stdout line is parsed directly.
"""

import json

import bench


FAKE_TPU_CACHE = {
    "metric": "resnet50_imagenet_train_throughput_per_chip",
    "value": 2281.16,
    "unit": "images/sec/chip",
    "vs_baseline": 6.337,
    "platform": "tpu",
    "device_kind": "TPU v5e",
    "mfu": 0.331,
    "measured_at": "2026-07-31 03:58:12 UTC",
    "measured_at_unix": 1785470292,
}

FAKE_CPU_PROBE = {
    "metric": "resnet_tiny_cpu_train_throughput_per_chip",
    "value": 30.29,
    "unit": "images/sec/chip",
    "vs_baseline": 0.084,
    "platform": "cpu",
}


def _run_main(monkeypatch, capsys, *, tpu_result, cpu_result, cache):
    calls = []

    def fake_run_child(platform, timeout):
        calls.append(platform)
        return tpu_result if platform == "tpu" else cpu_result

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    monkeypatch.setattr(bench, "_load_tpu_cache", lambda: cache)
    monkeypatch.setattr(bench, "_save_tpu_cache", lambda result: None)
    monkeypatch.setattr(bench, "TPU_ATTEMPTS", 1)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(out), calls


def test_tunnel_down_with_cache_leads_with_stale_tpu(monkeypatch, capsys):
    result, calls = _run_main(
        monkeypatch,
        capsys,
        tpu_result={"__error__": "tpu child timed out after 700s"},
        cpu_result=dict(FAKE_CPU_PROBE),
        cache=dict(FAKE_TPU_CACHE),
    )
    # headline IS the cached TPU record, clearly stamped stale
    assert result["value"] == FAKE_TPU_CACHE["value"]
    assert result["vs_baseline"] == FAKE_TPU_CACHE["vs_baseline"]
    assert result["platform"] == "tpu"
    assert result["stale"] is True
    assert result["degraded"] is True
    assert result["measured_at"] == FAKE_TPU_CACHE["measured_at"]
    assert "TPU unavailable" in result["error"]
    # the CPU number is present but DEMOTED
    assert result["fallback_probe"]["value"] == FAKE_CPU_PROBE["value"]
    assert result["fallback_probe"]["platform"] == "cpu"
    assert calls == ["tpu", "cpu"]


def test_tunnel_down_no_cache_promotes_cpu_probe(monkeypatch, capsys):
    result, _ = _run_main(
        monkeypatch,
        capsys,
        tpu_result={"__error__": "tpu child timed out after 700s"},
        cpu_result=dict(FAKE_CPU_PROBE),
        cache=None,
    )
    assert result["platform"] == "cpu"
    assert result["degraded"] is True
    assert "TPU unavailable" in result["error"]


def test_everything_dead_still_emits_valid_json(monkeypatch, capsys):
    result, _ = _run_main(
        monkeypatch,
        capsys,
        tpu_result={"__error__": "tpu child timed out after 700s"},
        cpu_result={"__error__": "cpu child rc=1"},
        cache=None,
    )
    assert result["value"] == 0.0
    assert "error" in result


def test_fresh_tpu_run_is_the_headline_unchanged(monkeypatch, capsys):
    fresh = dict(FAKE_TPU_CACHE)
    fresh.pop("measured_at")
    fresh.pop("measured_at_unix")
    result, calls = _run_main(
        monkeypatch,
        capsys,
        tpu_result=fresh,
        cpu_result=dict(FAKE_CPU_PROBE),
        cache=dict(FAKE_TPU_CACHE),
    )
    assert result["value"] == fresh["value"]
    assert "stale" not in result
    assert "fallback_probe" not in result
    assert calls == ["tpu"]  # no CPU child when the TPU answered


def test_stale_headline_survives_dead_cpu_probe(monkeypatch, capsys):
    result, _ = _run_main(
        monkeypatch,
        capsys,
        tpu_result={"__error__": "tpu child timed out after 700s"},
        cpu_result={"__error__": "cpu child rc=1"},
        cache=dict(FAKE_TPU_CACHE),
    )
    assert result["value"] == FAKE_TPU_CACHE["value"]
    assert result["stale"] is True
    assert "fallback_probe" not in result
