"""Gradient accumulation (``TrainConfig.grad_accum_steps``) and global-norm
gradient clipping (``TrainConfig.grad_clip_norm``).

Accumulation is a TPU-first capability the reference never had (its global
batch was bounded by what 2 GPUs held, model.py:156-159): the step splits each
shard's batch into microbatches under ``lax.scan`` and applies ONE optimizer
update on their mean gradient, so effective batch = accum x fed batch at one
microbatch's activation memory. For a BN-free model this is EXACT: the mean of
equal-size microbatch gradients equals the full-batch gradient, so the updated
parameters must match the accum=1 step bitwise-closely.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import synthetic_batches
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import make_mesh, replicate, shard_batch
from tensorflowdistributedlearning_tpu.train import (
    ClassificationTask,
    create_train_state,
    make_optimizer,
    make_train_step,
)
from tensorflowdistributedlearning_tpu.train.step import compute_metrics

TINY_VIT = ModelConfig(
    backbone="vit",
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    patch_size=4,
    embed_dim=32,
    vit_layers=2,
    num_heads=4,
    output_stride=None,
)
TINY_RESNET = ModelConfig(
    n_blocks=(1, 1, 1),
    input_shape=(16, 16),
    input_channels=3,
    num_classes=4,
    base_depth=8,
    width_multiplier=0.0625,
    output_stride=None,
)


def _state(cfg, tcfg, mesh):
    model = build_model(cfg)
    tx = make_optimizer(tcfg)
    shape = (1,) + cfg.input_shape + (cfg.input_channels,)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.ones(shape, jnp.float32)
    )
    return replicate(state, mesh)


def _cls_batch(n, shape=(16, 16), seed=0):
    return next(
        synthetic_batches(
            "classification",
            n,
            seed=seed,
            input_shape=shape,
            channels=3,
            num_classes=4,
        )
    )


def test_accum_matches_full_batch_exactly_bn_free():
    """ViT (no BN): accum=4 over the same 32 examples == one full-batch update."""
    mesh = make_mesh(8)
    task = ClassificationTask()
    tcfg = TrainConfig(optimizer="sgd", lr=0.01, weight_decay=1e-4)
    batch = shard_batch(_cls_batch(32), mesh)

    plain = make_train_step(mesh, task, donate=False)
    accum = make_train_step(mesh, task, donate=False, accum=4)

    s1, m1 = plain(_state(TINY_VIT, tcfg, mesh), batch)
    s2, m2 = accum(_state(TINY_VIT, tcfg, mesh), batch)

    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)
    # the metric streams saw the same examples (chunked vs whole)
    assert compute_metrics(m1)["loss"] == pytest.approx(
        compute_metrics(m2)["loss"], abs=1e-5
    )
    assert int(s2.step) == 1  # one UPDATE, not accum steps


def test_accum_trains_bn_model():
    """ResNet with BN: microbatch-sequential statistics train fine (loss falls,
    stats move off their init)."""
    mesh = make_mesh(8)
    task = ClassificationTask()
    tcfg = TrainConfig(lr=0.01)
    state = _state(TINY_RESNET, tcfg, mesh)
    init_stats = jax.tree.map(np.asarray, state.batch_stats)
    step = make_train_step(mesh, task, accum=2)
    losses = []
    for i in range(10):
        batch = shard_batch(_cls_batch(32, seed=i), mesh)
        state, metrics = step(state, batch)
        losses.append(compute_metrics(metrics)["loss"])
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
    moved = jax.tree.map(
        lambda a, b: not np.allclose(a, np.asarray(b)), init_stats, state.batch_stats
    )
    assert any(jax.tree.leaves(moved))


def test_accum_requires_divisible_batch():
    mesh = make_mesh(8)
    step = make_train_step(mesh, ClassificationTask(), donate=False, accum=3)
    state = _state(TINY_VIT, TrainConfig(), mesh)
    batch = shard_batch(_cls_batch(32), mesh)  # 4 per shard, not divisible by 3
    with pytest.raises(ValueError, match="divisible"):
        step(state, batch)


def test_grad_clip_bounds_first_sgd_update():
    """Nesterov SGD's first update is lr*(1+momentum)*g, so with a tiny clip
    the update norm must land exactly at lr*(1+momentum)*clip."""
    mesh = make_mesh(8)
    task = ClassificationTask()
    batch = shard_batch(_cls_batch(32), mesh)
    lr, clip, momentum = 0.1, 1e-3, 0.9

    def delta_norm(tcfg):
        state0 = _state(TINY_VIT, tcfg, mesh)
        state1, _ = make_train_step(mesh, task, donate=False)(state0, batch)
        sq = sum(
            float(jnp.sum((a - b) ** 2))
            for a, b in zip(
                jax.tree.leaves(state0.params), jax.tree.leaves(state1.params)
            )
        )
        return float(np.sqrt(sq))

    unclipped = delta_norm(TrainConfig(optimizer="sgd", lr=lr))
    clipped = delta_norm(TrainConfig(optimizer="sgd", lr=lr, grad_clip_norm=clip))
    bound = lr * (1.0 + momentum) * clip
    assert unclipped > bound * 1.5  # the gradient genuinely exceeds the clip
    assert clipped == pytest.approx(bound, rel=1e-4)


def test_config_validation():
    with pytest.raises(ValueError, match="grad_accum_steps"):
        TrainConfig(grad_accum_steps=0)
    with pytest.raises(ValueError, match="grad_clip_norm"):
        TrainConfig(grad_clip_norm=-1.0)
    with pytest.raises(ValueError, match="grad_accum_steps"):
        TrainConfig(grad_accum_steps=2, model_parallel=2)
    with pytest.raises(ValueError, match="grad_accum_steps"):
        TrainConfig(grad_accum_steps=2, pipeline_parallel=2)
    # spatial parallelism composes with accumulation (same shard_map step)
    TrainConfig(grad_accum_steps=2, sequence_parallel=2)


def test_fit_end_to_end_with_accum(tmp_path):
    """ClassifierTrainer.fit() trains, checkpoints, and evaluates through the
    accumulation path (TrainConfig.grad_accum_steps wired at the call site)."""
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    model_cfg = ModelConfig(
        num_classes=3,
        input_shape=(8, 8),
        input_channels=1,
        n_blocks=(1, 1, 1),
        block_type="basic_block",
        width_multiplier=0.25,
        output_stride=None,
    )
    train_cfg = TrainConfig(
        optimizer="sgd",
        lr=0.05,
        grad_accum_steps=2,
        grad_clip_norm=1.0,
        checkpoint_every_steps=2,
        n_devices=1,
    )
    trainer = ClassifierTrainer(str(tmp_path / "run"), None, model_cfg, train_cfg)
    result = trainer.fit(batch_size=8, steps=3, eval_every_steps=3)
    assert result.steps == 3
    assert np.isfinite(result.final_metrics["loss"])
    # the step counter counts UPDATES, not microbatches
    template = trainer._host_template()
    ckpt = trainer._checkpointer()
    try:
        latest = ckpt.restore_latest(template)
    finally:
        ckpt.close()
    assert int(jax.device_get(latest.step)) == 3
