"""Determinism regression (SURVEY §5.2's plan; VERDICT r1 #6): a fixed PRNG seed
must give a bitwise-stable loss sequence across two runs in one process — the SPMD
replacement for the race-freedom guarantees the reference got from synchronous
in-graph replication — plus a golden-value assertion to catch silent numerics
drift in the model/loss/augmentation stack."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import augment as augment_lib
from tensorflowdistributedlearning_tpu.data.synthetic import (
    synthetic_segmentation_batch,
)
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.state import create_train_state

STEPS = 3


def _run_losses(seed: int) -> list:
    """The trainer's full per-step recipe (on-device augmentation keyed by
    fold_in(seed, step) -> SPMD train step) on tiny shapes, returning the float32
    loss value of every step."""
    cfg = ModelConfig(
        input_shape=(16, 16), n_blocks=(1, 1, 1), base_depth=8, width_multiplier=0.0625
    )
    tcfg = TrainConfig(seed=seed)
    mesh = mesh_lib.make_mesh(8)
    model = build_model(cfg)
    state = mesh_lib.replicate(
        create_train_state(
            model,
            step_lib.make_optimizer(tcfg),
            jax.random.PRNGKey(seed),
            np.zeros((1, 16, 16, 2), np.float32),
        ),
        mesh,
    )
    train_step = step_lib.make_train_step(
        mesh, step_lib.SegmentationTask(), donate=False
    )
    acfg = augment_lib.AugmentConfig(crop_probability=0.0)

    @jax.jit
    def prepare(step, batch):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return augment_lib.augment_batch(key, batch["images"], batch["masks"], acfg)

    rng = np.random.default_rng(seed)
    losses = []
    for step_no in range(STEPS):
        # single-channel source images: augment_batch appends the Laplacian
        # channel to reach the model's input_channels=2
        raw = synthetic_segmentation_batch(rng, 8, input_shape=(16, 16), channels=1)
        batch = {"images": raw["images"], "masks": raw["labels"]}
        batch = prepare(jnp.asarray(step_no), mesh_lib.shard_batch(batch, mesh))
        state, metrics = train_step(state, batch)
        losses.append(float(step_lib.compute_metrics(jax.device_get(metrics))["loss"]))
    return losses


@pytest.fixture(scope="module")
def runs():
    """The minimum set of runs every assertion below needs: seed 0 twice (bitwise
    stability) and seed 1 once (seed sensitivity). Shared at module scope — each
    run pays a full train-step compile."""
    return _run_losses(0), _run_losses(0), _run_losses(1)


def test_fixed_seed_bitwise_stable_losses(runs):
    a, b, _ = runs
    assert a == b  # exact float equality, not approx


def test_different_seed_differs(runs):
    a, _, c = runs
    assert a != c


def test_golden_loss_after_k_steps(runs):
    """Golden regression: catches silent numerics drift (model structure, loss,
    augmentation, optimizer). Recorded on the 8-device CPU mesh; loosen only with
    an understood numerics change."""
    losses, *_ = runs
    golden = GOLDEN_LOSSES
    assert losses == pytest.approx(golden, rel=1e-4), (
        f"loss sequence drifted: {losses} != golden {golden}"
    )


# Recorded 2026-07-30, jax 0.9.0, 8-device CPU mesh, width_multiplier=1/16 fixture
# (re-recorded when the fixture architecture gained width_multiplier)
GOLDEN_LOSSES = [1.5637928247451782, 1.5359129905700684, 1.3671655654907227]
