"""Async host loop (train/async_loop.py) and its satellites.

The overlap layer must be a pure latency optimization: dispatch-ahead plus
deferred window fetch may change WHEN host work happens, never WHAT the run
computes. The pins here:

- sync (``dispatch_ahead_steps=0``) vs async fit() runs produce bit-identical
  final params and identical ledger scalar values (modulo event ordering);
- an eval pass performs exactly ONE host transfer of metrics regardless of
  batch count (device-resident accumulation), counted with a device_get spy;
- a preemption mid-window flushes the deferred window to the ledger BEFORE the
  preemption checkpoint/events, so resilience reporting stays complete;
- the host-side lr schedule mirror matches the optax schedules it replaces;
- ``device_prefetch`` releases its producer thread when the consumer abandons
  iteration early (or never iterates at all), and records its queue depth so
  underruns reach ``telemetry-report``.
"""

import gc
import itertools
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
from tensorflowdistributedlearning_tpu.obs.telemetry import (
    PREFETCH_DEPTH_HISTOGRAM,
    SPAN_FETCH_WAIT,
    Telemetry,
)
from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib
from tensorflowdistributedlearning_tpu.resilience import preempt
from tensorflowdistributedlearning_tpu.train import async_loop
from tensorflowdistributedlearning_tpu.train import step as step_lib
from tensorflowdistributedlearning_tpu.train.checkpoint import CheckpointManager
from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

TINY = dict(
    num_classes=4,
    input_shape=(16, 16),
    input_channels=3,
    n_blocks=(1, 1, 1),
    base_depth=8,
    width_multiplier=0.125,
    output_stride=None,
)


def _tiny_tcfg(dispatch_ahead: int) -> TrainConfig:
    return TrainConfig(
        seed=7,
        train_log_every_steps=2,
        checkpoint_every_steps=4,
        eval_every_steps=4,
        dispatch_ahead_steps=dispatch_ahead,
    )


# -- HostOverlap unit behavior -------------------------------------------------


def _mean(v: float) -> metrics_lib.Mean:
    return metrics_lib.Mean(
        total=jnp.asarray(v, jnp.float32), count=jnp.asarray(1.0, jnp.float32)
    )


def _window(step: int, value: float) -> async_loop.PendingWindow:
    return async_loop.PendingWindow(
        step=step, metrics={"loss": _mean(value)}, steps=2, lr=0.1
    )


def test_sync_mode_emits_in_place(tmp_path):
    tel = Telemetry(str(tmp_path), run_info={})
    emitted = []
    overlap = async_loop.HostOverlap(
        tel, dispatch_ahead=0, emit=lambda rec, scalars: emitted.append((rec.step, scalars))
    )
    assert not overlap.async_mode
    overlap.track({"loss": _mean(1.0)})  # no-op in sync mode
    overlap.window(_window(2, 3.0))
    assert [s for s, _ in emitted] == [2]
    assert emitted[0][1]["loss"] == pytest.approx(3.0)
    overlap.flush()  # nothing pending
    assert len(emitted) == 1
    tel.close()


def test_async_mode_defers_one_window_and_flushes(tmp_path):
    tel = Telemetry(str(tmp_path), run_info={})
    emitted = []
    overlap = async_loop.HostOverlap(
        tel, dispatch_ahead=2, emit=lambda rec, scalars: emitted.append((rec.step, scalars))
    )
    overlap.window(_window(2, 1.0))
    assert emitted == []  # deferred
    overlap.window(_window(4, 2.0))
    assert [s for s, _ in emitted] == [2]  # boundary N emits window N-1
    overlap.flush()
    assert [s for s, _ in emitted] == [2, 4]
    overlap.flush()  # idempotent
    assert len(emitted) == 2
    assert emitted[0][1]["loss"] == pytest.approx(1.0)
    assert emitted[1][1]["loss"] == pytest.approx(2.0)
    tel.close()


def test_dispatch_ahead_budget_blocks_and_records_fetch_wait(tmp_path):
    tel = Telemetry(str(tmp_path), run_info={})
    overlap = async_loop.HostOverlap(tel, dispatch_ahead=2, emit=lambda *_: None)
    for i in range(5):
        overlap.track({"loss": _mean(float(i))})
    waits = tel.drain_window_samples()[SPAN_FETCH_WAIT]
    # 5 tracked steps against a budget of 2: three blocking retirements
    assert len(waits) == 3
    tel.close()


def test_eval_budget_bounds_inflight_even_in_sync_mode(tmp_path):
    tel = Telemetry(str(tmp_path), run_info={})
    # sync mode (dispatch_ahead 0) still bounds eval to 1 in flight — the
    # legacy per-batch device_get throttled eval as a side effect, and
    # device-resident accumulation must not unbound it
    assert async_loop.eval_budget(tel, 0).budget == 1
    # the train-loop tracker records its blocking as fetch_wait samples...
    budget = async_loop.DispatchBudget(tel, 4)
    for i in range(6):
        budget.track({"loss": _mean(float(i))})
    assert len(tel.drain_window_samples()[SPAN_FETCH_WAIT]) == 2
    # ...the EVAL budget does NOT: its waits happen inside the eval span
    # (already counted as eval time) and a fetch_wait sample would drain into
    # the NEXT train window, double-counting eval in the goodput split
    ebudget = async_loop.eval_budget(tel, 4)
    assert ebudget.budget == 4
    for i in range(6):
        ebudget.track({"loss": _mean(float(i))})
    assert tel.drain_window_samples()[SPAN_FETCH_WAIT] == []
    tel.close()


# -- device-resident eval accumulation ----------------------------------------


def test_merge_metrics_device_matches_host_merge():
    a = {"loss": _mean(1.0), "metrics/top1": _mean(0.5)}
    b = {"loss": _mean(3.0), "metrics/top1": _mean(1.0)}
    acc = async_loop.merge_metrics_device(None, a)
    acc = async_loop.merge_metrics_device(acc, b)
    host = step_lib.merge_metrics(jax.device_get(a), jax.device_get(b))
    assert step_lib.compute_metrics(jax.device_get(acc)) == pytest.approx(
        step_lib.compute_metrics(host)
    )


def test_merge_metrics_device_rejects_non_mean_leaf():
    with pytest.raises(TypeError, match="not a .*Mean"):
        async_loop.merge_metrics_device(None, {"loss": jnp.zeros(())})


def test_fetch_metrics_counts_the_single_transfer(tmp_path):
    tel = Telemetry(str(tmp_path), run_info={})
    acc = async_loop.merge_metrics_device(None, {"loss": _mean(2.0)})
    out = async_loop.fetch_metrics(acc, telemetry=tel)
    assert out["loss"] == pytest.approx(2.0)
    assert tel.registry.counter(async_loop.EVAL_FETCH_COUNTER).value == 1
    with pytest.raises(ValueError, match="no eval batches"):
        async_loop.fetch_metrics(None)
    tel.close()


# -- host-side lr schedule mirror ---------------------------------------------


@pytest.mark.parametrize(
    "cfg",
    [
        TrainConfig(lr=0.01, lr_schedule="exponential", lr_decay_steps=100, lr_decay_rate=0.5),
        TrainConfig(lr=0.02, lr_schedule="cosine", lr_warmup_steps=0, lr_decay_steps=200),
        TrainConfig(lr=0.03, lr_schedule="cosine", lr_warmup_steps=10, lr_decay_steps=200),
    ],
    ids=["exponential", "cosine", "cosine_warmup"],
)
def test_host_lr_schedule_matches_optax(cfg):
    device = step_lib.make_lr_schedule(cfg)
    host = step_lib.make_host_lr_schedule(cfg)
    for step in [0, 1, 5, 9, 10, 11, 50, 150, 199, 200, 500]:
        # the optax schedules evaluate in float32; the host mirror in float64 —
        # float32-level agreement is the contract (this is the logging path)
        assert host(step) == pytest.approx(float(device(step)), rel=1e-3, abs=1e-8)


# -- device_prefetch shutdown + depth gauge -----------------------------------


def _spawn_prefetch(**kwargs):
    before = set(threading.enumerate())
    gen = pipeline_lib.device_prefetch(**kwargs)
    (thread,) = [
        t
        for t in threading.enumerate()
        if t not in before and t.name == "device_prefetch"
    ]
    return gen, thread


def test_device_prefetch_rejects_bad_depth_eagerly():
    with pytest.raises(ValueError, match="depth"):
        pipeline_lib.device_prefetch(iter([1]), place=lambda b: b, depth=0)


def test_device_prefetch_abandon_mid_stream_releases_producer():
    gen, thread = _spawn_prefetch(
        iterator=itertools.count(), place=lambda b: b, depth=2
    )
    assert next(gen) == 0
    # the producer is now blocked on a full queue of an infinite stream; an
    # abandoning consumer (preemption raise mid-epoch) must still release it
    gen.close()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_device_prefetch_dropped_unused_releases_producer():
    gen, thread = _spawn_prefetch(
        iterator=itertools.count(), place=lambda b: b, depth=1
    )
    del gen  # never iterated: the generator finalizer must signal stop
    gc.collect()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_device_prefetch_records_queue_depth():
    from tensorflowdistributedlearning_tpu.obs.metrics import MetricsRegistry

    registry = MetricsRegistry()
    out = list(
        pipeline_lib.device_prefetch(
            iter(range(6)), place=lambda b: b, depth=2, registry=registry
        )
    )
    assert out == list(range(6))
    depths = registry.histogram(PREFETCH_DEPTH_HISTOGRAM).drain()
    assert len(depths) == 6
    assert all(0 <= d <= 2 for d in depths)


# -- config / CLI knobs --------------------------------------------------------


def test_config_validates_overlap_knobs():
    with pytest.raises(ValueError, match="prefetch_depth"):
        TrainConfig(prefetch_depth=0)
    with pytest.raises(ValueError, match="dispatch_ahead_steps"):
        TrainConfig(dispatch_ahead_steps=-1)
    assert TrainConfig(dispatch_ahead_steps=0).dispatch_ahead_steps == 0


def test_cli_exposes_overlap_flags():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    parser = build_parser()
    args = parser.parse_args(
        ["train", "--model-dir", "/tmp/m", "--data-dir", "/tmp/d",
         "--prefetch-depth", "4", "--dispatch-ahead", "0"]
    )
    assert args.prefetch_depth == 4 and args.dispatch_ahead == 0
    args = parser.parse_args(
        ["fit", "--preset", "cifar10_smoke", "--model-dir", "/tmp/m"]
    )
    assert args.prefetch_depth is None and args.dispatch_ahead is None


# -- e2e: sync vs async parity on the 8-device mesh ---------------------------


def _run_fit(model_dir: str, dispatch_ahead: int, monkeypatch_ctx):
    """One synthetic fit() run; returns the params of the FINAL checkpoint
    save, captured bitwise via a CheckpointManager.save spy."""
    captured = {}
    orig_save = CheckpointManager.save

    def spy(self, state, *, force=False):
        captured["params"] = jax.device_get(state.params)
        return orig_save(self, state, force=force)

    with monkeypatch_ctx() as m:
        m.setattr(CheckpointManager, "save", spy)
        trainer = ClassifierTrainer(
            model_dir, None, ModelConfig(**TINY), _tiny_tcfg(dispatch_ahead)
        )
        result = trainer.fit(batch_size=8, steps=8)
    return result, captured["params"]


@pytest.fixture(scope="module")
def parity_runs(tmp_path_factory):
    from _pytest.monkeypatch import MonkeyPatch

    def ctx():
        return MonkeyPatch.context()

    sync_dir = str(tmp_path_factory.mktemp("fit_sync"))
    async_dir = str(tmp_path_factory.mktemp("fit_async"))
    sync_res, sync_params = _run_fit(sync_dir, 0, ctx)
    async_res, async_params = _run_fit(async_dir, 2, ctx)
    return {
        "sync": (sync_dir, sync_res, sync_params),
        "async": (async_dir, async_res, async_params),
    }


def test_async_final_params_bit_identical(parity_runs):
    _, _, sync_params = parity_runs["sync"]
    _, _, async_params = parity_runs["async"]
    s_leaves = jax.tree.leaves(sync_params)
    a_leaves = jax.tree.leaves(async_params)
    assert len(s_leaves) == len(a_leaves) > 0
    for s, a in zip(s_leaves, a_leaves):
        np.testing.assert_array_equal(np.asarray(s), np.asarray(a))


def _window_scalars(workdir: str):
    out = {}
    for e in obs.read_ledger(workdir):
        if e["event"] != "step_window":
            continue
        scalars = dict(e.get("scalars", {}))
        # wall-clock throughput is the one legitimately timing-dependent scalar
        scalars.pop("throughput/images_per_sec", None)
        out[e["step"]] = scalars
    return out


def test_async_ledger_scalars_identical(parity_runs):
    sync_dir, _, _ = parity_runs["sync"]
    async_dir, _, _ = parity_runs["async"]
    sync_w, async_w = _window_scalars(sync_dir), _window_scalars(async_dir)
    assert set(sync_w) == set(async_w) == {2, 4, 6, 8}
    for step in sync_w:
        assert sync_w[step] == async_w[step], f"window scalars differ @ {step}"


def test_async_eval_metrics_identical(parity_runs):
    def evals(workdir):
        return {
            e["step"]: e["metrics"]
            for e in obs.read_ledger(workdir)
            if e["event"] == "eval"
        }

    sync_e = evals(parity_runs["sync"][0])
    async_e = evals(parity_runs["async"][0])
    assert set(sync_e) == set(async_e) and sync_e
    for step in sync_e:
        assert sync_e[step] == async_e[step]


def test_async_windows_carry_overlap_telemetry(parity_runs):
    async_dir, _, _ = parity_runs["async"]
    windows = [
        e for e in obs.read_ledger(async_dir) if e["event"] == "step_window"
    ]
    assert windows
    for w in windows:
        assert "fetch_wait_s" in w
        # the prefetch gauge rides the window events (trainers pass their
        # registry into device_prefetch)
        assert "prefetch_queue_depth" in w
        assert w["prefetch_queue_depth"]["min"] >= 0


def test_eval_pass_single_host_transfer(tmp_path, monkeypatch):
    """The acceptance pin: one host transfer per eval pass regardless of
    batch count, asserted with a jax.device_get call counter scoped to
    ``_eval_pass`` (the jitted per-batch merges must not transfer)."""
    transfer_counts, batch_counts = [], []
    orig_pass = ClassifierTrainer._eval_pass

    def spy(self, state, batches, step_no=None):
        seen = [0]

        def counting_batches():
            for b in batches:
                seen[0] += 1
                yield b

        real_get = jax.device_get
        calls = [0]

        def counting_get(x):
            calls[0] += 1
            return real_get(x)

        jax.device_get = counting_get
        try:
            result = orig_pass(self, state, counting_batches(), step_no)
        finally:
            jax.device_get = real_get
        transfer_counts.append(calls[0])
        batch_counts.append(seen[0])
        return result

    monkeypatch.setattr(ClassifierTrainer, "_eval_pass", spy)
    trainer = ClassifierTrainer(
        str(tmp_path), None, ModelConfig(**TINY), _tiny_tcfg(2)
    )
    trainer.fit(batch_size=8, steps=4)
    assert transfer_counts and all(n == 1 for n in transfer_counts)
    # the synthetic eval split streams 4 batches — the single transfer above
    # really amortized a multi-batch pass
    assert all(n == 4 for n in batch_counts)


def test_preemption_mid_window_flushes_deferred_window(tmp_path, monkeypatch):
    """A preemption landing while a window is deferred must flush it to the
    ledger BEFORE the preemption checkpoint/events (resilience reporting
    depends on ledger completeness at that boundary)."""
    steps_seen = [0]

    def fake_requested():
        # True at the step AFTER the first log window (log_every=2): window@2
        # is deferred in async mode when the preemption lands at step 3
        return steps_seen[0] >= 3

    def fake_fire(site, step=None, **kw):
        if site == "step":
            steps_seen[0] = step

    from tensorflowdistributedlearning_tpu.resilience import faults

    monkeypatch.setattr(faults, "fire", fake_fire)
    monkeypatch.setattr(preempt, "requested", fake_requested)
    monkeypatch.setattr(preempt, "reason", lambda: "test:forced")
    trainer = ClassifierTrainer(
        str(tmp_path), None, ModelConfig(**TINY), _tiny_tcfg(2)
    )
    with pytest.raises(preempt.PreemptedError):
        trainer.fit(batch_size=8, steps=8)
    events = obs.read_ledger(str(tmp_path))
    kinds = [e["event"] for e in events]
    assert "preempted" in kinds
    window_steps = [e["step"] for e in events if e["event"] == "step_window"]
    assert window_steps == [2]
    # ordering: the flushed window precedes the preemption checkpoint + event
    assert kinds.index("step_window") < kinds.index("checkpoint")
    assert kinds.index("checkpoint") < kinds.index("preempted")


# -- telemetry-report surfacing ------------------------------------------------


def test_report_surfaces_fetch_wait_and_prefetch(parity_runs):
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    async_dir, _, _ = parity_runs["async"]
    report = build_report(async_dir)
    ts = report["time_split"]
    assert "fetch_wait_s" in ts and "fetch_wait_frac" in ts
    assert report["prefetch"]["windows"] == 4
    assert report["prefetch"]["min_queue_depth"] >= 0
    rendered = render_report(report)
    assert "input prefetch" in rendered


def test_report_flags_prefetch_underruns(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )
    from tensorflowdistributedlearning_tpu.obs.ledger import LEDGER_FILENAME

    events = [
        {"event": "run_header", "t": 0.0, "run": {}},
        {
            "event": "step_window", "t": 1.0, "step": 2, "steps": 2,
            "data_wait_s": 0.4, "compute_s": 0.5, "fetch_wait_s": 0.1,
            "data_wait_frac": 0.4, "dirty": False,
            "prefetch_queue_depth": {"mean": 0.5, "min": 0},
        },
        {
            "event": "step_window", "t": 2.0, "step": 4, "steps": 2,
            "data_wait_s": 0.1, "compute_s": 0.8, "fetch_wait_s": 0.0,
            "data_wait_frac": 0.1, "dirty": False,
            "prefetch_queue_depth": {"mean": 1.8, "min": 1},
        },
        {"event": "run_end", "t": 3.0},
    ]
    with open(os.path.join(str(tmp_path), LEDGER_FILENAME), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    report = build_report(str(tmp_path))
    assert report["prefetch"]["underrun_windows"] == 1
    assert report["prefetch"]["min_queue_depth"] == 0
    assert report["time_split"]["fetch_wait_s"] == pytest.approx(0.1)
    assert "underran" in render_report(report)
