import jax
import numpy as np
import pytest


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, "tests expect the 8-device CPU override from root conftest"
    return devices[:8]


@pytest.fixture
def rng():
    return np.random.default_rng(42)
