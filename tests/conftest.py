import jax
import numpy as np
import pytest


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, "tests expect the 8-device CPU override from root conftest"
    return devices[:8]


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def make_salt_dataset(root, n_images=16, n_test=6, shape=(32, 32), seed=0):
    """Write a tiny TGS-salt-layout dataset: ``{root}/data/images+masks`` and
    ``{root}/test/images`` (uint8 PNGs; every third mask empty — the
    stratification edge case). Shared by the trainer end-to-end suites."""
    import os

    from PIL import Image

    root = str(root)
    data, test = os.path.join(root, "data"), os.path.join(root, "test")
    os.makedirs(os.path.join(data, "images"), exist_ok=True)
    os.makedirs(os.path.join(data, "masks"), exist_ok=True)
    os.makedirs(os.path.join(test, "images"), exist_ok=True)
    rng = np.random.default_rng(seed)
    ids = [f"im{i:02d}" for i in range(n_images)]
    for i, id_ in enumerate(ids):
        img = rng.uniform(0, 255, shape).astype(np.uint8)
        Image.fromarray(img).save(os.path.join(data, "images", f"{id_}.png"))
        mask = (
            np.zeros(shape)
            if i % 3 == 0
            else (rng.uniform(0, 1, shape) > 0.5) * 255
        ).astype(np.uint8)
        Image.fromarray(mask).save(os.path.join(data, "masks", f"{id_}.png"))
    for i in range(n_test):
        img = rng.uniform(0, 255, shape).astype(np.uint8)
        Image.fromarray(img).save(os.path.join(test, "images", f"t{i}.png"))
    return data, test, ids
