"""Promotion controller: canary, shadow traffic, rollback — and satellites.

Unit layers (tier-1 fast): the drain-wins-over-reaper fleet fix, per-replica
artifact overrides, shadow duplication through REAL in-process servers (the
canary never answers a client), per-replica artifact identity polling with
the mixed-fleet aggregate, the controller's phase machine against fake
manager/router doubles (admission refusal, empty-shadow-window hold,
accuracy/latency/crash-loop rollback, the incumbent-deleted structured
abort), the deployment-history report rendering, and the telemetry-top
data-service row.

Subprocess end-to-end (slow-marked, run unfiltered by the focused ci.yml
step): the headline drill — a real 3-replica ``serve-fleet`` under
closed-loop load, ``promote`` CLI with ``sigkill@N`` on the canary
mid-rollout, zero client-visible errors, fleet converged on the candidate
fingerprint — and the rollback drill with a poisoned candidate.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.obs import Telemetry
from tensorflowdistributedlearning_tpu.serve import fleet as fleet_lib
from tensorflowdistributedlearning_tpu.serve import promote as promote_lib
from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine
from tensorflowdistributedlearning_tpu.serve.batcher import MicroBatcher
from tensorflowdistributedlearning_tpu.serve.promote import (
    PromoteConfig,
    PromotionController,
)
from tensorflowdistributedlearning_tpu.serve.router import (
    FleetRouter,
    ShadowStats,
    artifact_key,
)
from tensorflowdistributedlearning_tpu.serve.server import ServingServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEATURES = 6
CLASSES = 3


# -- fleet manager: per-replica artifacts + the drain/reaper race -------------


class _FakeProc:
    _next_pid = [1000]

    def __init__(self, argv):
        self.argv = argv
        self.pid = self._next_pid[0]
        self._next_pid[0] += 1
        self.rc = None
        self.signals = []
        self.stdout = []

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)


@pytest.fixture
def fake_manager(tmp_path, monkeypatch):
    """A FleetManager whose replica 'subprocesses' are in-memory fakes: the
    supervision/drain state machine runs for real, nothing forks."""
    spawned = []

    def fake_popen(argv, **kwargs):
        proc = _FakeProc(argv)
        spawned.append(proc)
        return proc

    monkeypatch.setattr(fleet_lib.subprocess, "Popen", fake_popen)
    manager = fleet_lib.FleetManager(
        fleet_lib.FleetConfig(
            artifact_dir="/incumbent", workdir=str(tmp_path / "wd"),
            backoff_base_s=0.01, backoff_max_s=0.02,
        )
    )
    return manager, spawned


def _argv_value(argv, flag):
    return argv[argv.index(flag) + 1] if flag in argv else None


def test_scale_up_artifact_override_persists_across_restart(fake_manager):
    """A canary spawned on a candidate artifact RESTARTS on it too — and the
    first-launch-only fault drill does not ride the restart."""
    manager, spawned = fake_manager
    rid = manager.scale_up(
        artifact_dir="/candidate", fault_spec="sigkill@5"
    )
    first = spawned[-1]
    assert _argv_value(first.argv, "--artifact-dir") == "/candidate"
    assert _argv_value(first.argv, "--inject-fault") == "sigkill@5"
    rep = manager.replicas()[0]
    assert rep.artifact_dir == "/candidate"

    first.rc = -signal.SIGKILL  # the drill fired
    manager.check()  # schedules the restart
    assert rep.state == fleet_lib.R_BACKOFF
    rep.restart_at = 0.0
    manager.check()  # executes it
    relaunch = spawned[-1]
    assert relaunch is not first
    assert _argv_value(relaunch.argv, "--artifact-dir") == "/candidate"
    assert "--inject-fault" not in relaunch.argv  # restarts are clean
    assert rep.restarts == 1


def test_default_spawn_uses_fleet_artifact(fake_manager):
    manager, spawned = fake_manager
    manager.scale_up()
    assert _argv_value(spawned[-1].argv, "--artifact-dir") == "/incumbent"


def test_drain_wins_over_pending_restart(fake_manager):
    """The satellite fix: a replica that died (restart scheduled) and is
    then drained must be forgotten — the monitor must NOT relaunch it."""
    manager, spawned = fake_manager
    rid = manager.scale_up()
    rep = manager.replicas()[0]
    rep.process.rc = 1  # crashed
    manager.check()
    assert rep.state == fleet_lib.R_BACKOFF
    n_spawns = len(spawned)

    assert manager.scale_down(rid) == rid  # drain decision on a dead replica
    assert manager.replicas() == []  # forgotten immediately
    rep.restart_at = 0.0
    manager.check()  # a due restart must not resurrect it
    assert manager.replicas() == []
    assert len(spawned) == n_spawns


def test_drain_request_survives_reaper_clobber(fake_manager):
    """The tighter race: scale_down marked the replica draining, but the
    monitor's sweep had already observed the death and moves it into the
    backoff path — drain_requested still wins, no relaunch."""
    manager, spawned = fake_manager
    rid = manager.scale_up()
    rep = manager.replicas()[0]
    assert manager.scale_down(rid) == rid
    assert rep.drain_requested
    assert signal.SIGTERM in rep.process.signals
    # simulate the reaper racing the drain: death observed, state clobbered
    # into the restart machinery
    rep.process.rc = -signal.SIGTERM
    rep.state = fleet_lib.R_BACKOFF
    rep.restart_at = 0.0
    n_spawns = len(spawned)
    manager.check()
    assert all(r.replica_id != rid for r in manager.replicas())
    assert len(spawned) == n_spawns  # never relaunched


def test_scale_down_default_prefers_live_over_backoff(fake_manager):
    manager, spawned = fake_manager
    manager.scale_up()
    manager.scale_up()
    reps = sorted(manager.replicas(), key=lambda r: r.replica_id)
    reps[1].process.rc = 1
    manager.check()  # replica 2 in backoff
    # default pick must drain the LIVE replica 1, not cancel 2's restart
    assert manager.scale_down() == reps[0].replica_id


# -- shadow traffic through real in-process servers ---------------------------


def _server(fn, *, replica_id, quantization=None, buckets=(1, 4)):
    engine = InferenceEngine(
        fn, (FEATURES,), buckets=buckets, quantization=quantization
    )
    engine.warmup()
    batcher = MicroBatcher(engine, max_wait_ms=1, max_queue=64)
    return ServingServer(
        engine, batcher, port=0, replica_id=replica_id, window_secs=0
    ).start()


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture(scope="module")
def paired_fns():
    """Primary and a deliberately-different canary model: the shadow compare
    must SEE disagreement (class flips + probability deltas)."""
    import jax
    import jax.numpy as jnp

    w1 = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.5
    w2 = jax.random.normal(jax.random.PRNGKey(9), (FEATURES, CLASSES)) * 0.5

    def make(w):
        @jax.jit
        def fn(x):
            return {
                "probabilities": jax.nn.softmax(x @ w, axis=-1),
                "class": jnp.argmax(x @ w, axis=-1),
            }

        return fn

    return make(w1), make(w2)


def test_shadow_duplicates_but_never_answers(paired_fns):
    primary_fn, canary_fn = paired_fns
    s1 = _server(primary_fn, replica_id=1)
    s2 = _server(canary_fn, replica_id=2)
    router = FleetRouter(
        [(1, s1.url), (2, s2.url)], port=0, window_secs=0,
        poll_interval_s=0.2,
    ).start()
    x = np.random.default_rng(3).normal(0, 1, (2, FEATURES)).astype(np.float32)
    try:
        router.start_shadow(2, fraction=1.0)
        # the shadow target is not a candidate: all traffic answers from 1
        assert [r.replica_id for r in router._candidates()] == [1]
        for _ in range(10):
            status, _ = _post(
                router.url + "/v1/predict", {"instances": x.tolist()}
            )
            assert status == 200
        snap = {r["replica"]: r for r in router.metrics_snapshot()["replicas"]}
        assert snap[1]["routed"] == 10
        assert snap[2]["routed"] == 0  # NEVER answered a client

        deadline = time.monotonic() + 30
        stats = {}
        while time.monotonic() < deadline:
            stats = router.shadow_snapshot() or {}
            if stats.get("compared", 0) >= 10:
                break
            time.sleep(0.1)
        assert stats["compared"] >= 10
        assert stats["selected"] >= stats["compared"]
        # genuinely different models must show up in the compare
        assert stats["max_abs_delta"] > 0.01
        assert stats.get("mean_disagree", 0) > 0.0
        lat = stats["latency_ms"]
        assert lat["primary_p99"] > 0 and lat["canary_p99"] > 0

        router.stop_shadow()
        router.poll_once()
        # disarmed: the canary is a normal candidate again
        assert 2 in [r.replica_id for r in router._candidates()]
    finally:
        router.shutdown()
        s1.shutdown()
        s2.shutdown()


def test_shadow_empty_window_has_no_math_errors():
    stats = ShadowStats()
    snap = stats.snapshot()
    assert snap["compared"] == 0
    assert "max_abs_delta" not in snap and "latency_ms" not in snap


def test_identical_models_compare_clean(paired_fns):
    """Same artifact on both sides: the shadow compare reports (near-)zero
    deltas — the promotion happy path's evidence."""
    primary_fn, _ = paired_fns
    s1 = _server(primary_fn, replica_id=1)
    s2 = _server(primary_fn, replica_id=2)
    router = FleetRouter(
        [(1, s1.url), (2, s2.url)], port=0, window_secs=0
    ).start()
    x = np.random.default_rng(4).normal(0, 1, (1, FEATURES)).astype(np.float32)
    try:
        router.start_shadow(2, fraction=1.0)
        for _ in range(5):
            _post(router.url + "/v1/predict", {"instances": x.tolist()})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            stats = router.shadow_snapshot() or {}
            if stats.get("compared", 0) >= 5:
                break
            time.sleep(0.1)
        assert stats["compared"] >= 5
        # float round-trip through JSON is exact: identical models agree
        assert stats["max_abs_delta"] == 0.0
        assert stats.get("mean_disagree", 0.0) == 0.0
    finally:
        router.shutdown()
        s1.shutdown()
        s2.shutdown()


def test_shadow_mismatched_outputs_count_as_canary_errors(paired_fns):
    """A canary answering with different output NAMES (or shapes) is a wrong
    answer, not a comparison to skip — counting it as 'compared' would let
    every accuracy gate pass vacuously."""
    import jax

    primary_fn, _ = paired_fns

    @jax.jit
    def renamed_fn(x):
        out = primary_fn(x)
        return {"logits": out["probabilities"]}  # different output name

    s1 = _server(primary_fn, replica_id=1)
    s2 = _server(renamed_fn, replica_id=2)
    router = FleetRouter(
        [(1, s1.url), (2, s2.url)], port=0, window_secs=0
    ).start()
    x = np.random.default_rng(6).normal(0, 1, (1, FEATURES)).astype(np.float32)
    try:
        router.start_shadow(2, fraction=1.0)
        for _ in range(5):
            _post(router.url + "/v1/predict", {"instances": x.tolist()})
        deadline = time.monotonic() + 30
        stats = {}
        while time.monotonic() < deadline:
            stats = router.shadow_snapshot() or {}
            if stats.get("canary_errors", 0) >= 5:
                break
            time.sleep(0.1)
        assert stats["canary_errors"] >= 5
        assert stats["compared"] == 0  # never evidence, never a pass
    finally:
        router.shutdown()
        s1.shutdown()
        s2.shutdown()


# -- artifact identity + mixed-fleet aggregation ------------------------------


def test_router_polls_artifact_identity_and_reports_mix(paired_fns):
    primary_fn, canary_fn = paired_fns
    q1 = {"dtype": "float32", "source_fingerprint": "a" * 16}
    q2 = {"dtype": "int8", "source_fingerprint": "b" * 16}
    s1 = _server(primary_fn, replica_id=1, quantization=q1)
    s2 = _server(canary_fn, replica_id=2, quantization=q2)
    router = FleetRouter(
        [(1, s1.url), (2, s2.url)], port=0, window_secs=0
    )
    try:
        router.poll_once()
        arts = router.replica_artifacts()
        assert arts[1]["source_fingerprint"] == "a" * 16
        assert arts[2]["dtype"] == "int8"
        mix = router.artifact_mix()
        assert mix == {"float32:aaaaaaaa": 1, "int8:bbbbbbbb": 1}
        health = router.healthz()
        assert health["mixed_artifacts"] is True
        assert health["artifacts"] == mix
        assert health["promotion_active"] is False
        window = router.emit_window()
        assert window["fleet"]["artifacts"] == mix
    finally:
        router._httpd.server_close()
        s1.shutdown()
        s2.shutdown()


def test_artifact_key_shapes():
    assert artifact_key(None) == "unknown"
    assert artifact_key({"dtype": "int8"}) == "int8:?"
    assert (
        artifact_key({"dtype": "float32", "source_fingerprint": "c" * 64})
        == "float32:cccccccc"
    )


# -- controller phase machine (fake fleet) ------------------------------------


class _FakeReplica:
    def __init__(self, rid, artifact_dir=None):
        self.replica_id = rid
        self.state = "live"
        self.restarts = 0
        self.url = f"http://127.0.0.1:{9000 + rid}"
        self.artifact_dir = artifact_dir
        self.exit_code = None
        self.ready = threading.Event()
        self.ready.set()


class _FakeManager:
    def __init__(self, n_incumbents=3, incumbent_dir="/v1"):
        self.config = types.SimpleNamespace(
            artifact_dir=incumbent_dir, registry=None
        )
        self._reps = {
            i: _FakeReplica(i) for i in range(1, n_incumbents + 1)
        }
        self._next = n_incumbents + 1
        self.spawn_fails_for = set()  # artifact dirs whose spawn never readies
        self.scale_ups = []
        self.scale_downs = []

    def replicas(self):
        return list(self._reps.values())

    def scale_up(self, artifact_dir=None, fault_spec=None, model=None):
        rid = self._next
        self._next += 1
        rep = _FakeReplica(rid, artifact_dir=artifact_dir)
        resolved = artifact_dir or self.config.artifact_dir
        if resolved in self.spawn_fails_for:
            # a spawn crash-looping without ever becoming ready (>= the
            # crash_loop_threshold: ONE death is a tolerated blip)
            rep.state = "backoff"
            rep.ready.clear()
            rep.url = None
            rep.restarts = 2
            rep.exit_code = 1
        self._reps[rid] = rep
        self.scale_ups.append((rid, artifact_dir, fault_spec))
        return rid

    def scale_down(self, replica_id=None):
        if replica_id is None or replica_id not in self._reps:
            return None
        self._reps.pop(replica_id)
        self.scale_downs.append(replica_id)
        return replica_id


class _FakeRouter:
    def __init__(self, manager, candidate_dir, candidate_fp="fp-cand"):
        self.manager = manager
        self.candidate_dir = candidate_dir
        self.candidate_fp = candidate_fp
        self.promotion_active = False
        self.promoter = None
        self.shadow_calls = []
        self.shadow_snaps = []  # scripted windows, popped per drain
        self._armed = None

    def start_shadow(self, rid, fraction):
        self._armed = rid
        self.shadow_calls.append(("start", rid, fraction))

    def stop_shadow(self):
        self.shadow_calls.append(("stop", self._armed))
        self._armed = None

    def shadow_snapshot(self, drain=False):
        if not self.shadow_snaps:
            return {"selected": 0, "compared": 0, "dropped": 0,
                    "canary_errors": 0, "send_failures": 0}
        if drain:
            return self.shadow_snaps.pop(0)
        return dict(self.shadow_snaps[0])

    def poll_once(self):
        pass

    def replica_artifacts(self):
        out = {}
        for rep in self.manager.replicas():
            if rep.artifact_dir == self.candidate_dir:
                fp = self.candidate_fp
            else:
                fp = "fp-incumbent"
            out[rep.replica_id] = {
                "dtype": "float32", "source_fingerprint": fp,
            }
        return out

    def artifact_mix(self):
        mix = {}
        for a in self.replica_artifacts().values():
            key = artifact_key(a)
            mix[key] = mix.get(key, 0) + 1
        return mix

    def fleet_snapshot(self):
        return {"worst_p99_ms": None}


def _fast_config(**overrides):
    base = dict(
        shadow_secs=0.02,
        shadow_fraction=0.5,
        shadow_min_requests=4,
        shadow_max_secs=1.0,
        observe_secs=0.01,
        ready_timeout_s=5.0,
        drain_timeout_s=5.0,
        identity_timeout_s=5.0,
        poll_interval_s=0.01,
    )
    base.update(overrides)
    return PromoteConfig(**base)


GOOD_WINDOW = {
    "selected": 20, "compared": 10, "dropped": 0, "canary_errors": 0,
    "send_failures": 0, "max_abs_delta": 0.01, "mean_abs_delta": 0.002,
    "min_iou": 0.99, "mean_disagree": 0.0,
    "latency_ms": {"primary_p50": 4.0, "primary_p99": 10.0,
                   "canary_p50": 4.2, "canary_p99": 11.0,
                   "canary_p99_ratio": 1.1},
}


def _controller(tmp_path, monkeypatch, *, n=3, candidate="/v2",
                manifest_quant=True):
    manager = _FakeManager(n_incumbents=n)
    router = _FakeRouter(manager, candidate)
    tel = Telemetry(str(tmp_path / "ledger"), run_info={"kind": "serve-fleet"})
    tel.test_workdir = str(tmp_path / "ledger")
    controller = PromotionController(manager, router, telemetry=tel)

    def fake_read_manifest(directory):
        m = {"input_shape": [None, FEATURES], "input_dtype": "float32"}
        if manifest_quant:
            m["quantization"] = {
                "dtype": "float32",
                "source_fingerprint": "fp-cand",
            }
        return m

    monkeypatch.setattr(
        "tensorflowdistributedlearning_tpu.train.serving.read_manifest",
        fake_read_manifest,
    )
    return controller, manager, router, tel


def _events(tel):
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    tel.flush()
    return read_ledger(tel.test_workdir)


def test_controller_happy_path_promotes_every_replica(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    router.shadow_snaps = [dict(GOOD_WINDOW)]
    controller.start("/v2", config=_fast_config())
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "complete", status
    # every live replica is on the candidate; the fleet default flipped
    assert manager.config.artifact_dir == "/v2"
    assert all(
        r.artifact_dir == "/v2" for r in manager.replicas()
    )
    assert len(manager.replicas()) == 3  # strength preserved
    # shadow was armed on the canary and disarmed before rollout
    assert router.shadow_calls[0][0] == "start"
    assert ("stop", router.shadow_calls[0][1]) in router.shadow_calls
    kinds = [e["event"] for e in _events(tel)]
    assert "promotion_start" in kinds
    assert "shadow_window" in kinds
    assert kinds.count("phase_advance") >= 3  # canary, shadow, rollouts
    assert kinds[-1] == "promotion_complete" or "promotion_complete" in kinds
    assert "promotion_rollback" not in kinds
    tel.close()


def test_controller_empty_shadow_window_holds_then_advances(
    tmp_path, monkeypatch
):
    """An empty-traffic window is NOT evidence: the phase holds (another
    window runs) and only a window with enough compares advances."""
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    empty = {"selected": 0, "compared": 0, "dropped": 0, "canary_errors": 0,
             "send_failures": 0}
    router.shadow_snaps = [dict(empty), dict(empty), dict(GOOD_WINDOW)]
    controller.start("/v2", config=_fast_config(shadow_max_secs=30.0))
    assert controller.wait(timeout=30)
    assert controller.status()["state"] == "complete"
    windows = [
        e for e in _events(tel) if e["event"] == "shadow_window"
    ]
    assert len(windows) == 3  # two held, one advanced
    assert windows[0]["compared"] == 0
    tel.close()


def test_controller_shadow_starvation_rolls_back(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    router.shadow_snaps = []  # never any traffic
    controller.start(
        "/v2", config=_fast_config(shadow_max_secs=0.1)
    )
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "rolled_back"
    assert "starved" in status["reason"]
    # fleet restored: 3 incumbents, no candidate replicas
    assert len(manager.replicas()) == 3
    assert all(r.artifact_dir is None for r in manager.replicas())
    assert manager.config.artifact_dir == "/v1"
    tel.close()


def test_controller_accuracy_regression_rolls_back(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    bad = dict(GOOD_WINDOW, min_iou=0.5, mean_disagree=0.4)
    router.shadow_snaps = [bad]
    controller.start("/v2", config=_fast_config())
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "rolled_back"
    assert "accuracy" in status["reason"]
    events = _events(tel)
    rollback = next(
        e for e in events if e["event"] == "promotion_rollback"
    )
    assert rollback["status"] == "rolled_back"
    assert rollback["phase"] == "shadow"
    # the canary was shadow-only: drained without a replacement spawn
    assert len(manager.replicas()) == 3
    assert all(r.artifact_dir is None for r in manager.replicas())
    tel.close()


def test_controller_latency_regression_rolls_back(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    slow = dict(
        GOOD_WINDOW,
        latency_ms={"primary_p50": 4.0, "primary_p99": 10.0,
                    "canary_p50": 9.0, "canary_p99": 40.0,
                    "canary_p99_ratio": 4.0},
    )
    router.shadow_snaps = [slow]
    controller.start("/v2", config=_fast_config(max_p99_ratio=1.5))
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "rolled_back"
    assert "latency" in status["reason"]
    tel.close()


def test_controller_canary_crash_loop_rolls_back(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    empty = {"selected": 0, "compared": 0, "dropped": 0, "canary_errors": 0,
             "send_failures": 0}
    router.shadow_snaps = [dict(empty) for _ in range(50)]

    orig_scale_up = manager.scale_up

    def crashing_scale_up(artifact_dir=None, fault_spec=None, model=None):
        rid = orig_scale_up(artifact_dir=artifact_dir, fault_spec=fault_spec)
        if artifact_dir == "/v2":
            # ready once, then flapping: restarts past the threshold
            manager._reps[rid].restarts = 3
        return rid

    manager.scale_up = crashing_scale_up
    controller.start("/v2", config=_fast_config(crash_loop_threshold=2))
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "rolled_back"
    assert "crash-loop" in status["reason"]
    assert len(manager.replicas()) == 3
    tel.close()


def test_controller_single_restart_is_tolerated(tmp_path, monkeypatch):
    """One canary death (the sigkill drill) is a blip the supervisor
    absorbs, NOT a crash loop — the promotion must converge."""
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    router.shadow_snaps = [dict(GOOD_WINDOW)]

    orig_scale_up = manager.scale_up

    def one_restart_scale_up(artifact_dir=None, fault_spec=None, model=None):
        rid = orig_scale_up(artifact_dir=artifact_dir, fault_spec=fault_spec)
        if fault_spec:
            manager._reps[rid].restarts = 1  # died once, restarted clean
        return rid

    manager.scale_up = one_restart_scale_up
    controller.start(
        "/v2", config=_fast_config(), fault_spec="sigkill@10"
    )
    assert controller.wait(timeout=30)
    assert controller.status()["state"] == "complete"
    assert manager.scale_ups[0] == (4, "/v2", "sigkill@10")
    tel.close()


def test_controller_admission_refuses_unreadable_manifest(
    tmp_path, monkeypatch
):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)

    def broken_read_manifest(directory):
        raise ValueError("no manifest.json")

    monkeypatch.setattr(
        "tensorflowdistributedlearning_tpu.train.serving.read_manifest",
        broken_read_manifest,
    )
    controller.start("/v2", config=_fast_config())
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "refused"
    assert "manifest" in status["reason"]
    # the fleet was never touched
    assert manager.scale_ups == [] and manager.scale_downs == []
    events = _events(tel)
    rollback = next(
        e for e in events if e["event"] == "promotion_rollback"
    )
    assert rollback["status"] == "refused"
    assert rollback["phase"] == "admission"
    tel.close()


def test_controller_admission_refuses_fingerprint_mismatch(
    tmp_path, monkeypatch
):
    """quantize-check is the admission gate: a failed pairing (fingerprint
    mismatch) refuses the candidate before any replica moves."""
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)

    def failing_quant_check(reference_dir, candidate_dir, **kwargs):
        return {
            "passed": False,
            "failures": [
                "source fingerprint mismatch — the artifacts derive from "
                "different checkpoints, the comparison is meaningless"
            ],
        }

    monkeypatch.setattr(
        "tensorflowdistributedlearning_tpu.serve.quant_check.run_quant_check",
        failing_quant_check,
    )
    controller.start(
        "/v2", reference_dir="/ref", config=_fast_config()
    )
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "refused"
    assert "fingerprint mismatch" in status["reason"]
    assert manager.scale_ups == []
    tel.close()


def test_controller_incumbent_deleted_aborts_structurally(
    tmp_path, monkeypatch
):
    """Rollback needs the incumbent artifact back; when it is gone the
    controller must ABORT with a ledgered verdict and leave the surviving
    candidate replicas serving — never a dead fleet."""
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    bad = dict(GOOD_WINDOW, min_iou=0.2)
    router.shadow_snaps = [bad]
    # the incumbent artifact dir vanishes mid-promotion: every incumbent
    # respawn fails
    manager.spawn_fails_for.add("/v1")
    # make rollback NEED a replacement: kill one incumbent at shadow time so
    # the fleet is below original strength when the gate trips
    orig_snapshot = router.shadow_snapshot

    def snapshot_and_lose_incumbent(drain=False):
        for rep in list(manager._reps.values()):
            if rep.artifact_dir is None:
                manager._reps.pop(rep.replica_id)
                break
        return orig_snapshot(drain=drain)

    router.shadow_snapshot = snapshot_and_lose_incumbent
    controller.start("/v2", config=_fast_config())
    assert controller.wait(timeout=30)
    status = controller.status()
    assert status["state"] == "aborted"
    assert "incumbent" in status["reason"]
    # the canary is still there, still serving — not a dead fleet
    survivors = manager.replicas()
    assert any(r.artifact_dir == "/v2" for r in survivors)
    events = _events(tel)
    rollback = next(
        e for e in events if e["event"] == "promotion_rollback"
    )
    assert rollback["status"] == "aborted"
    assert rollback["candidate_replicas_kept"] >= 1
    tel.close()


def test_controller_rejects_concurrent_promotions(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    empty = {"selected": 0, "compared": 0, "dropped": 0, "canary_errors": 0,
             "send_failures": 0}
    router.shadow_snaps = [dict(empty) for _ in range(100)]
    controller.start("/v2", config=_fast_config(shadow_max_secs=20.0))
    with pytest.raises(RuntimeError):
        controller.start("/v3", config=_fast_config())
    controller.abort()
    assert controller.wait(timeout=30)
    assert controller.status()["state"] == "rolled_back"
    assert controller.status()["reason"] == "operator abort"
    tel.close()


def test_admin_start_payload_validation(tmp_path, monkeypatch):
    controller, manager, router, tel = _controller(tmp_path, monkeypatch)
    with pytest.raises(ValueError, match="candidate_dir"):
        controller.admin_start({"action": "start"})
    with pytest.raises(ValueError, match="unknown promotion option"):
        controller.admin_start(
            {"action": "start", "candidate_dir": "/v2", "min_iou": 0.5}
        )
    # 0 would let the first empty shadow window pass every gate vacuously
    with pytest.raises(ValueError, match="shadow_min_requests"):
        controller.admin_start(
            {"action": "start", "candidate_dir": "/v2",
             "shadow_min_requests": 0}
        )
    tel.close()


def test_admin_endpoint_maps_caller_errors_to_400(tmp_path, monkeypatch):
    """A wrongly-typed config value over the wire is a 400 bad_request, not
    a 500 — the admin surface answers caller errors structurally."""
    controller, manager, _fake_router, tel = _controller(
        tmp_path, monkeypatch
    )
    router = FleetRouter([], port=0, window_secs=0).start()
    router.promoter = controller
    try:
        def post(payload):
            req = urllib.request.Request(
                router.url + "/admin/promotion",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        status, body = post({"action": "start", "candidate_dir": "/v2",
                             "shadow_secs": "ten"})
        assert status == 400
        assert body["error"]["code"] == "bad_request"
        status, body = post({"action": "sideways"})
        assert status == 400
        status, _ = post({"action": "abort"})  # no-op when nothing runs
        assert status == 202
    finally:
        router.shutdown()
        tel.close()


def test_autoscaler_pauses_during_promotion(tmp_path):
    """Mid-promotion the autoscaler must not scale (scale_down would drain
    the canary / newest candidate); ticks resume when the controller
    finishes."""
    from tensorflowdistributedlearning_tpu.serve import (
        AutoscaleConfig,
        FleetConfig,
        ServeFleet,
    )

    fleet = ServeFleet(
        FleetConfig(artifact_dir="/a", workdir=str(tmp_path / "wd")),
        autoscale=AutoscaleConfig(min_replicas=2, sustain=1, cooldown_s=0),
    )
    scale_ups = []
    fleet.manager.scale_up = lambda *a, **k: scale_ups.append(1)
    try:
        # a dead fleet normally triggers the no_capacity emergency — but
        # not while a promotion is in flight
        fleet.router.promotion_active = True
        assert fleet.autoscale_tick() is None
        assert scale_ups == []
        fleet.router.promotion_active = False
        decision = fleet.autoscale_tick()
        assert decision is not None and decision["reason"] == "no_capacity"
        assert scale_ups  # resumed the moment the promotion ended
    finally:
        fleet.router._httpd.server_close()


# -- report + console satellites ----------------------------------------------


def test_promotion_events_render_as_deployment_history(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    workdir = str(tmp_path / "fleet")
    tel = Telemetry(workdir, run_info={"kind": "serve-fleet"})
    tel.event("promotion_start", candidate_dir="/v2", dtype="float32",
              fingerprint="f" * 16, replicas=3)
    tel.event("phase_advance", phase="canary", replica=4)
    tel.event("shadow_window", replica=4, window=1, compared=12,
              max_abs_delta=0.01, mean_disagree=0.0, min_iou=0.99)
    tel.event("phase_advance", phase="shadow_complete", replica=4,
              windows=1, compared=12)
    tel.event("phase_advance", phase="rollout", replaced=1, remaining=1)
    tel.event("promotion_rollback", phase="rollout",
              reason="latency: fleet p99 regressed", status="rolled_back",
              restored=2, drained=2)
    tel.close()
    rendered = report_workdir(workdir)
    assert "deployment history" in rendered
    assert "1 ROLLED BACK" in rendered
    assert "phase canary" in rendered
    assert "shadow window" in rendered
    assert "latency: fleet p99 regressed" in rendered
    as_json = json.loads(report_workdir(workdir, as_json=True))
    pm = as_json["promotion"]
    assert pm["starts"] == 1 and pm["rolled_back"] == 1
    assert pm["shadow_windows"] == 1 and pm["shadow_compared"] == 12
    assert pm["last_rollback"]["phase"] == "rollout"


def test_silent_mixed_fleet_warns_in_report(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    workdir = str(tmp_path / "mixed")
    tel = Telemetry(workdir, run_info={"kind": "serve-fleet"})
    tel.event(
        "router_window", requests=10, routed=10, retries=0, shed=0,
        no_replica=0, replica_failures=0,
        per_replica_routed={"1": 5, "2": 5},
        fleet={"status": "ok", "live": 2, "starting": 0, "draining": 0,
               "dead": 0,
               "artifacts": {"float32:aaaaaaaa": 1, "int8:bbbbbbbb": 1},
               "promotion_active": False},
    )
    tel.close()
    rendered = report_workdir(workdir)
    assert "MIXED FLEET outside an active promotion" in rendered
    as_json = json.loads(report_workdir(workdir, as_json=True))
    assert as_json["serve_fleet"]["router"]["silent_mixed_fleet"] is True

    # the same mix DURING a promotion is expected, not a warning
    workdir2 = str(tmp_path / "promoting")
    tel = Telemetry(workdir2, run_info={"kind": "serve-fleet"})
    tel.event(
        "router_window", requests=10, routed=10, retries=0, shed=0,
        no_replica=0, replica_failures=0, per_replica_routed={},
        fleet={"status": "ok", "live": 2, "starting": 0, "draining": 0,
               "dead": 0,
               "artifacts": {"float32:aaaaaaaa": 1, "int8:bbbbbbbb": 1},
               "promotion_active": True},
    )
    tel.close()
    assert "MIXED FLEET" not in report_workdir(workdir2)


def test_telemetry_top_shows_data_service_row(tmp_path):
    from tensorflowdistributedlearning_tpu.obs import top as top_lib

    workdir = str(tmp_path / "train")
    tel = Telemetry(workdir, run_info={"kind": "fit"})
    tel.event(
        "step_window", step=40, steps=20, data_wait_s=0.1, compute_s=2.0,
        step_time_ms={"mean_ms": 100.0, "p50_ms": 99.0, "p90_ms": 110.0,
                      "p99_ms": 120.0, "max_ms": 130.0, "count": 20},
        data_service={"underruns": 2,
                      "ready_depth": {"mean": 1.5, "min": 0},
                      "worker_util": 0.83},
    )
    tel.close()
    frame = top_lib.build_frame(workdir)
    row = frame["rows"][0]
    assert row["data_service"]["underruns"] == 2
    assert row["data_service"]["ready_depth_mean"] == 1.5
    assert row["data_service"]["worker_util"] == 0.83
    rendered = top_lib.render_frame(frame)
    assert "data-svc:" in rendered
    assert "workers 83% busy" in rendered
    assert "STARVED" in rendered


# -- CLI surface --------------------------------------------------------------


def test_cli_promote_parser_defaults():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(["promote", "--candidate-dir", "/v2"])
    assert args.candidate_dir == "/v2"
    assert args.router is None and args.workdir is None
    assert not args.abort
    assert args.shadow_secs is None  # controller defaults rule
    args = build_parser().parse_args(
        ["promote", "--candidate-dir", "/v2", "--router",
         "http://127.0.0.1:8000", "--canary-inject-fault", "sigkill@25",
         "--min-iou", "0.95"]
    )
    assert args.canary_inject_fault == "sigkill@25"
    assert args.shadow_min_iou == 0.95


def test_cli_promote_resolves_router_from_workdir_ledger(tmp_path):
    from tensorflowdistributedlearning_tpu.cli import _resolve_router_url

    workdir = str(tmp_path / "fleet")
    tel = Telemetry(
        workdir,
        run_info={"kind": "serve-fleet",
                  "endpoint": "http://127.0.0.1:7777"},
    )
    tel.close()
    assert _resolve_router_url(None, workdir) == "http://127.0.0.1:7777"
    assert _resolve_router_url("http://10.0.0.1:9/", workdir) == "http://10.0.0.1:9"
    assert _resolve_router_url(None, str(tmp_path / "nope")) is None


def test_cli_promote_without_target_is_usage_error(capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main(["promote", "--candidate-dir", "/v2"])
    assert rc == 2
    assert "no router found" in capsys.readouterr().err
    # a start without a candidate is a usage error ...
    rc = main(["promote", "--router", "http://127.0.0.1:1"])
    assert rc == 2
    assert "--candidate-dir is required" in capsys.readouterr().err
    # ... but --abort alone must parse (the emergency stop needs no
    # candidate); it then fails on connectivity, not usage
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(["promote", "--abort"])
    assert args.abort and args.candidate_dir is None


# -- sentinel gate units ------------------------------------------------------


def test_sentinel_promotion_gates():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from regression_sentinel import check_promotion

    good = {
        "promotion": {
            "kill_canary": {"completed": True, "converged": True,
                            "client_errors": 0, "restarts": 1},
            "rollback": {"rolled_back": True, "client_errors": 0,
                         "restored": True},
        }
    }
    findings = check_promotion(good)
    assert findings and all(f["ok"] for f in findings)

    bad = json.loads(json.dumps(good))
    bad["promotion"]["kill_canary"]["client_errors"] = 2
    bad["promotion"]["kill_canary"]["converged"] = False
    bad["promotion"]["rollback"]["rolled_back"] = False
    failed = {f["metric"] for f in check_promotion(bad) if not f["ok"]}
    assert failed == {
        "kill_canary.client_errors",
        "kill_canary.converged",
        "rollback.rolled_back",
    }
    # pre-promotion baselines compare nothing
    assert check_promotion({}) == []


# -- subprocess end-to-end ----------------------------------------------------


def _export_identified_artifact(directory, seed, perturb=0.0):
    """Export a real artifact WITH a quantization identity section (float32
    identity recipe → dtype + source fingerprint over the params), so the
    promotion controller's identity verification is exercised for real.
    ``perturb`` nudges the weights: small = a passing candidate, large = a
    poisoned one the shadow gate must catch."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.train import quantize
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    w = jax.random.normal(
        jax.random.PRNGKey(seed), (FEATURES, CLASSES)
    ) * 0.5
    if perturb:
        w = w + perturb * jax.random.normal(
            jax.random.PRNGKey(seed + 100), w.shape
        )
    params = {"dense": {"kernel": w}}
    _, section = quantize.quantize_pytree(params, "float32")

    def serve(x):
        logits = x @ params["dense"]["kernel"]
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }

    serving_lib.export_serving_artifact(
        serve, (1, FEATURES), directory, quantization=section
    )
    return directory


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _spawn_fleet(artifact, workdir, replicas):
    proc = subprocess.Popen(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
         "serve-fleet", "--artifact-dir", artifact, "--workdir", workdir,
         "--port", "0", "--replicas", str(replicas), "--no-autoscale",
         "--window-secs", "2", "--buckets", "1", "4",
         "--poll-interval-s", "0.25"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_env(), text=True,
    )
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        line = proc.stdout.readline().strip()
        if line.startswith("{"):
            return proc, json.loads(line)["router"]
    proc.kill()
    raise RuntimeError("serve-fleet not ready")


def _stop_fleet(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(90)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(10)


class _LoadThread:
    """Closed-loop client against the router; every non-200 is recorded."""

    def __init__(self, url):
        self.url = url
        self.ok = 0
        self.errors = []
        self._stop = threading.Event()
        rng = np.random.default_rng(5)
        self.x = rng.normal(0, 1, (1, FEATURES)).astype(np.float32)
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        import http.client
        import urllib.parse

        parsed = urllib.parse.urlsplit(self.url)
        body = json.dumps({"instances": self.x.tolist()})
        conn = None
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        parsed.hostname, parsed.port, timeout=30
                    )
                conn.request("POST", "/v1/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    self.ok += 1
                else:
                    self.errors.append(resp.status)
            except (OSError, http.client.HTTPException) as e:
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = None
                self.errors.append(f"conn:{type(e).__name__}")
            time.sleep(0.01)

    def stop(self):
        self._stop.set()
        self.thread.join(10)


def _promote_cli(workdir, candidate, extra=()):
    return subprocess.run(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
         "promote", "--workdir", workdir, "--candidate-dir", candidate,
         "--shadow-secs", "1.5", "--shadow-fraction", "1.0",
         "--shadow-min-requests", "5", "--observe-secs", "0.5",
         # CPU tail latency swings several-fold under subprocess load (the
         # sentinel uses a 6x p99 band for the same reason); the accuracy
         # gates are what these drills pin
         "--max-p99-ratio", "5.0",
         "--timeout", "420", "--json", *extra],
        capture_output=True, text=True, env=_env(), timeout=600,
    )


@pytest.mark.slow
def test_promotion_e2e_kill_canary_converges(tmp_path):
    """The headline drill: 3-replica fleet under closed-loop load, promote a
    fresh (passing) artifact with the canary SIGKILLed mid-shadow — zero
    client-visible errors, the fleet converges on the candidate fingerprint,
    and telemetry-report renders the whole deployment history."""
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    v1 = _export_identified_artifact(str(tmp_path / "v1"), seed=1)
    v2 = _export_identified_artifact(
        str(tmp_path / "v2"), seed=1, perturb=0.002
    )
    v2_fp = serving_lib.read_manifest(v2)["quantization"][
        "source_fingerprint"
    ].split(":", 1)[-1]
    workdir = str(tmp_path / "fleet")
    proc, router_url = _spawn_fleet(v1, workdir, replicas=3)
    load = _LoadThread(router_url)
    try:
        time.sleep(1.0)  # some pre-promotion traffic
        result = _promote_cli(
            workdir, v2, extra=["--canary-inject-fault", "sigkill@10"]
        )
        assert result.returncode == 0, (
            f"promote failed: {result.stdout}\n{result.stderr}"
        )
        status = json.loads(result.stdout.strip().splitlines()[-1])
        assert status["state"] == "complete"
        # the whole fleet answers from the candidate fingerprint
        health = json.loads(
            urllib.request.urlopen(router_url + "/healthz", timeout=10).read()
        )
        assert health["mixed_artifacts"] is False
        assert list(health["artifacts"]) == [f"float32:{v2_fp[:8]}"]
        load.stop()
        assert load.errors == [], f"client-visible errors: {load.errors[:10]}"
        assert load.ok > 50
    finally:
        load.stop()
        _stop_fleet(proc)

    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    rendered = report_workdir(workdir)
    assert "deployment history" in rendered
    assert "complete: fleet on" in rendered
    as_json = json.loads(report_workdir(workdir, as_json=True))
    assert as_json["promotion"]["completed"] == 1
    assert as_json["promotion"]["shadow_compared"] >= 5
    # the canary death is on record: a replica_exit with rc 137 and exactly
    # one restart, absorbed without a rollback
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    events = read_ledger(workdir)
    kinds = [e["event"] for e in events]
    assert "replica_exit" in kinds and "replica_restart" in kinds
    assert "promotion_rollback" not in kinds


@pytest.mark.slow
def test_promotion_e2e_poisoned_candidate_rolls_back(tmp_path):
    """A behaviorally-regressed candidate passes admission (it is internally
    consistent) but the shadow compare catches it: automatic rollback, fleet
    back on the incumbent fingerprint, zero client-visible errors. Also pins
    admission refusal: a reference whose fingerprint mismatches is refused
    without touching the fleet."""
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    v1 = _export_identified_artifact(str(tmp_path / "v1"), seed=1)
    poisoned = _export_identified_artifact(
        str(tmp_path / "poisoned"), seed=1, perturb=2.0
    )
    v1_fp = serving_lib.read_manifest(v1)["quantization"][
        "source_fingerprint"
    ].split(":", 1)[-1]
    workdir = str(tmp_path / "fleet")
    proc, router_url = _spawn_fleet(v1, workdir, replicas=2)
    load = _LoadThread(router_url)
    try:
        # admission refusal first: pairing the poisoned candidate against
        # the v1 reference is a fingerprint mismatch — refused, fleet
        # untouched (no replica ever spawns on it)
        refused = _promote_cli(
            workdir, poisoned, extra=["--reference-dir", v1]
        )
        assert refused.returncode == 1
        refused_status = json.loads(
            refused.stdout.strip().splitlines()[-1]
        )
        assert refused_status["state"] == "refused"

        # now the real rollback drill: manifest-only admission passes, the
        # shadow compare must catch the behavioral regression
        result = _promote_cli(workdir, poisoned)
        assert result.returncode == 1, (
            f"poisoned candidate was promoted: {result.stdout}"
        )
        status = json.loads(result.stdout.strip().splitlines()[-1])
        assert status["state"] == "rolled_back"
        assert "accuracy" in status.get("reason", "")
        health = json.loads(
            urllib.request.urlopen(router_url + "/healthz", timeout=10).read()
        )
        assert health["mixed_artifacts"] is False
        assert list(health["artifacts"]) == [f"float32:{v1_fp[:8]}"]
        assert health["live"] == 2
        load.stop()
        assert load.errors == [], f"client-visible errors: {load.errors[:10]}"
    finally:
        load.stop()
        _stop_fleet(proc)

    as_json = json.loads(
        __import__(
            "tensorflowdistributedlearning_tpu.obs.report",
            fromlist=["report_workdir"],
        ).report_workdir(workdir, as_json=True)
    )
    pm = as_json["promotion"]
    assert pm["rolled_back"] == 1 and pm["refused"] == 1
    assert pm["completed"] == 0
