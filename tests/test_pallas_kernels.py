"""Pallas depthwise-conv kernel tests (interpreter mode on CPU — same kernel code
the TPU runs): forward exactness vs the XLA grouped-conv oracle across atrous
rates, gradient correctness via the custom VJP, and the VMEM fallback path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
    depthwise_conv2d,
    depthwise_conv2d_reference,
)


@pytest.mark.parametrize("rate", [1, 2, 4])
@pytest.mark.parametrize("shape", [(2, 13, 13, 128), (1, 10, 7, 128)])
def test_forward_matches_xla(rate, shape):
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, shape).astype(np.float32)
    w = rng.normal(0, 0.5, (3, 3, shape[-1])).astype(np.float32)
    got = depthwise_conv2d(jnp.asarray(x), jnp.asarray(w), rate, interpret=True)
    want = depthwise_conv2d_reference(jnp.asarray(x), jnp.asarray(w), rate)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_5x5_kernel():
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (1, 9, 9, 128)).astype(np.float32)
    w = rng.normal(0, 0.5, (5, 5, 128)).astype(np.float32)
    got = depthwise_conv2d(jnp.asarray(x), jnp.asarray(w), 1, interpret=True)
    want = depthwise_conv2d_reference(jnp.asarray(x), jnp.asarray(w), 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_gradients_match_reference():
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (2, 8, 8, 128)).astype(np.float32)
    w = rng.normal(0, 0.5, (3, 3, 128)).astype(np.float32)

    def loss_kernel(x, w):
        return jnp.sum(depthwise_conv2d(x, w, 2, interpret=True) ** 2)

    def loss_ref(x, w):
        return jnp.sum(depthwise_conv2d_reference(x, w, 2) ** 2)

    gx, gw = jax.grad(loss_kernel, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(w))
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-4, atol=1e-3)


def test_channel_tiling_matches_oracle():
    # budget that fits one 128-lane tile but not all 256 channels: the kernel must
    # tile C across the grid and still be exact
    rng = np.random.default_rng(6)
    x = rng.normal(0, 1, (2, 12, 12, 256)).astype(np.float32)
    w = rng.normal(0, 0.5, (3, 3, 256)).astype(np.float32)
    budget = (12 + 2) * (12 + 2) * 128 * 4 + 1
    got = depthwise_conv2d(
        jnp.asarray(x), jnp.asarray(w), 1, interpret=True, vmem_limit_bytes=budget
    )
    want = depthwise_conv2d_reference(jnp.asarray(x), jnp.asarray(w), 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_vmem_fallback_used_for_large_blocks():
    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (1, 64, 64, 128)).astype(np.float32)
    w = rng.normal(0, 0.5, (3, 3, 128)).astype(np.float32)
    # tiny budget forces the XLA path; result must still be exact
    got = depthwise_conv2d(
        jnp.asarray(x), jnp.asarray(w), 1, interpret=True, vmem_limit_bytes=1024
    )
    want = depthwise_conv2d_reference(jnp.asarray(x), jnp.asarray(w), 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_bfloat16_inputs():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(0, 1, (1, 8, 8, 128)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(0, 0.5, (3, 3, 128)), jnp.bfloat16)
    got = depthwise_conv2d(x, w, 1, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = depthwise_conv2d_reference(x.astype(jnp.float32), w.astype(jnp.float32), 1)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), rtol=0.05, atol=0.05
    )


def test_model_paths_agree(monkeypatch):
    # the ASPP with use_pallas_depthwise on/off must produce identical outputs from
    # the same parameters (pure execution-path switch); the platform gate is
    # patched open so the Pallas (interpreter) path actually runs on the CPU
    # mesh — without the patch both models would take XLA and the check would
    # be vacuous
    import tensorflowdistributedlearning_tpu.models.layers as layers_mod
    from tensorflowdistributedlearning_tpu.config import ModelConfig
    from tensorflowdistributedlearning_tpu.models import build_model

    monkeypatch.setattr(layers_mod, "_pallas_platform_ok", lambda: True)
    base = dict(input_shape=(33, 33), n_blocks=(1, 1, 1), base_depth=32)
    m_xla = build_model(ModelConfig(use_pallas_depthwise=False, **base))
    m_pl = build_model(ModelConfig(use_pallas_depthwise=True, **base))
    x = jnp.asarray(np.random.default_rng(5).normal(0, 1, (1, 33, 33, 2)), jnp.float32)
    variables = m_xla.init(jax.random.PRNGKey(0), x, train=False)
    out_xla = m_xla.apply(variables, x, train=False)
    out_pl = m_pl.apply(variables, x, train=False)  # same params, pallas path
    np.testing.assert_allclose(
        np.asarray(out_pl), np.asarray(out_xla), rtol=1e-4, atol=1e-4
    )


@pytest.mark.skipif(
    jax.default_backend() == "tpu",
    reason="asserts the gate's off-TPU behavior; on TPU the kernel SHOULD engage",
)
def test_platform_gate_blocks_pallas_off_tpu():
    """With the real (unpatched) gate on the CPU backend, use_pallas=True at
    a winning rate still dispatches to XLA — the default-ON config can never
    route CI or CPU-mesh users through the Pallas interpreter."""
    import tensorflowdistributedlearning_tpu.ops.pallas_kernels as pk
    from tensorflowdistributedlearning_tpu.models.layers import DepthwiseConv2D

    calls = []
    orig = pk.depthwise_conv2d
    try:
        pk.depthwise_conv2d = lambda *a, **k: calls.append(1) or orig(*a, **k)
        layer = DepthwiseConv2D(rate=8, use_pallas=True)
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(0), x)
        layer.apply(variables, x)
    finally:
        pk.depthwise_conv2d = orig
    assert not calls  # CPU backend: the gate kept everything on XLA


def test_validation():
    x = jnp.zeros((1, 4, 4, 8))
    with pytest.raises(ValueError, match="odd kernel"):
        depthwise_conv2d(x, jnp.zeros((2, 2, 8)), interpret=True)
    with pytest.raises(ValueError, match="channel mismatch"):
        depthwise_conv2d(x, jnp.zeros((3, 3, 4)), interpret=True)


def test_rate_gate_dispatch(monkeypatch):
    """The layer engages the Pallas kernel only at rates
    >= PALLAS_DEPTHWISE_MIN_RATE even when use_pallas=True. The threshold is
    1 as of the 2026-08-01 device-dominated microbench (Pallas wins every
    rate), so the gate is exercised here by PATCHING it back to 4 — the
    machinery must keep restricting correctly if a future re-measure
    re-raises it. The platform gate is patched open so the dispatch logic
    runs on the CPU test mesh."""
    import tensorflowdistributedlearning_tpu.models.layers as layers_mod
    import tensorflowdistributedlearning_tpu.ops.pallas_kernels as pk
    from tensorflowdistributedlearning_tpu.models.layers import DepthwiseConv2D

    monkeypatch.setattr(layers_mod, "_pallas_platform_ok", lambda: True)
    monkeypatch.setattr(pk, "PALLAS_DEPTHWISE_MIN_RATE", 4)
    taken = []
    real = pk.depthwise_conv2d
    monkeypatch.setattr(
        pk,
        "depthwise_conv2d",
        lambda *a, **k: taken.append("pallas") or real(*a, **k),
    )
    x = jnp.asarray(
        np.random.default_rng(0).normal(0, 1, (1, 16, 16, 8)), jnp.float32
    )
    # init() traces the layer too, so each engaged rate records two calls
    for rate, expect in ((1, 0), (2, 0), (4, 2), (8, 4)):
        layer = DepthwiseConv2D(rate=rate, use_pallas=True)
        variables = layer.init(jax.random.PRNGKey(0), x)
        layer.apply(variables, x)
        assert len(taken) == expect, (rate, taken)


# -- fused inference BN + activation (+ residual) ----------------------------


def _bn_vectors(c, seed=7):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.normal(1, 0.1, c), jnp.float32),
        jnp.asarray(rng.normal(0, 0.1, c), jnp.float32),
        jnp.asarray(rng.normal(0, 0.1, c), jnp.float32),
        jnp.asarray(rng.uniform(0.5, 1.5, c), jnp.float32),
    )


@pytest.mark.parametrize("act", ["none", "relu", "relu6", "sigmoid", "gelu"])
@pytest.mark.parametrize("with_residual", [False, True])
def test_fused_bn_act_matches_xla(act, with_residual):
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_bn_act,
        fused_bn_act_reference,
    )

    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(0, 1, (2, 9, 11, 128)), jnp.float32)
    r = jnp.asarray(rng.normal(0, 1, x.shape), jnp.float32) if with_residual else None
    got = fused_bn_act(x, *_bn_vectors(128), act=act, residual=r, interpret=True)
    want = fused_bn_act_reference(x, *_bn_vectors(128), act=act, residual=r)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_fused_bn_act_bfloat16_io():
    """bf16 activations (the quantized serving regime) compute in f32 inside
    and return bf16 — parity against the reference at bf16 resolution."""
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_bn_act,
        fused_bn_act_reference,
    )

    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(0, 1, (1, 8, 8, 128)), jnp.bfloat16)
    r = jnp.asarray(rng.normal(0, 1, x.shape), jnp.bfloat16)
    got = fused_bn_act(x, *_bn_vectors(128), residual=r, interpret=True)
    want = fused_bn_act_reference(x, *_bn_vectors(128), residual=r)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=1e-2, atol=1e-2,
    )


def test_fused_bn_act_channel_tiling_and_fallback():
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_bn_act,
        fused_bn_act_reference,
    )

    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.normal(0, 1, (2, 12, 12, 256)), jnp.float32)
    vecs = _bn_vectors(256)
    want = fused_bn_act_reference(x, *vecs)
    # budget fits one 128-lane tile but not all 256 channels: grid tiles C
    budget = 12 * 12 * 128 * 4 + 1
    got = fused_bn_act(x, *vecs, interpret=True, vmem_limit_bytes=budget)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
    # tiny budget: the XLA fallback must be exact too
    got = fused_bn_act(x, *vecs, interpret=True, vmem_limit_bytes=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_fused_bn_act_validation():
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import fused_bn_act

    x = jnp.zeros((1, 4, 4, 8))
    s = b = m = v = jnp.ones((8,))
    with pytest.raises(ValueError, match="act"):
        fused_bn_act(x, s, b, m, v, act="swiglu", interpret=True)
    with pytest.raises(ValueError, match="channels"):
        fused_bn_act(x, jnp.ones((4,)), b, m, v, interpret=True)
    with pytest.raises(ValueError, match="residual"):
        fused_bn_act(x, s, b, m, v, residual=jnp.zeros((1, 4, 4, 4)), interpret=True)
    with pytest.raises(ValueError, match="B, H, W, C"):
        fused_bn_act(jnp.zeros((4, 8)), s, b, m, v, interpret=True)


# -- fused bias + activation (the shared epilogue's standalone face) -----------


@pytest.mark.parametrize("act", ["none", "relu", "gelu"])
@pytest.mark.parametrize("with_bias", [False, True])
def test_fused_bias_act_matches_reference(act, with_bias):
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_bias_act,
        fused_bias_act_reference,
    )

    rng = np.random.default_rng(20)
    x = jnp.asarray(rng.normal(0, 1, (3, 7, 128)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.2, (128,)), jnp.float32) if with_bias else None
    got = fused_bias_act(x, b, act=act, interpret=True)
    want = fused_bias_act_reference(x, b, act=act)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_fused_bias_act_row_tiling_and_fallback():
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_bias_act,
        fused_bias_act_reference,
    )

    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.normal(0, 1, (16, 64)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.2, (64,)), jnp.float32)
    want = fused_bias_act_reference(x, b, act="relu")
    # budget admits a quarter of the rows: the grid must tile and stay exact
    tiled = fused_bias_act(
        x, b, act="relu", interpret=True, vmem_limit_bytes=4 * 64 * 8 + 1
    )
    np.testing.assert_array_equal(np.asarray(tiled), np.asarray(want))
    # tiny budget: XLA fallback, still exact
    fb = fused_bias_act(x, b, act="relu", interpret=True, vmem_limit_bytes=16)
    np.testing.assert_array_equal(np.asarray(fb), np.asarray(want))


def test_fused_bias_act_validation():
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import fused_bias_act

    with pytest.raises(ValueError, match="act"):
        fused_bias_act(jnp.zeros((2, 4)), act="swish", interpret=True)
    with pytest.raises(ValueError, match="bias"):
        fused_bias_act(jnp.zeros((2, 4)), jnp.zeros((3,)), interpret=True)


# -- fused sigmoid + threshold mask head (segmentation serve path) ------------


def _mask_logits(shape=(2, 9, 9, 1), seed=22):
    # spread logits across the threshold so some pixels land on each side,
    # including values AT zero (sigmoid(0) == 0.5 exactly — the boundary the
    # strict > must not flip)
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 2, shape).astype(np.float32)
    x.flat[:3] = 0.0
    return jnp.asarray(x)


@pytest.mark.parametrize("threshold", [0.5, 0.3])
def test_fused_sigmoid_mask_bit_identical(threshold):
    """The contract the serve head relies on: fusing is a memory-traffic
    change, not a numerics change — BITWISE equality with the unfused ops."""
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_sigmoid_mask,
        fused_sigmoid_mask_reference,
    )

    logits = _mask_logits()
    p_ref, m_ref = fused_sigmoid_mask_reference(logits, threshold)
    for kwargs in ({"interpret": True}, {}):  # kernel body AND auto-fallback
        probs, mask = fused_sigmoid_mask(logits, threshold, **kwargs)
        assert probs.dtype == logits.dtype and mask.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(probs), np.asarray(p_ref))
        np.testing.assert_array_equal(np.asarray(mask), np.asarray(m_ref))
        assert set(np.unique(np.asarray(mask))) <= {0.0, 1.0}


def test_fused_sigmoid_mask_vmem_and_rank_fallbacks():
    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_sigmoid_mask,
        fused_sigmoid_mask_reference,
    )

    logits = _mask_logits((2, 64, 64, 1), seed=23)
    p_ref, m_ref = fused_sigmoid_mask_reference(logits, 0.5)
    p, m = fused_sigmoid_mask(logits, 0.5, interpret=True, vmem_limit_bytes=128)
    np.testing.assert_array_equal(np.asarray(p), np.asarray(p_ref))
    np.testing.assert_array_equal(np.asarray(m), np.asarray(m_ref))
    v = jnp.asarray([0.0, -1.0, 3.0], jnp.float32)  # rank-1: reference path
    p1, m1 = fused_sigmoid_mask(v, 0.5, interpret=True)
    pr, mr = fused_sigmoid_mask_reference(v, 0.5)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(pr))
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(mr))


def test_segmentation_serve_predictions_uses_fused_head():
    """SegmentationTask.serve_predictions must agree bitwise with the
    training-path predictions() dict — same probabilities, same mask."""
    from tensorflowdistributedlearning_tpu.train.step import SegmentationTask

    task = SegmentationTask()
    logits = _mask_logits((2, 5, 5, 1), seed=24)
    served = task.serve_predictions(logits)
    trained = task.predictions(logits)
    assert set(served) == set(trained)
    for k in served:
        np.testing.assert_array_equal(
            np.asarray(served[k]), np.asarray(trained[k])
        )
