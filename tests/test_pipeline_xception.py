"""Pipeline parallelism over the CONV family: Xception's middle flow (8
identical 728-wide sum-skip units — the documented homogeneous-stage case,
models/xception.py) through the GPipe runner, parity-checked against the plain
data-parallel step. Completes the strategy matrix row VERDICT r3 #6 flagged as
ViT-only.

BN note: pipelined middle units normalize with per-microbatch statistics (the
standard GPipe regime). The parity tests therefore build batches whose
microbatches share statistics exactly (identical copies), where per-microbatch
BN == full-batch BN and the pipeline update must match the plain update to
numerical tolerance."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.models import xception as xc
from tensorflowdistributedlearning_tpu.parallel import pipeline as pp
from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS, make_mesh

CFG = ModelConfig(
    backbone="xception",
    num_classes=4,
    input_shape=(32, 32),
    input_channels=3,
    width_multiplier=0.125,
    output_stride=None,
    dtype="float32",
)
MIDDLE_WIDTH = 91  # scaled_width(728, 0.125)


@pytest.fixture(scope="module")
def middle_setup():
    """Canonical middle-flow param/stat trees + an identical-microbatch
    feature tensor at the middle flow's operating shape."""
    model = build_model(CFG)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), train=False
    )
    backbone_p = variables["params"]["backbone"]
    backbone_s = variables["batch_stats"]["backbone"]
    rng = np.random.default_rng(3)
    # 4x4 spatial, mb=2: BN statistics over 32 elements — well-conditioned
    # enough that f32 reassociation noise does not amplify through the 24 BN
    # layers (at 2x2/mb=1 even plain jit-vs-eager of the same sequential
    # composition drifts ~2e-3; measured while writing this test)
    one = rng.normal(0, 1, (2, 4, 4, MIDDLE_WIDTH)).astype(np.float32)
    # [M=4 microbatches, mb, H, W, C] — all four identical, so
    # per-microbatch BN statistics equal full-batch statistics
    micro = jnp.asarray(np.broadcast_to(one[None], (4,) + one.shape)).copy()
    return backbone_p, backbone_s, micro


def _unit_trees(tree):
    return [
        tree[f"{xc.MIDDLE_FLOW_PREFIX}{i + 1}"]
        for i in range(xc.MIDDLE_FLOW_UNITS)
    ]


def test_pipelined_middle_flow_matches_sequential(middle_setup):
    """Forward + train-mode BN stat updates of the pipelined middle flow equal
    sequential unit application (K=4 stages x 2 units/stage)."""
    backbone_p, backbone_s, micro = middle_setup
    k = 4
    mesh = make_mesh(4, model_parallel=4)
    stage_fn = xc.grouped_middle_stage_fn(CFG, xc.MIDDLE_FLOW_UNITS // k, True)
    stacked = (
        xc.stack_middle_unit_tree(backbone_p, k),
        xc.stack_middle_unit_tree(backbone_s, k),
    )

    def body(bundle_shard, x):
        my = jax.tree.map(lambda l: l[0], bundle_shard)
        return pp.pipeline_apply_aux(stage_fn, my, x)

    out_pipe, stats_pipe = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=((pp.stage_in_spec(), pp.stage_in_spec()), P()),
            # aux gathers along the stage axis -> [K, G, ...] grouped stats
            out_specs=(P(), P(MODEL_AXIS)),
        )
    )(stacked, micro)

    # sequential oracle: the same single microbatch through all 8 units
    module = xc.middle_unit_module(CFG)
    x = micro[0]
    seq_stats = []
    for p_i, s_i in zip(_unit_trees(backbone_p), _unit_trees(backbone_s)):
        x, mutated = module.apply(
            {"params": p_i, "batch_stats": s_i}, x, True, mutable=["batch_stats"]
        )
        seq_stats.append(mutated["batch_stats"])

    for m in range(micro.shape[0]):
        np.testing.assert_allclose(
            np.asarray(out_pipe[m]), np.asarray(x), rtol=1e-3, atol=2e-4
        )
    # the shard_map gather concatenates the stage axis: leaves arrive
    # [K*G, ...] = [8, ...] in unit order
    for i, seq in enumerate(seq_stats):
        got = jax.tree.map(lambda l, i=i: l[i], stats_pipe)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(seq)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )


def _train_state(cfg, tcfg):
    from tensorflowdistributedlearning_tpu.train import (
        create_train_state,
        make_optimizer,
    )

    model = build_model(cfg)
    return create_train_state(
        model,
        make_optimizer(tcfg),
        jax.random.PRNGKey(1),
        np.zeros((1, *cfg.input_shape, cfg.input_channels), np.float32),
    )


def test_xception_pipeline_train_step_matches_plain_step():
    """ONE pipeline-parallel update (dp=2 x stages=4) equals the plain dp=2
    update on the same global batch. Both strategies run dp=2 so the
    per-(step, batch-shard) dropout streams coincide, and each shard's batch
    is one example tiled 4x so per-microbatch BN equals full-batch BN — under
    those controls the two executions compute the same math."""
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train import pipeline_step as pp_step
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        compute_metrics,
    )

    tcfg = TrainConfig(optimizer="sgd", lr=0.05, weight_decay=1e-3)
    task = ClassificationTask()
    # Each dp shard's local batch is a distinct PAIR tiled 4x: every
    # microbatch holds one (x_a, x_b) pair, so per-microbatch BN statistics
    # equal full-batch statistics EXACTLY while intra-batch variance stays
    # nonzero at every feature extent. 64x64 input (not 32) keeps the trunk
    # output at 2x2 — at 1x1 the pair variance gets tiny deep in the network
    # and the BN backward amplifies f32 noise past any usable tolerance
    # (measured: ~30 absolute on exploded O(500) params at 32x32 vs 1.6e-4 on
    # O(1) params here; tiling a SINGLE example is worse still — variance 0,
    # degenerate zero logits). Measured parity at this construction: loss
    # rel 4e-7, params <=1.6e-4, stats <=7e-7.
    cfg = dataclasses.replace(CFG, input_shape=(64, 64))
    rng = np.random.default_rng(7)
    uniq = rng.normal(0, 1, (4, 64, 64, 3)).astype(np.float32)
    labels = np.array([1, 3, 0, 2], np.int32)
    images = np.concatenate(
        [np.tile(uniq[0:2], (4, 1, 1, 1)), np.tile(uniq[2:4], (4, 1, 1, 1))]
    )
    batch = {
        "images": jnp.asarray(images),
        "labels": jnp.asarray(
            np.concatenate([np.tile(labels[0:2], 4), np.tile(labels[2:4], 4)])
        ),
    }

    plain_mesh = make_mesh(2)
    state_a = mesh_lib.replicate(_train_state(cfg, tcfg), plain_mesh)
    plain_step = step_lib.make_train_step(plain_mesh, task, donate=False)
    state_a, metrics_a = plain_step(
        state_a, mesh_lib.shard_batch(batch, plain_mesh)
    )

    pp_mesh = make_mesh(8, model_parallel=4)
    state_b = mesh_lib.replicate(_train_state(cfg, tcfg), pp_mesh)
    pipe_step = pp_step.make_train_step_pipeline(
        pp_mesh, task, cfg, microbatches=4, donate=False
    )
    state_b, metrics_b = pipe_step(state_b, mesh_lib.shard_batch(batch, pp_mesh))

    assert compute_metrics(metrics_a)["loss"] == pytest.approx(
        compute_metrics(metrics_b)["loss"], rel=1e-3
    )
    # generous margin over the measured 1.6e-4 worst-leaf drift (f32 noise
    # through the 24-BN middle flow); a real assembly bug — a misrouted
    # stage, a double-counted grad, a wrong dropout mask — shows up at O(1)
    for a, b in zip(
        jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4
        )
    # BN bookkeeping matches too: stats are part of the training contract
    for a, b in zip(
        jax.tree.leaves(state_a.batch_stats),
        jax.tree.leaves(state_b.batch_stats),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4
        )


def test_fit_pipeline_parallel_xception_end_to_end(tmp_path):
    """TrainConfig.pipeline_parallel=4 trains the Xception classifier through
    fit(): finite loss, checkpoints land, and the canonical tree serves
    through the PLAIN model (strategies stay checkpoint-interchangeable)."""
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    trainer = ClassifierTrainer(
        str(tmp_path),
        None,
        CFG,
        TrainConfig(
            optimizer="adam",
            lr=1e-3,
            seed=0,
            pipeline_parallel=4,
            # M=8 > K=4 stages: the bubble-shrinking regime (fill/drain
            # fraction (K-1)/(M+K-1) = 3/11), not just the M=K minimum
            pipeline_microbatches=8,
            checkpoint_every_steps=4,
        ),
    )
    result = trainer.fit(batch_size=16, steps=4)
    assert result.steps == 4
    assert np.isfinite(result.final_metrics["loss"])
    assert "metrics/top1" in result.final_metrics

    serve = trainer.serving_fn()
    out = serve(np.zeros((2, 32, 32, 3), np.float32))
    assert np.asarray(out["probabilities"]).shape == (2, 4)


def test_xception_pipeline_validation():
    from tensorflowdistributedlearning_tpu.train.pipeline_step import (
        validate_pipeline_config,
    )

    # 8 middle units: K must divide 8
    with pytest.raises(ValueError, match="not.*divisible"):
        validate_pipeline_config(CFG, 3, 6)
    # segmentation layout is out of scope
    with pytest.raises(ValueError, match="classifier"):
        validate_pipeline_config(
            dataclasses.replace(CFG, num_classes=None), 4, 4
        )
    # resnet cannot pipeline, with guidance naming both supported families
    with pytest.raises(ValueError, match="xception"):
        validate_pipeline_config(
            ModelConfig(
                num_classes=4,
                input_shape=(16, 16),
                input_channels=3,
                n_blocks=(1, 1, 1),
                output_stride=None,
            ),
            2,
            2,
        )
    # whitelist, not a resnet blacklist: a backbone validate_pipeline_config
    # has never heard of must be rejected, not silently built as a ViT
    # pipeline (ModelConfig would refuse "densenet" at construction, so use a
    # stub to model a future backbone added without pipeline support)
    import types

    stub = types.SimpleNamespace(
        backbone="densenet", moe_experts=0, num_classes=4, vit_layers=4
    )
    with pytest.raises(ValueError, match="does not support backbone"):
        validate_pipeline_config(stub, 2, 2)


def test_exit_head_keep_prob_single_source():
    """The pipelined head's dropout must track Xception41's — checkpoints
    interchange between the strategies, so a drift here would silently change
    train-mode behavior on one side only."""
    from tensorflowdistributedlearning_tpu.models import xception as xc

    assert xc.XceptionExitHead.keep_prob == xc.Xception41.keep_prob
    assert xc.Xception41.keep_prob == xc.DEFAULT_KEEP_PROB
