"""Space-to-depth stem (models/layers.py:SpaceToDepthConv): the TPU stem trick
must be numerically identical to the plain 3x3 stride-2 SAME conv it replaces,
and checkpoint-compatible with it (same parameter tree)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.config import ModelConfig
from tensorflowdistributedlearning_tpu.models import build_model
from tensorflowdistributedlearning_tpu.models.layers import (
    ConvBN,
    SpaceToDepthConv,
    space_to_depth,
)


def test_space_to_depth_layout():
    """Channel order is (dy, dx, c): cell (i, j) holds rows 2i..2i+1."""
    x = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    # output cell (0, 0), channel block (dy=1, dx=0) == input pixel (1, 0)
    np.testing.assert_array_equal(y[0, 0, 0, 6:9], x[0, 1, 0, :])
    # output cell (1, 1), channel block (dy=0, dx=1) == input pixel (2, 3)
    np.testing.assert_array_equal(y[1, 1, 1, 3:6], x[1, 2, 3, :])


def test_space_to_depth_rejects_odd():
    with pytest.raises(ValueError, match="divisible"):
        space_to_depth(jnp.zeros((1, 5, 4, 3)), 2)


@pytest.mark.parametrize("hw", [(8, 8), (14, 10)])
def test_s2d_conv_matches_plain_conv(hw):
    """SpaceToDepthConv(k) == nn.Conv 3x3/2 SAME with the same kernel."""
    import flax.linen as nn

    h, w = hw
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (2, h, w, 3), jnp.float32)
    s2d = SpaceToDepthConv(16)
    params = s2d.init(jax.random.PRNGKey(1), x)
    ref = nn.Conv(
        16, (3, 3), strides=(2, 2), padding="SAME", use_bias=False
    )
    got = s2d.apply(params, x)
    want = ref.apply(params, x)
    assert got.shape == want.shape == (2, h // 2, w // 2, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_convbn_s2d_checkpoint_compatible():
    """The SAME params drive both ConvBN stems to the SAME output — switching
    stem_space_to_depth on a trained checkpoint changes nothing."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3), jnp.float32)
    plain = ConvBN(8, 3, stride=2)
    fast = ConvBN(8, 3, stride=2, space_to_depth=True)
    params = plain.init(jax.random.PRNGKey(1), x, True)
    assert jax.tree.structure(params) == jax.tree.structure(
        fast.init(jax.random.PRNGKey(1), x, True)
    )
    a, _ = plain.apply(params, x, True, mutable=["batch_stats"])
    b, _ = fast.apply(params, x, True, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_classifier_forward_parity_with_s2d_stem():
    """Whole-model parity: a classic-layout classifier's logits are unchanged
    by the stem transform (same params, fp32)."""
    base = dict(
        num_classes=5,
        input_shape=(16, 16),
        input_channels=3,
        n_blocks=(1, 1, 1, 1),
        block_layout="classic",
        width_multiplier=0.25,
        output_stride=None,
    )
    cfg_a = ModelConfig(**base)
    cfg_b = ModelConfig(**base, stem_space_to_depth=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3), jnp.float32)
    model_a, model_b = build_model(cfg_a), build_model(cfg_b)
    params = model_a.init(jax.random.PRNGKey(1), x, False)
    logits_a = model_a.apply(params, x, False)
    logits_b = model_b.apply(params, x, False)
    np.testing.assert_allclose(
        np.asarray(logits_a), np.asarray(logits_b), atol=1e-4
    )


def test_s2d_config_validation():
    with pytest.raises(ValueError, match="even input dims"):
        ModelConfig(
            num_classes=10,
            input_shape=(101, 101),
            input_channels=3,
            stem_space_to_depth=True,
        )
    with pytest.raises(ValueError, match="conv stems"):
        ModelConfig(
            backbone="vit",
            num_classes=10,
            input_shape=(32, 32),
            input_channels=3,
            output_stride=None,
            stem_space_to_depth=True,
        )


def test_convbn_s2d_guards():
    x = jnp.zeros((1, 8, 8, 3))
    with pytest.raises(ValueError, match="3x3 stride-2"):
        ConvBN(8, 3, stride=1, space_to_depth=True).init(
            jax.random.PRNGKey(0), x, True
        )
    with pytest.raises(ValueError, match="sequence-parallel"):
        ConvBN(
            8, 3, stride=2, space_to_depth=True, spatial_axis_name="sequence"
        ).init(jax.random.PRNGKey(0), x, True)
