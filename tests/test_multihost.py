"""Multi-host helper tests — single-process semantics on the 8-device CPU mesh
(the multi-process path differs only in which rows each process contributes;
jax.make_array_from_process_local_data handles the assembly either way)."""

import jax
import numpy as np

from tensorflowdistributedlearning_tpu.parallel import multihost
from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    make_mesh,
    shard_batch,
)


def test_initialize_is_safe_single_process():
    multihost.initialize()  # no coordinator: must not raise
    info = multihost.process_info()
    assert info["process_count"] == 1
    assert info["process_index"] == 0
    assert info["global_device_count"] >= 8


def test_global_shard_batch_matches_shard_batch():
    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(0, 1, (16, 4, 4, 2)).astype(np.float32),
        "labels": rng.integers(0, 2, (16, 4, 4, 1)).astype(np.float32),
    }
    a = multihost.global_shard_batch(batch, mesh)
    b = shard_batch(batch, mesh)
    for k in batch:
        assert a[k].sharding.spec == b[k].sharding.spec
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a[k])), np.asarray(jax.device_get(b[k]))
        )


def test_global_shard_batch_feeds_train_shapes():
    mesh = make_mesh(8)
    x = np.zeros((8, 2, 2, 1), np.float32)
    arr = multihost.global_shard_batch({"x": x}, mesh)["x"]
    assert arr.shape == (8, 2, 2, 1)
    # each device owns exactly one row
    assert len(arr.sharding.device_set) == 8
