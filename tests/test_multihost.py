"""Multi-host helper tests — single-process semantics on the 8-device CPU mesh
(the multi-process path differs only in which rows each process contributes;
jax.make_array_from_process_local_data handles the assembly either way)."""

import jax
import numpy as np

from tensorflowdistributedlearning_tpu.parallel import multihost
from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    make_mesh,
    shard_batch,
)


def test_initialize_is_safe_single_process():
    multihost.initialize()  # no coordinator: must not raise
    info = multihost.process_info()
    assert info["process_count"] == 1
    assert info["process_index"] == 0
    assert info["global_device_count"] >= 8


def test_global_shard_batch_matches_shard_batch():
    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(0, 1, (16, 4, 4, 2)).astype(np.float32),
        "labels": rng.integers(0, 2, (16, 4, 4, 1)).astype(np.float32),
    }
    a = multihost.global_shard_batch(batch, mesh)
    b = shard_batch(batch, mesh)
    for k in batch:
        assert a[k].sharding.spec == b[k].sharding.spec
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a[k])), np.asarray(jax.device_get(b[k]))
        )


def test_global_shard_batch_feeds_train_shapes():
    mesh = make_mesh(8)
    x = np.zeros((8, 2, 2, 1), np.float32)
    arr = multihost.global_shard_batch({"x": x}, mesh)["x"]
    assert arr.shape == (8, 2, 2, 1)
    # each device owns exactly one row
    assert len(arr.sharding.device_set) == 8


def test_process_local_rows_single_process_is_all_rows():
    mesh = make_mesh(8)
    np.testing.assert_array_equal(
        multihost.process_local_rows(16, mesh), np.arange(16)
    )


def test_shard_replicated_batch_and_fetch_roundtrip():
    mesh = make_mesh(8)
    x = np.random.default_rng(0).normal(size=(16, 3, 3, 1)).astype(np.float32)
    placed = multihost.shard_replicated_batch({"x": x}, mesh)["x"]
    np.testing.assert_array_equal(multihost.fetch(placed), x)


def test_per_process_batch_size_requires_divisibility(monkeypatch):
    import jax as jax_mod

    monkeypatch.setattr(jax_mod, "process_count", lambda: 4)
    assert multihost.per_process_batch_size(64) == 16
    import pytest

    with pytest.raises(ValueError):
        multihost.per_process_batch_size(62)


def test_eval_num_batches_equal_across_processes(monkeypatch):
    """Every process must run the SAME number of eval steps even when the
    round-robin host shards differ in size — the count comes only from global
    quantities, so it is identical on every process by construction."""
    import jax as jax_mod

    monkeypatch.setattr(jax_mod, "process_count", lambda: 4)
    # 13 examples over 4 processes: shards of 4,3,3,3; local batch 2 ⇒ largest
    # shard needs ceil(4/2)=2 steps, so EVERY process runs 2
    assert multihost.eval_num_batches(13, 2) == 2
    # empty-shard edge (3 examples, 4 processes): still at least 1 step each
    assert multihost.eval_num_batches(3, 1) == 1


def test_trainer_batch_assembly_under_mocked_processes(monkeypatch):
    """Simulate the trainer's per-process batch math for P=4 mocked processes:
    host shards are a disjoint cover of the fold, each process draws exactly
    batch/P examples per train step, and one eval pass counts every example
    exactly once across processes with equal step counts."""
    import jax as jax_mod

    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib

    P_COUNT = 4
    ids = [f"ex{i}" for i in range(13)]
    monkeypatch.setattr(jax_mod, "process_count", lambda: P_COUNT)

    global_batch = 8
    local_bs = multihost.per_process_batch_size(global_batch)
    assert local_bs == 2

    shards = []
    for p in range(P_COUNT):
        monkeypatch.setattr(jax_mod, "process_index", lambda p=p: p)
        shards.append(pipeline_lib.host_shard(ids))
    # disjoint cover
    flat = [i for s in shards for i in s]
    assert sorted(flat) == sorted(ids)
    assert len(set(flat)) == len(ids)

    # one training step: each process contributes exactly local_bs of ITS shard
    for shard in shards:
        images = np.arange(len(shard), dtype=np.float32).reshape(-1, 1, 1, 1)
        ds = pipeline_lib.InMemoryDataset(images, images.copy(), list(shard))
        batch = next(pipeline_lib.train_batches(ds, local_bs, seed=0))
        assert batch["images"].shape[0] == local_bs

    # one eval pass: equal step counts; every example counted exactly once
    num = multihost.eval_num_batches(len(ids), local_bs)
    seen = []
    for shard in shards:
        images = np.asarray(
            [float(ids.index(i)) for i in shard], np.float32
        ).reshape(-1, 1, 1, 1)
        ds = pipeline_lib.InMemoryDataset(images, images.copy(), list(shard))
        batches = list(pipeline_lib.eval_batches(ds, local_bs, num_batches=num))
        assert len(batches) == num
        for b in batches:
            seen.extend(
                b["images"][b["valid"].astype(bool), 0, 0, 0].tolist()
            )
    assert sorted(seen) == list(map(float, range(len(ids))))


def test_eval_batches_dataset_smaller_than_batch():
    """Regression (ADVICE r1): n < batch_size used to index out of bounds."""
    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib

    n, bs = 5, 64
    images = np.arange(n, dtype=np.float32).reshape(-1, 1, 1, 1)
    ds = pipeline_lib.InMemoryDataset(images, images.copy(), [str(i) for i in range(n)])
    (batch,) = list(pipeline_lib.eval_batches(ds, bs))
    assert batch["images"].shape[0] == bs
    assert batch["valid"].sum() == n
    np.testing.assert_array_equal(
        batch["images"][: n, 0, 0, 0], np.arange(n, dtype=np.float32)
    )


def test_eval_batches_empty_dataset():
    """Regression (code review r2): an empty host shard (global_n < process_count)
    must still emit the forced number of all-padding batches instead of crashing —
    the other processes are blocked in collective-bearing eval steps."""
    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib

    images = np.zeros((0, 2, 2, 1), np.float32)
    ds = pipeline_lib.InMemoryDataset(images, images.copy(), [])
    batches = list(pipeline_lib.eval_batches(ds, 4, num_batches=2))
    assert len(batches) == 2
    for b in batches:
        assert b["images"].shape == (4, 2, 2, 1)
        assert b["valid"].sum() == 0


def test_imagefolder_eval_batches_empty_dataset(tmp_path):
    from tensorflowdistributedlearning_tpu.data import imagefolder

    ds = imagefolder.ImageFolder(
        str(tmp_path), (2, 2), channels=3, paths=[], labels=np.zeros(0, np.int32),
        class_names=["a"],
    )
    batches = list(imagefolder.eval_batches(ds, 4, num_batches=3))
    assert len(batches) == 3
    for b in batches:
        assert b["images"].shape == (4, 2, 2, 3)
        assert b["labels"].shape == (4,)
        assert b["valid"].sum() == 0
