"""The partitioned suite runner's grouping logic (tools/run_suite.py) — the
structural containment for the XLA:CPU cumulative-compile segfault must cover
every test module exactly once and keep heavy modules spread across groups."""

import glob
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from run_suite import HEAVY, partition  # noqa: E402


def test_partition_covers_all_files_exactly_once():
    files = sorted(
        glob.glob(os.path.join(os.path.dirname(__file__), "test_*.py"))
    )
    groups = partition(files, 4)
    flat = [f for g in groups for f in g]
    assert sorted(flat) == files
    assert len(groups) <= 4


def test_partition_spreads_heavy_modules():
    files = [f"tests/{name}" for name in HEAVY] + [
        f"tests/test_light_{i}.py" for i in range(6)
    ]
    groups = partition(files, 4)
    # no group holds more than ceil(len(HEAVY)/4) heavy modules
    bound = -(-len(HEAVY) // 4)
    for g in groups:
        heavy_in_g = [f for f in g if os.path.basename(f) in HEAVY]
        assert len(heavy_in_g) <= bound


def test_heavy_list_names_real_modules():
    here = os.path.dirname(__file__)
    for name in HEAVY:
        assert os.path.exists(os.path.join(here, name)), name
