"""Quantized serving path: export recipes, manifest contract, engine, gate.

The contracts under test are the ones the promotion pipeline will be operated
by: the manifest ``quantization`` section round-trips and rejects corruption
at read time (never at serve time), legacy manifests pin to the float32 path,
every precision loads from the manifest alone and serves recompile-free
through the bucket ladder, quantized artifacts are genuinely small at rest
(int8 constants stay int8 in the serialized graph — a trace-time eager
upcast once silently doubled them), the engine's pad scratch buffer reuses
allocation without leaking stale rows between dispatches, and quantize-check
passes honest candidates, fails broken ones, and refuses mismatched pairs.
"""

import json
import os

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.obs import RecompileDetector, Telemetry
from tensorflowdistributedlearning_tpu.serve import (
    InferenceEngine,
    run_quant_check,
)
from tensorflowdistributedlearning_tpu.train import quantize
from tensorflowdistributedlearning_tpu.train import serving as serving_lib

FEATURES = 8
HIDDEN = 16
CLASSES = 4


def make_params(seed=0, scale=0.3):
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "dense1": {
            "kernel": jax.random.normal(k1, (FEATURES, HIDDEN)) * scale,
            "bias": jnp.zeros((HIDDEN,)),
        },
        "dense2": {"kernel": jax.random.normal(k2, (HIDDEN, CLASSES)) * scale},
    }


def make_serve(params, serving_dtype):
    """The trainers' serving-closure shape, built from a raw params tree —
    quantize once, dequantize inside the traced graph, f32 wire contract."""
    import jax
    import jax.numpy as jnp

    qtree, section = quantize.quantize_pytree(params, serving_dtype)
    act = quantize.compute_dtype(serving_dtype)

    def serve(x):
        p = quantize.dequantize_pytree(qtree, act)
        h = jnp.maximum(
            x.astype(act) @ p["dense1"]["kernel"] + p["dense1"]["bias"], 0
        )
        logits = h @ p["dense2"]["kernel"]
        out = {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "class": jnp.argmax(logits, axis=-1),
        }
        return quantize.cast_outputs_float32(out)

    serve.quantization = section
    return serve


def export_precision(params, serving_dtype, directory):
    serve = make_serve(params, serving_dtype)
    serving_lib.export_serving_artifact(
        serve, (1, FEATURES), str(directory), quantization=serve.quantization
    )
    return str(directory)


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """One f32/bf16/int8 artifact trio from the same params — the module's
    shared fixture (export is the slow part)."""
    root = tmp_path_factory.mktemp("quant_artifacts")
    params = make_params()
    return {
        dt: export_precision(params, dt, root / dt)
        for dt in ("float32", "bfloat16", "int8")
    }


# -- quantize library --------------------------------------------------------


def test_int8_per_channel_roundtrip():
    """Per-channel symmetric int8: dequantized kernels stay within one scale
    step of the original, channel-wise (the per-CHANNEL part is what keeps
    small-magnitude channels accurate next to large ones)."""
    rng = np.random.default_rng(0)
    # channels with wildly different magnitudes — per-tensor scaling would
    # crush the small ones to zero
    w = rng.normal(0, 1, (8, 6)).astype(np.float32) * np.logspace(
        -3, 1, 6, dtype=np.float32
    )
    tree = {"layer": {"kernel": w}}
    qtree, section = quantize.quantize_pytree(tree, "int8")
    rec = qtree["layer"]["kernel"]
    assert rec["q"].dtype == np.int8
    assert rec["scale"].shape == (6,)
    deq = np.asarray(
        quantize.dequantize_pytree(qtree)["layer"]["kernel"], np.float32
    )
    # error bounded by half a quantization step per channel (bf16 dequant
    # adds a relative ~0.4% on top)
    step = np.abs(w).max(axis=0) / 127.0
    assert np.all(np.abs(deq - w) <= step * 0.55 + np.abs(w) * 0.01)
    assert section["scheme"] == "per-channel-symmetric"
    assert "layer/kernel" in section["scales"]


def test_int8_zero_channel_safe():
    tree = {"m": {"kernel": np.zeros((4, 3), np.float32)}}
    qtree, section = quantize.quantize_pytree(tree, "int8")
    assert np.all(np.asarray(qtree["m"]["kernel"]["scale"]) == 1.0)
    deq = np.asarray(quantize.dequantize_pytree(qtree)["m"]["kernel"])
    assert np.all(deq == 0)
    quantize.validate_quantization(section)  # scale 1.0 is valid metadata


def test_bf16_and_float32_recipes():
    import jax.numpy as jnp

    tree = make_params()
    b16, section = quantize.quantize_pytree(tree, "bfloat16")
    assert b16["dense1"]["kernel"].dtype == jnp.bfloat16
    assert section["dtype"] == "bfloat16" and "scales" not in section
    f32, section = quantize.quantize_pytree(tree, "float32")
    # float32 is the identity recipe: the very same leaves, zero graph drift
    assert f32["dense1"]["kernel"] is tree["dense1"]["kernel"]
    assert section["dtype"] == "float32"
    with pytest.raises(ValueError, match="serving spec"):
        quantize.quantize_pytree(tree, "fp8")


def test_serving_spec_axis():
    """The (storage, compute) spec axis: every legacy dtype keeps its
    historical arithmetic; int8-compute is int8 storage + int8 arithmetic
    and produces BYTE-IDENTICAL quantized leaves to int8 storage (same
    export recipe — only the traced graph differs)."""
    assert quantize.parse_serving_spec("float32") == ("float32", "float32")
    assert quantize.parse_serving_spec("bfloat16") == ("bfloat16", "bfloat16")
    assert quantize.parse_serving_spec("int8") == ("int8", "bfloat16")
    assert quantize.parse_serving_spec("int8-compute") == ("int8", "int8")
    assert quantize.default_compute_dtype("int8") == "bfloat16"
    with pytest.raises(ValueError, match="serving spec"):
        quantize.check_serving_spec("int4-compute")
    tree = make_params()
    q_store, s_store = quantize.quantize_pytree(tree, "int8")
    q_comp, s_comp = quantize.quantize_pytree(tree, "int8-compute")
    assert s_store["dtype"] == s_comp["dtype"] == "int8"
    assert s_store["compute_dtype"] == "bfloat16"
    assert s_comp["compute_dtype"] == "int8"
    np.testing.assert_array_equal(
        np.asarray(q_store["dense1"]["kernel"]["q"]),
        np.asarray(q_comp["dense1"]["kernel"]["q"]),
    )
    # invalid pairings die in validation, not downstream
    bad = dict(s_comp, compute_dtype="float32")
    with pytest.raises(ValueError, match="compute_dtype"):
        quantize.validate_quantization(bad)


def test_int8_only_quantizes_kernels():
    """Biases/BN vectors/batch_stats cast to bf16; integer leaves pass
    through untouched (a step counter must not become bf16)."""
    import jax.numpy as jnp

    tree = {
        "bn": {"scale": np.ones(4, np.float32), "kernel": np.ones(3, np.float32)},
        "count": np.asarray(7, np.int32),
        "conv": {"kernel": np.ones((3, 3, 2, 4), np.float32)},
    }
    qtree, _ = quantize.quantize_pytree(tree, "int8")
    assert qtree["bn"]["scale"].dtype == jnp.bfloat16
    # a 1-D leaf NAMED kernel is not a matmul weight — bf16, not int8
    assert qtree["bn"]["kernel"].dtype == jnp.bfloat16
    assert qtree["count"].dtype == np.int32
    assert qtree["conv"]["kernel"]["q"].dtype == np.int8
    assert qtree["conv"]["kernel"]["scale"].shape == (4,)


def test_frozendict_trees_quantize():
    """flax FrozenDict params (the declared TrainState leaf container on
    older flax / flax_return_frozendict=True) must recurse like plain dicts
    — matching `dict` alone passed the whole frozen tree through untouched
    while the manifest still claimed int8."""
    from flax.core import FrozenDict
    import jax.numpy as jnp

    tree = FrozenDict(make_params())
    qtree, section = quantize.quantize_pytree(tree, "int8")
    assert section["scales"], "no kernels quantized — FrozenDict fell through"
    assert qtree["dense1"]["kernel"]["q"].dtype == np.int8
    restored = quantize.dequantize_pytree(qtree)
    assert restored["dense1"]["kernel"].dtype == jnp.bfloat16


def test_fingerprint_identity():
    a, b = make_params(seed=0), make_params(seed=1)
    fp_a, fp_a2 = quantize.fingerprint_tree(a), quantize.fingerprint_tree(
        make_params(seed=0)
    )
    assert fp_a == fp_a2 and fp_a.startswith("sha256:")
    assert fp_a != quantize.fingerprint_tree(b)
    # the section fingerprints the SOURCE tree: identical across recipes
    sections = [
        quantize.quantize_pytree(a, dt)[1]["source_fingerprint"]
        for dt in ("float32", "bfloat16", "int8")
    ]
    assert len(set(sections)) == 1


# -- manifest contract -------------------------------------------------------


def test_manifest_quantization_roundtrip(artifacts):
    for dtype, directory in artifacts.items():
        manifest = serving_lib.read_manifest(directory)
        q = manifest["quantization"]
        assert q["dtype"] == dtype
        assert q["source_fingerprint"].startswith("sha256:")
        if dtype == "int8":
            assert set(q["scales"]) == {"dense1/kernel", "dense2/kernel"}
            for meta in q["scales"].values():
                assert meta["scale_min"] > 0
                assert meta["scale_min"] <= meta["scale_max"]


def test_legacy_manifest_pins_float32_path(tmp_path):
    """A pre-quantization manifest (no input_dtype, no quantization section)
    must load exactly as before: float32 inputs, no validation error — the
    historical contract, applied in ONE place (read_manifest)."""
    serve = make_serve(make_params(), "float32")
    d = str(tmp_path / "legacy")
    serving_lib.export_serving_artifact(serve, (1, FEATURES), d)
    manifest_path = os.path.join(d, "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest.pop("input_dtype", None)
    manifest.pop("quantization", None)
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    assert serving_lib.read_manifest(d)["input_dtype"] == "float32"
    engine = InferenceEngine.from_artifact(d, buckets=(1, 4))
    assert engine.input_dtype == np.dtype("float32")
    assert engine.quantization is None
    out = engine.infer(np.zeros((2, FEATURES), np.float32))
    assert out["probabilities"].shape == (2, CLASSES)


@pytest.mark.parametrize(
    "corruption",
    [
        {"dtype": "int4"},
        {"dtype": "int8", "scales": "oops"},
        {"dtype": "int8", "scales": {}},
        {"dtype": "int8", "scales": {"k": {"shape": [0], "scale_min": 1.0, "scale_max": 1.0}}},
        {"dtype": "int8", "scales": {"k": {"shape": [4], "scale_min": -1.0, "scale_max": 1.0}}},
        {"dtype": "int8", "scales": {"k": {"shape": [4], "scale_min": float("nan"), "scale_max": 1.0}}},
        {"dtype": "int8", "scales": {"k": {"shape": [4], "scale_min": 2.0, "scale_max": 1.0}}},
        {"dtype": "float32", "scales": {"k": {}}},
    ],
)
def test_corrupt_quantization_rejected(tmp_path, corruption, artifacts):
    """Corrupt scale metadata fails at READ time with a pointed error — an
    artifact whose self-description lies must never reach the request path."""
    import shutil

    d = str(tmp_path / "corrupt")
    shutil.copytree(artifacts["int8"], d)
    manifest_path = os.path.join(d, "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest["quantization"] = corruption
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="quantization"):
        serving_lib.read_manifest(d)
    with pytest.raises(ValueError, match="quantization"):
        InferenceEngine.from_artifact(d)
    with pytest.raises(ValueError, match="quantization"):
        serving_lib.load_serving_artifact(d)


def test_export_rejects_corrupt_section(tmp_path):
    serve = make_serve(make_params(), "float32")
    with pytest.raises(ValueError, match="quantization.dtype"):
        serving_lib.export_serving_artifact(
            serve, (1, FEATURES), str(tmp_path / "x"),
            quantization={"dtype": "int3"},
        )


# -- per-precision execution through the engine ------------------------------


def test_every_precision_loads_and_serves_from_manifest_alone(artifacts, rng):
    x = rng.normal(0, 1, (5, FEATURES)).astype(np.float32)
    ref = None
    for dtype, directory in artifacts.items():
        engine = InferenceEngine.from_artifact(directory, buckets=(1, 4, 8))
        assert engine.quantization["dtype"] == dtype
        out = engine.infer(x)
        assert out["probabilities"].dtype == np.float32  # wire contract
        assert out["probabilities"].shape == (5, CLASSES)
        if ref is None:
            ref = out
        else:
            np.testing.assert_allclose(
                out["probabilities"], ref["probabilities"], atol=0.05
            )


def test_zero_post_warmup_recompiles_per_precision(artifacts, rng):
    """The bucket-ladder contract holds at EVERY precision: after warmup, no
    request batch size compiles anything."""
    for directory in artifacts.values():
        detector = RecompileDetector().attach()
        try:
            engine = InferenceEngine.from_artifact(directory, buckets=(1, 4, 8))
            engine.warmup()
            assert detector.compile_count >= 1, "detector saw no warmup compiles"
            detector.mark_warm()
            for n in range(1, 9):
                engine.infer(rng.normal(0, 1, (n, FEATURES)).astype(np.float32))
            assert detector.post_warmup_count == 0
        finally:
            detector.detach()


def test_quantized_artifacts_small_at_rest(tmp_path):
    """bf16 ~halves and int8 ~quarters the weight bytes in the serialized
    graph. Regression pin for the trace-time eager upcast that once baked
    int8 weights as bf16 constants (numpy .astype during tracing). Needs
    weights big enough that StableHLO framing overhead stops dominating."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    params = {
        "dense1": {
            "kernel": jax.random.normal(k1, (64, 512)) * 0.1,
            "bias": jnp.zeros((512,)),
        },
        "dense2": {"kernel": jax.random.normal(k2, (512, CLASSES)) * 0.1},
    }
    sizes = {}
    for dt in ("float32", "bfloat16", "int8"):
        qtree, section = quantize.quantize_pytree(params, dt)
        act = quantize.compute_dtype(dt)

        def serve(x, qtree=qtree, act=act):
            p = quantize.dequantize_pytree(qtree, act)
            h = jnp.maximum(
                x.astype(act) @ p["dense1"]["kernel"] + p["dense1"]["bias"], 0
            )
            return quantize.cast_outputs_float32(
                {"y": h @ p["dense2"]["kernel"]}
            )

        d = str(tmp_path / dt)
        serving_lib.export_serving_artifact(
            serve, (1, 64), d, quantization=section
        )
        sizes[dt] = os.path.getsize(os.path.join(d, "serving.stablehlo"))
    # ~34K weights: f32 ≈ 136KB of constants; framing is a few KB
    assert sizes["bfloat16"] < sizes["float32"] * 0.6
    assert sizes["int8"] < sizes["float32"] * 0.35


# -- engine scratch pad ------------------------------------------------------


def test_scratch_pad_reused_and_stale_rows_zeroed(rng):
    import jax
    import jax.numpy as jnp

    w = jax.random.normal(jax.random.PRNGKey(0), (FEATURES, CLASSES)) * 0.3

    @jax.jit
    def fn(x):
        # "sum" couples rows across the batch: any stale (non-zero) padding
        # row left in the scratch buffer changes every output row
        total = jnp.broadcast_to(jnp.sum(jnp.abs(x)), (x.shape[0], 1))
        return {"sum": total, "y": x @ w}

    engine = InferenceEngine(fn, (FEATURES,), buckets=(8,))
    big = rng.normal(0, 1, (7, FEATURES)).astype(np.float32)
    small = rng.normal(0, 1, (2, FEATURES)).astype(np.float32)
    engine.infer(big)
    buf_after_big = engine._scratch.bufs[8]
    out = engine.infer(small)
    # same buffer object (no per-dispatch allocation) ...
    assert engine._scratch.bufs[8] is buf_after_big
    # ... and rows 2..6 of the previous dispatch were zeroed: the padded
    # forward sums ONLY the two live rows
    np.testing.assert_allclose(
        out["sum"], np.full((2, 1), np.abs(small).sum()), rtol=1e-5
    )
    np.testing.assert_allclose(out["y"], small @ np.asarray(w), rtol=1e-5)


def test_padding_waste_accounting(rng):
    engine = InferenceEngine(lambda x: {"y": np.asarray(x)}, (FEATURES,),
                             buckets=(4, 8))
    for n in (2, 4, 6):
        engine.infer(rng.normal(0, 1, (n, FEATURES)).astype(np.float32))
    # bucket 4: hits 2 (n=2, n=4), examples 6 -> waste 1 - 6/8 = 0.25
    # bucket 8: hits 1 (n=6),      examples 6 -> waste 1 - 6/8 = 0.25
    assert engine.padding_waste == {4: 0.25, 8: 0.25}
    assert engine.bucket_hits == {4: 2, 8: 1}


def test_serve_window_carries_padding_waste_and_dtype(artifacts, tmp_path, rng):
    from tensorflowdistributedlearning_tpu.obs import read_ledger
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir
    from tensorflowdistributedlearning_tpu.serve import (
        MicroBatcher,
        ServingServer,
    )

    workdir = str(tmp_path / "serve_run")
    tel = Telemetry(workdir, run_info={"kind": "serve"})
    engine = InferenceEngine.from_artifact(
        artifacts["bfloat16"], buckets=(1, 4), registry=tel.registry
    )
    engine.warmup(telemetry=tel)
    batcher = MicroBatcher(engine, max_wait_ms=1, max_queue=16)
    server = ServingServer(engine, batcher, port=0, telemetry=tel,
                           window_secs=0).start()
    try:
        engine.infer(rng.normal(0, 1, (3, FEATURES)).astype(np.float32))
    finally:
        server.shutdown()
    events = read_ledger(workdir)
    warm = next(e for e in events if e["event"] == "serve_warmup")
    assert warm["serving_dtype"] == "bfloat16"
    window = [e for e in events if e["event"] == "serve_window"][-1]
    assert window["serving_dtype"] == "bfloat16"
    assert window["padding_waste"] == {"4": 0.25}
    rendered = report_workdir(workdir)
    assert "serving [bfloat16]" in rendered
    assert "padding waste" in rendered


# -- quantize-check ----------------------------------------------------------


def test_quant_check_passes_honest_candidates(artifacts, tmp_path):
    tel = Telemetry(str(tmp_path / "ledger"), run_info={"kind": "quant_check"})
    try:
        for dtype in ("bfloat16", "int8"):
            result = run_quant_check(
                artifacts["float32"], artifacts[dtype], telemetry=tel
            )
            assert result["passed"], result["failures"]
            assert result["dtype"] == dtype
            assert result["fingerprint_match"] is True
            assert result["outputs"]["probabilities"]["kind"] == "float"
            assert result["outputs"]["class"]["kind"] == "integer"
    finally:
        tel.close()
    from tensorflowdistributedlearning_tpu.obs import read_ledger

    events = read_ledger(str(tmp_path / "ledger"))
    checks = [e for e in events if e["event"] == "quant_check"]
    assert len(checks) == 2 and all(e["passed"] for e in checks)


def test_quant_check_fails_broken_candidate(artifacts, tmp_path):
    """A candidate quantized from DIFFERENT weights must fail twice over:
    fingerprint mismatch up front, and (when forced past it) output deltas
    beyond any budget."""
    broken_dir = export_precision(
        make_params(seed=9), "bfloat16", tmp_path / "broken"
    )
    result = run_quant_check(artifacts["float32"], broken_dir)
    assert not result["passed"]
    assert any("fingerprint" in f for f in result["failures"])
    # numerics are skipped on a refused pairing — nothing misleading recorded
    assert result["outputs"] == {}
    forced = run_quant_check(
        artifacts["float32"], broken_dir, allow_fingerprint_mismatch=True
    )
    assert not forced["passed"]
    assert any("delta" in f or "disagree" in f for f in forced["failures"])


def make_mask_serve(params, serving_dtype):
    """The segmentation trainers' output shape: a float {0,1} mask thresholded
    from probabilities — the output kind where a single boundary-pixel flip
    makes max|delta| exactly 1.0."""
    import jax
    import jax.numpy as jnp

    qtree, section = quantize.quantize_pytree(params, serving_dtype)
    act = quantize.compute_dtype(serving_dtype)

    def serve(x):
        p = quantize.dequantize_pytree(qtree, act)
        h = jnp.maximum(
            x.astype(act) @ p["dense1"]["kernel"] + p["dense1"]["bias"], 0
        )
        prob = jax.nn.sigmoid(h @ p["dense2"]["kernel"])
        out = {
            "probabilities": prob,
            "mask": (prob > 0.5).astype(act),
        }
        return quantize.cast_outputs_float32(out)

    serve.quantization = section
    return serve


def export_mask_precision(params, serving_dtype, directory):
    serve = make_mask_serve(params, serving_dtype)
    serving_lib.export_serving_artifact(
        serve, (1, FEATURES), str(directory), quantization=serve.quantization
    )
    return str(directory)


def test_quant_check_mask_gates_on_iou_not_max_delta(tmp_path):
    """Binary mask outputs gate on IoU/disagreement, NOT the float budgets:
    quantization inevitably flips some near-threshold pixels, making the
    mask's max|delta| exactly 1.0 — an honest int8 segmentation artifact with
    near-perfect IoU must still pass (caught live on the real seg model:
    IoU 0.9975 yet 'max|delta| 1.0 > 0.15' failed the gate)."""
    params = make_params(seed=4, scale=1.0)  # spread probs across 0.5
    ref = export_mask_precision(params, "float32", tmp_path / "f32")
    cand = export_mask_precision(params, "int8", tmp_path / "int8")
    result = run_quant_check(ref, cand, batch_size=64)
    mask = result["outputs"]["mask"]
    assert mask["kind"] == "binary"
    # the premise: at least one pixel flipped, so the float budget would fail
    assert mask["max_abs_delta"] == 1.0
    assert mask["iou"] >= 0.95
    assert result["passed"], result["failures"]
    # a mask from different weights still fails, on the mask's own budgets
    broken = export_mask_precision(
        make_params(seed=11, scale=1.0), "int8", tmp_path / "broken"
    )
    forced = run_quant_check(
        ref, broken, batch_size=64, allow_fingerprint_mismatch=True
    )
    assert not forced["passed"]
    assert any("IoU" in f or "mask disagreement" in f
               for f in forced["failures"])


def test_quant_check_threshold_overrides(artifacts):
    strict = run_quant_check(
        artifacts["float32"], artifacts["int8"],
        thresholds={"max_abs_delta": 1e-9, "mean_abs_delta": 1e-9},
    )
    assert not strict["passed"]
    assert any("max|delta|" in f for f in strict["failures"])


def test_quant_check_pinned_batch_deterministic(artifacts):
    a = run_quant_check(artifacts["float32"], artifacts["int8"], seed=3)
    b = run_quant_check(artifacts["float32"], artifacts["int8"], seed=3)
    assert a["outputs"] == b["outputs"]


def test_report_renders_quant_check(artifacts, tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    workdir = str(tmp_path / "ledger")
    tel = Telemetry(workdir, run_info={"kind": "quant_check"})
    try:
        run_quant_check(artifacts["float32"], artifacts["int8"], telemetry=tel)
    finally:
        tel.close()
    rendered = report_workdir(workdir)
    assert "quantize-check [int8] PASSED" in rendered


# -- CLI ---------------------------------------------------------------------


def test_cli_quantize_check(artifacts, tmp_path, capsys):
    from tensorflowdistributedlearning_tpu.cli import main

    rc = main([
        "quantize-check",
        "--reference-dir", artifacts["float32"],
        "--candidate-dir", artifacts["int8"],
        "--workdir", str(tmp_path / "wd"),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["passed"] and out["dtype"] == "int8"
    # the gate IS the exit status: an impossible budget must exit 1
    rc = main([
        "quantize-check",
        "--reference-dir", artifacts["float32"],
        "--candidate-dir", artifacts["int8"],
        "--workdir", str(tmp_path / "wd2"),
        "--max-abs-delta", "1e-12",
    ])
    assert rc == 1


def test_cli_train_serving_dtype_flag():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["train", "--data-dir", "d", "--model-dir", "m"]
    )
    assert args.serving_dtype == "float32"
    args = build_parser().parse_args(
        ["train", "--data-dir", "d", "--model-dir", "m",
         "--export-serving", "--serving-dtype", "int8"]
    )
    assert args.serving_dtype == "int8"
    with pytest.raises(SystemExit):
        build_parser().parse_args(
            ["train", "--data-dir", "d", "--model-dir", "m",
             "--serving-dtype", "fp4"]
        )


# -- int8-compute: real int8 arithmetic on the serve path ---------------------

COMPUTE_FEATURES = 64
COMPUTE_HIDDEN = 512


def make_flax_net():
    """A flax module (not raw matmuls): int8-compute routes through the
    nn.intercept_methods hook, so the closure must apply real nn.Dense."""
    from flax import linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(COMPUTE_HIDDEN, name="dense1")(x)
            x = nn.relu(x)
            return nn.Dense(CLASSES, name="dense2")(x)

    return Net()


def export_compute_precision(params, net, spec, directory):
    """The trainers' serving-closure shape for the full spec axis: quantize
    once, trace under int8_intercept when the section says int8 compute."""
    import jax
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.ops import quant_kernels

    qtree, section = quantize.quantize_pytree(params, spec)
    act = quantize.compute_dtype(spec)
    int8c = section.get("compute_dtype") == "int8"

    def serve(x):
        p = (
            params
            if spec == "float32"
            else quantize.dequantize_pytree(qtree, act)
        )
        xx = x.astype(act)
        if int8c:
            with quant_kernels.int8_intercept(qtree, act):
                logits = net.apply({"params": p}, xx)
        else:
            logits = net.apply({"params": p}, xx)
        out = {"probabilities": jax.nn.softmax(logits.astype(jnp.float32), -1)}
        return quantize.cast_outputs_float32(out)

    serving_lib.export_serving_artifact(
        serve, (1, COMPUTE_FEATURES), str(directory), quantization=section
    )
    return str(directory)


@pytest.fixture(scope="module")
def compute_artifacts(tmp_path_factory):
    """f32 / int8-store / int8-compute artifacts from the same flax params —
    weights big enough that at-rest sizes mean something."""
    import jax

    root = tmp_path_factory.mktemp("compute_artifacts")
    net = make_flax_net()
    x0 = np.zeros((1, COMPUTE_FEATURES), np.float32)
    params = net.init(jax.random.PRNGKey(3), x0)["params"]
    return {
        spec: export_compute_precision(params, net, spec, root / spec)
        for spec in ("float32", "int8", "int8-compute")
    }


def test_int8_compute_manifest_roundtrip(compute_artifacts):
    expected = {"float32": "float32", "int8": "bfloat16", "int8-compute": "int8"}
    for spec, directory in compute_artifacts.items():
        q = serving_lib.read_manifest(directory)["quantization"]
        assert q["compute_dtype"] == expected[spec], spec
    # legacy manifests (no compute_dtype) get the historical arithmetic
    # filled in at the ONE defaulting site
    import shutil

    d = compute_artifacts["int8"]
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["quantization"].pop("compute_dtype") == "bfloat16"
    legacy = d + "-legacy"
    shutil.copytree(d, legacy, dirs_exist_ok=True)
    with open(os.path.join(legacy, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    q = serving_lib.read_manifest(legacy)["quantization"]
    assert q["compute_dtype"] == "bfloat16"


def test_int8_compute_serves_recompile_free(compute_artifacts, rng):
    """The bucket-ladder contract extends to the quant-kernel graph: warmup
    compiles the ladder, then NO request batch size compiles anything — and
    the engine self-describes the arithmetic via compute_dtype."""
    detector = RecompileDetector().attach()
    try:
        engine = InferenceEngine.from_artifact(
            compute_artifacts["int8-compute"], buckets=(1, 4, 8)
        )
        assert engine.compute_dtype == "int8"
        engine.warmup()
        assert detector.compile_count >= 1
        detector.mark_warm()
        for n in range(1, 9):
            engine.infer(
                rng.normal(0, 1, (n, COMPUTE_FEATURES)).astype(np.float32)
            )
        assert detector.post_warmup_count == 0
    finally:
        detector.detach()
    # store-only int8 keeps its historical self-description
    store = InferenceEngine.from_artifact(compute_artifacts["int8"], buckets=(1,))
    assert store.compute_dtype == "bfloat16"


def test_int8_compute_outputs_track_f32(compute_artifacts, rng):
    x = rng.normal(0, 1, (5, COMPUTE_FEATURES)).astype(np.float32)
    ref = InferenceEngine.from_artifact(
        compute_artifacts["float32"], buckets=(8,)
    ).infer(x)
    got = InferenceEngine.from_artifact(
        compute_artifacts["int8-compute"], buckets=(8,)
    ).infer(x)
    assert got["probabilities"].dtype == np.float32  # wire contract holds
    np.testing.assert_allclose(
        got["probabilities"], ref["probabilities"], atol=0.05
    )


def test_int8_compute_warmup_ledger_stamps_compute_dtype(
    compute_artifacts, tmp_path
):
    from tensorflowdistributedlearning_tpu.obs import read_ledger

    workdir = str(tmp_path / "ledger")
    tel = Telemetry(workdir, run_info={"kind": "serve"})
    try:
        engine = InferenceEngine.from_artifact(
            compute_artifacts["int8-compute"], buckets=(1,)
        )
        engine.warmup(telemetry=tel)
    finally:
        tel.close()
    warm = next(
        e for e in read_ledger(workdir) if e["event"] == "serve_warmup"
    )
    assert warm["serving_dtype"] == "int8"
    assert warm["compute_dtype"] == "int8"


def test_int8_compute_artifact_small_at_rest(compute_artifacts):
    """int8-compute must keep int8-store's at-rest economics: the quant
    kernels consume the int8 records DIRECTLY (jnp.asarray before any
    astype), so no trace-time eager upcast re-embeds f32 constants."""
    sizes = {
        spec: os.path.getsize(os.path.join(d, "serving.stablehlo"))
        for spec, d in compute_artifacts.items()
    }
    assert sizes["int8-compute"] < sizes["float32"] * 0.35
    assert sizes["int8-compute"] < sizes["int8"] * 1.15


def test_quant_check_int8_compute_budget(compute_artifacts):
    """The gate compares int8-compute output against the F32 REFERENCE
    artifact — the real serving arithmetic, not the dequantize-f32 twin —
    under the wider int8-compute budget keyed off the manifest pair."""
    from tensorflowdistributedlearning_tpu.serve.quant_check import budget_key

    assert budget_key({"dtype": "int8", "compute_dtype": "int8"}) == "int8-compute"
    assert budget_key({"dtype": "int8", "compute_dtype": "bfloat16"}) == "int8"
    assert budget_key({"dtype": "int8"}) == "int8"
    assert budget_key(None) == "float32"
    result = run_quant_check(
        compute_artifacts["float32"], compute_artifacts["int8-compute"]
    )
    assert result["passed"], result["failures"]
    assert result["dtype"] == "int8-compute"
    assert result["fingerprint_match"] is True


def test_scratch_dtype_follows_input_dtype(rng):
    """Satellite of the int8-compute path: the pad scratch allocates in the
    engine's WIRE dtype. An int8-input artifact must not get a silent f32
    scratch upcast (4x the pad traffic and a dtype mismatch at dispatch)."""
    engine = InferenceEngine(
        lambda x: {"y": np.asarray(x, np.float32) * 2.0},
        (FEATURES,),
        buckets=(4,),
        input_dtype="int8",
    )
    x = (rng.integers(-5, 5, (2, FEATURES))).astype(np.int8)
    out = engine.infer(x)
    assert engine._scratch.bufs[4].dtype == np.int8
    np.testing.assert_allclose(out["y"], x.astype(np.float32) * 2.0)
    # rebinding the wire dtype REALLOCATES rather than serving stale-dtype rows
    engine.input_dtype = np.dtype("float32")
    engine.infer(rng.normal(0, 1, (2, FEATURES)).astype(np.float32))
    assert engine._scratch.bufs[4].dtype == np.float32


def test_cli_serving_dtype_accepts_int8_compute():
    from tensorflowdistributedlearning_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["train", "--data-dir", "d", "--model-dir", "m",
         "--export-serving", "--serving-dtype", "int8-compute"]
    )
    assert args.serving_dtype == "int8-compute"
