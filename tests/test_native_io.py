"""Native C++ PNG decoder tests: builds on this machine, matches PIL bit-for-bit
(both divide the same uint8 by 255), handles errors, and releases the GIL enough to
scale with threads."""

import os

import numpy as np
import pytest
from PIL import Image

from tensorflowdistributedlearning_tpu.native import decode_png_batch, native_available
from tensorflowdistributedlearning_tpu.native.loader import _decode_pil


@pytest.fixture(scope="module")
def png_files(tmp_path_factory):
    root = tmp_path_factory.mktemp("pngs")
    rng = np.random.default_rng(7)
    paths = []
    for i in range(12):
        arr = rng.integers(0, 256, (24, 24), dtype=np.uint8)
        p = str(root / f"g{i}.png")
        Image.fromarray(arr).save(p)
        paths.append(p)
    # one RGB file for the luma-conversion path
    rgb = rng.integers(0, 256, (24, 24, 3), dtype=np.uint8)
    rgb_path = str(root / "rgb.png")
    Image.fromarray(rgb).save(rgb_path)
    return paths, rgb_path


def test_native_builds_here():
    # this image ships g++ and libpng; the build must succeed, not silently fall back
    assert native_available()


def test_native_matches_pil_grayscale(png_files):
    paths, _ = png_files
    native = decode_png_batch(paths, 24, 24, channels=1)
    pil = _decode_pil(paths, 24, 24, channels=1)
    np.testing.assert_array_equal(native, pil)
    assert native.dtype == np.float32
    assert native.min() >= 0.0 and native.max() <= 1.0


def test_native_rgb_to_gray_close_to_pil(png_files):
    _, rgb_path = png_files
    native = decode_png_batch([rgb_path], 24, 24, channels=1)
    pil = _decode_pil([rgb_path], 24, 24, channels=1)
    # PIL rounds the luma to uint8 before /255; the native path keeps float precision
    assert np.abs(native - pil).max() < 2.0 / 255.0


def test_gray_broadcast_to_three_channels(png_files):
    paths, _ = png_files
    out = decode_png_batch(paths[:2], 24, 24, channels=3)
    np.testing.assert_array_equal(out[..., 0], out[..., 1])
    np.testing.assert_array_equal(out[..., 0], out[..., 2])


def test_wrong_shape_raises(png_files):
    paths, _ = png_files
    with pytest.raises(ValueError, match="decode failed"):
        decode_png_batch(paths[:1], 32, 32, channels=1)


def test_missing_file_raises(tmp_path):
    with pytest.raises(ValueError, match="decode failed"):
        decode_png_batch([str(tmp_path / "nope.png")], 8, 8)


def test_empty_input():
    out = decode_png_batch([], 8, 8)
    assert out.shape == (0, 8, 8, 1)


def test_interlaced_png_decodes_correctly(tmp_path):
    # Adam7-interlaced files must match PIL (png_read_image runs all passes)
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 256, (24, 24), dtype=np.uint8)
    p = str(tmp_path / "interlaced.png")
    Image.fromarray(arr).save(p, interlace=True)
    native = decode_png_batch([p], 24, 24, channels=1)
    pil = _decode_pil([p], 24, 24, channels=1)
    np.testing.assert_array_equal(native, pil)


def test_multithreaded_decode_consistent(png_files):
    paths, _ = png_files
    one = decode_png_batch(paths, 24, 24, n_threads=1)
    many = decode_png_batch(paths, 24, 24, n_threads=8)
    np.testing.assert_array_equal(one, many)


# ---------------------------------------------------------------------------
# decode_image_batch: PNG/JPEG at any size, antialiased bilinear resize
# ---------------------------------------------------------------------------


@pytest.fixture()
def mixed_files(tmp_path):
    from tensorflowdistributedlearning_tpu.native import decode_image_batch  # noqa: F401

    rng = np.random.default_rng(7)
    paths = []
    for i, (h, w) in enumerate([(90, 120), (64, 64), (300, 201), (17, 33)]):
        arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        if i % 2:
            p = str(tmp_path / f"im{i}.jpg")
            Image.fromarray(arr).save(p, quality=98)
        else:
            p = str(tmp_path / f"im{i}.png")
            Image.fromarray(arr).save(p)
        paths.append(p)
    return paths


def test_decode_image_batch_matches_pil_resize(mixed_files):
    """The ImageNet-class decode path (variable-size JPEG+PNG, triangle-filter
    bilinear) agrees with PIL's convert+resize to within uint8 rounding."""
    from tensorflowdistributedlearning_tpu.native import decode_image_batch
    from tensorflowdistributedlearning_tpu.native.loader import _decode_pil_resize

    out = decode_image_batch(mixed_files, 32, 48, channels=3)
    ref = _decode_pil_resize(mixed_files, 32, 48, 3)
    assert out.shape == (4, 32, 48, 3)
    assert np.abs(out - ref).max() < 0.02  # PIL rounds to uint8 per stage


def test_decode_image_batch_gray(mixed_files):
    from tensorflowdistributedlearning_tpu.native import decode_image_batch
    from tensorflowdistributedlearning_tpu.native.loader import _decode_pil_resize

    out = decode_image_batch(mixed_files, 24, 24, channels=1)
    ref = _decode_pil_resize(mixed_files, 24, 24, 1)
    assert out.shape == (4, 24, 24, 1)
    assert np.abs(out - ref).max() < 0.02


def test_decode_image_batch_missing_file(tmp_path):
    """A file the native decoder rejects retries through PIL (per-file
    fallback); a genuinely missing file surfaces PIL's error."""
    from tensorflowdistributedlearning_tpu.native import decode_image_batch

    with pytest.raises(FileNotFoundError):
        decode_image_batch([str(tmp_path / "nope.jpg")], 8, 8)


def test_decode_image_batch_partial_fallback(tmp_path):
    """One undecodable file in a batch falls back to PIL alone; the rest still
    decode natively and every row is correct."""
    from tensorflowdistributedlearning_tpu.native import decode_image_batch
    from tensorflowdistributedlearning_tpu.native.loader import _decode_pil_resize

    rng = np.random.default_rng(9)
    paths = []
    for i in range(3):
        arr = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
        p = str(tmp_path / f"ok{i}.png")
        Image.fromarray(arr).save(p)
        paths.append(p)
    # a BMP with a lying extension: native sniff fails, PIL handles it
    odd = str(tmp_path / "odd.png")
    Image.fromarray(
        rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
    ).save(odd, format="BMP")
    paths.insert(1, odd)
    out = decode_image_batch(paths, 16, 16, channels=3)
    ref = _decode_pil_resize(paths, 16, 16, 3)
    assert out.shape == (4, 16, 16, 3)
    assert np.abs(out - ref).max() < 0.02


def test_imagefolder_accepts_jpeg(tmp_path):
    """ImageFolder scans and decodes JPEG class dirs (the real ImageNet format)."""
    from tensorflowdistributedlearning_tpu.data import imagefolder

    rng = np.random.default_rng(8)
    for k in range(2):
        d = tmp_path / f"class{k}"
        d.mkdir()
        for i in range(3):
            arr = rng.integers(0, 256, (40 + 10 * i, 50, 3), dtype=np.uint8)
            Image.fromarray(arr).save(str(d / f"im{i}.jpg"), quality=95)
    ds = imagefolder.ImageFolder(str(tmp_path), (32, 32), channels=3)
    assert len(ds) == 6
    assert ds.num_classes == 2
    batch = next(imagefolder.train_batches(ds, 4, seed=0, steps=1))
    assert batch["images"].shape == (4, 32, 32, 3)
