"""Mesh/sharding layer tests (the distribution config the reference never tested —
reference: model.py:114-121, utils.py:6-8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.parallel import (
    BATCH_AXIS,
    available_devices,
    batch_sharding,
    local_batch_size,
    make_mesh,
    replicate,
    shard_batch,
)
from tensorflowdistributedlearning_tpu.parallel.mesh import data_parallel_degree
from tensorflowdistributedlearning_tpu.utils import get_available_devices


def test_available_devices(eight_devices):
    assert len(available_devices()) >= 8
    names = get_available_devices()
    assert all(isinstance(n, str) and ":" in n for n in names)


def test_make_mesh_default_uses_all_devices():
    mesh = make_mesh()
    assert mesh.shape[BATCH_AXIS] == len(available_devices())


def test_make_mesh_subset():
    mesh = make_mesh(4)
    assert mesh.shape[BATCH_AXIS] == 4
    assert data_parallel_degree(mesh) == 4


def test_make_mesh_model_axis():
    mesh = make_mesh(8, model_parallel=2)
    assert mesh.shape[BATCH_AXIS] == 4
    assert mesh.shape["model"] == 2


def test_make_mesh_too_many_devices_raises():
    with pytest.raises(ValueError):
        make_mesh(10_000)


def test_make_mesh_indivisible_raises():
    with pytest.raises(ValueError):
        make_mesh(8, model_parallel=3)


def test_local_batch_size_divisibility():
    mesh = make_mesh(8)
    assert local_batch_size(64, mesh) == 8
    # the reference raised on indivisible global batches (model.py:156-159)
    with pytest.raises(ValueError):
        local_batch_size(63, mesh)


def test_shard_batch_places_on_batch_axis():
    mesh = make_mesh(8)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    sharded = shard_batch({"images": x}, mesh)["images"]
    assert sharded.shape == (16, 3)
    assert sharded.sharding.is_equivalent_to(batch_sharding(mesh, 2), 2)
    np.testing.assert_array_equal(np.asarray(sharded), x)


def test_replicate_tree():
    mesh = make_mesh(8)
    tree = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    rep = replicate(tree, mesh)
    assert rep["w"].sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(rep["w"]), np.ones((4, 4)))
