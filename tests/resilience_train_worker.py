"""Worker for tests/test_resilience.py and ``tools/run_suite.py
--resilience-smoke``: one real (tiny, synthetic-data, single-CPU-device)
``ClassifierTrainer.fit`` run with the resilience stack installed.

Two modes:

``run``    — one training run. Installs the preemption handler and (optionally)
             a fault injector, trains ``--steps`` steps, dumps the final
             checkpoint's params to ``--params-out`` (.npz), prints a RESULT
             json line. Exits ``EXIT_PREEMPTED`` (75) after a preemption
             checkpoint — exactly what the CLI's train/fit commands do.

``smoke``  — the whole resilience drill: an uninterrupted golden run, then a
             supervised run injected with SIGTERM at a seeded-random step
             (restarted by resilience.supervisor), then a bit-for-bit compare
             of the final params. Prints ``{"ok": true, ...}``; exit 0 iff
             recovery produced identical params and the ledger shows the
             restart. This is the zero-hardware proof that kill -> resume ->
             identical result actually holds end to end.

The training setup is deliberately the smallest thing that exercises the real
fit loop: synthetic classification batches are index-keyed (pure function of
(seed, step)), so a resumed run replays the exact uninterrupted stream.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup_jax_env() -> None:
    """Single CPU device, BEFORE jax initializes (subprocesses do not load the
    root conftest). The persistent compile cache is deliberately NOT enabled:
    resumed children deterministically SIGSEGV'd inside XLA:CPU executable
    serialization with it on this box (the same cache flakiness the root
    conftest documents) — a resilience drill must not depend on it."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _tiny_trainer(model_dir: str, data_dir: str = None):
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    return ClassifierTrainer(
        model_dir,
        # None: synthetic data — index-keyed, restart-invariant. A data_dir
        # holding train-*.tfrecord shards exercises the SAME contract through
        # the streaming data service (global-shuffle epochs, parallel
        # workers, DataServiceState sidecar resume) — the headline drill of
        # tests/test_data_service.py.
        data_dir,
        ModelConfig(
            num_classes=4,
            input_shape=(16, 16),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        TrainConfig(
            seed=0,
            checkpoint_every_steps=2,
            train_log_every_steps=1,
            augmentation="none",
        ),
    )


def _dump_final_params(trainer, path: str) -> None:
    """Final checkpoint -> flat .npz (deterministic leaf order) for the
    bit-for-bit compare."""
    import jax
    import numpy as np

    state = trainer._checkpointer().restore_latest(trainer._host_template())
    arrays = {"step": np.asarray(jax.device_get(state.step))}
    for tree, prefix in ((state.params, "p"), (state.batch_stats, "bs")):
        for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
            arrays[f"{prefix}{i}"] = np.asarray(jax.device_get(leaf))
    np.savez(path, **arrays)


def cmd_run(args) -> int:
    _setup_jax_env()
    sys.path.insert(0, REPO)
    from tensorflowdistributedlearning_tpu.resilience import faults, preempt

    preempt.install(notice_file=args.notice_file)
    if args.inject_fault:
        faults.install(args.inject_fault, seed=args.seed)
    trainer = _tiny_trainer(args.model_dir, args.data_dir)
    try:
        result = trainer.fit(
            batch_size=4, steps=args.steps, eval_every_steps=args.steps
        )
    except preempt.PreemptedError as e:
        print(json.dumps({"preempted": True, "step": e.step}), flush=True)
        return preempt.EXIT_PREEMPTED
    if args.params_out:
        _dump_final_params(trainer, args.params_out)
    import tensorflowdistributedlearning_tpu.resilience.retry as retry_lib

    print(
        json.dumps(
            {
                "steps": result.steps,
                "final_metrics": result.final_metrics,
                "retries": retry_lib.retries(),
            }
        ),
        flush=True,
    )
    return 0


def _run_child(argv, timeout=420) -> int:
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), *argv],
        timeout=timeout,
        check=False,
    ).returncode


def cmd_smoke(args) -> int:
    sys.path.insert(0, REPO)
    import numpy as np

    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger
    from tensorflowdistributedlearning_tpu.resilience import parse_fault_spec
    from tensorflowdistributedlearning_tpu.resilience.supervisor import Supervisor

    golden_dir = os.path.join(args.workdir, "golden")
    sup_dir = os.path.join(args.workdir, "supervised")
    golden_npz = os.path.join(args.workdir, "golden.npz")
    sup_npz = os.path.join(args.workdir, "supervised.npz")

    data_args = ["--data-dir", args.data_dir] if args.data_dir else []
    rc = _run_child(
        ["run", "--model-dir", golden_dir, "--steps", str(args.steps),
         "--params-out", golden_npz, *data_args]
    )
    if rc != 0:
        print(json.dumps({"ok": False, "stage": "golden", "rc": rc}))
        return 1

    # kill at a seeded-random mid-run step (never the final step: preemption
    # AT the end would leave nothing to resume)
    fault = f"sigterm@2-{args.steps - 1}"
    kill_step = parse_fault_spec(fault, seed=args.seed).at
    result = Supervisor(
        [sys.executable, os.path.abspath(__file__), "run",
         "--model-dir", sup_dir, "--steps", str(args.steps),
         "--params-out", sup_npz, "--inject-fault", fault,
         "--seed", str(args.seed), *data_args],
        workdir=sup_dir,
        max_restarts=3,
        backoff_base_s=0.1,
        backoff_max_s=1.0,
        seed=args.seed,
    ).run()

    events = read_ledger(sup_dir) if os.path.exists(
        os.path.join(sup_dir, "telemetry.jsonl")
    ) else []
    kinds = [e.get("event") for e in events]
    identical = False
    if result.ok and os.path.exists(sup_npz):
        a, b = np.load(golden_npz), np.load(sup_npz)
        identical = sorted(a.files) == sorted(b.files) and all(
            np.array_equal(a[k], b[k]) for k in a.files
        )
    ok = (
        result.ok
        and result.restarts >= 1
        and identical
        and "preempted" in kinds
        and "restart" in kinds
        and "resumed" in kinds
    )
    print(
        json.dumps(
            {
                "ok": ok,
                "kill_step": kill_step,
                "restarts": result.restarts,
                "identical": identical,
                "downtime_s": result.downtime_s,
                "ledger_events": sorted(
                    {k for k in kinds if k in (
                        "preempted", "restart", "resumed", "run_header",
                        "run_end",
                    )}
                ),
            }
        ),
        flush=True,
    )
    return 0 if ok else 1


def main() -> int:
    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers(dest="mode", required=True)
    p_run = sub.add_parser("run")
    p_run.add_argument("--model-dir", required=True)
    p_run.add_argument("--steps", type=int, default=8)
    p_run.add_argument("--inject-fault", default=None)
    p_run.add_argument("--notice-file", default=None)
    p_run.add_argument("--params-out", default=None)
    p_run.add_argument("--seed", type=int, default=0)
    p_run.add_argument("--data-dir", default=None)
    p_smoke = sub.add_parser("smoke")
    p_smoke.add_argument("--workdir", required=True)
    p_smoke.add_argument("--steps", type=int, default=8)
    p_smoke.add_argument("--seed", type=int, default=0)
    p_smoke.add_argument("--data-dir", default=None)
    args = parser.parse_args()
    return {"run": cmd_run, "smoke": cmd_smoke}[args.mode](args)


if __name__ == "__main__":
    raise SystemExit(main())
