"""Capacity/cost layer tests (obs/capacity.py + its wiring): HBM watermark
tracking with measured-vs-predicted deltas, chip-seconds cost accounting for
training windows and serving requests, the headroom health monitor, the
``telemetry-top`` console, the ledger exit-flush fix, and the regression
sentinel's new tolerance bands.

Degraded paths are first-class here (the ISSUE's satellite): CPU-only JAX
reports NO allocator stats (``device.memory_stats()`` returns None), so
every watermark test that needs device numbers injects a stats_fn — and the
statless path itself is pinned as a no-event, no-crash contract."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tensorflowdistributedlearning_tpu import obs
from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib
from tensorflowdistributedlearning_tpu.obs import top as top_lib
from tensorflowdistributedlearning_tpu.obs.health import HeadroomMonitor


def _stats(peak, limit=None, in_use=None):
    s = {"peak_bytes_in_use": peak, "bytes_in_use": in_use or peak}
    if limit is not None:
        s["bytes_limit"] = limit
    return {"TPU_0": s}


# -- WatermarkTracker --------------------------------------------------------


def test_watermark_statless_backend_degrades_to_none():
    """CPU-only JAX: memory_stats is empty — samples yield nothing, nothing
    crashes, headroom stays unknown."""
    tr = capacity_lib.WatermarkTracker(stats_fn=dict)
    assert tr.sample(capacity_lib.PHASE_STEP) is None
    assert tr.headroom() is None
    assert tr.snapshot()["peak_bytes"] == 0


def test_watermark_stats_fn_raising_degrades_to_none():
    def boom():
        raise RuntimeError("allocator query unsupported")

    tr = capacity_lib.WatermarkTracker(stats_fn=boom)
    assert tr.sample(capacity_lib.PHASE_STEP) is None


def test_watermark_attributes_phases_and_predicted_delta():
    state = {"stats": _stats(1000, limit=10_000)}
    tr = capacity_lib.WatermarkTracker(
        predicted_bytes_per_device=800, stats_fn=lambda: state["stats"]
    )
    first = tr.sample(capacity_lib.PHASE_COMPILE, step=0)
    assert first["phase"] == "compile" and first["peak_bytes"] == 1000
    assert first["measured_minus_predicted_bytes"] == 200
    assert first["headroom_frac"] == pytest.approx(0.9)
    # peak unchanged: the step phase records its first watermark once, then
    # stays silent (steady state under the compile peak is the healthy case)
    assert tr.sample(capacity_lib.PHASE_STEP, step=5) is not None
    assert tr.sample(capacity_lib.PHASE_STEP, step=10) is None
    # eval pushes the peak: the advance is attributed to eval
    state["stats"] = _stats(4000, limit=10_000)
    ev = tr.sample(capacity_lib.PHASE_EVAL, step=20)
    assert ev["phase"] == "eval" and ev["peak_bytes"] == 4000
    snap = tr.snapshot()
    assert set(snap["phases"]) == {"compile", "step", "eval"}
    assert snap["phases"]["eval"]["peak_bytes"] == 4000


def test_watermark_trend_projects_samples_to_limit():
    state = {"peak": 1000}
    tr = capacity_lib.WatermarkTracker(
        stats_fn=lambda: _stats(state["peak"], limit=100_000)
    )
    for _ in range(6):
        tr.sample(capacity_lib.PHASE_STEP)
        state["peak"] += 1000  # a steady climb: ~1000 bytes/sample
    hr = tr.headroom()
    assert hr["trend_bytes_per_sample"] == pytest.approx(1000, rel=0.01)
    assert 0 < hr["samples_to_limit"] < 120


# -- CostMeter ---------------------------------------------------------------


def test_cost_meter_train_window_accounting():
    cm = capacity_lib.CostMeter(n_chips=8)
    fields = cm.train_window(2.0, 10, examples=1280, step=50)
    assert fields["chip_seconds"] == pytest.approx(16.0)
    assert fields["chip_seconds_per_step"] == pytest.approx(1.6)
    assert fields["examples_per_chip_second"] == pytest.approx(80.0)
    fields = cm.train_window(1.0, 10)
    assert fields["chip_seconds_total"] == pytest.approx(24.0)
    # empty windows never emit
    assert cm.train_window(0.0, 10) is None
    assert cm.train_window(1.0, 0) is None


def test_cost_meter_serve_batch_share_attribution():
    cm = capacity_lib.CostMeter(n_chips=2)
    # one batch of 0.1s compute split 1:3 across two requests
    cm.add_batch(0.1, [1, 3])
    out = cm.serve_window()
    assert out["requests"] == 2
    assert out["chip_seconds"] == pytest.approx(0.2)
    per = out["chip_seconds_per_request"]
    # batch-share: 0.05 and 0.15 chip-seconds
    assert per["p50"] == pytest.approx(0.05, abs=0.06)
    assert per["mean"] == pytest.approx(0.1)
    # drained: an idle window emits nothing
    assert cm.serve_window() is None


def test_cost_meter_lazy_chip_count_does_not_touch_backend():
    cm = capacity_lib.CostMeter()
    assert cm._n_chips is None  # no jax call at construction
    assert cm.n_chips >= 1


# -- HeadroomMonitor ---------------------------------------------------------


def test_headroom_monitor_transitions_and_recovery():
    mon = HeadroomMonitor(min_headroom_frac=0.10)
    assert mon.check(1, 5_000, 10_000) is None  # 50% headroom: fine
    alert = mon.check(2, 9_500, 10_000)  # 5% headroom: degrade
    assert alert["monitor"] == "hbm_headroom"
    assert alert["severity"] == "critical"
    assert alert["reason"] == "low_headroom"
    assert mon.degraded
    assert mon.check(3, 9_600, 10_000) is None  # still degraded: no flood
    resolved = mon.check(4, 5_000, 10_000)
    assert resolved["resolved"] is True
    assert not mon.degraded


def test_headroom_monitor_trend_alert_and_no_limit_noop():
    mon = HeadroomMonitor(min_headroom_frac=0.05, horizon_samples=10)
    alert = mon.check(1, 2_000, 10_000, samples_to_limit=3)
    assert alert and alert["reason"] == "trend"
    mon2 = HeadroomMonitor()
    assert mon2.check(1, 2_000, None) is None  # no limit = nothing to budget


# -- Telemetry wiring --------------------------------------------------------


def test_telemetry_emits_watermark_and_cost_events(tmp_path):
    tel = obs.Telemetry(str(tmp_path), is_main=True, run_info={"task": "t"})
    state = {"stats": _stats(3_000, limit=10_000)}
    tel.watermarks._stats_fn = lambda: state["stats"]
    with tel.span(obs.SPAN_STEP):
        time.sleep(0.01)
    tel.window_event(5, steps=5, examples=320)
    tel.memory_event(
        step=5, params_bytes_per_device=1_000, opt_state_bytes_per_device=500
    )
    tel.close(steps=5)
    events = obs.read_ledger(str(tmp_path))
    kinds = [e["event"] for e in events]
    assert "cost" in kinds and "memory_watermark" in kinds
    cost = next(e for e in events if e["event"] == "cost")
    assert cost["scope"] == "train" and cost["chip_seconds"] > 0
    assert cost["examples"] == 320
    wm = next(e for e in events if e["event"] == "memory_watermark")
    # the trainers' tree_bytes_per_device extras became the prediction
    assert wm["predicted_bytes_per_device"] == 1_500
    assert wm["measured_minus_predicted_bytes"] == 1_500
    assert wm["phase"] in ("compile", "step")


def test_telemetry_statless_backend_emits_no_watermarks(tmp_path):
    """The CPU degraded path end to end: memory events flow, watermark events
    do not, nothing crashes (profiling.memory_stats is empty here)."""
    tel = obs.Telemetry(str(tmp_path), is_main=True)
    tel.memory_event(step=1)
    tel.eval_event(1, {"loss": 1.0}, 0.1)
    tel.checkpoint_event(1)
    tel.close()
    kinds = [e["event"] for e in obs.read_ledger(str(tmp_path))]
    assert "memory" in kinds
    assert "memory_watermark" not in kinds


def test_cost_events_on_unwritable_workdir_never_crash(tmp_path):
    target = tmp_path / "file_in_the_way"
    target.write_text("occupied")
    tel = obs.Telemetry(str(target), is_main=True)
    tel.watermarks._stats_fn = lambda: _stats(1_000, limit=10_000)
    with tel.span(obs.SPAN_STEP):
        pass
    tel.window_event(1, steps=1, examples=8)  # cost path, ledger disabled
    tel.memory_event(step=1)  # watermark path, ledger disabled
    tel.close()
    assert tel.ledger is None or not tel.ledger.enabled


def test_capacity_sampling_off_is_inert(tmp_path):
    tel = obs.Telemetry(
        str(tmp_path), is_main=True, capacity_sampling=False
    )
    tel.watermarks._stats_fn = lambda: _stats(1_000, limit=10_000)
    with tel.span(obs.SPAN_STEP):
        pass
    tel.window_event(1, steps=1, examples=8)
    tel.memory_event(step=1)
    tel.close()
    kinds = [e["event"] for e in obs.read_ledger(str(tmp_path))]
    assert "cost" not in kinds and "memory_watermark" not in kinds


def test_headroom_alert_flows_through_health_monitor(tmp_path):
    mon = obs.HealthMonitor()
    tel = obs.Telemetry(str(tmp_path), is_main=True, health=mon)
    tel.watermarks._stats_fn = lambda: _stats(9_900, limit=10_000)
    tel.memory_event(step=1)
    tel.close()
    alerts = [
        e
        for e in obs.read_ledger(str(tmp_path))
        if e["event"] == "health_alert"
    ]
    assert any(a["monitor"] == "hbm_headroom" for a in alerts)
    assert mon.status == "degraded"


def test_trend_degraded_resolves_after_plateau(tmp_path):
    """Review pin: a trend-triggered degraded state must RESOLVE once the
    peak plateaus — the monitor re-evaluates on every sample, not only on
    peak advances (a lifetime peak stops advancing by definition)."""
    mon = obs.HealthMonitor(
        headroom=HeadroomMonitor(min_headroom_frac=0.05, horizon_samples=30)
    )
    tel = obs.Telemetry(str(tmp_path), is_main=True, health=mon)
    state = {"peak": 50_000}
    tel.watermarks._stats_fn = lambda: _stats(state["peak"], limit=1_000_000)
    for _ in range(8):  # steep climb: trend projects the limit crossing
        tel.memory_event(step=1)
        state["peak"] += 30_000
    assert mon.headroom.degraded
    for _ in range(20):  # plateau: slope decays, projection clears
        tel.memory_event(step=2)
    assert not mon.headroom.degraded
    tel.close()
    alerts = [
        e
        for e in obs.read_ledger(str(tmp_path))
        if e["event"] == "health_alert" and e["monitor"] == "hbm_headroom"
    ]
    assert any(a.get("resolved") for a in alerts)


def test_memory_event_queries_allocator_once(tmp_path):
    """Review pin: the window's memory snapshot is REUSED by the watermark
    sample — one allocator query per memory_event, not two."""
    calls = {"n": 0}

    def stats():
        calls["n"] += 1
        return _stats(1_000, limit=10_000)

    tel = obs.Telemetry(str(tmp_path), is_main=True)
    tel.watermarks._stats_fn = stats
    import tensorflowdistributedlearning_tpu.utils.profiling as profiling

    orig = profiling.memory_stats
    profiling.memory_stats = stats
    try:
        tel.memory_event(step=1)
    finally:
        profiling.memory_stats = orig
    tel.close()
    assert calls["n"] == 1
    kinds = [e["event"] for e in obs.read_ledger(str(tmp_path))]
    assert "memory_watermark" in kinds


def test_server_capacity_works_without_telemetry():
    """Review pin: a ServingServer on the default NULL_TELEMETRY still owns a
    PRIVATE cost meter and watermark tracker — two servers cannot
    cross-contaminate through the shared null singleton, and the /healthz
    OOM-drain protection stays live (no ledger, but gauges and health do)."""
    import numpy as np
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.obs.telemetry import NULL_TELEMETRY
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
    )
    from tensorflowdistributedlearning_tpu.serve.server import ServingServer

    def fn(x):
        return {"y": jnp.asarray(x).sum(axis=1)}

    servers = []
    for _ in range(2):
        eng = InferenceEngine(
            fn, example_shape=(4,), buckets=(1, 4), input_dtype=np.float32
        )
        servers.append(
            ServingServer(eng, MicroBatcher(eng, max_wait_ms=1.0), window_secs=0)
        )
    a, b = servers
    try:
        assert a.cost_meter is not NULL_TELEMETRY.cost
        assert a.cost_meter is not b.cost_meter
        assert a.watermarks is not NULL_TELEMETRY.watermarks
        # drive one server; the other's meter must stay untouched
        a.batcher.submit(np.ones((1, 4), np.float32)).result(10)
        a.emit_window()
        assert a.cost_meter.chip_seconds_total > 0
        assert b.cost_meter.chip_seconds_total == 0
        # the headroom protection runs off the server-owned tracker
        a.watermarks._stats_fn = lambda: _stats(9_900, limit=10_000)
        a.emit_window()
        assert a.health_status == "degraded"
        assert b.health_status == "ok"
    finally:
        for s in servers:
            s.shutdown()


# -- report / compare sections -----------------------------------------------


def _run_with_capacity(workdir, *, serve=False):
    tel = obs.Telemetry(str(workdir), is_main=True, run_info={"task": "t"})
    tel.watermarks._stats_fn = lambda: _stats(3_000, limit=10_000)
    with tel.span(obs.SPAN_STEP):
        time.sleep(0.01)
    tel.window_event(5, steps=5, examples=320, images_per_sec=100.0)
    tel.memory_event(step=5, params_bytes_per_device=1_000)
    if serve:
        tel.cost.add_batch(0.02, [1, 3])
        fields = tel.cost.serve_window()
        tel.event(capacity_lib.COST_EVENT, **fields)
    tel.close(steps=5)


def test_report_renders_watermark_and_cost_sections(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.report import (
        build_report,
        render_report,
    )

    _run_with_capacity(tmp_path, serve=True)
    report = build_report(str(tmp_path))
    wm = report["memory"]["watermarks"]
    assert wm["peak_bytes"] == 3_000
    assert wm["predicted_bytes_per_device"] == 1_000
    cost = report["cost"]
    assert cost["train"]["chip_seconds_total"] > 0
    assert cost["serve"]["rps_per_chip"] > 0
    assert "p99_worst_window" in cost["serve"]["chip_seconds_per_request"]
    text = render_report(report)
    assert "HBM watermarks" in text
    assert "measured vs predicted" in text
    assert "chip-seconds" in text
    # stable --json schema: the keys CI consumers parse
    blob = json.loads(json.dumps(report))
    assert {"events", "peak_bytes", "phases"} <= set(
        blob["memory"]["watermarks"]
    )
    assert {"train", "serve"} <= set(blob["cost"])


def test_compare_emits_cost_deltas(tmp_path):
    from tensorflowdistributedlearning_tpu.obs import compare as compare_lib

    a, b = tmp_path / "a", tmp_path / "b"
    _run_with_capacity(a)
    _run_with_capacity(b)
    result = compare_lib.compare_workdirs(str(a), str(b))
    metrics = {d["metric"] for d in result["deltas"]}
    assert "chip_seconds_per_step" in metrics
    assert "hbm_peak_bytes" in metrics
    hbm = next(d for d in result["deltas"] if d["metric"] == "hbm_peak_bytes")
    assert hbm["verdict"] == "neutral"  # identical runs


# -- telemetry-top -----------------------------------------------------------


def test_top_empty_workdir_renders_honest_frame(tmp_path):
    frame = top_lib.build_frame(str(tmp_path))
    assert frame["processes"] == 0
    assert "no ledgers yet" in top_lib.render_frame(frame)


def test_top_training_only_ledger(tmp_path):
    _run_with_capacity(tmp_path)
    frame = top_lib.build_frame(str(tmp_path))
    assert frame["processes"] == 1
    row = frame["rows"][0]
    assert row["step"] == 5
    assert row["cost"]["chip_seconds_per_step"] > 0
    assert row["memory"]["peak_bytes"] == 3_000
    text = top_lib.render_frame(frame)
    assert "step 5" in text and "hbm peak" in text
    assert "serve" not in text.split("\n")[1]


def test_top_serving_only_ledger(tmp_path):
    tel = obs.Telemetry(str(tmp_path), run_info={"kind": "serve", "replica": 0})
    tel.event(
        "serve_window",
        requests=10,
        completed=9,
        queue_depth=3,
        replica=0,
        latency_ms={"request": {"p99_ms": 12.5}},
        slo={"healthy": False},
    )
    tel.close()
    frame = top_lib.build_frame(str(tmp_path))
    row = frame["rows"][0]
    assert row["serve"]["backlog"] == 3
    assert row["serve"]["p99_ms"] == 12.5
    text = top_lib.render_frame(frame)
    assert "9/10 ok" in text and "SLO BREACHED" in text


def test_top_once_cli_exits_zero_on_all_shapes(tmp_path):
    """The CI smoke contract: `telemetry-top WORKDIR --once` exits 0 on an
    empty workdir and on a populated one, printing a frame either way."""
    from tensorflowdistributedlearning_tpu.cli import main

    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["telemetry-top", str(empty), "--once"]) == 0
    _run_with_capacity(tmp_path / "run")
    assert main(["telemetry-top", str(tmp_path / "run"), "--once"]) == 0


def test_top_fleet_merge_and_straggler_flag(tmp_path):
    for proc, mean_ms in ((0, 10.0), (1, 30.0)):
        tel = obs.Telemetry(
            str(tmp_path), is_main=proc == 0, process_index=proc,
            run_info={"task": "t"},
        )
        for step in (5, 10):
            tel.event(
                "step_window",
                step=step,
                steps=5,
                compute_s=mean_ms / 1000 * 5,
                data_wait_s=0.0,
                step_time_ms={"mean_ms": mean_ms, "p50_ms": mean_ms,
                              "p90_ms": mean_ms, "p99_ms": mean_ms},
            )
        tel.close()
    frame = top_lib.build_frame(str(tmp_path))
    assert frame["processes"] == 2
    assert frame["straggler"]["worst_process"] == 1
    assert frame["straggler"]["alert_count"] > 0
    assert "straggler skew" in top_lib.render_frame(frame)


# -- ledger exit flush (the tail-loss satellite) -----------------------------


_FLUSH_DRILL = """
import os, sys, time
from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger

led = RunLedger(sys.argv[1])
led.event("run_header", drill=True)
for i in range(50):
    led.event_buffered("trace", name="span", i=i)  # buffered: no flush
print("READY", flush=True)
time.sleep(30)  # killed here — the exit hooks must flush the buffered tail
"""


def test_sigterm_flushes_buffered_ledger_tail(tmp_path):
    """Kill drill: a process holding buffered high-rate events dies on
    SIGTERM between flushes; the default-SIGTERM flush hook must land the
    tail (and preserve the 128+SIGTERM exit convention)."""
    drill = tmp_path / "drill.py"
    drill.write_text(_FLUSH_DRILL)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, str(drill), str(tmp_path)],
        stdout=subprocess.PIPE,
        text=True,
        cwd=repo,
        env=env,
    )
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == -signal.SIGTERM  # default action preserved after the flush
    events = obs.read_ledger(str(tmp_path))
    traces = [e for e in events if e["event"] == "trace"]
    assert len(traces) == 50  # nothing buffered was lost


def test_flush_all_ledgers_flushes_buffered_lines(tmp_path):
    led = obs.RunLedger(str(tmp_path))
    led.event_buffered("trace", i=1)
    # not yet on disk (stdio-buffered) — barring an unluckily tiny buffer
    obs.flush_all_ledgers()
    events = obs.read_ledger(str(tmp_path))
    assert [e["event"] for e in events] == ["trace"]
    led.close()
    obs.flush_all_ledgers()  # closed ledgers are dropped from the registry


# -- regression sentinel bands -----------------------------------------------


def test_sentinel_gates_peak_hbm_growth():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import regression_sentinel as rs
    finally:
        sys.path.pop(0)
    base = {"async": {"step_time_ms": 10.0}, "peak_hbm_bytes": 1_000_000}
    ok = rs.check_async(base, dict(base, peak_hbm_bytes=1_100_000))
    bad = rs.check_async(base, dict(base, peak_hbm_bytes=2_000_000))
    hbm_ok = next(f for f in ok if f["metric"] == "peak_hbm_bytes")
    hbm_bad = next(f for f in bad if f["metric"] == "peak_hbm_bytes")
    assert hbm_ok["ok"] and not hbm_bad["ok"]
    # absent on either side (CPU baseline): no finding, not a failure
    none = rs.check_async({"async": {"step_time_ms": 10.0}}, base)
    assert not any(f["metric"] == "peak_hbm_bytes" for f in none)


def test_sentinel_gates_rps_per_chip():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import regression_sentinel as rs
    finally:
        sys.path.pop(0)
    base = {"batched": {"requests_per_sec": 1000.0, "rps_per_chip": 1000.0}}
    ok = rs.check_serve(base, {"batched": {"rps_per_chip": 900.0}})
    bad = rs.check_serve(base, {"batched": {"rps_per_chip": 100.0}})
    rpc_ok = next(f for f in ok if f["metric"] == "batched.rps_per_chip")
    rpc_bad = next(f for f in bad if f["metric"] == "batched.rps_per_chip")
    assert rpc_ok["ok"] and not rpc_bad["ok"]
