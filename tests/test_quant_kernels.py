"""Quantized-compute kernel parity (interpreter mode on CPU — the same
integer kernel body the TPU compiles): int8 matmul/conv vs the
dequantize-f32 oracle across odd channels, zero-scale channels, and the
bucket-ladder batch sizes; bitwise accumulator equivalence against XLA's
genuine int8 arithmetic (fallback-path proof); the dynamic activation
quantizer's padding invariant the serving engine relies on; and the
interceptor's routing envelope (quantized dense/conv in, everything else
falls through untouched)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.ops.quant_kernels import (
    int8_conv2d,
    int8_conv2d_reference,
    int8_intercept,
    int8_matmul,
    int8_matmul_reference,
    int8_matmul_xla,
    quantize_activations,
)
from tensorflowdistributedlearning_tpu.train.quantize import quantize_pytree


def quantize_weight(w):
    """Per-channel symmetric int8 via the real export recipe — the same
    records the interceptor sees, not a test-local reimplementation."""
    qtree, _ = quantize_pytree({"m": {"kernel": w}}, "int8")
    rec = qtree["m"]["kernel"]
    return jnp.asarray(rec["q"]), jnp.asarray(rec["scale"])


# -- dynamic activation quantization ------------------------------------------


def test_quantize_activations_roundtrip_and_zero_guard():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 2, (4, 33)), jnp.float32)
    q, s = quantize_activations(x)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(q, np.float32) * np.asarray(s), np.asarray(x),
        atol=float(s) * 0.5 + 1e-7,
    )
    # all-zero tensor: scale pins to 1.0, nothing divides by zero
    qz, sz = quantize_activations(jnp.zeros((3, 5)))
    assert float(sz) == 1.0 and not np.any(np.asarray(qz))


def test_quantize_activations_padding_invariant():
    """Zero-point 0 is the property the bucket ladder leans on: appending
    zero rows (engine pad) changes neither the scale nor the quantized
    values of the live rows."""
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (3, 16)).astype(np.float32)
    padded = np.zeros((8, 16), np.float32)
    padded[:3] = x
    q, s = quantize_activations(jnp.asarray(x))
    qp, sp = quantize_activations(jnp.asarray(padded))
    assert float(s) == float(sp)
    np.testing.assert_array_equal(np.asarray(qp[:3]), np.asarray(q))
    assert not np.any(np.asarray(qp[3:]))


# -- int8 matmul: kernel vs dequantize-f32 oracle ------------------------------


@pytest.mark.parametrize("m", [1, 4, 16, 64])  # the serve bucket ladder
@pytest.mark.parametrize("k,n", [(32, 48), (33, 129)])  # even and odd channels
def test_matmul_parity_vs_reference(m, k, n):
    rng = np.random.default_rng(m * 1000 + k)
    x = jnp.asarray(rng.normal(0, 1, (m, k)), jnp.float32)
    wq, ws = quantize_weight(
        jnp.asarray(rng.normal(0, 0.5, (k, n)), jnp.float32)
    )
    bias = jnp.asarray(rng.normal(0, 0.1, (n,)), jnp.float32)
    got = int8_matmul(x, wq, ws, bias=bias, act="relu", interpret=True)
    want = int8_matmul_reference(x, wq, ws, bias=bias, act="relu")
    # integer accumulation is exact; only f32 rounding differs between paths
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-3
    )


def test_matmul_zero_scale_channels():
    """All-zero weight columns quantize with the scale-1.0 guard; the kernel
    must emit exact zeros there (bias-only after the epilogue)."""
    rng = np.random.default_rng(2)
    w = rng.normal(0, 0.5, (16, 8)).astype(np.float32)
    w[:, 3] = 0.0
    w[:, 6] = 0.0
    wq, ws = quantize_weight(jnp.asarray(w))
    assert float(ws[3]) == 1.0 and float(ws[6]) == 1.0
    x = jnp.asarray(rng.normal(0, 1, (4, 16)), jnp.float32)
    bias = jnp.asarray(rng.normal(0, 1, (8,)), jnp.float32)
    got = np.asarray(int8_matmul(x, wq, ws, bias=bias, interpret=True))
    np.testing.assert_allclose(got[:, 3], float(bias[3]), rtol=1e-6)
    np.testing.assert_allclose(got[:, 6], float(bias[6]), rtol=1e-6)
    want = np.asarray(int8_matmul_reference(x, wq, ws, bias=bias))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_matmul_leading_dims_and_out_dtype():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(0, 1, (2, 3, 16)), jnp.float32)
    wq, ws = quantize_weight(jnp.asarray(rng.normal(0, 0.5, (16, 8))))
    got = int8_matmul(x, wq, ws, out_dtype=jnp.bfloat16, interpret=True)
    assert got.shape == (2, 3, 8) and got.dtype == jnp.bfloat16
    want = int8_matmul_reference(x, wq, ws, out_dtype=jnp.bfloat16)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_matmul_n_tiling_matches_untiled():
    """A VMEM budget that forces output-feature tiling across the grid must
    not change results."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(0, 1, (8, 32)), jnp.float32)
    wq, ws = quantize_weight(jnp.asarray(rng.normal(0, 0.5, (32, 64))))
    full = int8_matmul(x, wq, ws, interpret=True)
    # budget fits ~a quarter of N: fixed 8*32 + nt*(32+8*4+8)
    tiled = int8_matmul(
        x, wq, ws, interpret=True, vmem_limit_bytes=8 * 32 + 16 * 72 + 1
    )
    np.testing.assert_array_equal(np.asarray(full), np.asarray(tiled))


def test_matmul_vmem_overflow_falls_back_to_reference():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(0, 1, (4, 16)), jnp.float32)
    wq, ws = quantize_weight(jnp.asarray(rng.normal(0, 0.5, (16, 6))))
    got = int8_matmul(x, wq, ws, interpret=True, vmem_limit_bytes=64)
    want = int8_matmul_reference(x, wq, ws)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_matmul_integer_accumulator_bitwise_vs_xla():
    """Fallback-path equivalence at the arithmetic level: the interpreted
    Pallas kernel and XLA's int8 dot produce BITWISE-equal int32
    accumulators (both integer paths are exact; only the separately-compiled
    f32 epilogues may differ in the last ulp from FMA fusion)."""
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(0, 1, (16, 64)), jnp.float32)
    wq, ws = quantize_weight(jnp.asarray(rng.normal(0, 0.5, (64, 40))))
    ones = jnp.ones((40,), jnp.float32)
    # scale=1, no bias, no act: the raw accumulator in f32 carry-out
    acc_kernel = int8_matmul(x, wq, ones, interpret=True)
    acc_xla = int8_matmul_xla(x, wq, ones)
    # int32 accumulators cast to f32 are exact for |acc| < 2^24
    np.testing.assert_array_equal(np.asarray(acc_kernel), np.asarray(acc_xla))


def test_matmul_validation():
    x = jnp.zeros((2, 8))
    wq = jnp.zeros((8, 4), jnp.int8)
    with pytest.raises(ValueError, match="int8"):
        int8_matmul(x, jnp.zeros((8, 4)), jnp.ones((4,)), interpret=True)
    with pytest.raises(ValueError, match="last dim"):
        int8_matmul(jnp.zeros((2, 7)), wq, jnp.ones((4,)), interpret=True)
    with pytest.raises(ValueError, match="w_scale"):
        int8_matmul(x, wq, jnp.ones((3,)), interpret=True)
    with pytest.raises(ValueError, match="bias"):
        int8_matmul(x, wq, jnp.ones((4,)), bias=jnp.ones((5,)), interpret=True)


# -- int8 conv2d ---------------------------------------------------------------


@pytest.mark.parametrize("padding", ["SAME", "VALID"])
@pytest.mark.parametrize("khw,cin,cout", [(3, 8, 16), (1, 8, 16), (3, 5, 7)])
def test_conv_parity_vs_reference(padding, khw, cin, cout):
    rng = np.random.default_rng(khw * 100 + cin)
    x = jnp.asarray(rng.normal(0, 1, (2, 9, 11, cin)), jnp.float32)
    wq, ws = quantize_weight(
        jnp.asarray(rng.normal(0, 0.5, (khw, khw, cin, cout)), jnp.float32)
    )
    bias = jnp.asarray(rng.normal(0, 0.1, (cout,)), jnp.float32)
    got = int8_conv2d(
        x, wq, ws, padding=padding, bias=bias, act="relu", interpret=True
    )
    want = int8_conv2d_reference(x, wq, ws, padding=padding, bias=bias, act="relu")
    assert got.shape == want.shape
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-3
    )


def test_conv_explicit_padding_and_zero_scale():
    rng = np.random.default_rng(9)
    w = rng.normal(0, 0.5, (3, 3, 4, 6)).astype(np.float32)
    w[..., 2] = 0.0  # zero output channel -> scale-1.0 guard
    wq, ws = quantize_weight(jnp.asarray(w))
    assert float(ws[2]) == 1.0
    x = jnp.asarray(rng.normal(0, 1, (1, 7, 7, 4)), jnp.float32)
    pads = ((2, 0), (0, 2))
    got = int8_conv2d(x, wq, ws, padding=pads, interpret=True)
    want = int8_conv2d_reference(x, wq, ws, padding=pads)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-3
    )
    assert not np.any(np.asarray(got)[..., 2])


def test_conv_vmem_overflow_falls_back():
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.normal(0, 1, (1, 8, 8, 4)), jnp.float32)
    wq, ws = quantize_weight(jnp.asarray(rng.normal(0, 0.5, (3, 3, 4, 6))))
    got = int8_conv2d(x, wq, ws, interpret=True, vmem_limit_bytes=256)
    want = int8_conv2d_reference(x, wq, ws)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_conv_validation():
    x = jnp.zeros((1, 8, 8, 4))
    wq = jnp.zeros((3, 3, 4, 6), jnp.int8)
    ws = jnp.ones((6,))
    with pytest.raises(ValueError, match="int8"):
        int8_conv2d(x, jnp.zeros((3, 3, 4, 6)), ws, interpret=True)
    with pytest.raises(ValueError, match="channels"):
        int8_conv2d(jnp.zeros((1, 8, 8, 3)), wq, ws, interpret=True)
    with pytest.raises(ValueError, match="padding"):
        int8_conv2d(x, wq, ws, padding="CIRCULAR", interpret=True)
    with pytest.raises(ValueError, match="expects"):
        int8_conv2d(jnp.zeros((8, 4)), wq, ws, interpret=True)


# -- the interceptor -----------------------------------------------------------


class _MixedNet:
    """A net straddling the interceptor envelope: a supported conv + dense,
    and a STRIDED conv that must fall through to the float path."""

    def __new__(cls):
        from flax import linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.Conv(8, (3, 3), padding="SAME", name="conv_ok")(x)
                x = nn.relu(x)
                x = nn.Conv(8, (3, 3), strides=(2, 2), name="conv_strided")(x)
                x = x.reshape((x.shape[0], -1))
                return nn.Dense(4, name="head")(x)

        return Net()


def _init_mixed(net):
    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    params = net.init(jax.random.PRNGKey(0), x)["params"]
    return params, x


def test_interceptor_routes_supported_layers_only(monkeypatch):
    import tensorflowdistributedlearning_tpu.ops.quant_kernels as qk

    net = _MixedNet()
    params, x = _init_mixed(net)
    qparams, _ = quantize_pytree(params, "int8-compute")
    calls = []
    real_mm, real_conv = qk.int8_matmul, qk.int8_conv2d
    monkeypatch.setattr(
        qk, "int8_matmul", lambda *a, **k: calls.append("mm") or real_mm(*a, **k)
    )
    monkeypatch.setattr(
        qk, "int8_conv2d",
        lambda *a, **k: calls.append("conv") or real_conv(*a, **k),
    )
    from tensorflowdistributedlearning_tpu.train.quantize import (
        dequantize_pytree,
    )

    deq = dequantize_pytree(qparams, jnp.float32)
    with int8_intercept(qparams, jnp.float32):
        out = net.apply({"params": deq}, x)
    # dense + the stride-1 conv routed; the strided conv did NOT
    assert sorted(calls) == ["conv", "mm"]
    assert out.shape == (2, 4)


def test_interceptor_output_tracks_dequantized_path():
    """int8-compute differs from the dequantized float path only by
    activation-quantization noise — same weights, bounded drift. (Exact
    equality would mean the interceptor silently fell through.)"""
    from tensorflowdistributedlearning_tpu.train.quantize import (
        dequantize_pytree,
    )

    net = _MixedNet()
    params, _ = _init_mixed(net)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(0, 1, (2, 8, 8, 3)), jnp.float32)
    qparams, _ = quantize_pytree(params, "int8-compute")
    deq = dequantize_pytree(qparams, jnp.float32)
    float_path = net.apply({"params": deq}, x)
    with int8_intercept(qparams, jnp.float32):
        quant_path = net.apply({"params": deq}, x)
    delta = np.abs(np.asarray(quant_path) - np.asarray(float_path))
    assert delta.max() > 0  # genuinely different arithmetic
    assert delta.max() < 0.25  # within the int8-compute drift budget


def test_interceptor_noop_on_unquantized_tree():
    """A float32 params tree holds no records: the interceptor must leave
    every layer on the float path, bit-identically."""
    net = _MixedNet()
    params, x = _init_mixed(net)
    plain = net.apply({"params": params}, x)
    with int8_intercept(params, jnp.float32):
        intercepted = net.apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(intercepted))
