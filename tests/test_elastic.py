"""Elastic pod-scale training (parallel/elastic.py): eviction-policy state
machine, coordinator resize machinery on fake children, the data service's
validated world-resize re-deal, the planner's measured-margin feedback, the
elastic report/top sections, sentinel gates — and the slow-marked REAL
multi-process drills: a 2-process gloo fit over record shards (per-epoch
shard reassignment + the elastic re-deal on a world-1 resume) and the
headline host-death drill with final params bit-identical to a clean dp−1
run from the same checkpoint."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tensorflowdistributedlearning_tpu.data import records as rec
from tensorflowdistributedlearning_tpu.data import service as svc
from tensorflowdistributedlearning_tpu.parallel import elastic
from tensorflowdistributedlearning_tpu.parallel import planner
from tensorflowdistributedlearning_tpu.resilience import parse_fault_spec
from tensorflowdistributedlearning_tpu.resilience.faults import SITE_STEP

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_elastic  # noqa: E402
import regression_sentinel  # noqa: E402


# -- eviction policy ----------------------------------------------------------


def _policy(**kw):
    kw.setdefault("threshold", 1.25)
    kw.setdefault("sustained", 3)
    kw.setdefault("cooldown_s", 60.0)
    kw.setdefault("min_hosts", 1)
    return elastic.EvictionPolicy(**kw)


def test_eviction_fires_only_past_sustained_threshold():
    p = _policy(sustained=3)
    alert = {"worst_process": 2, "skew": 1.6}
    assert p.observe(0.0, 4, 10, alert) is None
    assert p.observe(1.0, 4, 11, alert) is None
    assert p.observe(2.0, 4, 12, alert) == 2  # third consecutive window


def test_stale_window_not_double_counted():
    p = _policy(sustained=2)
    alert = {"worst_process": 1, "skew": 2.0}
    assert p.observe(0.0, 4, 10, alert) is None
    # the same window observed again (polls outpace windows): not fresh
    assert p.observe(1.0, 4, 10, alert) is None
    assert p.observe(2.0, 4, 10, alert) is None
    assert p.observe(3.0, 4, 11, alert) == 1


def test_flapping_host_never_evicted():
    """A clean fresh window resets the streak — a host that is slow for
    sustained-1 windows then recovers never trips the eviction."""
    p = _policy(sustained=3)
    alert = {"worst_process": 2, "skew": 1.6}
    for start in (10, 20, 30):  # three bursts of 2 alerts + 1 clean window
        assert p.observe(0.0, 4, start, alert) is None
        assert p.observe(0.0, 4, start + 1, alert) is None
        assert p.observe(0.0, 4, start + 2, None) is None  # clean: reset
    # a different worst host also resets the streak
    assert p.observe(0.0, 4, 40, alert) is None
    assert p.observe(0.0, 4, 41, {"worst_process": 0, "skew": 1.5}) is None
    assert p.observe(0.0, 4, 42, alert) is None


def test_never_evicts_below_min_hosts():
    p = _policy(sustained=1, min_hosts=2)
    alert = {"worst_process": 1, "skew": 3.0}
    assert p.observe(0.0, 2, 10, alert) is None  # 2 - 1 < min_hosts
    assert p.observe(0.0, 3, 11, alert) == 1


def test_cooldown_blocks_eviction_cascade():
    p = _policy(sustained=1, cooldown_s=30.0)
    alert = {"worst_process": 1, "skew": 2.0}
    assert p.observe(0.0, 4, 10, alert) == 1
    p.notify_resize(10.0)
    # after the resize the NEW relative-slowest host alerts immediately (the
    # resized fleet re-warms) — cooldown must absorb it
    assert p.observe(20.0, 3, 11, {"worst_process": 0, "skew": 1.9}) is None
    assert p.observe(45.0, 3, 12, {"worst_process": 0, "skew": 1.9}) == 0


def test_skew_at_or_below_threshold_is_clean():
    p = _policy(sustained=1, threshold=1.5)
    assert p.observe(0.0, 4, 10, {"worst_process": 1, "skew": 1.5}) is None
    assert p.observe(0.0, 4, 11, {"worst_process": 1, "skew": 1.51}) == 1


# -- coordinator on fake children --------------------------------------------


class FakeChild:
    """Scripted child: ``rc_plan`` is the returncode it will exit with once
    ``exit_after`` polls elapsed (None = runs until signaled)."""

    _next_pid = 1000

    def __init__(self, rc=None, exit_after=0):
        FakeChild._next_pid += 1
        self.pid = FakeChild._next_pid
        self._rc = rc
        self._exit_after = exit_after
        self._polls = 0
        self.signals = []

    def poll(self):
        if self._rc is not None:
            self._polls += 1
            if self._polls > self._exit_after:
                return self._rc
        return None

    def send_signal(self, sig):
        self.signals.append(sig)
        # the preemption contract: a SIGTERMed child drains with rc 75
        self._rc = 75
        self._exit_after = 0

    def kill(self):
        self.signals.append(signal.SIGKILL)
        self._rc = -9
        self._exit_after = 0


def _coordinator(tmp_path, script, cfg=None, probe=None, plan_fn=None):
    """Coordinator over scripted generations: ``script[g]`` is a list of
    FakeChild factories for generation g (missing generations spawn clean
    children that exit 0 immediately)."""
    spawned = []

    def spawn(argv, env):
        gen = len([s for s in spawned if s[0] == "spawn"])  # not used
        return None  # replaced below

    calls = {"argv": [], "gen": -1, "idx": 0}

    def argv_fn(world, pid, coord, generation):
        if generation != calls["gen"]:
            calls["gen"] = generation
            calls["idx"] = 0
        calls["argv"].append(
            {"world": world, "pid": pid, "coord": coord, "gen": generation}
        )
        return ["child", str(world), str(pid)]

    children = []

    def spawn(argv, env):  # noqa: F811 — the real fake
        gen = calls["gen"]
        plan = script.get(gen, [])
        idx = calls["idx"]
        calls["idx"] += 1
        child = plan[idx]() if idx < len(plan) else FakeChild(rc=0)
        children.append(child)
        return child

    cfg = cfg or elastic.ElasticConfig(
        hosts=2,
        min_hosts=1,
        poll_interval_s=0.0,
        straggler_poll_s=0.0,
        drain_timeout_s=0.5,
        backoff_base_s=0.0,
        backoff_max_s=0.0,
        heartbeat_timeout_s=0.0,
    )
    coord = elastic.ElasticCoordinator(
        argv_fn,
        str(tmp_path),
        cfg,
        spawn=spawn,
        straggler_probe=probe or (lambda world: (None, None)),
        plan_fn=plan_fn,
        sleep=lambda s: None,
    )
    return coord, calls, children


def _events(tmp_path):
    out = []
    path = os.path.join(str(tmp_path), "telemetry.jsonl")
    if os.path.exists(path):
        for line in open(path, encoding="utf-8"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def test_coordinator_clean_world_completes(tmp_path):
    coord, calls, _ = _coordinator(
        tmp_path, {0: [lambda: FakeChild(rc=0), lambda: FakeChild(rc=0)]}
    )
    result = coord.run()
    assert result.ok and result.resizes == 0 and result.world_size == 2
    kinds = [e["event"] for e in _events(tmp_path)]
    assert kinds[0] == "elastic_start" and kinds[-1] == "elastic_end"
    assert "world_resize" not in kinds
    # both slots spawned with the coordinator address set (world > 1)
    assert [c["pid"] for c in calls["argv"]] == [0, 1]
    assert all(c["coord"] for c in calls["argv"])


def test_host_death_resizes_to_smaller_world(tmp_path):
    """A SIGKILLed child (rc -9) triggers drain + resize: the next
    generation spawns world-1 children (single host ⇒ no coordinator
    address), and the ledger carries the world_resize with the plan delta."""
    script = {
        # host 1 vanishes after a few polls; host 0 keeps running until the
        # drain SIGTERMs it (FakeChild then exits 75 — the preempt contract)
        0: [lambda: FakeChild(), lambda: FakeChild(rc=-9, exit_after=2)],
        1: [lambda: FakeChild(rc=0)],
    }
    plans = []

    def plan_fn(world, margin):
        plans.append((world, margin))
        return {
            "layout": {"data_parallel": world},
            "predicted": {"total_bytes_per_chip": 1000 * world},
        }

    coord, calls, children = _coordinator(tmp_path, script, plan_fn=plan_fn)
    result = coord.run()
    assert result.ok and result.resizes == 1 and result.world_size == 1
    gen1 = [c for c in calls["argv"] if c["gen"] == 1]
    assert [c["world"] for c in gen1] == [1]
    assert gen1[0]["coord"] is None  # single-host world: no cluster
    # the survivor was drained with SIGTERM
    assert signal.SIGTERM in children[0].signals
    resize = [e for e in _events(tmp_path) if e["event"] == "world_resize"]
    assert len(resize) == 1
    assert resize[0]["old_world"] == 2 and resize[0]["new_world"] == 1
    assert resize[0]["reason"] == "host_death"
    assert resize[0]["process_index"] == 1
    assert resize[0]["evicted_process"] is None
    assert resize[0]["rc"] == 137  # folded SIGKILL
    assert resize[0]["plan_old"]["layout"]["data_parallel"] == 2
    assert resize[0]["plan_new"]["layout"]["data_parallel"] == 1
    assert plans == [(2, None), (1, None)]


def test_resize_below_min_hosts_aborts(tmp_path):
    cfg = elastic.ElasticConfig(
        hosts=2, min_hosts=2, poll_interval_s=0.0, drain_timeout_s=0.5,
        backoff_base_s=0.0, heartbeat_timeout_s=0.0,
    )
    script = {0: [lambda: FakeChild(), lambda: FakeChild(rc=-9)]}
    coord, _, _ = _coordinator(tmp_path, script, cfg=cfg)
    result = coord.run()
    assert not result.ok and result.aborted == elastic.ABORT_MIN_HOSTS
    kinds = [e["event"] for e in _events(tmp_path)]
    assert "elastic_abort" in kinds and "world_resize" not in kinds


def test_plain_crash_restarts_same_shape(tmp_path):
    """A nonzero (non-SIGKILL) exit is a crash, not a host loss: the world
    respawns at the SAME size under the restart budget."""
    script = {
        0: [lambda: FakeChild(), lambda: FakeChild(rc=1, exit_after=1)],
        1: [lambda: FakeChild(rc=0), lambda: FakeChild(rc=0)],
    }
    coord, calls, _ = _coordinator(tmp_path, script)
    result = coord.run()
    assert result.ok and result.resizes == 0 and result.restarts == 1
    assert result.world_size == 2
    gen1 = [c for c in calls["argv"] if c["gen"] == 1]
    assert [c["world"] for c in gen1] == [2, 2]
    kinds = [e["event"] for e in _events(tmp_path)]
    assert "restart" in kinds and "world_resize" not in kinds


def test_progressless_resizes_do_not_feed_crash_loop(tmp_path):
    """Two quick host deaths before any ledger progress (normal spot churn
    during warm-up) must not pre-charge the crash-loop counter: the first
    ORDINARY crash afterwards still gets its same-shape restart."""
    cfg = elastic.ElasticConfig(
        hosts=3, min_hosts=1, poll_interval_s=0.0, straggler_poll_s=0.0,
        drain_timeout_s=0.5, backoff_base_s=0.0, heartbeat_timeout_s=0.0,
    )
    script = {
        0: [lambda: FakeChild(), lambda: FakeChild(),
            lambda: FakeChild(rc=-9, exit_after=1)],
        1: [lambda: FakeChild(), lambda: FakeChild(rc=-9, exit_after=1)],
        2: [lambda: FakeChild(rc=1, exit_after=1)],
        3: [lambda: FakeChild(rc=0)],
    }
    coord, _, _ = _coordinator(tmp_path, script, cfg=cfg)
    result = coord.run()
    assert result.ok, result
    assert result.resizes == 2 and result.restarts == 1
    assert result.aborted is None


def test_crash_loop_aborts(tmp_path):
    script = {
        g: [lambda: FakeChild(rc=1), lambda: FakeChild(rc=1)]
        for g in range(6)
    }
    coord, _, _ = _coordinator(tmp_path, script)
    result = coord.run()
    assert not result.ok
    assert result.aborted == elastic.ABORT_CRASH_LOOP


def test_straggler_eviction_resizes_with_events(tmp_path):
    """The live probe path: sustained fresh alerts on host 1 evict it —
    host_evicted + world_resize(straggler_evicted) land in the ledger and
    the next generation runs the smaller world."""
    steps = iter(range(100, 200))

    def probe(world):
        return next(steps), {"worst_process": 1, "skew": 1.8}

    cfg = elastic.ElasticConfig(
        hosts=2, min_hosts=1, poll_interval_s=0.0, straggler_poll_s=0.0,
        straggler_sustained=2, drain_timeout_s=0.5, backoff_base_s=0.0,
        heartbeat_timeout_s=0.0,
    )
    script = {
        0: [lambda: FakeChild(), lambda: FakeChild()],
        1: [lambda: FakeChild(rc=0)],
    }
    coord, calls, children = _coordinator(
        tmp_path, script, cfg=cfg, probe=probe
    )
    result = coord.run()
    assert result.ok and result.resizes == 1 and result.evictions == 1
    events = _events(tmp_path)
    evicted = [e for e in events if e["event"] == "host_evicted"]
    assert len(evicted) == 1 and evicted[0]["process_index"] == 1
    resize = [e for e in events if e["event"] == "world_resize"][0]
    assert resize["reason"] == "straggler_evicted"
    assert resize["evicted_process"] == 1
    # EVERY host was drained cooperatively (eviction keeps collectives live)
    assert signal.SIGTERM in children[0].signals
    assert signal.SIGTERM in children[1].signals


def test_ledger_straggler_probe_reads_current_world(tmp_path):
    """The default probe merges per-process ledgers, returns the newest
    cross-compared step and the alert at it, and excludes stale ledgers of
    slots outside the current world."""
    def write(path, proc, step_ms):
        with open(os.path.join(str(tmp_path), path), "w") as f:
            f.write(json.dumps({
                "event": "run_header", "t": 1.0, "process_index": proc,
            }) + "\n")
            for step, ms in step_ms:
                f.write(json.dumps({
                    "event": "step_window", "t": 2.0, "step": step,
                    "steps": 1, "step_time_ms": {"mean_ms": ms},
                }) + "\n")

    write("telemetry.jsonl", 0, [(1, 100.0), (2, 100.0)])
    write("telemetry-1.jsonl", 1, [(1, 100.0), (2, 250.0)])
    # a stale third ledger with absurd skew must be ignored at world 2
    write("telemetry-2.jsonl", 2, [(1, 9000.0), (2, 9000.0)])
    step, alert = elastic.ledger_straggler_probe(
        str(tmp_path), 2, threshold=1.25
    )
    assert step == 2
    # skew = worst / median; the 2-host median averages (100, 250) -> 175
    assert alert == {"worst_process": 1, "skew": 1.429}
    # at the full world the stale host dominates
    step3, alert3 = elastic.ledger_straggler_probe(
        str(tmp_path), 3, threshold=1.25
    )
    assert step3 == 2 and alert3["worst_process"] == 2


# -- data service: validated world-resize re-deal -----------------------------


def _shards(tmp_path, n=40, shards=3, hw=12, classes=5, seed=1):
    rng = np.random.default_rng(seed)
    images = [
        rng.integers(0, 255, (hw, hw, 3), dtype=np.uint8) for _ in range(n)
    ]
    labels = list(rng.integers(0, classes, n))
    return rec.write_classification_shards(
        str(tmp_path), images, labels, shards=shards
    )


def _source(paths, process_index=0, process_count=1):
    return svc.ClassificationRecordSource(
        paths, image_shape=(12, 12), channels=3,
        process_index=process_index, process_count=process_count,
    )


def test_redeal_accepts_changed_process_count(tmp_path):
    paths = _shards(tmp_path)
    old = svc.StreamingDataService(
        _source(paths, 0, 2), batch_size=8, seed=7, workers=1, start_batch=4,
    )
    sidecar = old.state(4).to_json()
    old.close()
    assert sidecar["process_count"] == 2
    resumed = svc.StreamingDataService(
        _source(paths, 0, 1), batch_size=8, seed=7, workers=1, start_batch=4,
        resume_state=sidecar,
    )
    assert resumed.redeal == {
        "old_process_count": 2, "new_process_count": 1, "batch_index": 4,
    }
    # the re-dealt stream is EXACTLY the stream a clean world-1 service
    # produces from the same (seed, batch_index) — the bit-identity half
    fresh = svc.StreamingDataService(
        _source(paths, 0, 1), batch_size=8, seed=7, workers=1, start_batch=4,
    )
    for a, b in zip(resumed.batches(steps=4), fresh.batches(steps=4)):
        assert np.array_equal(a["images"], b["images"])
        assert np.array_equal(a["labels"], b["labels"])


def test_redeal_still_refuses_real_mismatches(tmp_path):
    paths = _shards(tmp_path)
    service = svc.StreamingDataService(
        _source(paths, 0, 2), batch_size=8, seed=7, workers=1, start_batch=4,
    )
    sidecar = service.state(4).to_json()
    service.close()
    # wrong seed and wrong per-host batch still refuse even across a resize
    with pytest.raises(ValueError, match="resume state mismatch"):
        svc.StreamingDataService(
            _source(paths, 0, 1), batch_size=8, seed=8, workers=1,
            start_batch=4, resume_state=sidecar,
        )
    with pytest.raises(ValueError, match="resume state mismatch"):
        svc.StreamingDataService(
            _source(paths, 0, 1), batch_size=16, seed=7, workers=1,
            start_batch=4, resume_state=sidecar,
        )
    # changed shard SET refuses (re-sharding is not a world resize)
    with pytest.raises(ValueError, match="resume state mismatch"):
        svc.StreamingDataService(
            _source(paths[:-1], 0, 1), batch_size=8, seed=7, workers=1,
            start_batch=4, resume_state=sidecar,
        )
    # unchanged world: no redeal flagged
    ok = svc.StreamingDataService(
        _source(paths, 0, 2), batch_size=8, seed=7, workers=1, start_batch=4,
        resume_state=sidecar,
    )
    assert ok.redeal is None
    ok.close()


def test_array_source_carries_world_identity():
    source = svc.ArrayBatchSource(
        {"x": np.zeros((6, 2), np.float32)}, process_count=2
    )
    service = svc.StreamingDataService(
        source, batch_size=2, seed=1, workers=1
    )
    assert service.state(0).process_count == 2
    service.close()


# -- planner: measured-margin feedback ---------------------------------------


def _sds(shape, dtype=np.float32):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _margin_profile():
    count = 8 * 4
    return planner.ModelProfile(
        params={"w": _sds((8, 4))},
        batch_stats={},
        opt_state={"mu": _sds((8, 4))},
        activation_bytes_per_example=0,
        param_count=count,
    )


def test_measured_margin_tightens_budget():
    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig

    cfg = ModelConfig(
        num_classes=10, input_shape=(32, 32), input_channels=3,
        n_blocks=(1, 1, 1), base_depth=8, width_multiplier=0.0625,
        output_stride=None,
    )
    topo = planner.Topology(n_devices=8, local_device_count=8)
    profile = _margin_profile()
    # budget that fits every layout without margin (params+opt = 256 B)
    plan = planner.plan(
        cfg, TrainConfig(), 64, topology=topo, profile=profile,
        hbm_bytes_per_device=1000,
    )
    assert plan.chosen.feasible
    assert "measured_margin_bytes" not in (plan.chosen.bytes or {})
    # a measured residual bigger than the budget rejects everything
    with pytest.raises(planner.PlanError, match=planner.REJECT_BUDGET):
        planner.plan(
            cfg, TrainConfig(), 64, topology=topo, profile=profile,
            hbm_bytes_per_device=1000, measured_margin_bytes=2000,
        )
    # a margin that still fits rides the candidate's bytes + headroom
    plan = planner.plan(
        cfg, TrainConfig(), 64, topology=topo, profile=profile,
        hbm_bytes_per_device=1000, measured_margin_bytes=100,
    )
    assert plan.chosen.bytes["measured_margin_bytes"] == 100
    assert plan.chosen.bytes["total_bytes_per_chip"] >= 100


def test_measured_margin_from_workdir(tmp_path):
    from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger

    assert planner.measured_margin_from_workdir(str(tmp_path)) is None
    ledger = RunLedger(str(tmp_path))
    ledger.event("run_header", process_index=0)
    ledger.event(
        "memory_watermark", phase="step", peak_bytes=1000,
        predicted_bytes_per_device=800, measured_minus_predicted_bytes=200,
    )
    ledger.close()
    assert planner.measured_margin_from_workdir(str(tmp_path)) == 200
    # the fleet-wide WORST residual wins; negative residuals clamp to 0
    ledger = RunLedger(str(tmp_path), filename="telemetry-1.jsonl")
    ledger.event("run_header", process_index=1)
    ledger.event(
        "memory_watermark", phase="step", peak_bytes=1500,
        measured_minus_predicted_bytes=450,
    )
    ledger.close()
    assert planner.measured_margin_from_workdir(str(tmp_path)) == 450


# -- fault spec ---------------------------------------------------------------


def test_sigkill_step_fault_spec():
    spec = parse_fault_spec("sigkill-step@6")
    assert spec.kind == "sigkill-step" and spec.at == 6
    assert spec.site == SITE_STEP
    # the serve-side sigkill kind still parses as before
    assert parse_fault_spec("sigkill@30").site != SITE_STEP


# -- report / top -------------------------------------------------------------


def _elastic_history():
    t = [0.0]

    def ev(kind, **fields):
        t[0] += 1.0
        return {"event": kind, "t": t[0], **fields}

    return [
        ev("elastic_start", hosts=3, min_hosts=1),
        ev("run_header", process_index=0),
        ev("world_resize", old_world=3, new_world=2, reason="host_death",
           progress_step=7, downtime_s=4.5,
           plan_old={"layout": {"data_parallel": 3}},
           plan_new={"layout": {"data_parallel": 2}}),
        ev("host_evicted", process_index=1, skew=1.8, world_size=2, step=20),
        ev("world_resize", old_world=2, new_world=1,
           reason="straggler_evicted", evicted_process=1, progress_step=20,
           downtime_s=2.5),
        ev("data_redeal", step=20, old_process_count=2, new_process_count=1),
        ev("elastic_end", ok=True, world_size=1, resizes=2, restarts=0,
           evictions=1, resize_downtime_s=7.0),
    ]


def test_elastic_report_section():
    from tensorflowdistributedlearning_tpu.obs import report as report_lib

    section = report_lib._elastic_section(_elastic_history())
    assert section["hosts"] == 3 and section["world_size"] == 1
    assert section["resizes"] == 2 and section["evictions"] == 1
    assert section["data_redeals"] == 1
    assert section["resize_downtime_s"] == 7.0
    assert section["ok"] is True and section["live"] is False
    reasons = [e["reason"] for e in section["resize_events"]]
    assert reasons == ["host_death", "straggler_evicted"]
    assert section["resize_events"][1]["evicted_process"] == 1
    # no elastic history -> no section
    assert report_lib._elastic_section(
        [{"event": "run_header", "t": 0.0}]
    ) is None


def test_elastic_report_renders(tmp_path):
    """End to end through build_report/render_report on a synthesized
    workdir ledger."""
    from tensorflowdistributedlearning_tpu.obs import report as report_lib
    from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger

    ledger = RunLedger(str(tmp_path))
    for e in _elastic_history():
        kind = e.pop("event")
        e.pop("t")
        ledger.event(kind, **e)
    ledger.close()
    report = report_lib.build_report(str(tmp_path))
    assert report["elastic"]["resizes"] == 2
    rendered = report_lib.render_report(report)
    assert "elastic: world 3 -> 1" in rendered
    assert "straggler_evicted" in rendered
    assert "evicted host 1" in rendered


def test_top_frame_carries_elastic_row(tmp_path):
    from tensorflowdistributedlearning_tpu.obs import top as top_lib
    from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger

    ledger = RunLedger(str(tmp_path))
    ledger.event("run_header", process_index=0)
    ledger.event("elastic_start", hosts=2, min_hosts=1)
    ledger.event("world_resize", old_world=2, new_world=1,
                 reason="host_death", downtime_s=1.5)
    ledger.close()
    frame = top_lib.build_frame(str(tmp_path))
    assert frame["elastic"]["world_size"] == 1
    assert frame["elastic"]["live"] is True
    rendered = top_lib.render_frame(frame)
    assert "elastic: world 1/2 [LIVE]" in rendered


# -- CLI ----------------------------------------------------------------------


def test_fit_parser_accepts_elastic_flags():
    from tensorflowdistributedlearning_tpu import cli

    args = cli.build_parser().parse_args([
        "fit", "--preset", "elastic_smoke", "--model-dir", "/tmp/x",
        "--elastic", "2", "--min-hosts", "1", "--devices-per-host", "2",
        "--host-inject-fault", "1:sigkill-step@6",
    ])
    assert args.elastic == 2 and args.min_hosts == 1
    assert args.host_inject_fault == ["1:sigkill-step@6"]
    assert args.coordinator_address is None


def test_strip_elastic_flags_removes_coordinator_knobs():
    from tensorflowdistributedlearning_tpu import cli

    argv = [
        "fit", "--preset", "p", "--model-dir", "m", "--elastic", "2",
        "--min-hosts=1", "--batch-size", "16", "--no-straggler-evict",
        "--host-inject-fault", "1:sigkill-step@6", "--steps", "30",
        "--weight-update-sharding",
    ]
    assert cli._strip_elastic_flags(argv) == [
        "fit", "--preset", "p", "--model-dir", "m", "--steps", "30",
        "--weight-update-sharding",
    ]


def test_parse_host_faults_validates():
    from tensorflowdistributedlearning_tpu import cli

    assert cli._parse_host_faults(["1:sigkill-step@6", "0:raise@3"]) == {
        1: "sigkill-step@6", 0: "raise@3",
    }
    with pytest.raises(SystemExit):
        cli._parse_host_faults(["nonsense"])
    with pytest.raises(ValueError):
        cli._parse_host_faults(["1:bogus@2"])


# -- sentinel -----------------------------------------------------------------


def test_sentinel_elastic_passes_on_committed_baseline():
    rc = regression_sentinel.main(["--check", "--benches", "elastic"])
    assert rc == 0


def test_sentinel_elastic_fails_on_injected_regressions(tmp_path):
    with open(os.path.join(REPO, "BENCH_ELASTIC.json")) as f:
        record = json.load(f)
    bad = dict(record, bit_identical_resume=False)
    fresh = tmp_path / "bad.json"
    fresh.write_text(json.dumps(bad))
    rc = regression_sentinel.main([
        "--check", "--benches", "elastic", "--fresh-elastic", str(fresh),
    ])
    assert rc == 1
    # a drill that never resized must also fail
    bad = dict(record)
    bad["resize"] = dict(record["resize"], new_world=record["resize"]["old_world"])
    fresh.write_text(json.dumps(bad))
    rc = regression_sentinel.main([
        "--check", "--benches", "elastic", "--fresh-elastic", str(fresh),
    ])
    assert rc == 1


def test_bench_check_record_gates():
    with open(os.path.join(REPO, "BENCH_ELASTIC.json")) as f:
        record = json.load(f)
    assert bench_elastic.check_record(
        record, max_downtime_s=60.0, min_throughput_ratio=0.4
    ) == []
    broken = dict(record, bit_identical_resume=False)
    failures = bench_elastic.check_record(
        broken, max_downtime_s=60.0, min_throughput_ratio=0.4
    )
    assert any("bit_identical" in f for f in failures)


# -- REAL multi-process drills (slow) -----------------------------------------


def _gloo_unavailable():
    try:
        import jax

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return False
    except Exception:  # noqa: BLE001
        return True


@pytest.mark.slow
def test_two_process_fit_over_records_with_redeal_resume(tmp_path):
    """PR 12 follow-on made REAL: a 2-process gloo ``fit`` over record
    shards through the streaming data service (per-epoch shard reassignment
    exercised across >= 2 epochs), then a WORLD-1 resume of the same workdir
    — the elastic re-deal through the plain CLI (process_count 2 -> 1,
    ledgered ``data_redeal``), completing to the target step."""
    if _gloo_unavailable():
        pytest.skip("gloo CPU collectives unavailable")
    data_dir = str(tmp_path / "data")
    model_dir = str(tmp_path / "m")
    os.makedirs(data_dir)
    bench_elastic.write_drill_shards(data_dir, n=40, shards=3)

    def run_fit(steps, world, extra):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
        )
        argv_base = [
            sys.executable, "-m", "tensorflowdistributedlearning_tpu",
            "fit", "--preset", "elastic_smoke", "--model-dir", model_dir,
            "--data-dir", data_dir, "--steps", str(steps),
            "--batch-size", str(4 * world), "--eval-every", "100000",
        ]
        if world == 1:
            return [subprocess.run(
                argv_base + extra, env=env, capture_output=True, text=True,
                timeout=420,
            )]
        port = elastic.free_port()
        procs = [
            subprocess.Popen(
                argv_base + extra + [
                    "--coordinator-address", f"127.0.0.1:{port}",
                    "--num-processes", str(world), "--process-id", str(pid),
                ],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
            for pid in range(world)
        ]
        outs = []
        for p in procs:
            stdout, stderr = p.communicate(timeout=420)
            outs.append(subprocess.CompletedProcess(
                p.args, p.returncode, stdout, stderr
            ))
        return outs

    # 2-process fit across >= 2 epochs (40 records / 2 hosts ~ 20/epoch per
    # host; 10 steps x 4 = 40 virtual records per host)
    outs = run_fit(10, 2, [])
    for out in outs:
        assert out.returncode == 0, out.stderr[-1200:]
    assert os.path.exists(os.path.join(model_dir, "telemetry.jsonl"))
    assert os.path.exists(os.path.join(model_dir, "telemetry-1.jsonl"))
    # world-1 resume of the same workdir: validated re-deal, not a refusal
    outs = run_fit(14, 1, [])
    assert outs[0].returncode == 0, outs[0].stderr[-1200:]
    events = []
    for line in open(os.path.join(model_dir, "telemetry.jsonl")):
        try:
            events.append(json.loads(line))
        except ValueError:
            pass
    redeals = [e for e in events if e.get("event") == "data_redeal"]
    assert redeals and redeals[-1]["old_process_count"] == 2
    assert redeals[-1]["new_process_count"] == 1
    resumed = [e for e in events if e.get("event") == "resumed"]
    assert resumed and resumed[-1]["step"] == 10


@pytest.mark.slow
def test_headline_host_death_drill_bit_identical(tmp_path):
    """THE acceptance drill: SIGKILL one host of a 2-process elastic run
    (ZeRO-1 on, record shards through the data service) → coordinated drain
    → planner re-plan at dp−1 → resume with optimizer state resharded and
    the shard plan re-dealt → final params BIT-IDENTICAL to a clean dp−1
    run from the same checkpoint."""
    if _gloo_unavailable():
        pytest.skip("gloo CPU collectives unavailable")
    data_dir = str(tmp_path / "data")
    drill_dir = str(tmp_path / "drill")
    golden_dir = str(tmp_path / "golden")
    os.makedirs(data_dir)
    bench_elastic.write_drill_shards(data_dir)
    drill = bench_elastic.run_elastic_drill(
        drill_dir, data_dir, steps=12, kill_step=8, devices_per_host=2,
    )
    resize = drill["resize"]
    assert resize["old_world"] == 2 and resize["new_world"] == 1
    assert resize["reason"] == "host_death"
    assert drill["redeals"] >= 1
    bench_elastic.run_clean_comparison(
        golden_dir, data_dir, drill_dir, drill["resume_step"],
        steps=12, new_world=1, devices_per_host=2,
    )
    a = bench_elastic.params_digest(drill_dir)
    b = bench_elastic.params_digest(golden_dir)
    assert a["step"] == 12
    assert a == b, f"elastic resume diverged from the clean dp-1 oracle: {a} vs {b}"
