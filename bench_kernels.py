"""Microbenchmark: Pallas depthwise conv vs XLA grouped conv at ASPP shapes.

The Pallas VMEM shift-accumulate kernel (ops/pallas_kernels.py) exists on the
claim that XLA's grouped-convolution lowering of the depthwise stage is
VPU-suboptimal. This benchmark decides that claim on real hardware at exactly the
shapes the flagship runs: the ASPP head's atrous depthwise convs (rates 2/4/8 on
the [B, 13, 13, 1024] output-stride-8 feature map of a 101x101 input) and the
decoder's rate-1 conv. ``use_pallas_depthwise`` in the flagship preset should be
flipped on if and only if the Pallas column wins here.

Run: ``python bench_kernels.py [--platform=cpu]`` — prints one JSON line.
bench.py embeds the same measurement in its TPU child ("depthwise_kernels").
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict


def _chained(fn, repeats: int):
    """``fn`` applied ``repeats`` times inside ONE jitted program, output fed
    back as the first argument (every kernel here maps arg0's shape to
    itself). This is the r5 dispatch-latency fix: a single kernel call over
    the tunnel costs 35-135 ms of dispatch/sync for sub-millisecond device
    work, so unchained microbenches measured the TUNNEL (ratios compressed
    toward 1, earlier single-window swings of 0.9x-2.8x were pure dispatch
    noise in both directions). Chaining makes device work dominate the
    window; per-kernel time = call time / repeats. An rsqrt renorm keeps the
    iterates bounded. The renorm is an ADDITIVE shared cost c on both
    sides, which compresses ratios toward 1 by c/(kernel time); at these
    shapes c is a single elementwise pass (~20-100 MB at 819 GB/s, 25-120us)
    against per-kernel times of 4,600-26,000us — a <1% bias, far below the
    decision margins quoted from this file."""
    import jax
    import jax.numpy as jnp

    def run(x, *rest):
        def body(_, acc):
            y = fn(acc, *rest)
            scale = jax.lax.rsqrt(jnp.mean(jnp.square(y).astype(jnp.float32)) + 1e-6)
            return (y.astype(jnp.float32) * scale).astype(y.dtype)

        return jax.lax.fori_loop(0, repeats, body, x)

    return jax.jit(run)


def _paired_us(fn_a, fn_b, args, iters: int, warmup: int, trials: int = 5,
               repeats: int = 1):
    """A/B comparison robust to tunnel drift: r5 observed the SAME depthwise
    column swing 0.9x-2.8x across bench runs because each side got one
    sequential window and the tunnel's throughput drifts minute-to-minute.
    Here the two sides run in short INTERLEAVED trials (A,B,A,B,...) and the
    decision column is the MEDIAN of per-trial ratios — drift hits adjacent
    trials equally and cancels in the ratio; the median rejects stragglers.
    ``repeats`` chains the kernel inside each call (see ``_chained``) so
    device work dominates the tunnel's per-dispatch cost.
    Returns (a_us, b_us, b_over_a) as medians of PER-KERNEL microseconds."""
    from tensorflowdistributedlearning_tpu.utils.profiling import sync

    if repeats > 1:
        fn_a = _chained(fn_a, repeats)
        fn_b = _chained(fn_b, repeats)
    else:
        # repeats=1 must still time a compiled executable, not eager tracing
        import jax

        fn_a, fn_b = jax.jit(fn_a), jax.jit(fn_b)

    for fn in (fn_a, fn_b):  # compile + warm both before any timing
        out = fn(*args)
        sync(out)
        for _ in range(warmup):
            out = fn(*args)
        sync(out)

    def window(fn) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        sync(out)
        return (time.perf_counter() - t0) / (iters * repeats) * 1e6

    a_times, b_times, ratios = [], [], []
    for _ in range(trials):
        a = window(fn_a)
        b = window(fn_b)
        a_times.append(a)
        b_times.append(b)
        ratios.append(b / a)

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    return med(a_times), med(b_times), med(ratios)


def bench_depthwise(
    batch: int = 32,
    hw: int = 13,
    channels: int = 1024,
    rates=(1, 2, 4, 8),
    iters: int = 30,
    warmup: int = 5,
    repeats: int = 64,
) -> Dict:
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        depthwise_conv2d,
        depthwise_conv2d_reference,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, hw, hw, channels)).astype(np.float32)
    w = rng.normal(0, 0.3, (3, 3, channels)).astype(np.float32)
    x, w = jax.device_put(x), jax.device_put(w)

    results: Dict = {}
    wins = 0
    for rate in rates:
        pallas_us, xla_us, speedup = _paired_us(
            lambda a, b, r=rate: depthwise_conv2d(a, b, r),
            lambda a, b, r=rate: depthwise_conv2d_reference(a, b, r),
            (x, w), max(2, iters // 10), warmup, repeats=repeats,
        )
        results[f"rate{rate}"] = {
            "pallas_us": round(pallas_us, 1),
            "xla_us": round(xla_us, 1),
            "speedup": round(speedup, 3),
        }
        wins += speedup > 1.0
    results["pallas_wins"] = bool(wins > len(rates) / 2)
    results["shape"] = [batch, hw, hw, channels]
    return results


def bench_fused_bn_act(
    batch: int = 32,
    hw: int = 13,
    channels: int = 1024,
    iters: int = 30,
    warmup: int = 5,
    repeats: int = 64,
) -> Dict:
    """Fused inference BN+act(+residual) Pallas pass vs XLA's fusion at the
    serving-relevant shape: the ASPP feature map the step profile's dominant
    elementwise/BN bucket (PROFILE_SEG_r05.json: 53.2%) runs over. Both
    columns are HBM-roofline candidates — the question this answers is
    whether Mosaic's single VMEM pass beats XLA's elementwise fusion on real
    hardware, per variant (plain BN+relu, +residual)."""
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_bn_act,
        fused_bn_act_reference,
    )

    rng = np.random.default_rng(2)
    x = jax.device_put(
        rng.normal(0, 1, (batch, hw, hw, channels)).astype(np.float32)
    )
    r = jax.device_put(
        rng.normal(0, 1, (batch, hw, hw, channels)).astype(np.float32)
    )
    vecs = tuple(
        jax.device_put(v.astype(np.float32))
        for v in (
            rng.normal(1, 0.1, channels),
            rng.normal(0, 0.1, channels),
            rng.normal(0, 0.1, channels),
            rng.uniform(0.5, 1.5, channels),
        )
    )

    results: Dict = {}
    wins = 0
    for name, resid in (("bn_relu", False), ("bn_relu_residual", True)):
        pallas_us, xla_us, speedup = _paired_us(
            lambda a, rr: fused_bn_act(
                a, *vecs, residual=rr if resid else None
            ),
            lambda a, rr: fused_bn_act_reference(
                a, *vecs, residual=rr if resid else None
            ),
            (x, r), max(2, iters // 10), warmup, repeats=repeats,
        )
        results[name] = {
            "pallas_us": round(pallas_us, 1),
            "xla_us": round(xla_us, 1),
            "speedup": round(speedup, 3),
        }
        wins += speedup > 1.0
    results["pallas_wins"] = bool(wins == 2)
    results["shape"] = [batch, hw, hw, channels]
    return results


def bench_quant(
    batch: int = 64,
    features: int = 1024,
    hw: int = 13,
    conv_channels: int = 128,
    mask_hw: int = 101,
    iters: int = 30,
    warmup: int = 5,
    repeats: int = 64,
) -> Dict:
    """int8-compute kernels vs their dequantize-f32 XLA twins at the serving
    shapes (the quant model's dense width; the seg head's mask). On TPU the
    Pallas column is the real int8 x int8 -> int32 MXU kernel and the gate is
    a speedup floor; off-TPU ``int8_matmul``/``int8_conv2d`` auto-dispatch TO
    the reference, so the honest CPU column is a dispatch-overhead tripwire
    (ratio pinned ~1.0) — never the minutes-per-call interpreter. Weights are
    square / channel-preserving so the chained harness can feed outputs back
    as inputs."""
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        fused_sigmoid_mask,
        fused_sigmoid_mask_reference,
    )
    from tensorflowdistributedlearning_tpu.ops.quant_kernels import (
        int8_conv2d,
        int8_conv2d_reference,
        int8_matmul,
        int8_matmul_reference,
    )
    from tensorflowdistributedlearning_tpu.train.quantize import quantize_pytree

    rng = np.random.default_rng(3)

    def qweight(shape):
        qtree, _ = quantize_pytree(
            {"m": {"kernel": rng.normal(0, 0.5, shape).astype(np.float32)}},
            "int8",
        )
        rec = qtree["m"]["kernel"]
        return jax.device_put(rec["q"]), jax.device_put(rec["scale"])

    results: Dict = {}
    wins = 0

    x = jax.device_put(
        rng.normal(0, 1, (batch, features)).astype(np.float32)
    )
    wq, ws = qweight((features, features))
    mm_pallas, mm_xla, mm_speedup = _paired_us(
        lambda a: int8_matmul(a, wq, ws, act="relu"),
        lambda a: int8_matmul_reference(a, wq, ws, act="relu"),
        (x,), max(2, iters // 10), warmup, repeats=repeats,
    )
    results["matmul"] = {
        "pallas_us": round(mm_pallas, 1),
        "xla_us": round(mm_xla, 1),
        "speedup": round(mm_speedup, 3),
        "shape": [batch, features, features],
    }
    wins += mm_speedup > 1.0

    xc = jax.device_put(
        rng.normal(0, 1, (8, hw, hw, conv_channels)).astype(np.float32)
    )
    cq, cs = qweight((3, 3, conv_channels, conv_channels))
    cv_pallas, cv_xla, cv_speedup = _paired_us(
        lambda a: int8_conv2d(a, cq, cs, padding="SAME", act="relu"),
        lambda a: int8_conv2d_reference(a, cq, cs, padding="SAME", act="relu"),
        (xc,), max(2, iters // 10), warmup, repeats=repeats,
    )
    results["conv"] = {
        "pallas_us": round(cv_pallas, 1),
        "xla_us": round(cv_xla, 1),
        "speedup": round(cv_speedup, 3),
        "shape": [8, hw, hw, conv_channels],
    }
    wins += cv_speedup > 1.0

    logits = jax.device_put(
        rng.normal(0, 2, (8, mask_hw, mask_hw, 1)).astype(np.float32)
    )
    # both outputs consumed (p + m is shape/dtype-preserving for the chain)
    # so neither side can dead-code the mask
    sm_pallas, sm_xla, sm_speedup = _paired_us(
        lambda a: (lambda p, m: p + m)(*fused_sigmoid_mask(a, 0.5)),
        lambda a: (lambda p, m: p + m)(*fused_sigmoid_mask_reference(a, 0.5)),
        (logits,), max(2, iters // 10), warmup, repeats=repeats,
    )
    results["sigmoid_mask"] = {
        "pallas_us": round(sm_pallas, 1),
        "xla_us": round(sm_xla, 1),
        "speedup": round(sm_speedup, 3),
        "shape": [8, mask_hw, mask_hw, 1],
    }
    wins += sm_speedup > 1.0

    results["pallas_wins"] = bool(wins >= 2)
    return results


def bench_attention(
    batch: int = 32,
    heads: int = 6,
    head_dim: int = 64,
    seq_lens=(196, 1024),
    iters: int = 30,
    warmup: int = 5,
    train_cols: bool = True,
    on_forward_done=None,
    repeats: int = 16,
) -> Dict:
    """Fused Pallas block attention vs the XLA einsum path at ViT-S shapes
    (T=196 is ViT-S/16 at 224x224; T=1024 is the long-block regime the ring
    hands each device). bf16 inputs, float32 softmax both ways.

    Phase 1 measures the forward for EVERY seq_len, then calls
    ``on_forward_done(snapshot)`` (probe_attention prints it immediately);
    phase 2 adds the TRAINING value+grad columns — use_fused_attention rides
    the train step, so the flip decision must price the custom-vjp backward
    (which REBUILDS the score tile) against XLA's autodiff; a forward-only
    win that loses the backward is a net training loss. The train compiles
    are the big fresh-HLO work on the tunneled TPU, so a window that dies in
    phase 2 still leaves the phase-1 data. ``use_fused_attention`` should be
    flipped on iff ``pallas_wins`` (both phases won at most seq_lens)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.flash_attention import flash_attention
    from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
        attention_reference,
    )

    rng = np.random.default_rng(1)
    results: Dict = {}
    qkv = {}
    fwd_wins = {}
    for t in seq_lens:
        qkv[t] = tuple(
            jax.device_put(
                rng.normal(0, 1, (batch, t, heads, head_dim)).astype(np.float32)
            ).astype(jnp.bfloat16)
            for _ in range(3)
        )
        pallas_us, xla_us, speedup = _paired_us(
            lambda a, b, c: flash_attention(a, b, c),
            lambda a, b, c: attention_reference(a, b, c),
            qkv[t], max(2, iters // 10), warmup, repeats=repeats,
        )
        results[f"seq{t}"] = {
            "pallas_us": round(pallas_us, 1),
            "xla_us": round(xla_us, 1),
            "speedup": round(speedup, 3),
        }
        fwd_wins[t] = speedup > 1.0

    results["shape"] = [batch, "T", heads, head_dim]
    results["pallas_wins_fwd"] = bool(sum(fwd_wins.values()) > len(seq_lens) / 2)
    if on_forward_done is not None:
        # deep-enough copy: phase 2 updates the nested per-seq dicts in
        # place, and the snapshot must stay forward-only for a callback
        # that retains it
        on_forward_done(
            {
                key: (dict(value) if isinstance(value, dict) else value)
                for key, value in results.items()
            }
        )

    wins = 0
    if train_cols:
        def train_readout(fn):
            """fwd+bwd per chained iteration: the grad tuple is not shape-
            preserving, so the chain carries q through a tiny SGD-like update
            (one forward + one backward per repeat — the quantity the train
            step pays; same chain on both comparison sides)."""
            grad_fn = jax.grad(
                lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32)),
                argnums=(0, 1, 2),  # full backward — all of dq/dk/dv, as the
                # train step pays; q/k/v share one shape so the sum chains
            )

            def one(a, b, c):
                gq, gk, gv = grad_fn(a, b, c)
                upd = (gq.astype(jnp.float32) + gk.astype(jnp.float32)
                       + gv.astype(jnp.float32))
                return (a.astype(jnp.float32) - 1e-3 * upd).astype(a.dtype)

            return one

        for t in seq_lens:
            pallas_train_us, xla_train_us, speedup_train = _paired_us(
                train_readout(flash_attention),
                train_readout(attention_reference),
                qkv[t], max(2, iters // 10), warmup,
                repeats=max(repeats // 2, 1),
            )
            results[f"seq{t}"].update(
                {
                    "pallas_train_us": round(pallas_train_us, 1),
                    "xla_train_us": round(xla_train_us, 1),
                    "speedup_train": round(speedup_train, 3),
                }
            )
            wins += fwd_wins[t] and speedup_train > 1.0
    else:
        wins = sum(fwd_wins.values())
    results["pallas_wins"] = bool(wins > len(seq_lens) / 2)
    return results


def main() -> None:
    import jax

    if "--platform=cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() == "tpu":
        out = bench_depthwise()
    else:
        # chained repeats through the Pallas interpreter are minutes-per-call;
        # tiny everything keeps the CPU smoke bounded
        out = bench_depthwise(batch=2, hw=5, channels=8, iters=2, warmup=1,
                              repeats=2)
    out["platform"] = jax.default_backend()
    print(json.dumps(out), flush=True)
    if jax.default_backend() == "tpu":
        bn = bench_fused_bn_act()
    else:
        bn = bench_fused_bn_act(batch=2, hw=5, channels=8, iters=2, warmup=1,
                                repeats=2)
    bn["platform"] = jax.default_backend()
    print(json.dumps({"fused_bn_act": bn}), flush=True)
    if jax.default_backend() == "tpu":
        qk = bench_quant()
    else:
        qk = bench_quant(batch=4, features=32, hw=5, conv_channels=8,
                         mask_hw=9, iters=2, warmup=1, repeats=2)
    qk["platform"] = jax.default_backend()
    print(json.dumps({"quant_kernels": qk}), flush=True)
    if jax.default_backend() == "tpu":
        attn = bench_attention()
    else:
        # off-TPU the kernel runs in the (slow) Pallas interpreter; tiny shapes
        # keep the smoke run bounded — the decision data only means anything on
        # real hardware anyway
        attn = bench_attention(batch=2, seq_lens=(64,), iters=2, warmup=1,
                               repeats=2)
    attn["platform"] = jax.default_backend()
    print(json.dumps({"attention": attn}), flush=True)


if __name__ == "__main__":
    main()
