"""Microbenchmark: Pallas depthwise conv vs XLA grouped conv at ASPP shapes.

The Pallas VMEM shift-accumulate kernel (ops/pallas_kernels.py) exists on the
claim that XLA's grouped-convolution lowering of the depthwise stage is
VPU-suboptimal. This benchmark decides that claim on real hardware at exactly the
shapes the flagship runs: the ASPP head's atrous depthwise convs (rates 2/4/8 on
the [B, 13, 13, 1024] output-stride-8 feature map of a 101x101 input) and the
decoder's rate-1 conv. ``use_pallas_depthwise`` in the flagship preset should be
flipped on if and only if the Pallas column wins here.

Run: ``python bench_kernels.py [--platform=cpu]`` — prints one JSON line.
bench.py embeds the same measurement in its TPU child ("depthwise_kernels").
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict


def _timed_us(fn, args, iters: int, warmup: int) -> float:
    """Shared measurement protocol for every kernel comparison in this file:
    compile once, warm up, then one synchronized timed loop (microseconds per
    call). Keeping one copy keeps the pallas/XLA decision columns comparable.

    Synchronizes via ``profiling.sync`` (a real value fetch): on the tunneled
    TPU backend ``block_until_ready`` alone has been observed to return before
    execution finishes, inflating throughput ~10x (see bench.py's measure)."""
    from tensorflowdistributedlearning_tpu.utils.profiling import sync

    out = fn(*args)  # compile
    sync(out)
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters * 1e6


def bench_depthwise(
    batch: int = 32,
    hw: int = 13,
    channels: int = 1024,
    rates=(1, 2, 4, 8),
    iters: int = 30,
    warmup: int = 5,
) -> Dict:
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        depthwise_conv2d,
        depthwise_conv2d_reference,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, hw, hw, channels)).astype(np.float32)
    w = rng.normal(0, 0.3, (3, 3, channels)).astype(np.float32)
    x, w = jax.device_put(x), jax.device_put(w)

    results: Dict = {}
    wins = 0
    for rate in rates:
        pallas_us = _timed_us(
            jax.jit(lambda a, b, r=rate: depthwise_conv2d(a, b, r)),
            (x, w), iters, warmup,
        )
        xla_us = _timed_us(
            jax.jit(lambda a, b, r=rate: depthwise_conv2d_reference(a, b, r)),
            (x, w), iters, warmup,
        )
        results[f"rate{rate}"] = {
            "pallas_us": round(pallas_us, 1),
            "xla_us": round(xla_us, 1),
            "speedup": round(xla_us / pallas_us, 3),
        }
        wins += pallas_us < xla_us
    results["pallas_wins"] = bool(wins > len(rates) / 2)
    results["shape"] = [batch, hw, hw, channels]
    return results


def bench_attention(
    batch: int = 32,
    heads: int = 6,
    head_dim: int = 64,
    seq_lens=(196, 1024),
    iters: int = 30,
    warmup: int = 5,
    train_cols: bool = True,
    on_forward_done=None,
) -> Dict:
    """Fused Pallas block attention vs the XLA einsum path at ViT-S shapes
    (T=196 is ViT-S/16 at 224x224; T=1024 is the long-block regime the ring
    hands each device). bf16 inputs, float32 softmax both ways.

    Phase 1 measures the forward for EVERY seq_len, then calls
    ``on_forward_done(snapshot)`` (probe_attention prints it immediately);
    phase 2 adds the TRAINING value+grad columns — use_fused_attention rides
    the train step, so the flip decision must price the custom-vjp backward
    (which REBUILDS the score tile) against XLA's autodiff; a forward-only
    win that loses the backward is a net training loss. The train compiles
    are the big fresh-HLO work on the tunneled TPU, so a window that dies in
    phase 2 still leaves the phase-1 data. ``use_fused_attention`` should be
    flipped on iff ``pallas_wins`` (both phases won at most seq_lens)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.flash_attention import flash_attention
    from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
        attention_reference,
    )

    rng = np.random.default_rng(1)
    results: Dict = {}
    qkv = {}
    fwd_wins = {}
    for t in seq_lens:
        qkv[t] = tuple(
            jax.device_put(
                rng.normal(0, 1, (batch, t, heads, head_dim)).astype(np.float32)
            ).astype(jnp.bfloat16)
            for _ in range(3)
        )
        pallas_us = _timed_us(
            jax.jit(lambda a, b, c: flash_attention(a, b, c)), qkv[t], iters, warmup
        )
        xla_us = _timed_us(
            jax.jit(lambda a, b, c: attention_reference(a, b, c)), qkv[t], iters, warmup
        )
        results[f"seq{t}"] = {
            "pallas_us": round(pallas_us, 1),
            "xla_us": round(xla_us, 1),
            "speedup": round(xla_us / pallas_us, 3),
        }
        fwd_wins[t] = pallas_us < xla_us

    results["shape"] = [batch, "T", heads, head_dim]
    results["pallas_wins_fwd"] = bool(sum(fwd_wins.values()) > len(seq_lens) / 2)
    if on_forward_done is not None:
        # deep-enough copy: phase 2 updates the nested per-seq dicts in
        # place, and the snapshot must stay forward-only for a callback
        # that retains it
        on_forward_done(
            {
                key: (dict(value) if isinstance(value, dict) else value)
                for key, value in results.items()
            }
        )

    wins = 0
    if train_cols:
        def train_readout(fn):
            def loss(a, b, c):
                return jnp.sum(fn(a, b, c).astype(jnp.float32))

            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        for t in seq_lens:
            pallas_train_us = _timed_us(
                train_readout(flash_attention), qkv[t], iters, warmup
            )
            xla_train_us = _timed_us(
                train_readout(attention_reference), qkv[t], iters, warmup
            )
            results[f"seq{t}"].update(
                {
                    "pallas_train_us": round(pallas_train_us, 1),
                    "xla_train_us": round(xla_train_us, 1),
                    "speedup_train": round(xla_train_us / pallas_train_us, 3),
                }
            )
            wins += fwd_wins[t] and (pallas_train_us < xla_train_us)
    else:
        wins = sum(fwd_wins.values())
    results["pallas_wins"] = bool(wins > len(seq_lens) / 2)
    return results


def main() -> None:
    import jax

    if "--platform=cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    out = bench_depthwise()
    out["platform"] = jax.default_backend()
    print(json.dumps(out), flush=True)
    if jax.default_backend() == "tpu":
        attn = bench_attention()
    else:
        # off-TPU the kernel runs in the (slow) Pallas interpreter; tiny shapes
        # keep the smoke run bounded — the decision data only means anything on
        # real hardware anyway
        attn = bench_attention(batch=2, seq_lens=(64,), iters=3, warmup=1)
    attn["platform"] = jax.default_backend()
    print(json.dumps({"attention": attn}), flush=True)


if __name__ == "__main__":
    main()
