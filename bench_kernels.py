"""Microbenchmark: Pallas depthwise conv vs XLA grouped conv at ASPP shapes.

The Pallas VMEM shift-accumulate kernel (ops/pallas_kernels.py) exists on the
claim that XLA's grouped-convolution lowering of the depthwise stage is
VPU-suboptimal. This benchmark decides that claim on real hardware at exactly the
shapes the flagship runs: the ASPP head's atrous depthwise convs (rates 2/4/8 on
the [B, 13, 13, 1024] output-stride-8 feature map of a 101x101 input) and the
decoder's rate-1 conv. ``use_pallas_depthwise`` in the flagship preset should be
flipped on if and only if the Pallas column wins here.

Run: ``python bench_kernels.py [--platform=cpu]`` — prints one JSON line.
bench.py embeds the same measurement in its TPU child ("depthwise_kernels").
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict


def bench_depthwise(
    batch: int = 32,
    hw: int = 13,
    channels: int = 1024,
    rates=(1, 2, 4, 8),
    iters: int = 30,
    warmup: int = 5,
) -> Dict:
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.ops.pallas_kernels import (
        depthwise_conv2d,
        depthwise_conv2d_reference,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, hw, hw, channels)).astype(np.float32)
    w = rng.normal(0, 0.3, (3, 3, channels)).astype(np.float32)
    x, w = jax.device_put(x), jax.device_put(w)

    def timed(fn) -> float:
        out = fn(x, w)  # compile
        jax.block_until_ready(out)
        for _ in range(warmup):
            out = fn(x, w)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(x, w)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e6  # us

    results: Dict = {}
    wins = 0
    for rate in rates:
        pallas_us = timed(jax.jit(lambda a, b, r=rate: depthwise_conv2d(a, b, r)))
        xla_us = timed(
            jax.jit(lambda a, b, r=rate: depthwise_conv2d_reference(a, b, r))
        )
        results[f"rate{rate}"] = {
            "pallas_us": round(pallas_us, 1),
            "xla_us": round(xla_us, 1),
            "speedup": round(xla_us / pallas_us, 3),
        }
        wins += pallas_us < xla_us
    results["pallas_wins"] = bool(wins > len(rates) / 2)
    results["shape"] = [batch, hw, hw, channels]
    return results


def main() -> None:
    import jax

    if "--platform=cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    out = bench_depthwise()
    out["platform"] = jax.default_backend()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
