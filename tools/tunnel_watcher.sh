#!/bin/bash
# Probe the axon TPU tunnel every ~3 min; the moment jax.devices() answers,
# run tools/window_sprint.py (the standing order: first window goes to the
# pending hardware probes). Appends a status line per probe to the log so a
# supervisor can see liveness; exits after window_sprint completes so the
# driver can decide what the NEXT window is for.
#
# Usage: setsid nohup bash tools/tunnel_watcher.sh >> /tmp/tunnel_watcher.log 2>&1 &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
while true; do
  ts=$(date -u '+%Y-%m-%d %H:%M:%S')
  if timeout 75 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; then
    echo "[$ts] TUNNEL UP - launching window_sprint"
    python tools/window_sprint.py
    rc=$?
    echo "[$(date -u '+%Y-%m-%d %H:%M:%S')] window_sprint finished rc=$rc"
    exit 0
  fi
  echo "[$ts] tunnel down"
  # 3-minute cadence: r3 windows lasted ~30 min — every minute of detection
  # lag is a minute of lost hardware evidence; the down-probe itself is cheap
  sleep 180
done
