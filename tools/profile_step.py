"""Profile one preset's train step on the current backend and print the XLA op
breakdown.

This is the "where does the time go" probe VERDICT r2 asked for: it builds the
SAME train step bench.py measures (preset model config, shard_map step,
AOT-compiled executable, profiling.sync value-fetch barrier), captures a
``jax.profiler`` trace around N timed steps, and folds the device plane into
coarse buckets with utils/xplane.py.

Usage (TPU tunnel or CPU):
    python tools/profile_step.py --preset resnet50_classic_imagenet \
        --batch 256 --steps 5 --logdir /tmp/prof
Prints one JSON line: {"preset", "step_time_ms", "buckets": {...}, "top_ops": [...]}.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--preset", default="resnet50_classic_imagenet")
    parser.add_argument("--batch", type=int, default=256, help="per-chip batch")
    parser.add_argument("--steps", type=int, default=5, help="traced steps")
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--logdir", default="/tmp/tfdl_profile")
    parser.add_argument("--top", type=int, default=15)
    parser.add_argument(
        "--s2d",
        action="store_true",
        help="override stem_space_to_depth=True on the preset's model config",
    )
    parser.add_argument(
        "--no-trace",
        action="store_true",
        help="timing only (skip jax.profiler; faster, no breakdown)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        help="force a backend (e.g. cpu) — set via jax.config because this "
        "image's sitecustomize pre-imports jax (env vars are too late)",
    )
    args = parser.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache_tpu")
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # noqa: BLE001
        pass

    import numpy as np

    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.configs import PRESETS
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        make_mesh,
        replicate,
        shard_batch,
    )
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.data.synthetic import (
        synthetic_segmentation_batch,
    )
    from tensorflowdistributedlearning_tpu.train.step import (
        ClassificationTask,
        SegmentationTask,
        make_optimizer,
        make_train_step,
    )
    from tensorflowdistributedlearning_tpu.utils import xplane
    from tensorflowdistributedlearning_tpu.utils.profiling import sync, trace

    cfg = PRESETS[args.preset].model
    if args.s2d:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, stem_space_to_depth=True)
    devices = jax.devices()
    n = len(devices)
    mesh = make_mesh(n)
    model = build_model(cfg)
    h, w = cfg.input_shape
    sample = np.zeros((1, h, w, cfg.input_channels), np.float32)
    state = replicate(
        create_train_state(model, make_optimizer(TrainConfig()), jax.random.PRNGKey(0), sample),
        mesh,
    )
    gen = np.random.default_rng(0)
    global_b = args.batch * n
    # segmentation presets (tgs_salt*) have no class count — dense [B,H,W,1]
    # labels and the SegmentationTask loss; classification presets get the
    # integer-label task bench.py's headline measures
    if cfg.num_classes:
        batch = {
            "images": gen.normal(0, 1, (global_b, h, w, cfg.input_channels)).astype(
                np.float32
            ),
            "labels": gen.integers(0, cfg.num_classes, global_b).astype(np.int32),
        }
        task = ClassificationTask()
    else:
        batch = synthetic_segmentation_batch(
            gen, global_b, input_shape=(h, w), channels=cfg.input_channels
        )
        task = SegmentationTask()
    batch = shard_batch(batch, mesh)
    step = make_train_step(mesh, task, donate=False)
    comp = step.lower(state, batch).compile()
    s = state
    for _ in range(max(args.warmup, 1)):  # >=1: the timed loop needs a synced start
        s, metrics = comp(s, batch)
    sync(metrics)

    import contextlib

    t0 = time.perf_counter()
    with contextlib.nullcontext() if args.no_trace else trace(args.logdir):
        for _ in range(args.steps):
            s, metrics = comp(s, batch)
        sync(metrics)
    dt = time.perf_counter() - t0

    if args.no_trace:
        print(
            json.dumps(
                {
                    "preset": args.preset,
                    "s2d": args.s2d,
                    "platform": devices[0].platform,
                    "global_batch": global_b,
                    "step_time_ms": round(dt / args.steps * 1000, 2),
                    "images_per_sec_per_chip": round(
                        global_b * args.steps / dt / n, 1
                    ),
                }
            ),
            flush=True,
        )
        return 0

    plane = "TPU" if devices[0].platform == "tpu" else "/host:CPU"
    rows = xplane.op_breakdown(args.logdir, plane_filter=plane)
    print(
        json.dumps(
            {
                "preset": args.preset,
                "platform": devices[0].platform,
                "global_batch": global_b,
                "step_time_ms": round(dt / args.steps * 1000, 2),
                "planes": xplane.plane_names(args.logdir),
                "buckets_ms": xplane.grouped_breakdown(rows),
                "top_ops": [dataclasses.asdict(r) for r in rows[: args.top]],
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
