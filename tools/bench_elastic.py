"""Elastic resize bench: the headline host-death drill, measured.

Drives the REAL ``fit --elastic`` CLI end to end on the CPU pod harness:

1. writes tiny classification record shards;
2. runs a 2-host elastic world (``--devices-per-host 2`` → a dp4 mesh with
   ZeRO-1 on) fed by the streaming data service, with
   ``--host-inject-fault 1:sigkill-step@K`` vanishing host 1 after step K —
   the un-drainable host death;
3. lets the coordinator detect the death, drain the survivor (bounded — its
   collectives point at a dead peer), re-plan at world 1 via the parallelism
   planner, and resume with ZeRO-1 optimizer state resharded dp4→dp2 and the
   data service re-dealt to the new ``process_count``;
4. replays a CLEAN dp−1 run from the SAME checkpoint (copied resume-step
   checkpoint + data-state sidecar into a fresh workdir) and requires the
   final params BIT-IDENTICAL — the proof that the elastic path introduces
   no hidden state;
5. records the measured resize downtime and throughput-per-chip before/after
   the resize (from the ledger's ``cost`` events) into BENCH_ELASTIC.json.

``--check`` gates the result; the COMMITTED BENCH_ELASTIC.json replays as
hard gates in tools/regression_sentinel.py (an elastic-path PR must re-run
this bench and commit numbers that still clear them)::

    python tools/bench_elastic.py --check --json-out BENCH_ELASTIC.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRESET = "elastic_smoke"
# per-host batch of the drill (global batch = LOCAL_BS * world — the elastic
# contract keeps the per-host batch constant across resizes, so the data
# sidecar revalidates and the stream re-deals instead of refusing)
LOCAL_BS = 4


def _env(devices: int) -> Dict[str, str]:
    return dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={devices}",
    )


def write_drill_shards(data_dir: str, *, n: int = 48, shards: int = 3) -> None:
    """Record shards matching the ``elastic_smoke`` preset's input shape, in
    a subprocess (shard writing needs no devices and must not initialize jax
    in the bench process)."""
    code = f"""
import sys
sys.path.insert(0, {REPO!r})
import numpy as np
from tensorflowdistributedlearning_tpu.data import records as rec
rng = np.random.default_rng(5)
images = [rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
          for _ in range({n})]
labels = list(rng.integers(0, 4, {n}))
rec.write_classification_shards({data_dir!r}, images, labels,
                                shards={shards})
"""
    subprocess.run(
        [sys.executable, "-c", code], env=_env(1), check=True,
        capture_output=True, text=True, cwd=REPO,
    )


def _read_ledger(path: str) -> List[Dict]:
    events = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return events


def run_elastic_drill(
    workdir: str,
    data_dir: str,
    *,
    steps: int = 12,
    kill_step: int = 8,
    hosts: int = 2,
    devices_per_host: int = 2,
    zero1: bool = True,
    drain_timeout: float = 30.0,
    timeout: int = 600,
    extra_argv: Optional[List[str]] = None,
) -> Dict:
    """The headline drill through the real CLI. Returns the measured facts;
    raises RuntimeError when the run itself failed. ``extra_argv`` appends
    drill variations (bench_coldstart reuses this for --compile-cache-dir /
    --aot-standby runs)."""
    argv = [
        sys.executable, "-m", "tensorflowdistributedlearning_tpu", "fit",
        "--preset", PRESET,
        "--model-dir", workdir,
        "--data-dir", data_dir,
        "--steps", str(steps),
        "--batch-size", str(LOCAL_BS * hosts),
        "--eval-every", "100000",
        "--elastic", str(hosts),
        "--min-hosts", "1",
        "--devices-per-host", str(devices_per_host),
        "--host-inject-fault", f"{hosts - 1}:sigkill-step@{kill_step}",
        "--drain-timeout", str(drain_timeout),
    ]
    if zero1:
        argv.append("--weight-update-sharding")
    if extra_argv:
        argv.extend(extra_argv)
    t0 = time.time()
    out = subprocess.run(
        argv, env=_env(devices_per_host), capture_output=True, text=True,
        timeout=timeout, cwd=REPO,
    )
    wall_s = time.time() - t0
    verdict_lines = [
        ln for ln in out.stderr.splitlines() if ln.startswith('{"elastic"')
    ]
    if out.returncode != 0 or not verdict_lines:
        raise RuntimeError(
            f"elastic drill failed rc={out.returncode}: "
            f"{out.stderr[-1500:]}"
        )
    verdict = json.loads(verdict_lines[-1])
    events = _read_ledger(os.path.join(workdir, "telemetry.jsonl"))
    resizes = [e for e in events if e.get("event") == "world_resize"]
    resumed = [e for e in events if e.get("event") == "resumed"]
    redeals = [e for e in events if e.get("event") == "data_redeal"]
    if not verdict.get("ok") or not resizes or not resumed:
        raise RuntimeError(
            f"drill did not resize+resume: verdict={verdict}, "
            f"resizes={len(resizes)}, resumed={len(resumed)}"
        )
    return {
        "verdict": verdict,
        "resize": resizes[-1],
        "resume_step": resumed[-1]["step"],
        "redeals": len(redeals),
        "wall_s": round(wall_s, 3),
        "events": events,
    }


def run_clean_comparison(
    golden_dir: str,
    data_dir: str,
    drill_dir: str,
    resume_step: int,
    *,
    steps: int = 12,
    new_world: int = 1,
    devices_per_host: int = 2,
    zero1: bool = True,
    timeout: int = 420,
) -> None:
    """A clean dp−1 run from the drill's resume checkpoint: copy that step's
    checkpoint + data-state sidecar into a fresh workdir and run plain
    ``fit`` at the post-resize world size. Its final params are the oracle
    the elastic run must match bit-for-bit."""
    ckpt_src = os.path.join(drill_dir, "checkpoints", str(resume_step))
    if not os.path.isdir(ckpt_src):
        raise RuntimeError(
            f"resume-step checkpoint {resume_step} was pruned from "
            f"{drill_dir} — shorten the drill (max_to_keep must retain it)"
        )
    os.makedirs(os.path.join(golden_dir, "checkpoints"), exist_ok=True)
    shutil.copytree(
        ckpt_src, os.path.join(golden_dir, "checkpoints", str(resume_step))
    )
    sidecar = os.path.join(
        drill_dir, "checkpoints", f"data_state-{resume_step}.json"
    )
    if os.path.exists(sidecar):
        shutil.copy(sidecar, os.path.join(golden_dir, "checkpoints"))
    argv = [
        sys.executable, "-m", "tensorflowdistributedlearning_tpu", "fit",
        "--preset", PRESET,
        "--model-dir", golden_dir,
        "--data-dir", data_dir,
        "--steps", str(steps),
        "--batch-size", str(LOCAL_BS * new_world),
        "--eval-every", "100000",
    ]
    if zero1:
        argv.append("--weight-update-sharding")
    out = subprocess.run(
        argv, env=_env(devices_per_host), capture_output=True, text=True,
        timeout=timeout, cwd=REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"clean comparison run failed rc={out.returncode}: "
            f"{out.stderr[-1500:]}"
        )


def params_digest(model_dir: str, timeout: int = 240) -> Dict:
    """sha256 over the latest checkpoint's params+batch_stats leaves,
    computed in a subprocess (fresh interpreter, single device — the digest
    must not depend on the caller's jax state)."""
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--digest", model_dir],
        env=_env(1), capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"digest of {model_dir} failed rc={out.returncode}: "
            f"{out.stderr[-800:]}"
        )
    return json.loads(lines[-1])


def _cmd_digest(model_dir: str) -> int:
    import hashlib

    sys.path.insert(0, REPO)
    import jax
    import numpy as np

    from tensorflowdistributedlearning_tpu.configs import get_preset
    from tensorflowdistributedlearning_tpu.train.fit import ClassifierTrainer

    preset = get_preset(PRESET)
    trainer = ClassifierTrainer(model_dir, None, preset.model, preset.train)
    ckpt = trainer._checkpointer()
    try:
        state = ckpt.restore_latest(trainer._host_template())
    finally:
        ckpt.close()
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(
        {"p": state.params, "bs": state.batch_stats}
    ):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    print(json.dumps({
        "step": int(jax.device_get(state.step)),
        "digest": h.hexdigest(),
    }))
    return 0


def throughput_per_chip_split(events: List[Dict], resize_t: float) -> Dict:
    """Median ``examples_per_chip_second`` of the clean cost windows before
    vs after the resize timestamp — the per-chip efficiency the resize must
    roughly preserve (each generation pays one fresh compile, excluded by
    taking the median, not the mean)."""

    def med(rows: List[float]) -> Optional[float]:
        return round(statistics.median(rows), 3) if rows else None

    before, after = [], []
    for e in events:
        if e.get("event") != "cost" or e.get("scope") != "train":
            continue
        rate = e.get("examples_per_chip_second")
        if rate is None:
            continue
        (before if e.get("t", 0) < resize_t else after).append(float(rate))
    out = {
        "before": med(before),
        "after": med(after),
        "windows_before": len(before),
        "windows_after": len(after),
    }
    if out["before"] and out["after"]:
        out["after_over_before"] = round(out["after"] / out["before"], 4)
    return out


def run_bench(args) -> Dict:
    with tempfile.TemporaryDirectory(prefix="bench_elastic_") as tmp:
        data_dir = os.path.join(tmp, "data")
        drill_dir = os.path.join(tmp, "drill")
        golden_dir = os.path.join(tmp, "golden")
        os.makedirs(data_dir)
        write_drill_shards(data_dir)
        drill = run_elastic_drill(
            drill_dir, data_dir,
            steps=args.steps, kill_step=args.kill_step,
            devices_per_host=args.devices_per_host,
            timeout=args.timeout,
        )
        resize = drill["resize"]
        run_clean_comparison(
            golden_dir, data_dir, drill_dir, drill["resume_step"],
            steps=args.steps, new_world=resize["new_world"],
            devices_per_host=args.devices_per_host,
        )
        a = params_digest(drill_dir)
        b = params_digest(golden_dir)
        record = {
            "bench": "elastic",
            "preset": PRESET,
            "hosts": 2,
            "devices_per_host": args.devices_per_host,
            "steps": args.steps,
            "kill_step": args.kill_step,
            "zero1": True,
            "resize": {
                k: resize.get(k)
                for k in (
                    "old_world", "new_world", "reason", "progress_step",
                    "downtime_s", "rc",
                )
            },
            "resume_step": drill["resume_step"],
            "data_redeals": drill["redeals"],
            "final_step": a["step"],
            "bit_identical_resume": a == b,
            "throughput_per_chip": throughput_per_chip_split(
                drill["events"], resize["t"]
            ),
            "resize_downtime_s": drill["verdict"]["resize_downtime_s"],
            "wall_s": drill["wall_s"],
        }
    return record


def check_record(
    record: Dict,
    *,
    max_downtime_s: float,
    min_throughput_ratio: float,
) -> List[str]:
    """The bench's own gate (the sentinel replays the committed record with
    the same rules). Returns failure strings; empty = pass."""
    failures = []
    if not record.get("bit_identical_resume"):
        failures.append("bit_identical_resume != true (HARD)")
    resize = record.get("resize") or {}
    if resize.get("old_world") == resize.get("new_world"):
        failures.append("no world resize happened (HARD)")
    if resize.get("reason") != "host_death":
        failures.append(f"resize reason {resize.get('reason')} != host_death")
    downtime = record.get("resize_downtime_s")
    if downtime is None or downtime > max_downtime_s:
        failures.append(
            f"resize_downtime_s {downtime} > ceiling {max_downtime_s}"
        )
    ratio = (record.get("throughput_per_chip") or {}).get("after_over_before")
    if ratio is not None and ratio < min_throughput_ratio:
        failures.append(
            f"throughput_per_chip after/before {ratio} < floor "
            f"{min_throughput_ratio}"
        )
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    parser.add_argument("--digest", default=None, metavar="MODEL_DIR",
                        help="internal: print the latest checkpoint's param "
                        "digest for MODEL_DIR and exit")
    parser.add_argument("--steps", type=int, default=12)
    parser.add_argument("--kill-step", type=int, default=8)
    parser.add_argument("--devices-per-host", type=int, default=2)
    parser.add_argument("--timeout", type=int, default=600)
    parser.add_argument("--json-out", default=None)
    parser.add_argument("--check", action="store_true",
                        help="gate on the drill's hard invariants "
                        "(bit-identical resume, a real resize, downtime "
                        "ceiling, throughput floor)")
    parser.add_argument("--max-downtime", type=float, default=60.0,
                        help="resize downtime ceiling in seconds (drain + "
                        "re-plan + respawn as the coordinator measured it; "
                        "generous — CI boxes are slow, and the committed "
                        "record is the real gate)")
    parser.add_argument("--min-throughput-ratio", type=float, default=0.4,
                        help="floor on median examples-per-chip-second "
                        "after/before the resize (per-chip efficiency must "
                        "survive the resize; dp shrinks but so does the "
                        "batch, so the per-chip rate should hold)")
    args = parser.parse_args(argv)
    if args.digest:
        return _cmd_digest(args.digest)

    record = run_bench(args)
    print(json.dumps(record, indent=1))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    if args.check:
        failures = check_record(
            record,
            max_downtime_s=args.max_downtime,
            min_throughput_ratio=args.min_throughput_ratio,
        )
        for failure in failures:
            print(f"CHECK FAILED: {failure}", file=sys.stderr)
        return 1 if failures else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
