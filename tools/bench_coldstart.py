"""Cold-start bench: compile-free restarts, replicas, and resizes, measured.

Drives the REAL CLI + serving seams end to end on the CPU harness and
records the three cold-start cliffs this codebase claims to have killed:

1. **Train rerun** — ``fit --compile-cache-dir`` twice with the same shape
   into a shared cache: the second run must ledger cache hits and reach its
   first step measurably faster (warmup is loads, not compiles).
2. **Replica time-to-ready** — the first run's ``--export-serving``
   artifact ships its compiled bucket ladder (manifest-fingerprinted cache
   subdir); a replica loading the shipped cache must go ready in ≤ half the
   cold (stripped-cache) load time, with the ladder answered from cache.
3. **Elastic AOT standby** — the host-death resize drill with and without
   ``--aot-standby``: with the standby, the resized generation's compiles
   are served from the cache the standby mini-world populated, and the
   resume stays bit-identical to a clean run (the standby must never touch
   training math).

``--check`` gates the result; the COMMITTED BENCH_COLDSTART.json replays
as hard gates in tools/regression_sentinel.py (a cold-start-path PR must
re-run this bench and commit numbers that still clear them)::

    python tools/bench_coldstart.py --check --json-out BENCH_COLDSTART.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
sys.path.insert(0, TOOLS)

import bench_elastic  # noqa: E402  — shared drill/shard/digest harness

PRESET = bench_elastic.PRESET
LOCAL_BS = bench_elastic.LOCAL_BS
_env = bench_elastic._env
_read_ledger = bench_elastic._read_ledger


# -- scenario 1: same-shape train rerun --------------------------------------


def run_train(
    workdir: str,
    data_dir: str,
    cache_dir: str,
    *,
    steps: int = 6,
    export_serving: bool = False,
    timeout: int = 420,
) -> Dict:
    """One plain ``fit`` through the real CLI with the persistent cache on.
    Returns ledger-derived facts: time from run header to the first stepped
    event (the warmup the cache is supposed to shrink) and the run_end
    cache counters."""
    argv = [
        sys.executable, "-m", "tensorflowdistributedlearning_tpu", "fit",
        "--preset", PRESET,
        "--model-dir", workdir,
        "--data-dir", data_dir,
        "--steps", str(steps),
        "--batch-size", str(LOCAL_BS),
        "--eval-every", "100000",
        "--compile-cache-dir", cache_dir,
    ]
    if export_serving:
        argv.append("--export-serving")
    out = subprocess.run(
        argv, env=_env(1), capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"train run failed rc={out.returncode}: {out.stderr[-1500:]}"
        )
    events = _read_ledger(os.path.join(workdir, "telemetry.jsonl"))
    header_t = next(
        (e["t"] for e in events if e.get("event") == "run_header"), None
    )
    first_step_t = next(
        (
            e["t"]
            for e in events
            if isinstance(e.get("step"), (int, float)) and e.get("t")
        ),
        None,
    )
    run_end = next(
        (e for e in reversed(events) if e.get("event") == "run_end"), {}
    )
    compiles = [e for e in events if e.get("event") == "compile"]
    if header_t is None or first_step_t is None:
        raise RuntimeError(f"train ledger in {workdir} has no header/steps")
    facts = {
        "time_to_first_step_s": round(first_step_t - header_t, 3),
        "cache_hits": run_end.get("compile_cache_hits"),
        "cache_misses": run_end.get("compile_cache_misses"),
        "ledgered_cache_hits": sum(
            1 for e in compiles if e.get("cache_hit") is True
        ),
        "compiles": len(compiles),
    }
    if export_serving:
        artifact = os.path.join(workdir, "export", "serving")
        if not os.path.isdir(artifact):
            raise RuntimeError(f"--export-serving left no artifact in {workdir}")
        facts["artifact"] = artifact
    return facts


# -- scenario 2: replica time-to-ready ----------------------------------------

_REPLICA_SCRIPT = """
import json, sys, time
sys.path.insert(0, {repo!r})
from tensorflowdistributedlearning_tpu.utils import compile_cache
assert compile_cache.configure({cache_dir!r})
t0 = time.monotonic()
from tensorflowdistributedlearning_tpu.serve.engine import InferenceEngine
engine = InferenceEngine.from_artifact({artifact!r})
engine.warmup()
print(json.dumps({{
    "time_to_ready_s": round(time.monotonic() - t0, 4),
    "stats": compile_cache.stats(),
    "warmed": sorted(int(b) for b in engine.warmed_buckets),
}}))
"""


def load_replica(artifact: str, cache_dir: str, timeout: int = 240) -> Dict:
    """Measure a serve replica's load→ready time in a fresh interpreter
    (1-device serving topology, own persistent cache): engine construction
    through warmup — the window the shipped cache subdir is meant to
    collapse. Interpreter/jax import time is excluded; both the cold and
    warm variants pay it identically and the fleet already ledgers the
    spawn-inclusive time_to_ready_s per replica."""
    script = _REPLICA_SCRIPT.format(
        repo=REPO, cache_dir=cache_dir, artifact=artifact
    )
    out = subprocess.run(
        [sys.executable, "-c", script], env=_env(1), capture_output=True,
        text=True, timeout=timeout, cwd=REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"replica load failed rc={out.returncode}: {out.stderr[-1500:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


# serve/engine.py ARTIFACT_CACHE_SUBDIR — inlined so the bench process never
# imports the package (and with it jax); subprocesses own all device state
ARTIFACT_CACHE_SUBDIR = "compile_cache"


def replica_cold_vs_warm(artifact: str, tmp: str) -> Dict:
    bare = os.path.join(tmp, "bare_artifact")
    shutil.copytree(artifact, bare)
    shutil.rmtree(os.path.join(bare, ARTIFACT_CACHE_SUBDIR))
    cold = load_replica(bare, os.path.join(tmp, "replica_cache_cold"))
    warm = load_replica(artifact, os.path.join(tmp, "replica_cache_warm"))
    out = {
        "cold_time_to_ready_s": cold["time_to_ready_s"],
        "warm_time_to_ready_s": warm["time_to_ready_s"],
        "cold_misses": cold["stats"]["misses"],
        "warm_hits": warm["stats"]["hits"],
        "warm_misses": warm["stats"]["misses"],
        "warmed_buckets": warm["warmed"],
    }
    if cold["time_to_ready_s"]:
        out["warm_over_cold"] = round(
            warm["time_to_ready_s"] / cold["time_to_ready_s"], 4
        )
    return out


# -- scenario 3: elastic resize with the AOT standby ---------------------------


def elastic_standby_drill(
    tmp: str,
    data_dir: str,
    *,
    steps: int,
    kill_step: int,
    devices_per_host: int,
    timeout: int,
) -> Dict:
    """The bench_elastic host-death drill twice — persistent cache on both
    times, ``--aot-standby`` on the second — plus the clean-run comparison
    on the standby drill (the standby must not perturb training math)."""
    facts: Dict = {}
    for label, extra in (
        ("nostandby", []),
        ("standby", ["--aot-standby"]),
    ):
        workdir = os.path.join(tmp, f"drill_{label}")
        cache = os.path.join(tmp, f"cache_{label}")
        drill = bench_elastic.run_elastic_drill(
            workdir, data_dir,
            steps=steps, kill_step=kill_step,
            devices_per_host=devices_per_host, timeout=timeout,
            extra_argv=["--compile-cache-dir", cache, *extra],
        )
        resize_t = drill["resize"]["t"]
        post_hits = sum(
            1
            for e in drill["events"]
            if e.get("event") == "compile"
            and e.get("cache_hit") is True
            and e.get("t", 0) > resize_t
        )
        standby_events = [
            e for e in drill["events"] if e.get("event") == "aot_standby"
        ]
        facts[label] = {
            "post_resize_settle_s": drill["verdict"].get(
                "post_resize_settle_s"
            ),
            "resize_downtime_s": drill["verdict"]["resize_downtime_s"],
            "post_resize_cache_hits": post_hits,
            "standby_started": any(
                e.get("action") == "start" for e in standby_events
            ),
            # terminal lifecycle state: "ready" (finished before the death),
            # "superseded" (reaped at drain — every entry compiled so far is
            # already on disk), "failed", or None (never started)
            "standby_outcome": next(
                (
                    e.get("action")
                    for e in reversed(standby_events)
                    if e.get("action") != "start"
                ),
                None,
            ),
            "wall_s": drill["wall_s"],
        }
        facts[f"_drill_{label}"] = drill  # internal: clean-run comparison
    drill = facts.pop("_drill_standby")
    facts.pop("_drill_nostandby")
    golden = os.path.join(tmp, "golden")
    bench_elastic.run_clean_comparison(
        golden, data_dir, os.path.join(tmp, "drill_standby"),
        drill["resume_step"],
        steps=steps, new_world=drill["resize"]["new_world"],
        devices_per_host=devices_per_host,
    )
    a = bench_elastic.params_digest(os.path.join(tmp, "drill_standby"))
    b = bench_elastic.params_digest(golden)
    facts["bit_identical_resume"] = a == b
    ns, sb = facts["nostandby"], facts["standby"]
    if ns["post_resize_settle_s"] and sb["post_resize_settle_s"]:
        facts["settle_standby_over_nostandby"] = round(
            sb["post_resize_settle_s"] / ns["post_resize_settle_s"], 4
        )
    return facts


# -- record / gates ------------------------------------------------------------


def run_bench(args) -> Dict:
    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="bench_coldstart_") as tmp:
        data_dir = os.path.join(tmp, "data")
        cache = os.path.join(tmp, "train_cache")
        os.makedirs(data_dir)
        bench_elastic.write_drill_shards(data_dir)
        cold = run_train(
            os.path.join(tmp, "train_cold"), data_dir, cache,
            steps=args.train_steps, export_serving=True,
        )
        warm = run_train(
            os.path.join(tmp, "train_warm"), data_dir, cache,
            steps=args.train_steps,
        )
        rerun = {
            "cold_time_to_first_step_s": cold["time_to_first_step_s"],
            "warm_time_to_first_step_s": warm["time_to_first_step_s"],
            "cold_cache_hits": cold["cache_hits"],
            "warm_cache_hits": warm["cache_hits"],
            "warm_ledgered_cache_hits": warm["ledgered_cache_hits"],
            "warm_cache_misses": warm["cache_misses"],
        }
        if cold["time_to_first_step_s"]:
            rerun["warm_over_cold"] = round(
                warm["time_to_first_step_s"] / cold["time_to_first_step_s"],
                4,
            )
        replica = replica_cold_vs_warm(cold["artifact"], tmp)
        elastic = elastic_standby_drill(
            tmp, data_dir,
            steps=args.steps, kill_step=args.kill_step,
            devices_per_host=args.devices_per_host, timeout=args.timeout,
        )
    return {
        "bench": "coldstart",
        "preset": PRESET,
        "train_steps": args.train_steps,
        "elastic_steps": args.steps,
        "kill_step": args.kill_step,
        "devices_per_host": args.devices_per_host,
        "train_rerun": rerun,
        "replica": replica,
        "elastic_standby": elastic,
        "wall_s": round(time.time() - t0, 3),
    }


def check_record(
    record: Dict,
    *,
    max_replica_ratio: float,
    max_rerun_ratio: float,
) -> List[str]:
    """The bench's own gate (the sentinel replays the committed record with
    the same rules). Returns failure strings; empty = pass."""
    failures = []
    rerun = record.get("train_rerun") or {}
    if not (rerun.get("warm_cache_hits") or 0) >= 1:
        failures.append(
            f"second train run ledgered {rerun.get('warm_cache_hits')} "
            "cache hits — persistent cache not serving reruns (HARD)"
        )
    ratio = rerun.get("warm_over_cold")
    if ratio is None or ratio > max_rerun_ratio:
        failures.append(
            f"warm/cold time-to-first-step {ratio} > ceiling "
            f"{max_rerun_ratio} — rerun warmup not reduced"
        )
    replica = record.get("replica") or {}
    if not (replica.get("warm_hits") or 0) >= 1:
        failures.append(
            "warm replica load had no cache hits — shipped artifact cache "
            "not consumed (HARD)"
        )
    r_ratio = replica.get("warm_over_cold")
    if r_ratio is None or r_ratio > max_replica_ratio:
        failures.append(
            f"warm/cold replica time-to-ready {r_ratio} > ceiling "
            f"{max_replica_ratio}"
        )
    elastic = record.get("elastic_standby") or {}
    if not elastic.get("bit_identical_resume"):
        failures.append(
            "resume with --aot-standby not bit-identical to clean run (HARD)"
        )
    sb = elastic.get("standby") or {}
    if not sb.get("standby_started"):
        failures.append("aot standby never ledgered action=start (HARD)")
    if sb.get("standby_outcome") not in ("ready", "superseded"):
        failures.append(
            f"aot standby ended {sb.get('standby_outcome')!r} — expected "
            "ready (finished) or superseded (reaped at drain)"
        )
    if not (sb.get("post_resize_cache_hits") or 0) >= 1:
        failures.append(
            "resized generation had no compile-cache hits — standby entries "
            "not consumed"
        )
    ns_settle = (elastic.get("nostandby") or {}).get("post_resize_settle_s")
    sb_settle = sb.get("post_resize_settle_s")
    if ns_settle is not None and sb_settle is not None:
        # absolute delta, not a ratio: settle is quantized by the
        # coordinator's poll interval (~2s ticks on a ~6s base), so a ratio
        # gate flaps on one tick. 4s = two ticks of headroom; the contention
        # bug this gate exists for (standby competing with the respawn)
        # measured +6s before the drain-time reap fixed it.
        if sb_settle - ns_settle > 4.0:
            failures.append(
                f"standby drill settled {sb_settle - ns_settle:.1f}s slower "
                "than the no-standby drill — the standby is competing with "
                "the respawn instead of pre-warming it"
            )
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--train-steps", type=int, default=6)
    parser.add_argument("--steps", type=int, default=14,
                        help="elastic drill steps (kill late enough that "
                        "the standby mini-world finishes compiling before "
                        "the host death)")
    parser.add_argument("--kill-step", type=int, default=10)
    parser.add_argument("--devices-per-host", type=int, default=2)
    parser.add_argument("--timeout", type=int, default=600)
    parser.add_argument("--json-out", default=None)
    parser.add_argument("--check", action="store_true",
                        help="gate on the cold-start invariants (warm "
                        "replica ≤ half cold, rerun cache hits, standby "
                        "consumed, bit-identical resume)")
    parser.add_argument("--max-replica-ratio", type=float, default=0.5,
                        help="ceiling on warm/cold replica time-to-ready "
                        "(the ISSUE's headline: a shipped cache must at "
                        "least halve replica readiness)")
    parser.add_argument("--max-rerun-ratio", type=float, default=0.9,
                        help="ceiling on warm/cold train time-to-first-step "
                        "(generous: compile is most but not all of warmup)")
    args = parser.parse_args(argv)

    record = run_bench(args)
    print(json.dumps(record, indent=1))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    if args.check:
        failures = check_record(
            record,
            max_replica_ratio=args.max_replica_ratio,
            max_rerun_ratio=args.max_rerun_ratio,
        )
        for failure in failures:
            print(f"CHECK FAILED: {failure}", file=sys.stderr)
        return 1 if failures else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
